#!/usr/bin/env python
"""Benchmark driver: scheduler-session latency, serial loop vs TPU solve.

Prints ONE final JSON line:
    {"metric": "...", "value": N, "unit": "ms", "vs_baseline": N}

- value: TPU-backend allocate-session latency (encode + device solve + apply)
  at the headline config (BASELINE.json cfg 5: 50k tasks x 10k nodes), warm
  (compile excluded — the scheduler reuses the compiled program every cycle).
- vs_baseline: speedup over the serial oracle loop at the same config. The
  reference publishes no numbers (BASELINE.md), so the baseline is the
  serial path measured here; where the serial loop would take > --serial-budget
  seconds it is measured at a reduced scale and extrapolated linearly in
  (tasks x nodes), reported with "serial_extrapolated": true.

Usage:
    python bench.py                     # headline (cfg 5, full scale)
    python bench.py --config 1 --scale 0.2 --backend both
    python bench.py --all --scale 0.05  # all five configs, smoke scale
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _session_once(cache, tiers, actions, mesh=None):
    """Open a session, run the actions, close; returns (latency_s, binds, profile)."""
    import volcano_tpu.scheduler.actions  # noqa: F401 (register actions)
    from volcano_tpu.scheduler.framework import close_session, get_action, open_session

    if mesh is not None:
        from volcano_tpu.scheduler.plugins import tpuscore

        tpuscore.set_default_mesh(mesh)
    t0 = time.perf_counter()
    ssn = open_session(cache, tiers)
    t_open = time.perf_counter()
    for name in actions:
        get_action(name).execute(ssn)
    t_act = time.perf_counter()
    profile = dict(ssn.plugins["tpuscore"].profile) if "tpuscore" in ssn.plugins else {}
    close_session(ssn)
    return {
        "open_s": t_open - t0,
        "actions_s": t_act - t_open,
        "binds": len(cache.binder.binds),
        "profile": profile,
    }


def run_config(cfg: int, scale: float, backend: str, serial_budget: float,
               mesh=None, verbose=True):
    from volcano_tpu.bench.clusters import CONFIGS, build_config

    bc = CONFIGS[cfg]
    out = {"config": cfg, "name": bc.name, "scale": scale}

    if backend in ("serial", "both", "auto"):
        # estimate serial cost before committing to it: measured at small
        # scale, the serial loop is ~linear in placed-tasks x nodes
        serial_scale = scale
        est = None
        if backend == "auto" or cfg >= 3:
            probe_scale = min(scale, 0.02)
            cache, st, _, actions, _ = build_config(cfg, probe_scale)
            t0 = time.perf_counter()
            probe = _session_once(cache, st, actions)
            probe_s = time.perf_counter() - t0
            unit = probe_scale * probe_scale  # tasks*nodes both scale
            est = probe_s / unit * (scale * scale)
            if est > serial_budget:
                serial_scale = max((serial_budget / (probe_s / unit)) ** 0.5, probe_scale)
        cache, serial_tiers, _, actions, n_tasks = build_config(cfg, serial_scale)
        r = _session_once(cache, serial_tiers, actions)
        serial_s = r["actions_s"]
        if serial_scale < scale:
            factor = (scale * scale) / (serial_scale * serial_scale)
            out["serial_measured_scale"] = serial_scale
            out["serial_measured_ms"] = serial_s * 1e3
            serial_s = serial_s * factor
            out["serial_extrapolated"] = True
        out["serial_ms"] = serial_s * 1e3
        out["serial_binds"] = r["binds"]
        if verbose:
            print(f"[cfg{cfg}] serial: {out['serial_ms']:.1f} ms "
                  f"({'extrapolated' if out.get('serial_extrapolated') else 'measured'})",
                  file=sys.stderr)

    if backend in ("tpu", "both", "auto"):
        cache, _, tpu_tiers, actions, n_tasks = build_config(cfg, scale)
        cold = _session_once(cache, tpu_tiers, actions, mesh=mesh)
        out["tpu_cold_ms"] = cold["actions_s"] * 1e3
        out["tpu_cold_profile"] = cold["profile"]
        # warm: fresh identical cluster, compiled program reused
        cache, _, tpu_tiers, actions, n_tasks = build_config(cfg, scale)
        warm = _session_once(cache, tpu_tiers, actions, mesh=mesh)
        out["tpu_ms"] = warm["actions_s"] * 1e3
        out["tpu_binds"] = warm["binds"]
        out["tpu_profile"] = warm["profile"]
        out["tasks"] = n_tasks
        if verbose:
            p = warm["profile"]
            print(f"[cfg{cfg}] tpu warm: {out['tpu_ms']:.1f} ms "
                  f"(encode {p.get('encode_s', 0)*1e3:.1f} solve {p.get('solve_s', 0)*1e3:.1f} "
                  f"apply {p.get('apply_s', 0)*1e3:.1f}) binds={warm['binds']}",
                  file=sys.stderr)

    if "serial_ms" in out and "tpu_ms" in out and out["tpu_ms"] > 0:
        out["speedup"] = out["serial_ms"] / out["tpu_ms"]
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=5, choices=[1, 2, 3, 4, 5])
    ap.add_argument("--all", action="store_true", help="run all five configs")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--backend", choices=["serial", "tpu", "both", "auto"], default="auto")
    ap.add_argument("--serial-budget", type=float, default=60.0,
                    help="max seconds to spend measuring the serial loop")
    ap.add_argument("--mesh", action="store_true",
                    help="shard the node axis across all local devices")
    args = ap.parse_args()

    mesh = None
    if args.mesh:
        import jax
        import numpy as np
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) > 1:
            mesh = Mesh(np.array(devs), ("nodes",))

    results = []
    cfgs = [1, 2, 3, 4, 5] if args.all else [args.config]
    for cfg in cfgs:
        results.append(run_config(cfg, args.scale, args.backend,
                                  args.serial_budget, mesh=mesh))

    headline = results[-1]
    final = {
        "metric": "scheduler-session latency (ms) @ %dk tasks x %dk nodes"
                  % (int(50 * args.scale), int(10 * args.scale))
                  if headline["config"] == 5 else
                  f"scheduler-session latency (ms), cfg {headline['config']} ({headline['name']})",
        "value": round(headline.get("tpu_ms", headline.get("serial_ms", 0.0)), 3),
        "unit": "ms",
        "vs_baseline": round(headline.get("speedup", 0.0), 3),
    }
    if len(results) > 1:
        final["all_configs"] = [
            {k: v for k, v in r.items() if not k.endswith("profile")} for r in results
        ]
    print(json.dumps(final))
    return 0


if __name__ == "__main__":
    sys.exit(main())
