#!/usr/bin/env python
"""Benchmark driver: scheduler-session latency, serial loop vs TPU solve.

Prints a headline JSON line right after the cfg-5 run, and (in the default
all-configs mode) a final combined JSON line — TAIL LINE WINS; the early
line exists so a time-boxed harness that kills the run mid-way still
captures the headline:
    {"metric": "...", "value": N, "unit": "ms", "vs_baseline": N}

- value: TPU-backend END-TO-END session latency (open_session + actions +
  close_session — the exact span the production loop's e2e metric and the
  reference's E2eSchedulingLatency measure), warm MEDIAN across samples, at
  the headline config (BASELINE.json cfg 5: 50k tasks x 10k nodes). Compile
  excluded (the scheduler reuses the compiled program every cycle); nothing
  else is excluded — session open and the close-time mirror flush are inside
  the timed window. The full record (all configs, per-phase and per-action
  splits, every sample) is also written to BENCH_local.json.
- vs_baseline: speedup over the serial oracle loop at the same config, on
  MATCHING spans — serial full-session e2e over tpu warm-median e2e. The
  reference publishes no numbers (BASELINE.md), so the baseline is the
  serial path measured here; where the serial loop would take > --serial-budget
  seconds its actions window is measured at a reduced scale and extrapolated
  linearly in (tasks x nodes) (open/close extrapolate linearly in scale),
  reported with "serial_extrapolated": true.

Usage:
    python bench.py                     # headline (cfg 5, full scale)
    python bench.py --config 1 --scale 0.2 --backend both
    python bench.py --all --scale 0.05  # all five configs, smoke scale
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _session_once(cache, tiers, actions, mesh=None):
    """Open a session, run the actions, close; returns per-phase timings.

    The measured span is the full production cycle — open_session through
    close_session — exactly what Scheduler.run_once times into its e2e
    metric (volcano_tpu/scheduler/scheduler.py:211-223) and what the
    reference's E2eSchedulingLatency covers (reference
    pkg/scheduler/metrics/metrics.go:38-45, spanning scheduler.go:71-87).
    Work deferred to close (the cache-mirror flush) is inside the window.
    """
    import volcano_tpu.scheduler.actions  # noqa: F401 (register actions)
    from volcano_tpu.scheduler.framework import (
        close_session, open_session, run_actions)

    if mesh is not None:
        from volcano_tpu.scheduler.plugins import tpuscore

        tpuscore.set_default_mesh(mesh)
    if _GC_POLICY is not None:
        _GC_POLICY.maintain()  # between-cycle collection, as in the loop
    # compile watching needs jax; the serial baseline must keep running on
    # jax-free hosts (it never touches the device path)
    try:
        from volcano_tpu.utils.jaxcompile import CompileWatcher

        win = CompileWatcher.install().window()
    except Exception:
        # no jax, or a jax whose (private) monitoring hook moved — compile
        # accounting degrades to absent, the measurement itself still runs
        win = None
    try:
        from volcano_tpu.utils import devprof
    except Exception:  # pragma: no cover - minimal host
        devprof = None
    if devprof is not None:
        # fence: the timed window must not inherit queued device work from
        # the previous build/session (jax dispatch is async on every
        # backend — without this, open_s could absorb a straggling flush)
        devprof.drain()
    devc = {}
    t0 = time.perf_counter()
    ssn = open_session(cache, tiers)
    t_open = time.perf_counter()
    if devprof is not None:
        with devprof.session(devc):
            action_ms = run_actions(ssn, actions)
    else:
        action_ms = run_actions(ssn, actions)
    t_act = time.perf_counter()
    profile = dict(ssn.plugins["tpuscore"].profile) if "tpuscore" in ssn.plugins else {}
    profile.update(devc)  # tpu_sync_points / tpu_d2h_fetches / tpu_overlap_ms
    close_session(ssn)
    if devprof is not None:
        # fence at the close boundary: e2e ends only when the device is
        # drained, so nothing can hide past the timed window
        devprof.drain()
    t_close = time.perf_counter()
    # compile accounting: a warm session with compiles > 0 is a retrace —
    # exactly the regression the warm-sample spread is meant to expose
    if win is not None:
        cs = win.delta()
        profile["compiles"] = cs.compiles
        profile["compile_s"] = round(cs.compile_s, 3)
    return {
        "open_s": t_open - t0,
        "actions_s": t_act - t_open,
        "close_s": t_close - t_act,
        "e2e_s": t_close - t0,
        "action_ms": action_ms,
        "binds": len(cache.binder.binds),
        "profile": profile,
    }


def run_config(cfg: int, scale: float, backend: str, serial_budget: float,
               mesh=None, verbose=True, warm_iters: int = 5,
               scenario: str = None):
    warm_iters = max(warm_iters, 1)
    from volcano_tpu.bench.clusters import CONFIGS, build_config
    from volcano_tpu.bench.clusters import build_scenario

    # build the native engines BEFORE any timed window — including the
    # serial baseline, whose session transition path also reaches for
    # fasttrans: the _nowait accessors silently fall back to Python while
    # the background cc runs, which would bench the wrong implementation
    from volcano_tpu import _native

    native_ok = {"fastapply": _native.get_fastapply() is not None,
                 "fasttrans": _native.get_fasttrans() is not None}

    if scenario is None:
        name = CONFIGS[cfg].name
        build = build_config
    else:
        # --scenario: the cluster snapshot comes from a sim scenario file
        # (volcano_tpu/sim/scenarios) through the SAME populate path the
        # simulator uses — one cluster-shape source, two harnesses
        import os as _os

        name = f"scenario:{_os.path.splitext(_os.path.basename(scenario))[0]}"

        def build(_cfg, s, _ref=scenario):
            return build_scenario(_ref, s)
    out = {"config": cfg, "name": name, "scale": scale,
           "native_engines": native_ok}

    if backend in ("serial", "both", "auto"):
        # estimate serial cost before committing to it: measured at small
        # scale, the serial loop is ~linear in placed-tasks x nodes
        serial_scale = scale
        est = None
        if backend == "auto" or cfg >= 3:
            probe_scale = min(scale, 0.02)
            cache, st, _, actions, _ = build(cfg, probe_scale)
            t0 = time.perf_counter()
            probe = _session_once(cache, st, actions)
            probe_s = time.perf_counter() - t0
            unit = probe_scale * probe_scale  # tasks*nodes both scale
            est = probe_s / unit * (scale * scale)
            if est > serial_budget:
                serial_scale = max((serial_budget / (probe_s / unit)) ** 0.5, probe_scale)
        cache, serial_tiers, _, actions, n_tasks = build(cfg, serial_scale)
        r = _session_once(cache, serial_tiers, actions)
        serial_s = r["actions_s"]
        open_close_s = r["open_s"] + r["close_s"]
        if serial_scale < scale:
            factor = (scale * scale) / (serial_scale * serial_scale)
            out["serial_measured_scale"] = serial_scale
            out["serial_measured_ms"] = serial_s * 1e3
            serial_s = serial_s * factor
            # open/close walk every object once -> ~linear in scale, not
            # quadratic like the per-(task,node) action loops
            open_close_s = open_close_s * (scale / serial_scale)
            out["serial_extrapolated"] = True
        out["serial_ms"] = serial_s * 1e3
        # full-session serial span, matching tpu_e2e_*: actions plus the
        # (linearly extrapolated, when reduced-scale) open+close
        out["serial_e2e_ms"] = round((serial_s + open_close_s) * 1e3, 3)
        out["serial_binds"] = r["binds"]
        out["serial_open_ms"] = round(r["open_s"] * 1e3, 3)
        out["serial_close_ms"] = round(r["close_s"] * 1e3, 3)
        if verbose:
            print(f"[cfg{cfg}] serial: {out['serial_ms']:.1f} ms "
                  f"({'extrapolated' if out.get('serial_extrapolated') else 'measured'})",
                  file=sys.stderr)

    if backend in ("tpu", "both", "auto"):
        import gc

        cache, _, tpu_tiers, actions, n_tasks = build(cfg, scale)
        cold = _session_once(cache, tpu_tiers, actions, mesh=mesh)
        out["tpu_cold_ms"] = cold["actions_s"] * 1e3
        out["tpu_cold_profile"] = cold["profile"]
        # warm: fresh identical clusters, compiled program reused. Take the
        # best of a few iterations — the device hop here is a tunneled PJRT
        # connection whose per-round-trip latency jitters by 2-3x, and the
        # min is the reproducible figure (the scheduler reuses the compiled
        # program every cycle).
        # per-sample link floor: the tunnel's RTT drifts hour-to-hour, and
        # a floor measured once at process start can misattribute link
        # jitter to (or hide it inside) the solve term — a median-of-k
        # no-op dispatch+fetch right before each timed sample pins the
        # floor that sample actually ran against, with the probe spread
        # recorded so floor noise can't masquerade as a solve regression
        sample_floor = _measure_floor_ms

        samples = []        # actions window, ms (back-compat headline)
        e2e_samples = []    # open + actions + close, ms — the honest span
        floor_samples = []  # per-sample link floor (median of k probes)
        floor_spreads = []  # max-min of each sample's floor probes
        floor_notes = []    # per-sample floor cause annotations
        warm = None
        warm_compiles = []
        # one extra warm session whose sample is DISCARDED: the first
        # post-compile session still pays one-off warmup (allocator pools,
        # device-cache fills, branch-predictor state) that the production
        # steady state never sees — recording it as tpu_first_warm_ms keeps
        # it visible without letting it shape the median
        for it in range(warm_iters + 1):
            del cache
            gc.collect()
            cache, _, tpu_tiers, actions, n_tasks = build(cfg, scale)
            # building the cluster allocates heavily; collect that debt
            # BEFORE the timed window so a generational collection isn't
            # charged to whichever session phase it randomly lands in (the
            # production loop schedules between-cycle collections the same
            # way — utils/gcpolicy.py)
            gc.collect()
            f_med, f_spread, f_note = sample_floor()
            w = _session_once(cache, tpu_tiers, actions, mesh=mesh)
            if it == 0:
                out["tpu_first_warm_ms"] = round(w["e2e_s"] * 1e3, 3)
                out["tpu_first_warm_compiles"] = \
                    w["profile"].get("compiles", 0)
                continue
            floor_samples.append(f_med)
            floor_spreads.append(f_spread)
            floor_notes.append(f_note)
            samples.append(w["actions_s"] * 1e3)
            e2e_samples.append(w["e2e_s"] * 1e3)
            warm_compiles.append(w["profile"].get("compiles", 0))
            if warm is None or w["e2e_s"] * 1e3 <= min(e2e_samples):
                warm = w
        # min is the reproducible figure on a jittery tunneled link, but a
        # min-only report buries warm-path retraces/stalls — median and max
        # make the spread (and any hidden recompile) part of the record.
        # The BARS bind on median e2e: the full production span, at the
        # middle of the observed jitter, not its luckiest tail.
        import statistics

        out["tpu_ms"] = min(samples)
        out["tpu_warm_median_ms"] = round(statistics.median(samples), 3)
        out["tpu_warm_max_ms"] = round(max(samples), 3)
        out["tpu_warm_samples_ms"] = [round(s, 3) for s in samples]
        out["tpu_e2e_ms"] = round(min(e2e_samples), 3)
        out["tpu_e2e_median_ms"] = round(statistics.median(e2e_samples), 3)
        out["tpu_e2e_samples_ms"] = [round(s, 3) for s in e2e_samples]
        out["tpu_floor_samples_ms"] = floor_samples
        out["tpu_floor_spread_ms"] = floor_spreads
        # cause annotations: every probe's individual wall plus its counted
        # sync/fetch budget — a floor swing must now be attributable to a
        # specific slow round trip, not inferred from the aggregate
        out["tpu_floor_probe_notes"] = floor_notes
        # phase split of the best-e2e sample: nothing hides outside the
        # timed window anymore, but the split still shows where it went
        out["tpu_open_ms"] = round(warm["open_s"] * 1e3, 3)
        out["tpu_close_ms"] = round(warm["close_s"] * 1e3, 3)
        out["tpu_action_ms"] = warm["action_ms"]
        out["tpu_warm_compiles"] = warm_compiles
        out["tpu_binds"] = warm["binds"]
        # candidate-window round profile: the device solve is ONE fused
        # program, so per-round wall splits are not observable without
        # breaking the single-dispatch contract — the record carries the
        # device-reported placed-per-round histogram, the full-sweep
        # (exactness-fallback) round count, and the derived avg ms/round
        # from the dispatch window; the serial-tail terms come from the
        # allocate action's residue-pass timer
        wp = warm["profile"]
        if wp.get("rounds"):
            out["tpu_round_profile"] = {
                "rounds": wp["rounds"],
                "placed": wp.get("round_placed", []),
                "full_sweep_rounds": wp.get("full_sweep_rounds"),
                "window_k": wp.get("window_k"),
                "dirty_k": wp.get("dirty_k"),
                "tail_placed": wp.get("tail_placed", 0),
                "avg_round_ms": round(
                    wp.get("dispatch_s", 0.0) * 1e3 / max(wp["rounds"], 1),
                    3),
            }
        out["tpu_residue_ms"] = wp.get("residue_pass_ms", 0.0)
        out["tpu_residue_tasks"] = wp.get("residue_pass_tasks", 0)
        # encode split (ROADMAP item 3): one opaque encode number hides
        # whether sharding moved the bottleneck — snapshot is the
        # session->arrays encode, host_pack the grouped buffer build, h2d
        # the device staging (per-shard puts under a mesh; h2d_shard_*
        # counters in tpu_profile carry the per-shard reuse story)
        out["tpu_encode_split_ms"] = {
            "snapshot": round(wp.get("encode_s", 0.0) * 1e3, 3),
            "host_pack": round(wp.get("pack_s", 0.0) * 1e3, 3),
            "h2d": round(wp.get("h2d_s", 0.0) * 1e3, 3),
        }
        # steady-state incremental sessions: the production loop reuses ONE
        # cache across cycles, so its open/close ride the delta-maintained
        # snapshot (scheduler/cache/snapkeeper.py) instead of the wholesale
        # rebuild a first session pays. Three more sessions on the last
        # warm cache measure that: the first reconciles the placements the
        # mirror flush synced, the rest are the no-churn steady state.
        incr_open, incr_close = [], []
        steady_encode, steady_replica = [], {}
        for _ in range(3):
            w2 = _session_once(cache, tpu_tiers, actions, mesh=mesh)
            incr_open.append(round(w2["open_s"] * 1e3, 3))
            incr_close.append(round(w2["close_s"] * 1e3, 3))
            p2 = w2["profile"]
            steady_encode.append(round(p2.get("encode_s", 0.0) * 1e3, 3))
            steady_replica.update({
                k: p2[k] for k in ("encode_reused", "h2d_puts",
                                   "replica_rebuilds",
                                   "replica_scatter_rows",
                                   "tpu_replica_scatter_ms",
                                   "replica_epoch") if k in p2})
        out["tpu_incr_open_ms"] = incr_open
        out["tpu_incr_close_ms"] = incr_close
        out["tpu_incr_open_close_ms"] = round(statistics.median(
            o + c for o, c in zip(incr_open, incr_close)), 3)
        # device-replica steady state (ROADMAP item 2): the incr sessions
        # above ride the standing replica — session 1 reconciles the bulk
        # placements (a scatter/dense diff), sessions 2-3 are the no-churn
        # steady state whose encode should be ~zero (whole-prepare reuse,
        # h2d_puts == 0). The median over the stable tail is the tracked
        # steady-state encode figure.
        out["tpu_steady_encode_ms"] = steady_encode
        out["tpu_steady_state"] = dict(
            steady_replica,
            encode_ms=round(statistics.median(steady_encode[1:]
                                              or steady_encode), 3))
        out["snap_keeper_stats"] = dict(cache.snap_keeper.stats)
        out["tpu_profile"] = {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in warm["profile"].items()}
        out["tasks"] = n_tasks
        if verbose:
            p = warm["profile"]
            print(f"[cfg{cfg}] tpu warm e2e: {out['tpu_e2e_ms']:.1f} ms "
                  f"(open {out['tpu_open_ms']:.1f} actions {warm['actions_s']*1e3:.1f} "
                  f"close {out['tpu_close_ms']:.1f}) "
                  f"(encode {p.get('encode_s', 0)*1e3:.1f} solve {p.get('solve_s', 0)*1e3:.1f} "
                  f"apply {p.get('apply_s', 0)*1e3:.1f}) binds={warm['binds']} "
                  f"actions={out['tpu_action_ms']} "
                  f"e2e_samples={[round(s) for s in e2e_samples]} compiles={warm_compiles}",
                  file=sys.stderr)

    if "serial_ms" in out and "tpu_ms" in out and out["tpu_ms"] > 0:
        # actions-window min-vs-actions speedup, kept for cross-round
        # comparability with r1-r4 records
        out["speedup_actions_min"] = out["serial_ms"] / out["tpu_ms"]
        # the published speedup binds on MATCHING spans at matching
        # percentiles: serial full-session e2e over tpu warm MEDIAN e2e
        if out.get("tpu_e2e_median_ms", 0) > 0:
            out["speedup"] = out["serial_e2e_ms"] / out["tpu_e2e_median_ms"]
    return out


_GC_POLICY = None


def run_mesh_curve(scale: float, counts, warm_iters: int = 2, cfg: int = 7):
    """The standing mesh-scaling curve (ROADMAP item 3): cfg7 (paper-2x,
    100k tasks x 50k nodes at scale 1.0) run at each device count in
    ``counts``, recording a per-device-count warm-session curve so mesh
    efficiency is a tracked trajectory number like sessions/sec.

    Two figures per device count:
    - ``warm_e2e_ms`` / ``solve_ms`` etc: the full warm session under that
      mesh — on the CPU proxy the virtual devices share one host, so this
      column is structural (zero warm compiles, sharded staging engaged),
      not a parallel-speedup claim;
    - ``per_device_stage_ms``: the MEASURED wall of one shard's slice of
      the sharded stages (the rounds score refresh + the evict victim
      fold, ops/shard.probe_per_device_stage_ms) at per-shard width N/d,
      over the config's real encoded arrays. On the real mesh the shards
      run concurrently, so this per-shard wall IS the stage's critical
      path up to the cross-shard verdict reduce — the honest CPU-proxy
      measurement of the scaling the shard buys
      (``sharded_stage_speedup_8v1`` is its 8-vs-1 ratio)."""
    import statistics

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from volcano_tpu.bench.clusters import CONFIGS, make_cache, make_tiers
    from volcano_tpu.ops import shard as shard_mod
    from volcano_tpu.ops.solver import _NODE_AXIS
    from volcano_tpu.scheduler.framework import close_session, open_session
    from volcano_tpu.scheduler.plugins import tpuscore

    devs = jax.devices()
    counts = [d for d in counts if d <= len(devs)] or [1]
    bc = CONFIGS[cfg]
    # rounds mode forced: the curve's job is the sharded stages, and at
    # reduced CPU-proxy scales auto mode would hand the session to the
    # serial loop below its task threshold
    tiers = make_tiers(["tpuscore"], *bc.tiers,
                       arguments={"tpuscore": {"tpuscore.mode": "rounds"}})

    def build():
        cache = make_cache()
        n_tasks = bc.populate(cache, scale)
        return cache, n_tasks

    # one encode of the real config feeds the per-shard stage probes
    cache, n_tasks = build()
    ssn = open_session(cache, tiers)
    prep = ssn.batch_allocator._prepare(ssn)
    probe_arrays = dict(prep["arrays"]) if prep is not None else None
    probe_spec = prep["spec"] if prep is not None else None
    close_session(ssn)

    curve = []
    try:
        for d in counts:
            mesh = Mesh(np.array(devs[:d]), ("nodes",)) if d > 1 else None
            tpuscore.set_default_mesh(mesh)
            shard_mod.clear_cache()
            cache, _ = build()
            cold = _session_once(cache, tiers, bc.actions, mesh=mesh)
            e2e, w = [], cold
            for _ in range(max(warm_iters, 1)):
                cache, _ = build()
                w = _session_once(cache, tiers, bc.actions, mesh=mesh)
                e2e.append(w["e2e_s"] * 1e3)
            p = w["profile"]
            entry = {
                "devices": d,
                "warm_e2e_ms": round(statistics.median(e2e), 3),
                "solve_ms": round(p.get("solve_s", 0.0) * 1e3, 3),
                "encode_ms": round(p.get("encode_s", 0.0) * 1e3, 3),
                "host_pack_ms": round(p.get("pack_s", 0.0) * 1e3, 3),
                "h2d_ms": round(p.get("h2d_s", 0.0) * 1e3, 3),
                "h2d_puts": p.get("h2d_puts", 0),
                "h2d_shard_puts": p.get("h2d_shard_puts", 0),
                "h2d_shard_cached": p.get("h2d_shard_cached", 0),
                "warm_compiles": p.get("compiles", 0),
                "binds": w["binds"],
            }
            if probe_arrays is not None:
                entry["per_device_stage_ms"] = \
                    shard_mod.probe_per_device_stage_ms(
                        probe_spec, probe_arrays, _NODE_AXIS, d)
            curve.append(entry)
    finally:
        tpuscore.set_default_mesh(None)
    out = {"config": cfg, "name": bc.name, "scale": scale,
           "tasks": n_tasks, "devices": counts, "curve": curve}
    first, last = curve[0], curve[-1]
    if "per_device_stage_ms" in first and last["devices"] > 1 \
            and last.get("per_device_stage_ms"):
        out["sharded_stage_speedup"] = round(
            first["per_device_stage_ms"] / last["per_device_stage_ms"], 3)
        out["sharded_stage_speedup_devices"] = \
            [first["devices"], last["devices"]]
    if first.get("warm_e2e_ms") and last.get("warm_e2e_ms") \
            and last["devices"] > 1:
        out["warm_e2e_speedup"] = round(
            first["warm_e2e_ms"] / last["warm_e2e_ms"], 3)
    return out


def run_express(scale: float, arrivals: int = 96, rate_per_s: float = 50.0,
                warm: int = 16, seed: int = 7):
    """--express: Poisson interactive arrivals against a warm cfg5-scale
    snapshot, through the event-driven express lane (volcano_tpu/express).

    One full session settles the backlog first (warm cfg5 snapshot), then
    each iteration submits the arrivals one ~20 ms service period accrued
    (Poisson at `rate_per_s`) and services the lane once. The first
    `warm` iterations absorb compiles and are excluded from the latency
    percentiles (recorded separately); the measured iterations must not
    retrace — `express_warm_compiles` is the proof, exactly the
    assert_no_compiles contract the tests pin. After the arrival storm, a
    full session reconciles and the confirm/revert counts land in the
    record. The PR 6 devprof counters attribute every express-path sync
    point."""
    import random
    import statistics

    from volcano_tpu.api import objects
    from volcano_tpu.bench.clusters import build_config
    from volcano_tpu.express import ExpressLane
    from volcano_tpu.scheduler.util.test_utils import (
        build_pod, build_pod_group)

    cache, _, tpu_tiers, actions, n_tasks = build_config(5, scale)
    lane = ExpressLane(cache)
    settle = _session_once(cache, tpu_tiers, actions)
    lane.run_once()  # drain the backlog notifications (all ineligible/bound)

    rng = random.Random(seed)
    period_s = 0.02
    counter = [0]

    def submit_burst():
        """Arrivals accrued over one service period of the Poisson
        process (>= 1 so every iteration measures a real batch)."""
        n = 0
        budget = period_s
        while True:
            gap = rng.expovariate(rate_per_s)
            if gap > budget and n > 0:
                break
            budget -= gap
            n += 1
        for _ in range(max(n, 1)):
            counter[0] += 1
            pg = f"xpr-{counter[0]:05d}"
            cache.add_pod_group(build_pod_group(
                pg, namespace="express", min_member=1))
            cache.add_pod(build_pod(
                "express", f"{pg}-t0", "", objects.POD_PHASE_PENDING,
                {"cpu": f"{rng.choice([100, 250])}m",
                 "memory": rng.choice(["128Mi", "256Mi"])}, pg))
        return max(n, 1)

    try:
        from volcano_tpu.utils.jaxcompile import CompileWatcher

        watcher = CompileWatcher.install()
    except Exception:
        watcher = None
    lat_ms = []
    warm_lat_ms = []
    sync_points = 0
    batch_sizes = []
    win = None
    for it in range(arrivals + warm):
        if it == warm and watcher is not None:
            win = watcher.window()
        batch_sizes.append(submit_burst())
        rep = lane.run_once()
        (lat_ms if it >= warm else warm_lat_ms).append(rep["ms"])
        if it >= warm:
            sync_points += rep["profile"].get("tpu_sync_points", 0)
    compiles = win.delta().compiles if win is not None else None

    # the reconciling full session: every optimistic bind gets a verdict
    _session_once(cache, tpu_tiers, actions)

    ordered = sorted(lat_ms)

    def pick(q):
        return round(ordered[min(int(q * len(ordered)), len(ordered) - 1)], 3)

    return {
        "scale": scale,
        "snapshot_tasks": n_tasks,
        "settle_session_ms": round(settle["e2e_s"] * 1e3, 3),
        "arrivals": counter[0],
        "batches": len(lat_ms),
        "mean_batch": round(statistics.mean(batch_sizes), 2),
        "tpu_express_p50_ms": pick(0.50),
        "tpu_express_p99_ms": pick(0.99),
        "tpu_express_max_ms": round(ordered[-1], 3),
        "tpu_express_warm_max_ms": round(max(warm_lat_ms), 3)
        if warm_lat_ms else 0.0,
        "express_placed": lane.counters["placed"],
        "express_deferred": lane.counters["deferred"],
        # deferral RATE (per arrival) — the number the serving_mix
        # auditor budget binds on, tracked here as a trajectory column
        "express_deferral_rate": round(
            lane.counters["deferred"]
            / max(lane.counters["arrivals"], 1), 4),
        "express_reconciled": lane.counters["reconciled"],
        "express_reverted": lane.counters["reverted"],
        "express_warm_compiles": compiles,
        "express_sync_points_per_batch": round(
            sync_points / max(len(lat_ms), 1), 3),
        "express_state": dict(lane.state.stats),
    }


def run_pipeline(scale: float, cycles: int = 24, warm: int = 4,
                 rate_per_cycle: float = 3.0, seed: int = 7):
    """--pipeline: back-to-back sessions under Poisson arrivals — no
    isolated warm probes — through the serial loop and the continuous
    pipeline (volcano_tpu/pipeline), on identical pregenerated arrival
    schedules, promoting SUSTAINED sessions/sec + p99 submit->bind task
    wait to the headline (ROADMAP item 2's metric switch).

    Arrivals are quantized through the pipeline's intake hook (the
    watch-ingest point), so each batch lands before the next snapshot
    seals — the speculative solve-ahead then overlaps the previous
    cycle's close instead of being invalidated by its own bench driver.
    The serial arm injects the same batch right before each cycle: both
    arms' session k sees exactly arrival batches 0..k.

    Measurement hygiene (the fence-the-lane bugfix): an express lane is
    attached (the production co-resident state) but PARKED and drained
    before the floor probes and the measured window, so background lane
    state can never interleave with a timed sample; the per-arm floor
    probe notes (probe walls + sync/fetch counts) are recorded exactly
    as the warm-latency benches record theirs."""
    import gc
    import random
    import time as _time

    import volcano_tpu.scheduler.actions  # noqa: F401 (register actions)
    from volcano_tpu.api import objects
    from volcano_tpu.bench.clusters import (
        DEFAULT_TIERS, build_config, make_tiers)
    from volcano_tpu.scheduler.util.test_utils import (
        build_pod, build_pod_group)
    from volcano_tpu.utils import devprof

    total = cycles + warm
    rng = random.Random(seed)
    batches = []
    for k in range(total):
        n, budget = 0, 1.0
        while True:
            gap = rng.expovariate(rate_per_cycle)
            if gap > budget:
                break
            budget -= gap
            n += 1
        batches.append([
            (f"arr-{k:03d}-{j:02d}", rng.choice([1, 2, 4]),
             rng.choice([250, 500, 1000])) for j in range(n)])

    actions = ["allocate", "backfill"]
    args = {"tpuscore": {"tpuscore.mode": "rounds"}}

    def _arm(pipelined: bool):
        from volcano_tpu.express import ExpressLane
        from volcano_tpu.scheduler.framework import (
            close_session, open_session, run_actions)

        cache, _, _, _, n_tasks = build_config(5, scale)
        tiers = make_tiers(["tpuscore"], *DEFAULT_TIERS, arguments=args)
        lane = ExpressLane(cache)
        submit_t = {}
        waits = []

        orig_bind = cache.binder.bind
        orig_many = cache.binder.bind_many
        orig_keyed = getattr(cache.binder, "bind_many_keyed", None)

        def _record(keys, now):
            for key in keys:
                t = submit_t.get(key)
                if t is not None:
                    waits.append(now - t)

        def bind(pod, hostname):
            orig_bind(pod, hostname)
            _record([f"{pod.metadata.namespace}/{pod.metadata.name}"],
                    _time.perf_counter())

        def bind_many(pairs):
            pairs = list(pairs)
            orig_many(pairs)
            _record([f"{p.metadata.namespace}/{p.metadata.name}"
                     for p, _h in pairs], _time.perf_counter())

        cache.binder.bind, cache.binder.bind_many = bind, bind_many
        if orig_keyed is not None:
            # the bulk writeback prefers the keyed batch entrypoint
            def bind_many_keyed(keys, pods, hosts):
                orig_keyed(keys, pods, hosts)
                _record(list(keys), _time.perf_counter())

            cache.binder.bind_many_keyed = bind_many_keyed

        def inject(batch):
            now = _time.perf_counter()
            for name, tasks, cpu in batch:
                cache.add_pod_group(build_pod_group(
                    name, namespace="arr", min_member=tasks))
                for t in range(tasks):
                    pod = build_pod(
                        "arr", f"{name}-t{t}", "",
                        objects.POD_PHASE_PENDING,
                        {"cpu": f"{cpu}m", "memory": "256Mi"}, name)
                    cache.add_pod(pod)
                    submit_t[f"arr/{name}-t{t}"] = now

        pending = list(batches)
        drv = None
        if pipelined:
            from volcano_tpu.pipeline import PipelineDriver

            def intake():
                if pending:
                    inject(pending.pop(0))

            drv = PipelineDriver(
                cache, lambda: (actions, tiers), intake=intake)
            inject(pending.pop(0))  # batch 0, visible to cycle 0

        def cycle():
            if drv is not None:
                drv.run_cycle()
                return
            inject(pending.pop(0))
            ssn = open_session(cache, tiers)
            try:
                run_actions(ssn, actions)
            finally:
                close_session(ssn)

        try:
            from volcano_tpu.utils.jaxcompile import CompileWatcher

            watcher = CompileWatcher.install()
        except Exception:
            watcher = None
        win = None
        t_start = None
        floor = (None, None, None)
        for k in range(total):
            if k == warm:
                # measurement fence: background lane parked, device
                # drained, per-arm link floor pinned with its notes
                lane.park("bench_measurement")
                gc.collect()
                devprof.drain()
                floor = _measure_floor_ms()
                if watcher is not None:
                    win = watcher.window()
                t_start = _time.perf_counter()
                # waits bind only to POST-fence submissions: a warmup
                # arrival binding after the fence would otherwise charge
                # the gc/floor-probe wall to its submit->bind span
                submit_t.clear()
                waits.clear()
            cycle()
        devprof.drain()
        wall = _time.perf_counter() - t_start
        if drv is not None:
            drv.abandon()
        compiles = win.delta().compiles if win is not None else None
        ordered = sorted(waits)

        def pick(q):
            if not ordered:
                return 0.0
            return round(
                ordered[min(int(q * len(ordered)), len(ordered) - 1)] * 1e3,
                3)

        out = {
            "sessions_per_sec": round(cycles / wall, 3) if wall > 0 else 0.0,
            "measured_cycles": cycles,
            "wall_s": round(wall, 3),
            "mean_cycle_ms": round(wall / cycles * 1e3, 3),
            "p50_task_wait_ms": pick(0.50),
            "p99_task_wait_ms": pick(0.99),
            "binds": len(cache.binder.binds),
            "snapshot_tasks": n_tasks,
            "warm_compiles": compiles,
            "express_parked": bool(lane.parked),
            "tpu_floor_probe_notes": floor[2],
            "tpu_floor_ms": floor[0],
            "tpu_floor_spread_ms": floor[1],
        }
        if drv is not None:
            out["driver"] = {k: (dict(v) if isinstance(v, dict) else v)
                             for k, v in drv.stats.items()}
        return out

    # discarded prewarm arm: replays the identical schedule once so the
    # jit bucket ladder is saturated BEFORE either measured arm — without
    # it, whichever arm runs first pays every first-compile inside its
    # measured window and the sessions/sec ratio measures compile order,
    # not the pipeline
    _arm(pipelined=False)
    serial = _arm(pipelined=False)
    pipelined = _arm(pipelined=True)
    speedup = (pipelined["sessions_per_sec"] / serial["sessions_per_sec"]
               if serial["sessions_per_sec"] else 0.0)
    churn = _pipeline_churn(scale, batches, actions, args, seed,
                            warm=warm)
    return {
        "scale": scale,
        "arrival_rate_per_cycle": rate_per_cycle,
        "serial": serial,
        "pipeline": pipelined,
        "pipeline_sessions_per_sec": pipelined["sessions_per_sec"],
        "p99_submit_bind_ms": pipelined["p99_task_wait_ms"],
        "speedup_sessions_per_sec": round(speedup, 3),
        "churn": churn,
        "pipeline_spec_commit_rate": churn["commit_rate_readset"],
    }


def _pipeline_churn(scale, batches, actions, args, seed,
                    queue_rate_per_cycle: float = 3.0,
                    node_rate_per_cycle: float = 0.35, warm: int = 4):
    """The --pipeline churn arm (PR 15): replay run_pipeline's exact
    arrival schedule with a pregenerated Poisson mix of value-neutral
    deltas injected BETWEEN each speculation's seal and its apply —
    spec echoes on bystander queues no sealed solve ever consumed (the
    other-tenant watch-noise family, the dominant steady-state delta in
    a shared cluster), salted with node status echoes. Three arms on
    identical inputs:

      serial    — the byte-for-byte oracle (echoes are placement no-ops);
      whole_fp  — pipelined with VOLCANO_TPU_READSET=0: every echoed
                  window moves the coarse fingerprint, so the sealed
                  solve is discarded on ANY movement (~0 commit rate —
                  the pre-PR-15 behavior this arm keeps measurable);
      readset   — pipelined with the read-set seal: bystander-queue
                  noise is provably disjoint from the sealed read set,
                  so those windows COMMIT; the node-echo salt shows the
                  conservative direction in the same run (cfg5's
                  homogeneous node scores leave the windowed solve no
                  provable coverage, so its touched mask is full-width
                  and a node echo honestly discards — the partial-mask
                  commit case is pinned by tests/test_continuous_pipeline
                  on a window-exact regime).

    The acceptance triplet: readset commit rate >= 0.5 under churn where
    whole_fp sits at ~0, binds byte-identical across all three arms, and
    zero warm compiles in the readset arm's measured window (the echo
    stream must never perturb bucket shapes)."""
    import copy as _copy
    import os as _os
    import random

    import volcano_tpu.scheduler.actions  # noqa: F401 (register actions)
    from volcano_tpu.api import objects
    from volcano_tpu.bench.clusters import (
        DEFAULT_TIERS, build_config, make_tiers)
    from volcano_tpu.scheduler.util.test_utils import (
        build_pod, build_pod_group, build_queue)
    from volcano_tpu.utils import devprof

    total = len(batches)
    n_bystanders = 8
    rng = random.Random(seed * 7919)

    def _poisson_burst(rate):
        n, budget = 0, 1.0
        while True:
            gap = rng.expovariate(rate)
            if gap > budget:
                return n
            budget -= gap
            n += 1

    echoes = []
    for _ in range(total):
        burst = [("queue", rng.random())
                 for _ in range(max(_poisson_burst(queue_rate_per_cycle), 1))]
        burst += [("node", rng.random())
                  for _ in range(_poisson_burst(node_rate_per_cycle))]
        # at least one echo per window: every speculation faces a delta,
        # so a commit can never be the degenerate quiet-window kind
        echoes.append(burst)

    def _inject_jobs(cache, batch):
        for name, tasks, cpu in batch:
            cache.add_pod_group(build_pod_group(
                name, namespace="arr", min_member=tasks))
            for t in range(tasks):
                cache.add_pod(build_pod(
                    "arr", f"{name}-t{t}", "", objects.POD_PHASE_PENDING,
                    {"cpu": f"{cpu}m", "memory": "256Mi"}, name))

    def _arm(mode):
        from volcano_tpu.scheduler.framework import (
            close_session, open_session, run_actions)

        prev = _os.environ.get("VOLCANO_TPU_READSET")
        if mode == "whole_fp":
            _os.environ["VOLCANO_TPU_READSET"] = "0"
        try:
            cache, _, _, _, _ = build_config(5, scale)
            tiers = make_tiers(["tpuscore"], *DEFAULT_TIERS,
                               arguments=args)
            node_names = sorted(cache.nodes)
            # bystander queues exist BEFORE the first session: later
            # re-adds are spec echoes on an existing queue (the scoped
            # mark), never a queue-SET change (wholesale invalidation)
            bystanders = [build_queue(f"bystander-{i}", weight=1)
                          for i in range(n_bystanders)]
            for q in bystanders:
                cache.add_queue(q)
            pending = list(batches)
            drv = None
            if mode != "serial":
                from volcano_tpu.pipeline import PipelineDriver

                def intake():
                    if pending:
                        _inject_jobs(cache, pending.pop(0))

                drv = PipelineDriver(
                    cache, lambda: (actions, tiers), intake=intake)
                _inject_jobs(cache, pending.pop(0))
            try:
                from volcano_tpu.utils.jaxcompile import CompileWatcher

                watcher = CompileWatcher.install()
            except Exception:
                watcher = None
            win = None
            for k in range(total):
                if k == warm:
                    devprof.drain()
                    if watcher is not None:
                        win = watcher.window()
                if drv is not None:
                    drv.run_cycle()
                else:
                    _inject_jobs(cache, pending.pop(0))
                    ssn = open_session(cache, tiers)
                    try:
                        run_actions(ssn, actions)
                    finally:
                        close_session(ssn)
                # the echo stream lands AFTER this cycle sealed the next
                # solve-ahead — between seal and apply, the window the
                # whole-fingerprint seal can never survive
                for fam, frac in echoes[k]:
                    if fam == "queue":
                        cache.add_queue(_copy.deepcopy(
                            bystanders[int(frac * n_bystanders)
                                       % n_bystanders]))
                    else:
                        name = node_names[int(frac * len(node_names))
                                          % len(node_names)]
                        cache.add_node(
                            _copy.deepcopy(cache.nodes[name].node))
            devprof.drain()
            if drv is not None:
                drv.abandon()
            out = {
                "binds": dict(cache.binder.binds),
                "warm_compiles":
                    win.delta().compiles if win is not None else None,
            }
            if drv is not None:
                st = drv.stats
                out["spec_dispatched"] = st["spec_dispatched"]
                out["spec_applied"] = st["spec_applied"]
                out["spec_commits"] = dict(st["spec_commits"])
                out["spec_discards"] = dict(st["spec_discards"])
                out["commit_rate"] = round(
                    st["spec_applied"] / max(st["spec_dispatched"], 1), 4)
            return out
        finally:
            if prev is None:
                _os.environ.pop("VOLCANO_TPU_READSET", None)
            else:
                _os.environ["VOLCANO_TPU_READSET"] = prev

    serial = _arm("serial")
    whole = _arm("whole_fp")
    scoped = _arm("readset")
    return {
        "queue_echo_rate_per_cycle": queue_rate_per_cycle,
        "node_echo_rate_per_cycle": node_rate_per_cycle,
        "echo_deltas_total": sum(len(e) for e in echoes),
        "commit_rate_readset": scoped["commit_rate"],
        "commit_rate_whole_fingerprint": whole["commit_rate"],
        "spec_commits": scoped["spec_commits"],
        "spec_discards": scoped["spec_discards"],
        "whole_fp_discards": whole["spec_discards"],
        "binds_match_serial": scoped["binds"] == serial["binds"],
        "whole_fp_binds_match_serial": whole["binds"] == serial["binds"],
        "binds": len(serial["binds"]),
        "warm_compiles_readset": scoped["warm_compiles"],
    }


def _storm_headline(scale: float, seed: int = 7, duration: float = 60.0):
    """cfg5_storm sustained-throughput headline from the sim harness: the
    scheduler loop driven by Poisson arrivals instead of isolated warm
    probes (ROADMAP item 2's headline-metric switch). Returns the two
    numbers that bind — sustained sessions/sec and p99 submit->bind task
    wait — plus enough context to rescale them."""
    from volcano_tpu.sim.harness import SimCluster
    from volcano_tpu.sim.workload import load_scenario, scale_scenario

    cfg = scale_scenario(load_scenario("cfg5_storm"), scale)
    sim = SimCluster(cfg, seed=seed, repro_dir=None)
    s = sim.run(duration=duration)
    fb = s.get("fallbacks") or {}
    return {
        "sessions_per_sec": s["sessions_per_sec"],
        "p99_task_wait_s": s["task_wait_s"]["p99"],
        "sessions": s["sessions"],
        "binds": s["binds"],
        "scale": scale,
        "sim_duration_s": s["sim_duration_s"],
        # envelope honesty as a tracked trajectory number (ROADMAP item
        # 4): the same rates the sim auditor budgets in chaos_soak /
        # serving_mix, promoted into the standing tail
        "fallback_rates": {k: v for k, v in sorted(fb.items())
                           if k.endswith("_rate")},
    }


def _front_door_headline(scale: float = 0.5, seed: int = 7,
                         duration: float = 60.0):
    """front_door_storm headline from the sim harness (ROADMAP item 3's
    admission column): offered submissions/sec vs admitted-and-scheduled
    under a heavy-tailed storm, with the shed/coalesce rates the auditor
    budgets riding along."""
    from volcano_tpu.sim.harness import SimCluster
    from volcano_tpu.sim.workload import load_scenario, scale_scenario

    cfg = scale_scenario(load_scenario("front_door_storm"), scale)
    sim = SimCluster(cfg, seed=seed, repro_dir=None)
    s = sim.run(duration=duration)
    fd = s.get("front_door") or {}
    fb = s.get("fallbacks") or {}
    return {
        "submitted_per_sim_s": fd.get("submitted_per_sim_s"),
        "admitted_per_sim_s": fd.get("admitted_per_sim_s"),
        "binds": s["binds"],
        "sessions_per_sec": s["sessions_per_sec"],
        "admission_shed_rate": fb.get("admission_shed_rate"),
        "watch_coalesce_rate": fb.get("watch_coalesce_rate"),
        "watch_demotions": ((fd.get("watch") or {}).get(
            "counters") or {}).get("demotions"),
        "violations": s["audit"]["violations"],
        "scale": scale,
    }


def run_fanout_bench(watchers: int = 10000, batches: int = 40,
                     churn: int = 96, cap: int = 4096,
                     slow_every: int = 500, slow_stride: int = 8,
                     sample: int = 64, pods: int = 512):
    """Watch fan-out at 10k+ concurrent watchers over ONE shared journal.

    Synchronous (no threads — the shared-slice fast path is what's under
    test): each batch mutates ``churn`` pods, then every watcher polls
    once through the flow-control layer. Every ``slow_every``-th watcher
    only polls every ``slow_stride`` batches — the laggard tail that must
    ride bounded retention and demotion-to-resync instead of pinning the
    ring. Reports per-event delivery latency percentiles (append-stamp to
    delivery, sampled over the first ``sample`` watchers), throughput,
    and the per-watcher memory footprint — cursor + counters only, which
    is the O(events + watchers) proof."""
    import copy

    from volcano_tpu.api import objects
    from volcano_tpu.scheduler.util.test_utils import build_pod
    from volcano_tpu.store.flowcontrol import WatchFanout, WatcherState
    from volcano_tpu.store.gateway import _WatchJournal
    from volcano_tpu.store.store import Store

    store = Store()
    journal = _WatchJournal(store, "Pod", cap=cap)
    fanout = WatchFanout(journal, demote_lag=2 * cap, pin_factor=4)

    def make(i):
        pod = build_pod("bench", f"pod-{i:06d}", "",
                        objects.POD_PHASE_PENDING,
                        {"cpu": "100m", "memory": "64Mi"}, "")
        pod.metadata.ensure_identity()
        return pod

    live = []
    for i in range(pods):
        pod = make(i)
        store.create(pod)
        live.append(pod)
    cursors = [0] * watchers
    classes = ["interactive" if i % 3 == 0 else "batch"
               for i in range(watchers)]
    latencies = []
    delivered = resyncs = 0
    next_pod = pods
    wall0 = time.perf_counter()
    for batch in range(batches):
        for k in range(churn):
            idx = (batch * churn + k) % len(live)
            if k % 7 == 0:
                pod = make(next_pod)
                next_pod += 1
                store.create(pod)
                live.append(pod)
            else:
                cur = store.try_get("Pod", "bench",
                                    live[idx].metadata.name)
                if cur is None:
                    continue
                upd = copy.deepcopy(cur)
                upd.metadata.annotations["b"] = str(batch)
                store.update(upd)
        poll_t = time.monotonic()
        for i in range(watchers):
            if slow_every and i % slow_every == slow_every - 1 \
                    and batch % slow_stride != 0:
                continue  # the deliberately slow tail
            events, nxt, reset = fanout.poll_for(
                f"w{i:05d}", cursors[i], 0.0, cls=classes[i])
            cursors[i] = nxt
            if reset:
                resyncs += 1
                continue
            delivered += len(events)
            if i < sample:
                latencies.extend(poll_t - e["ts"] for e in events
                                 if "ts" in e)
    wall = time.perf_counter() - wall0
    latencies.sort()

    def pct(q):
        if not latencies:
            return 0.0
        return round(
            latencies[min(int(q * len(latencies)), len(latencies) - 1)]
            * 1e3, 3)

    ws_bytes = sys.getsizeof(WatcherState("x", "batch", 0)) \
        + sum(sys.getsizeof(getattr(WatcherState("x", "batch", 0), s))
              for s in WatcherState.__slots__)
    stats = fanout.watch_stats()
    return {
        "watchers": watchers,
        "batches": batches,
        "events_appended": stats["journal"]["appended"],
        "deliveries": delivered,
        "fanout_p50_ms": pct(0.50),
        "fanout_p99_ms": pct(0.99),
        "polls_per_sec": round(watchers * batches / wall, 1),
        "deliveries_per_sec": round(delivered / wall, 1),
        "coalesced": stats["counters"]["coalesced"],
        "demotions": stats["counters"]["demotions"],
        "resyncs": resyncs,
        "journal_peak_occupancy": stats["journal"]["peak_occupancy"],
        "journal_hard_cap": stats["journal"]["hard_cap"],
        "per_watcher_state_bytes": ws_bytes,
        "wall_s": round(wall, 3),
        "pid_rss_mb": _rss_mb(),
    }


def _rss_mb():
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except OSError:
        pass
    return None


def _standing_mesh_curve(scale: float):
    """The standing cfg7 mesh curve recorded in every all-configs run —
    in a SUBPROCESS: the CPU proxy needs the 8-virtual-device XLA flag,
    which must be set before the first jax import and must not reshape
    the main run's device platform. Returns the parsed tpu_mesh_curve
    summary object from the child's tail line."""
    import os
    import subprocess

    cmd = [sys.executable, os.path.abspath(__file__), "--mesh", "1,2,4,8",
           "--scale", str(scale), "--warm-iters", "2"]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=1800)
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        curve = obj.get("summary", {}).get("tpu_mesh_curve")
        if curve is not None:
            return curve
    raise RuntimeError(
        f"mesh-curve subprocess rc={r.returncode}: {r.stderr[-400:]}")


_FLOOR_PROBE = None  # (jitted no-op, device operand) or False when absent


def _floor_probe():
    """One compiled no-op dispatch+fetch — the link round-trip floor
    probe, built ONCE and shared by the startup [link] measurement and
    the per-sample floors (so both always measure the same thing).
    Returns (f, x) or None when jax/numpy are unavailable."""
    global _FLOOR_PROBE
    if _FLOOR_PROBE is None:
        try:
            import jax
            import jax.numpy as jnp
            import numpy as np

            f = jax.jit(lambda x: x + 1)
            x = jnp.zeros((1,), jnp.int32)
            np.asarray(f(x))  # compile outside any timed window
            _FLOOR_PROBE = (f, x)
        except Exception:
            _FLOOR_PROBE = False
    return _FLOOR_PROBE or None


def _probe_once_ms():
    """One timed probe round trip, or None. The probe is fenced (nothing
    queued may overlap it) and its fetch is routed through devprof so the
    sync/D2H budget lands in the floor annotations."""
    probe = _floor_probe()
    if probe is None:
        return None
    try:
        from volcano_tpu.utils import devprof

        f, x = probe
        devprof.drain()  # fence: probe measures ONLY its own round trip
        t0 = time.perf_counter()
        devprof.start_fetch(f(x))()
        return round((time.perf_counter() - t0) * 1e3, 3)
    except Exception:
        return None


def _measure_floor_ms(probes: int = 5):
    """Median-of-k floor measurement: (median_ms, spread_ms, annotation)
    or (None, None, None).

    A single probe inherits the tunnel's full per-RTT jitter — BENCH_r05's
    cfg6 floor samples swung 56->97 ms within one run, and every speedup
    ratio computed against such a floor inherits that noise. The median of
    k back-to-back probes is stable against one slow RTT; the spread
    (max - min) is recorded next to it, and the annotation carries every
    probe's wall plus the counted sync-point/D2H budget, so a drifting
    link is attributable in the record instead of silently reshaping the
    headline.

    The FIRST probe after the drain fence is systematically unlike the
    rest (cfg6 in BENCH_r05: a ~56 ms first probe against a ~96 ms stable
    tail — the fence leaves the link/device queue in a state no later
    probe sees), so it is discarded from the aggregate and carried in the
    annotation as first_probe_ms: the median and spread come from the
    stable tail only."""
    import statistics

    counters = {}
    try:
        from volcano_tpu.utils import devprof

        scope = devprof.session(counters)
    except Exception:  # pragma: no cover - minimal host
        class scope:  # noqa: N801 - inline null context
            def __enter__(self):
                return None

            def __exit__(self, *a):
                return None

        scope = scope()
    with scope:
        raw = [s for s in (_probe_once_ms() for _ in range(probes + 1))
               if s is not None]
    if not raw:
        return None, None, None
    first, samples = raw[0], (raw[1:] or raw)
    note = {"probes_ms": samples,
            "first_probe_ms": first,
            "sync_points": counters.get("tpu_sync_points"),
            "d2h_fetches": counters.get("tpu_d2h_fetches")}
    return (round(statistics.median(samples), 3),
            round(max(samples) - min(samples), 3), note)


def main() -> int:
    global _GC_POLICY
    from volcano_tpu.utils.gcpolicy import LowLatencyGC

    # the production scheduler loop runs under this policy (Scheduler._loop);
    # measuring without it would charge random full-heap GC pauses to
    # whichever phase they land in. run_config calls maintain() between
    # sessions, mirroring the loop's between-cycle collections.
    _GC_POLICY = LowLatencyGC.install()
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=None,
                    choices=[1, 2, 3, 4, 5, 6, 7],
                    help="run ONE config (default: all six, headline = cfg 5; "
                         "cfg6 = cfg2 + affinity/hostPort residue; cfg7 = "
                         "paper-2x 100k tasks x 50k nodes, the mesh-curve "
                         "standing config)")
    ap.add_argument("--all", action="store_true",
                    help="run all six configs (the default when --config is absent)")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--backend", choices=["serial", "tpu", "both", "auto"], default="auto")
    ap.add_argument("--serial-budget", type=float, default=30.0,
                    help="max seconds to spend measuring the serial loop per config")
    ap.add_argument("--warm-iters", type=int, default=5,
                    help="warm TPU sessions per config (>=1); the headline "
                         "binds on the MEDIAN e2e, and 5 samples keep one "
                         "link-jitter outlier from dragging it")
    ap.add_argument("--scenario", default=None,
                    help="source the cluster snapshot from a sim scenario "
                         "file or committed scenario name "
                         "(volcano_tpu/sim/scenarios) instead of the "
                         "built-in configs")
    ap.add_argument("--mesh", nargs="?", const="all", default=None,
                    help="bare flag: shard the node axis across all local "
                         "devices for the config runs. With a device-count "
                         "list (--mesh 1,2,4,8): run the cfg7 mesh-scaling "
                         "sweep instead, emitting tpu_mesh_curve in the "
                         "summary tail, then exit")
    ap.add_argument("--mesh-curve-scale", type=float, default=0.02,
                    help="cfg7 scale for the STANDING mesh curve recorded "
                         "in every all-configs run (the explicit "
                         "--mesh 1,2,4,8 sweep uses --scale)")
    ap.add_argument("--no-mesh-curve", action="store_true",
                    help="skip the standing cfg7 mesh curve in the "
                         "all-configs summary tail")
    ap.add_argument("--express", action="store_true",
                    help="express-lane mode: Poisson interactive arrivals "
                         "against a warm cfg5-scale snapshot; records "
                         "tpu_express_p50/p99_ms and the placed/deferred/"
                         "reconciled/reverted counts, then exits")
    ap.add_argument("--express-arrivals", type=int, default=96,
                    help="measured express batches (after 16 warmup)")
    ap.add_argument("--express-rate", type=float, default=50.0,
                    help="Poisson arrival rate for --express, jobs/sec")
    ap.add_argument("--pipeline", action="store_true",
                    help="continuous-pipeline mode: back-to-back sessions "
                         "under Poisson arrivals through the serial loop "
                         "AND volcano_tpu/pipeline on identical arrival "
                         "schedules; reports sustained sessions/sec, p99 "
                         "submit->bind task wait, the speculation "
                         "commit/discard ledger, and the sessions/sec "
                         "speedup, then exits")
    ap.add_argument("--pipeline-cycles", type=int, default=24,
                    help="measured back-to-back cycles per arm "
                         "(after 4 warmup cycles)")
    ap.add_argument("--pipeline-rate", type=float, default=3.0,
                    help="Poisson arrival rate for --pipeline, jobs/cycle")
    ap.add_argument("--fanout", nargs="?", const=10000, default=None,
                    type=int,
                    help="run the watch fan-out bench alone at N watchers "
                         "(default 10000) and print its summary tail")
    ap.add_argument("--no-fanout", action="store_true",
                    help="skip the standing 10k-watcher fan-out column in "
                         "the all-configs summary tail")
    ap.add_argument("--no-front-door", action="store_true",
                    help="skip the front_door_storm submissions/sec "
                         "headline in the all-configs summary tail")
    ap.add_argument("--no-storm", action="store_true",
                    help="skip the cfg5_storm sustained sessions/sec + p99 "
                         "task-wait headline (runs only in all-configs mode)")
    ap.add_argument("--storm-scale", type=float, default=0.01,
                    help="cfg5_storm scale for the throughput headline "
                         "(default matches the tier-1 sim gate)")
    ap.add_argument("--storm-duration", type=float, default=60.0,
                    help="cfg5_storm simulated horizon, seconds")
    args = ap.parse_args()

    if args.fanout is not None:
        # jax-free path: the fan-out bench exercises only the store/
        # journal/flow-control layer, so it runs (and exits) before any
        # device machinery loads
        result = run_fanout_bench(watchers=args.fanout)
        print(json.dumps({
            "metric": "watch fan-out p99 delivery latency @ %d watchers"
                      % args.fanout,
            "value": result["fanout_p99_ms"],
            "unit": "ms",
        }), flush=True)
        print(json.dumps({"summary": {"watch_fanout": result}},
                         separators=(",", ":")), flush=True)
        return 0

    mesh_counts = None
    if args.mesh is not None and args.mesh != "all":
        mesh_counts = sorted({max(int(x), 1)
                              for x in args.mesh.split(",") if x.strip()})
    # the mesh sweep needs multiple devices; on a CPU-only host force the
    # virtual device split BEFORE the first jax import (same flag the test
    # conftest pins) — a no-op when a real multi-device backend exists
    if mesh_counts is not None and max(mesh_counts) > 1:
        import os as _os

        flags = _os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            _os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count"
                        f"={max(mesh_counts)}").strip()

    if mesh_counts is not None:
        result = run_mesh_curve(args.scale, mesh_counts,
                                warm_iters=max(args.warm_iters // 2, 1))
        print(json.dumps({
            "metric": "cfg7 (paper-2x) per-device sharded-stage wall at "
                      "%d devices, x %s scale"
                      % (result["devices"][-1], args.scale),
            "value": result["curve"][-1].get("per_device_stage_ms", 0.0),
            "unit": "ms",
            "vs_baseline": result.get("sharded_stage_speedup", 0.0),
        }), flush=True)
        print(json.dumps({"summary": {"tpu_mesh_curve": result}},
                         separators=(",", ":")), flush=True)
        return 0

    if args.pipeline:
        result = run_pipeline(args.scale, cycles=args.pipeline_cycles,
                              rate_per_cycle=args.pipeline_rate)
        print(json.dumps({
            "metric": "pipelined sustained sessions/sec @ cfg5 x %s "
                      "under Poisson arrivals" % args.scale,
            "value": result["pipeline_sessions_per_sec"],
            "unit": "sessions/s",
            "vs_baseline": result["speedup_sessions_per_sec"],
        }), flush=True)
        print(json.dumps({"summary": {
            "cfg5_pipeline": {
                "pipeline_sessions_per_sec":
                    result["pipeline_sessions_per_sec"],
                "serial_sessions_per_sec":
                    result["serial"]["sessions_per_sec"],
                "speedup_sessions_per_sec":
                    result["speedup_sessions_per_sec"],
                "p99_submit_bind_ms": result["p99_submit_bind_ms"],
                "serial_p99_submit_bind_ms":
                    result["serial"]["p99_task_wait_ms"],
                "pipeline_warm_compiles":
                    result["pipeline"]["warm_compiles"],
                "spec": result["pipeline"].get("driver", {}),
                "pipeline_spec_discard_rate": round(
                    result["pipeline"].get("driver", {}).get(
                        "spec_discarded", 0)
                    / max(result["pipeline"].get("driver", {}).get(
                        "spec_dispatched", 0), 1), 4),
                # the churn arm's standing column (PR 15): the read-set
                # seal committing the solve-ahead through echo churn the
                # whole-fingerprint seal discards wholesale
                "pipeline_spec_commit_rate":
                    result["pipeline_spec_commit_rate"],
                "churn": result["churn"],
            },
            "pipeline_full": result,
        }}, separators=(",", ":"), default=str), flush=True)
        return 0

    if args.express:
        result = run_express(args.scale, arrivals=args.express_arrivals,
                             rate_per_s=args.express_rate)
        print(json.dumps({
            "metric": "express placement latency p99 (ms) @ cfg5 x %s"
                      % args.scale,
            "value": result["tpu_express_p99_ms"],
            "unit": "ms",
        }), flush=True)
        print(json.dumps({"summary": {"express": result}},
                         separators=(",", ":")), flush=True)
        return 0

    mesh = None
    if args.mesh:
        import jax
        import numpy as np
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) > 1:
            mesh = Mesh(np.array(devs), ("nodes",))

    # the device-link latency floor: one jitted no-op dispatch + 4-byte
    # fetch. On a co-located TPU this is ~100 us; on a tunneled PJRT link
    # it is the hard lower bound of any session's solve phase, recorded so
    # the BENCH numbers carry their own link context.
    rtt_floor_ms = None
    if args.backend in ("tpu", "both", "auto"):
        rtt_floor_ms, rtt_spread, _ = _measure_floor_ms(probes=7)
        if rtt_floor_ms is not None:
            print(f"[link] device round-trip floor: {rtt_floor_ms} ms "
                  f"(median of 7, spread {rtt_spread} ms)",
                  file=sys.stderr)

    def headline_json(headline):
        # the headline value is the MEDIAN e2e session latency — the full
        # open+actions+close span the production loop and the reference both
        # measure, at the middle of the link jitter (not the luckiest min)
        value = headline.get(
            "tpu_e2e_median_ms",
            headline.get("serial_e2e_ms",     # --backend serial: same span
                         headline.get("tpu_ms",
                                      headline.get("serial_ms", 0.0))))
        final = {
            "metric": "scheduler e2e session latency, warm median (ms) @ %dk tasks x %dk nodes"
                      % (int(50 * args.scale), int(10 * args.scale))
                      if headline["config"] == 5 else
                      f"scheduler e2e session latency, warm median (ms), cfg {headline['config']} ({headline['name']})",
            "value": round(value, 3),
            "unit": "ms",
            "vs_baseline": round(headline.get("speedup", 0.0), 3),
        }
        # host-side session bracket, first-session (wholesale snapshot)
        # and steady-state (delta-maintained snapshot) — the round-6
        # open/close story lives in these three numbers
        for src, dst in (("tpu_open_ms", "open_ms"),
                         ("tpu_close_ms", "close_ms"),
                         ("tpu_incr_open_close_ms", "incr_open_close_ms")):
            if src in headline:
                final[dst] = headline[src]
        # the headline baseline may be a reduced-scale serial run
        # extrapolated linearly in tasks x nodes — say so next to the
        # number it shaped
        if headline.get("serial_extrapolated"):
            final["serial_extrapolated"] = True
            final["serial_measured_scale"] = headline.get("serial_measured_scale")
        return final

    import os

    def write_record(results, final=None):
        # persist the COMPLETE record from here, re-written after EVERY
        # config: the driver keeps only the last 2,000 chars of stdout
        # (which lost cfg1/2/3/5 in rounds 3 AND 4), and a time-boxed
        # harness can kill the run mid-sweep — the file survives both
        try:
            import subprocess

            sha = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10,
                cwd=os.path.dirname(os.path.abspath(__file__))
            ).stdout.strip() or None
        except Exception:
            sha = None
        record = {"rtt_floor_ms": rtt_floor_ms, "git_sha": sha,
                  "argv": sys.argv[1:],
                  "complete": final is not None,
                  "results": [
                      {k: v for k, v in r.items() if k != "tpu_cold_profile"}
                      for r in results]}
        if final is not None:
            record["headline"] = {k: v for k, v in final.items()
                                  if k != "all_configs"}
        try:
            out_path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_local.json")
            with open(out_path, "w") as fh:
                json.dump(record, fh, indent=1)
                fh.write("\n")
        except Exception as e:
            print(f"[bench] could not write BENCH_local.json: {e}",
                  file=sys.stderr)

    results = []
    # headline (cfg 5) runs FIRST and prints its JSON line immediately: a
    # time-boxed harness that kills the run mid-way still captures the
    # headline number in its tail; the combined line (with all_configs)
    # prints last and supersedes it when the run completes
    if args.scenario is not None:
        cfgs = [0]  # one scenario-sourced run; headline falls through to it
    else:
        cfgs = [args.config] if args.config is not None else [5, 1, 2, 3, 4, 6]
    for cfg in cfgs:
        results.append(run_config(cfg, args.scale, args.backend,
                                  args.serial_budget, mesh=mesh,
                                  warm_iters=args.warm_iters,
                                  scenario=args.scenario))
        write_record(results)
        if cfg == 5 and len(cfgs) > 1:
            print(json.dumps(headline_json(results[0])), flush=True)

    headline = results[0] if cfgs[0] == 5 else results[-1]
    final = headline_json(headline)
    if rtt_floor_ms is not None:
        final["rtt_floor_ms"] = rtt_floor_ms
    if len(results) > 1:
        # tpu_profile (warm per-phase splits incl. pack/dispatch/apply and
        # the compile counters) stays in the record — the per-hop budget is
        # part of the result, not debug noise; only the verbose cold
        # profile is dropped
        final["all_configs"] = [
            {k: v for k, v in r.items() if k != "tpu_cold_profile"}
            for r in results
        ]
    write_record(results, final=final)
    print(json.dumps(final))
    # compact trajectory line, printed LAST: the driver keeps only the final
    # ~2,000 chars of stdout (cfg1/2/3/5 records were lost in rounds 3 and
    # 4 behind the full record above), so the whole-sweep summary — and
    # cfg4's per-action eviction-path timings — must fit in the tail
    summary = {}
    for r in results:
        entry = {
            "e2e_ms": r.get("tpu_e2e_median_ms", r.get("serial_e2e_ms")),
            "speedup": round(r.get("speedup", 0.0), 3),
        }
        # steady-state encode column (device replica, ROADMAP item 2):
        # the delta-fed figure the replica work binds on, next to the
        # cold-ish warm-session headline
        st = r.get("tpu_steady_state")
        if st is not None:
            entry["steady_encode_ms"] = st.get("encode_ms")
        if r["config"] == 4 and "tpu_action_ms" in r:
            entry["action_ms"] = {
                k: v for k, v in r["tpu_action_ms"].items()
                if k in ("preempt", "reclaim", "backfill")}
        summary[f"cfg{r['config']}"] = entry
    # sustained-throughput headline (ROADMAP item 2): cfg5_storm from the
    # sim harness, promoted into the same tail line as the warm latencies —
    # sessions/sec and p99 task wait are the numbers the continuous
    # pipeline work will bind on
    if (not args.no_storm and args.scenario is None
            and args.backend in ("tpu", "both", "auto") and len(cfgs) > 1):
        try:
            summary["cfg5_storm"] = _storm_headline(
                args.storm_scale, duration=args.storm_duration)
        except Exception as e:
            print(f"[bench] storm headline failed: {e}", file=sys.stderr)
    # the standing front-door columns (ROADMAP item 3): 10k-watcher
    # fan-out p50/p99 delivery latency + bounded per-watcher memory, and
    # the storm's offered-vs-admitted submissions/sec — tracked
    # trajectory numbers like sessions/sec
    if (not args.no_fanout and args.scenario is None and len(cfgs) > 1):
        try:
            summary["watch_fanout"] = run_fanout_bench()
        except Exception as e:
            print(f"[bench] fan-out bench failed: {e}", file=sys.stderr)
    if (not args.no_front_door and args.scenario is None
            and args.backend in ("tpu", "both", "auto") and len(cfgs) > 1):
        try:
            summary["front_door_storm"] = _front_door_headline()
        except Exception as e:
            print(f"[bench] front-door headline failed: {e}",
                  file=sys.stderr)
    # the standing mesh-scaling curve (ROADMAP item 3): cfg7 at 1/2/4/8
    # devices in every all-configs run, so mesh efficiency is a tracked
    # trajectory number like sessions/sec
    if (not args.no_mesh_curve and args.scenario is None
            and args.backend in ("tpu", "both", "auto") and len(cfgs) > 1):
        try:
            summary["tpu_mesh_curve"] = _standing_mesh_curve(
                args.mesh_curve_scale)
        except Exception as e:
            print(f"[bench] mesh curve failed: {e}", file=sys.stderr)
    print(json.dumps({"summary": summary}, separators=(",", ":")),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
