"""Express lane: reconciliation parity, revert hygiene, warm no-compile,
and the eligibility-envelope honesty contract (volcano_tpu/express).

The parity fuzz pins the load-bearing claim: an express-placed arrival
confirmed by the next full session lands the SAME end state the full
session would have produced on its own — same task -> node bindings, same
node accounting — because the express kernel reproduces the serial
allocator's scoring (fused least-requested + balanced) and visit order
for its envelope, and the reconciler reverts anything the session would
not have agreed to.
"""

from __future__ import annotations

import random

import pytest

import volcano_tpu.scheduler.actions  # noqa: F401 (register actions)
import volcano_tpu.scheduler.plugins  # noqa: F401 (register plugins)
from volcano_tpu.api import objects
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.bench.clusters import DEFAULT_TIERS, make_cache, make_tiers
from volcano_tpu.express import ExpressLane
from volcano_tpu.scheduler.framework import (
    close_session,
    open_session,
    run_actions,
)
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list_with_pods,
)

ACTIONS = ("enqueue", "allocate", "backfill")


def build_cluster(n_nodes=6, rng=None):
    cache = make_cache()
    rng = rng or random.Random(0)
    for n in range(n_nodes):
        cpu = rng.choice(["4", "8", "16"])
        mem = rng.choice(["8Gi", "16Gi", "32Gi"])
        cache.add_node(build_node(
            f"node-{n:03d}", build_resource_list_with_pods(cpu, mem,
                                                           pods=64),
            labels={"zone": f"zone-{n % 2}"}))
    cache.add_queue(build_queue("default"))
    return cache


def submit_job(cache, name, tasks=1, min_member=1, cpu="500m", mem="512Mi",
               ns="xp", priority=None, phase=objects.PodGroupPhase.INQUEUE,
               request_extra=None, node_selector=None):
    cache.add_pod_group(build_pod_group(
        name, namespace=ns, min_member=min_member, phase=phase))
    req = {"cpu": cpu, "memory": mem}
    if request_extra:
        req.update(request_extra)
    for i in range(tasks):
        cache.add_pod(build_pod(
            ns, f"{name}-t{i}", "", objects.POD_PHASE_PENDING, req, name,
            node_selector=node_selector, priority=priority))
    return f"{ns}/{name}"


def run_session(cache, actions=ACTIONS):
    ssn = open_session(cache, make_tiers(*DEFAULT_TIERS))
    try:
        run_actions(ssn, list(actions))
    finally:
        close_session(ssn)


def end_state(cache):
    """(task -> (status, node), node -> (cpu, mem) used) — the parity
    comparison surface."""
    tasks = {}
    for uid in sorted(cache.jobs):
        job = cache.jobs[uid]
        for tuid in sorted(job.tasks):
            t = job.tasks[tuid]
            tasks[t.key] = (t.status, t.node_name)
    nodes = {name: (round(cache.nodes[name].used.milli_cpu, 6),
                    round(cache.nodes[name].used.memory, 3))
             for name in sorted(cache.nodes)}
    return tasks, nodes


class TestExpressFastPath:
    def test_single_arrival_places_and_confirms(self):
        cache = build_cluster()
        lane = ExpressLane(cache)
        submit_job(cache, "svc-1")
        assert lane.has_pending()
        rep = lane.run_once()
        assert rep["placed"] == 1 and rep["deferred"] == 0
        job = cache.jobs["xp/svc-1"]
        (task,) = job.tasks.values()
        assert task.status == TaskStatus.BINDING and task.node_name
        assert cache.binder.binds["xp/svc-1-t0"] == task.node_name
        assert "xp/svc-1" in lane.outstanding
        run_session(cache)
        assert lane.outstanding == {}
        assert lane.counters["reconciled"] == 1
        assert lane.counters["reverted"] == 0
        # confirmed bind survives the session untouched
        assert job.tasks[task.uid].node_name == task.node_name

    def test_tiny_gang_places_all_or_nothing(self):
        cache = build_cluster()
        lane = ExpressLane(cache)
        submit_job(cache, "gang-1", tasks=2, min_member=2)
        rep = lane.run_once()
        assert rep["placed"] == 2
        job = cache.jobs["xp/gang-1"]
        assert all(t.status == TaskStatus.BINDING for t in job.tasks.values())

    def test_oversized_arrival_defers_whole_gang(self):
        # a gang whose members cannot ALL fit must not half-commit
        cache = make_cache()
        cache.add_node(build_node(
            "only", build_resource_list_with_pods("2", "4Gi", pods=64)))
        cache.add_queue(build_queue("default"))
        lane = ExpressLane(cache)
        submit_job(cache, "big", tasks=3, min_member=3, cpu="1000m")
        rep = lane.run_once()
        assert rep["placed"] == 0
        job = cache.jobs["xp/big"]
        assert all(t.status == TaskStatus.PENDING
                   for t in job.tasks.values())
        assert lane.outstanding == {}


class TestReconciliationParity:
    @pytest.mark.parametrize("seed", [1, 2, 3, 4])
    def test_express_plus_session_equals_session_only(self, seed):
        rng = random.Random(seed)
        shapes = []
        for i in range(rng.randint(2, 6)):
            gang = rng.random() < 0.4
            shapes.append(dict(
                name=f"job-{i:03d}",
                tasks=2 if gang else 1,
                min_member=2 if gang else 1,
                cpu=rng.choice(["250m", "500m", "1000m"]),
                mem=rng.choice(["256Mi", "512Mi", "1Gi"]),
            ))
        node_rng_a = random.Random(100 + seed)
        node_rng_b = random.Random(100 + seed)
        a = build_cluster(n_nodes=rng.randint(3, 8), rng=node_rng_a)
        b = build_cluster(n_nodes=len([n for n in a.nodes]),
                          rng=node_rng_b)
        lane = ExpressLane(a)
        for s in shapes:
            submit_job(a, **s)
            submit_job(b, **s)
        rep = lane.run_once()
        assert rep["placed"] > 0
        run_session(a)
        run_session(b)
        assert lane.counters["reverted"] == 0, lane.counters
        assert end_state(a) == end_state(b)

    def test_confirmed_binds_follow_serial_node_choice(self):
        # uneven nodes: the serial allocator's fused scoring picks a
        # specific node; express must pick the same one
        cache_a = make_cache()
        cache_b = make_cache()
        for c in (cache_a, cache_b):
            c.add_node(build_node(
                "small", build_resource_list_with_pods("2", "4Gi", pods=64)))
            c.add_node(build_node(
                "big", build_resource_list_with_pods("32", "64Gi", pods=64)))
            c.add_queue(build_queue("default"))
        lane = ExpressLane(cache_a)
        submit_job(cache_a, "pick-1")
        submit_job(cache_b, "pick-1")
        assert lane.run_once()["placed"] == 1
        run_session(cache_a)
        run_session(cache_b)
        assert end_state(cache_a) == end_state(cache_b)


class TestRevertHygiene:
    def test_broken_gang_reverts_with_zero_residue(self):
        """A gang that loses a member in the optimistic window is reverted
        by the next session through the real evict machinery, and the
        reverted bind leaves no residue in cache, mirror, or dirty-sets."""
        from volcano_tpu.cluster import Kubelet
        from volcano_tpu.scheduler.cache import SchedulerCache
        from volcano_tpu.store.store import Store

        store = Store()
        cache = SchedulerCache(store=store)
        cache.run()
        for n in range(3):
            store.create(build_node(
                f"node-{n}", build_resource_list_with_pods("8", "16Gi",
                                                           pods=64)))
        store.create(build_queue("default"))
        lane = ExpressLane(cache)
        store.create(build_pod_group("gang-x", namespace="xp",
                                     min_member=2))
        pods = [build_pod("xp", f"gang-x-t{i}", "",
                          objects.POD_PHASE_PENDING,
                          {"cpu": "500m", "memory": "512Mi"}, "gang-x")
                for i in range(2)]
        for pod in pods:
            pod.spec.scheduler_name = "volcano"
            store.create(pod)
        rep = lane.run_once()
        assert rep["placed"] == 2
        # the optimistic window: one member dies before the next session
        store.try_delete("Pod", "xp", "gang-x-t0")
        run_session(cache)
        assert lane.counters["reverted"] == 1
        assert "xp/gang-x" in lane.denylist
        assert lane.outstanding == {}
        # eviction completes through the normal machinery
        Kubelet(store).step()
        job = cache.jobs.get("xp/gang-x")
        live = list(job.tasks.values()) if job is not None else []
        assert not [t for t in live if t.node_name], live
        cache.flush_mirror()
        for name in sorted(cache.nodes):
            node = cache.nodes[name]
            assert not node.tasks, (name, sorted(node.tasks))
            used = node.used
            assert used.milli_cpu == 0 and used.memory == 0
        # a denylisted job never re-enters the lane
        lane.note_arrival("xp/gang-x")
        rep = lane.run_once()
        assert rep["placed"] == 0

    def test_queue_overuse_is_reverted(self):
        """proportion's deserved-share gate: an express bind that lands in
        an overused queue is reverted by the session (the authority check
        express itself deliberately does not model)."""
        cache = make_cache()
        cache.add_node(build_node(
            "n0", build_resource_list_with_pods("4", "8Gi", pods=64)))
        cache.add_queue(build_queue("greedy", weight=1))
        cache.add_queue(build_queue("other", weight=1))
        lane = ExpressLane(cache)
        # fill 'greedy' far past its 50% deserved share with resident load
        cache.add_pod_group(build_pod_group(
            "resident", namespace="xp", min_member=1, queue="greedy"))
        cache.add_pod(build_pod(
            "xp", "resident-t0", "n0", objects.POD_PHASE_RUNNING,
            {"cpu": "3000m", "memory": "6Gi"}, "resident"))
        # 'other' has pending demand, so deserved splits between queues
        cache.add_pod_group(build_pod_group(
            "waiting", namespace="xp", min_member=1, queue="other"))
        cache.add_pod(build_pod(
            "xp", "waiting-t0", "", objects.POD_PHASE_PENDING,
            {"cpu": "2000m", "memory": "4Gi"}, "waiting"))
        cache.add_pod_group(build_pod_group(
            "burst", namespace="xp", min_member=1, queue="greedy"))
        cache.add_pod(build_pod(
            "xp", "burst-t0", "", objects.POD_PHASE_PENDING,
            {"cpu": "500m", "memory": "512Mi"}, "burst"))
        rep = lane.run_once()
        assert rep["placed"] >= 1
        run_session(cache, actions=("allocate",))
        assert lane.counters["reverted"] >= 1
        assert "xp/burst" in lane.denylist


class TestWarmPath:
    def test_repeat_arrivals_do_not_recompile(self):
        from volcano_tpu.utils.jaxcompile import CompileWatcher

        cache = build_cluster()
        lane = ExpressLane(cache)
        # warm the program + the patch kernel (two cold compiles)
        for i in range(2):
            submit_job(cache, f"warm-{i}")
            assert lane.run_once()["placed"] == 1
        watcher = CompileWatcher.install()
        with watcher.assert_no_compiles("express repeat arrivals"):
            for i in range(4):
                submit_job(cache, f"hot-{i}")
                rep = lane.run_once()
                assert rep["placed"] == 1
                assert rep["profile"]["tpu_d2h_fetches"] == 1

    def test_dirty_rows_only_after_warm(self):
        cache = build_cluster()
        lane = ExpressLane(cache)
        submit_job(cache, "first")
        lane.run_once()
        assert lane.state.stats["rebuilds"] == 1
        submit_job(cache, "second")
        lane.run_once()
        # the second refresh patches the rows the first bind touched —
        # never a wholesale rebuild
        assert lane.state.stats["rebuilds"] == 1
        assert lane.state.stats["row_patches"] >= 1
        assert lane.state.stats["patched_rows"] <= 2


class TestEligibilityHonesty:
    def test_ineligible_arrivals_fall_through_to_session(self):
        cache = build_cluster(n_nodes=8)
        lane = ExpressLane(cache)
        submit_job(cache, "big-gang", tasks=6, min_member=6)  # > max_gang
        submit_job(cache, "gpu", request_extra={"nvidia.com/gpu": "1"})
        submit_job(cache, "selector", node_selector={"zone": "zone-0"})
        submit_job(cache, "unadmitted",
                   phase=objects.PodGroupPhase.PENDING)
        rep = lane.run_once()
        assert rep["placed"] == 0
        assert lane.outstanding == {}
        reasons = rep["reasons"]
        assert reasons.get("gang_too_big") == 1
        assert reasons.get("scalar_resources") == 1
        assert reasons.get("constraints") == 1
        assert reasons.get("not_admitted") == 1
        # the full session owns them all: gpu stays pending (no GPU
        # nodes), the rest place
        run_session(cache)
        for name in ("big-gang", "selector", "unadmitted"):
            job = cache.jobs[f"xp/{name}"]
            assert all(t.node_name for t in job.tasks.values()), name
        assert lane.counters["reverted"] == 0

    def test_unknown_plugin_disables_lane(self):
        cache = build_cluster()
        lane = ExpressLane(cache)
        lane.set_tiers(make_tiers(["priority", "gang"], ["binpack"]))
        assert not lane.enabled
        submit_job(cache, "svc-1")
        rep = lane.run_once()
        assert rep["placed"] == 0
        assert rep["reasons"] == {"lane_disabled": 1}
        lane.set_tiers(make_tiers(*DEFAULT_TIERS))
        assert lane.enabled
