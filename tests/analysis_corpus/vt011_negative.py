"""VT011 negative corpus — the sanctioned guards: conjunction with the
node-validity mask, an explicit real_n window, and one justified
suppression proving the disable comment is load-bearing."""

import jax.numpy as jnp


def _window_masked(elig, real, rr):
    # masking with the validity guard sanitizes the pad rows BEFORE the
    # cross-row count — the post-PR-16 _sample_window shape
    rolled = jnp.roll(elig & real, -rr)
    cs = jnp.cumsum(rolled.astype(jnp.int32))
    return cs


def _window_real_n(used, real_n):
    # the scalar-guard spelling: lanes past real_n are forced to the
    # neutral fill before the reduce
    n = used.shape[0]
    lanes = jnp.where(jnp.arange(n) < real_n, used, 0.0)
    return jnp.sum(lanes)


def _raw_probe(used, real):
    return jnp.sum(used)  # vclint: disable=VT011 - debug histogram: the probe harness zero-fills pad rows at allocation
