"""VT003 negative corpus: the discipline followed — reads under the lock,
writes after it, handlers that only mirror + enqueue, deferred closures,
and the suppression path."""

import threading


class GoodCache:
    def __init__(self, store):
        self.store = store
        self._lock = threading.Lock()
        self._jobs = {}
        self._queue = []
        store.watch("Job", WatchHandler(added=self._on_job))

    def _on_job(self, job):
        # handler contract: mirror + enqueue only
        self._queue.append(job)

    def writeback(self, pod):
        with self._lock:
            pending = list(self._jobs)
        # store write AFTER the lock is released — no ABBA window
        self.store.update(pod)
        return pending

    def lookup(self, key):
        with self._lock:
            return self._jobs.get(key)

    def deferred(self):
        with self._lock:
            def flush():
                # closure body runs later, outside the locked region
                self.store.update_status(self._jobs)
            self._cb = flush

    def legacy_sync(self):
        with self._lock:
            self.store.delete("Pod", "ns", "p")  # vclint: disable=VT003 - single-threaded bootstrap, store has no watchers yet


class GoodPipeline:
    """Pipeline scope, discipline followed: snapshot/fingerprint under
    the lock, dispatch and fetch strictly after it — the flush of cycle N
    overlaps the solve of N+1 without the cache lock bridging queues."""

    def __init__(self, cache):
        self.cache = cache
        self._lock = threading.Lock()

    def solve_ahead(self, spec, layout, staged):
        with self._lock:
            fingerprint = self.cache.fingerprint()
        dev = solve_rounds_packed(spec, layout, staged)  # after release
        return fingerprint, devprof.start_fetch(dev)

    def legacy_probe(self, spec, layout, staged):
        with self._lock:
            return solve_rounds_packed(spec, layout, staged)  # vclint: disable=VT003 - cold-start probe before any watcher attaches; nothing can contend


class GoodElector:
    """HA scope, discipline followed: the lease write happens after the
    record lock is released; the breaker gate never calls back into a
    self-lock-acquiring method while held."""

    def __init__(self, store):
        self.store = store
        self._record_lock = threading.Lock()
        self._record = None

    def renew(self, record):
        with self._record_lock:
            stale = self._record
        self.store.update(record)  # write AFTER release
        return stale

    def allow(self):
        with self._record_lock:
            return self._record is not None
