"""VT009 negative corpus — every bumped channel sealed, plus the
suppression path for a derived (transitively-sealed) channel."""


class SealedKeeper:
    def mark_local(self):
        self.local_epoch += 1

    def wholesale(self):
        self.local_gen += 1


class SealedCacheFingerprint:
    def pipeline_fingerprint(self):
        return (self.keeper.local_epoch, self.keeper.local_gen)


class DerivedMemo:
    def refresh(self):
        # a REAL unsealed-channel finding silenced only by the justified
        # suppression (the in-tree analog: nodeaxis.epoch, a derived memo
        # key sealed transitively via dirty_epoch + the acct sum)
        self.memo_epoch += 1  # vclint: disable=VT009 - corpus fixture: derived memo key, sealed transitively
