"""VT009 negative corpus — every bumped channel sealed, plus the
suppression path for a derived (transitively-sealed) channel."""


class SealedKeeper:
    def mark_local(self):
        self.local_epoch += 1

    def wholesale(self):
        self.local_gen += 1


class SealedCacheFingerprint:
    def pipeline_fingerprint(self):
        return (self.keeper.local_epoch, self.keeper.local_gen)


class ScopedIntersect:
    """PR 15 read-set scope, clean: the intersect consumes only channels
    the fingerprint below already seals (cursor exactness over
    local_epoch), so scoping can never outrun the seal."""

    def marks_since(self, cursor):
        if self.journal_base + len(self.journal) != self.local_epoch:
            return None
        return self.journal[cursor - self.journal_base:]


class DerivedMemo:
    def refresh(self):
        # a REAL unsealed-channel finding silenced only by the justified
        # suppression (the in-tree analog: nodeaxis.epoch, a derived memo
        # key sealed transitively via dirty_epoch + the acct sum)
        self.memo_epoch += 1  # vclint: disable=VT009 - corpus fixture: derived memo key, sealed transitively
