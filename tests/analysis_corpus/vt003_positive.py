"""VT003 positive corpus: re-entrant lock acquisition, store writes under a
held lock, and watch handlers that write back into the store."""

import threading


class BadCache:
    def __init__(self, store):
        self.store = store
        self._lock = threading.Lock()
        self._jobs = {}
        store.watch("Job", WatchHandler(added=self._on_job))

    def refresh(self):
        with self._lock:
            self._rebuild()  # vclint-expect: VT003

    def _rebuild(self):
        with self._lock:
            self._jobs.clear()

    def writeback(self, pod):
        with self._lock:
            self.store.update(pod)  # vclint-expect: VT003

    def _on_job(self, job):
        # watch handlers run under the STORE lock: a synchronous write
        # re-enters dispatch
        self.store.update_status(job)  # vclint-expect: VT003


class BadPipeline:
    """Pipeline scope: a device dispatch (or the devprof fetch seam)
    under the cache lock bridges host and device queues — every watch
    handler and effector stalls behind async device work (worse: an
    implicit compile)."""

    def __init__(self, cache):
        self.cache = cache
        self._lock = threading.Lock()

    def solve_ahead(self, spec, layout, staged):
        with self._lock:
            return solve_rounds_packed(spec, layout, staged)  # vclint-expect: VT003

    def fetch_under_lock(self, dev):
        with self._lock:
            wait = devprof.start_fetch(dev)  # vclint-expect: VT003
        return wait


class BadElector:
    """HA scope: the lease record lock sits UNDER the store lock in the
    callback graph — renewing (a store write) while holding it inverts
    the order exactly like a cache writeback would."""

    def __init__(self, store):
        self.store = store
        self._record_lock = threading.Lock()
        self._record = None

    def renew(self, record):
        with self._record_lock:
            self.store.update(record)  # vclint-expect: VT003

    def observe(self):
        with self._record_lock:
            self._refresh()  # vclint-expect: VT003

    def _refresh(self):
        with self._record_lock:
            self._record = self.store
