"""VT012 positive corpus — aliases of a donated buffer read after the
dispatch: the alias outlives the donation even though the donated NAME
itself is never touched again (that direct read is VT006's territory)."""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("spec",), donate_argnums=(1,))
def stage(spec, carry):
    return carry, carry


def driver(spec, carry, audit):
    # both of these capture the SAME device buffers the donation below
    # invalidates — rebinding 'carry' from the result does not help them
    mirror = carry if audit else None
    handle = carry["used"]
    packed, carry = stage(spec, carry)
    a = mirror["alloc"]  # vclint-expect: VT012
    b = handle.sum()  # vclint-expect: VT012
    return packed, carry, a, b
