"""VT008 positive corpus — inferred lock/field races and device
dispatch reached through a call made under a held lock."""

import threading


class RacyLane:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters = {}
        self.pending = []

    def noted(self, uid):
        # establishes the inferred guard: counters/pending belong to
        # self._lock
        with self._lock:
            self.counters[uid] = 1
            self.pending.append(uid)

    def racy(self, uid):
        self.counters[uid] = 2  # vclint-expect: VT008

    def racy_list(self, uid):
        self.pending.append(uid)  # vclint-expect: VT008

    def dispatch_under_lock(self, spec):
        with self._lock:
            return self._go(spec)  # vclint-expect: VT008

    def _go(self, spec):
        # the device sink is one call away — only the whole-program
        # closure walk sees it (VT003's lexical check cannot)
        return solve_rounds_packed(spec)


class LeakyJournal:
    """PR 12 front-door scope: blocking network sends under the journal
    lock serialize every watcher behind one slow peer."""

    def __init__(self):
        import threading

        self.cond = threading.Condition()
        self.events = []

    def broadcast_under_lock(self, req):
        with self.cond:
            return urlopen(req)  # vclint-expect: VT008

    def notify_under_lock(self, req):
        with self.cond:
            return self._push(req)  # vclint-expect: VT008

    def _push(self, req):
        # the send is one call away — the closure walk sees it
        return urlopen(req)
