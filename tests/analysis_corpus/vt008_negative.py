"""VT008 negative corpus — consistently guarded fields, transitively
lock-safe helpers, snapshot-then-dispatch, and the suppression path."""

import threading


class GoodLane:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters = {}

    def noted(self, uid):
        with self._lock:
            self.counters[uid] = 1

    def bump(self, uid):
        with self._lock:
            self.counters[uid] = 2

    def _helper(self, uid):
        # every call site is lexically under the lock -> transitively
        # lock-safe; this write is dynamically guarded
        self.counters[uid] = 3

    def outer(self, uid):
        with self._lock:
            self._helper(uid)

    def snapshot_then_dispatch(self, spec):
        # the sanctioned shape: snapshot under the lock, dispatch after
        with self._lock:
            snap = dict(self.counters)
        return self._go(snap, spec)

    def _go(self, snap, spec):
        return solve_rounds_packed(spec)

    def suppressed(self, uid):
        # a REAL inferred-guard violation silenced only by the justified
        # suppression
        self.counters[uid] = 4  # vclint: disable=VT008 - corpus fixture: exercises the suppression path


class GoodJournal:
    """PR 12 front-door scope: the sanctioned send shapes."""

    def __init__(self):
        import threading

        self.cond = threading.Condition()
        self.events = []

    def snapshot_then_send(self, req):
        # snapshot under the journal lock, send AFTER it
        with self.cond:
            batch = tuple(self.events)
        return self._push(batch, req)

    def _push(self, batch, req):
        return urlopen(req)

    def list(self, req):
        # a method shadowing a builtin name that happens to send
        return urlopen(req)

    def drain_under_lock(self):
        # traversal deliberately does NOT resolve builtin-shadow names
        # ("list", "get", ...): program-wide they alias dict/built-in
        # calls far more often than real send paths
        with self.cond:
            return self.list(None)
