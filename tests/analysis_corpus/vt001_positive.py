"""VT001 positive corpus: host syncs / impure calls inside jit regions.

Parsed by vclint only — never imported; names may be undefined at runtime.
Markers: a "vclint-expect" comment sits on every line the rule must flag
(the same convention holds across the corpus).
"""

import functools
import time

import jax
import numpy as np


@functools.partial(jax.jit, static_argnames=("spec",))
def solve(spec, arrays):
    total = arrays["req"].sum()
    budget = float(arrays["budget"][0])  # vclint-expect: VT001
    t0 = time.time()  # vclint-expect: VT001
    host = np.cumsum(arrays["req"])  # vclint-expect: VT001
    n = total.item()  # vclint-expect: VT001
    return _reachable_helper(total, budget, host, n, t0)


def _reachable_helper(total, budget, host, n, t0):
    # not decorated, but referenced from the jit root above -> in-region
    return total + budget + host + int(total[0]) + t0  # vclint-expect: VT001


@jax.jit
def solve_bare_decorator(arrays):
    return arrays["req"].item()  # vclint-expect: VT001


@functools.partial(jax.jit, static_argnames=("spec",))
def solve_evict_walk(spec, enc):
    # victim-axis walk: host syncs on traced cut state break the one-
    # dispatch eviction contract
    got = enc["vic_req"].sum(axis=1)
    covered = bool(got[0])  # vclint-expect: VT001
    chosen = np.argmax(got)  # vclint-expect: VT001
    t_cut = time.perf_counter()  # vclint-expect: VT001
    return covered, chosen, t_cut
