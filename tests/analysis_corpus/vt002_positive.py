"""VT002 positive corpus: raw len()/.shape extents reaching jit-static
sinks (pad sizes, SolveSpec fields, kernel-input allocations)."""

import numpy as np


def _bucket(n):
    b = 16
    while b < n:
        b *= 2
    return b


def _pad_axis(a, axis, size, fill=0):
    return a


def dispatch(enc, tasks, spec):
    t = len(tasks)
    arr = np.zeros((t, 4))  # vclint-expect: VT002
    padded = _pad_axis(arr, 0, enc["x"].shape[0])  # vclint-expect: VT002
    spec2 = spec._replace(round_min_progress=t)  # vclint-expect: VT002
    return solve_rounds(spec2, {"a": padded})  # vclint-expect: VT002


def build_spec(tasks):
    return SolveSpec(round_min_progress=len(tasks))  # vclint-expect: VT002


def window_rounds(scores, live_nodes):
    # candidate-window sizes are jit-static shapes: a raw live count here
    # re-keys the compiled program every churn
    k = len(live_nodes)
    top = lax.top_k(scores, k)  # vclint-expect: VT002
    w = scores.shape[-1] // 4
    return top, lax.top_k(scores, k=w)  # vclint-expect: VT002


def evict_dispatch(vic_rows, jobs, spec):
    # victim-axis width is a jit-static shape: a raw per-node victim count
    # re-keys the eviction program on every running-pod churn
    v = len(vic_rows[0])
    vic_req = np.zeros((8, v, 2))  # vclint-expect: VT002
    spec2 = EvictSpec(kind="preempt", log_rows=len(jobs))  # vclint-expect: VT002
    return solve_preempt(spec2, {"vic_req": vic_req})  # vclint-expect: VT002


def express_dispatch(batch, jobs, dev):
    # express batch axes are jit-static exactly like the rounds buckets: a
    # raw arrival count re-keys the express program on every batch size
    t = len(batch)
    spec = ExpressSpec(tb=t, jb=len(jobs), window_k=t * 4)  # vclint-expect: VT002
    req = np.zeros((t, 2))  # vclint-expect: VT002
    return solve_express(spec, req)  # vclint-expect: VT002


def sharded_stage(arrays, live_nodes, spec):
    # per-shard slice widths are jit-static shapes (the sharded encoder/
    # evict staging, ops/shard.py): keyed off raw GLOBAL N they re-key
    # every shard's program whenever the live node count churns — and at
    # 8 devices they size per-shard work off the wrong axis entirely
    width = len(live_nodes) // 8
    sl = np.zeros((width, 2))  # vclint-expect: VT002
    return solve_rounds(spec, {"node_idle": sl})


def replica_patch(dev, rows, vals):
    # the replica's dirty-row scatter: a raw churn count reaching the
    # index shape re-keys the shared row-scatter program on every delta
    idx = np.zeros((len(rows),), np.int32)  # vclint-expect: VT002
    return scatter_rows(dev, idx, vals)
