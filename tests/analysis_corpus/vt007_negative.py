"""VT007 negative corpus — covered mutations (mark-before, sync-after,
callee-closure, caller-coverage), the neutral() bless, and the
suppression path."""


class GoodCache:
    def __init__(self):
        self.jobs = {}
        self.nodes = {}
        self.snap_keeper = None
        self._echo = None

    def delete_job(self, uid):
        # mark-before-mutation on the same path
        self.snap_keeper.mark_job(uid)
        self.jobs.pop(uid, None)

    def flush(self, uid, version):
        # mutate-then-sync: the invalidation may legally FOLLOW the
        # mutation on the same path (the bulk-flush shape)
        self.jobs[uid] = object()
        self.snap_keeper.sync_job(uid, version)

    def delete_via_helper(self, uid):
        # callee closure: the helper carries the mark
        self._mark_and_drop(uid)

    def _mark_and_drop(self, uid):
        self.snap_keeper.mark_evict(uid, "")
        self.jobs.pop(uid, None)

    def echo(self, job, pg):
        if pg is self._echo:
            # vclint: neutral(same-object echo; the value is already visible to every clone)
            job.set_pod_group(pg)
            return
        self.snap_keeper.mark_job("uid")
        job.set_pod_group(pg)

    def _caller_covered(self, uid):
        # pure helper: every known caller marks before calling
        self.jobs.pop(uid, None)

    def covered_caller(self, uid):
        self.snap_keeper.mark_job(uid)
        self._caller_covered(uid)

    def suppressed_gap(self, uid):
        # a REAL finding silenced only by the justified suppression —
        # proves the disable comment is what silences the rule
        self.nodes.pop(uid, None)  # vclint: disable=VT007 - corpus fixture: exercises the suppression path


class GoodFanout:
    """PR 12 front-door scope: every watcher-map mutation bumps
    stats_gen (the memoized watch_stats() invalidation channel)."""

    def __init__(self):
        self.watchers = {}
        self.stats_gen = 0

    def register(self, wid):
        self.watchers[wid] = object()
        self.stats_gen += 1

    def unregister(self, wid):
        self.watchers.pop(wid, None)
        self.stats_gen += 1


class GoodReplica:
    """PR 13 device-replica scope: every standing-buffer swap bumps
    replica_epoch, the channel cache.pipeline_fingerprint seals."""

    def __init__(self):
        self.nodes = {}
        self.replica_epoch = 0

    def adopt(self, name, buf):
        self.nodes[name] = buf
        self.replica_epoch += 1

    def invalidate(self):
        self.nodes.pop("stale", None)
        self.replica_epoch += 1
