"""VT011 positive corpus — pad-tainted rows reaching unmasked cross-row
sinks: the pre-PR-10 window-count shape (roll + cumsum over the raw
eligibility mask) and an argsort over pad-garbage node payloads."""

import jax.numpy as jnp


def _window_unmasked(elig, real, rr):
    # the pre-PR-10 bug shape: rolling the RAW eligibility mask brings
    # pad rows into the window before the count
    rolled = jnp.roll(elig, -rr)
    cs = jnp.cumsum(rolled.astype(jnp.int32))  # vclint-expect: VT011
    return cs


def _rank_unmasked(used, real):
    # argsort over a node-axis payload: pad rows hold stale garbage and
    # land anywhere in the permutation
    order = jnp.argsort(used)  # vclint-expect: VT011
    return order
