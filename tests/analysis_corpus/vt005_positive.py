"""VT005 positive corpus: unsorted set iteration feeding dense arrays."""

import numpy as np


def encode(tasks, names):
    uids = {t.uid for t in tasks}
    rows = [lookup(u) for u in uids]  # vclint-expect: VT005
    for name in set(names):  # vclint-expect: VT005
        rows.append(name)
    order = list(uids)  # vclint-expect: VT005
    return np.array(rows), order


def merge(seen, extra):
    combined = set(seen) | set(extra)
    out = []
    while combined:
        out.append(combined.pop())  # vclint-expect: VT005
    return out


def encode_victim_axis(nodes):
    # victim claimee order must be deterministic: set iteration over the
    # victim jobs reorders the cumulative drf/proportion walks per process
    vic_jobs = {t.job for nd in nodes for t in nd.tasks}
    rows = [job_row(j) for j in vic_jobs]  # vclint-expect: VT005
    return np.array(rows)


def sim_fire_faults(engine, flap_names, flip):
    # sim determinism: a chaos injector iterating its down-node SET while
    # scheduling re-add events reorders the virtual event log per process
    down_nodes = {n for n in flap_names}
    for name in down_nodes:  # vclint-expect: VT005
        engine.schedule(name)
    pending = {j for j in flip}
    return [audit(j) for j in pending]  # vclint-expect: VT005


def takeover_drain(tokens, rungs):
    # HA scope: the new leader's first session drains standby-era express
    # tokens — set iteration here reorders the revert/confirm event log
    # and forks the same-seed hash between active and standby
    undrained = {t.uid for t in tokens}
    for uid in undrained:  # vclint-expect: VT005
        drain(uid)
    active = {r for r in rungs}
    return [publish(r) for r in active]  # vclint-expect: VT005
