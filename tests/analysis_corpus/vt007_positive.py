"""VT007 positive corpus — snapshot-bearing mutations that can complete
without reaching any invalidation channel (mark / version bump /
fingerprint component)."""


class MiniCache:
    def __init__(self):
        self.jobs = {}
        self.nodes = {}
        self.snap_keeper = None
        self._echo = None

    def delete_job_unmarked(self, uid):
        # no invalidation anywhere in this function's closure, and no
        # effectful caller exists — the mutation is orphaned
        self.jobs.pop(uid, None)  # vclint-expect: VT007

    def echo_window(self, job, pg):
        # the PR 9 shape: the early-return echo path mutates WITHOUT the
        # mark the normal path performs — it needs an explicit
        # neutral(<reason>) bless or a mark of its own
        if pg is self._echo:
            job.set_pod_group(pg)  # vclint-expect: VT007
            return
        self.snap_keeper.mark_job("uid")
        job.set_pod_group(pg)

    def empty_bless(self, uid):
        # a neutral() bless with no reason is itself a finding — the
        # grammar requires the WHY, exactly like VT000 for suppressions
        self.jobs.pop(uid, None)  # vclint: neutral()  # vclint-expect: VT007


class MiniFanout:
    """PR 12 front-door scope: the watcher map's stats snapshot is
    memoized on stats_gen — a mutation that skips the bump serves stale
    lag/demotion accounting forever."""

    def __init__(self):
        self.watchers = {}
        self.stats_gen = 0

    def register_unmarked(self, wid):
        self.watchers[wid] = object()  # vclint-expect: VT007

    def drop_unmarked(self, wid):
        del self.watchers[wid]  # vclint-expect: VT007


class MiniReplica:
    """PR 13 device-replica scope: standing-buffer swaps must move the
    replica epoch (the sealed consumer-invalidation channel) or a
    memoized whole-encode prepare replays against the old content."""

    def __init__(self):
        self.nodes = {}
        self.replica_epoch = 0

    def adopt_unbumped(self, name, buf):
        self.nodes[name] = buf  # vclint-expect: VT007
