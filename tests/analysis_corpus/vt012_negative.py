"""VT012 negative corpus — aliases rebound from the dispatch result
before any further read (the sanctioned carry-threading idiom extended
to derived handles), plus a justified suppression for a path-correlated
ghost alias the may-analysis cannot see is dead."""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("spec",), donate_argnums=(1,))
def stage(spec, carry):
    return carry, carry


def driver(spec, carry):
    handle = carry["used"]
    probe = handle.shape  # pre-dispatch reads are legal
    packed, carry = stage(spec, carry)
    handle = carry["used"]  # re-derived from the NEW carry
    return packed, probe, handle.sum()


def driver_suppressed(spec, carry, audit):
    mirror = carry if audit else None
    packed, carry = stage(spec, carry)
    tail = mirror if audit else packed  # vclint: disable=VT012 - audit mode pins donation off upstream: mirror is only non-None when stage ran undonated
    return packed, tail
