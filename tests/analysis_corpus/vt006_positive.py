"""VT006 positive corpus: donated buffers read host-side after dispatch."""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("spec",), donate_argnums=(1,))
def stage(spec, carry):
    return carry


@functools.partial(
    jax.jit, static_argnames=("layout",),
    donate_argnums=(1, 2))
def stage_two(layout, carry, scratch):
    return carry


def driver(spec, carry):
    packed = stage(spec, carry)
    total = carry["used"].sum()  # vclint-expect: VT006
    return packed, total


def driver_two(layout, carry, scratch):
    out = stage_two(layout, carry, scratch)
    # reading EITHER donated argument after dispatch is a stale deref
    leak = scratch  # vclint-expect: VT006
    return out, leak


def driver_chain(spec, carry):
    # donation without rebinding, then a second dispatch reads the corpse
    stage(spec, carry)
    return stage(spec, carry)  # vclint-expect: VT006
