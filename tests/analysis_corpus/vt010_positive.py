"""VT010 positive corpus — int32 ranges that exceed 2**31-1 at the cfg7
bucket extents (100k tasks x 50k nodes, mesh-padded): the pre-PR-16
flat op-log encoding and an unbounded per-node-cap running sum."""

import jax.numpy as jnp


def _log_append_flat(log, node, slot, vic_job):
    # the pre-PR-16 evict op-log encoding: node * V_WIDTH + slot spans
    # ~6.6e9 at NODES_PAD x V_WIDTH extents — silently wraps in int32
    v_width = vic_job.shape[1]
    code = node * v_width + slot  # vclint-expect: VT010
    return log.at[0, 1].set(code)


def _quadratic_caps(node_maxt):
    # per-node caps carry no mass bound (unlike per-node counts): the
    # running sum genuinely reaches NODES_PAD * TASKS at the extremes
    cs = jnp.cumsum(node_maxt)  # vclint-expect: VT010
    return cs
