"""VT009 positive corpus — invalidation channels bumped by mutation
paths but absent from the speculation fingerprint's sealed tuple."""


class LeakyKeeper:
    def mark_foo(self):
        # a channel the fingerprint below never reads: a speculative
        # solve sealed before this bump would commit against state it
        # never saw
        self.foo_epoch += 1  # vclint-expect: VT009

    def wholesale(self):
        self.baz_gen += 1  # vclint-expect: VT009

    def mark_bar(self):
        self.bar_epoch += 1  # sealed below — clean


class LeakyCacheFingerprint:
    def pipeline_fingerprint(self):
        # seals bar_epoch but neither foo_epoch nor baz_gen
        return (self.keeper.bar_epoch,)


class LeakyReplica:
    """PR 13 device-replica scope: device content that moves behind an
    unsealed channel — a speculative prepare sealed before the scatter
    would replay stale standing buffers."""

    def scatter(self):
        self.buffer_seq += 1  # vclint-expect: VT009
