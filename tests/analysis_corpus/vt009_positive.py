"""VT009 positive corpus — invalidation channels bumped by mutation
paths but absent from the speculation fingerprint's sealed tuple."""


class LeakyKeeper:
    def mark_foo(self):
        # a channel the fingerprint below never reads: a speculative
        # solve sealed before this bump would commit against state it
        # never saw
        self.foo_epoch += 1  # vclint-expect: VT009

    def wholesale(self):
        self.baz_gen += 1  # vclint-expect: VT009

    def mark_bar(self):
        self.bar_epoch += 1  # sealed below — clean


class LeakyCacheFingerprint:
    def pipeline_fingerprint(self):
        # seals bar_epoch but neither foo_epoch nor baz_gen
        return (self.keeper.bar_epoch,)


class LeakyReplica:
    """PR 13 device-replica scope: device content that moves behind an
    unsealed channel — a speculative prepare sealed before the scatter
    would replay stale standing buffers."""

    def scatter(self):
        self.buffer_seq += 1  # vclint-expect: VT009


class LeakyIntersect:
    """PR 15 read-set scope: the seal/intersect path consumes a channel
    the fingerprint never seals — movement on it alone can never trigger
    the re-check, so a sealed stage commits as a quiet window."""

    def marks_since(self, cursor):
        if cursor < self.policy_epoch:  # vclint-expect: VT009
            return None
        return self.journal[cursor:]


class LeakyDriverCheck:
    """Same hole one call deep: the consumer closure must follow the
    intersect into its helpers."""

    def _readset_check(self, st):
        return self._delta_ok(st)

    def _delta_ok(self, st):
        return st.cursor == self.mesh_gen  # vclint-expect: VT009
