"""VT009 positive corpus — invalidation channels bumped by mutation
paths but absent from the speculation fingerprint's sealed tuple."""


class LeakyKeeper:
    def mark_foo(self):
        # a channel the fingerprint below never reads: a speculative
        # solve sealed before this bump would commit against state it
        # never saw
        self.foo_epoch += 1  # vclint-expect: VT009

    def wholesale(self):
        self.baz_gen += 1  # vclint-expect: VT009

    def mark_bar(self):
        self.bar_epoch += 1  # sealed below — clean


class LeakyCacheFingerprint:
    def pipeline_fingerprint(self):
        # seals bar_epoch but neither foo_epoch nor baz_gen
        return (self.keeper.bar_epoch,)
