"""VT000 corpus: a suppression with no justification is itself a finding —
the gate cannot be quietly eroded."""


def probe(x):
    return x.value.item()  # vclint: disable=VT001
