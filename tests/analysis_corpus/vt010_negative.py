"""VT010 negative corpus — the sanctioned ways to carry wide products:
int64 widening, mass-conserved indicator sums, explicit low-bit masking,
a machine-checked headroom bless, and one justified suppression."""

import jax.numpy as jnp


def _flat_code_wide(node, slot, vic_job):
    # widened BEFORE the product: int64 holds NODES_PAD * V_WIDTH fine
    v_width = vic_job.shape[1]
    code = node.astype(jnp.int64) * v_width + slot
    return code


def _indicator_mass(node_cnt):
    # per-node counts are mass-conserved (each task counted once): the
    # running sum is bounded by TASKS, not NODES_PAD * TASKS
    return jnp.cumsum(node_cnt)


def _masked_lanes(node_maxt):
    # the low-bit mask caps every element at 2**15-1 before the sum:
    # NODES_PAD * 0x7FFF stays under 2**31
    return jnp.cumsum(node_maxt & 0x7FFF)


def _blessed_tight_cap(node, t_cap):
    # the abstract cap on t_cap is TASKS, but cfg7 pins the per-step
    # admission cap at 4096 — prove the real bound instead of widening
    rows = node * t_cap  # vclint: headroom(NODES_PAD * 4096)
    return rows


def _suppressed_overflow(node_maxt):
    cs = jnp.cumsum(node_maxt)  # vclint: disable=VT010 - host-only debug path: replayed on numpy int64, never traced
    return cs
