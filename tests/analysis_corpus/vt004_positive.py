"""VT004 positive corpus: statements with tentative ops dropped on the
floor — no commit()/discard() and no ownership transfer."""


def place_no_close(ssn, tasks, host):
    stmt = ssn.statement()
    for t in tasks:
        stmt.allocate(t, host)  # vclint-expect: VT004
    return True


def evict_no_close(ssn, victim):
    st = ssn.statement()
    st.evict(victim, "preempt")  # vclint-expect: VT004
    if victim.ready():
        return victim
    return None


def sim_slice_drops_statement(ssn, gang, host):
    # a sim harness replaying an eviction plan must close what it opens
    stmt = ssn.statement()
    for t in gang:
        stmt.evict(t, "chaos")  # vclint-expect: VT004
    return len(gang)
