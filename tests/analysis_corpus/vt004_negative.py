"""VT004 negative corpus: the commit-or-discard gate followed, caller-owned
statements, ownership escapes, and the suppression path."""


def place(ssn, tasks, host):
    stmt = ssn.statement()
    ok = True
    for t in tasks:
        try:
            stmt.allocate(t, host)
        except KeyError:
            ok = False
            break
    if ok and ssn.job_ready():
        stmt.commit()
    else:
        stmt.discard()


def helper_owns_nothing(stmt, task, host):
    # caller-owned statement (a parameter): closing is the caller's job
    stmt.pipeline(task, host)


def build(ssn, task):
    stmt = ssn.statement()
    stmt.allocate(task, "n1")
    return stmt  # escapes to the caller, which commits/discards


def delegate(ssn, task, closer):
    stmt = ssn.statement()
    stmt.allocate(task, "n1")
    closer(stmt)  # ownership handed to the closer callable


def fire_and_forget(ssn, task, host):
    stmt = ssn.statement()
    stmt.pipeline(task, host)  # vclint: disable=VT004 - session-local pipeline, never committed by design


def sim_slice_closes_statement(ssn, gang, host, ok):
    stmt = ssn.statement()
    for t in gang:
        stmt.evict(t, "chaos")
    if ok:
        stmt.commit()
    else:
        stmt.discard()
