"""VT001 negative corpus: host work outside jit regions, static casts
inside them, and the suppression path. vclint must stay silent here."""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("chunk",))
def solve(chunk, arrays):
    # float() of a static python scalar (a bare name) is trace-time config,
    # not a host sync of a traced value
    big = jnp.asarray(float(chunk), arrays["req"].dtype)
    return jnp.cumsum(arrays["req"]) + big


def host_prepare(arrays):
    # host-side encode path: numpy + wall clocks are fine outside jit
    t0 = time.time()
    pad = np.zeros_like(arrays["req"])
    return pad, time.time() - t0


def host_probe(x):
    # .item() on the host fetch path, not reachable from any jit root
    return x.item()


@jax.jit
def debug_solve(arrays):
    probe = arrays["req"].item()  # vclint: disable=VT001 - debug-only kernel, gated off the warm path
    return probe


@functools.partial(jax.jit, static_argnames=("spec",))
def solve_evict_walk(spec, enc):
    # the victim cut stays traced end to end: jnp reductions, no host casts
    got = jnp.cumsum(enc["vic_req"], axis=1)
    covered = jnp.all(enc["need"] < got[-1])
    return jnp.where(covered, jnp.argmax(got[-1]), -1)


def encode_victims(nodes):
    # host-side victim-axis encode: numpy is fine outside the jit region
    rows = np.zeros((len(nodes), 4, 2))
    return rows
