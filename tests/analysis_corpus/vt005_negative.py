"""VT005 negative corpus: sorted iteration, order-free set uses
(membership, sizes), dict iteration (insertion-ordered), and the
suppression path."""


def encode(tasks, names):
    uids = {t.uid for t in tasks}
    rows = [lookup(u) for u in sorted(uids)]
    seen = set()
    out = []
    for t in tasks:
        if t.uid in seen:  # membership is order-free
            continue
        seen.add(t.uid)
        out.append(t)
    count = len(uids)  # size is order-free
    by_name = {t.name: t for t in out}
    for name in by_name:  # dicts iterate in insertion order — deterministic
        count += 1
    return rows, out, count


def commutative_fold(names, weight):
    scratch = {n for n in names}
    total = 0.0
    for n in scratch:  # vclint: disable=VT005 - feeds a commutative sum; order cannot change the result
        total += weight(n)
    return total


def encode_victim_axis(nodes):
    # victim claimee order from dict iteration (insertion-ordered) plus a
    # sorted dedup: deterministic across replicas
    vic_jobs = {t.job for nd in nodes for t in nd.tasks}
    return [job_row(j) for j in sorted(vic_jobs)]


def sim_fire_faults(engine, down_nodes, flip):
    # the sim's replay contract: sorted() pins the event order
    for name in sorted(down_nodes):
        engine.schedule(name)
    pending = {j for j in flip}
    return [audit(j) for j in sorted(pending)]


def takeover_drain(tokens, rungs):
    # HA scope: sorted() pins the drain order — active and standby replay
    # the takeover identically under the same seed
    undrained = {t.uid for t in tokens}
    for uid in sorted(undrained):
        drain(uid)
    active = {r for r in rungs}
    return [publish(r) for r in sorted(active)]
