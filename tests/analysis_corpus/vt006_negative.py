"""VT006 negative corpus: the sanctioned carry-threading idiom (rebind the
donated name from the dispatch result before any further read), plus a
justified suppression proving the disable comment is load-bearing."""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("spec",), donate_argnums=(1,))
def stage(spec, carry):
    return carry, carry


@functools.partial(jax.jit, static_argnames=("spec",))
def stage_undonated(spec, carry):
    return carry


def driver(spec, carry):
    # rebinding from the call's own result clears the donation: every
    # later read sees the NEW carry, never the invalidated buffer
    packed, carry = stage(spec, carry)
    packed2, carry = stage(spec, carry)
    return packed, packed2, carry["used"]


def driver_undonated(spec, carry):
    out = stage_undonated(spec, carry)
    return out, carry["used"]  # no donation — reads stay legal


def driver_suppressed(spec, carry):
    packed = stage(spec, carry)
    shape = carry["used"].shape  # vclint: disable=VT006 - CPU-backend test shim: donation is a no-op there and this reads metadata only
    return packed, shape
