"""VT002 negative corpus: bucketed extents, post-pad shape reads, host-only
allocations, and the suppression path."""

import numpy as np


def _bucket(n):
    b = 16
    while b < n:
        b *= 2
    return b


def _pad_axis(a, axis, size, fill=0):
    return a


def dispatch(enc, tasks, spec):
    # the pad-to-bucket contract, followed
    tb = _bucket(len(tasks))
    arr = np.zeros((tb, 4))
    arrays = pad_encoded(enc)
    # shapes read back from padded buffers are bucket-stable
    kb = int(arrays["cls_req"].shape[0])
    spec2 = spec._replace(round_min_progress=max(2, kb // 128))
    out = _pad_axis(arr, 0, tb)
    return solve_rounds(spec2, {"a": out})


def host_stats(enc, tasks):
    # no kernel dispatch in this function: host accounting buffers may be
    # sized by live counts freely
    return np.zeros((len(tasks), 2))


def mesh_pad(a, node_multiple):
    n = a.shape[0]
    nb = ((n + node_multiple - 1) // node_multiple) * node_multiple
    return _pad_axis(a, 0, nb)  # vclint: disable=VT002 - mesh-multiple node pad; node count is deployment-stable


def window_rounds(scores, live_nodes, spec):
    # window widths off the bucket ladder (or the jit-static spec) are
    # compile-stable
    k = _bucket(len(live_nodes))
    top = lax.top_k(scores, k)
    return top, lax.top_k(scores, spec.window_k)


def evict_dispatch(vic_rows, jobs, spec):
    # victim-axis width off the bucket ladder: compile-stable across
    # running-pod churn
    v = _bucket(len(vic_rows[0]))
    vic_req = np.zeros((8, v, 2))
    return solve_preempt(spec, {"vic_req": vic_req})


def express_dispatch(batch, jobs, n_nodes):
    # express buckets off the same ladder: repeat arrivals of any size up
    # to the bucket reuse one compiled program, and the candidate window
    # comes from the blessed ladder helper
    tb = _bucket(len(batch))
    jb = _bucket(len(jobs))
    spec = ExpressSpec(tb=tb, jb=jb, window_k=window_for(n_nodes, tb))
    req = np.zeros((tb, 2))
    return solve_express(spec, req)


def sharded_stage(arrays, spec):
    # the sharded-staging discipline: pad the node axis to the device
    # multiple first (append-only, deployment-stable like the mesh pad),
    # then derive the per-shard width from THAT padded extent — both
    # helpers are ladder-blessed, so per-shard shapes are mesh-stable
    nb = pad_axis_multiple(arrays["node_idle"], 0, 8).shape[0]
    width = per_shard(nb, 8)
    sl = np.zeros((width, 2))
    return solve_rounds(spec, {"node_idle": sl})


def replica_patch(dev, rows, arrays):
    # the replica's dirty-row scatter: the index is padded by the blessed
    # bucket helper, so churn of any size up to the bucket reuses ONE
    # compiled row-scatter program
    idx = bucket_pad_rows(rows)
    vals = {k: arrays[k][idx] for k in arrays}
    return scatter_rows(dev, idx, vals)
