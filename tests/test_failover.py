"""Fenced active/standby failover + the fault-degradation ladder.

Four layers (docs/DESIGN.md §15):

1. lease-epoch fencing at the store: stale-epoch writes rejected with
   exact accounting, lease writes advance the fence atomically, the
   FencedStoreView facade, and the HTTP hop (ApiGateway + RemoteStore)
   preserving the FencedError subtype end-to-end;
2. the two-elector race: over one store, exactly one epoch's binds land
   — before AND after a leadership transition;
3. warm standby + FailoverScheduler: a non-leading member keeps its
   snapshot warm and takes over binding authority with the fence
   stamped before its first session;
4. the degradation ladder: deterministic capped/jittered backoff,
   per-dependency circuit breakers on the virtual clock, the bounded
   session-skip budget, and the scheduler loop actually honoring it.
"""

from __future__ import annotations

import threading
import time
import urllib.request

import pytest

from volcano_tpu.api import objects
from volcano_tpu.scheduler import degrade, metrics
from volcano_tpu.scheduler.cache import SchedulerCache
from volcano_tpu.scheduler.cache.cache import (
    DefaultBinder,
    DefaultEvictor,
    DefaultStatusUpdater,
)
from volcano_tpu.scheduler.ha import FailoverScheduler, WarmStandby
from volcano_tpu.scheduler.leaderelection import (
    LeaderElectionRecord,
    LeaderElector,
    ResourceLock,
)
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list_with_pods,
)
from volcano_tpu.store import FencedError, FencedStoreView, Store
from volcano_tpu.store.gateway import ApiGateway
from volcano_tpu.store.remote import RemoteStore
from volcano_tpu.utils import clock

FAST = dict(lease_duration=0.5, renew_deadline=0.3, retry_period=0.1)


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def _lease(store, transitions=0, holder="h1"):
    """Write a lease record through the real resource-lock path; the
    store's fence advances to transitions + 1 in the same atomic step."""
    lock = ResourceLock(store, "volcano-system", "vc-scheduler", holder)
    got = lock.get()
    now = time.monotonic()
    new = LeaderElectionRecord(
        holder_identity=holder, lease_duration=30.0,
        acquire_time=now, renew_time=now, leader_transitions=transitions)
    if got is None:
        assert lock.create(new)
    else:
        assert lock.update(new, got[1])


# ---------------------------------------------------------------------------
# 1. store-level fencing
# ---------------------------------------------------------------------------


class TestStoreFencing:
    def test_unstamped_writes_always_pass(self):
        store = Store()
        _lease(store)  # fence armed at epoch 1
        pod = build_pod("ns", "p", "", objects.POD_PHASE_PENDING,
                        {"cpu": "1"}, "")
        store.create(pod)         # controllers/kubelets carry no stamp
        store.update(pod)
        store.delete("Pod", "ns", "p")
        assert store.fence_stats["rejected"] == 0

    def test_stale_epoch_rejected_with_accounting(self):
        store = Store()
        pod = build_pod("ns", "p", "", objects.POD_PHASE_PENDING,
                        {"cpu": "1"}, "")
        store.create(pod, epoch=0)  # no lease yet: 0 >= fence 0 passes
        _lease(store)               # epoch 1
        assert store.fence_epoch == 1
        with pytest.raises(FencedError):
            store.update(pod, epoch=0)
        with pytest.raises(FencedError):
            store.update_status(pod, epoch=0)
        with pytest.raises(FencedError):
            store.delete("Pod", "ns", "p", epoch=0)
        store.update(pod, epoch=1)  # the current term still writes
        stats = store.fence_stats
        assert stats["rejected"] == 3
        assert stats["rejected_by_kind"] == {"Pod": 3}
        assert stats["rejected_by_epoch"] == {0: 3}

    def test_fenced_error_is_a_conflict(self):
        # every pre-existing 409/conflict handler must keep working
        from volcano_tpu.store import ConflictError

        assert issubclass(FencedError, ConflictError)

    def test_lease_transition_advances_fence_never_lowers(self):
        store = Store()
        _lease(store, transitions=0)
        assert store.fence_epoch == 1
        _lease(store, transitions=4, holder="h2")  # takeover
        assert store.fence_epoch == 5
        _lease(store, transitions=1, holder="h3")  # replayed old lease
        assert store.fence_epoch == 5, "fence must be monotonic"
        store.advance_fence(3)
        assert store.fence_epoch == 5
        store.advance_fence(9)
        assert store.fence_epoch == 9

    def test_clean_release_keeps_fence(self):
        store = Store()
        _lease(store, transitions=2)
        assert store.fence_epoch == 3
        # a released lease (empty holder) keeps the current epoch in
        # force: un-led intervals must not reopen the old term's window
        _lease(store, transitions=2, holder="")
        assert store.fence_epoch == 3

    def test_fenced_store_view_stamps_every_mutator(self):
        store = Store()
        epoch = {"v": 1}
        view = FencedStoreView(store, lambda: epoch["v"])
        pod = build_pod("ns", "p", "", objects.POD_PHASE_PENDING,
                        {"cpu": "1"}, "")
        view.create(pod)
        _lease(store, transitions=4)  # fence jumps to 5
        with pytest.raises(FencedError):
            view.update(pod)
        with pytest.raises(FencedError):
            view.update_status(pod)
        with pytest.raises(FencedError):
            view.delete("Pod", "ns", "p")
        epoch["v"] = 5  # the view re-reads the source at call time
        view.update(pod)
        # reads pass through unchanged
        assert view.get("Pod", "ns", "p") is not None
        assert view.try_delete("Pod", "ns", "missing") is None

    def test_effectors_count_fenced_rejections(self):
        store = Store()
        pod = build_pod("ns", "p", "", objects.POD_PHASE_PENDING,
                        {"cpu": "1"}, "")
        store.create(pod)
        _lease(store, transitions=1)  # fence 2
        binder = DefaultBinder(store)
        evictor = DefaultEvictor(store)
        updater = DefaultStatusUpdater(store)
        for eff in (binder, evictor, updater):
            eff.fence_epoch = 1  # the deposed term's stamp
        with pytest.raises(FencedError):
            binder.bind(pod, "n1")
        with pytest.raises(FencedError):
            evictor.evict(pod, "test")
        cond = objects.PodCondition(
            type="PodScheduled", status="False", reason="x", message="")
        updater.update_pod_condition(pod, cond)  # swallowed, counted
        assert binder.fenced_rejections == 1
        assert evictor.fenced_rejections == 1
        assert updater.fenced_rejections == 1
        assert store.fence_stats["rejected"] == 3

    def test_metrics_counter_tracks_rejections(self):
        metrics.reset()
        store = Store()
        pod = build_pod("ns", "p", "", objects.POD_PHASE_PENDING,
                        {"cpu": "1"}, "")
        store.create(pod)
        _lease(store)
        with pytest.raises(FencedError):
            store.update(pod, epoch=0)
        assert metrics.registry().fenced_writes_rejected.get() == 1


# ---------------------------------------------------------------------------
# 1b. fencing across the HTTP hop
# ---------------------------------------------------------------------------


class TestGatewayFencing:
    def test_epoch_stamp_enforced_and_subtype_survives(self):
        store = Store()
        gateway = ApiGateway(store).start()
        try:
            remote = RemoteStore(f"127.0.0.1:{gateway.port}")
            # a REMOTE elector arms the fence through the gateway: the
            # lease CAS and the write-authority revocation are one step
            _lease(remote, transitions=0, holder="remote-a")
            assert store.fence_epoch == 1
            pod = build_pod("ns", "p", "", objects.POD_PHASE_PENDING,
                            {"cpu": "1"}, "")
            remote.create(pod)  # unstamped: fine
            pod = remote.get("Pod", "ns", "p")
            pod.spec.node_name = "n1"
            with pytest.raises(FencedError):
                remote.update(pod, epoch=0)
            remote.update(pod, epoch=1)
            with pytest.raises(FencedError):
                remote.delete("Pod", "ns", "p", epoch=0)
            with pytest.raises(FencedError):
                remote.create(build_pod(
                    "ns", "p2", "", objects.POD_PHASE_PENDING,
                    {"cpu": "1"}, ""), epoch=0)
            remote.delete("Pod", "ns", "p", epoch=1)
            assert store.fence_stats["rejected"] == 3
        finally:
            gateway.stop()

    def test_malformed_epoch_is_a_400(self):
        store = Store()
        gateway = ApiGateway(store).start()
        try:
            remote = RemoteStore(f"127.0.0.1:{gateway.port}")
            pod = build_pod("ns", "p", "", objects.POD_PHASE_PENDING,
                            {"cpu": "1"}, "")
            remote.create(pod)
            req = urllib.request.Request(
                f"http://127.0.0.1:{gateway.port}/apis/Pod/ns/p?epoch=abc",
                method="DELETE")
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(req, timeout=5)
            assert exc_info.value.code == 400
        finally:
            gateway.stop()


# ---------------------------------------------------------------------------
# 2. the two-elector race
# ---------------------------------------------------------------------------


class TestTwoElectorRace:
    def test_exactly_one_epochs_binds_land(self):
        """Two electors race from scratch over one store; each side binds
        with ITS elector's epoch. Only the winner's binds land — and
        after a transition, only the NEW epoch's."""
        store = Store()
        ea = LeaderElector(
            ResourceLock(store, "volcano-system", "vc-scheduler", "a"),
            lambda: None, lambda: None, **FAST)
        eb = LeaderElector(
            ResourceLock(store, "volcano-system", "vc-scheduler", "b"),
            lambda: None, lambda: None, **FAST)
        ea.start()
        eb.start()
        try:
            assert _wait(lambda: ea.is_leader() or eb.is_leader())
            time.sleep(0.2)  # let the loser observe the lease
            winner, loser = (ea, eb) if ea.is_leader() else (eb, ea)
            assert not (ea.is_leader() and eb.is_leader()), "split brain"

            def bind_with(elector, name):
                import copy

                store.create(build_pod(
                    "ns", name, "", objects.POD_PHASE_PENDING,
                    {"cpu": "1"}, ""))
                # bind a CLONE, as the scheduler cache does — the store
                # must stay pristine when the write is fenced
                pod = copy.deepcopy(store.get("Pod", "ns", name))
                binder = DefaultBinder(store)
                binder.fence_epoch = elector.epoch()
                binder.bind(pod, "n1")
                return store.get("Pod", "ns", name)

            assert bind_with(winner, "w1").spec.node_name == "n1"
            with pytest.raises(FencedError):
                bind_with(loser, "l1")  # epoch 0: never led
            assert store.get("Pod", "ns", "l1").spec.node_name == ""

            # transition: the winner releases, the loser takes over with
            # a HIGHER epoch; the deposed term's stamp is now fenced
            deposed_epoch = winner.epoch()
            winner.stop()
            assert _wait(loser.is_leader, timeout=3.0)
            assert loser.epoch() > deposed_epoch
            assert bind_with(loser, "l2").spec.node_name == "n1"
            import copy

            store.create(build_pod(
                "ns", "w2", "", objects.POD_PHASE_PENDING,
                {"cpu": "1"}, ""))
            pod = copy.deepcopy(store.get("Pod", "ns", "w2"))
            stale = DefaultBinder(store)
            stale.fence_epoch = deposed_epoch
            with pytest.raises(FencedError):
                stale.bind(pod, "n1")
            assert store.get("Pod", "ns", "w2").spec.node_name == ""
        finally:
            ea.stop()
            eb.stop()

    def test_elector_epoch_survives_loss(self):
        """A deposed elector keeps its stale epoch (never regresses to
        unfenced 0) so in-flight writes stay rejectable."""
        store = Store()
        el = LeaderElector(
            ResourceLock(store, "volcano-system", "vc-scheduler", "a"),
            lambda: None, lambda: None, **FAST)
        el.start()
        try:
            assert _wait(el.is_leader)
            epoch = el.epoch()
            assert epoch >= 1
        finally:
            el.stop()
        assert not el.is_leader()
        assert el.epoch() == epoch


# ---------------------------------------------------------------------------
# 3. warm standby + FailoverScheduler
# ---------------------------------------------------------------------------


def _seed_cluster(store, pods=3):
    store.create(build_queue("default"))
    store.create(build_node(
        "n1", build_resource_list_with_pods("8", "16Gi")))
    store.create(build_pod_group("pg0", namespace="default", min_member=1))
    for i in range(pods):
        store.create(build_pod(
            "default", f"seed-{i}", "", objects.POD_PHASE_PENDING,
            {"cpu": "100m"}, "pg0"))


class TestWarmStandby:
    def test_follow_keeps_snapshot_incremental(self):
        store = Store()
        _seed_cluster(store)
        cache = SchedulerCache(store=store, scheduler_name="volcano")
        standby = WarmStandby(cache, follow_period=0.02).start()
        try:
            assert _wait(lambda: standby.stats["follows"] >= 3)
            rebuilds0 = cache.snap_keeper.stats["rebuilds"]
            # churn while following: deltas absorbed incrementally
            store.create(build_pod(
                "default", "late", "", objects.POD_PHASE_PENDING,
                {"cpu": "100m"}, "pg0"))
            follows = standby.stats["follows"]
            assert _wait(lambda: standby.stats["follows"] >= follows + 2)
            assert cache.snap_keeper.stats["rebuilds"] == rebuilds0, \
                "standby follow paid a wholesale rebuild"
            assert cache.snap_keeper.stats["incremental"] >= 2
            # pause (leading): the loop stops following
            standby.pause()
            paused_at = standby.stats["follows"]
            time.sleep(0.1)
            assert standby.stats["follows"] <= paused_at + 1
            standby.resume()
            assert _wait(
                lambda: standby.stats["follows"] > paused_at + 1)
        finally:
            standby.stop()
            cache.detach_watches()

    def test_failover_scheduler_moves_binding_authority(self):
        """Two FailoverScheduler members over one store: the leader binds
        under its fence epoch; on its death the warm standby takes over,
        stamps the NEXT epoch, and binds — while the store's fence holds
        the deposed term out."""
        store = Store()
        store.create(build_queue("default"))
        store.create(build_node(
            "n1", build_resource_list_with_pods("8", "16Gi")))

        def member(identity):
            cache = SchedulerCache(store=store, scheduler_name="volcano")
            sched = Scheduler(cache, schedule_period=0.05)
            return FailoverScheduler(
                sched, store, identity=identity,
                follow_period=0.05, **FAST)

        a = member("a").start()
        assert _wait(a.is_leader)
        b = member("b").start()
        try:
            time.sleep(0.2)
            assert not b.is_leader()
            store.create(build_pod_group(
                "pg1", namespace="default", min_member=1))
            store.create(build_pod(
                "default", "p1", "", objects.POD_PHASE_PENDING,
                {"cpu": "1"}, "pg1"))
            assert _wait(lambda: (store.get("Pod", "default", "p1")
                                  .spec.node_name == "n1"), timeout=3.0)
            epoch_a = a.elector.epoch()
            assert a.scheduler.cache.fence_epoch == epoch_a
            assert store.fence_epoch == epoch_a

            a.stop()  # the active member dies; the standby must take over
            assert _wait(b.is_leader, timeout=3.0)
            assert b.elector.epoch() > epoch_a
            assert b.scheduler.cache.fence_epoch == b.elector.epoch()
            assert store.fence_epoch == b.elector.epoch()
            store.create(build_pod_group(
                "pg2", namespace="default", min_member=1))
            store.create(build_pod(
                "default", "p2", "", objects.POD_PHASE_PENDING,
                {"cpu": "1"}, "pg2"))
            assert _wait(lambda: (store.get("Pod", "default", "p2")
                                  .spec.node_name == "n1"), timeout=3.0)
            # the deposed term's stamp no longer writes
            pod = store.get("Pod", "default", "p1")
            with pytest.raises(FencedError):
                store.update(pod, epoch=epoch_a)
        finally:
            a.stop()
            b.stop()


# ---------------------------------------------------------------------------
# 4. the degradation ladder
# ---------------------------------------------------------------------------


class TestBackoff:
    def test_deterministic_capped_jittered(self):
        a = degrade.Backoff("x", base=0.5, cap=4.0)
        b = degrade.Backoff("x", base=0.5, cap=4.0)
        da = [a.next_delay() for _ in range(8)]
        db = [b.next_delay() for _ in range(8)]
        assert da == db, "same name must retry identically (replay)"
        assert degrade.Backoff("y", base=0.5, cap=4.0).next_delay() != da[0]
        # jittered delays live in [peek*(1-jitter), peek], capped
        c = degrade.Backoff("z", base=0.5, cap=4.0, jitter=0.5)
        for i in range(10):
            peek = c.peek()
            assert peek <= 4.0
            d = c.next_delay()
            assert peek * 0.5 <= d <= peek
        assert c.peek() == 4.0  # capped, not 0.5 * 2**10
        c.reset()
        assert c.peek() == 0.5
        assert c.stats()["retries"] == 10
        assert c.stats()["total_backoff_s"] > 0

    def test_rejects_nonsense_parameters(self):
        with pytest.raises(ValueError):
            degrade.Backoff("x", base=0.0)
        with pytest.raises(ValueError):
            degrade.Backoff("x", base=2.0, cap=1.0)
        with pytest.raises(ValueError):
            degrade.Backoff("x", factor=0.5)


class TestCircuitBreaker:
    def test_open_halfopen_close_cycle_on_virtual_clock(self):
        t = {"now": 1000.0}
        clock.set_source(lambda: t["now"])
        try:
            br = degrade.CircuitBreaker("dep", threshold=3, cooldown_s=10.0)
            assert br.allow()
            br.record_failure()
            br.record_failure()
            assert br.state == degrade.CircuitBreaker.CLOSED
            br.record_failure()
            assert br.state == degrade.CircuitBreaker.OPEN
            assert not br.allow()
            t["now"] += 9.9
            assert not br.allow()
            t["now"] += 0.2  # cooldown elapsed: exactly one probe
            assert br.allow()
            assert br.state == degrade.CircuitBreaker.HALF_OPEN
            br.record_failure()  # probe failed: straight back to OPEN
            assert br.state == degrade.CircuitBreaker.OPEN
            t["now"] += 10.1
            assert br.allow()
            br.record_success()
            assert br.state == degrade.CircuitBreaker.CLOSED
            assert br.stats["opens"] == 2
            assert br.stats["probes"] == 2
            assert br.stats["closes"] == 1
        finally:
            clock.set_source(None)

    def test_success_resets_consecutive_failures(self):
        br = degrade.CircuitBreaker("dep", threshold=3)
        br.record_failure()
        br.record_failure()
        br.record_success()
        br.record_failure()
        br.record_failure()
        assert br.state == degrade.CircuitBreaker.CLOSED


class TestDegradeLadder:
    def test_session_skip_budget_is_bounded(self):
        ladder = degrade.DegradeLadder(
            store_threshold=2, store_cooldown_s=1e9, max_session_skips=3)
        for _ in range(2):
            ladder.note_store_error()
        assert ladder.rung() == "session_skip"
        skips = [ladder.should_skip_session() for _ in range(4)]
        # 3 skips then a FORCED session — a dead probe can never park the
        # scheduler forever (bounded staleness)
        assert skips == [True, True, True, False]
        assert ladder.counters["sessions_skipped"] == 3
        assert ladder.counters["forced_sessions"] == 1
        ladder.note_store_ok()
        assert ladder.rung() == ""
        assert not ladder.should_skip_session()

    def test_kernel_breaker_forces_serial_and_recovers(self):
        t = {"now": 0.0}
        clock.set_source(lambda: t["now"])
        try:
            ladder = degrade.DegradeLadder(
                kernel_threshold=2, kernel_cooldown_s=5.0)
            assert not ladder.force_serial()
            ladder.note_kernel_failure()
            ladder.note_kernel_failure()
            assert ladder.force_serial()
            assert ladder.rung() == "serial_host_solve"
            t["now"] += 5.1
            # the half-open probe lets exactly one dispatch through
            assert not ladder.force_serial()
            ladder.note_kernel_ok()
            assert ladder.rung() == ""
        finally:
            clock.set_source(None)

    def test_rungs_published_on_metrics(self):
        metrics.reset()
        ladder = degrade.DegradeLadder(store_threshold=1,
                                       store_cooldown_s=1e9)
        ladder.note_store_error()
        body = metrics.render()
        assert 'volcano_degraded_mode{rung="session_skip"} 1' in body
        ladder.note_store_ok()
        body = metrics.render()
        assert 'volcano_degraded_mode{rung="session_skip"} 0' in body

    def test_process_default_ladder_shared_and_resettable(self):
        ladder = degrade.default_ladder()
        assert degrade.default_ladder() is ladder
        degrade.note_kernel_failure()
        assert ladder.counters["per_action_fallbacks"] == 1
        degrade.reset()
        assert degrade.default_ladder() is not ladder


class TestSchedulerSessionSkip:
    def test_loop_skips_then_forces_bounded_staleness_session(self):
        store = Store()
        _seed_cluster(store, pods=1)
        cache = SchedulerCache(store=store, scheduler_name="volcano")
        sched = Scheduler(cache, schedule_period=0.02)
        ladder = sched.degrade
        ladder.max_session_skips = 4
        for _ in range(ladder.store.threshold):
            ladder.note_store_error()  # remote store declared down
        assert ladder.rung() == "session_skip"
        sched.run()
        try:
            # the loop skips while the breaker holds, then the staleness
            # budget forces a session; that session succeeds against the
            # in-process store and closes the breaker
            assert _wait(
                lambda: ladder.counters["forced_sessions"] >= 1,
                timeout=5.0)
            assert ladder.counters["sessions_skipped"] >= 4
            assert _wait(lambda: ladder.rung() == "", timeout=5.0)
        finally:
            sched.stop()


class TestRemoteWatchBackoff:
    def test_poll_failures_back_off_and_surface_counters(self):
        # no gateway behind this address: every poll errors; the retry
        # loop must back off (never fixed-interval hammer) and meter it
        remote = RemoteStore("127.0.0.1:1", timeout=0.2)
        from volcano_tpu.store.store import WatchHandler

        remote.watch("Pod", WatchHandler(), poll_timeout=0.05)
        try:
            assert _wait(
                lambda: remote.watch_stats()["poll_errors"] >= 3,
                timeout=10.0)
            stats = remote.watch_stats()
            assert stats["backoff_s"] > 0
            assert stats["max_backoff_s"] > 0
            assert stats["polls"] == 0
        finally:
            remote.stop_watches()

    def test_healthy_polls_do_not_back_off(self):
        store = Store()
        gateway = ApiGateway(store).start()
        try:
            remote = RemoteStore(f"127.0.0.1:{gateway.port}")
            from volcano_tpu.store.store import WatchHandler

            remote.watch("Pod", WatchHandler(), poll_timeout=0.05)
            assert _wait(lambda: remote.watch_stats()["polls"] >= 2,
                         timeout=10.0)
            stats = remote.watch_stats()
            assert stats["poll_errors"] == 0
            assert stats["backoff_s"] == 0.0
            remote.stop_watches()
        finally:
            gateway.stop()
