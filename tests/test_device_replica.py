"""Device-resident cluster state (ops/replica.py).

The standing per-cache device replica must be a pure transport
optimisation: with it on (the default), every session's binds and staged
device content are bit-identical to the replica-off oracle
(``VOLCANO_TPU_REPLICA=0``), across randomized churn, every fallback
reason, and sharded meshes. On top of parity:

- consecutive unchanged sessions reuse the whole prepare bundle with
  ZERO warm compiles and ZERO h2d puts (the cfg5 steady-state claim);
- every wholesale restage is counted under an honest reason
  (``replica_rebuild{reason}``) — the replica never silently degrades;
- under ``VOLCANO_TPU_WITNESS=1`` every scattered row must be explained
  by a keeper mark or a generation/status-version movement, and an
  unexplained divergence is detected, counted, and healed by a rebuild.
"""

from __future__ import annotations

import os
import random
from contextlib import contextmanager

import numpy as np
import pytest

from volcano_tpu.api import objects
from volcano_tpu.ops import replica as replica_mod
from volcano_tpu.scheduler.framework import (
    close_session,
    get_action,
    open_session,
)
from tests.helpers import (  # noqa: F401 (registers actions)
    make_cache,
    make_tiers,
)
from tests.test_snapshot_incremental import (
    DEFAULT_TIERS,
    ROUNDS_ARGS,
    _assert_encodes_equal,
    _populate_small,
)
from tests.test_snapshot_incremental import TestChurnParity as _ChurnDeltas
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
    build_resource_list_with_pods,
)


@contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update(kv)
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _session(cache, replica="1", mesh=None):
    """One allocate session in rounds mode; returns the tpuscore profile."""
    from volcano_tpu.scheduler.plugins import tpuscore

    if mesh is not None:
        tpuscore.set_default_mesh(mesh)
    try:
        with _env(VOLCANO_TPU_REPLICA=replica):
            ssn = open_session(cache, make_tiers(
                ["tpuscore"], *DEFAULT_TIERS, arguments=ROUNDS_ARGS))
            try:
                get_action("allocate").execute(ssn)
                prof = dict(ssn.plugins["tpuscore"].profile)
            finally:
                close_session(ssn)
    finally:
        if mesh is not None:
            tpuscore.set_default_mesh(None)
    return prof


def _populate_over(c, groups=20, nodes=24, node_cpu="1"):
    """Demand >> capacity: every session keeps a pending backlog, so the
    solver encodes (and the replica serves) every single session."""
    c.add_queue(build_queue("default"))
    for g in range(groups):
        pg = f"pg-{g:03d}"
        c.add_pod_group(build_pod_group(pg, namespace="ns", min_member=2))
        for i in range(4):
            c.add_pod(build_pod(
                "ns", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                build_resource_list("500m", "256Mi"), pg))
    for n in range(nodes):
        c.add_node(build_node(
            f"node-{n:03d}",
            build_resource_list_with_pods(node_cpu, "16Gi", pods=64)))


def _assert_device_matches_mirror(rep, ctx=""):
    """The standing buffers must equal the host mirror bit-for-bit — the
    mirror is by construction the oracle's padded+cast staging input."""
    for name, dev in rep.dev.items():
        host = np.asarray(dev)
        assert np.array_equal(host, rep.mirror[name]), f"{ctx}: {name}"


def _upd_node(caches, name, cpu):
    """Capacity update of ONE existing node on every twin — a legal
    single-row watch delta even on a saturated cluster."""
    for c in caches:
        c.add_node(build_node(
            name, build_resource_list_with_pods(cpu, "16Gi", pods=64)))


class TestChurnFuzzParity:
    """Randomized churn: replica-fed sessions vs the replica-off oracle."""

    N_STEPS = 18

    def test_replica_matches_oracle_under_churn(self):
        rng = random.Random(23)
        a, b = make_cache(), make_cache()
        for c in (a, b):
            _populate_small(c, groups=8, nodes=12)
        state = {"groups": [f"pg-{g:03d}" for g in range(8)],
                 "nodes": [f"node-{n:03d}" for n in range(12)],
                 "pods": [("ns", f"pg-{g:03d}-t{i}", f"pg-{g:03d}")
                          for g in range(8) for i in range(4)],
                 "seq": 0}
        churn = _ChurnDeltas()
        for step in range(self.N_STEPS):
            for _ in range(rng.randrange(4)):
                churn._apply_random_delta(rng, (a, b), state)
            if step % 3 == 2:
                _session(a, replica="1")
                _session(b, replica="0")
                assert a.binder.binds == b.binder.binds, f"step {step}"
                rep = a._device_replica
                _assert_device_matches_mirror(rep, ctx=f"step {step}")
        # the oracle twin never grew a replica; the replica twin stayed a
        # pure transport (its host-visible encode is the oracle's)
        assert not hasattr(b, "_device_replica")
        _assert_encodes_equal(a, b, ctx="final")
        rep = a._device_replica
        assert rep.stats["serves"] > 0
        assert rep.stats["rebuilds"].get("cold") == 1


class TestScatterPath:
    """Small marked churn must travel as a bucketed row scatter, not a
    wholesale restage, and land bit-exact."""

    def test_single_row_churn_scatters(self):
        cache = make_cache()
        _populate_over(cache, groups=20, nodes=24, node_cpu="1")
        p1 = _session(cache)
        assert p1.get("mode") == "rounds", p1
        rep = cache._device_replica
        assert rep.stats["rebuilds"].get("cold") == 1
        # absorb session 1's bulk placements (a wide diff), then touch
        # ONE node: the next serve must patch, not restage — and count
        # the rows it shipped
        _session(cache)
        before = dict(rep.stats["rebuilds"])
        _upd_node([cache], "node-023", "2")
        p2 = _session(cache)
        # the NODE family must travel as a scatter (tiny families like
        # queue/ns may honestly go dense — their whole axis is a row or
        # two, below any patch budget)
        after = rep.stats["rebuilds"]
        for k in ("cold", "generation", "dense:node"):
            assert after.get(k, 0) == before.get(k, 0), after
        assert rep.stats["scatters"] >= 1
        assert p2.get("replica_scatter_rows", 0) >= 1
        assert "tpu_replica_scatter_ms" in p2
        _assert_device_matches_mirror(rep, ctx="post-scatter")

    def test_bulk_churn_goes_dense_honestly(self):
        cache = make_cache()
        _populate_over(cache, groups=10, nodes=5, node_cpu="2")
        _session(cache)
        _session(cache)  # absorb the placement diff
        rep = cache._device_replica
        # touch most of the node axis: the patch budget (PATCH_FRACTION)
        # makes a dense re-put cheaper, counted under its own reason
        for n in range(4):
            cache.add_node(build_node(
                f"node-{n:03d}",
                build_resource_list_with_pods("3", "16Gi", pods=64)))
        _session(cache)
        reasons = rep.stats["rebuilds"]
        assert reasons.get("dense:node", 0) >= 1, reasons
        _assert_device_matches_mirror(rep, ctx="post-dense")


class TestSteadyStateReuse:
    """Unchanged overcommitted backlog: sessions reuse the whole encode
    with zero compiles and zero h2d puts — steady-state encode ~zero."""

    def _populate_overcommitted(self, c):
        c.add_queue(build_queue("default"))
        for g in range(20):
            pg = f"job-{g:04d}"
            c.add_pod_group(build_pod_group(pg, namespace="bench",
                                            min_member=2))
            for i in range(4):
                c.add_pod(build_pod(
                    "bench", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                    build_resource_list("2", "2Gi"), pg))
        for n in range(4):
            c.add_node(build_node(
                f"node-{n:03d}",
                build_resource_list_with_pods("8", "32Gi", pods=64)))

    def test_unchanged_sessions_reuse_whole_encode(self):
        from volcano_tpu.utils.jaxcompile import CompileWatcher

        cache = make_cache()
        self._populate_overcommitted(cache)
        p1 = _session(cache)
        assert p1.get("mode") == "rounds", p1
        binds1 = dict(cache.binder.binds)
        assert binds1  # saturated the cluster, backlog remains pending
        rep = cache._device_replica
        # session 2 re-encodes (session 1's flush moved the accounting)
        # but places nothing: the cluster is full, so from here on the
        # fingerprint freezes
        p2 = _session(cache)
        assert dict(cache.binder.binds) == binds1
        assert p2.get("mode") == "rounds", p2

        watcher = CompileWatcher.install()
        with watcher.assert_no_compiles("replica steady-state sessions"):
            p3 = _session(cache)
            p4 = _session(cache)
        for p in (p3, p4):
            assert p.get("encode_reused") is True, p
            assert p.get("h2d_puts") == 0, p
            assert p.get("encode_s", 1.0) < 0.005, p
        assert rep.stats["encode_reuses"] >= 2
        assert dict(cache.binder.binds) == binds1

    def test_flag_off_disables_and_restores(self):
        cache = make_cache()
        self._populate_overcommitted(cache)
        _session(cache)
        _session(cache)
        # kill-switch session: no reuse, no replica serve, oracle staging
        p_off = _session(cache, replica="0")
        assert "encode_reused" not in p_off
        assert "replica_epoch" not in p_off
        # back on: the standing replica is still valid and serves again
        p_on = _session(cache)
        assert p_on.get("encode_reused") is True \
            or "replica_epoch" in p_on, p_on


class TestFallbackReasons:
    """Every envelope miss restages wholesale under an honest counted
    reason, and the session's binds stay oracle-identical through it."""

    def _twins(self):
        a, b = make_cache(), make_cache()
        for c in (a, b):
            _populate_over(c, groups=12, nodes=5, node_cpu="2")
        return a, b

    def _step(self, a, b, ctx):
        _session(a, replica="1")
        _session(b, replica="0")
        assert a.binder.binds == b.binder.binds, ctx

    def test_reason_ladder_keeps_parity(self):
        a, b = self._twins()
        self._step(a, b, "cold")
        rep = a._device_replica
        assert rep.stats["rebuilds"] == {"cold": 1}

        # queue-set change: keeper invalidates wholesale -> "generation"
        for c in (a, b):
            c.add_queue(build_queue("burst"))
        self._step(a, b, "generation")
        assert rep.stats["rebuilds"].get("generation") == 1

        # leadership fence moved: staged buffers may carry pre-fence
        # state -> "fence"
        for c in (a, b):
            c.set_fence_epoch(7)
        self._step(a, b, "fence")
        assert rep.stats["rebuilds"].get("fence") == 1

        # node-axis membership drift that survived every earlier check
        # (defense in depth; churn normally trips "generation" first).
        # Nothing real moved, so drop the whole-encode memo by hand or
        # the session would — correctly — just reuse the last prepare.
        rep._node_names = list(reversed(rep._node_names))
        rep.forget_prepare()
        self._step(a, b, "axis")
        assert rep.stats["rebuilds"].get("axis") == 1

        # mirror shape drift (a stale replica surviving an axis resize):
        # the envelope restages instead of wedging the session
        rep.mirror["node_used"] = rep.mirror["node_used"][:-1]
        rep.forget_prepare()
        self._step(a, b, "shape")
        assert any(k.startswith("error:") or k == "shape"
                   for k in rep.stats["rebuilds"]), rep.stats["rebuilds"]
        _assert_device_matches_mirror(rep, ctx="post-ladder")
        _assert_encodes_equal(a, b, ctx="post-ladder")


class TestWitnessMode:
    """VOLCANO_TPU_WITNESS=1: every replica scatter is explained by a
    keeper mark or generation movement; unexplained divergence is caught."""

    def test_marked_churn_is_fully_explained(self):
        with _env(VOLCANO_TPU_WITNESS="1"):
            cache = make_cache()
            _populate_over(cache, groups=16, nodes=12, node_cpu="1")
            _session(cache)
            rep = cache._device_replica
            for step in range(3):
                _upd_node([cache], f"node-{step:03d}", "2")
                _session(cache)
            assert rep.stats["witness_violations"] == 0
            assert not any(k.startswith("error:")
                           for k in rep.stats["rebuilds"])
            _assert_device_matches_mirror(rep, ctx="witnessed")

    def test_unexplained_divergence_is_detected_and_healed(self):
        with _env(VOLCANO_TPU_WITNESS="1"):
            cache = make_cache()
            _populate_over(cache, groups=12, nodes=8, node_cpu="1")
            _session(cache)
            _session(cache)
            rep = cache._device_replica
            # corrupt one mirror row with no keeper mark and no
            # generation movement: the next serve sees a changed row it
            # cannot explain — the runtime half of VT007. Drop the
            # whole-encode memo so the session re-encodes (the corruption
            # itself is invisible to the fingerprint — that's the point).
            rep.mirror["node_used"] = rep.mirror["node_used"].copy()
            rep.mirror["node_used"][0] += 1
            rep.forget_prepare()
            _session(cache)
            assert rep.stats["witness_violations"] >= 1
            assert rep.stats["rebuilds"].get("error:WitnessViolation") == 1
            # the rebuild healed the divergence: device == mirror == truth
            _assert_device_matches_mirror(rep, ctx="healed")
            _session(cache)
            assert rep.stats["witness_violations"] == 1


class TestMeshParity:
    """Replica-on under a sharded mesh: binds bit-identical to the
    replica-off mesh oracle; per-shard buffers equal the host mirror."""

    def _mesh(self, devices):
        import jax
        from jax.sharding import Mesh

        if len(jax.devices()) < devices:
            pytest.skip(f"needs {devices} devices")
        return Mesh(np.array(jax.devices()[:devices]), ("nodes",))

    @pytest.mark.parametrize("devices", [2, 4, 8])
    def test_mesh_replica_matches_oracle(self, devices):
        mesh = self._mesh(devices)
        a, b = make_cache(), make_cache()
        for c in (a, b):
            _populate_over(c, groups=20, nodes=24, node_cpu="1")
        pa = _session(a, replica="1", mesh=mesh)
        pb = _session(b, replica="0", mesh=mesh)
        assert pa.get("mode") == "rounds", pa
        assert pb.get("mode") == "rounds", pb
        assert a.binder.binds == b.binder.binds
        rep = a._device_replica
        _assert_device_matches_mirror(rep, ctx=f"mesh{devices} cold")
        # churn two nodes in different shards, then re-serve: the delta
        # path walks only the shards the rows land on, content stays exact
        _upd_node([a, b], "node-003", "2")
        _upd_node([a, b], "node-019", "3")
        _session(a, replica="1", mesh=mesh)
        _session(b, replica="0", mesh=mesh)
        assert a.binder.binds == b.binder.binds
        _assert_device_matches_mirror(rep, ctx=f"mesh{devices} delta")
        _assert_encodes_equal(a, b, ctx=f"mesh{devices}")
