"""Networked vcctl: a REAL cluster process (python -m volcano_tpu.scheduler
--api-address) driven over HTTP by the vcctl CLI through RemoteStore —
the reference's remote-client architecture (cmd/cli/vcctl.go:34;
pkg/cli/job/run.go:55-80), job run/list/view/suspend/resume/delete and
queue create/get/list end to end.
"""

from __future__ import annotations

import io
import os
import subprocess
import sys
import time
from contextlib import redirect_stdout

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cluster_proc():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("VOLCANO_TPU_PANIC", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "volcano_tpu.scheduler",
         "--api-address", ":0",
         "--listen-address", ":0", "--healthz-address", "127.0.0.1:0",
         "--schedule-period", "0.2",
         "--cluster-state", os.path.join(REPO, "example", "cluster.yaml"),
         "--run-for", "90"],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    port = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("api gateway on :"):
            port = int(line.rsplit(":", 1)[1])
            break
    if port is None:
        proc.terminate()
        out, err = proc.communicate(timeout=10)
        pytest.fail(f"cluster process exposed no api port:\n{out}\n{err}")
    yield proc, port
    proc.terminate()
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()


def _vcctl(port, *argv) -> str:
    from volcano_tpu.cli.vcctl import main

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(["--server", f"127.0.0.1:{port}", *argv])
    assert rc == 0, (argv, buf.getvalue())
    return buf.getvalue()


def _wait(predicate, timeout=30.0, interval=0.25):
    deadline = time.time() + timeout
    while time.time() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(interval)
    return None


def test_job_lifecycle_over_http(cluster_proc):
    _, port = cluster_proc

    out = _vcctl(port, "job", "run", "-f",
                 os.path.join(REPO, "example", "job.yaml"))
    assert "created" in out

    # scheduled + running: the live cluster's scheduler/controllers drive
    # the job to Running, observed purely through the remote list verb
    out = _wait(lambda: (
        lambda s: s if "Running" in s else None)(
            _vcctl(port, "job", "list")))
    assert out is not None, "job never reached Running over HTTP"
    assert "test-job" in out

    out = _vcctl(port, "job", "view", "-n", "default", "-N", "test-job")
    assert "Name:       \ttest-job" in out
    assert "Phase:" in out

    # suspend -> Aborted (Command bus consumed by the live controller)
    _vcctl(port, "job", "suspend", "-n", "default", "-N", "test-job")
    out = _wait(lambda: (
        lambda s: s if ("Aborted" in s or "Aborting" in s) else None)(
            _vcctl(port, "job", "list")))
    assert out is not None, "suspend never took effect over HTTP"

    # resume -> back to Running
    _vcctl(port, "job", "resume", "-n", "default", "-N", "test-job")
    out = _wait(lambda: (
        lambda s: s if "Running" in s else None)(
            _vcctl(port, "job", "list")))
    assert out is not None, "resume never took effect over HTTP"

    # delete: gone from the remote list
    _vcctl(port, "job", "delete", "-n", "default", "-N", "test-job")
    out = _wait(lambda: (
        lambda s: s if "test-job" not in s else None)(
            _vcctl(port, "job", "list")))
    assert out is not None, "delete never took effect over HTTP"


def test_queue_ops_over_http(cluster_proc):
    _, port = cluster_proc

    _vcctl(port, "queue", "create", "-N", "remote-q", "-w", "3")
    out = _vcctl(port, "queue", "get", "-N", "remote-q")
    assert "remote-q" in out and "3" in out
    out = _vcctl(port, "queue", "list")
    assert "remote-q" in out and "default" in out


def test_admission_rejection_travels_back(cluster_proc):
    """Server-side admission (job validator middleware) must reject over
    the wire with the CLI reporting the error, not a traceback."""
    import tempfile

    _, port = cluster_proc
    bad = """
apiVersion: batch.volcano.sh/v1alpha1
kind: Job
metadata:
  name: bad-job
spec:
  minAvailable: -1
  tasks: []
"""
    with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as f:
        f.write(bad)
        path = f.name
    from volcano_tpu.cli.vcctl import main

    buf_out, buf_err = io.StringIO(), io.StringIO()
    from contextlib import redirect_stderr

    with redirect_stdout(buf_out), redirect_stderr(buf_err):
        rc = main(["--server", f"127.0.0.1:{port}",
                   "job", "run", "-f", path])
    os.unlink(path)
    assert rc == 1
    assert "error:" in buf_err.getvalue()
