"""vclint — the static-analysis tier-1 gate.

Two halves:
1. the golden corpus (tests/analysis_corpus/): every rule fires on every
   marked line of its positive fixture and stays silent on its negative
   fixture (which includes the suppression-comment path);
2. the repo gate: the full rule set over volcano_tpu/ yields ZERO
   unsuppressed findings, via the same tools/lint.sh entry point any CI
   uses — so the kernel-purity / bucket-shape / lock-discipline /
   statement-hygiene / determinism contracts are machine-checked on every
   PR, not rediscovered in bench regressions.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
from pathlib import Path

import pytest

from volcano_tpu.analysis import (
    all_rules,
    analyze_file,
    analyze_paths,
    analyze_source,
    get_rule,
)

pytestmark = pytest.mark.lint

REPO = Path(__file__).resolve().parent.parent
CORPUS = Path(__file__).resolve().parent / "analysis_corpus"
RULE_IDS = ("VT001", "VT002", "VT003", "VT004", "VT005", "VT006",
            "VT007", "VT008", "VT009", "VT010", "VT011", "VT012")

_EXPECT_RE = re.compile(r"#\s*vclint-expect:\s*(VT\d{3})")


def expected_lines(path: Path, rule_id: str) -> set:
    out = set()
    for lineno, line in enumerate(path.read_text().splitlines(), start=1):
        m = _EXPECT_RE.search(line)
        if m and m.group(1) == rule_id:
            out.add(lineno)
    return out


def rule_findings(path: Path, rule_id: str):
    findings = analyze_file(str(path), rules=[get_rule(rule_id)],
                            respect_filters=False)
    return [f for f in findings if f.rule == rule_id]


class TestCorpus:
    @pytest.mark.parametrize("rule_id", RULE_IDS)
    def test_rule_fires_on_positive_corpus(self, rule_id):
        path = CORPUS / f"{rule_id.lower()}_positive.py"
        expected = expected_lines(path, rule_id)
        assert len(expected) >= 2, f"{path} needs >= 2 positive cases"
        got = {f.line for f in rule_findings(path, rule_id) if not f.suppressed}
        assert got == expected, (
            f"{rule_id} on {path.name}: expected lines {sorted(expected)}, "
            f"got {sorted(got)}")

    @pytest.mark.parametrize("rule_id", RULE_IDS)
    def test_rule_silent_on_negative_corpus(self, rule_id):
        path = CORPUS / f"{rule_id.lower()}_negative.py"
        active = [f for f in rule_findings(path, rule_id) if not f.suppressed]
        assert active == [], (
            f"{rule_id} false positives on {path.name}: "
            f"{[f.format() for f in active]}")

    @pytest.mark.parametrize("rule_id", RULE_IDS)
    def test_negative_corpus_exercises_suppression(self, rule_id):
        """Each negative fixture must carry a real-but-suppressed violation,
        proving the disable comment is what silences the rule."""
        path = CORPUS / f"{rule_id.lower()}_negative.py"
        suppressed = [f for f in rule_findings(path, rule_id) if f.suppressed]
        assert suppressed, f"{path.name} has no suppressed finding"

    def test_bare_suppression_is_a_finding(self):
        path = CORPUS / "vt000_bare_suppression.py"
        findings = analyze_file(str(path), respect_filters=False)
        vt000 = [f for f in findings if f.rule == "VT000" and not f.suppressed]
        assert len(vt000) == 1, [f.format() for f in findings]
        src = path.read_text().splitlines()
        assert "vclint: disable=VT001" in src[vt000[0].line - 1]

    def test_justified_suppression_is_not_a_finding(self):
        findings = analyze_source(
            "x = 1  # vclint: disable=VT005 - feeds an order-free sum\n",
            "inline.py", respect_filters=False)
        assert not [f for f in findings if f.rule == "VT000"]


class TestFramework:
    def test_every_rule_registered_with_scope(self):
        rules = {r.id: r for r in all_rules()}
        for rid in RULE_IDS:
            assert rid in rules
            assert rules[rid].patterns, f"{rid} has no default path scope"

    def test_path_scoping(self):
        vt1 = get_rule("VT001")
        assert vt1.applies_to("volcano_tpu/ops/kernels.py")
        assert vt1.applies_to(str(REPO / "volcano_tpu/ops/rounds.py"))
        assert not vt1.applies_to("volcano_tpu/controllers/queue.py")
        # the continuous pipeline sits inside the lock-discipline,
        # hot-path-determinism, and donated-buffer scopes
        for rid in ("VT003", "VT005", "VT006"):
            assert get_rule(rid).applies_to(
                "volcano_tpu/pipeline/driver.py"), rid
        vt3 = get_rule("VT003")
        assert vt3.applies_to("volcano_tpu/controllers/job/controller.py")
        assert vt3.applies_to("volcano_tpu/scheduler/cache/cache.py")
        assert not vt3.applies_to("volcano_tpu/ops/solver.py")
        # the front-door layer (PR 12) sits inside the mutation->
        # invalidation and whole-program lock scopes
        for rid in ("VT007", "VT008"):
            for path in ("volcano_tpu/store/flowcontrol.py",
                         "volcano_tpu/store/gateway.py",
                         "volcano_tpu/admission/intake.py"):
                assert get_rule(rid).applies_to(path), (rid, path)

    def test_syntax_error_reported_not_raised(self):
        findings = analyze_source("def broken(:\n", "broken.py",
                                  respect_filters=False)
        assert findings and findings[0].rule == "VT999"

    def test_cli_json_and_exit_codes(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        pos = subprocess.run(
            [sys.executable, "-m", "volcano_tpu.analysis", "--json",
             "--no-default-filter",
             str(CORPUS / "vt004_positive.py")],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert pos.returncode == 1, pos.stderr
        payload = json.loads(pos.stdout)
        assert any(f["rule"] == "VT004" for f in payload)
        neg = subprocess.run(
            [sys.executable, "-m", "volcano_tpu.analysis", "--json",
             "--no-default-filter",
             str(CORPUS / "vt004_negative.py")],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert neg.returncode == 0, neg.stdout + neg.stderr
        assert json.loads(neg.stdout) == []


class TestTooling:
    """v2 CLI satellites: the JSON report, the suppression baseline, and
    the --explain effect-chain printer."""

    def _run(self, *argv):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "volcano_tpu.analysis", *argv],
            cwd=REPO, env=env, capture_output=True, text=True)

    def test_report_file_is_machine_readable(self, tmp_path):
        report = tmp_path / "report.json"
        proc = self._run("--report", str(report), "volcano_tpu")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(report.read_text())
        assert set(payload) == {"findings", "suppressed", "counts",
                                "lint_wall_ms"}
        assert payload["findings"] == []
        # the tree's justified suppressions are IN the report
        assert any(f["suppressed"] for f in payload["suppressed"])

    def test_baseline_gate_matches_and_drifts(self, tmp_path):
        base = tmp_path / "base.json"
        gen = self._run("--write-baseline", str(base), "volcano_tpu")
        assert gen.returncode == 0, gen.stdout + gen.stderr
        ok = self._run("--baseline", str(base), "volcano_tpu")
        assert ok.returncode == 0, ok.stdout + ok.stderr
        # drift: drop a recorded suppression -> the gate must fail with
        # a 'new suppression' message even though findings are clean
        payload = json.loads(base.read_text())
        key = sorted(payload["suppressed"])[0]
        del payload["suppressed"][key]
        base.write_text(json.dumps(payload))
        drift = self._run("--baseline", str(base), "volcano_tpu")
        assert drift.returncode == 1
        assert "new suppression" in drift.stderr
        # the committed baseline matches the committed tree
        committed = self._run(
            "--baseline", str(REPO / "tools" / "lint_baseline.json"),
            "volcano_tpu")
        assert committed.returncode == 0, committed.stdout + committed.stderr

    def test_explain_prints_effect_chains(self):
        proc = self._run("--explain", "VT007",
                         "volcano_tpu/scheduler/cache/cache.py")
        assert proc.returncode == 0, proc.stderr
        out = proc.stdout
        assert "covered via" in out          # callee-closure chains
        assert "dirty_epoch" in out          # ... naming the channel
        assert "blessed neutral(" in out     # the echo-window blesses
        assert "UNCOVERED" not in out        # repo scans clean
        vt9 = self._run("--explain", "VT009")
        assert vt9.returncode == 0, vt9.stderr
        assert "sealed" in vt9.stdout
        assert "UNSEALED" not in vt9.stdout

    def test_neutral_bless_requires_reason(self):
        findings = analyze_source(
            "class C:\n"
            "    def f(self, uid):\n"
            "        self.jobs.pop(uid, None)  # vclint: neutral()\n",
            "inline_neutral.py", respect_filters=False)
        vt7 = [f for f in findings if f.rule == "VT007"]
        assert vt7 and "without a reason" in vt7[0].message
        findings = analyze_source(
            "class C:\n"
            "    def f(self, uid):\n"
            "        self.jobs.pop(uid, None)"
            "  # vclint: neutral(echo window, see docs)\n",
            "inline_neutral.py", respect_filters=False)
        assert not [f for f in findings if f.rule == "VT007"]


def _run_cli(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "volcano_tpu.analysis", *argv],
        cwd=REPO, env=env, capture_output=True, text=True)


class TestAbstractInterp:
    """v3 non-vacuity: the abstract-interpretation rules must fire when
    their fixed defects are re-injected into the REAL kernel sources —
    proving the clean scan is the analysis passing, not the analysis
    missing."""

    def _reinject(self, rel, old, new, rule_id):
        path = REPO / rel
        src = path.read_text()
        assert old in src, f"{rel} drifted: {old!r} not found"
        rule = [get_rule(rule_id)]
        pristine = [f for f in analyze_source(
            src, str(path), rules=rule, respect_filters=False)
            if f.rule == rule_id and not f.suppressed]
        assert pristine == [], [f.format() for f in pristine]
        mutated = [f for f in analyze_source(
            src.replace(old, new), str(path), rules=rule,
            respect_filters=False)
            if f.rule == rule_id and not f.suppressed]
        assert mutated, (
            f"{rule_id} stayed silent on the re-injected defect in {rel}")
        return mutated

    def test_vt010_fires_on_reinjected_flat_encoding(self):
        # put the pre-PR-16 flat (node, slot) op-log encoding back
        self._reinject(
            "volcano_tpu/ops/evict.py",
            "    return _log_append(st, OP_EVICT, node, slot, active)",
            "    flat = node * enc[\"vic_job\"].shape[1] + slot\n"
            "    return _log_append(st, OP_EVICT, flat, "
            "jnp.zeros_like(flat), active)",
            "VT010")

    def test_vt011_fires_on_reinjected_unmasked_window(self):
        # undo the _sample_window pad-masking hardening
        found = self._reinject(
            "volcano_tpu/ops/kernels.py",
            "rolled = jnp.roll(mask & node_real, -rr)",
            "rolled = jnp.roll(mask, -rr)",
            "VT011")
        assert any("cumsum" in f.message for f in found)

    def test_vt012_fires_without_the_suppression(self):
        # stripping the justification comment must re-activate the
        # adopt_carry alias finding
        found = self._reinject(
            "volcano_tpu/ops/session_fuse.py",
            "# vclint: disable=VT012 -",
            "# note:",
            "VT012")
        assert any(f.rule == "VT012" for f in found)

    def test_headroom_proof_is_machine_checked(self):
        # a bless whose arithmetic does NOT prove < 2**31 is itself a
        # finding, never a silencer
        src = ("import jax.numpy as jnp\n\n\n"
               "def f(node, t_cap):\n"
               "    return node * t_cap"
               "  # vclint: headroom(NODES_PAD * TASKS)\n")
        found = [f for f in analyze_source(
            src, "inline_abs.py", rules=[get_rule("VT010")],
            respect_filters=False) if not f.suppressed]
        assert found and "proof rejected" in found[0].message

    def test_explain_absint_reports(self):
        p10 = _run_cli("--explain", "VT010", "volcano_tpu/ops/evict.py")
        assert p10.returncode == 0, p10.stderr
        assert "checked" in p10.stdout and "OVERFLOW" not in p10.stdout
        p11 = _run_cli("--explain", "VT011", "volcano_tpu/ops/kernels.py")
        assert p11.returncode == 0, p11.stderr
        assert "ok:" in p11.stdout and "TAINT SINK" not in p11.stdout
        p12 = _run_cli("--explain", "VT012",
                       "volcano_tpu/ops/session_fuse.py")
        assert p12.returncode == 0, p12.stderr
        assert "donate" in p12.stdout and "READ" in p12.stdout
        bad = _run_cli("--explain", "VT013")
        assert bad.returncode == 2


class TestIncrementalLint:
    """v3 satellite: warm runs reuse memoized per-file findings and the
    report records the wall-clock evidence."""

    def test_cold_then_warm_cache(self, tmp_path):
        cache = tmp_path / "cache.json"
        report = tmp_path / "report.json"
        cold = _run_cli("--cache", str(cache), "--report", str(report),
                        "volcano_tpu")
        assert cold.returncode == 0, cold.stdout + cold.stderr
        first = json.loads(report.read_text())
        w = first["lint_wall_ms"]
        assert w["mode"] == "cold" and w["files_reused"] == 0
        assert w["files_analyzed"] > 0
        # the lint runtime budget: a full cold scan stays under 60 s
        assert w["run"] < 60_000, f"cold lint took {w['run']}ms"
        warm = _run_cli("--cache", str(cache), "--report", str(report),
                        "volcano_tpu")
        assert warm.returncode == 0, warm.stdout + warm.stderr
        second = json.loads(report.read_text())
        w2 = second["lint_wall_ms"]
        assert w2["mode"] == "warm" and w2["files_analyzed"] == 0
        assert w2["files_reused"] == w["files_analyzed"]
        assert w2["cold"] == w["run"]  # cold reference survives the reuse
        # reuse must be lossless: identical findings either way
        assert second["findings"] == first["findings"]
        assert second["suppressed"] == first["suppressed"]

    def test_select_bypasses_cache(self, tmp_path):
        report = tmp_path / "report.json"
        proc = _run_cli("--cache", str(tmp_path / "c.json"),
                        "--report", str(report), "--select", "VT001",
                        "volcano_tpu")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        w = json.loads(report.read_text())["lint_wall_ms"]
        assert w["mode"] == "off"
        assert not (tmp_path / "c.json").exists()


class TestRepoGate:
    """The analyzer is part of tier-1 forever: the package must scan clean."""

    def test_repo_has_zero_unsuppressed_findings(self):
        findings = analyze_paths([str(REPO / "volcano_tpu")])
        active = [f.format() for f in findings if not f.suppressed]
        assert active == [], "\n".join(active)

    def test_lint_sh_gate_passes(self, tmp_path):
        """The shared entry point (analyzer + compileall) — the exact
        command CI runs — must exit 0."""
        env = dict(os.environ, PYTHON=sys.executable, JAX_PLATFORMS="cpu",
                   LINT_REPORT=str(tmp_path / "report.json"),
                   LINT_CACHE=str(tmp_path / "cache.json"))
        proc = subprocess.run(
            ["bash", str(REPO / "tools" / "lint.sh")],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr
