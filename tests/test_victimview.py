"""Batched victim selection (ops/victimview.py) vs the serial tiered
dispatch — victim sets must be BIT-IDENTICAL (same objects, same order) on
randomized sessions, for every stock plugin combination and both extension
points. Also covers the preempt/reclaim actions end-to-end: with the
selector active the evictions and pipelines must match a serial-only rerun.
"""

from __future__ import annotations

import random

import pytest

from tests.helpers import close_session, make_cache, make_tiers, open_session
from volcano_tpu.api import objects
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.ops import victimview
from volcano_tpu.scheduler.util.test_utils import (
    build_node, build_pod, build_pod_group, build_queue,
    build_resource_list_with_pods,
)


def _cluster(seed: int, nodes: int = 6, running_jobs: int = 12,
             tasks_per_job: int = 4, queues: int = 2):
    """Cache with RUNNING filler spread over few nodes (dense residents)
    plus pending high-priority gangs (claimers)."""
    rng = random.Random(seed)
    c = make_cache()
    for q in range(queues):
        c.add_queue(build_queue(f"q{q}", weight=1 + q))
    for n in range(nodes):
        c.add_node(build_node(
            f"node-{n:03d}", build_resource_list_with_pods("64", "128Gi", pods=256)))
    for g in range(running_jobs):
        pg = f"run-{g:03d}"
        queue = f"q{g % queues}"
        min_member = rng.choice([1, 2, tasks_per_job])
        c.add_pod_group(build_pod_group(
            pg, namespace="vv", min_member=min_member, queue=queue))
        for i in range(tasks_per_job):
            pod = build_pod(
                "vv", f"{pg}-t{i}", f"node-{rng.randrange(nodes):03d}",
                objects.POD_PHASE_RUNNING,
                {"cpu": f"{rng.choice([500, 1000, 2000])}m",
                 "memory": rng.choice(["1Gi", "2Gi"])},
                pg, priority=rng.choice([0, 1, 5]))
            if rng.random() < 0.1:
                pod.spec.priority_class_name = objects.SYSTEM_CLUSTER_CRITICAL
            c.add_pod(pod)
    for g in range(3):
        pg = f"hi-{g:02d}"
        c.add_pod_group(build_pod_group(
            pg, namespace="vv", min_member=2, queue="q0"))
        for i in range(2):
            c.add_pod(build_pod(
                "vv", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                {"cpu": "4000m", "memory": "8Gi"}, pg, priority=100))
    return c


TIER_SETS = [
    # default conf shape: gang decides in tier1
    (["priority", "gang"], ["drf", "predicates", "proportion", "nodeorder"]),
    # single tier: gang ∩ drf ∩ conformance intersection actually engages
    (["gang", "drf", "conformance", "proportion", "predicates"],),
    # drf-deciding tier
    (["priority"], ["drf", "conformance", "proportion"]),
]


@pytest.mark.parametrize("tiers_spec", TIER_SETS)
@pytest.mark.parametrize("kind", ["preemptable", "reclaimable"])
@pytest.mark.parametrize("seed", [7, 21, 63])
def test_selector_matches_serial_dispatch(tiers_spec, kind, seed, monkeypatch):
    # force the batch path even on sparse nodes — duplicating claimees
    # instead would fabricate resource underflows the serial path asserts on
    monkeypatch.setattr(victimview.VictimSelector, "MIN_BATCH", 1)
    cache = _cluster(seed)
    ssn = open_session(cache, make_tiers(["tpuscore"], *tiers_spec))
    try:
        sel = victimview.build(ssn, kind)
        assert sel is not None
        claimers = [
            t for job in ssn.jobs.values()
            for t in job.task_status_index.get(TaskStatus.PENDING, {}).values()
        ]
        assert claimers
        serial = (ssn.preemptable if kind == "preemptable"
                  else ssn.reclaimable)
        rng = random.Random(seed * 3)
        for claimer in claimers:
            for node in ssn.nodes.values():
                claimees = [
                    t.shared_clone() for t in node.tasks.values()
                    if t.status == TaskStatus.RUNNING
                    and rng.random() < 0.9  # vary the candidate mix
                ]
                got = sel.victims(claimer, claimees)
                want = serial(claimer, claimees)
                assert [v.uid for v in got] == [v.uid for v in want], (
                    kind, tiers_spec, node.name)
                # same objects, not just same uids (eviction mutates them)
                assert all(a is b for a, b in zip(got, want))
    finally:
        close_session(ssn)


def test_unsupported_plugin_falls_back():
    cache = _cluster(3)
    ssn = open_session(cache, make_tiers(["gang", "drf"]))
    try:
        # register a custom victim fn through the public seam: the batch
        # selector must refuse the session
        ssn.add_preemptable_fn("custom", lambda claimer, claimees: claimees)
        assert victimview.build(ssn, "preemptable") is None
        # reclaimable untouched by the custom fn -> still batchable
        assert victimview.build(ssn, "reclaimable") is not None
    finally:
        close_session(ssn)


@pytest.mark.parametrize("seed", [11, 42])
def test_preempt_reclaim_actions_bit_parity(seed):
    """End-to-end: run allocate+preempt+reclaim with the selector active
    (tpuscore on, dense view) vs fully serial; evictions and final binds
    must match exactly."""
    from volcano_tpu.scheduler.framework import get_action

    def run(with_tpuscore: bool):
        cache = _cluster(seed, nodes=4, running_jobs=16)
        tiers_spec = (["priority", "gang"],
                      ["drf", "predicates", "proportion", "nodeorder"])
        tiers = make_tiers(["tpuscore"], *tiers_spec) if with_tpuscore \
            else make_tiers(*tiers_spec)
        ssn = open_session(cache, tiers)
        # force victim batching even for small nodes
        import volcano_tpu.ops.victimview as vv
        old = vv.VictimSelector.MIN_BATCH
        vv.VictimSelector.MIN_BATCH = 1
        try:
            for name in ("allocate", "backfill", "preempt", "reclaim"):
                get_action(name).execute(ssn)
        finally:
            vv.VictimSelector.MIN_BATCH = old
            close_session(ssn)
        return (dict(cache.binder.binds),
                sorted((p.metadata.name, r) for p, r in cache.evictor.evicts))

    binds_tpu, evicts_tpu = run(True)
    binds_serial, evicts_serial = run(False)
    assert evicts_tpu == evicts_serial
    assert binds_tpu == binds_serial
