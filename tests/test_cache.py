"""SchedulerCache tests (mirrors pkg/scheduler/cache/{cache,event_handlers}_test.go)."""

from volcano_tpu.api import objects
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.scheduler.cache import SchedulerCache
from volcano_tpu.scheduler.util.test_utils import (
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)
from volcano_tpu.store import Store


def make_cache(store=None):
    return SchedulerCache(
        store=store,
        binder=FakeBinder(),
        evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
        volume_binder=FakeVolumeBinder(),
    )


class TestEventHandlers:
    def test_add_pod_creates_shadow_job(self):
        c = make_cache()
        c.add_pod(build_pod("ns1", "p1", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1"))
        assert "ns1/pg1" in c.jobs
        assert len(c.jobs["ns1/pg1"].tasks) == 1

    def test_bound_pod_on_unknown_node_makes_shadow_node(self):
        c = make_cache()
        c.add_pod(build_pod("ns1", "p1", "ghost", objects.POD_PHASE_RUNNING,
                            build_resource_list("1", "1Gi"), "pg1"))
        assert "ghost" in c.nodes
        assert not c.nodes["ghost"].ready()  # uninitialized

    def test_other_scheduler_pod_ignored(self):
        c = make_cache()
        pod = build_pod("ns1", "p1", "", objects.POD_PHASE_PENDING,
                        build_resource_list("1", "1Gi"))
        pod.spec.scheduler_name = "default-scheduler"
        c.add_pod(pod)
        assert not c.jobs

    def test_pod_group_binds_to_job(self):
        c = make_cache()
        c.add_pod(build_pod("ns1", "p1", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1"))
        c.add_pod_group(build_pod_group("pg1", namespace="ns1", min_member=1, queue="q1"))
        job = c.jobs["ns1/pg1"]
        assert job.min_available == 1
        assert job.queue == "q1"

    def test_pod_group_default_queue(self):
        c = make_cache()
        pg = build_pod_group("pg1", namespace="ns1", queue="")
        c.add_pod_group(pg)
        assert c.jobs["ns1/pg1"].queue == "default"

    def test_delete_pod_then_podgroup_removes_job(self):
        c = make_cache()
        pod = build_pod("ns1", "p1", "", objects.POD_PHASE_PENDING,
                        build_resource_list("1", "1Gi"), "pg1")
        c.add_pod(pod)
        c.add_pod_group(build_pod_group("pg1", namespace="ns1"))
        c.delete_pod(pod)
        c.delete_pod_group(build_pod_group("pg1", namespace="ns1"))
        assert "ns1/pg1" not in c.jobs


class TestSnapshot:
    def build(self):
        c = make_cache()
        c.add_node(build_node("n1", build_resource_list("8", "16Gi")))
        c.add_node(build_node("n2", build_resource_list("8", "16Gi")))
        c.add_queue(build_queue("q1", weight=2))
        c.add_pod_group(build_pod_group("pg1", namespace="ns1", min_member=2, queue="q1"))
        for i in range(2):
            c.add_pod(build_pod("ns1", f"p{i}", "", objects.POD_PHASE_PENDING,
                                build_resource_list("1", "1Gi"), "pg1"))
        return c

    def test_snapshot_contents(self):
        snap = self.build().snapshot()
        assert set(snap.nodes) == {"n1", "n2"}
        assert set(snap.queues) == {"q1"}
        assert set(snap.jobs) == {"ns1/pg1"}
        assert len(snap.jobs["ns1/pg1"].tasks) == 2

    def test_snapshot_is_deep(self):
        c = self.build()
        snap = c.snapshot()
        task = next(iter(snap.jobs["ns1/pg1"].tasks.values()))
        snap.jobs["ns1/pg1"].update_task_status(task, TaskStatus.ALLOCATED)
        assert c.jobs["ns1/pg1"].allocated.milli_cpu == 0

    def test_snapshot_skips_jobs_without_queue(self):
        c = make_cache()
        c.add_pod_group(build_pod_group("pg1", namespace="ns1", queue="missing"))
        snap = c.snapshot()
        assert not snap.jobs

    def test_snapshot_skips_jobs_without_podgroup(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod(build_pod("ns1", "p1", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1"))
        snap = c.snapshot()
        assert not snap.jobs

    def test_snapshot_skips_not_ready_nodes(self):
        c = self.build()
        bad = build_node("n3", build_resource_list("1", "1Gi"))
        bad.status.conditions = [objects.NodeCondition(type="Ready", status="False")]
        c.add_node(bad)
        assert "n3" not in c.snapshot().nodes

    def test_priority_class_applied(self):
        c = self.build()
        pg = build_pod_group("pg2", namespace="ns1", queue="q1")
        pg.spec.priority_class_name = "high"
        c.add_pod_group(pg)
        c.add_priority_class(objects.PriorityClass(
            metadata=objects.ObjectMeta(name="high"), value=1000))
        snap = c.snapshot()
        assert snap.jobs["ns1/pg2"].priority == 1000
        assert snap.jobs["ns1/pg1"].priority == 0


class TestBindEvict:
    def setup_cache(self):
        c = make_cache()
        c.add_node(build_node("n1", build_resource_list("8", "16Gi")))
        c.add_queue(build_queue("q1"))
        c.add_pod_group(build_pod_group("pg1", namespace="ns1", min_member=1, queue="q1"))
        pod = build_pod("ns1", "p1", "", objects.POD_PHASE_PENDING,
                        build_resource_list("2", "4Gi"), "pg1")
        c.add_pod(pod)
        return c

    def test_bind(self):
        c = self.setup_cache()
        task = next(iter(c.jobs["ns1/pg1"].tasks.values()))
        c.bind(task, "n1")
        assert c.binder.binds == {"ns1/p1": "n1"}
        assert task.status == TaskStatus.BINDING
        assert c.nodes["n1"].idle.milli_cpu == 6000

    def test_bind_unknown_host_raises(self):
        import pytest

        c = self.setup_cache()
        task = next(iter(c.jobs["ns1/pg1"].tasks.values()))
        with pytest.raises(KeyError):
            c.bind(task, "nope")

    def test_bind_failure_resyncs(self):
        class FailingBinder:
            def bind(self, pod, hostname):
                raise RuntimeError("apiserver down")

        store = Store()
        c = SchedulerCache(store=store, binder=FailingBinder(),
                           evictor=FakeEvictor(), status_updater=FakeStatusUpdater(),
                           volume_binder=FakeVolumeBinder())
        c.run()
        store.create(build_node("n1", build_resource_list("8", "16Gi")))
        store.create(build_queue("q1"))
        store.create(build_pod_group("pg1", namespace="ns1", min_member=1, queue="q1"))
        store.create(build_pod("ns1", "p1", "", objects.POD_PHASE_PENDING,
                               build_resource_list("2", "4Gi"), "pg1"))
        task = next(iter(c.jobs["ns1/pg1"].tasks.values()))
        c.bind(task, "n1")
        assert len(c._err_tasks) == 1
        # resync re-fetches truth: pod in store is still unbound/pending
        c.process_resync_tasks()
        assert not c._err_tasks
        job_task = next(iter(c.jobs["ns1/pg1"].tasks.values()))
        assert job_task.status == TaskStatus.PENDING
        assert c.nodes["n1"].idle.milli_cpu == 8000

    def test_evict(self):
        c = self.setup_cache()
        task = next(iter(c.jobs["ns1/pg1"].tasks.values()))
        c.bind(task, "n1")
        c.evict(task, "preempted")
        assert c.evictor.evicts == ["ns1/p1"]
        assert task.status == TaskStatus.RELEASING
        assert c.nodes["n1"].releasing.milli_cpu == 2000


class TestStoreIntegration:
    def test_watch_driven_mirror(self):
        store = Store()
        c = make_cache(store)
        c.run()
        store.create(build_node("n1", build_resource_list("4", "8Gi")))
        store.create(build_queue("default"))
        pg = store.create(build_pod_group("pg1"))
        pod = store.create(build_pod("default", "p1", "", objects.POD_PHASE_PENDING,
                                     build_resource_list("1", "1Gi"), "pg1"))
        assert "n1" in c.nodes
        assert "default/pg1" in c.jobs
        assert len(c.jobs["default/pg1"].tasks) == 1
        # pod phase transition via store update flows through
        pod.status.phase = objects.POD_PHASE_RUNNING
        pod.spec.node_name = "n1"
        store.update(pod)
        task = next(iter(c.jobs["default/pg1"].tasks.values()))
        assert task.status == TaskStatus.RUNNING
        assert c.nodes["n1"].used.milli_cpu == 1000
        store.delete("Pod", "default", "p1")
        assert not c.jobs["default/pg1"].tasks
