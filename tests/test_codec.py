"""Wire codec (api/codec.py): JSON-safe roundtrips over the object zoo."""

from __future__ import annotations

import json

from volcano_tpu.api import codec, objects
from volcano_tpu.cli.job import job_from_yaml
from volcano_tpu.cli.vcctl import DEMO_JOB_YAML
from volcano_tpu.scheduler.util.test_utils import (
    build_node, build_pod, build_pod_group, build_queue,
    build_resource_list_with_pods,
)


def _roundtrip(obj):
    env = codec.envelope(obj)
    back = codec.from_envelope(json.loads(json.dumps(env)))
    assert codec.to_wire(back) == codec.to_wire(obj), type(obj).__name__
    return back


def test_roundtrip_object_zoo():
    pod = build_pod("ns", "p1", "n1", "Running", {"cpu": "1"}, "pg",
                    labels={"a": "b"})
    pod.spec.affinity = objects.Affinity(
        pod_anti_affinity=objects.PodAntiAffinity(required_terms=[
            objects.PodAffinityTerm(
                label_selector=objects.LabelSelector(match_labels={"x": "y"}),
                topology_key="kubernetes.io/hostname")]))
    for obj in (
        build_node("n1", build_resource_list_with_pods("4", "8Gi")),
        pod,
        build_pod_group("pg", min_member=3),
        build_queue("q", weight=2),
        job_from_yaml(DEMO_JOB_YAML),
        objects.Command(
            metadata=objects.ObjectMeta(name="c"), action="AbortJob",
            target_object=objects.OwnerReference(kind="Job", name="j")),
    ):
        _roundtrip(obj)


def test_nested_optionals_and_unknown_fields():
    pod = build_pod("ns", "p", "", "Pending", {}, "")
    wire = codec.envelope(pod)
    wire["object"]["not_a_field"] = 42  # forward compatibility: ignored
    back = codec.from_envelope(wire)
    assert back.metadata.name == "p"
    assert back.spec.affinity is None


def test_every_store_kind_registered():
    for kind in ("Pod", "Node", "PodGroup", "Queue", "Job", "Command",
                 "PriorityClass", "ResourceQuota", "PodDisruptionBudget",
                 "PersistentVolumeClaim", "ConfigMap", "Service"):
        assert codec.kind_class(kind).KIND == kind
