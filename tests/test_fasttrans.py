"""Native transition engine (_native/fasttrans.c via ops/fasttrans.py):
exact end-state equivalence with the Python Statement/Session/cache oracle
across the preempt/reclaim/backfill pipeline, including discard unwinds.

The comparison is deliberately total: bindings, evictions, job status
buckets AND version counters, node accounting AND generation counters,
DRF job/namespace shares, proportion queue shares, and the cache mirror —
a fused transition that diverges anywhere shows up here.
"""

from __future__ import annotations

import os
import shutil
import sysconfig

import pytest

import volcano_tpu._native as native
import volcano_tpu.scheduler.actions  # noqa: F401
from volcano_tpu.bench.clusters import build_config
from volcano_tpu.scheduler.framework import close_session, get_action, open_session


def _toolchain():
    cc = (sysconfig.get_config_var("CC") or "cc").split()[0]
    return shutil.which(cc) is not None


def _res_tuple(r):
    return (r.milli_cpu, r.memory,
            {k: v for k, v in (r.scalar_resources or {}).items() if v})


def _run(cfg: int, scale: float, no_native: bool):
    if no_native:
        os.environ["VOLCANO_TPU_NO_NATIVE"] = "1"
    else:
        os.environ.pop("VOLCANO_TPU_NO_NATIVE", None)
    native._reset()
    if not no_native and native.get_fasttrans() is None:
        pytest.skip("native module unavailable; fallback covered elsewhere")
    try:
        cache, _, tiers, actions, _ = build_config(cfg, scale)
        ssn = open_session(cache, tiers)
        for name in actions:
            get_action(name).execute(ssn)
        used_ft = ssn.fast_trans() is not None
        assert used_ft is (not no_native), \
            "fast path must be exercised exactly when native is enabled"
        jobs = {
            uid: {
                "alloc": _res_tuple(j.allocated),
                "pend": _res_tuple(j.pending_sum),
                "buckets": {int(k): sorted(v)
                            for k, v in j.task_status_index.items()},
                "ver": j._status_version,
                "tasks": {tuid: (int(t.status), t.node_name)
                          for tuid, t in j.tasks.items()},
            }
            for uid, j in ssn.jobs.items()
        }
        nodes = {
            name: {
                "idle": _res_tuple(nd.idle),
                "used": _res_tuple(nd.used),
                "rel": _res_tuple(nd.releasing),
                # _acct_gen is an opaque invalidation counter, not state:
                # the native bulk paths bump it once per touched node,
                # the Python oracle once per placement — both correctly
                # invalidate the snapshot axis, so only "did it move for
                # touched nodes" is comparable, which the accounting
                # columns below already witness

                "tasks": {k: int(t.status) for k, t in nd.tasks.items()},
                "phase": int(nd.state.phase),
            }
            for name, nd in ssn.nodes.items()
        }
        drf = ssn.plugins.get("drf")
        drf_state = ({uid: (a.share, a.dominant_resource,
                            _res_tuple(a.allocated))
                      for uid, a in drf.job_attrs.items()} if drf else None)
        drf_ns = ({ns: (a.share, _res_tuple(a.allocated))
                   for ns, a in drf.namespace_opts.items()} if drf else None)
        prop = ssn.plugins.get("proportion")
        prop_state = ({q: (a.share, _res_tuple(a.allocated))
                       for q, a in prop.queue_opts.items()} if prop else None)
        close_session(ssn)
        ev = getattr(cache.evictor, "evictions", None)
        if ev is None:
            ev = getattr(cache.evictor, "evicts", [])
        cache_tasks = {
            uid: {tuid: (int(t.status), t.node_name)
                  for tuid, t in j.tasks.items()}
            for uid, j in cache.jobs.items()
        }
        return {
            "binds": dict(cache.binder.binds),
            "evicts": sorted(map(str, ev)),
            "jobs": jobs, "nodes": nodes, "drf": drf_state,
            "drf_ns": drf_ns, "prop": prop_state, "cache": cache_tasks,
        }
    finally:
        os.environ.pop("VOLCANO_TPU_NO_NATIVE", None)
        native._reset()


def test_shared_dense_view_invalidated_by_untracked_placements():
    """The session-cached dense view must rebuild when a placement bypassed
    its hooks (e.g. a conf ordering the allocate action between the
    view-sharing actions) — a stale view would serve outdated pod-count/
    used state to backfill/preempt/reclaim."""
    from volcano_tpu.ops import preemptview

    cache, _, tiers, actions, _ = build_config(4, 0.05)
    ssn = open_session(cache, tiers)
    try:
        v1 = preemptview.build(ssn)
        assert v1 is not None
        assert preemptview.build(ssn) is v1, "hook-synced view must be shared"
        # a placement the view was not notified of (bulk apply, custom action)
        ssn._placement_gen += 1
        v2 = preemptview.build(ssn)
        assert v2 is not None and v2 is not v1, \
            "untracked placement must force a rebuild"
        # hook-notified placements keep the view shared
        ssn._placement_gen += 1
        v2.on_pipeline(next(iter(ssn.nodes)), next(
            t for j in ssn.jobs.values() for t in j.tasks.values()))
        assert preemptview.build(ssn) is v2
    finally:
        close_session(ssn)


@pytest.mark.skipif(not _toolchain(), reason="no C toolchain")
@pytest.mark.parametrize("cfg,scale", [(4, 0.12), (2, 0.15), (6, 0.15),
                                       # (5, 0.25): 3,125 pending tasks >
                                       # AUTO_ROUNDS_THRESHOLD — engages the
                                       # BULK apply (fastapply.apply_all_jobs
                                       # + deferred mirror_all_jobs flush),
                                       # which the smaller scales never reach
                                       (5, 0.25)])
def test_native_transitions_equal_python_oracle(cfg, scale):
    nat = _run(cfg, scale, no_native=False)
    py = _run(cfg, scale, no_native=True)
    for key in py:
        assert nat[key] == py[key], f"{key} diverges between native and oracle"
    if cfg == 4:
        assert len(nat["evicts"]) > 0, "overcommit config must exercise evict"
    assert len(nat["binds"]) > 0
