"""Front-door overload layer — admission backpressure + overload rungs.

Four seams:
1. IntakeGate units: token-bucket determinism under an injected clock,
   typed OverloadedError with a computed retry-after, priority-ordered
   shedding (batch first on BOTH the rate and backlog axes), refill
   recovery;
2. the admission storm end-to-end: a burst of valid Jobs against an
   in-process store sheds with bounded admission (never more than
   burst + refill admitted), every rejection typed-with-retry, and the
   interactive class admitted preferentially;
3. the HTTP hop: gateway maps OverloadedError to 429 + Retry-After,
   RemoteStore re-raises it typed and honors the hint through
   degrade.Backoff;
4. the policy layer: the new ladder rungs (watch_coalesce_aggressive,
   admission_shed, snapshot_resync_only) arm/clear from front-door
   signals, and the new /metrics series render (incl. the mandatory
   +Inf bucket on the retry-after histogram).
"""

from __future__ import annotations

import json
import subprocess
import sys

import pytest

from volcano_tpu.admission.intake import (
    IntakeGate, classify_job, install_intake)
from volcano_tpu.api import objects
from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.degrade import DegradeLadder
from volcano_tpu.store.gateway import ApiGateway
from volcano_tpu.store.remote import RemoteStore
from volcano_tpu.store.store import OverloadedError, Store
from volcano_tpu.utils import clock


def _job(name: str, replicas: int = 2, min_available: int = 1,
         queue: str = "") -> objects.Job:
    task = objects.TaskSpec(
        name="w", replicas=replicas,
        template=objects.PodTemplateSpec(
            spec=objects.PodSpec(containers=[objects.Container(
                name="c", image="t",
                requests={"cpu": "100m", "memory": "64Mi"})])))
    job = objects.Job(
        metadata=objects.ObjectMeta(name=name, namespace="fd"),
        spec=objects.JobSpec(min_available=min_available, tasks=[task],
                             queue=queue))
    return job


class _FakeClock:
    def __init__(self, t0: float = 1000.0):
        self.t = t0

    def __call__(self) -> float:
        return self.t


@pytest.fixture
def fake_clock():
    fc = _FakeClock()
    clock.set_source(fc)
    yield fc
    clock.set_source(None)


class TestIntakeGate:
    def test_bucket_deterministic_and_typed_retry_after(self, fake_clock):
        gate = IntakeGate(rate_per_s=2.0, burst=4.0,
                          interactive_reserve=0.0)
        for _ in range(4):
            gate.admit("batch")
        with pytest.raises(OverloadedError) as exc:
            gate.admit("batch")
        assert exc.value.reason == "rate"
        # empty bucket, rate 2/s: one token is 0.5s away — exactly
        assert exc.value.retry_after == pytest.approx(0.5)
        # refill admits again, deterministically
        fake_clock.t += 0.5
        gate.admit("batch")
        with pytest.raises(OverloadedError):
            gate.admit("batch")

    def test_priority_shedding_batch_first_on_rate(self, fake_clock):
        gate = IntakeGate(rate_per_s=1.0, burst=4.0,
                          interactive_reserve=0.5)
        # batch may not spend the reserved half: 2 tokens usable
        gate.admit("batch")
        gate.admit("batch")
        with pytest.raises(OverloadedError):
            gate.admit("batch")
        # interactive rides the reserve to the bottom
        gate.admit("interactive")
        gate.admit("interactive")
        with pytest.raises(OverloadedError):
            gate.admit("interactive")
        st = gate.stats()
        assert st["admitted_batch"] == 2
        assert st["admitted_interactive"] == 2
        assert st["shed_batch"] == 1 and st["shed_interactive"] == 1

    def test_priority_shedding_batch_first_on_backlog(self, fake_clock):
        gate = IntakeGate(rate_per_s=100.0, burst=100.0,
                          max_backlog=100, interactive_reserve=0.25,
                          backlog_retry_s=3.0)
        gate.set_backlog(80)  # >= 75 = batch bound, < 100 = interactive
        with pytest.raises(OverloadedError) as exc:
            gate.admit("batch")
        assert exc.value.reason == "backlog"
        assert exc.value.retry_after == pytest.approx(3.0)
        gate.admit("interactive")  # interactive still admitted
        gate.set_backlog(100)
        with pytest.raises(OverloadedError):
            gate.admit("interactive")

    def test_classify_job_express_envelope(self):
        assert classify_job(_job("tiny", replicas=1,
                                 min_available=1)) == "interactive"
        assert classify_job(_job("gang", replicas=24,
                                 min_available=16)) == "batch"


class TestAdmissionStorm:
    def test_storm_bounded_typed_and_priority_ordered(self, fake_clock):
        """A 60-submission burst against burst=8: admission stays
        bounded at the bucket depth, every rejection is the typed
        rejected-with-retry contract, and the interactive class is shed
        strictly less than batch."""
        store = Store()
        gate = IntakeGate(rate_per_s=2.0, burst=8.0,
                          interactive_reserve=0.25)
        install_intake(store, gate)
        admitted, shed = [], []
        for i in range(60):
            interactive = i % 2 == 0
            job = _job(f"j{i:03d}",
                       replicas=1 if interactive else 24,
                       min_available=1 if interactive else 16)
            try:
                store.create(job)
                admitted.append(job)
            except OverloadedError as e:
                shed.append(e)
        # bounded inflight: never more than the bucket depth in a burst
        assert len(admitted) <= 8
        assert len(shed) == 60 - len(admitted)
        assert all(e.retry_after > 0 for e in shed)
        assert all(e.reason == "rate" for e in shed)
        st = gate.stats()
        # priority order: batch exhausted the unreserved tranche first;
        # interactive kept admitting into the reserve
        assert st["admitted_interactive"] > st["admitted_batch"]
        shed_rate_batch = st["shed_batch"] / 30
        shed_rate_inter = st["shed_interactive"] / 30
        assert shed_rate_batch > shed_rate_inter
        # nothing dropped without a retry hint, and the ledger balances
        assert st["shed_total"] == len(shed)
        assert st["attempts"] == 60

    def test_admitted_jobs_actually_landed(self, fake_clock):
        store = Store()
        gate = IntakeGate(rate_per_s=1.0, burst=2.0,
                          interactive_reserve=0.0)
        install_intake(store, gate)
        store.create(_job("a"))
        store.create(_job("b"))
        with pytest.raises(OverloadedError):
            store.create(_job("c"))
        names = sorted(j.metadata.name for j in store.list("Job"))
        assert names == ["a", "b"], "shed submission must not land"


class TestHttpHop:
    def test_gateway_429_and_remote_typed(self):
        store = Store()
        gate = IntakeGate(rate_per_s=0.5, burst=1.0,
                          interactive_reserve=0.0)
        install_intake(store, gate)
        gateway = ApiGateway(store).start()
        try:
            remote = RemoteStore(f"127.0.0.1:{gateway.port}",
                                 overload_retries=0)
            remote.create(_job("ok"))
            with pytest.raises(OverloadedError) as exc:
                remote.create(_job("nope"))
            assert exc.value.retry_after > 0
            assert exc.value.reason == "rate"
            # the raw HTTP reply carries the Retry-After header
            import urllib.error
            import urllib.request

            from volcano_tpu.api import codec

            req = urllib.request.Request(
                f"http://127.0.0.1:{gateway.port}/apis/Job",
                data=json.dumps(codec.envelope(_job("raw"))).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as raw:
                urllib.request.urlopen(req, timeout=5)
            assert raw.value.code == 429
            assert float(raw.value.headers["Retry-After"]) > 0
        finally:
            gateway.stop()

    def test_remote_honors_retry_after_with_backoff(self):
        """overload_retries: the client pauses >= the server hint (via
        degrade.Backoff) and the re-submission succeeds."""
        store = Store()
        gate = IntakeGate(rate_per_s=20.0, burst=1.0,
                          interactive_reserve=0.0)
        install_intake(store, gate)
        gateway = ApiGateway(store).start()
        try:
            remote = RemoteStore(f"127.0.0.1:{gateway.port}",
                                 overload_retries=3)
            remote.create(_job("first"))
            # bucket empty; rate 20/s -> retry_after 0.05s: the retry
            # path must absorb it transparently
            created = remote.create(_job("second"))
            assert created.metadata.name == "second"
            st = remote.intake_stats()
            assert st["overloaded"] >= 1
            assert st["retries"] >= 1
            assert st["backoff_s"] > 0
        finally:
            gateway.stop()


class TestOverloadRungs:
    def test_admission_shed_rung_arms_and_clears(self, fake_clock):
        ladder = DegradeLadder(shed_hold_s=5.0)
        assert ladder.rung() == ""
        ladder.note_admission_shed()
        assert ladder.rung() == "admission_shed"
        fake_clock.t += 6.0
        assert ladder.rung() == ""

    def test_coalesce_rung_arms_on_lag_signal(self, fake_clock):
        ladder = DegradeLadder(coalesce_hold_s=10.0)
        ladder.note_watch_lag(10, 100)  # under half the budget: quiet
        assert ladder.rung() == ""
        assert not ladder.watch_coalesce_aggressive()
        ladder.note_watch_lag(60, 100)  # over half: armed
        assert ladder.watch_coalesce_aggressive()
        assert ladder.rung() == "watch_coalesce_aggressive"
        fake_clock.t += 11.0
        assert not ladder.watch_coalesce_aggressive()

    def test_resync_only_breaker_demotion_storm(self, fake_clock):
        ladder = DegradeLadder(frontdoor_threshold=3,
                               frontdoor_cooldown_s=10.0)
        assert not ladder.watch_resync_only()
        for _ in range(3):
            ladder.note_watch_demotion()
        assert ladder.rung() == "snapshot_resync_only"
        assert ladder.watch_resync_only()
        # open implies coalesce-hard too
        assert ladder.watch_coalesce_aggressive()
        # cooldown passes: one probe allowed, and a completed resync
        # closes the breaker
        fake_clock.t += 11.0
        assert not ladder.watch_resync_only()  # the half-open probe
        ladder.note_watch_promoted()
        assert ladder.rung() == ""
        assert not ladder.watch_resync_only()

    def test_session_skip_still_most_severe(self, fake_clock):
        ladder = DegradeLadder(frontdoor_threshold=1)
        ladder.note_watch_demotion()
        for _ in range(3):
            ladder.note_store_error()
        assert ladder.rung() == "session_skip"


class TestFrontDoorMetrics:
    def test_new_series_render_with_inf_bucket(self):
        metrics.reset()
        try:
            metrics.set_watch_queue_depth("interactive", 7)
            metrics.set_watch_queue_depth("batch", 123)
            metrics.register_watch_coalesced(41)
            metrics.register_admission_shed("rate", 3)
            metrics.register_admission_shed("backlog")
            metrics.observe_admission_retry_after(0.3)
            metrics.observe_admission_retry_after(42.0)  # beyond buckets
            body = metrics.render()
            assert ('volcano_watch_queue_depth{watcher_class='
                    '"interactive"} 7' in body)
            assert ('volcano_watch_queue_depth{watcher_class='
                    '"batch"} 123' in body)
            assert "volcano_watch_events_coalesced_total 41" in body
            assert 'volcano_admission_shed_total{reason="rate"} 3' in body
            assert ('volcano_admission_shed_total{reason="backlog"} 1'
                    in body)
            # +Inf bucket is mandatory and equals _count (2 observations,
            # one past the last finite bucket)
            assert ('volcano_admission_retry_after_seconds_bucket'
                    '{le="+Inf"} 2' in body)
            assert "volcano_admission_retry_after_seconds_count 2" in body
        finally:
            metrics.reset()


class TestFanoutBenchCli:
    def test_bench_fanout_tail(self, tmp_path):
        """`bench.py --fanout N` — the standing 10k-watcher column at a
        smoke size: bounded per-watcher memory (cursor+counters only)
        and a recorded p99 delivery latency."""
        out = subprocess.run(
            [sys.executable, "bench.py", "--fanout", "400"],
            capture_output=True, text=True, timeout=240,
            cwd="/root/repo")
        assert out.returncode == 0, out.stderr[-2000:]
        tail = json.loads(out.stdout.strip().splitlines()[-1])
        fanout = tail["summary"]["watch_fanout"]
        assert fanout["watchers"] == 400
        assert fanout["deliveries"] > 0
        assert fanout["fanout_p99_ms"] >= 0.0
        # the O(events + watchers) proof: per-watcher state is a few
        # hundred bytes (cursor + counters), no queues, no copies
        assert fanout["per_watcher_state_bytes"] < 4096
        assert fanout["journal_peak_occupancy"] \
            <= fanout["journal_hard_cap"]
