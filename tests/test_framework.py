"""Framework semantics tests: tiered dispatch, statement commit/rollback,
priority queue, job updater dedup."""

from tests.helpers import make_cache, make_tiers
from volcano_tpu.api import objects
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.scheduler import conf
from volcano_tpu.scheduler.framework import open_session
from volcano_tpu.scheduler.framework.session import Session
from volcano_tpu.scheduler.util.priority_queue import PriorityQueue
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)


def rl(cpu, mem):
    r = build_resource_list(cpu, mem)
    r["pods"] = 110
    return r


def make_session_with_cluster(tiers=None, nodes=1, gang_size=2, min_member=2):
    c = make_cache()
    c.add_queue(build_queue("default"))
    c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=min_member))
    for i in range(gang_size):
        c.add_pod(build_pod("c1", f"p{i}", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1"))
    for n in range(nodes):
        c.add_node(build_node(f"n{n}", rl("8", "16Gi")))
    ssn = open_session(c, tiers if tiers is not None else make_tiers(["gang"]))
    return c, ssn


class TestTieredDispatch:
    def _session_with_tiers(self, *tier_names):
        ssn = Session.__new__(Session)
        Session.__init__(ssn, cache=None)
        ssn.tiers = make_tiers(*tier_names)
        return ssn

    def test_victim_intersection_within_tier(self):
        ssn = self._session_with_tiers(["a", "b"])

        class T:
            def __init__(self, uid):
                self.uid = uid

        t1, t2, t3 = T("1"), T("2"), T("3")
        ssn.add_preemptable_fn("a", lambda p, lst: [t1, t2])
        ssn.add_preemptable_fn("b", lambda p, lst: [t2, t3])
        assert ssn.preemptable(None, [t1, t2, t3]) == [t2]

    def test_first_deciding_tier_wins(self):
        ssn = self._session_with_tiers(["a"], ["b"])

        class T:
            def __init__(self, uid):
                self.uid = uid

        t1, t2 = T("1"), T("2")
        ssn.add_preemptable_fn("a", lambda p, lst: [t1])
        ssn.add_preemptable_fn("b", lambda p, lst: [t2])
        # tier 1 decides (non-None result), tier 2 never consulted
        assert ssn.preemptable(None, [t1, t2]) == [t1]

    def test_empty_first_tier_decides_no_victims(self):
        ssn = self._session_with_tiers(["a"], ["b"])

        class T:
            def __init__(self, uid):
                self.uid = uid

        t1 = T("1")
        ssn.add_preemptable_fn("a", lambda p, lst: [])
        ssn.add_preemptable_fn("b", lambda p, lst: [t1])
        # [] is non-None: tier 1 decided there are no victims
        assert ssn.preemptable(None, [t1]) == []

    def test_order_first_nonzero_wins(self):
        ssn = self._session_with_tiers(["a", "b"])
        ssn.add_job_order_fn("a", lambda l, r: 0)
        ssn.add_job_order_fn("b", lambda l, r: -1)

        class J:
            creation_timestamp = 0
            uid = "x"

        assert ssn.job_order_fn(J(), J()) is True

    def test_job_ready_is_and(self):
        ssn = self._session_with_tiers(["a", "b"])
        ssn.add_job_ready_fn("a", lambda j: True)
        ssn.add_job_ready_fn("b", lambda j: False)
        assert ssn.job_ready(None) is False

    def test_overused_is_or(self):
        ssn = self._session_with_tiers(["a", "b"])
        ssn.add_overused_fn("a", lambda q: False)
        ssn.add_overused_fn("b", lambda q: True)
        assert ssn.overused(None) is True

    def test_disabled_flag_skips_plugin(self):
        ssn = Session.__new__(Session)
        Session.__init__(ssn, cache=None)
        option = conf.PluginOption(name="a")
        from volcano_tpu.scheduler.plugins import apply_plugin_conf_defaults

        apply_plugin_conf_defaults(option)
        option.enabled_job_ready = False
        ssn.tiers = [conf.Tier(plugins=[option])]
        ssn.add_job_ready_fn("a", lambda j: False)
        assert ssn.job_ready(None) is True  # disabled -> not consulted

    def test_node_order_sums(self):
        ssn = self._session_with_tiers(["a", "b"])
        ssn.add_node_order_fn("a", lambda t, n: 3.0)
        ssn.add_node_order_fn("b", lambda t, n: 4.0)
        assert ssn.node_order_fn(None, None) == 7.0


class TestStatement:
    def test_commit_binds(self):
        c, ssn = make_session_with_cluster(min_member=2)
        stmt = ssn.statement()
        job = ssn.jobs["c1/pg1"]
        tasks = list(job.task_status_index[TaskStatus.PENDING].values())
        for t in tasks:
            stmt.allocate(t, "n0")
        assert c.binder.binds == {}  # nothing until commit
        stmt.commit()
        assert len(c.binder.binds) == 2

    def test_discard_restores_state(self):
        c, ssn = make_session_with_cluster(min_member=2)
        job = ssn.jobs["c1/pg1"]
        node = ssn.nodes["n0"]
        idle_before = node.idle.milli_cpu
        stmt = ssn.statement()
        t = next(iter(job.task_status_index[TaskStatus.PENDING].values()))
        stmt.allocate(t, "n0")
        assert node.idle.milli_cpu == idle_before - 1000
        assert job.ready_task_num() == 1
        stmt.discard()
        assert node.idle.milli_cpu == idle_before
        assert job.ready_task_num() == 0
        assert t.status == TaskStatus.PENDING
        assert c.binder.binds == {}

    def test_discard_reverses_evict(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=1))
        c.add_pod(build_pod("c1", "r1", "n0", objects.POD_PHASE_RUNNING,
                            build_resource_list("2", "4Gi"), "pg1"))
        c.add_node(build_node("n0", rl("8", "16Gi")))
        ssn = open_session(c, make_tiers(["gang"]))
        job = ssn.jobs["c1/pg1"]
        task = next(iter(job.task_status_index[TaskStatus.RUNNING].values()))
        node = ssn.nodes["n0"]
        stmt = ssn.statement()
        stmt.evict(task, "test")
        assert node.releasing.milli_cpu == 2000
        stmt.discard()
        assert node.releasing.milli_cpu == 0
        assert task.status == TaskStatus.RUNNING
        assert c.evictor.evicts == []


class TestPriorityQueue:
    def test_ordering(self):
        q = PriorityQueue(lambda l, r: l < r)
        for v in [5, 1, 3]:
            q.push(v)
        assert [q.pop(), q.pop(), q.pop()] == [1, 3, 5]

    def test_stability(self):
        q = PriorityQueue(lambda l, r: False)  # all equal
        for v in ["a", "b", "c"]:
            q.push(v)
        assert [q.pop(), q.pop(), q.pop()] == ["a", "b", "c"]

    def test_empty_pop(self):
        assert PriorityQueue().pop() is None


class TestJobValidMemo:
    def test_gang_rejection_survives_pre_registration_dispatch(self):
        """open_session_state dispatches job_valid BEFORE plugins register;
        the per-status-version memo must not latch a pass verdict against
        the empty validator set (a stale hit would silently bypass gang's
        NOT_ENOUGH_PODS rejection for every job whose status is unchanged
        since the snapshot)."""
        # gang of 2 but only 1 task exists -> invalid under gang
        c, ssn = make_session_with_cluster(gang_size=1, min_member=2)
        job = next(iter(ssn.jobs.values()))
        vr = ssn.job_valid(job)
        assert vr is not None and not vr.pass_, \
            "gang must reject an under-populated gang after registration"
        # memoized second call returns the same verdict
        assert ssn.job_valid(job) is vr

    def test_memo_invalidated_by_status_change(self):
        c, ssn = make_session_with_cluster(gang_size=2, min_member=2)
        job = next(iter(ssn.jobs.values()))
        assert ssn.job_valid(job) is None  # valid gang
        # removing a task flips validity; the version-keyed memo must see it
        t = next(iter(job.tasks.values()))
        job.delete_task_info(t)
        vr = ssn.job_valid(job)
        assert vr is not None and not vr.pass_
