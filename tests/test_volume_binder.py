"""StoreVolumeBinder: PV assume/bind semantics at binding time
(the reference's defaultVolumeBinder wraps the k8s volumebinder,
pkg/scheduler/cache/cache.go:240-258 — assume on allocate, bind on
commit, placement fails when no compatible volume exists)."""

from __future__ import annotations

import pytest

from tests.helpers import close_session, make_tiers, open_session
from volcano_tpu.api import objects
from volcano_tpu.scheduler.cache.cache import SchedulerCache, StoreVolumeBinder
from volcano_tpu.scheduler.framework import get_action
from volcano_tpu.scheduler.util.test_utils import (
    FakeBinder, FakeEvictor, FakeStatusUpdater,
    build_node, build_pod, build_pod_group, build_queue,
    build_resource_list_with_pods,
)
from volcano_tpu.store.store import Store

TIERS = (["priority", "gang"], ["drf", "predicates", "proportion", "nodeorder"])


def _pv(name, storage="10Gi", node_names=()):
    return objects.PersistentVolume(
        metadata=objects.ObjectMeta(name=name),
        capacity={"storage": storage}, node_names=list(node_names))


def _pvc(ns, name, storage="5Gi"):
    return objects.PersistentVolumeClaim(
        metadata=objects.ObjectMeta(name=name, namespace=ns),
        requests={"storage": storage})


def _cluster(nodes=2):
    store = Store()
    cache = SchedulerCache(
        store=store, binder=FakeBinder(), evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater())  # volume binder defaults: store
    cache.run()
    store.create(build_queue("default"))
    for i in range(nodes):
        store.create(build_node(
            f"n{i}", build_resource_list_with_pods("8", "16Gi")))
    assert isinstance(cache.volume_binder, StoreVolumeBinder)
    return store, cache


def _pod_with_pvc(ns, name, pvc_name, group):
    pod = build_pod(ns, name, "", "Pending", {"cpu": "1"}, group)
    pod.spec.volumes.append(objects.Volume(
        name="data", persistent_volume_claim=pvc_name))
    return pod


def _schedule(cache):
    ssn = open_session(cache, make_tiers(*TIERS))
    for action in ("enqueue", "allocate", "backfill"):
        get_action(action).execute(ssn)
    close_session(ssn)


def test_assume_and_bind_commits_pv_pvc():
    store, cache = _cluster()
    store.create(_pv("pv-a", "10Gi"))
    store.create(_pvc("default", "claim-a"))
    store.create(build_pod_group("pg", min_member=1))
    store.create(_pod_with_pvc("default", "p0", "claim-a", "pg"))

    _schedule(cache)
    assert len(cache.binder.binds) == 1
    pv = store.get("PersistentVolume", "", "pv-a")
    pvc = store.get("PersistentVolumeClaim", "default", "claim-a")
    assert pv.phase == "Bound" and pv.claim_ref == "default/claim-a"
    assert pvc.phase == "Bound" and pvc.volume_name == "pv-a"


def test_local_volume_constrains_host():
    """A node-local PV: binding succeeds only when the chosen host carries
    the volume; a host mismatch fails the allocation (assume failure)."""
    store, cache = _cluster(nodes=3)
    store.create(_pv("pv-local", "10Gi", node_names=["n1"]))
    store.create(_pvc("default", "claim-l"))
    store.create(build_pod_group("pg", min_member=1))
    store.create(_pod_with_pvc("default", "p0", "claim-l", "pg"))

    _schedule(cache)
    binds = cache.binder.binds
    if binds:  # bound => it MUST be the volume's node
        assert binds["default/p0"] == "n1", binds
        assert store.get("PersistentVolume", "", "pv-local").phase == "Bound"
    else:  # chosen host mismatched: allocation failed, nothing half-bound
        assert store.get("PersistentVolume", "", "pv-local").phase == "Available"
        pvc = store.get("PersistentVolumeClaim", "default", "claim-l")
        assert pvc.phase == "Pending"


def test_smallest_sufficient_volume_wins():
    store, cache = _cluster()
    store.create(_pv("pv-big", "100Gi"))
    store.create(_pv("pv-small", "6Gi"))
    store.create(_pvc("default", "claim-s", "5Gi"))
    store.create(build_pod_group("pg", min_member=1))
    store.create(_pod_with_pvc("default", "p0", "claim-s", "pg"))

    _schedule(cache)
    assert len(cache.binder.binds) == 1
    assert store.get("PersistentVolumeClaim",
                     "default", "claim-s").volume_name == "pv-small"
    assert store.get("PersistentVolume", "", "pv-big").phase == "Available"


def test_no_fitting_volume_blocks_placement():
    store, cache = _cluster()
    store.create(_pv("pv-tiny", "1Gi"))
    store.create(_pvc("default", "claim-x", "50Gi"))
    store.create(build_pod_group("pg", min_member=1))
    store.create(_pod_with_pvc("default", "p0", "claim-x", "pg"))

    _schedule(cache)
    assert "default/p0" not in cache.binder.binds
    assert store.get("PersistentVolume", "", "pv-tiny").phase == "Available"


def test_two_claims_cannot_share_one_volume():
    store, cache = _cluster()
    store.create(_pv("pv-only", "10Gi"))
    store.create(_pvc("default", "claim-1"))
    store.create(_pvc("default", "claim-2"))
    store.create(build_pod_group("pg", min_member=1))
    store.create(_pod_with_pvc("default", "p1", "claim-1", "pg"))
    store.create(_pod_with_pvc("default", "p2", "claim-2", "pg"))

    _schedule(cache)
    bound = [k for k in cache.binder.binds]
    assert len(bound) == 1, bound  # exactly one pod got the volume
    pv = store.get("PersistentVolume", "", "pv-only")
    assert pv.phase == "Bound"


def test_pvc_pods_take_residue_under_rounds_mode():
    """PVC-referencing pods are excluded from the device bulk solve (the
    volume assume is live per-host logic) and placed by the serial residue
    pass — same session, volumes bound, plain pods still bulk-placed."""
    from tests.helpers import make_tiers as mk

    store, cache = _cluster(nodes=3)
    store.create(_pv("pv-r", "10Gi"))
    store.create(_pvc("default", "claim-r"))
    store.create(build_pod_group("pg", min_member=1))
    store.create(_pod_with_pvc("default", "pv-pod", "claim-r", "pg"))
    for i in range(6):
        store.create(build_pod("default", f"plain-{i}", "", "Pending",
                               {"cpu": "1"}, "pg"))

    ssn = open_session(cache, mk(["tpuscore"], *TIERS))
    assert ssn.batch_allocator is not None
    ssn.batch_allocator.mode = "rounds"
    for action in ("enqueue", "allocate", "backfill"):
        get_action(action).execute(ssn)
    prof = dict(ssn.plugins["tpuscore"].profile)
    close_session(ssn)
    assert prof.get("mode") == "rounds", prof
    assert prof.get("residue", 0) >= 1, prof  # the PVC pod went serial
    assert len(cache.binder.binds) == 7, cache.binder.binds
    assert store.get("PersistentVolume", "", "pv-r").phase == "Bound"


def test_pvc_free_sessions_keep_native_bulk_path():
    """The PVC-pod counter gates the per-task volume calls: with a real
    StoreVolumeBinder but no PVC pods, the bulk writeback must stay
    eligible for the native loop (vols_noop)."""
    store, cache = _cluster()
    store.create(build_pod_group("pg", min_member=2))
    for i in range(2):
        store.create(build_pod("default", f"p{i}", "", "Pending",
                               {"cpu": "1"}, "pg"))
    assert cache._pvc_pod_count == 0
    store.create(_pvc("default", "c"))
    store.create(_pod_with_pvc("default", "pv-pod", "c", "pg"))
    assert cache._pvc_pod_count == 1
    store.delete("Pod", "default", "pv-pod")
    assert cache._pvc_pod_count == 0
