"""Allocate-residue dense assist (preemptview.build_alloc_assist +
allocate._serial_execute wiring) vs the legacy serial sweep — placements,
round-robin cursor, and node accounting must be BIT-IDENTICAL. The assist
claims exact window semantics (signature ∧ pod-count ∧ epsilon resource
fit ∧ live residual affinity/ports), exact score parity via the cached
rows, and select_best_node's max-score/min-name pick.
"""

from __future__ import annotations

import random

import pytest

from tests.helpers import close_session, make_cache, make_tiers, open_session
from volcano_tpu.api import objects
from volcano_tpu.ops import preemptview
from volcano_tpu.scheduler.actions.allocate import AllocateAction
from volcano_tpu.scheduler.util import scheduler_helper as helper
from volcano_tpu.scheduler.util.test_utils import (
    build_node, build_pod, build_pod_group, build_queue,
    build_resource_list_with_pods,
)

TIERS = (["priority", "gang"], ["predicates", "binpack", "proportion"])
TIERS_NODEORDER = (["priority", "gang"],
                   ["drf", "predicates", "proportion", "nodeorder"])


def _anti_affinity(labels):
    return objects.Affinity(
        pod_anti_affinity=objects.PodAntiAffinity(required_terms=[
            objects.PodAffinityTerm(
                label_selector=objects.LabelSelector(match_labels=labels),
                topology_key="kubernetes.io/hostname")]))


def _cluster(seed: int, affinity: bool, ports: bool, resident_anti: bool,
             nodes: int = 40, groups: int = 60):
    def populate(c):
        rng = random.Random(seed)
        c.add_queue(build_queue("default"))
        for n in range(nodes):
            c.add_node(build_node(
                f"node-{n:03d}",
                build_resource_list_with_pods("8", "16Gi", pods=32),
                labels={"zone": f"z{n % 4}"}))
        if resident_anti:
            for g in range(6):
                pg = f"res-{g:02d}"
                c.add_pod_group(build_pod_group(
                    pg, namespace="aa", min_member=1))
                pod = build_pod(
                    "aa", f"{pg}-t0", f"node-{rng.randrange(nodes):03d}",
                    objects.POD_PHASE_RUNNING,
                    {"cpu": "500m", "memory": "512Mi"}, pg,
                    labels={"solo": f"s{g}"})
                pod.spec.affinity = _anti_affinity({"solo": f"s{g}"})
                c.add_pod(pod)
        for g in range(groups):
            pg = f"pg-{g:03d}"
            c.add_pod_group(build_pod_group(pg, namespace="aa", min_member=2))
            for i in range(3):
                pod = build_pod(
                    "aa", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                    {"cpu": f"{rng.choice([250, 500, 1000])}m",
                     "memory": rng.choice(["256Mi", "512Mi"])}, pg)
                r = rng.random()
                if affinity and r < 0.2:
                    lbl = {"app": f"a{g % 8}"}
                    pod.metadata.labels.update(lbl)
                    pod.spec.affinity = _anti_affinity(lbl)
                elif ports and r < 0.3:
                    pod.spec.containers[0].ports = [
                        objects.ContainerPort(host_port=9000 + g % 16,
                                              container_port=80)]
                # a pod that lands on a matching resident's node must be
                # rejected by the symmetry clause
                if resident_anti and r > 0.9:
                    pod.metadata.labels["solo"] = f"s{g % 6}"
                c.add_pod(pod)

    return populate


def _run(populate, assisted: bool):
    cache = make_cache()
    populate(cache)
    tiers = make_tiers(["tpuscore"], *TIERS)
    ssn = open_session(cache, tiers)
    action = AllocateAction()
    assist = preemptview.build_alloc_assist(ssn) if assisted else None
    if assisted:
        assert assist is not None
    action._serial_execute(ssn, assist=assist)
    cursor = helper._last_processed_node_index
    idle = {n: (nd.idle.milli_cpu, nd.idle.memory)
            for n, nd in ssn.nodes.items()}
    close_session(ssn)
    return dict(cache.binder.binds), cursor, idle


@pytest.mark.parametrize("affinity,ports,resident", [
    (False, False, False),
    (True, False, False),
    (False, True, False),
    (True, True, True),
])
@pytest.mark.parametrize("seed", [5, 19])
def test_assisted_serial_parity(seed, affinity, ports, resident):
    populate = _cluster(seed, affinity, ports, resident)
    binds_a, cursor_a, idle_a = _run(populate, assisted=True)
    binds_s, cursor_s, idle_s = _run(populate, assisted=False)
    assert binds_a == binds_s
    assert cursor_a == cursor_s
    assert idle_a == idle_s


def test_assist_matrices_track_objects():
    """After an assisted pass the view's idle/releasing/used mirrors equal
    the live node objects exactly (the incremental hook arithmetic)."""
    populate = _cluster(3, True, True, True)
    cache = make_cache()
    populate(cache)
    ssn = open_session(cache, make_tiers(["tpuscore"], *TIERS))
    assist = preemptview.build_alloc_assist(ssn)
    assert assist is not None
    AllocateAction()._serial_execute(ssn, assist=assist)
    for i, name in enumerate(assist.node_names):
        nd = ssn.nodes[name]
        assert assist.idle[i, 0] == nd.idle.milli_cpu, name
        assert assist.idle[i, 1] == nd.idle.memory, name
        assert assist.used[i, 0] == nd.used.milli_cpu, name
    close_session(ssn)


def test_resident_preferred_terms_disable_assist():
    """nodeorder's InterPodAffinity batch scorer reads preferred terms of
    resident pods; such residents must disable the assist entirely."""
    cache = make_cache()
    cache.add_queue(build_queue("default"))
    cache.add_node(build_node("n0", build_resource_list_with_pods("8", "16Gi")))
    cache.add_pod_group(build_pod_group("r", namespace="aa", min_member=1))
    pod = build_pod("aa", "r-t0", "n0", objects.POD_PHASE_RUNNING,
                    {"cpu": "500m", "memory": "512Mi"}, "r",
                    labels={"x": "y"})
    pod.spec.affinity = objects.Affinity(
        pod_anti_affinity=objects.PodAntiAffinity(preferred_terms=[
            objects.WeightedPodAffinityTerm(
                weight=1,
                pod_affinity_term=objects.PodAffinityTerm(
                    label_selector=objects.LabelSelector(
                        match_labels={"x": "y"})))]))
    cache.add_pod(pod)
    ssn = open_session(cache, make_tiers(["tpuscore"], *TIERS_NODEORDER))
    assert preemptview.build_alloc_assist(ssn) is None
    # without the batch scorer the same resident is tolerated
    close_session(ssn)
    cache2 = make_cache()
    cache2.add_queue(build_queue("default"))
    cache2.add_node(build_node("n0", build_resource_list_with_pods("8", "16Gi")))
    ssn2 = open_session(cache2, make_tiers(["tpuscore"], *TIERS))
    assert preemptview.build_alloc_assist(ssn2) is not None
    close_session(ssn2)
