"""Single-host integration: the full submit -> enqueue -> allocate -> bind
-> run -> complete pipeline over the in-process cluster with a simulated
kubelet (SURVEY.md §4 tier 3; mirrors the reference's kind-cluster e2e
coverage: job_scheduling.go, job_lifecycle.go, job_plugins.go, mpi.go)."""

from __future__ import annotations

import copy

from tests.test_controllers import make_job
from volcano_tpu.api import objects
from volcano_tpu.api.objects import JobAction, JobEvent, JobPhase
from volcano_tpu.cluster import Cluster
from volcano_tpu.scheduler.scheduler import TPU_SCHEDULER_CONF
from volcano_tpu.scheduler.util.test_utils import build_node, build_resource_list_with_pods
from volcano_tpu.store.store import AdmissionError

import pytest


def make_cluster(nodes=3, cpu="8", mem="16Gi", **kwargs) -> Cluster:
    cluster = Cluster(**kwargs)
    for n in range(nodes):
        node = build_node(f"node-{n}", build_resource_list_with_pods(cpu, mem))
        cluster.store.create(node)
    return cluster


def finish_pods(cluster: Cluster, phase=objects.POD_PHASE_SUCCEEDED) -> None:
    for pod in cluster.store.list("Pod"):
        if pod.status.phase == objects.POD_PHASE_RUNNING:
            updated = copy.deepcopy(pod)
            updated.status.phase = phase
            cluster.store.update_status(updated)


def job_state(cluster, name="job1", namespace="ns1"):
    return cluster.store.get("Job", namespace, name).status.state.phase


class TestPipeline:
    def test_submit_to_completed(self):
        cluster = make_cluster()
        job = make_job(min_available=2, tasks=(("worker", 2),))
        job.spec.scheduler_name = "volcano"
        cluster.store.create(job)

        cluster.settle(4)
        # pods created, gated until Inqueue, then bound and started
        pods = cluster.store.list("Pod", namespace="ns1")
        assert len(pods) == 2
        assert all(p.spec.node_name for p in pods)
        assert all(p.status.phase == objects.POD_PHASE_RUNNING for p in pods)
        assert job_state(cluster) == JobPhase.RUNNING
        pg = cluster.store.get("PodGroup", "ns1", "job1")
        assert pg.status.phase == objects.PodGroupPhase.RUNNING

        finish_pods(cluster)
        cluster.settle(3)
        assert job_state(cluster) == JobPhase.COMPLETED

    def test_delay_pod_creation_gate(self):
        # without capacity the PodGroup stays Pending and pods are never
        # admitted (docs/design/delay-pod-creation.md)
        cluster = make_cluster(nodes=0)
        job = make_job(min_available=2, tasks=(("worker", 2),))
        job.spec.scheduler_name = "volcano"
        cluster.store.create(job)
        cluster.settle(3)
        assert cluster.store.list("Pod", namespace="ns1") == []
        pg = cluster.store.get("PodGroup", "ns1", "job1")
        assert pg.status.phase == objects.PodGroupPhase.PENDING

    def test_gang_all_or_nothing_across_jobs(self):
        # one node fits only one 4-gang; second job waits entirely
        cluster = make_cluster(nodes=1, cpu="4", mem="8Gi")
        for name in ("gang-a", "gang-b"):
            job = make_job(name=name, min_available=4, tasks=(("w", 4),))
            job.spec.scheduler_name = "volcano"
            cluster.store.create(job)
        cluster.settle(4)
        bound = {p.metadata.annotations[objects.JOB_NAME_KEY]
                 for p in cluster.store.list("Pod") if p.spec.node_name}
        assert len(bound) == 1  # exactly one whole gang

    def test_tpu_conf_pipeline(self):
        cluster = make_cluster(scheduler_conf=TPU_SCHEDULER_CONF)
        job = make_job(min_available=2, tasks=(("worker", 2),))
        job.spec.scheduler_name = "volcano"
        cluster.store.create(job)
        cluster.settle(4)
        pods = cluster.store.list("Pod", namespace="ns1")
        assert len(pods) == 2 and all(p.spec.node_name for p in pods)


class TestMPIRendezvous:
    def test_mpi_job_hostfile_and_keys(self):
        """The reference's e2e MPI flow (test/e2e/mpi.go:26-78): master +
        workers with svc/ssh plugins; hostfile lists worker DNS names."""
        cluster = make_cluster()
        job = make_job(
            name="lm-mpi-job", min_available=3,
            tasks=(("mpimaster", 1), ("mpiworker", 2)),
            plugins={"ssh": [], "svc": []})
        job.spec.scheduler_name = "volcano"
        cluster.store.create(job)
        cluster.settle(4)

        cm = cluster.store.get("ConfigMap", "ns1", "lm-mpi-job-svc")
        assert cm.data["mpiworker.host"].splitlines() == [
            "lm-mpi-job-mpiworker-0.lm-mpi-job",
            "lm-mpi-job-mpiworker-1.lm-mpi-job",
        ]
        assert "id_rsa" in cluster.store.get("ConfigMap", "ns1", "lm-mpi-job-ssh").data
        pods = cluster.store.list("Pod", namespace="ns1")
        assert len(pods) == 3
        assert all(p.status.phase == objects.POD_PHASE_RUNNING for p in pods)
        # every pod has a stable DNS identity for rendezvous (per pod: a
        # swapped-hostname bug cannot hide behind set equality)
        for p in pods:
            assert p.spec.hostname == p.metadata.name
        assert {p.spec.subdomain for p in pods} == {"lm-mpi-job"}


class TestPSWorkerRendezvous:
    def test_tf_style_ps_worker_job(self):
        """The reference's distributed-TF e2e (test/e2e/tensorflow.go:123):
        a job with HETEROGENEOUS task groups — ps x2 + worker x4 — using
        the env and svc plugins. Every pod gets its per-group VK_TASK_INDEX
        and a stable DNS identity; the svc ConfigMap carries a hostfile PER
        GROUP (the TF_CONFIG cluster-spec analog); gang scheduling blocks
        the WHOLE job until both groups fit."""
        # one 4-cpu node: the 6-pod gang needs 6 cpu total, so nothing may
        # bind until more capacity arrives
        cluster = make_cluster(nodes=1, cpu="4", mem="8Gi")
        job = make_job(
            name="dist-mnist", min_available=6,
            tasks=(("ps", 2), ("worker", 4)),
            plugins={"env": [], "svc": []})
        job.spec.scheduler_name = "volcano"
        cluster.store.create(job)
        cluster.settle(4)

        # gang-blocked: 6 x 1cpu > 4 cpu — no pod of EITHER group binds
        bound = [p for p in cluster.store.list("Pod", namespace="ns1")
                 if p.spec.node_name]
        assert bound == [], "gang must stay whole while capacity is short"
        pg = cluster.store.get("PodGroup", "ns1", "dist-mnist")
        assert pg.status.phase != objects.PodGroupPhase.RUNNING

        # capacity arrives -> the whole heterogeneous gang binds at once
        cluster.store.create(build_node(
            "node-late", build_resource_list_with_pods("8", "16Gi")))
        cluster.settle(5)
        pods = cluster.store.list("Pod", namespace="ns1")
        assert len(pods) == 6
        assert all(p.spec.node_name for p in pods)
        assert all(p.status.phase == objects.POD_PHASE_RUNNING for p in pods)

        # per-group hostfiles in the svc ConfigMap (tensorflow.go's
        # cluster-spec rendezvous: ps hosts + worker hosts, separately)
        cm = cluster.store.get("ConfigMap", "ns1", "dist-mnist-svc")
        assert cm.data["ps.host"].splitlines() == [
            "dist-mnist-ps-0.dist-mnist",
            "dist-mnist-ps-1.dist-mnist",
        ]
        assert cm.data["worker.host"].splitlines() == [
            f"dist-mnist-worker-{i}.dist-mnist" for i in range(4)
        ]

        # VK_TASK_INDEX: per-group replica index, 0..N-1 within each group
        by_group = {}
        for p in pods:
            group = p.metadata.annotations[objects.TASK_SPEC_KEY]
            env = {e.name: e.value for c in p.spec.containers
                   for e in c.env}
            by_group.setdefault(group, []).append(int(env["VK_TASK_INDEX"]))
        assert sorted(by_group["ps"]) == [0, 1]
        assert sorted(by_group["worker"]) == [0, 1, 2, 3]

        # stable DNS identity for the TF_CONFIG addresses — per pod, so a
        # swapped-hostname indexing bug cannot hide behind set equality
        assert {p.spec.subdomain for p in pods} == {"dist-mnist"}
        for p in pods:
            assert p.spec.hostname == p.metadata.name

        # all pods (ps + workers) completing completes the job
        finish_pods(cluster)
        cluster.settle(3)
        assert job_state(cluster, "dist-mnist") == JobPhase.COMPLETED


class TestLifecyclePolicies:
    def test_pod_failure_restarts_and_reschedules(self):
        cluster = make_cluster()
        job = make_job(
            min_available=2, tasks=(("worker", 2),),
            policies=[objects.LifecyclePolicy(
                event=JobEvent.POD_FAILED, action=JobAction.RESTART_JOB)])
        job.spec.scheduler_name = "volcano"
        cluster.store.create(job)
        cluster.settle(4)
        assert job_state(cluster) == JobPhase.RUNNING

        # kill one pod -> RestartJob -> pods recreated and rescheduled
        victim = cluster.store.list("Pod", namespace="ns1")[0]
        updated = copy.deepcopy(victim)
        updated.status.phase = objects.POD_PHASE_FAILED
        updated.status.container_statuses = [
            objects.ContainerStatus(name="c", exit_code=1)]
        cluster.store.update_status(updated)

        cluster.settle(6)
        stored = cluster.store.get("Job", "ns1", "job1")
        assert stored.status.retry_count >= 1
        assert stored.status.state.phase == JobPhase.RUNNING
        pods = cluster.store.list("Pod", namespace="ns1")
        assert len(pods) == 2
        assert all(p.status.phase == objects.POD_PHASE_RUNNING for p in pods)

    def test_ttl_garbage_collection(self):
        cluster = make_cluster()
        job = make_job(min_available=1, tasks=(("w", 1),), ttl=0)
        job.spec.scheduler_name = "volcano"
        cluster.store.create(job)
        cluster.settle(4)
        finish_pods(cluster)
        cluster.settle(4)
        # ttl=0: collected as soon as it finishes
        assert cluster.store.try_get("Job", "ns1", "job1") is None


class TestAdmission:
    def test_invalid_jobs_rejected(self):
        cluster = make_cluster()
        bad = make_job(min_available=0)
        with pytest.raises(AdmissionError, match="minAvailable"):
            cluster.store.create(bad)

        bad = make_job(min_available=5, tasks=(("w", 2),))
        with pytest.raises(AdmissionError, match="total replicas"):
            cluster.store.create(bad)

        bad = make_job(tasks=(("w", 2), ("w", 1)), min_available=1)
        with pytest.raises(AdmissionError, match="duplicated task name"):
            cluster.store.create(bad)

        bad = make_job(min_available=1, tasks=(("UPPER", 1),))
        with pytest.raises(AdmissionError, match="RFC 1123"):
            cluster.store.create(bad)

        bad = make_job(min_available=1, tasks=(("w", 1),),
                       policies=[objects.LifecyclePolicy(
                           event=JobEvent.POD_FAILED, exit_code=3,
                           action=JobAction.ABORT_JOB)])
        with pytest.raises(AdmissionError, match="simultaneously"):
            cluster.store.create(bad)

        bad = make_job(min_available=1, tasks=(("w", 1),))
        bad.spec.queue = "no-such-queue"
        with pytest.raises(AdmissionError, match="queue"):
            cluster.store.create(bad)

        bad = make_job(min_available=1, tasks=(("w", 1),),
                       plugins={"teleport": []})
        with pytest.raises(AdmissionError, match="job plugin"):
            cluster.store.create(bad)

    def test_mutation_defaults(self):
        cluster = make_cluster()
        job = make_job(min_available=1, tasks=(("", 1),))
        job.spec.queue = ""
        cluster.store.create(job)
        stored = cluster.store.get("Job", "ns1", "job1")
        assert stored.spec.queue == "default"
        assert stored.spec.tasks[0].name == "task0"


class TestThreadedCluster:
    def test_threaded_pipeline(self):
        cluster = make_cluster(schedule_period=0.05)
        cluster.run()
        try:
            job = make_job(min_available=2, tasks=(("worker", 2),))
            job.spec.scheduler_name = "volcano"
            cluster.store.create(job)

            import time

            deadline = time.time() + 20
            while time.time() < deadline:
                pods = cluster.store.list("Pod", namespace="ns1")
                if (len(pods) == 2 and all(
                        p.status.phase == objects.POD_PHASE_RUNNING
                        for p in pods)):
                    break
                time.sleep(0.05)
            else:
                raise AssertionError("pods never started running")
        finally:
            cluster.stop()


class TestJobCascadeDeletion:
    def test_deleting_job_reaps_children(self):
        """Deleting a Job must cascade to its pods, PodGroup, and
        plugin-controlled resources — the reference gets this from
        Kubernetes OwnerReference GC (job_controller.go:418-448); here
        the job controller owns the cascade. Regression: children used
        to orphan forever, permanently occupying cluster capacity."""
        cluster = make_cluster()
        job = make_job(min_available=2, tasks=(("worker", 2),),
                       plugins={"svc": [], "ssh": []})
        job.spec.scheduler_name = "volcano"
        cluster.store.create(job)
        cluster.settle(4)
        assert len(cluster.store.list("Pod", namespace="ns1")) == 2
        assert cluster.store.try_get("PodGroup", "ns1", "job1") is not None
        assert cluster.store.try_get("ConfigMap", "ns1", "job1-svc") is not None

        cluster.store.delete("Job", "ns1", "job1")
        cluster.settle(4)
        assert cluster.store.list("Pod", namespace="ns1") == []
        assert cluster.store.try_get("PodGroup", "ns1", "job1") is None
        assert cluster.store.try_get("ConfigMap", "ns1", "job1-svc") is None
        assert cluster.store.try_get("ConfigMap", "ns1", "job1-ssh") is None

        # freed capacity is actually reusable: a new gang binds fully
        job2 = make_job(name="job2", min_available=2, tasks=(("w", 2),))
        job2.spec.scheduler_name = "volcano"
        cluster.store.create(job2)
        cluster.settle(4)
        pods = cluster.store.list("Pod", namespace="ns1")
        assert len(pods) == 2 and all(p.spec.node_name for p in pods)
