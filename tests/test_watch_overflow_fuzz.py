"""Watch-journal overflow under churn — the phantom-object fuzz.

A consumer that lags past the journal's ring cap must converge back to
ground truth through the reset/re-list protocol: no phantom objects (a
delete that fell off the ring must still be observed via DELETED
synthesis), no lost adds, no stale versions. Fuzzed on BOTH paths:

1. the local path — sim/mirror.JournalMirror polling store/gateway.py's
   _WatchJournal directly (deterministic, virtual-time style);
2. the remote path — RemoteStore.watch long-polling a REAL ApiGateway
   over HTTP with a deliberately tiny journal_cap, the PR-2
   relist/DELETED-synthesis machinery.

Plus the poll-protocol regression for the future-cursor case: a cursor
beyond the journal's head (a client that outlived a gateway restart)
must get the 410-style reset, not a silent wait that skips the gap.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from volcano_tpu.api import objects
from volcano_tpu.scheduler.util.test_utils import build_pod
from volcano_tpu.sim.mirror import JournalMirror
from volcano_tpu.store.gateway import ApiGateway, _WatchJournal
from volcano_tpu.store.remote import RemoteStore
from volcano_tpu.store.store import Store, WatchHandler, object_key


def _make_pod(i: int) -> objects.Pod:
    pod = build_pod("fuzz", f"pod-{i:05d}", "", objects.POD_PHASE_PENDING,
                    {"cpu": "100m", "memory": "64Mi"}, "")
    pod.metadata.ensure_identity()
    return pod


def _churn(store: Store, rng: random.Random, live: dict, i: int) -> int:
    """One random store mutation; returns the next pod index."""
    roll = rng.random()
    if not live or roll < 0.45:
        pod = _make_pod(i)
        store.create(pod)
        live[object_key(pod)] = pod
        return i + 1
    key = rng.choice(sorted(live))
    if roll < 0.75:
        import copy

        pod = copy.deepcopy(live[key])
        pod.metadata.annotations["fuzz"] = str(i)
        live[key] = store.update(pod)
    else:
        ns, name = key.split("/", 1)
        store.delete("Pod", ns, name)
        del live[key]
    return i + 1


class TestJournalPollProtocol:
    def test_future_cursor_signals_reset(self):
        store = Store()
        journal = _WatchJournal(store, "Pod", cap=8)
        store.create(_make_pod(0))
        events, nxt, reset = journal.poll(0, 0.0)
        assert not reset and len(events) == 1
        # a cursor beyond the head (stale client after a journal rebuild)
        events, nxt2, reset = journal.poll(nxt + 100, 0.0)
        assert reset and events == []
        assert nxt2 == nxt  # resume point is the real head

    def test_fallen_off_ring_signals_reset(self):
        store = Store()
        journal = _WatchJournal(store, "Pod", cap=4)
        idx = 0
        for idx in range(10):
            store.create(_make_pod(idx))
        events, nxt, reset = journal.poll(0, 0.0)
        assert reset, "cursor 0 predates the 4-event ring"
        # resuming from the returned head is consistent
        events, _, reset = journal.poll(nxt, 0.0)
        assert not reset and events == []


class TestJournalSquash:
    """MODIFIED-squash backpressure: while no poll has served a key's
    latest MODIFIED, a newer MODIFIED coalesces into it in place — a
    status-churn storm against a slow watcher costs one ring entry per
    pod, not one per update, so bounded journals stop forcing spurious
    410 resets. Served entries are immutable; resets freeze the whole
    ring prefix (a squash into the reset gap would lose a final state)."""

    def test_modified_storm_coalesces_instead_of_overflowing(self):
        store = Store()
        mirror = JournalMirror(store, "Pod", cap=32)
        live: dict = {}
        for i in range(8):
            pod = _make_pod(i)
            store.create(pod)
            live[object_key(pod)] = pod
        # the watcher never drains while 20 no-op update rounds hammer
        # every pod: 160 MODIFIEDs squash to at most one live entry per
        # pod, so the 32-slot ring never rolls past the cursor
        import copy

        for round_no in range(20):
            for key in sorted(live):
                pod = copy.deepcopy(live[key])
                pod.metadata.annotations["storm"] = str(round_no)
                live[key] = store.update(pod)
        mirror.catch_up()
        assert mirror.resets == 0, \
            "squash failed: the storm rolled the ring and forced a reset"
        assert mirror.journal.squashed >= 100, mirror.journal.squashed
        diff = mirror.diff_vs_store()
        assert diff == {"phantom": [], "missing": [], "stale": []}, diff

    def test_served_entries_are_immutable(self):
        """A MODIFIED the consumer already received must not be rewritten:
        the follow-up update appends instead, and both states arrive in
        order."""
        import copy

        store = Store()
        journal = _WatchJournal(store, "Pod", cap=32)
        pod = _make_pod(0)
        store.create(pod)
        pod = copy.deepcopy(pod)
        pod.metadata.annotations["v"] = "1"
        pod = store.update(pod)
        from volcano_tpu.api import codec

        events, nxt, reset = journal.poll(0, 0.0)
        assert not reset and len(events) == 2  # ADDED + MODIFIED, served
        v1 = codec.from_envelope(
            events[1]["object"]).metadata.resource_version
        pod = copy.deepcopy(pod)
        pod.metadata.annotations["v"] = "2"
        pod = store.update(pod)
        # the served MODIFIED kept v1; the new state came as a NEW entry
        events2, _, reset = journal.poll(nxt, 0.0)
        assert not reset and len(events2) == 1
        assert codec.from_envelope(
            events[1]["object"]).metadata.resource_version == v1
        assert codec.from_envelope(
            events2[0]["object"]).metadata.resource_version \
            == pod.metadata.resource_version
        assert journal.squashed == 0

    def test_reset_freezes_ring_against_late_squash(self):
        """Regression: after a reset tells a client to re-list and resume
        from ``end``, a later MODIFIED must NOT squash into a ring entry
        below ``end`` — the client would never see that final state (it
        happened after the re-list read the store)."""
        import copy

        store = Store()
        mirror = JournalMirror(store, "Pod", cap=8)
        live: dict = {}
        for i in range(4):
            pod = _make_pod(i)
            store.create(pod)
            live[object_key(pod)] = pod
        mirror.catch_up()
        # roll the ring past the cursor, with a MODIFIED for pod-0 still
        # IN the ring when the reset fires
        for i in range(4, 12):
            pod = _make_pod(i)
            store.create(pod)
            live[object_key(pod)] = pod
        key0 = sorted(live)[0]
        pod = copy.deepcopy(live[key0])
        pod.metadata.annotations["gen"] = "in-ring"
        live[key0] = store.update(pod)
        _, reset_taken = mirror.poll_once()
        assert reset_taken, "cursor should have fallen off the ring"
        # the state that changes AFTER the re-list: without the freeze it
        # squashes into the in-ring entry behind the client's new cursor
        pod = copy.deepcopy(live[key0])
        pod.metadata.annotations["gen"] = "after-relist"
        live[key0] = store.update(pod)
        mirror.catch_up()
        diff = mirror.diff_vs_store()
        assert diff["stale"] == [], \
            f"post-reset squash swallowed a final state: {diff}"

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_update_heavy_fuzz_converges_with_squashes(self, seed):
        """Update-biased churn against a frequently-skipping consumer:
        squashing must actually engage AND the protocol still converges
        exactly (squash can reorder nothing, lose nothing)."""
        import copy

        rng = random.Random(seed)
        store = Store()
        mirror = JournalMirror(store, "Pod", cap=16)
        live: dict = {}
        idx = 0
        for _ in range(50):
            for _ in range(rng.randrange(1, 30)):
                roll = rng.random()
                if not live or roll < 0.15:
                    pod = _make_pod(idx)
                    store.create(pod)
                    live[object_key(pod)] = pod
                    idx += 1
                elif roll < 0.9:
                    key = rng.choice(sorted(live))
                    pod = copy.deepcopy(live[key])
                    pod.metadata.annotations["fuzz"] = str(idx)
                    live[key] = store.update(pod)
                    idx += 1
                else:
                    key = rng.choice(sorted(live))
                    ns, name = key.split("/", 1)
                    store.delete("Pod", ns, name)
                    del live[key]
            mirror.drain(rng=rng, skip_prob=0.6, error_prob=0.2)
        assert mirror.journal.squashed > 0, "fuzz never exercised squash"
        mirror.catch_up()
        diff = mirror.diff_vs_store()
        assert diff == {"phantom": [], "missing": [], "stale": []}, diff


class TestLocalMirrorFuzz:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_lagging_consumer_converges(self, seed):
        rng = random.Random(seed)
        store = Store()
        mirror = JournalMirror(store, "Pod", cap=16)
        live: dict = {}
        idx = 0
        for _ in range(40):
            # a burst larger than the ring, then a maybe-skipped drain:
            # the consumer repeatedly falls off the ring and must re-list
            for _ in range(rng.randrange(1, 40)):
                idx = _churn(store, rng, live, idx)
            mirror.drain(rng=rng, skip_prob=0.5, error_prob=0.3)
        assert mirror.resets > 0, "fuzz never overflowed the ring"
        # faults stop; the protocol must converge to ground truth
        mirror.catch_up()
        diff = mirror.diff_vs_store()
        assert diff == {"phantom": [], "missing": [], "stale": []}, diff
        assert sorted(mirror.known) == sorted(object_key(p)
                                              for p in store.list("Pod"))

    def test_delete_burst_past_ring_synthesizes_deletes(self):
        store = Store()
        mirror = JournalMirror(store, "Pod", cap=8)
        live: dict = {}
        for i in range(20):
            pod = _make_pod(i)
            store.create(pod)
            live[object_key(pod)] = pod
        mirror.catch_up()
        assert len(mirror.known) == 20
        # delete EVERYTHING while the consumer sleeps — far past the ring
        for key in sorted(live):
            ns, name = key.split("/", 1)
            store.delete("Pod", ns, name)
        mirror.catch_up()
        assert mirror.known == {}, "phantom objects survived the reset"
        assert mirror.synthesized_deletes == 20


class TestRemoteWatchFuzz:
    def test_remote_consumer_lags_past_tiny_ring(self):
        store = Store()
        gateway = ApiGateway(store, journal_cap=16).start()
        try:
            remote = RemoteStore(f"127.0.0.1:{gateway.port}")
            known: dict = {}
            lock = threading.Lock()

            def on_added(obj):
                with lock:
                    known[object_key(obj)] = obj.metadata.resource_version

            def on_updated(old, new):
                with lock:
                    known[object_key(new)] = new.metadata.resource_version

            def on_deleted(obj):
                with lock:
                    known.pop(object_key(obj), None)

            remote.watch("Pod", WatchHandler(
                added=on_added, updated=on_updated, deleted=on_deleted),
                poll_timeout=0.2)

            rng = random.Random(99)
            live: dict = {}
            idx = 0
            for _ in range(6):
                # bursts far past the 16-event ring while the poller's
                # long-poll sleeps between rounds
                for _ in range(60):
                    idx = _churn(store, rng, live, idx)
                time.sleep(0.05)

            truth = {object_key(p): p.metadata.resource_version
                     for p in store.list("Pod")}
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                with lock:
                    snapshot = dict(known)
                if snapshot == truth:
                    break
                time.sleep(0.1)
            assert snapshot == truth, (
                f"remote mirror did not converge: "
                f"{len(set(snapshot) - set(truth))} phantom, "
                f"{len(set(truth) - set(snapshot))} missing")
            remote.stop_watches()
        finally:
            gateway.stop()
