"""Watch-journal overflow under churn — the phantom-object fuzz.

A consumer that lags past the journal's ring cap must converge back to
ground truth through the reset/re-list protocol: no phantom objects (a
delete that fell off the ring must still be observed via DELETED
synthesis), no lost adds, no stale versions. Fuzzed on BOTH paths:

1. the local path — sim/mirror.JournalMirror polling store/gateway.py's
   _WatchJournal directly (deterministic, virtual-time style);
2. the remote path — RemoteStore.watch long-polling a REAL ApiGateway
   over HTTP with a deliberately tiny journal_cap, the PR-2
   relist/DELETED-synthesis machinery.

Plus the poll-protocol regression for the future-cursor case: a cursor
beyond the journal's head (a client that outlived a gateway restart)
must get the 410-style reset, not a silent wait that skips the gap.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from volcano_tpu.api import objects
from volcano_tpu.scheduler.util.test_utils import build_pod
from volcano_tpu.sim.mirror import JournalMirror
from volcano_tpu.store.gateway import ApiGateway, _WatchJournal
from volcano_tpu.store.remote import RemoteStore
from volcano_tpu.store.store import Store, WatchHandler, object_key


def _make_pod(i: int) -> objects.Pod:
    pod = build_pod("fuzz", f"pod-{i:05d}", "", objects.POD_PHASE_PENDING,
                    {"cpu": "100m", "memory": "64Mi"}, "")
    pod.metadata.ensure_identity()
    return pod


def _churn(store: Store, rng: random.Random, live: dict, i: int) -> int:
    """One random store mutation; returns the next pod index."""
    roll = rng.random()
    if not live or roll < 0.45:
        pod = _make_pod(i)
        store.create(pod)
        live[object_key(pod)] = pod
        return i + 1
    key = rng.choice(sorted(live))
    if roll < 0.75:
        import copy

        pod = copy.deepcopy(live[key])
        pod.metadata.annotations["fuzz"] = str(i)
        live[key] = store.update(pod)
    else:
        ns, name = key.split("/", 1)
        store.delete("Pod", ns, name)
        del live[key]
    return i + 1


class TestJournalPollProtocol:
    def test_future_cursor_signals_reset(self):
        store = Store()
        journal = _WatchJournal(store, "Pod", cap=8)
        store.create(_make_pod(0))
        events, nxt, reset = journal.poll(0, 0.0)
        assert not reset and len(events) == 1
        # a cursor beyond the head (stale client after a journal rebuild)
        events, nxt2, reset = journal.poll(nxt + 100, 0.0)
        assert reset and events == []
        assert nxt2 == nxt  # resume point is the real head

    def test_fallen_off_ring_signals_reset(self):
        store = Store()
        journal = _WatchJournal(store, "Pod", cap=4)
        idx = 0
        for idx in range(10):
            store.create(_make_pod(idx))
        events, nxt, reset = journal.poll(0, 0.0)
        assert reset, "cursor 0 predates the 4-event ring"
        # resuming from the returned head is consistent
        events, _, reset = journal.poll(nxt, 0.0)
        assert not reset and events == []


class TestLocalMirrorFuzz:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_lagging_consumer_converges(self, seed):
        rng = random.Random(seed)
        store = Store()
        mirror = JournalMirror(store, "Pod", cap=16)
        live: dict = {}
        idx = 0
        for _ in range(40):
            # a burst larger than the ring, then a maybe-skipped drain:
            # the consumer repeatedly falls off the ring and must re-list
            for _ in range(rng.randrange(1, 40)):
                idx = _churn(store, rng, live, idx)
            mirror.drain(rng=rng, skip_prob=0.5, error_prob=0.3)
        assert mirror.resets > 0, "fuzz never overflowed the ring"
        # faults stop; the protocol must converge to ground truth
        mirror.catch_up()
        diff = mirror.diff_vs_store()
        assert diff == {"phantom": [], "missing": [], "stale": []}, diff
        assert sorted(mirror.known) == sorted(object_key(p)
                                              for p in store.list("Pod"))

    def test_delete_burst_past_ring_synthesizes_deletes(self):
        store = Store()
        mirror = JournalMirror(store, "Pod", cap=8)
        live: dict = {}
        for i in range(20):
            pod = _make_pod(i)
            store.create(pod)
            live[object_key(pod)] = pod
        mirror.catch_up()
        assert len(mirror.known) == 20
        # delete EVERYTHING while the consumer sleeps — far past the ring
        for key in sorted(live):
            ns, name = key.split("/", 1)
            store.delete("Pod", ns, name)
        mirror.catch_up()
        assert mirror.known == {}, "phantom objects survived the reset"
        assert mirror.synthesized_deletes == 20


class TestRemoteWatchFuzz:
    def test_remote_consumer_lags_past_tiny_ring(self):
        store = Store()
        gateway = ApiGateway(store, journal_cap=16).start()
        try:
            remote = RemoteStore(f"127.0.0.1:{gateway.port}")
            known: dict = {}
            lock = threading.Lock()

            def on_added(obj):
                with lock:
                    known[object_key(obj)] = obj.metadata.resource_version

            def on_updated(old, new):
                with lock:
                    known[object_key(new)] = new.metadata.resource_version

            def on_deleted(obj):
                with lock:
                    known.pop(object_key(obj), None)

            remote.watch("Pod", WatchHandler(
                added=on_added, updated=on_updated, deleted=on_deleted),
                poll_timeout=0.2)

            rng = random.Random(99)
            live: dict = {}
            idx = 0
            for _ in range(6):
                # bursts far past the 16-event ring while the poller's
                # long-poll sleeps between rounds
                for _ in range(60):
                    idx = _churn(store, rng, live, idx)
                time.sleep(0.05)

            truth = {object_key(p): p.metadata.resource_version
                     for p in store.list("Pod")}
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                with lock:
                    snapshot = dict(known)
                if snapshot == truth:
                    break
                time.sleep(0.1)
            assert snapshot == truth, (
                f"remote mirror did not converge: "
                f"{len(set(snapshot) - set(truth))} phantom, "
                f"{len(set(truth) - set(snapshot))} missing")
            remote.stop_watches()
        finally:
            gateway.stop()
