"""Watch-journal overflow under churn — the phantom-object fuzz.

A consumer that lags past the journal's ring cap must converge back to
ground truth through the reset/re-list protocol: no phantom objects (a
delete that fell off the ring must still be observed via DELETED
synthesis), no lost adds, no stale versions. Fuzzed on BOTH paths:

1. the local path — sim/mirror.JournalMirror polling store/gateway.py's
   _WatchJournal directly (deterministic, virtual-time style);
2. the remote path — RemoteStore.watch long-polling a REAL ApiGateway
   over HTTP with a deliberately tiny journal_cap, the PR-2
   relist/DELETED-synthesis machinery.

Plus the poll-protocol regression for the future-cursor case: a cursor
beyond the journal's head (a client that outlived a gateway restart)
must get the 410-style reset, not a silent wait that skips the gap.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from volcano_tpu.api import objects
from volcano_tpu.scheduler.util.test_utils import build_pod
from volcano_tpu.sim.mirror import JournalMirror
from volcano_tpu.store.gateway import ApiGateway, _WatchJournal
from volcano_tpu.store.remote import RemoteStore
from volcano_tpu.store.store import Store, WatchHandler, object_key


def _make_pod(i: int) -> objects.Pod:
    pod = build_pod("fuzz", f"pod-{i:05d}", "", objects.POD_PHASE_PENDING,
                    {"cpu": "100m", "memory": "64Mi"}, "")
    pod.metadata.ensure_identity()
    return pod


def _churn(store: Store, rng: random.Random, live: dict, i: int) -> int:
    """One random store mutation; returns the next pod index."""
    roll = rng.random()
    if not live or roll < 0.45:
        pod = _make_pod(i)
        store.create(pod)
        live[object_key(pod)] = pod
        return i + 1
    key = rng.choice(sorted(live))
    if roll < 0.75:
        import copy

        pod = copy.deepcopy(live[key])
        pod.metadata.annotations["fuzz"] = str(i)
        live[key] = store.update(pod)
    else:
        ns, name = key.split("/", 1)
        store.delete("Pod", ns, name)
        del live[key]
    return i + 1


class TestJournalPollProtocol:
    def test_future_cursor_signals_reset(self):
        store = Store()
        journal = _WatchJournal(store, "Pod", cap=8)
        store.create(_make_pod(0))
        events, nxt, reset = journal.poll(0, 0.0)
        assert not reset and len(events) == 1
        # a cursor beyond the head (stale client after a journal rebuild)
        events, nxt2, reset = journal.poll(nxt + 100, 0.0)
        assert reset and events == []
        assert nxt2 == nxt  # resume point is the real head

    def test_fallen_off_ring_signals_reset(self):
        store = Store()
        journal = _WatchJournal(store, "Pod", cap=4)
        idx = 0
        for idx in range(10):
            store.create(_make_pod(idx))
        events, nxt, reset = journal.poll(0, 0.0)
        assert reset, "cursor 0 predates the 4-event ring"
        # resuming from the returned head is consistent
        events, _, reset = journal.poll(nxt, 0.0)
        assert not reset and events == []


class TestJournalSquash:
    """MODIFIED-squash backpressure: while no poll has served a key's
    latest MODIFIED, a newer MODIFIED coalesces into it in place — a
    status-churn storm against a slow watcher costs one ring entry per
    pod, not one per update, so bounded journals stop forcing spurious
    410 resets. Served entries are immutable; resets freeze the whole
    ring prefix (a squash into the reset gap would lose a final state)."""

    def test_modified_storm_coalesces_instead_of_overflowing(self):
        store = Store()
        mirror = JournalMirror(store, "Pod", cap=32)
        live: dict = {}
        for i in range(8):
            pod = _make_pod(i)
            store.create(pod)
            live[object_key(pod)] = pod
        # the watcher never drains while 20 no-op update rounds hammer
        # every pod: 160 MODIFIEDs squash to at most one live entry per
        # pod, so the 32-slot ring never rolls past the cursor
        import copy

        for round_no in range(20):
            for key in sorted(live):
                pod = copy.deepcopy(live[key])
                pod.metadata.annotations["storm"] = str(round_no)
                live[key] = store.update(pod)
        mirror.catch_up()
        assert mirror.resets == 0, \
            "squash failed: the storm rolled the ring and forced a reset"
        assert mirror.journal.squashed >= 100, mirror.journal.squashed
        diff = mirror.diff_vs_store()
        assert diff == {"phantom": [], "missing": [], "stale": []}, diff

    def test_served_entries_are_immutable(self):
        """A MODIFIED the consumer already received must not be rewritten:
        the follow-up update appends instead, and both states arrive in
        order."""
        import copy

        store = Store()
        journal = _WatchJournal(store, "Pod", cap=32)
        pod = _make_pod(0)
        store.create(pod)
        pod = copy.deepcopy(pod)
        pod.metadata.annotations["v"] = "1"
        pod = store.update(pod)
        from volcano_tpu.api import codec

        events, nxt, reset = journal.poll(0, 0.0)
        assert not reset and len(events) == 2  # ADDED + MODIFIED, served
        v1 = codec.from_envelope(
            events[1]["object"]).metadata.resource_version
        pod = copy.deepcopy(pod)
        pod.metadata.annotations["v"] = "2"
        pod = store.update(pod)
        # the served MODIFIED kept v1; the new state came as a NEW entry
        events2, _, reset = journal.poll(nxt, 0.0)
        assert not reset and len(events2) == 1
        assert codec.from_envelope(
            events[1]["object"]).metadata.resource_version == v1
        assert codec.from_envelope(
            events2[0]["object"]).metadata.resource_version \
            == pod.metadata.resource_version
        assert journal.squashed == 0

    def test_reset_freezes_ring_against_late_squash(self):
        """Regression: after a reset tells a client to re-list and resume
        from ``end``, a later MODIFIED must NOT squash into a ring entry
        below ``end`` — the client would never see that final state (it
        happened after the re-list read the store)."""
        import copy

        store = Store()
        mirror = JournalMirror(store, "Pod", cap=8)
        live: dict = {}
        for i in range(4):
            pod = _make_pod(i)
            store.create(pod)
            live[object_key(pod)] = pod
        mirror.catch_up()
        # roll the ring past the cursor, with a MODIFIED for pod-0 still
        # IN the ring when the reset fires
        for i in range(4, 12):
            pod = _make_pod(i)
            store.create(pod)
            live[object_key(pod)] = pod
        key0 = sorted(live)[0]
        pod = copy.deepcopy(live[key0])
        pod.metadata.annotations["gen"] = "in-ring"
        live[key0] = store.update(pod)
        _, reset_taken = mirror.poll_once()
        assert reset_taken, "cursor should have fallen off the ring"
        # the state that changes AFTER the re-list: without the freeze it
        # squashes into the in-ring entry behind the client's new cursor
        pod = copy.deepcopy(live[key0])
        pod.metadata.annotations["gen"] = "after-relist"
        live[key0] = store.update(pod)
        mirror.catch_up()
        diff = mirror.diff_vs_store()
        assert diff["stale"] == [], \
            f"post-reset squash swallowed a final state: {diff}"

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_update_heavy_fuzz_converges_with_squashes(self, seed):
        """Update-biased churn against a frequently-skipping consumer:
        squashing must actually engage AND the protocol still converges
        exactly (squash can reorder nothing, lose nothing)."""
        import copy

        rng = random.Random(seed)
        store = Store()
        mirror = JournalMirror(store, "Pod", cap=16)
        live: dict = {}
        idx = 0
        for _ in range(50):
            for _ in range(rng.randrange(1, 30)):
                roll = rng.random()
                if not live or roll < 0.15:
                    pod = _make_pod(idx)
                    store.create(pod)
                    live[object_key(pod)] = pod
                    idx += 1
                elif roll < 0.9:
                    key = rng.choice(sorted(live))
                    pod = copy.deepcopy(live[key])
                    pod.metadata.annotations["fuzz"] = str(idx)
                    live[key] = store.update(pod)
                    idx += 1
                else:
                    key = rng.choice(sorted(live))
                    ns, name = key.split("/", 1)
                    store.delete("Pod", ns, name)
                    del live[key]
            mirror.drain(rng=rng, skip_prob=0.6, error_prob=0.2)
        assert mirror.journal.squashed > 0, "fuzz never exercised squash"
        mirror.catch_up()
        diff = mirror.diff_vs_store()
        assert diff == {"phantom": [], "missing": [], "stale": []}, diff


class TestLocalMirrorFuzz:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_lagging_consumer_converges(self, seed):
        rng = random.Random(seed)
        store = Store()
        mirror = JournalMirror(store, "Pod", cap=16)
        live: dict = {}
        idx = 0
        for _ in range(40):
            # a burst larger than the ring, then a maybe-skipped drain:
            # the consumer repeatedly falls off the ring and must re-list
            for _ in range(rng.randrange(1, 40)):
                idx = _churn(store, rng, live, idx)
            mirror.drain(rng=rng, skip_prob=0.5, error_prob=0.3)
        assert mirror.resets > 0, "fuzz never overflowed the ring"
        # faults stop; the protocol must converge to ground truth
        mirror.catch_up()
        diff = mirror.diff_vs_store()
        assert diff == {"phantom": [], "missing": [], "stale": []}, diff
        assert sorted(mirror.known) == sorted(object_key(p)
                                              for p in store.list("Pod"))

    def test_delete_burst_past_ring_synthesizes_deletes(self):
        store = Store()
        mirror = JournalMirror(store, "Pod", cap=8)
        live: dict = {}
        for i in range(20):
            pod = _make_pod(i)
            store.create(pod)
            live[object_key(pod)] = pod
        mirror.catch_up()
        assert len(mirror.known) == 20
        # delete EVERYTHING while the consumer sleeps — far past the ring
        for key in sorted(live):
            ns, name = key.split("/", 1)
            store.delete("Pod", ns, name)
        mirror.catch_up()
        assert mirror.known == {}, "phantom objects survived the reset"
        assert mirror.synthesized_deletes == 20


def _replay(events, state: dict) -> None:
    """Apply a delivered batch to a level-triggered mirror state."""
    for entry in events:
        etype = entry.get("type")
        if etype in ("ADDED", "MODIFIED"):
            from volcano_tpu.api import codec

            obj = codec.from_envelope(entry["object"])
            state[object_key(obj)] = obj.metadata.resource_version
        elif etype == "DELETED":
            from volcano_tpu.api import codec

            obj = codec.from_envelope(entry["old"])
            state.pop(object_key(obj), None)


class TestEventCompactor:
    """compact_events — the general delivery-side coalescer: a compacted
    batch must drive any level-triggered consumer to the IDENTICAL final
    state as the raw batch, for a strictly smaller decode bill."""

    @pytest.mark.parametrize("seed", [21, 22, 23, 24])
    def test_compacted_replay_matches_raw_replay(self, seed):
        from volcano_tpu.store.flowcontrol import compact_events

        rng = random.Random(seed)
        store = Store()
        journal = _WatchJournal(store, "Pod", cap=100000)
        live: dict = {}
        idx = 0
        for _ in range(400):
            idx = _churn(store, rng, live, idx)
        events, _, reset = journal.poll(0, 0.0)
        assert not reset
        compacted, coalesced = compact_events(events)
        assert coalesced > 0, "fuzz never exercised compaction"
        assert len(compacted) == len(events) - coalesced
        raw_state: dict = {}
        compact_state: dict = {}
        _replay(events, raw_state)
        _replay(compacted, compact_state)
        assert compact_state == raw_state
        # and the final state is the store's truth
        truth = {object_key(p): p.metadata.resource_version
                 for p in store.list("Pod")}
        assert compact_state == truth

    def test_delete_recreate_never_merges(self):
        from volcano_tpu.store.flowcontrol import compact_events

        store = Store()
        journal = _WatchJournal(store, "Pod", cap=100000)
        pod = _make_pod(0)
        store.create(pod)
        store.delete("Pod", "fuzz", pod.metadata.name)
        pod2 = _make_pod(0)
        store.create(pod2)
        events, _, _ = journal.poll(0, 0.0)
        compacted, coalesced = compact_events(events)
        # ADDED+DELETED annihilate; the re-create survives as its own
        # ADDED (never merged across the delete boundary — the objects
        # carry different identities)
        kinds = [e["type"] for e in compacted]
        assert kinds == ["ADDED"], kinds
        from volcano_tpu.api import codec

        assert codec.from_envelope(
            compacted[0]["object"]).metadata.uid == pod2.metadata.uid


class TestFanoutDemotion:
    """Slow-watcher demotion -> snapshot-resync on the gateway-local
    path: the laggard is evicted with a resumable cursor (never buffered
    for), resyncs through the reset/re-list protocol, and converges —
    while the shared journal's occupancy stays bounded by
    min(demote_lag, hard_cap) with the stalled watcher unable to pin it
    past the cap after demotion."""

    def _fanout_mirrors(self, store, cap=16, demote_lag=24, n=3):
        from volcano_tpu.sim.mirror import JournalMirror
        from volcano_tpu.store.flowcontrol import WatchFanout

        journal = _WatchJournal(store, "Pod", cap=cap)
        fanout = WatchFanout(journal, demote_lag=demote_lag,
                             pin_factor=4)
        mirrors = [JournalMirror(store, "Pod", journal=journal,
                                 fanout=fanout,
                                 watcher_id=f"w{i}",
                                 watcher_class="batch")
                   for i in range(n)]
        return journal, fanout, mirrors

    @pytest.mark.parametrize("seed", [31, 32, 33])
    def test_slow_watcher_demotes_then_converges(self, seed):
        rng = random.Random(seed)
        store = Store()
        journal, fanout, mirrors = self._fanout_mirrors(store)
        fast, slow = mirrors[0], mirrors[1]
        live: dict = {}
        idx = 0
        for _ in range(4):
            idx = _churn(store, rng, live, idx)
        for m in mirrors:
            m.drain()  # register every cursor before the storm
        for _ in range(30):
            for _ in range(rng.randrange(4, 20)):
                idx = _churn(store, rng, live, idx)
            fast.drain()
            # the slow watcher drains rarely: it must fall past
            # demote_lag and be demoted instead of pinning the ring
            if rng.random() < 0.1:
                slow.drain()
        assert fanout.counters["demotions"] >= 1, fanout.counters
        # demotion freed the ring: occupancy bounded by the cap once the
        # laggard is demoted (one more append settles the trim)
        idx = _churn(store, rng, live, idx)
        assert len(journal.events) <= max(
            journal.cap, fanout.demote_lag), journal.stats()
        # both converge — the demoted one via reset/re-list resync
        for m in mirrors:
            m.catch_up()
            assert m.diff_vs_store() == {
                "phantom": [], "missing": [], "stale": []}
        assert slow.resets >= 1
        assert fanout.counters["promotions"] >= 1

    def test_stalled_watcher_cannot_pin_past_cap(self):
        """The journal-accounting fix: a live laggard may hold retention
        open (bounded), but once it lags past demote_lag it is demoted
        AT APPEND TIME — even if it never polls again — and the ring
        falls back to its soft cap."""
        store = Store()
        journal, fanout, mirrors = self._fanout_mirrors(
            store, cap=16, demote_lag=24)
        stalled = mirrors[0]
        for i in range(8):
            store.create(_make_pod(i))
        stalled.drain()  # registers the cursor, then stalls forever
        peak = 0
        for i in range(8, 80):
            store.create(_make_pod(i))
            peak = max(peak, len(journal.events))
        # while live, retention stretched past the soft cap...
        assert peak > journal.cap
        # ...but never past min(demote_lag, hard_cap)
        assert peak <= min(fanout.demote_lag, fanout.hard_cap), peak
        # and after the append-time demotion the ring is back at cap
        assert len(journal.events) <= journal.cap
        assert fanout.demotions_by_reason.get("append_lag", 0) >= 1
        # the stalled watcher still converges when it finally wakes
        stalled.catch_up()
        assert stalled.diff_vs_store() == {
            "phantom": [], "missing": [], "stale": []}

    def test_shared_batch_is_one_object(self):
        """The fan-out fast path: watchers at the same cursor receive
        the SAME immutable batch — O(events + watchers), not
        O(events x watchers) copies."""
        store = Store()
        journal, fanout, _ = self._fanout_mirrors(store, cap=64)
        for i in range(10):
            store.create(_make_pod(i))
        a, _, _ = fanout.poll_for("wa", 0, 0.0)
        b, _, _ = fanout.poll_for("wb", 0, 0.0)
        assert a is b, "same-cursor watchers must share one batch"

    def test_aggressive_coalesce_rung_compacts_small_batches(self):
        """watch_coalesce_aggressive: with the ladder's hold armed, even
        tiny batches are compacted (threshold drops to 2)."""
        from volcano_tpu.scheduler.degrade import DegradeLadder
        from volcano_tpu.sim.mirror import JournalMirror
        from volcano_tpu.store.flowcontrol import WatchFanout

        ladder = DegradeLadder()
        store = Store()
        journal = _WatchJournal(store, "Pod", cap=64)
        fanout = WatchFanout(journal, demote_lag=128, coalesce_min=64,
                             ladder=ladder)
        pod = _make_pod(0)
        store.create(pod)
        import copy

        # a pacer watcher serves the head after every update, so the
        # MODIFIED chain cannot write-side squash — the catch-up batch
        # genuinely holds one entry per update
        pacer = JournalMirror(store, "Pod", journal=journal,
                              fanout=fanout, watcher_id="pacer")
        pacer.catch_up()
        since = pacer.since
        for i in range(3):
            upd = copy.deepcopy(store.get("Pod", "fuzz",
                                          pod.metadata.name))
            upd.metadata.annotations["i"] = str(i)
            store.update(upd)
            pacer.catch_up()
        baseline = fanout.counters["coalesced"]
        events, _, _ = fanout.poll_for("cold", since, 0.0)
        assert len(events) == 3, [e["type"] for e in events]
        assert fanout.counters["coalesced"] == baseline, \
            "small batch must NOT compact while healthy"
        ladder.note_watch_lag(100, 128)  # arm the rung
        events, _, _ = fanout.poll_for("cold2", since, 0.0)
        assert len(events) == 1, [e["type"] for e in events]
        assert fanout.counters["coalesced"] > baseline, \
            "armed rung must compact even small batches"


class TestRemoteWatchFuzz:
    def test_remote_consumer_lags_past_tiny_ring(self):
        store = Store()
        gateway = ApiGateway(store, journal_cap=16).start()
        try:
            remote = RemoteStore(f"127.0.0.1:{gateway.port}")
            known: dict = {}
            lock = threading.Lock()

            def on_added(obj):
                with lock:
                    known[object_key(obj)] = obj.metadata.resource_version

            def on_updated(old, new):
                with lock:
                    known[object_key(new)] = new.metadata.resource_version

            def on_deleted(obj):
                with lock:
                    known.pop(object_key(obj), None)

            remote.watch("Pod", WatchHandler(
                added=on_added, updated=on_updated, deleted=on_deleted),
                poll_timeout=0.2)

            rng = random.Random(99)
            live: dict = {}
            idx = 0
            for _ in range(6):
                # bursts far past the 16-event ring while the poller's
                # long-poll sleeps between rounds
                for _ in range(60):
                    idx = _churn(store, rng, live, idx)
                time.sleep(0.05)

            truth = {object_key(p): p.metadata.resource_version
                     for p in store.list("Pod")}
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                with lock:
                    snapshot = dict(known)
                if snapshot == truth:
                    break
                time.sleep(0.1)
            assert snapshot == truth, (
                f"remote mirror did not converge: "
                f"{len(set(snapshot) - set(truth))} phantom, "
                f"{len(set(truth) - set(snapshot))} missing")
            remote.stop_watches()
        finally:
            gateway.stop()

    def test_remote_watcher_demoted_to_resync_converges(self):
        """The RemoteStore half of the demotion contract: a flow-
        controlled remote watcher (watcher_id on the wire) that lags
        past demote_lag is demoted server-side; the client sees the
        standard reset, re-lists, and converges — no phantoms, no lost
        deletes — while the gateway's watch_stats records the demotion."""
        store = Store()
        gateway = ApiGateway(store, journal_cap=16,
                             watch_demote_lag=24).start()
        try:
            remote = RemoteStore(f"127.0.0.1:{gateway.port}")
            known: dict = {}
            lock = threading.Lock()

            def on_added(obj):
                with lock:
                    known[object_key(obj)] = obj.metadata.resource_version

            def on_updated(old, new):
                with lock:
                    known[object_key(new)] = new.metadata.resource_version

            def on_deleted(obj):
                with lock:
                    known.pop(object_key(obj), None)

            remote.watch("Pod", WatchHandler(
                added=on_added, updated=on_updated, deleted=on_deleted),
                poll_timeout=0.2, watcher_id="remote-consumer",
                watcher_class="batch")

            rng = random.Random(7)
            live: dict = {}
            idx = 0
            for _ in range(5):
                # bursts far past cap AND demote_lag between long-poll
                # rounds: the server must demote rather than stream an
                # unbounded catch-up
                for _ in range(80):
                    idx = _churn(store, rng, live, idx)
                time.sleep(0.05)

            truth = {object_key(p): p.metadata.resource_version
                     for p in store.list("Pod")}
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                with lock:
                    snapshot = dict(known)
                if snapshot == truth:
                    break
                time.sleep(0.1)
            assert snapshot == truth, (
                f"demoted remote watcher did not converge: "
                f"{len(set(snapshot) - set(truth))} phantom, "
                f"{len(set(truth) - set(snapshot))} missing")
            stats = gateway.watch_stats()["Pod"]
            assert stats["counters"]["registered"] >= 1, stats
            assert remote.watch_stats()["resets"] >= 1
            remote.stop_watches()
        finally:
            gateway.stop()
