"""vcctl CLI tests (mirrors pkg/cli/job/*_test.go output expectations)."""

from __future__ import annotations

from volcano_tpu.api import objects
from volcano_tpu.api.objects import JobPhase
from volcano_tpu.cli import job as job_cli
from volcano_tpu.cli import queue as queue_cli
from volcano_tpu.cli.vcctl import DEMO_JOB_YAML
from volcano_tpu.cluster import Cluster
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_resource_list_with_pods,
)


def make_cluster(nodes=3) -> Cluster:
    cluster = Cluster()
    for n in range(nodes):
        cluster.store.create(build_node(
            f"node-{n}", build_resource_list_with_pods("8", "16Gi")))
    return cluster


class TestJobCli:
    def test_run_from_yaml(self):
        cluster = make_cluster()
        job = job_cli.run_job(cluster.store, DEMO_JOB_YAML)
        assert job.metadata.name == "test-job"
        assert job.spec.min_available == 3
        assert [t.name for t in job.spec.tasks] == ["mpimaster", "mpiworker"]
        assert "ssh" in job.spec.plugins

        cluster.settle(4)
        pods = cluster.store.list("Pod", namespace="default")
        assert len(pods) == 3
        assert all(p.status.phase == objects.POD_PHASE_RUNNING for p in pods)

    def test_list_and_view(self):
        cluster = make_cluster()
        job_cli.run_job(cluster.store, DEMO_JOB_YAML)
        cluster.settle(4)

        table = job_cli.list_jobs(cluster.store, namespace="default")
        lines = table.strip().splitlines()
        assert lines[0].startswith("Name")
        assert "test-job" in lines[1]
        assert "Running" in lines[1]

        view = job_cli.view_job(cluster.store, "default", "test-job")
        assert "Name:       \ttest-job" in view
        assert "mpiworker\treplicas: 2" in view

    def test_suspend_resume_cycle(self):
        cluster = make_cluster()
        job_cli.run_job(cluster.store, DEMO_JOB_YAML)
        cluster.settle(4)

        job_cli.suspend_job(cluster.store, "default", "test-job")
        cluster.settle(4)
        stored = cluster.store.get("Job", "default", "test-job")
        assert stored.status.state.phase == JobPhase.ABORTED
        assert cluster.store.list("Pod", namespace="default") == []

        job_cli.resume_job(cluster.store, "default", "test-job")
        cluster.settle(6)
        stored = cluster.store.get("Job", "default", "test-job")
        assert stored.status.state.phase in (JobPhase.PENDING, JobPhase.RUNNING)
        assert len(cluster.store.list("Pod", namespace="default")) == 3

    def test_delete(self):
        cluster = make_cluster()
        job_cli.run_job(cluster.store, DEMO_JOB_YAML)
        cluster.settle(2)
        job_cli.delete_job(cluster.store, "default", "test-job")
        assert cluster.store.try_get("Job", "default", "test-job") is None


class TestQueueCli:
    def test_create_get_list(self):
        cluster = make_cluster()
        queue_cli.create_queue(cluster.store, "gold", weight=5)
        out = queue_cli.get_queue(cluster.store, "gold")
        assert "gold" in out and "5" in out

        table = queue_cli.list_queues(cluster.store)
        lines = table.strip().splitlines()
        assert lines[0].startswith("Name")
        assert any("default" in line for line in lines)
        assert any("gold" in line for line in lines)

    def test_queue_status_columns(self):
        cluster = make_cluster()
        job_cli.run_job(cluster.store, DEMO_JOB_YAML)
        cluster.settle(4)
        out = queue_cli.get_queue(cluster.store, "default")
        # one running podgroup aggregated into the queue status
        row = out.strip().splitlines()[1].split()
        assert row[0] == "default"
        assert "1" in row  # running count


def test_vcctl_version_subcommand(capsys):
    """vcctl version (reference cmd/cli/vcctl.go versionCommand): the
    Version/GitSHA/Built banner, exit 0."""
    from volcano_tpu.cli.vcctl import main

    assert main(["version"]) == 0
    out = capsys.readouterr().out
    assert "Version:" in out and "Git SHA:" in out and "Built At:" in out
