"""Binding parity: TPU batch solve vs the serial oracle loop.

The BASELINE.json gate: the batched device solve must produce *identical
binding decisions* to the serial allocate action. Each case builds two
identical synthetic clusters, runs the serial loop on one and the
tpuscore-gated batch solve on the other, and compares the FakeBinder maps
byte-for-byte. Runs on the 8-device virtual CPU mesh in float64 (conftest),
so host and device arithmetic agree exactly.
"""

from __future__ import annotations

import random

import numpy as np

from tests.helpers import make_cache, make_tiers
from volcano_tpu.api import objects
from volcano_tpu.scheduler.framework import close_session, get_action, open_session
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list_with_pods,
)

DEFAULT_TIERS = (["priority", "gang"], ["drf", "predicates", "proportion", "nodeorder"])


PARITY_ARGS = {"tpuscore": {"tpuscore.mode": "parity"}}


def run_backend(populate, tiers, tpu: bool):
    cache = make_cache()
    populate(cache)
    tier_spec = list(tiers)
    if tpu:
        tier_spec = [["tpuscore"], *tier_spec]
    # parity mode is opt-in: auto hands small sessions to the serial loop
    # (which would make these comparisons vacuous)
    ssn = open_session(cache, make_tiers(*tier_spec, arguments=PARITY_ARGS))
    get_action("allocate").execute(ssn)
    if tpu:
        assert getattr(ssn, "batch_allocator", None) is not None
        prof = ssn.plugins["tpuscore"].profile
        assert "fallback" not in prof, f"unexpected serial fallback: {prof}"
    close_session(ssn)
    return cache.binder.binds


def assert_parity(populate, tiers=DEFAULT_TIERS):
    serial = run_backend(populate, tiers, tpu=False)
    batched = run_backend(populate, tiers, tpu=True)
    assert batched == serial, (
        f"binding divergence: serial={len(serial)} batched={len(batched)} "
        f"only_serial={dict(sorted(set(serial.items()) - set(batched.items()))[:5])} "
        f"only_batched={dict(sorted(set(batched.items()) - set(serial.items()))[:5])}"
    )
    return serial


def gang_cluster(n_groups=12, min_member=4, n_nodes=8, seed=0):
    def populate(c):
        rng = random.Random(seed)  # fresh stream per cluster build
        c.add_queue(build_queue("default"))
        for g in range(n_groups):
            pg = f"pg{g}"
            c.add_pod_group(build_pod_group(pg, namespace="ns1", min_member=min_member))
            for i in range(min_member):
                c.add_pod(build_pod(
                    "ns1", f"{pg}-p{i}", "", objects.POD_PHASE_PENDING,
                    {"cpu": f"{rng.choice([500, 1000, 2000])}m", "memory": "1Gi"},
                    pg))
        for n in range(n_nodes):
            c.add_node(build_node(
                f"node-{n:03d}", build_resource_list_with_pods("8", "16Gi")))

    return populate


class TestTpuParity:
    def test_gang_blocks_default_conf(self):
        binds = assert_parity(gang_cluster())
        assert len(binds) > 0

    def test_gang_partial_capacity(self):
        # capacity for only some gangs; later gangs must discard whole blocks
        binds = assert_parity(gang_cluster(n_groups=20, min_member=4, n_nodes=4))
        assert len(binds) % 4 == 0  # whole gangs only

    def test_gang_no_capacity(self):
        def populate(c):
            c.add_queue(build_queue("default"))
            c.add_pod_group(build_pod_group("pg1", namespace="ns1", min_member=5))
            for i in range(5):
                c.add_pod(build_pod("ns1", f"p{i}", "", objects.POD_PHASE_PENDING,
                                    {"cpu": "3", "memory": "1Gi"}, "pg1"))
            c.add_node(build_node("n1", build_resource_list_with_pods("4", "8Gi")))
        assert assert_parity(populate) == {}

    def test_heterogeneous_binpack(self):
        def populate(c):
            rng = random.Random(7)
            c.add_queue(build_queue("default"))
            for g in range(15):
                pg = f"pg{g}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1", min_member=1))
                for i in range(rng.randint(1, 4)):
                    req = {
                        "cpu": f"{rng.choice([250, 500, 1500])}m",
                        "memory": rng.choice(["512Mi", "1Gi", "2Gi"]),
                    }
                    if rng.random() < 0.3:
                        req["nvidia.com/gpu"] = "1"
                    c.add_pod(build_pod("ns1", f"{pg}-p{i}", "",
                                        objects.POD_PHASE_PENDING, req, pg))
            for n in range(10):
                rl = build_resource_list_with_pods("4", "8Gi")
                if n % 2 == 0:
                    rl["nvidia.com/gpu"] = "4"
                c.add_node(build_node(f"node-{n:03d}", rl))

        assert_parity(
            populate,
            tiers=(["priority", "gang"], ["predicates", "binpack"]),
        )

    def test_node_selectors(self):
        def populate(c):
            c.add_queue(build_queue("default"))
            for g, zone in enumerate(["a", "b", "a", "b", "a"]):
                pg = f"pg{g}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1", min_member=2))
                for i in range(2):
                    c.add_pod(build_pod("ns1", f"{pg}-p{i}", "",
                                        objects.POD_PHASE_PENDING,
                                        {"cpu": "1", "memory": "1Gi"}, pg,
                                        node_selector={"zone": zone}))
            for n in range(6):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("4", "8Gi"),
                    labels={"zone": "a" if n < 3 else "b"}))

        serial = assert_parity(populate)
        assert len(serial) == 10

    def test_multi_queue_fair_share(self):
        def populate(c):
            rng = random.Random(3)
            c.add_queue(build_queue("q-gold", weight=3))
            c.add_queue(build_queue("q-silver", weight=2))
            c.add_queue(build_queue("q-bronze", weight=1))
            for g in range(18):
                q = ["q-gold", "q-silver", "q-bronze"][g % 3]
                pg = f"pg{g}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1",
                                                min_member=2, queue=q))
                for i in range(3):
                    c.add_pod(build_pod("ns1", f"{pg}-p{i}", "",
                                        objects.POD_PHASE_PENDING,
                                        {"cpu": f"{rng.choice([500, 1000])}m",
                                         "memory": "1Gi"}, pg))
            for n in range(6):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("6", "12Gi")))

        assert_parity(populate)

    def test_priorities_order(self):
        # job priority flows from the PodGroup's PriorityClassName
        # (reference cache.go:741-748), not from pod priority
        def populate(c):
            c.add_queue(build_queue("default"))
            for g in range(6):
                pc = objects.PriorityClass(
                    metadata=objects.ObjectMeta(name=f"prio-{g}"), value=g)
                pc.metadata.ensure_identity()
                c.add_priority_class(pc)
            for g in range(6):
                pg = f"pg{g}"
                pgobj = build_pod_group(pg, namespace="ns1", min_member=2)
                pgobj.spec.priority_class_name = f"prio-{g}"
                c.add_pod_group(pgobj)
                for i in range(2):
                    c.add_pod(build_pod("ns1", f"{pg}-p{i}", "",
                                        objects.POD_PHASE_PENDING,
                                        {"cpu": "2", "memory": "2Gi"}, pg))
            # capacity for 3 gangs only -> highest priorities win
            c.add_node(build_node("n1", build_resource_list_with_pods("12", "24Gi")))

        binds = assert_parity(populate)
        bound_groups = {k.split("/")[1].rsplit("-", 1)[0] for k in binds}
        assert bound_groups == {"pg5", "pg4", "pg3"}

    def test_node_sampling_window(self):
        # >100 nodes triggers the adaptive sampling + round-robin window
        # (scheduler_helper.go:42-118); the kernel must reproduce it exactly
        def populate(c):
            rng = random.Random(11)
            c.add_queue(build_queue("default"))
            for g in range(25):
                pg = f"pg{g}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1", min_member=2))
                for i in range(2):
                    c.add_pod(build_pod("ns1", f"{pg}-p{i}", "",
                                        objects.POD_PHASE_PENDING,
                                        {"cpu": f"{rng.choice([1000, 2000])}m",
                                         "memory": "1Gi"}, pg))
            for n in range(120):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("2", "4Gi")))

        assert_parity(populate)

    def test_multi_namespace(self):
        def populate(c):
            c.add_queue(build_queue("default"))
            for ns in ("ns-a", "ns-b"):
                for g in range(4):
                    pg = f"{ns}-pg{g}"
                    c.add_pod_group(build_pod_group(pg, namespace=ns, min_member=2))
                    for i in range(2):
                        c.add_pod(build_pod(ns, f"{pg}-p{i}", "",
                                            objects.POD_PHASE_PENDING,
                                            {"cpu": "1", "memory": "1Gi"}, pg))
            for n in range(4):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("4", "8Gi")))

        assert_parity(populate)

    def test_reordered_tiers_drf_before_priority(self):
        # job-order dispatch is first-nonzero ACROSS TIERS, so putting drf in
        # tier 1 must beat priority in tier 2 on both backends
        def populate(c):
            c.add_queue(build_queue("default"))
            pc = objects.PriorityClass(metadata=objects.ObjectMeta(name="hi"), value=100)
            pc.metadata.ensure_identity()
            c.add_priority_class(pc)
            # job A: high priority, already-running share; job B: zero share
            pg_a = build_pod_group("pg-a", namespace="ns1", min_member=1)
            pg_a.spec.priority_class_name = "hi"
            c.add_pod_group(pg_a)
            c.add_pod(build_pod("ns1", "a-run", "n1", objects.POD_PHASE_RUNNING,
                                {"cpu": "2", "memory": "2Gi"}, "pg-a"))
            c.add_pod(build_pod("ns1", "a-p0", "", objects.POD_PHASE_PENDING,
                                {"cpu": "1", "memory": "1Gi"}, "pg-a"))
            c.add_pod_group(build_pod_group("pg-b", namespace="ns1", min_member=1))
            c.add_pod(build_pod("ns1", "b-p0", "", objects.POD_PHASE_PENDING,
                                {"cpu": "1", "memory": "1Gi"}, "pg-b"))
            c.add_node(build_node("n1", build_resource_list_with_pods("4", "8Gi")))

        binds = assert_parity(
            populate, tiers=(["drf"], ["priority", "gang"], ["proportion"]))
        assert "ns1/b-p0" in binds  # zero-share job goes first under DRF

    def test_mesh_sharded_non_divisible_nodes(self):
        # 5 nodes on an 8-device mesh: the node axis pads to 8 and the
        # sampling window must still match the serial helper over 5 nodes
        import jax
        from jax.sharding import Mesh

        def populate(c):
            c.add_queue(build_queue("default"))
            for g in range(6):
                pg = f"pg{g}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1", min_member=2))
                for i in range(2):
                    c.add_pod(build_pod("ns1", f"{pg}-p{i}", "",
                                        objects.POD_PHASE_PENDING,
                                        {"cpu": "1", "memory": "1Gi"}, pg))
            for n in range(5):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("3", "6Gi")))

        serial = run_backend(populate, DEFAULT_TIERS, tpu=False)

        cache = make_cache()
        populate(cache)
        ssn = open_session(
            cache, make_tiers(["tpuscore"], *DEFAULT_TIERS, arguments=PARITY_ARGS))
        mesh = Mesh(np.array(jax.devices()[:8]), ("nodes",))
        ssn.plugins["tpuscore"].mesh = mesh
        ssn.batch_allocator.mesh = mesh
        get_action("allocate").execute(ssn)
        prof = ssn.plugins["tpuscore"].profile
        assert "fallback" not in prof, prof
        close_session(ssn)
        assert cache.binder.binds == serial

    def test_fallback_on_pod_affinity(self):
        """Sessions with constructs the kernel doesn't model must fall back
        to the serial loop, not silently mis-schedule."""
        def populate(c):
            c.add_queue(build_queue("default"))
            c.add_pod_group(build_pod_group("pg1", namespace="ns1", min_member=1))
            pod = build_pod("ns1", "p1", "", objects.POD_PHASE_PENDING,
                            {"cpu": "1", "memory": "1Gi"}, "pg1",
                            labels={"app": "x"})
            pod.spec.affinity = objects.Affinity(
                pod_anti_affinity=objects.PodAntiAffinity(required_terms=[
                    objects.PodAffinityTerm(
                        label_selector=objects.LabelSelector(match_labels={"app": "x"}),
                        topology_key="kubernetes.io/hostname",
                    )
                ])
            )
            c.add_pod(pod)
            c.add_node(build_node("n1", build_resource_list_with_pods("4", "8Gi")))

        # parity mode keeps the session-wide fallback (bit-exactness);
        # rounds mode handles the same construct as serial residue instead
        # (tests/test_rounds.py TestRoundsResidue)
        cache = make_cache()
        populate(cache)
        ssn = open_session(
            cache, make_tiers(["tpuscore"], *DEFAULT_TIERS, arguments=PARITY_ARGS))
        get_action("allocate").execute(ssn)
        prof = ssn.plugins["tpuscore"].profile
        assert "fallback" in prof
        close_session(ssn)
        assert cache.binder.binds == {"ns1/p1": "n1"}
