"""JobInfo/TaskInfo tests (mirrors pkg/scheduler/api/job_info_test.go)."""

from volcano_tpu.api import objects
from volcano_tpu.api.job_info import JobInfo, get_job_id, new_task_info
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.scheduler.util.test_utils import build_pod, build_resource_list


def make_task(name, phase=objects.POD_PHASE_PENDING, node="", cpu="1000m", group="pg1"):
    pod = build_pod("ns1", name, node, phase, build_resource_list(cpu, "1Gi"), group)
    return new_task_info(pod)


class TestTaskInfo:
    def test_status_mapping(self):
        assert make_task("a").status == TaskStatus.PENDING
        assert make_task("b", node="n1").status == TaskStatus.BOUND
        assert (
            make_task("c", phase=objects.POD_PHASE_RUNNING, node="n1").status
            == TaskStatus.RUNNING
        )
        assert make_task("d", phase=objects.POD_PHASE_SUCCEEDED).status == TaskStatus.SUCCEEDED
        assert make_task("e", phase=objects.POD_PHASE_FAILED).status == TaskStatus.FAILED

    def test_releasing_on_deletion(self):
        pod = build_pod("ns1", "x", "n1", objects.POD_PHASE_RUNNING,
                        build_resource_list("1", "1Gi"), "pg1")
        pod.metadata.deletion_timestamp = 123.0
        assert new_task_info(pod).status == TaskStatus.RELEASING

    def test_job_id(self):
        pod = build_pod("ns1", "x", "", objects.POD_PHASE_PENDING,
                        build_resource_list("1", "1Gi"), "pg1")
        assert get_job_id(pod) == "ns1/pg1"
        pod2 = build_pod("ns1", "y", "", objects.POD_PHASE_PENDING,
                         build_resource_list("1", "1Gi"))
        assert get_job_id(pod2) == ""

    def test_init_resreq_max(self):
        pod = build_pod("ns1", "x", "", objects.POD_PHASE_PENDING,
                        build_resource_list("2", "1Gi"), "pg1")
        pod.spec.init_containers = [
            objects.Container(name="init", requests=build_resource_list("4", "512Mi"))
        ]
        ti = new_task_info(pod)
        assert ti.resreq.milli_cpu == 2000
        assert ti.init_resreq.milli_cpu == 4000
        assert ti.init_resreq.memory == 2**30  # main containers' sum wins


class TestJobInfo:
    def test_add_task(self):
        job = JobInfo("ns1/pg1", make_task("t1"), make_task("t2", node="n1"))
        assert len(job.tasks) == 2
        assert job.total_request.milli_cpu == 2000
        # bound task counts as allocated
        assert job.allocated.milli_cpu == 1000
        assert len(job.task_status_index[TaskStatus.PENDING]) == 1
        assert len(job.task_status_index[TaskStatus.BOUND]) == 1

    def test_delete_task(self):
        t1, t2 = make_task("t1"), make_task("t2", node="n1")
        job = JobInfo("ns1/pg1", t1, t2)
        job.delete_task_info(t2)
        assert job.allocated.milli_cpu == 0
        assert job.total_request.milli_cpu == 1000
        assert TaskStatus.BOUND not in job.task_status_index

    def test_update_task_status(self):
        t1 = make_task("t1")
        job = JobInfo("ns1/pg1", t1)
        job.update_task_status(t1, TaskStatus.ALLOCATED)
        assert job.allocated.milli_cpu == 1000
        assert job.ready_task_num() == 1
        job.update_task_status(t1, TaskStatus.PENDING)
        assert job.allocated.milli_cpu == 0

    def test_readiness(self):
        tasks = [make_task(f"t{i}") for i in range(4)]
        job = JobInfo("ns1/pg1", *tasks)
        job.min_available = 3
        assert not job.ready()
        for t in tasks[:2]:
            job.update_task_status(t, TaskStatus.ALLOCATED)
        assert not job.ready()
        job.update_task_status(tasks[2], TaskStatus.PIPELINED)
        assert not job.ready()
        assert job.pipelined()  # 2 ready + 1 pipelined >= 3
        job.update_task_status(tasks[3], TaskStatus.ALLOCATED)
        assert job.ready()

    def test_valid_task_num(self):
        tasks = [make_task(f"t{i}") for i in range(3)]
        job = JobInfo("ns1/pg1", *tasks)
        job.update_task_status(tasks[0], TaskStatus.FAILED)
        assert job.valid_task_num() == 2

    def test_clone_independent(self):
        t1 = make_task("t1")
        job = JobInfo("ns1/pg1", t1)
        clone = job.clone()
        clone.update_task_status(clone.tasks[t1.uid], TaskStatus.ALLOCATED)
        assert job.allocated.milli_cpu == 0
        assert clone.allocated.milli_cpu == 1000
