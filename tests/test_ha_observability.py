"""HA + observability: leader election failover, served /metrics + /healthz,
driver entry point, version metadata, example corpus.

Reference seams: cmd/scheduler/app/server.go:97-160 (metrics mux, healthz,
resource-lock leader election), pkg/version/version.go, example/.
"""

from __future__ import annotations

import os
import threading
import time
import urllib.request

import pytest

from volcano_tpu.api import objects
from volcano_tpu.cluster import Cluster
from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.httpserver import ObservabilityServer
from volcano_tpu.scheduler.leaderelection import (
    LeaderElector,
    LeaderElectionRecord,
    ResourceLock,
)
from volcano_tpu.store.store import ConflictError, Store

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "example")

FAST = dict(lease_duration=0.5, renew_deadline=0.3, retry_period=0.1)


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


class TestStoreCAS:
    def test_stale_version_conflicts(self):
        store = Store()
        cm = objects.ConfigMap(metadata=objects.ObjectMeta(
            name="lock", namespace="volcano-system"))
        store.create(cm)
        v = cm.metadata.resource_version
        store.update(cm, expect_version=v)  # fresh version: ok
        with pytest.raises(ConflictError):
            store.update(cm, expect_version=v)  # now stale


class TestLeaderElection:
    def test_single_elector_acquires(self):
        store = Store()
        lock = ResourceLock(store, "volcano-system", "vc-scheduler", "a")
        started, stopped = threading.Event(), threading.Event()
        el = LeaderElector(lock, started.set, stopped.set, **FAST)
        el.start()
        assert _wait(el.is_leader)
        assert started.is_set()
        el.stop()
        assert stopped.is_set()
        assert not el.is_leader()

    def test_standby_takes_over_after_clean_release(self):
        store = Store()
        la = ResourceLock(store, "volcano-system", "vc-scheduler", "a")
        lb = ResourceLock(store, "volcano-system", "vc-scheduler", "b")
        ea = LeaderElector(la, lambda: None, lambda: None, **FAST)
        eb = LeaderElector(lb, lambda: None, lambda: None, **FAST)
        ea.start()
        assert _wait(ea.is_leader)
        eb.start()
        time.sleep(0.3)
        assert not eb.is_leader()  # lease held by a
        ea.stop()  # clean shutdown releases the lease
        t0 = time.monotonic()
        assert _wait(eb.is_leader, timeout=2.0)
        # a RELEASED lease must not cost the standby a full lease wait —
        # the empty-holder fast path takes over within ~a retry period
        # (this is what distinguishes release from crash takeover below)
        assert time.monotonic() - t0 < FAST["lease_duration"], \
            "clean release fell back to full lease expiry"
        eb.stop()

    def test_standby_takes_over_after_crash(self):
        """A leader that dies without releasing loses the lease at expiry."""
        store = Store()
        lock = ResourceLock(store, "volcano-system", "vc-scheduler", "dead")
        now = time.monotonic()
        # simulate a crashed holder: record exists, renewals stopped
        lock.create(LeaderElectionRecord(
            holder_identity="dead", lease_duration=0.5,
            acquire_time=now, renew_time=now))
        lb = ResourceLock(store, "volcano-system", "vc-scheduler", "b")
        eb = LeaderElector(lb, lambda: None, lambda: None, **FAST)
        eb.start()
        time.sleep(0.2)
        assert not eb.is_leader()  # dead leader's lease not yet expired
        assert _wait(eb.is_leader, timeout=2.0)  # expiry -> takeover
        eb.stop()

    def test_corrupt_record_recovered_via_cas_update(self):
        """Lock ConfigMap exists but its record annotation is garbage: the
        elector must claim it through the CAS update path (create would
        conflict forever and deadlock the election)."""
        store = Store()
        cm = objects.ConfigMap(metadata=objects.ObjectMeta(
            name="vc-scheduler", namespace="volcano-system",
            annotations={"control-plane.alpha.volcano/leader": "{not json"}))
        store.create(cm)
        lock = ResourceLock(store, "volcano-system", "vc-scheduler", "a")
        el = LeaderElector(lock, lambda: None, lambda: None, **FAST)
        el.start()
        assert _wait(el.is_leader, timeout=2.0)
        el.stop()

    def test_exactly_one_scheduler_binds(self):
        """VERDICT r1 missing #1: two scheduler instances over one store,
        exactly one (the leader) binds; failover moves binding authority."""
        from volcano_tpu.scheduler.cache import SchedulerCache
        from volcano_tpu.scheduler.scheduler import Scheduler
        from volcano_tpu.scheduler.util.test_utils import (
            build_node, build_pod, build_pod_group, build_queue,
            build_resource_list_with_pods)

        store = Store()
        store.create(build_queue("default"))
        store.create(build_node("n1", build_resource_list_with_pods("8", "16Gi")))

        def make_instance(identity):
            cache = SchedulerCache(store=store, scheduler_name="volcano")
            sched = Scheduler(cache, schedule_period=0.05)
            lock = ResourceLock(store, "volcano-system", "vc-scheduler", identity)
            el = LeaderElector(
                lock, on_started_leading=sched.run,
                on_stopped_leading=lambda: sched.stop(stop_cache=False),
                **FAST)
            return sched, el

        sched_a, el_a = make_instance("a")
        sched_b, el_b = make_instance("b")
        el_a.start()
        assert _wait(el_a.is_leader)
        el_b.start()

        store.create(build_pod_group("pg1", namespace="default", min_member=1))
        store.create(build_pod("default", "p1", "", objects.POD_PHASE_PENDING,
                               {"cpu": "1"}, "pg1"))
        assert _wait(lambda: (store.get("Pod", "default", "p1")
                              .spec.node_name == "n1"), timeout=3.0)
        assert el_a.is_leader() and not el_b.is_leader()

        el_a.stop()  # leader goes away; standby must take over and bind
        assert _wait(el_b.is_leader, timeout=2.0)
        store.create(build_pod_group("pg2", namespace="default", min_member=1))
        store.create(build_pod("default", "p2", "", objects.POD_PHASE_PENDING,
                               {"cpu": "1"}, "pg2"))
        assert _wait(lambda: (store.get("Pod", "default", "p2")
                              .spec.node_name == "n1"), timeout=3.0)
        el_b.stop()


class TestObservabilityEndpoints:
    def test_metrics_endpoint_serves_series(self):
        metrics.reset()
        metrics.update_e2e_duration(0.01)
        metrics.register_schedule_attempts("success")
        srv = ObservabilityServer(":0").start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5).read().decode()
            assert "volcano_e2e_scheduling_latency_milliseconds" in body
            assert "volcano_schedule_attempts_total" in body
        finally:
            srv.stop()

    def test_metrics_gauges_and_histogram_exposition(self):
        """Gauges (pending pods / queue depth / sessions run) and the full
        histogram exposition contract: per-label-set _sum/_count plus the
        mandatory le=\"+Inf\" bucket equal to _count."""
        metrics.reset()
        metrics.update_action_duration("allocate", 0.002)
        metrics.update_action_duration("allocate", 0.004)
        metrics.update_action_duration("backfill", 0.001)
        metrics.set_pending_pods(17)
        metrics.set_queue_depth("default", 3)
        metrics.set_queue_depth("batch", 9)
        metrics.set_sessions_run(42)
        srv = ObservabilityServer(":0").start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=5).read().decode()
        finally:
            srv.stop()
        lines = body.splitlines()
        # gauges, typed and labeled
        assert "# TYPE volcano_pending_pods gauge" in lines
        assert "volcano_pending_pods 17.0" in lines
        assert 'volcano_queue_depth{queue="default"} 3.0' in lines
        assert 'volcano_queue_depth{queue="batch"} 9.0' in lines
        assert "volcano_sessions_run 42.0" in lines
        # histogram per-label-set _sum/_count and the +Inf bucket
        h = "volcano_action_scheduling_latency_microseconds"
        assert f'{h}_count{{action="allocate"}} 2' in lines
        assert f'{h}_sum{{action="allocate"}} 0.006' in lines
        assert f'{h}_count{{action="backfill"}} 1' in lines
        assert f'{h}_bucket{{action="allocate",le="+Inf"}} 2' in lines
        assert f'{h}_bucket{{action="backfill",le="+Inf"}} 1' in lines
        # e2e histogram (no labels) also carries its +Inf bucket
        metrics.reset()

    def test_metrics_express_series(self):
        """Express-lane counters + latency histogram on /metrics: the
        placements/reverted/deferred totals and the latency series with
        its mandatory le=\"+Inf\" bucket."""
        metrics.reset()
        metrics.register_express_placements(5)
        metrics.register_express_reverted(2)
        metrics.register_express_deferred(3)
        metrics.observe_express_latency(0.002)
        metrics.observe_express_latency(0.004)
        srv = ObservabilityServer(":0").start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=5).read().decode()
        finally:
            srv.stop()
        lines = body.splitlines()
        assert "# TYPE volcano_express_placements_total counter" in lines
        assert "volcano_express_placements_total 5.0" in lines
        assert "volcano_express_reverted_total 2.0" in lines
        assert "volcano_express_deferred_total 3.0" in lines
        h = "volcano_express_latency_seconds"
        assert f"# TYPE {h} histogram" in lines
        assert f"{h}_count 2" in lines
        assert f'{h}_bucket{{le="+Inf"}} 2' in lines
        # sub-10 ms envelope is resolvable: both observations land at or
        # below the 0.005 bucket
        assert f'{h}_bucket{{le="0.005"}} 2' in lines
        metrics.reset()

    def test_metrics_pipeline_series(self):
        """Continuous-pipeline observability on /metrics: the sustained
        sessions/sec gauge, the per-reason speculation-discard and
        per-kind commit counters (the never-applied proof and the
        read-set scope's earning surfaced to operators), and the overlap
        histogram with its mandatory le=\"+Inf\" bucket."""
        metrics.reset()
        metrics.set_pipeline_sessions_per_sec(12.5)
        metrics.register_pipeline_spec_discard("readset:node", 3)
        metrics.register_pipeline_spec_discard("express_commit")
        metrics.register_pipeline_spec_commit("readset", 2)
        metrics.register_pipeline_spec_commit("quiet")
        metrics.observe_pipeline_overlap(0.002)
        metrics.observe_pipeline_overlap(0.05)
        srv = ObservabilityServer(":0").start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=5).read().decode()
        finally:
            srv.stop()
        lines = body.splitlines()
        assert "# TYPE volcano_pipeline_sessions_per_sec gauge" in lines
        assert "volcano_pipeline_sessions_per_sec 12.5" in lines
        c = "volcano_pipeline_spec_discards_total"
        assert f"# TYPE {c} counter" in lines
        assert f'{c}{{reason="readset:node"}} 3.0' in lines
        assert f'{c}{{reason="express_commit"}} 1.0' in lines
        # the commit side of the ledger (PR 15): per-kind applied stages
        # — "readset" is the scoped seal committing THROUGH a delta
        k = "volcano_pipeline_spec_commits_total"
        assert f"# TYPE {k} counter" in lines
        assert f'{k}{{kind="readset"}} 2.0' in lines
        assert f'{k}{{kind="quiet"}} 1.0' in lines
        h = "volcano_pipeline_overlap_seconds"
        assert f"# TYPE {h} histogram" in lines
        assert f"{h}_count 2" in lines
        assert f'{h}_bucket{{le="+Inf"}} 2' in lines
        # the bucket ladder resolves the small-overlap regime
        assert f'{h}_bucket{{le="0.0025"}} 1' in lines
        metrics.reset()

    def test_healthz(self):
        healthy = {"ok": True}
        srv = ObservabilityServer(
            "127.0.0.1:0", healthy=lambda: healthy["ok"]).start()
        try:
            r = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5)
            assert r.status == 200 and r.read() == b"ok"
            healthy["ok"] = False
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/healthz", timeout=5)
            assert ei.value.code == 500
        finally:
            srv.stop()

    def test_unknown_path_404(self):
        srv = ObservabilityServer(":0").start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/nope", timeout=5)
            assert ei.value.code == 404
        finally:
            srv.stop()


class TestDriverMain:
    def test_version_flag(self, capsys):
        from volcano_tpu.scheduler.__main__ import main

        assert main(["--version"]) == 0
        out = capsys.readouterr().out
        assert "Version:" in out and "Git SHA:" in out and "Built At:" in out

    def test_run_with_cluster_state(self):
        """`python -m volcano_tpu.scheduler --cluster-state example/cluster.yaml`
        schedules example/job.yaml pods inside --run-for."""
        from volcano_tpu.scheduler.__main__ import main, seed_cluster_state

        # smoke the real main() briefly on free ports
        rc = main(["--run-for", "0.3", "--listen-address", ":0",
                   "--healthz-address", "127.0.0.1:0",
                   "--cluster-state", os.path.join(EXAMPLES, "cluster.yaml")])
        assert rc == 0

        # end-to-end: seeded cluster runs the example job to Running
        cluster = Cluster()
        seed_cluster_state(cluster.store, os.path.join(EXAMPLES, "cluster.yaml"))
        with open(os.path.join(EXAMPLES, "job.yaml")) as f:
            from volcano_tpu.cli import job as job_cli

            job_cli.run_job(cluster.store, f.read())
        cluster.settle(6)
        pods = cluster.store.list("Pod", namespace="default")
        assert len(pods) == 6
        assert all(p.status.phase == objects.POD_PHASE_RUNNING for p in pods)

    def test_leader_elect_flag_smoke(self):
        from volcano_tpu.scheduler.__main__ import main

        rc = main(["--run-for", "0.3", "--leader-elect",
                   "--listen-address", ":0",
                   "--healthz-address", "127.0.0.1:0"])
        assert rc == 0


class TestExampleCorpus:
    def test_example_job_runs(self):
        from volcano_tpu.cli import job as job_cli
        from volcano_tpu.scheduler.util.test_utils import (
            build_node, build_resource_list_with_pods)

        cluster = Cluster()
        for n in range(3):
            cluster.store.create(build_node(
                f"node-{n}", build_resource_list_with_pods("8", "16Gi")))
        with open(os.path.join(EXAMPLES, "mpi-job.yaml")) as f:
            job = job_cli.run_job(cluster.store, f.read())
        cluster.settle(5)
        assert job.metadata.name == "mpi-job"
        pods = cluster.store.list("Pod", namespace="default")
        assert len(pods) == 3
        assert all(p.status.phase == objects.POD_PHASE_RUNNING for p in pods)

    def test_invalid_jobs_denied(self):
        from volcano_tpu.cli import job as job_cli
        from volcano_tpu.store.store import AdmissionError

        invalid_dir = os.path.join(EXAMPLES, "invalid_jobs")
        samples = sorted(os.listdir(invalid_dir))
        assert len(samples) >= 3
        for name in samples:
            cluster = Cluster()
            with open(os.path.join(invalid_dir, name)) as f:
                with pytest.raises(AdmissionError):
                    job_cli.run_job(cluster.store, f.read())


class TestVersionBanner:
    def test_version_string_fields(self):
        from volcano_tpu import version

        banner = version.version_string()
        assert "Version:" in banner
        assert "Git SHA:" in banner
        assert "Built At:" in banner
        assert version.VERSION in banner


class TestObservabilityConcurrency:
    def test_concurrent_scrapes(self):
        """ThreadingHTTPServer must serve overlapping /metrics scrapes."""
        import concurrent.futures
        import urllib.request

        metrics.reset()
        metrics.update_e2e_duration(0.01)
        srv = ObservabilityServer(":0").start()
        try:
            url = f"http://127.0.0.1:{srv.port}/metrics"

            def scrape(_):
                return urllib.request.urlopen(url, timeout=5).status

            with concurrent.futures.ThreadPoolExecutor(8) as ex:
                statuses = list(ex.map(scrape, range(16)))
            assert statuses == [200] * 16
        finally:
            srv.stop()
