"""Delta-maintained snapshot (scheduler/cache/snapkeeper.py).

Covers the incremental open/close tentpole:
- keeper mechanics: reuse of clean clones, re-clone on watch deltas and on
  session-side mutations (pipelined placements MUST revert), per-session
  scratch cleared on reuse, queue/PC changes forcing a full rebuild;
- randomized churn parity: the incremental snapshot and a wholesale
  rebuild produce identical session state and identical bindings, step
  after step, under a random stream of watch deltas interleaved with
  scheduling sessions;
- consecutive rounds sessions on ONE cache: the bulk mirror flush leaves
  the snapshot in sync, so steady-state opens reuse everything and warm
  sessions stay retrace-free (CompileWatcher.assert_no_compiles);
- the flush's per-flipped-task node accounting: a placement whose cache
  twin was deleted in the defer window contributes nothing to cache node
  idle/used (ADVICE r5, cache.py:748), native and Python paths both.
"""

from __future__ import annotations

import random

import pytest

from volcano_tpu.api import objects
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.scheduler.cache.snapkeeper import SnapshotKeeper
from volcano_tpu.scheduler.framework import close_session, get_action, open_session
from tests.helpers import (  # noqa: F401 (registers actions)
    make_cache,
    make_tiers,
)
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
    build_resource_list_with_pods,
)

ROUNDS_ARGS = {"tpuscore": {"tpuscore.mode": "rounds"}}
DEFAULT_TIERS = (["priority", "gang"],
                 ["drf", "predicates", "proportion", "nodeorder"])


def _res_tuple(r):
    return (r.milli_cpu, r.memory,
            tuple(sorted((r.scalar_resources or {}).items())))


def _digest(snap):
    """Content digest of a snapshot, independent of object identity."""
    jobs = {}
    for uid, j in snap.jobs.items():
        jobs[uid] = (
            j.queue, j.priority, j.min_available,
            _res_tuple(j.allocated), _res_tuple(j.total_request),
            _res_tuple(j.pending_sum),
            tuple(sorted((t.uid, int(t.status), t.node_name,
                          _res_tuple(t.resreq))
                         for t in j.tasks.values())),
            tuple(sorted((int(s), tuple(sorted(b)))
                         for s, b in j.task_status_index.items())),
        )
    nodes = {}
    for name, nd in snap.nodes.items():
        nodes[name] = (
            _res_tuple(nd.idle), _res_tuple(nd.used),
            _res_tuple(nd.releasing), nd.ready(),
            tuple(sorted((k, int(t.status), _res_tuple(t.resreq))
                         for k, t in nd.tasks.items())),
        )
    return jobs, nodes, tuple(sorted(snap.queues))


def _axis_digest(axis):
    if axis is None:
        return None
    import numpy as np

    return (tuple(axis.names), axis.flags.tolist(),
            {a: (axis.cpu[a].tolist(), axis.mem[a].tolist(),
                 {rn: c.tolist() for rn, c in axis.scalars[a].items()})
             for a in ("idle", "used", "alloc")},
            axis.node_cnt.tolist(), axis.max_tasks.tolist(),
            bool(np.all(axis.gens >= 0)))


def _oracle_digest(cache):
    """Wholesale rebuild of the same cache — the parity oracle."""
    snap = SnapshotKeeper().snapshot(cache)
    return _digest(snap), _axis_digest(snap.node_axis)


def _populate_small(c, groups=6, nodes=5):
    c.add_queue(build_queue("default"))
    for g in range(groups):
        pg = f"pg-{g:03d}"
        c.add_pod_group(build_pod_group(pg, namespace="ns", min_member=2))
        for i in range(4):
            c.add_pod(build_pod(
                "ns", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                build_resource_list("500m", "512Mi"), pg))
    for n in range(nodes):
        c.add_node(build_node(
            f"node-{n:03d}", build_resource_list_with_pods("8", "16Gi",
                                                           pods=64)))


class TestKeeperBasics:
    def test_second_snapshot_reuses_clean_objects(self):
        c = make_cache()
        _populate_small(c)
        s1 = c.snapshot()
        s2 = c.snapshot()
        ks = c.snap_keeper
        assert ks.stats["rebuilds"] == 1 and ks.stats["incremental"] == 1
        assert ks.stats["cloned_jobs"] == 0 and ks.stats["cloned_nodes"] == 0
        for uid in s1.jobs:
            assert s2.jobs[uid] is s1.jobs[uid]
        for name in s1.nodes:
            assert s2.nodes[name] is s1.nodes[name]
        # the dicts themselves are fresh: consumers may delete entries
        assert s2.jobs is not s1.jobs

    def test_watch_delta_reclones_only_touched(self):
        c = make_cache()
        _populate_small(c)
        s1 = c.snapshot()
        c.add_pod(build_pod("ns", "pg-000-extra", "",
                            objects.POD_PHASE_PENDING,
                            build_resource_list("250m", "256Mi"), "pg-000"))
        s2 = c.snapshot()
        assert s2.jobs["ns/pg-000"] is not s1.jobs["ns/pg-000"]
        assert len(s2.jobs["ns/pg-000"].tasks) == 5
        assert s2.jobs["ns/pg-001"] is s1.jobs["ns/pg-001"]
        assert _digest(s2) == _oracle_digest(c)[0]

    def test_session_mutation_reverts_to_cache_truth(self):
        # session-only placements (this is what a pipeline/un-dispatched
        # allocate leaves behind) must NOT survive into the next session:
        # the version gap between the handed-out clone and the keeper's
        # record forces a re-clone back to the cache's PENDING truth
        c = make_cache()
        _populate_small(c)
        s1 = c.snapshot()
        job = s1.jobs["ns/pg-002"]
        task = next(iter(job.tasks.values()))
        job.update_task_status(task, TaskStatus.ALLOCATED)
        task.node_name = "node-000"
        s1.nodes["node-000"].add_task(task)
        s2 = c.snapshot()
        j2 = s2.jobs["ns/pg-002"]
        assert j2 is not job
        assert all(t.status == TaskStatus.PENDING for t in j2.tasks.values())
        n2 = s2.nodes["node-000"]
        assert not n2.tasks and n2.used.milli_cpu == 0
        assert _digest(s2) == _oracle_digest(c)[0]

    def test_fit_errors_cleared_on_reuse(self):
        c = make_cache()
        _populate_small(c)
        s1 = c.snapshot()
        job = s1.jobs["ns/pg-003"]
        job.job_fit_errors = "0/5 nodes available"
        job.nodes_fit_errors["some-task"] = object()
        s2 = c.snapshot()
        j2 = s2.jobs["ns/pg-003"]
        assert j2 is job  # reused (fit errors don't move the version) ...
        assert j2.job_fit_errors == "" and not j2.nodes_fit_errors

    def test_queue_and_priority_class_changes_rebuild(self):
        c = make_cache()
        _populate_small(c)
        c.snapshot()
        c.add_queue(build_queue("burst"))
        c.snapshot()
        assert c.snap_keeper.stats["rebuilds"] == 2
        c.add_priority_class(objects.PriorityClass(
            metadata=objects.ObjectMeta(name="high"), value=100))
        s3 = c.snapshot()
        assert c.snap_keeper.stats["rebuilds"] == 3
        assert _digest(s3) == _oracle_digest(c)[0]

    def test_node_readiness_flip_updates_membership_and_axis(self):
        c = make_cache()
        _populate_small(c)
        s1 = c.snapshot()
        assert "node-004" in s1.nodes
        bad = build_node("node-004",
                         build_resource_list_with_pods("8", "16Gi", pods=64))
        bad.status.conditions = [
            objects.NodeCondition(type="Ready", status="False")]
        c.add_node(bad)
        s2 = c.snapshot()
        assert "node-004" not in s2.nodes
        assert list(s2.node_axis.names) == sorted(s2.nodes)
        d, ax = _oracle_digest(c)
        assert _digest(s2) == d and _axis_digest(s2.node_axis) == ax


def _encode_state(cache):
    """Open a tpuscore session and encode it; returns comparable state."""
    import numpy as np

    from volcano_tpu.ops.encoder import encode_session

    ssn = open_session(cache, make_tiers(
        ["tpuscore"], *DEFAULT_TIERS, arguments=ROUNDS_ARGS))
    try:
        enc = encode_session(ssn, allow_residue=True)
        arrays = {k: np.asarray(v).copy() for k, v in enc.arrays.items()}
        meta = (list(enc.node_names), list(enc.resource_names),
                list(enc.queue_uids), list(enc.ns_names),
                [t.uid for t in enc.task_infos],
                [j.uid for j in enc.job_infos],
                enc.residue_count, enc.has_releasing)
    finally:
        close_session(ssn)
    return arrays, meta


def _assert_encodes_equal(cache_a, cache_b, ctx=""):
    import numpy as np

    (arrs_a, meta_a) = _encode_state(cache_a)
    (arrs_b, meta_b) = _encode_state(cache_b)
    assert meta_a == meta_b, ctx
    assert set(arrs_a) == set(arrs_b), ctx
    for k in arrs_a:
        assert np.array_equal(arrs_a[k], arrs_b[k]), f"{ctx}: array {k!r}"


class TestChurnParity:
    """Randomized watch deltas + sessions: incremental vs wholesale."""

    N_STEPS = 24

    def _apply_random_delta(self, rng, caches, state):
        op = rng.choice(["add_pod", "add_pod", "del_pod", "rebind_pod",
                         "add_group", "upd_node", "add_node", "del_node"])
        if op == "add_pod" and state["groups"]:
            pg = rng.choice(state["groups"])
            name = f"{pg}-x{state['seq']}"
            cpu = f"{rng.choice([250, 500])}m"  # drawn ONCE per delta so
            for c in caches:                    # both caches stay twins
                c.add_pod(build_pod(
                    "ns", name, "", objects.POD_PHASE_PENDING,
                    build_resource_list(cpu, "256Mi"), pg))
            state["pods"].append(("ns", name, pg))
        elif op == "del_pod" and state["pods"]:
            ns, name, pg = state["pods"].pop(
                rng.randrange(len(state["pods"])))
            for c in caches:
                job = c.jobs.get(f"{ns}/{pg}")
                task = None
                if job is not None:
                    task = next((t for t in job.tasks.values()
                                 if t.name == name), None)
                if task is not None and task.pod is not None:
                    c.delete_pod(task.pod)
        elif op == "rebind_pod" and state["pods"]:
            ns, name, pg = rng.choice(state["pods"])
            node = rng.choice(state["nodes"]) if state["nodes"] else None
            if node is None:
                return
            for c in caches:
                job = c.jobs.get(f"{ns}/{pg}")
                task = None
                if job is not None:
                    task = next((t for t in job.tasks.values()
                                 if t.name == name), None)
                if task is not None and task.pod is not None:
                    old = task.pod
                    new = build_pod(ns, name, node,
                                    objects.POD_PHASE_RUNNING,
                                    build_resource_list("250m", "256Mi"), pg)
                    new.metadata.uid = old.metadata.uid
                    new.metadata.creation_timestamp = \
                        old.metadata.creation_timestamp
                    c.update_pod_from_watch(old, new)
        elif op == "add_group":
            pg = f"pg-n{state['seq']}"
            for c in caches:
                c.add_pod_group(build_pod_group(pg, namespace="ns",
                                                min_member=1))
            state["groups"].append(pg)
        elif op == "upd_node" and state["nodes"]:
            name = rng.choice(state["nodes"])
            cpu = rng.choice(["8", "12"])
            for c in caches:
                c.add_node(build_node(
                    name, build_resource_list_with_pods(cpu, "16Gi",
                                                        pods=64)))
        elif op == "add_node":
            name = f"node-n{state['seq']}"
            for c in caches:
                c.add_node(build_node(
                    name, build_resource_list_with_pods("8", "16Gi",
                                                        pods=64)))
            state["nodes"].append(name)
        elif op == "del_node" and len(state["nodes"]) > 2:
            name = state["nodes"].pop(rng.randrange(len(state["nodes"])))
            for c in caches:
                c.delete_node(build_node(
                    name, build_resource_list_with_pods("8", "16Gi",
                                                        pods=64)))
        state["seq"] += 1

    def test_incremental_matches_wholesale_under_churn(self):
        rng = random.Random(17)
        a, b = make_cache(), make_cache()
        b.snap_keeper.enabled = False  # wholesale rebuild every snapshot
        for c in (a, b):
            _populate_small(c)
        state = {"groups": [f"pg-{g:03d}" for g in range(6)],
                 "nodes": [f"node-{n:03d}" for n in range(5)],
                 "pods": [("ns", f"pg-{g:03d}-t{i}", f"pg-{g:03d}")
                          for g in range(6) for i in range(4)],
                 "seq": 0}
        tiers = (["priority", "gang"], ["drf", "proportion", "nodeorder"])
        for step in range(self.N_STEPS):
            for _ in range(rng.randrange(4)):
                self._apply_random_delta(rng, (a, b), state)
            if step % 3 == 2:
                # full session through the statement path on both caches
                for c in (a, b):
                    ssn = open_session(c, make_tiers(*tiers))
                    get_action("allocate").execute(ssn)
                    close_session(ssn)
                assert a.binder.binds == b.binder.binds, f"step {step}"
            sa, sb = a.snapshot(), b.snapshot()
            assert _digest(sa) == _digest(sb), f"step {step}"
            assert _axis_digest(sa.node_axis) == _axis_digest(sb.node_axis), \
                f"step {step}"
            if step % 6 == 5:
                # full delta-maintained ENCODE vs from-scratch rebuild+
                # encode: the device-feed arrays must be bit-identical
                _assert_encodes_equal(a, b, ctx=f"step {step}")
        _assert_encodes_equal(a, b, ctx="final")
        assert a.snap_keeper.stats["incremental"] > 0
        assert a.snap_keeper.stats["reused_jobs"] > 0


class TestConsecutiveRoundsSessions:
    def _populate(self, c):
        c.add_queue(build_queue("default"))
        for g in range(12):
            pg = f"job-{g:04d}"
            c.add_pod_group(build_pod_group(pg, namespace="bench",
                                            min_member=2))
            for i in range(4):
                c.add_pod(build_pod(
                    "bench", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                    build_resource_list("500m", "512Mi"), pg))
        for n in range(6):
            c.add_node(build_node(
                f"node-{n:03d}",
                build_resource_list_with_pods("16", "32Gi", pods=64)))

    def _session(self, cache):
        ssn = open_session(cache, make_tiers(
            ["tpuscore"], *DEFAULT_TIERS, arguments=ROUNDS_ARGS))
        get_action("allocate").execute(ssn)
        prof = dict(ssn.plugins["tpuscore"].profile)
        close_session(ssn)
        return prof

    def test_three_sessions_reuse_and_stay_warm(self):
        from volcano_tpu.utils.jaxcompile import CompileWatcher

        cache = make_cache()
        self._populate(cache)
        prof1 = self._session(cache)
        assert prof1.get("mode") == "rounds", prof1
        binds1 = dict(cache.binder.binds)
        assert binds1
        ks = cache.snap_keeper

        watcher = CompileWatcher.install()
        cloned_before = ks.stats["cloned_jobs"]
        with watcher.assert_no_compiles("steady-state incremental sessions"):
            self._session(cache)
            self._session(cache)
        # the flush synced the bulk placements, so sessions 2-3 reused the
        # whole snapshot: no job re-clones, no new binds, no lost binds
        assert ks.stats["cloned_jobs"] == cloned_before
        assert ks.stats["incremental"] >= 2
        assert dict(cache.binder.binds) == binds1
        # cache accounting stayed per-task exact through the mirror flush
        for node in cache.nodes.values():
            replay = node.clone_replay()
            assert _res_tuple(node.idle) == _res_tuple(replay.idle), node.name
            assert _res_tuple(node.used) == _res_tuple(replay.used), node.name


class TestFlushSkippedPlacements:
    """ADVICE r5 (cache.py:748): a placement whose cache twin vanished in
    the defer window must contribute NOTHING to cache node idle/used."""

    def _run(self):
        cache = make_cache()
        cache.add_queue(build_queue("default"))
        for g in range(8):
            pg = f"job-{g:03d}"
            cache.add_pod_group(build_pod_group(pg, namespace="ns",
                                                min_member=1))
            for i in range(4):
                cache.add_pod(build_pod(
                    "ns", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                    build_resource_list("500m", "512Mi"), pg))
        for n in range(4):
            cache.add_node(build_node(
                f"node-{n:03d}",
                build_resource_list_with_pods("16", "32Gi", pods=64)))
        ssn = open_session(cache, make_tiers(
            ["tpuscore"], *DEFAULT_TIERS, arguments=ROUNDS_ARGS))
        get_action("allocate").execute(ssn)
        assert cache._pending_mirrors, "bulk apply should defer its mirror"
        p = cache._pending_mirrors[0]
        # delete one placed task's cache twin inside the defer window,
        # bypassing the watch path (which would flush first): this is the
        # race the flush must tolerate per-task
        k = 0
        ti = int(p["placed"][k])
        task = p["task_infos"][ti]
        host = p["node_names"][int(p["assign"][ti])]
        cache_job = cache.jobs[task.job]
        cache_job.delete_task_info(cache_job.tasks[task.uid])
        close_session(ssn)  # flush runs here
        return cache, task, host

    def _check(self, cache, task, host):
        node = cache.nodes[host]
        assert task.key not in node.tasks
        replay = node.clone_replay()
        assert _res_tuple(node.idle) == _res_tuple(replay.idle)
        assert _res_tuple(node.used) == _res_tuple(replay.used)
        # job accounting is per-flipped too: allocated excludes the
        # deleted task (its sums were settled by delete_task_info)
        job = cache.jobs[task.job]
        jreplay = job.clone_replay()
        assert _res_tuple(job.allocated) == _res_tuple(jreplay.allocated)
        assert _res_tuple(job.pending_sum) == _res_tuple(jreplay.pending_sum)
        # and the keeper re-dirties the affected job/node so the next
        # snapshot re-clones them from cache truth
        assert task.job in cache.snap_keeper.dirty_jobs
        assert host in cache.snap_keeper.dirty_nodes

    def test_native_flush_skips_deleted_task(self):
        from volcano_tpu import _native

        if _native.get_fastapply() is None:
            pytest.skip("native fastapply unavailable")
        self._check(*self._run())

    def test_python_flush_skips_deleted_task(self, monkeypatch):
        from volcano_tpu import _native

        monkeypatch.setenv("VOLCANO_TPU_NO_NATIVE", "1")
        _native._reset()
        try:
            self._check(*self._run())
        finally:
            monkeypatch.delenv("VOLCANO_TPU_NO_NATIVE", raising=False)
            _native._reset()
