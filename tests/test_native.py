"""Native fast-apply (volcano_tpu/_native): build, fallback, and exact
equivalence with the Python oracle loop in ops/solver.py::_apply_bulk."""

from __future__ import annotations

import os

import pytest

from volcano_tpu.api.types import TaskStatus
from volcano_tpu.bench.clusters import build_config
import volcano_tpu.scheduler.actions  # noqa: F401
from volcano_tpu.scheduler.framework import close_session, get_action, open_session


def _run_cfg5(no_native: bool):
    if no_native:
        os.environ["VOLCANO_TPU_NO_NATIVE"] = "1"
    else:
        os.environ.pop("VOLCANO_TPU_NO_NATIVE", None)
    # reset the once-per-process memo so the env var takes effect
    import volcano_tpu._native as native

    native._reset()
    if not no_native:
        # block on the build so the native path is genuinely exercised
        # (the solver's nowait call would otherwise fall back this session)
        if native.get_fastapply() is None:
            pytest.skip("native module unavailable; fallback covered elsewhere")
    try:
        cache, _, tiers, actions, _ = build_config(5, 0.02)
        ssn = open_session(cache, tiers)
        ssn.batch_allocator.mode = "rounds"
        for name in actions:
            get_action(name).execute(ssn)
        binds = dict(cache.binder.binds)
        # full cache/session state fingerprints
        node_state = {
            name: (round(n.idle.milli_cpu, 6), round(n.used.milli_cpu, 6),
                   len(n.tasks))
            for name, n in cache.nodes.items()
        }
        statuses = {
            t.uid: (t.status, t.node_name)
            for job in cache.jobs.values() for t in job.tasks.values()
        }
        ssn_statuses = {
            t.uid: (t.status, t.node_name)
            for job in ssn.jobs.values() for t in job.tasks.values()
        }
        close_session(ssn)
        return binds, node_state, statuses, ssn_statuses
    finally:
        os.environ.pop("VOLCANO_TPU_NO_NATIVE", None)
        native._reset()


class TestNativeFastApply:
    def test_builds_and_loads(self):
        import shutil
        import sysconfig

        import volcano_tpu._native as native

        cc = (sysconfig.get_config_var("CC") or "cc").split()[0]
        if shutil.which(cc) is None:
            pytest.skip(f"no C toolchain ({cc}); Python fallback covers this")
        native._reset()
        mod = native.get_fastapply()
        assert mod is not None, "toolchain present; native module must build"
        assert hasattr(mod, "apply_job_tasks")

    def test_native_equals_python_oracle(self):
        """Same bindings, node accounting, and task statuses (session +
        cache trees) from the native loop and the Python loop."""
        py = _run_cfg5(no_native=True)
        nat = _run_cfg5(no_native=False)
        assert py[0] == nat[0], "bindings diverge"
        assert py[1] == nat[1], "node accounting diverges"
        assert py[2] == nat[2], "cache task statuses diverge"
        assert py[3] == nat[3], "session task statuses diverge"
        assert len(py[0]) > 0

    def test_env_gate_disables_native(self, monkeypatch):
        import volcano_tpu._native as native

        monkeypatch.setenv("VOLCANO_TPU_NO_NATIVE", "1")
        native._reset()
        assert native.get_fastapply() is None
        assert native.get_fasttrans() is None
        native._reset()
