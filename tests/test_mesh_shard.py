"""Mesh-sharded session end-to-end: sharded == unsharded, bit-for-bit.

ROADMAP item 3: the node-axis mesh shard runs through the WHOLE session —
sharded encoder staging (per-shard device buffers, ops/shard.py), sharded
evict victim walks (per-shard [N/d, V] folds), and the fused session chain
with donated carries. The contract these tests pin: under the 8-device
host mesh (conftest) the sharded session produces bit-identical bindings,
evictions (in effector order), shares, fit errors and metrics to the
single-device path — which is itself parity-pinned against the serial
oracle by tests/test_evict_kernel.py and tests/test_tpu_parity.py, so the
chain serial == unsharded == sharded closes transitively.

Node counts here are deliberately NOT multiples of 8: the mesh pad
(append-only slots with sig_mask=False / vic_valid=False / node_real=False)
and the round-robin window's real-axis wrap (ops/evict._window) are part
of the contract under test. Runs under ``-m mesh`` (tier-1 at this reduced
scale; the wide fuzz band is ``-m slow``).
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from tests.helpers import close_session, make_cache, make_tiers, open_session
from tests.test_evict_kernel import (
    ACTIONS,
    TIER_SETS,
    _overcommit_cluster,
    _session_signature,
)
from volcano_tpu.scheduler.framework import get_action
from volcano_tpu.utils.jaxcompile import CompileWatcher

pytestmark = pytest.mark.mesh

ROUNDS_ARGS = {"tpuscore": {"tpuscore.mode": "rounds"}}


def _mesh(devices: int):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:devices]), ("nodes",))


@pytest.fixture(autouse=True)
def _no_default_mesh_leak():
    from volcano_tpu.scheduler.plugins import tpuscore

    yield
    tpuscore.set_default_mesh(None)


def _run(cache, tiers_spec, mesh, monkeypatch, fuse: bool,
         sessions: int = 1, actions=ACTIONS):
    import volcano_tpu.ops.victimview as vv
    from volcano_tpu.scheduler.plugins import tpuscore

    monkeypatch.setenv("VOLCANO_TPU_EVICT", "1")
    monkeypatch.setenv("VOLCANO_TPU_FUSE", "1" if fuse else "0")
    monkeypatch.setattr(vv.VictimSelector, "MIN_BATCH", 1)
    tpuscore.set_default_mesh(mesh)
    try:
        sig = None
        profs = []
        for _ in range(sessions):
            ssn = open_session(
                cache, make_tiers(["tpuscore"], *tiers_spec,
                                  arguments=ROUNDS_ARGS))
            try:
                if fuse:
                    from volcano_tpu.scheduler.framework import run_actions

                    run_actions(ssn, list(actions))
                else:
                    for name in actions:
                        get_action(name).execute(ssn)
                sig = _session_signature(ssn)
                profs.append(dict(ssn.plugins["tpuscore"].profile))
            finally:
                close_session(ssn)
    finally:
        tpuscore.set_default_mesh(None)
    return sig, dict(cache.binder.binds), list(cache.evictor.evicts), profs


@pytest.mark.parametrize("tiers_spec", TIER_SETS)
@pytest.mark.parametrize("seed", [11, 42])
def test_sharded_eviction_parity(tiers_spec, seed, monkeypatch):
    """Satellite contract: per-action preempt/reclaim/backfill under the
    8-device mesh == unsharded, over op log effects (eviction order),
    shares, fit errors and preemption metrics — mirroring the rounds-kernel
    mesh parity tests at the eviction layer."""
    got = _run(_overcommit_cluster(seed, nodes=5), tiers_spec, _mesh(8),
               monkeypatch, fuse=False)
    want = _run(_overcommit_cluster(seed, nodes=5), tiers_spec, None,
                monkeypatch, fuse=False)
    assert got[0] == want[0], (tiers_spec, seed)
    assert got[1] == want[1]          # binds
    assert got[2] == want[2]          # evictions, in effector order
    # the sharded kernels must actually have run (no silent fallback)
    prof = got[3][0]
    for kind in ("preempt", "reclaim", "backfill"):
        assert f"evict_{kind}" in prof, prof.get(
            f"evict_{kind}_fallback", prof)
    assert prof.get("mesh_devices") == 8, prof


@pytest.mark.parametrize("seed", [11, 7])
def test_sharded_fused_chain_parity(seed, monkeypatch):
    """The fused chain (allocate -> backfill -> preempt -> reclaim as one
    device program chain with donated carries) under the mesh == the
    unsharded fused chain: no stage de-shards the axis mid-session."""
    tiers_spec = TIER_SETS[0]
    got = _run(_overcommit_cluster(seed, nodes=6), tiers_spec, _mesh(8),
               monkeypatch, fuse=True)
    want = _run(_overcommit_cluster(seed, nodes=6), tiers_spec, None,
                monkeypatch, fuse=True)
    assert got[0] == want[0], seed
    assert got[1] == want[1]
    assert got[2] == want[2]
    assert got[3][0].get("fuse") == 1, got[3][0].get(
        "fuse_fallback", got[3][0])


@pytest.mark.parametrize("devices", [2, 4])
def test_sharded_parity_smaller_meshes(devices, monkeypatch):
    """The bench sweep's intermediate device counts shard the same axis
    with different pad extents — parity must hold at each."""
    tiers_spec = TIER_SETS[0]
    got = _run(_overcommit_cluster(13, nodes=5), tiers_spec,
               _mesh(devices), monkeypatch, fuse=False)
    want = _run(_overcommit_cluster(13, nodes=5), tiers_spec, None,
                monkeypatch, fuse=False)
    assert got[0] == want[0], devices
    assert got[1] == want[1]
    assert got[2] == want[2]


def test_sharded_consecutive_sessions_parity(monkeypatch):
    """Two back-to-back sharded sessions on one cache: the second rides
    the SnapshotKeeper delta path and the per-shard device cache — the
    accounting must stay identical to the unsharded arm."""
    tiers_spec = TIER_SETS[0]
    got = _run(_overcommit_cluster(21), tiers_spec, _mesh(8),
               monkeypatch, fuse=False, sessions=2)
    want = _run(_overcommit_cluster(21), tiers_spec, None,
                monkeypatch, fuse=False, sessions=2)
    assert got[0] == want[0]
    assert got[1] == want[1]
    assert got[2] == want[2]


def test_sharded_warm_no_compiles(monkeypatch):
    """Second identical-shape sharded session must reuse every compiled
    program: the per-shard staging and mesh padding are shape-stable, so
    a retrace here is a caching regression, not a legitimate compile."""
    tiers_spec = TIER_SETS[0]
    _run(_overcommit_cluster(11), tiers_spec, _mesh(8), monkeypatch,
         fuse=False)
    watcher = CompileWatcher.install()
    with watcher.assert_no_compiles("second identical sharded session"):
        got = _run(_overcommit_cluster(11), tiers_spec, _mesh(8),
                   monkeypatch, fuse=False)
    assert "evict_preempt" in got[3][0]


def test_sharded_warm_reuses_device_shards(monkeypatch):
    """Unchanged node rows must not re-cross the link: the second
    identical session's sharded encode reuses the per-shard device
    buffers (h2d_shard_cached > 0) instead of re-putting the axis."""
    tiers_spec = TIER_SETS[0]
    cache = _overcommit_cluster(11)
    _run(cache, tiers_spec, _mesh(8), monkeypatch, fuse=False)
    got = _run(cache, tiers_spec, _mesh(8), monkeypatch, fuse=False)
    prof = got[3][0]
    assert prof.get("h2d_shard_cached", 0) > 0, prof


class TestShardHelpers:
    def test_pad_axis_multiple_append_only(self):
        from volcano_tpu.ops import shard

        a = np.arange(10).reshape(5, 2)
        p = shard.pad_axis_multiple(a, 0, 8, fill=-1)
        assert p.shape == (8, 2)
        assert (p[:5] == a).all() and (p[5:] == -1).all()
        # already-multiple extents are returned untouched (identity)
        assert shard.pad_axis_multiple(p, 0, 8) is p
        assert shard.per_shard(8, 8) == 1
        assert shard.per_shard(16, 4) == 4

    def test_stage_values_match_single_device_layout(self):
        """The assembled sharded array's VALUES are the single-device
        layout byte-for-byte — the oracle contract of the staging."""
        from volcano_tpu.ops import shard

        mesh = _mesh(8)
        shard.clear_cache()
        rng = np.random.default_rng(3)
        arrays = {"node_idle": rng.uniform(0, 8, (16, 2)),
                  "sig_mask": rng.random((3, 16)) < 0.5}
        axes = {"node_idle": 0, "sig_mask": 1}
        staged = shard.stage_node_arrays(arrays, axes, mesh)
        for k in arrays:
            np.testing.assert_array_equal(np.asarray(staged[k]), arrays[k])

    def test_stage_identity_fast_path_skips_puts(self):
        from volcano_tpu.ops import shard

        mesh = _mesh(8)
        shard.clear_cache()
        arr = np.random.default_rng(4).uniform(0, 8, (16, 2))
        prof1, prof2 = {}, {}
        shard.stage_node_arrays({"x": arr}, {"x": 0}, mesh, prof1)
        shard.stage_node_arrays({"x": arr}, {"x": 0}, mesh, prof2)
        assert prof1["h2d_shard_puts"] == 8
        assert prof2["h2d_shard_puts"] == 0
        assert prof2["h2d_shard_cached"] == 8

    def test_stage_dirty_rows_reput_only_their_shard(self):
        """O(changed rows) per shard: a single changed row re-puts ONE
        shard; the other seven stay device-resident."""
        from volcano_tpu.ops import shard

        mesh = _mesh(8)
        shard.clear_cache()
        arr = np.random.default_rng(5).uniform(0, 8, (16, 2))
        shard.stage_node_arrays({"x": arr}, {"x": 0}, mesh, {})
        arr2 = arr.copy()
        arr2[3, 0] += 1.0   # row 3 -> shard 1 (width 2)
        prof = {}
        staged = shard.stage_node_arrays({"x": arr2}, {"x": 0}, mesh, prof)
        assert prof["h2d_shard_puts"] == 1, prof
        assert prof["h2d_shard_cached"] == 7, prof
        np.testing.assert_array_equal(np.asarray(staged["x"]), arr2)


@pytest.mark.slow
@pytest.mark.parametrize("seed", list(range(100, 110)))
def test_sharded_parity_wide(seed, monkeypatch):
    """Wide fuzz band: the SAME randomized cluster shapes the unsharded
    wide fuzz proves feasible (test_evict_kernel seeds/rng), re-run
    sharded-vs-unsharded across tier sets, fused and per-action."""
    rng = random.Random(seed * 7)
    kw = dict(nodes=rng.choice([4, 7, 9]),
              running_jobs=rng.choice([8, 14, 18]),
              tasks_per_job=rng.choice([3, 4, 5]),
              queues=rng.choice([2, 3]),
              hi_jobs=rng.choice([3, 5]))
    tiers_spec = TIER_SETS[seed % len(TIER_SETS)]
    fuse = bool(seed % 2)
    got = _run(_overcommit_cluster(seed, **kw), tiers_spec, _mesh(8),
               monkeypatch, fuse=fuse)
    want = _run(_overcommit_cluster(seed, **kw), tiers_spec, None,
                monkeypatch, fuse=fuse)
    assert got[0] == want[0], (kw, tiers_spec, fuse)
    assert got[1] == want[1]
    assert got[2] == want[2]
