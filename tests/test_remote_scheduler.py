"""The reference's real process topology: an out-of-process SCHEDULER.

The cluster subprocess runs --api-server-only (store + admission +
controllers + kubelet + gateway, no scheduler). THIS process runs the
full scheduler stack — SchedulerCache wired to a RemoteStore, so all
seven informer streams arrive over HTTP long-poll watches, and every
effector write (binds, pod conditions, PodGroup statuses) goes back
through the gateway — exactly vc-scheduler against the API server
(reference cmd/scheduler; pkg/scheduler/cache/cache.go:322-425).

The job's pods must end up bound and Running IN THE REMOTE STORE, with
the subprocess kubelet/controllers driving phases — proof that the
scheduler's entire read AND write surface is network-transparent.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

import pytest

from volcano_tpu.store.remote import RemoteStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def api_server_proc():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("VOLCANO_TPU_PANIC", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "volcano_tpu.scheduler",
         "--api-server-only", "--api-address", ":0",
         "--listen-address", ":0", "--healthz-address", "127.0.0.1:0",
         "--cluster-state", os.path.join(REPO, "example", "cluster.yaml"),
         "--run-for", "120"],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    port = None
    deadline = time.time() + 60
    # select-gated reads: a plain readline() blocks with no timeout, so an
    # alive-but-silent server would stall setup for the full --run-for
    # window instead of failing at the deadline
    import select

    while time.time() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [],
                                    max(deadline - time.time(), 0.1))
        if not ready:
            break
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("api gateway on :"):
            port = int(line.rsplit(":", 1)[1])
            break
    if port is None:
        proc.terminate()
        out, err = proc.communicate(timeout=10)
        pytest.fail(f"api-server process exposed no port:\n{out}\n{err}")
    yield proc, port
    proc.terminate()
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()


def _wait(predicate, timeout=30.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(interval)
    return None


def test_out_of_process_scheduler_binds_over_http(api_server_proc):
    from volcano_tpu.cli import job as job_cli
    from volcano_tpu.scheduler.cache import SchedulerCache
    from volcano_tpu.scheduler.scheduler import Scheduler

    _, port = api_server_proc
    remote = RemoteStore(f"127.0.0.1:{port}")
    try:
        cache = SchedulerCache(store=remote)
        cache.run()  # seven informer streams over HTTP long-poll
        scheduler = Scheduler(cache, schedule_period=0.2)

        # informer sync is asynchronous over the network (unlike the
        # in-process store's synchronous watches): wait for the seeded
        # cluster state to arrive before the first cycle
        assert _wait(lambda: len(cache.nodes) >= 3), \
            "remote informers never delivered the seeded nodes"

        # submit the job through the same gateway the scheduler consumes;
        # the API-server process admits it and its controllers create the
        # PodGroup/pods — which reach THIS process as watch events
        with open(os.path.join(REPO, "example", "job.yaml")) as f:
            job_cli.run_job(remote, f.read())

        # pod creation is GATED behind the enqueue action (delay-pod-
        # creation): the remote scheduler's cycles must flip the PodGroup
        # to Inqueue (a status PUT through the gateway) before the
        # API-server process's job controller materializes pods
        def pods_pending():
            scheduler.run_once()
            pods = remote.list("Pod", namespace="default")
            return pods if len(pods) >= 3 else None

        assert _wait(pods_pending), "controllers never created the pods"

        # drive scheduling cycles from THIS process until every pod is
        # bound in the REMOTE store (binds travel as HTTP PUTs through
        # the gateway, then return as watch events)
        def all_bound():
            scheduler.run_once()
            pods = remote.list("Pod", namespace="default")
            return pods if pods and all(p.spec.node_name for p in pods) \
                else None

        bound = _wait(all_bound, timeout=45)
        assert bound, "remote scheduler never bound the job's pods"

        # the subprocess kubelet starts bound pods; its controllers flip
        # the PodGroup — observed here purely through remote reads
        def all_running():
            scheduler.run_once()
            pods = remote.list("Pod", namespace="default")
            from volcano_tpu.api import objects

            return pods if pods and all(
                p.status.phase == objects.POD_PHASE_RUNNING
                for p in pods) else None

        assert _wait(all_running, timeout=45), \
            "pods never reached Running through the remote pipeline"

        pg = _wait(lambda: remote.try_get("PodGroup", "default", "test-job"))
        assert pg is not None

        # Scheduled events recorded by the remote scheduler's effectors
        # must land in the API-server process's event log
        remote.flush_events()
        pod = remote.list("Pod", namespace="default")[0]
        evs = _wait(lambda: [e for e in remote.events_for(pod)
                             if e.reason == "Scheduled"] or None)
        assert evs, "Scheduled event never landed across the wire"
    finally:
        remote.stop_watches()


def test_remote_scheduler_binary_mode(api_server_proc):
    """The CLI form of the split: `python -m volcano_tpu.scheduler
    --server host:port` as a THIRD process schedules jobs submitted
    through the gateway (reference: vc-scheduler binary vs API server)."""
    _, port = api_server_proc
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("VOLCANO_TPU_PANIC", None)
    sched = subprocess.Popen(
        [sys.executable, "-m", "volcano_tpu.scheduler",
         "--server", f"127.0.0.1:{port}",
         "--listen-address", ":0", "--healthz-address", "127.0.0.1:0",
         "--schedule-period", "0.2", "--run-for", "60"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    remote = RemoteStore(f"127.0.0.1:{port}")
    try:
        from volcano_tpu.cli import job as job_cli

        with open(os.path.join(REPO, "example", "job.yaml")) as f:
            job_cli.run_job(remote, f.read().replace(
                "name: test-job", "name: binary-job"))

        def all_bound():
            # an immediately-dead scheduler binary must fail the test NOW
            # with its output, not after the full wait budget
            assert sched.poll() is None, \
                f"scheduler binary exited early:\n{sched.stdout.read()}"
            pods = remote.list("Pod", namespace="default")
            return pods if pods and all(p.spec.node_name for p in pods) \
                else None

        assert _wait(all_bound, timeout=45), \
            "the scheduler binary never bound the job over HTTP"
    finally:
        sched.terminate()
        try:
            sched.wait(timeout=10)
        except subprocess.TimeoutExpired:
            sched.kill()


def test_remote_scheduler_under_churn(api_server_proc):
    """Concurrency over the wire: jobs are submitted AND deleted from a
    churn thread while the remote scheduler's cycles run — watch events
    land on the cache from poll threads concurrently with session
    snapshots. The end state must be consistent: every surviving job's
    pods bound, no session crash, cache accounting matching the remote
    truth."""
    from volcano_tpu.cli import job as job_cli
    from volcano_tpu.scheduler.cache import SchedulerCache
    from volcano_tpu.scheduler.scheduler import Scheduler

    _, port = api_server_proc
    remote = RemoteStore(f"127.0.0.1:{port}")
    try:
        cache = SchedulerCache(store=remote)
        cache.run()
        scheduler = Scheduler(cache, schedule_period=0.1)
        assert _wait(lambda: len(cache.nodes) >= 3)

        with open(os.path.join(REPO, "example", "job.yaml")) as f:
            yaml_text = f.read()

        import threading

        errors = []

        def churn():
            try:
                for i in range(6):
                    job_cli.run_job(remote, yaml_text.replace(
                        "name: test-job", f"name: churn-{i}"))
                    time.sleep(0.15)
                # delete half mid-flight
                for i in range(0, 6, 2):
                    remote.try_delete("Job", "default", f"churn-{i}")
                    time.sleep(0.1)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=churn)
        t.start()
        deadline = time.time() + 45
        while time.time() < deadline:
            scheduler.run_once()  # must survive concurrent churn
            if not t.is_alive():
                # settle: survivors fully bound
                pods = remote.list("Pod", namespace="default")
                alive = [p for p in pods
                         if p.metadata.deletion_timestamp is None]
                if alive and all(p.spec.node_name for p in alive):
                    break
            time.sleep(0.05)
        t.join(timeout=10)
        assert not errors, errors

        surviving = {j.metadata.name
                     for j in remote.list("Job", namespace="default")}
        assert {f"churn-{i}" for i in (1, 3, 5)} <= surviving

        # judge only SURVIVING jobs' pods: a deleted job's pods may
        # still be mid-teardown in the API-server process (controller
        # stamps deletion, kubelet collects) — that cleanup is its
        # business, not this scheduler's
        from volcano_tpu.api import objects as _o

        def surviving_pods():
            return [p for p in remote.list("Pod", namespace="default")
                    if p.metadata.annotations.get(_o.JOB_NAME_KEY)
                    in surviving]

        def all_surviving_bound():
            scheduler.run_once()
            pods = surviving_pods()
            return pods if pods and all(p.spec.node_name for p in pods) \
                else None

        pods = _wait(all_surviving_bound, timeout=30)
        assert pods, "surviving jobs' pods must all be bound after churn"
        # remote-truth vs cache consistency for surviving pods; read the
        # cache under ITS lock — the HTTP poll threads mutate jobs/tasks
        # concurrently and a lock-free comprehension could flake with
        # "dict changed size during iteration"
        def cache_consistent():
            bound = {p.metadata.name for p in pods}
            with cache._lock:
                seen = {t.name for j in cache.jobs.values()
                        for t in j.tasks.values() if t.node_name}
            return bound <= seen
        assert _wait(cache_consistent, timeout=15), \
            "cache never converged to the remote truth"
    finally:
        remote.stop_watches()
