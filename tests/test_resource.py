"""Resource arithmetic parity tests (mirrors pkg/scheduler/api/resource_info_test.go)."""

import pytest

from volcano_tpu.api.quantity import milli_value, parse_quantity
from volcano_tpu.api.resource import (
    MIN_MEMORY,
    MIN_MILLI_CPU,
    MIN_MILLI_SCALAR,
    Resource,
)
from volcano_tpu.utils.assertions import AssertionViolation


def res(mcpu=0.0, mem=0.0, scalars=None):
    return Resource(mcpu, mem, dict(scalars) if scalars else None)


class TestQuantity:
    def test_plain(self):
        assert parse_quantity("2") == 2.0
        assert parse_quantity(1.5) == 1.5
        assert parse_quantity("1e3") == 1000.0

    def test_milli(self):
        assert parse_quantity("500m") == 0.5
        assert milli_value("500m") == 500.0
        assert milli_value("2") == 2000.0

    def test_binary(self):
        assert parse_quantity("1Ki") == 1024
        assert parse_quantity("8Gi") == 8 * 2**30
        assert parse_quantity("1.5Mi") == 1.5 * 2**20

    def test_decimal_suffix(self):
        assert parse_quantity("2k") == 2000
        assert parse_quantity("1G") == 10**9


class TestFromResourceList:
    def test_basic(self):
        r = Resource.from_resource_list(
            {"cpu": "4", "memory": "8Gi", "pods": 110, "nvidia.com/gpu": 2}
        )
        assert r.milli_cpu == 4000
        assert r.memory == 8 * 2**30
        assert r.max_task_num == 110
        assert r.scalar_resources == {"nvidia.com/gpu": 2000.0}

    def test_ignores_unknown_native(self):
        r = Resource.from_resource_list({"ephemeral-storage": "10Gi"})
        assert r.is_empty()


class TestComparisons:
    def test_less_equal_epsilon_cpu(self):
        # within epsilon counts as equal (resource_info.go:267-275)
        assert res(mcpu=1009).less_equal(res(mcpu=1000))
        assert not res(mcpu=1011).less_equal(res(mcpu=1000))

    def test_less_equal_epsilon_memory(self):
        assert res(mem=MIN_MEMORY - 1).less_equal(res(mem=0))
        assert not res(mem=MIN_MEMORY + 1).less_equal(res(mem=0))

    def test_less_equal_scalar_below_min_ignored(self):
        # scalar dims at or below the min are skipped entirely
        assert res(scalars={"nvidia.com/gpu": MIN_MILLI_SCALAR}).less_equal(res())
        assert not res(scalars={"nvidia.com/gpu": 1000}).less_equal(res())

    def test_less_equal_scalar_against_nil(self):
        # rr has no scalar map but we need >min scalar: not fitting
        assert not res(scalars={"x/y": 100}).less_equal(res(mcpu=10000, mem=1e12))

    def test_less_strict(self):
        assert res(mcpu=1, mem=1).less(res(mcpu=2, mem=2))
        assert not res(mcpu=2, mem=1).less(res(mcpu=2, mem=2))

    def test_less_nil_scalars_lhs(self):
        # lhs nil scalars: rr scalar <= min makes it non-less (go semantics)
        assert not res(mcpu=1, mem=1).less(
            res(mcpu=2, mem=2, scalars={"a/b": MIN_MILLI_SCALAR})
        )
        assert res(mcpu=1, mem=1).less(res(mcpu=2, mem=2, scalars={"a/b": 100}))

    def test_less_nil_scalars_rhs(self):
        assert not res(mcpu=1, mem=1, scalars={"a/b": 5}).less(res(mcpu=2, mem=2))


class TestArithmetic:
    def test_add(self):
        r = res(mcpu=1000, mem=100)
        r.add(res(mcpu=500, mem=50, scalars={"nvidia.com/gpu": 1000}))
        assert r.milli_cpu == 1500
        assert r.memory == 150
        assert r.scalar_resources["nvidia.com/gpu"] == 1000

    def test_sub(self):
        r = res(mcpu=1000, mem=1e9, scalars={"nvidia.com/gpu": 2000})
        r.sub(res(mcpu=400, mem=2e8, scalars={"nvidia.com/gpu": 1000}))
        assert r.milli_cpu == 600
        assert r.memory == 8e8
        assert r.scalar_resources["nvidia.com/gpu"] == 1000

    def test_sub_insufficient_panics(self):
        with pytest.raises(AssertionViolation):
            res(mcpu=100).sub(res(mcpu=500))

    def test_sub_within_epsilon_allowed(self):
        # epsilon tolerance lets slightly-over subtraction through; result
        # may go slightly negative, matching the reference
        r = res(mcpu=1000)
        r.sub(res(mcpu=1005))
        assert r.milli_cpu == -5

    def test_multi(self):
        r = res(mcpu=1000, mem=100, scalars={"a/b": 10})
        r.multi(1.2)
        assert r.milli_cpu == 1200
        assert abs(r.memory - 120) < 1e-9
        assert r.scalar_resources["a/b"] == 12

    def test_set_max_resource(self):
        r = res(mcpu=1000, mem=100)
        r.set_max_resource(res(mcpu=500, mem=200, scalars={"a/b": 7}))
        assert r.milli_cpu == 1000
        assert r.memory == 200
        assert r.scalar_resources == {"a/b": 7}

    def test_fit_delta(self):
        r = res(mcpu=1000, mem=MIN_MEMORY * 3)
        r.fit_delta(res(mcpu=500, mem=MIN_MEMORY))
        assert r.milli_cpu == 1000 - 500 - MIN_MILLI_CPU
        assert r.memory == MIN_MEMORY * 3 - MIN_MEMORY - MIN_MEMORY

    def test_diff(self):
        inc, dec = res(mcpu=1000, mem=50).diff(res(mcpu=400, mem=100))
        assert inc.milli_cpu == 600 and inc.memory == 0
        assert dec.milli_cpu == 0 and dec.memory == 50

    def test_clone_independent(self):
        r = res(mcpu=1, scalars={"a/b": 1})
        c = r.clone()
        c.add(res(mcpu=5, scalars={"a/b": 5}))
        assert r.milli_cpu == 1
        assert r.scalar_resources["a/b"] == 1


class TestEmptyZero:
    def test_is_empty(self):
        assert Resource.empty().is_empty()
        assert res(mcpu=MIN_MILLI_CPU - 1, mem=MIN_MEMORY - 1).is_empty()
        assert not res(mcpu=MIN_MILLI_CPU).is_empty()
        assert not res(scalars={"a/b": MIN_MILLI_SCALAR}).is_empty()

    def test_is_zero(self):
        assert res(mcpu=5).is_zero("cpu")
        assert not res(mcpu=50).is_zero("cpu")
        assert res().is_zero("some/scalar")  # nil map => zero

    def test_is_zero_unknown_scalar_panics(self):
        with pytest.raises(AssertionViolation):
            res(scalars={"a/b": 5}).is_zero("c/d")
