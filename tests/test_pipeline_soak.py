"""Full-action-pipeline churn soak: ~18 consecutive sessions on ONE evolving
overcommitted cache, conf = enqueue + allocate + backfill + preempt +
reclaim, rounds mode forced — preemption and reclamation fire ACROSS
cycles, with a simulated kubelet (bound pods flip to Running, evicted pods
get deleted a cycle later) so the eviction -> releasing -> pipelined ->
deleted -> re-placed lifecycle actually turns over (reference analog:
test/e2e/job_error_handling.go's continuously reconciling evict/restart
suites).

Asserted:
- accounting oracle every cycle: node used/idle/releasing and job
  allocated recomputed from first principles match the incremental state —
  THE stale-state detector for the preempt-view/victim-view/fused-
  transition caches under churn;
- every eviction the effector records corresponds to a cache task that is
  RELEASING (until the kubelet deletes it);
- preempt fires (high-priority gangs land while lower-priority tasks get
  evicted) and reclaim fires (the starved queue's share grows);
- pipelined placements resolve: tasks the session pipelined onto releasing
  capacity are bound in a later cycle once victims die;
- gang atomicity on new placements, nothing binds twice, the drained node
  receives nothing after the drain;
- ZERO steady-state XLA recompiles (cycle >= 4) with the full program-
  variant set live.
"""

from __future__ import annotations

import pytest

from tests.helpers import close_session, make_cache, make_tiers, open_session
from volcano_tpu.api import objects
from volcano_tpu.api.types import TaskStatus, allocated_status
from volcano_tpu.scheduler.framework import get_action
from volcano_tpu.scheduler.util.test_utils import (
    build_node, build_pod, build_pod_group, build_queue,
    build_resource_list_with_pods,
)
from volcano_tpu.utils.jaxcompile import CompileWatcher

CYCLES = 18
NODES = 48
GANG = 4

TIERS = (["priority", "gang"],
         ["drf", "predicates", "proportion", "nodeorder"])
ACTIONS = ("enqueue", "allocate", "backfill", "preempt", "reclaim")


def _add_job(cache, name: str, queue: str, priority: int, cpu: str,
             best_effort: bool = False, min_member: int = GANG) -> None:
    cache.add_pod_group(build_pod_group(
        name, namespace="soak", min_member=min_member, queue=queue,
        phase=objects.PodGroupPhase.PENDING))
    for i in range(GANG):
        req = {} if best_effort else {"cpu": cpu, "memory": "512Mi"}
        cache.add_pod(build_pod(
            "soak", f"{name}-t{i}", "", objects.POD_PHASE_PENDING,
            req, name, priority=priority))


def _kubelet_start_bound(cache) -> int:
    """Simulated kubelet: freshly bound pods flip to Running via the watch
    path (the scheduler only preempts RUNNING victims)."""
    started = 0
    for job in list(cache.jobs.values()):
        for t in list(job.tasks.values()):
            if t.status in (TaskStatus.BINDING, TaskStatus.BOUND) \
                    and t.pod is not None:
                pod = t.pod
                pod.spec.node_name = t.node_name
                pod.status.phase = objects.POD_PHASE_RUNNING
                cache.update_pod_from_watch(pod, pod)
                started += 1
    return started


def _kubelet_kill_releasing(cache) -> int:
    """Simulated kubelet/controller: evicted (RELEASING) pods die, freeing
    their capacity for the tasks pipelined onto it."""
    victims = [t.pod for job in cache.jobs.values()
               for t in job.tasks.values()
               if t.status == TaskStatus.RELEASING and t.pod is not None]
    for pod in victims:
        cache.delete_pod(pod)
    return len(victims)


def _assert_accounting(cache, cycle) -> None:
    for name, node in cache.nodes.items():
        used_cpu = sum(t.resreq.milli_cpu for t in node.tasks.values())
        rel_cpu = sum(t.resreq.milli_cpu for t in node.tasks.values()
                      if t.status == TaskStatus.RELEASING)
        assert abs(node.used.milli_cpu - used_cpu) < 1e-6, (cycle, name)
        assert abs(node.releasing.milli_cpu - rel_cpu) < 1e-6, (cycle, name)
        if node.allocatable is not None:
            assert abs(node.idle.milli_cpu + used_cpu
                       - node.allocatable.milli_cpu) < 1e-6, (cycle, name)
    for uid, job in cache.jobs.items():
        alloc_cpu = sum(t.resreq.milli_cpu for t in job.tasks.values()
                        if allocated_status(t.status))
        assert abs(job.allocated.milli_cpu - alloc_cpu) < 1e-6, (cycle, uid)


@pytest.mark.slow
def test_full_pipeline_churn_soak():
    cache = make_cache()
    cache.add_queue(build_queue("qa", weight=1))
    cache.add_queue(build_queue("qb", weight=1))
    for n in range(NODES):
        cache.add_node(build_node(
            f"node-{n:03d}",
            build_resource_list_with_pods("16", "32Gi", pods=48)))
    # initial low-priority filler saturates the 768-cpu cluster;
    # min_member=2 of 4 leaves two evictable members per gang (a gang at
    # min_member == size is never preemptable — gang.go:82-86)
    for j in range(48):
        _add_job(cache, f"fill-000-{j:03d}", "qa", 1, "4", min_member=2)
    tiers = make_tiers(["tpuscore"], *TIERS)

    watcher = CompileWatcher.install()
    drained = "node-005"
    all_bound: dict = {}
    recompiles = []
    evictions_total = 0
    qb_bound = 0
    pipelined_waiting: dict = {}  # key -> cycle first seen pipelined
    pipelined_resolved = 0
    preempt_cycles = 0

    for cycle in range(CYCLES):
        # ---- world churn BEFORE the cycle's session ----------------------
        if cycle > 0:
            _kubelet_start_bound(cache)
            killed = _kubelet_kill_releasing(cache)
            assert killed == evictions_pending, (cycle, killed)
        if cycle == 6:
            # drain (cordon) via the watch path: spec flip + node update
            node_obj = cache.nodes[drained].node
            node_obj.spec.unschedulable = True
            cache.add_node(node_obj)
        if cycle >= 1:
            # completions: ~12% of the oldest Running pods finish, so
            # capacity churns and table rows recycle
            running = sorted(
                (t.pod for job in cache.jobs.values()
                 for t in job.tasks.values()
                 if t.status == TaskStatus.RUNNING and t.pod is not None),
                key=lambda pp: (pp.metadata.namespace, pp.metadata.name))
            for pod in running[:max(1, len(running) // 8)]:
                cache.delete_pod(pod)
            # keep qa saturated with low-priority filler
            for j in range(8):
                _add_job(cache, f"fill-{cycle:03d}-{j:03d}", "qa", 1, "4",
                         min_member=2)
            # best-effort pods exercise backfill
            _add_job(cache, f"be-{cycle:03d}", "qa", 1, "0",
                     best_effort=True)
        if cycle >= 2:
            # high-priority gangs in qa force preemption under saturation
            for j in range(4):
                _add_job(cache, f"hi-{cycle:03d}-{j:03d}", "qa", 10, "2")
        if cycle >= 3:
            # starved queue-b demand forces reclaim from qa's overage
            for j in range(2):
                _add_job(cache, f"qb-{cycle:03d}-{j:03d}", "qb", 5, "2")

        # ---- one full-pipeline session ----------------------------------
        ev_before = len(cache.evictor.evicts)
        before = set(cache.binder.binds)
        win = watcher.window()
        ssn = open_session(cache, tiers)
        if ssn.batch_allocator is not None:
            ssn.batch_allocator.mode = "rounds"
        for name in ACTIONS:
            get_action(name).execute(ssn)
        # capture session-local pipelined placements before close
        pipelined_now = [
            t.key for job in ssn.jobs.values()
            for t in job.task_status_index.get(
                TaskStatus.PIPELINED, {}).values()]
        close_session(ssn)
        recompiles.append(win.delta().compiles)

        new = {k: cache.binder.binds[k]
               for k in set(cache.binder.binds) - before}
        evicted_this = len(cache.evictor.evicts) - ev_before
        evictions_total += evicted_this
        evictions_pending = sum(
            1 for job in cache.jobs.values() for t in job.tasks.values()
            if t.status == TaskStatus.RELEASING)
        if evicted_this:
            preempt_cycles += 1

        # ---- per-cycle assertions ---------------------------------------
        _assert_accounting(cache, cycle)
        # every recorded eviction leaves a RELEASING cache task (until the
        # kubelet deletes it next cycle); evictions within one session are
        # unique tasks, so counts line up
        assert evictions_pending == evicted_this, (
            cycle, evictions_pending, evicted_this)
        if cycle > 6:
            assert not any(v == drained for v in new.values()), cycle
        dup = set(new) & set(all_bound)
        assert not dup, (cycle, sorted(dup)[:3])
        all_bound.update(new)
        qb_bound += sum(1 for k in new if k.split("/")[1].startswith("qb-"))

        # pipelined placements must resolve to binds in later cycles
        for key in list(pipelined_waiting):
            if key in all_bound:
                pipelined_waiting.pop(key)
                pipelined_resolved += 1
        for key in pipelined_now:
            pipelined_waiting.setdefault(key, cycle)

        # gang atomicity on new placements: a gang below min_available
        # must not appear partially unless earlier cycles already bound it
        per_pg: dict = {}
        for key in new:
            pg = key.split("/", 1)[1].rsplit("-", 1)[0]
            per_pg[pg] = per_pg.get(pg, 0) + 1
        for pg in per_pg:
            job = cache.jobs.get(f"soak/{pg}")
            if job is not None:
                prior = sum(
                    1 for k in all_bound
                    if k.split("/", 1)[1].rsplit("-", 1)[0] == pg)
                assert prior >= job.min_available, (cycle, pg, prior)

    # ---- whole-soak assertions ------------------------------------------
    assert evictions_total >= 3 * GANG, evictions_total  # preempt/reclaim real
    assert preempt_cycles >= 3, preempt_cycles           # ...across cycles
    assert qb_bound >= GANG, qb_bound                    # reclaim landed qb work
    # pipelined-onto-releasing placements resolved once victims died.
    # Low-priority fillers may legitimately starve behind the endless
    # high-priority arrivals (that IS the scheduler working), so the
    # must-resolve guarantee applies to the high-priority preemptors —
    # nothing outranks them, their victims die next cycle
    assert pipelined_resolved >= 1, (pipelined_resolved, pipelined_waiting)
    unresolved_hi = {k: c for k, c in pipelined_waiting.items()
                     if k.startswith("soak/hi-") and c < CYCLES - 2}
    assert not unresolved_hi, unresolved_hi
    # zero steady-state recompiles with the full variant set live
    assert all(c == 0 for c in recompiles[4:]), recompiles
