"""Remote watch streams + gateway auth/TLS.

The gateway serves a long-poll watch journal per kind (/watch/{Kind}) and
RemoteStore.watch dispatches the same informer-style WatchHandler
callbacks as the in-process Store.watch — closing the architectural
asymmetry with the reference, whose controllers are remote informer
clients of the API server (pkg/scheduler/cache/cache.go:322-425).

Covered here:
- in-process gateway: watch ADDED/MODIFIED/DELETED over HTTP, journal
  reset/re-list, bearer-token auth (401 anonymous write), malformed
  selector -> 400, PUT path/body mismatch -> 400, TLS serving;
- cross-process: a QueueController running in THIS process against a
  live cluster subprocess observes a PodGroup phase flip through the
  remote watch and aggregates it into QueueStatus (VERDICT r5 #5).
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

import pytest

from volcano_tpu.api import objects
from volcano_tpu.store.gateway import ApiGateway
from volcano_tpu.store.remote import RemoteStore, RemoteStoreError
from volcano_tpu.store.store import Store, WatchHandler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _queue(name, weight=1):
    return objects.Queue(
        metadata=objects.ObjectMeta(name=name),
        spec=objects.QueueSpec(weight=weight))


def _wait(predicate, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(interval)
    return None


class TestGatewayWatch:
    def setup_method(self):
        self.store = Store()
        self.gw = ApiGateway(self.store, ":0").start()
        self.remote = RemoteStore(f"127.0.0.1:{self.gw.port}")

    def teardown_method(self):
        self.remote.stop_watches()
        self.gw.stop()

    def test_watch_added_modified_deleted(self):
        self.store.create(_queue("pre-existing", 2))
        events = []
        cond = threading.Condition()

        def record(kind):
            def cb(*args):
                with cond:
                    events.append((kind, args))
                    cond.notify_all()
            return cb

        self.remote.watch("Queue", WatchHandler(
            added=record("added"), updated=record("updated"),
            deleted=record("deleted")))
        # initial sync: the pre-existing object arrives as ADDED
        assert _wait(lambda: [e for e in events if e[0] == "added"])
        added = [e for e in events if e[0] == "added"][0]
        assert added[1][0].metadata.name == "pre-existing"

        q2 = self.store.create(_queue("flip", 1))
        assert _wait(lambda: [e for e in events
                              if e[0] == "added"
                              and e[1][0].metadata.name == "flip"])

        import copy

        q2b = copy.deepcopy(q2)  # the store holds q2 live; don't alias it
        q2b.spec.weight = 7
        self.store.update(q2b)
        got = _wait(lambda: [e for e in events if e[0] == "updated"])
        assert got, "MODIFIED never arrived over the remote watch"
        old, new = got[0][1]
        assert old.spec.weight == 1 and new.spec.weight == 7

        self.store.delete("Queue", "", "flip")
        got = _wait(lambda: [e for e in events if e[0] == "deleted"])
        assert got and got[0][1][0].metadata.name == "flip"

    def test_watch_reset_relists(self):
        # a tiny journal forces the reset path: the client's cursor falls
        # behind the ring and it must re-list (at-least-once re-ADDs)
        from volcano_tpu.store import gateway as gw_mod

        self.store.create(_queue("q0"))
        j = gw_mod._WatchJournal(self.store, "Queue", cap=2)
        with self.gw._journals_lock:
            self.gw._journals["Queue"] = j
        for i in range(1, 6):
            self.store.create(_queue(f"q{i}"))
        events, nxt, reset = j.poll(0, 0)
        assert reset and nxt == 6  # 6 appends total, ring holds last 2

        seen = []
        self.remote.watch("Queue", WatchHandler(added=seen.append))
        assert _wait(lambda: len(seen) >= 6)
        names = {q.metadata.name for q in seen}
        assert names == {f"q{i}" for i in range(6)}

    def test_event_flusher_respawns_after_stop_timeout(self):
        # a stop_events() whose join timed out leaves _event_stop set and
        # a flusher that exits after one drain; later events must still
        # reach the gateway (a dead thread reference must not latch the
        # recorder off forever)
        q = self.store.create(_queue("evq"))
        self.remote._event_stop = True  # simulate the timed-out stop
        self.remote.record_event(q, "Normal", "First", "m1")
        self.remote.flush_events()
        self.remote.record_event(q, "Normal", "Second", "m2")
        self.remote.flush_events()
        reasons = {e.reason for e in self.store.events_for(q)}
        assert {"First", "Second"} <= reasons

    def test_malformed_selector_is_400(self):
        with pytest.raises(ValueError):
            self.remote._request("GET", "/apis/Queue",
                                 query={"selector": "no-equals-sign"})

    def test_put_path_body_mismatch_is_400(self):
        from volcano_tpu.api import codec

        q = self.store.create(_queue("real"))
        env = codec.envelope(q)
        with pytest.raises(ValueError, match="path/body mismatch"):
            self.remote._request("PUT", "/apis/Queue/-/other", env)

    def test_watch_bad_since_is_400(self):
        with pytest.raises(ValueError):
            self.remote._request("GET", "/watch/Queue",
                                 query={"since": "nan-o-second"})


class TestWatchResetSynthesis:
    """The poller's reset handling, against a scripted transport (the
    live-gateway race — an in-flight long-poll draining the burst before
    the cursor falls behind — makes the ring-overflow path untestable
    deterministically end-to-end).

    Protocol script: the client syncs q0..q2, then every poll at its
    cursor returns `reset`. The first re-list attempts FAIL (the cursor
    must not advance past the gap), then a successful list returns only
    q0+q5 — the poller must synthesize DELETED for q1/q2 (removed while
    it was behind the journal ring; ADVICE r5 remote.py:344), re-ADD the
    listed set, and resume from the reset's `next` cursor."""

    def test_reset_diffs_known_set_and_retries_failed_relist(self):
        from volcano_tpu.api import codec

        remote = RemoteStore("127.0.0.1:1")  # transport is stubbed below
        calls = {"list": 0, "polls": []}
        stopper = threading.Event()

        def fake_request(method, path, payload=None, query=None,
                         timeout=None):
            if path == "/apis/Queue":
                calls["list"] += 1
                if calls["list"] <= 2:
                    raise RemoteStoreError("re-list unavailable")
                return {"items": [codec.envelope(_queue("q0")),
                                  codec.envelope(_queue("q5"))]}
            assert path == "/watch/Queue"
            since = int(query["since"])
            calls["polls"].append(since)
            if since == 0:
                return {"events": [
                    {"type": "ADDED", "object": codec.envelope(_queue(n))}
                    for n in ("q0", "q1", "q2")], "next": 3}
            if since == 3:
                return {"reset": True, "next": 9}
            # post-reset steady state: park until the test ends
            stopper.wait(0.2)
            return {"events": [], "next": since}

        remote._request = fake_request
        adds, dels = [], []
        remote.watch("Queue", WatchHandler(
            added=lambda o: adds.append(o.metadata.name),
            deleted=lambda o: dels.append(o.metadata.name)))
        try:
            assert _wait(lambda: set(dels) == {"q1", "q2"}, timeout=30.0), \
                (adds, dels, calls)
            assert calls["list"] >= 3  # two failures retried, not skipped
            # survivors + new objects re-ADDed after the deletes
            assert adds[:3] == ["q0", "q1", "q2"]
            assert set(adds[3:]) == {"q0", "q5"}
            assert "q0" not in dels and "q5" not in dels
            # the cursor resumed from the reset's `next`, and never
            # advanced while the re-list was still failing
            assert _wait(lambda: 9 in calls["polls"])
            assert [s for s in calls["polls"] if s == 3][:3] == [3, 3, 3]
        finally:
            stopper.set()
            remote.stop_watches()


class TestGatewayAuth:
    def test_anonymous_write_rejected(self):
        store = Store()
        gw = ApiGateway(store, ":0", token="sekrit").start()
        try:
            anon = RemoteStore(f"127.0.0.1:{gw.port}")
            with pytest.raises(RemoteStoreError, match="401"):
                anon.create(_queue("nope"))
            # reads are gated too
            with pytest.raises(RemoteStoreError, match="401"):
                anon.list("Queue")
            # healthz stays open (liveness probes carry no credentials)
            assert anon.healthy()
            authed = RemoteStore(f"127.0.0.1:{gw.port}", token="sekrit")
            created = authed.create(_queue("yes"))
            assert created.metadata.name == "yes"
            assert [q.metadata.name for q in authed.list("Queue")] == ["yes"]
        finally:
            gw.stop()

    def test_non_loopback_bind_requires_token(self):
        gw = ApiGateway(Store(), "0.0.0.0:0")
        with pytest.raises(ValueError, match="requires --api-token"):
            gw.start()
        # and the same bind WITH a token is accepted
        gw2 = ApiGateway(Store(), "0.0.0.0:0", token="t").start()
        gw2.stop()


@pytest.mark.skipif(shutil.which("openssl") is None,
                    reason="openssl binary unavailable")
def test_gateway_tls_roundtrip(tmp_path):
    cert = tmp_path / "gw.crt"
    key = tmp_path / "gw.key"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=127.0.0.1"],
        check=True, capture_output=True)
    store = Store()
    gw = ApiGateway(store, ":0", token="tls-tok",
                    tls_cert=str(cert), tls_key=str(key)).start()
    try:
        remote = RemoteStore(f"https://127.0.0.1:{gw.port}",
                             token="tls-tok", tls_verify=False)
        created = remote.create(_queue("over-tls", 5))
        assert created.spec.weight == 5
        # plaintext client against the TLS port fails at the transport
        with pytest.raises(RemoteStoreError):
            RemoteStore(f"127.0.0.1:{gw.port}", token="tls-tok",
                        timeout=3).list("Queue")
    finally:
        gw.stop()


@pytest.fixture(scope="module")
def cluster_proc():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("VOLCANO_TPU_PANIC", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "volcano_tpu.scheduler",
         "--api-address", ":0", "--api-token", "watch-tok",
         "--listen-address", ":0", "--healthz-address", "127.0.0.1:0",
         "--schedule-period", "0.2",
         "--cluster-state", os.path.join(REPO, "example", "cluster.yaml"),
         "--run-for", "90"],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    port = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("api gateway on :"):
            port = int(line.rsplit(":", 1)[1])
            break
    if port is None:
        proc.terminate()
        out, err = proc.communicate(timeout=10)
        pytest.fail(f"cluster process exposed no api port:\n{out}\n{err}")
    yield proc, port
    proc.terminate()
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_remote_controller_observes_phase_flip(cluster_proc):
    """A controller OUTSIDE the cluster process: QueueController wired to
    a RemoteStore watches Queue/PodGroup over HTTP, sees the live
    scheduler flip a PodGroup's phase, and aggregates it into QueueStatus
    via remote update_status — the reference's informer-client topology."""
    from volcano_tpu.controllers.queue import QueueController

    _, port = cluster_proc
    remote = RemoteStore(f"127.0.0.1:{port}", token="watch-tok")
    try:
        # the cluster process mutates stored objects in place before
        # publishing (in-process aliasing), so MODIFIED's old/new can show
        # the same phase — observe the phase TIMELINE instead and assert
        # the flip from the sequence of watch events
        phases = {}
        def saw(pg):
            phases.setdefault(
                f"{pg.metadata.namespace}/{pg.metadata.name}", []
            ).append(pg.status.phase)
        remote.watch("PodGroup", WatchHandler(
            added=saw, updated=lambda old, new: saw(new)))

        ctl = QueueController(remote)

        # submit a job through the same remote surface; the LIVE cluster
        # process schedules it and flips its PodGroup phase
        from volcano_tpu.cli import job as job_cli

        with open(os.path.join(REPO, "example", "job.yaml")) as f:
            yaml_text = f.read().replace("name: test-job", "name: watch-job")
        job_cli.run_job(remote, yaml_text)

        got = _wait(lambda: [k for k, seq in phases.items()
                             if len(set(seq)) >= 2], timeout=30)
        assert got, \
            f"no PodGroup phase flip observed over the remote watch: {phases}"

        # the remote controller aggregates the flip into the queue status
        def queue_running():
            ctl.process_all()
            q = remote.try_get("Queue", "", "default")
            return q is not None and (q.status.running or q.status.inqueue)

        assert _wait(queue_running, timeout=30), \
            "remote QueueController never aggregated the phase flip"
    finally:
        remote.stop_watches()


def test_leader_election_over_remote_store(cluster_proc):
    """HA across the wire: two electors on SEPARATE RemoteStore clients
    CAS the same ConfigMap lock through a LIVE cluster process's gateway
    (the reference's client-go election against the API server). Exactly
    one leads; when it stops, the standby takes over."""
    import threading

    from volcano_tpu.scheduler.leaderelection import (
        LeaderElector, ResourceLock)

    _, port = cluster_proc
    a = RemoteStore(f"127.0.0.1:{port}", token="watch-tok")
    b = RemoteStore(f"127.0.0.1:{port}", token="watch-tok")
    leads = {"a": threading.Event(), "b": threading.Event()}

    def elector(name, store):
        lock = ResourceLock(store, "volcano-system", "remote-ha", name)
        return LeaderElector(
            lock,
            on_started_leading=leads[name].set,
            on_stopped_leading=leads[name].clear,
            lease_duration=2.0, renew_deadline=1.0, retry_period=0.3)

    ea, eb = elector("a", a), elector("b", b)
    try:
        ea.start()
        assert leads["a"].wait(10), "first elector never acquired over HTTP"
        eb.start()
        # the standby must NOT lead while the leader renews
        assert not leads["b"].wait(2.5)
        assert ea.is_leader() and not eb.is_leader()

        # leader releases -> standby acquires through the same remote lock
        ea.stop()
        assert leads["b"].wait(10), "standby never took over after release"
        assert eb.is_leader()
    finally:
        # an assertion mid-flight must not leave elector threads CASing a
        # dead gateway for the rest of the pytest session
        ea.stop()
        eb.stop()
