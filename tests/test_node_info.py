"""NodeInfo accounting tests (mirrors pkg/scheduler/api/node_info_test.go)."""

import pytest

from volcano_tpu.api import objects
from volcano_tpu.api.job_info import new_task_info
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.types import NodePhase, TaskStatus
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_resource_list,
)


def task(name, cpu="1000m", status_phase=objects.POD_PHASE_RUNNING, node="n1"):
    pod = build_pod("ns1", name, node, status_phase,
                    build_resource_list(cpu, "1Gi"), "pg1")
    return new_task_info(pod)


class TestNodeInfo:
    def test_add_remove(self):
        ni = NodeInfo(build_node("n1", build_resource_list("8", "16Gi")))
        assert ni.ready()
        t1 = task("t1", "2000m")
        ni.add_task(t1)
        assert ni.idle.milli_cpu == 6000
        assert ni.used.milli_cpu == 2000
        ni.remove_task(t1)
        assert ni.idle.milli_cpu == 8000
        assert ni.used.milli_cpu == 0

    def test_clone_holds_copies(self):
        ni = NodeInfo(build_node("n1", build_resource_list("8", "16Gi")))
        t1 = task("t1", "2000m")
        ni.add_task(t1)
        # mutating the original task's status must not affect node accounting
        t1.status = TaskStatus.SUCCEEDED
        ni.remove_task(t1)  # looked up by key; uses held clone's status
        assert ni.idle.milli_cpu == 8000

    def test_releasing(self):
        ni = NodeInfo(build_node("n1", build_resource_list("8", "16Gi")))
        pod = build_pod("ns1", "t1", "n1", objects.POD_PHASE_RUNNING,
                        build_resource_list("2", "1Gi"), "pg1")
        pod.metadata.deletion_timestamp = 1.0
        ti = new_task_info(pod)
        assert ti.status == TaskStatus.RELEASING
        ni.add_task(ti)
        assert ni.releasing.milli_cpu == 2000
        assert ni.idle.milli_cpu == 6000
        ni.remove_task(ti)
        assert ni.releasing.milli_cpu == 0
        assert ni.idle.milli_cpu == 8000

    def test_pipelined_consumes_releasing(self):
        ni = NodeInfo(build_node("n1", build_resource_list("8", "16Gi")))
        rel = task("rel", "4000m")
        rel.status = TaskStatus.RELEASING
        ni.add_task(rel)
        assert ni.releasing.milli_cpu == 4000
        pip = task("pip", "3000m")
        pip.status = TaskStatus.PIPELINED
        ni.add_task(pip)
        # pipelined task eats into releasing, not idle
        assert ni.releasing.milli_cpu == 1000
        assert ni.idle.milli_cpu == 4000
        assert ni.used.milli_cpu == 7000

    def test_out_of_sync_on_overalloc(self):
        ni = NodeInfo(build_node("n1", build_resource_list("2", "4Gi")))
        with pytest.raises(RuntimeError):
            ni.add_task(task("big", "4000m"))
        assert not ni.ready()
        assert ni.state.reason == "OutOfSync"

    def test_duplicate_add_rejected(self):
        ni = NodeInfo(build_node("n1", build_resource_list("8", "16Gi")))
        ni.add_task(task("t1"))
        with pytest.raises(RuntimeError):
            ni.add_task(task("t1"))

    def test_not_ready_node(self):
        n = build_node("n1", build_resource_list("8", "16Gi"))
        n.status.conditions = [objects.NodeCondition(type="Ready", status="False")]
        ni = NodeInfo(n)
        assert not ni.ready()
        assert ni.state.phase == NodePhase.NOT_READY

    def test_set_node_recomputes(self):
        small = build_node("n1", build_resource_list("4", "8Gi"))
        ni = NodeInfo(small)
        ni.add_task(task("t1", "2000m"))
        bigger = build_node("n1", build_resource_list("16", "32Gi"))
        ni.set_node(bigger)
        assert ni.allocatable.milli_cpu == 16000
        assert ni.idle.milli_cpu == 14000
        assert ni.used.milli_cpu == 2000
