"""The rounds solver's diminishing-returns exit (rounds.py capped path):
capped stragglers are placed by the in-program sequential tail pass
(tail_pass) when the kernel models them; anything the tail cannot finish
(overused-gated tasks, stripped gangs) is marked assign=-2, folded into
residue accounting, and retried by the allocate action's serial residue
pass the SAME session — complete outcomes, invariants intact,
rollback-retired jobs not re-dumped.

Also pins the keyed-binder pod contract both ways: a binder that declines
pod objects (KEYED_NEEDS_PODS=False) gets pods=None; one that does not
declare gets the full pods list aligned with keys/hosts.
"""

from __future__ import annotations

from collections import defaultdict

import pytest

from tests.helpers import close_session, open_session
from volcano_tpu.scheduler.framework import get_action
from volcano_tpu.scheduler.util.test_utils import FakeBinder


def _run_cfg6(cache, tiers, actions):
    ssn = open_session(cache, tiers)
    assert ssn.batch_allocator is not None
    ssn.batch_allocator.mode = "rounds"
    for name in actions:
        get_action(name).execute(ssn)
    prof = dict(ssn.plugins["tpuscore"].profile)
    close_session(ssn)
    return prof


class TestRoundCap:
    def test_capped_leftovers_complete_via_device_tail(self):
        """At the affinity bench's shape the solve exits early (capped);
        the in-program tail pass (with the serial residue as backstop for
        whatever it cannot model) must finish the stragglers: full binds,
        anti-affinity exclusion intact."""
        from volcano_tpu.bench.clusters import build_config

        cache, _, tiers, actions, n = build_config(6, 0.4)
        prof = _run_cfg6(cache, tiers, actions)
        assert prof.get("mode") == "rounds"
        capped = prof.get("round_capped_tasks", 0)
        # the explicit capped flag, not tail_placed: the straggler rounds
        # (rounds.py) can legitimately drain the whole remainder before the
        # sequential tail sees it
        assert prof.get("round_capped"), \
            "expected the diminishing-returns exit to fire"
        # whatever the tail left (-2) is residue for the serial pass; the
        # session outcome must still be COMPLETE either way
        assert prof.get("residue", 0) >= capped
        assert len(cache.binder.binds) == n
        # required anti-affinity: no two same-app pods share a node
        app_nodes = defaultdict(lambda: defaultdict(int))
        for job in cache.jobs.values():
            for t in job.tasks.values():
                pod = t.pod
                if pod is not None and "app" in pod.metadata.labels \
                        and t.node_name:
                    app_nodes[pod.metadata.labels["app"]][t.node_name] += 1
        violations = [
            (app, node, c)
            for app, m in app_nodes.items()
            for node, c in m.items() if c > 1
        ]
        assert not violations, violations[:3]

    def test_capped_run_matches_uncapped_outcome(self):
        """Disabling the cap (min_progress=0) must place the same pod SET —
        only WHICH engine (device round vs serial pass) places the tail
        may differ."""
        from volcano_tpu.bench.clusters import build_config

        cache, _, tiers, actions, n = build_config(6, 0.3)
        _run_cfg6(cache, tiers, actions)
        capped_binds = dict(cache.binder.binds)

        # faithful no-cap twin: neutralize the floor the solver stamps
        src_attr = "round_min_progress"
        from volcano_tpu.ops.kernels import SolveSpec

        orig_replace = SolveSpec._replace

        def patched_replace(spec, **kw):
            kw[src_attr] = 0
            return orig_replace(spec, **kw)

        cache2, _, tiers2, actions2, n2 = build_config(6, 0.3)
        SolveSpec._replace = patched_replace
        try:
            _run_cfg6(cache2, tiers2, actions2)
        finally:
            SolveSpec._replace = orig_replace
        assert set(capped_binds) == set(cache2.binder.binds)
        assert len(capped_binds) == n


class TestKeyedBinderPodContract:
    @pytest.mark.parametrize("needs_pods", [False, True])
    def test_keyed_binder_pod_delivery(self, needs_pods):
        """want_pods routing: a binder that declines pods gets pods=None;
        a declaring-nothing binder gets the aligned pods list (the default
        production path through fastapply's pod-extraction branch)."""
        from volcano_tpu.bench.clusters import build_config

        seen = {}

        class RecordingBinder(FakeBinder):
            def bind_many_keyed(self, keys, pods, hosts):
                seen["pods"] = pods
                seen["keys"] = list(keys)
                super().bind_many_keyed(keys, pods, hosts)

        if needs_pods:
            RecordingBinder.KEYED_NEEDS_PODS = True

        cache, _, tiers, actions, n = build_config(2, 0.5)
        cache.binder = RecordingBinder()
        ssn = open_session(cache, tiers)
        ssn.batch_allocator.mode = "rounds"
        for name in actions:
            get_action(name).execute(ssn)
        close_session(ssn)
        assert len(cache.binder.binds) == n
        assert len(seen["keys"]) == n
        if needs_pods:
            assert seen["pods"] is not None and len(seen["pods"]) == n
            # pods aligned with keys
            for key, pod in zip(seen["keys"][:50], seen["pods"][:50]):
                assert key == f"{pod.metadata.namespace}/{pod.metadata.name}"
        else:
            assert seen["pods"] is None
