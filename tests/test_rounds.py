"""Rounds-mode solver invariants.

Rounds mode trades the serial loop's visit-granular ordering for bulk
placement (ops/rounds.py), so bindings are not bit-identical to the oracle.
These tests assert what IS guaranteed: feasibility of every placement under
the epsilon arithmetic and predicate masks, node capacity and pod-count
limits, gang all-or-nothing atomicity, and placement quality (>= the serial
loop's bind count on capacity-abundant clusters, since rounds mode sees every
node where the serial loop samples).
"""

from __future__ import annotations

import random

from tests.helpers import make_cache, make_tiers
from tests.test_tpu_parity import DEFAULT_TIERS, gang_cluster
from volcano_tpu.api import objects
from volcano_tpu.api.resource import Resource
from volcano_tpu.scheduler.framework import close_session, get_action, open_session
from volcano_tpu.utils.jaxcompile import CompileWatcher
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list_with_pods,
)

ROUNDS_ARGS = {"tpuscore": {"tpuscore.mode": "rounds"}}


def run_rounds(populate, tiers=DEFAULT_TIERS):
    cache = make_cache()
    populate(cache)
    ssn = open_session(
        cache, make_tiers(["tpuscore"], *tiers, arguments=ROUNDS_ARGS))
    get_action("allocate").execute(ssn)
    prof = dict(ssn.plugins["tpuscore"].profile)
    assert prof.get("mode") == "rounds", prof
    assert "fallback" not in prof, prof
    close_session(ssn)
    return cache, prof


def run_serial(populate, tiers=DEFAULT_TIERS):
    cache = make_cache()
    populate(cache)
    ssn = open_session(cache, make_tiers(*tiers))
    get_action("allocate").execute(ssn)
    close_session(ssn)
    return cache.binder.binds


def check_invariants(cache, populate_min_members):
    """Feasibility + gang atomicity over the FakeBinder result."""
    binds = cache.binder.binds
    # rebuild node capacity from the cache's own node infos
    per_node = {}
    for key, node_name in binds.items():
        per_node.setdefault(node_name, []).append(key)
    for node_name, keys in per_node.items():
        node = cache.nodes[node_name]
        total = Resource.empty()
        for key in keys:
            ns, name = key.split("/")
            pg = name.rsplit("-", 1)[0]
            job = cache.jobs[f"{ns}/{pg}"]
            task = next(t for t in job.tasks.values() if t.name == name)
            total.add(task.resreq)
        assert total.less_equal(node.allocatable), (
            f"node {node_name} over-allocated: {total} > {node.allocatable}")
        assert len(keys) <= node.allocatable.max_task_num

    # gang all-or-nothing
    counts = {}
    for key in binds:
        pg = key.split("/")[1].rsplit("-", 1)[0]
        counts[pg] = counts.get(pg, 0) + 1
    for pg, n in counts.items():
        assert n >= populate_min_members, f"gang {pg} bound {n} < min"


class TestRounds:
    def test_gang_atomicity_and_feasibility(self):
        populate = gang_cluster(n_groups=20, min_member=4, n_nodes=6)
        cache, prof = run_rounds(populate)
        check_invariants(cache, 4)
        assert prof["rounds"] >= 1

    def test_matches_serial_quality_when_abundant(self):
        # with abundant capacity both backends must place every task
        populate = gang_cluster(n_groups=10, min_member=4, n_nodes=20)
        serial = run_serial(populate)
        cache, _ = run_rounds(populate)
        assert len(cache.binder.binds) == len(serial) == 40

    def test_quality_at_contention(self):
        # tight capacity: rounds mode must bind at least as many whole gangs
        # as the serial loop does (it sees all nodes, never samples)
        populate = gang_cluster(n_groups=24, min_member=4, n_nodes=5)
        serial = run_serial(populate)
        cache, _ = run_rounds(populate)
        check_invariants(cache, 4)
        assert len(cache.binder.binds) >= len(serial) * 0.9

    def test_no_capacity_binds_nothing(self):
        def populate(c):
            c.add_queue(build_queue("default"))
            c.add_pod_group(build_pod_group("pg1", namespace="ns1", min_member=3))
            for i in range(3):
                c.add_pod(build_pod("ns1", f"pg1-p{i}", "", objects.POD_PHASE_PENDING,
                                    {"cpu": "4", "memory": "4Gi"}, "pg1"))
            c.add_node(build_node("n1", build_resource_list_with_pods("4", "8Gi")))

        cache, _ = run_rounds(populate)
        assert cache.binder.binds == {}

    def test_selectors_respected(self):
        def populate(c):
            c.add_queue(build_queue("default"))
            for g, zone in enumerate(["a", "b", "a", "b"]):
                pg = f"pg{g}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1", min_member=2))
                for i in range(2):
                    c.add_pod(build_pod("ns1", f"{pg}-p{i}", "",
                                        objects.POD_PHASE_PENDING,
                                        {"cpu": "1", "memory": "1Gi"}, pg,
                                        node_selector={"zone": zone}))
            for n in range(4):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("4", "8Gi"),
                    labels={"zone": "a" if n < 2 else "b"}))

        cache, _ = run_rounds(populate)
        assert len(cache.binder.binds) == 8
        for key, node in cache.binder.binds.items():
            g = int(key.split("/")[1][2])
            want = "a" if g % 2 == 0 else "b"
            n = int(node.split("-")[1])
            assert (n < 2) == (want == "a"), f"{key} on wrong zone node {node}"

    def test_fair_share_multi_queue(self):
        # 2 queues, equal weight, demand 2x capacity: each queue should land
        # roughly half the bindings through the overused gate
        def populate(c):
            rng = random.Random(9)
            c.add_queue(build_queue("q-a", weight=1))
            c.add_queue(build_queue("q-b", weight=1))
            for g in range(16):
                q = "q-a" if g % 2 == 0 else "q-b"
                pg = f"pg{g:02d}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1",
                                                min_member=2, queue=q))
                for i in range(2):
                    c.add_pod(build_pod("ns1", f"{pg}-p{i}", "",
                                        objects.POD_PHASE_PENDING,
                                        {"cpu": "1", "memory": "1Gi"}, pg))
            for n in range(4):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("4", "8Gi")))

        cache, _ = run_rounds(populate, tiers=(["priority", "gang"],
                                               ["drf", "proportion"]))
        by_queue = {"q-a": 0, "q-b": 0}
        for key in cache.binder.binds:
            g = int(key.split("/")[1][2:4])
            by_queue["q-a" if g % 2 == 0 else "q-b"] += 1
        total = sum(by_queue.values())
        assert total > 0
        assert abs(by_queue["q-a"] - by_queue["q-b"]) <= 4, by_queue


class TestInt32OverflowExactness:
    """Regression: per-segment cumulative request sums can exceed 2^31
    quantized units (e.g. 50k tasks x 64-core requests in one queue
    segment); a wrapped int32 cumsum went negative and passed the
    budget/fit comparisons. rounds._seg_limbs keeps the sums exact as
    two 15-bit limbs."""

    def test_queue_budget_exact_past_int32(self):
        import jax.numpy as jnp
        from volcano_tpu.ops import rounds as R

        t = 70
        req = 36_000_000  # 36k cores in milli-cpu: 60 of these wrap int32
        enc = {
            "is_scalar": jnp.array([False]),
            "res_unit": jnp.array([1.0]),
            "eps": jnp.array([10.0]),
            "task_req": jnp.full((t, 1), float(req)),
            "queue_deserved": jnp.array([[2.0e9]]),
        }
        accept = jnp.ones(t, bool)
        task_rank = jnp.arange(t, dtype=jnp.int32)
        task_queue = jnp.zeros(t, jnp.int32)
        task_job = jnp.arange(t, dtype=jnp.int32)  # one job per task
        out = R._queue_budget(enc, jnp.zeros((1, 1)), accept,
                              task_rank, task_queue, task_job)
        got = int(jnp.sum(out))
        # jobs 0..55 see alloc_before = k*36e6 < 2e9 + 10; job 56 is the
        # first over; a wrapped cumsum would re-admit jobs >= 60
        assert got == 56, got
        assert not bool(out[60]), "wrapped cumsum re-admitted job 60"

    def test_resolve_exact_past_int32(self):
        import jax.numpy as jnp
        from volcano_tpu.ops import rounds as R
        from volcano_tpu.ops.kernels import SolveSpec

        t = 70
        spec = SolveSpec(job_order_keys=("priority",), use_drf_ns_order=False,
                         use_prop_queue_order=False, use_prop_overused=False,
                         check_pod_count=False, use_binpack=False,
                         use_nodeorder=False)
        enc = {
            "is_scalar": jnp.array([False]),
            "res_unit": jnp.array([1.0]),
            "eps": jnp.array([10.0]),
            "task_req": jnp.full((t, 1), 36_000_000.0),
            "task_has_pod": jnp.zeros(t, bool),
        }
        idle = jnp.array([[40_000_000.0]])  # fits exactly one task
        choice = jnp.zeros(t, jnp.int32)    # everyone picks node 0
        task_rank = jnp.arange(t, dtype=jnp.int32)
        accept = R._resolve(spec, enc, idle, jnp.zeros(1, jnp.int32),
                            choice, task_rank)
        assert int(jnp.sum(accept)) == 1, int(jnp.sum(accept))
        assert bool(accept[0])


class TestRoundsPluginGate:
    def test_custom_plugin_forces_serial_fallback(self):
        """A plugin outside ROUNDS_SAFE_PLUGINS (even one contributing only
        event handlers, invisible to the encoder's extension-point checks)
        must not be silently dropped by the statement-free bulk apply."""
        from volcano_tpu.scheduler.framework import plugins as plugin_registry
        from volcano_tpu.scheduler.framework.interface import Plugin

        class EventOnlyPlugin(Plugin):
            def name(self):
                return "event_only_test"

            def on_session_open(self, ssn):
                pass

            def on_session_close(self, ssn):
                pass

        plugin_registry.register_plugin_builder(
            "event_only_test", lambda args: EventOnlyPlugin())
        try:
            def populate(c):
                c.add_queue(build_queue("default"))
                c.add_pod_group(build_pod_group("pg0", namespace="ns1",
                                                min_member=2))
                for i in range(4):
                    c.add_pod(build_pod("ns1", f"pg0-p{i}", "",
                                        objects.POD_PHASE_PENDING,
                                        {"cpu": "1", "memory": "1Gi"}, "pg0"))
                c.add_node(build_node(
                    "node-000", build_resource_list_with_pods("8", "16Gi")))

            cache = make_cache()
            populate(cache)
            ssn = open_session(cache, make_tiers(
                ["tpuscore"], ["priority", "gang", "event_only_test"],
                arguments=ROUNDS_ARGS))
            get_action("allocate").execute(ssn)
            prof = dict(ssn.plugins["tpuscore"].profile)
            close_session(ssn)
            assert "fallback" in prof, prof
            assert "event_only_test" in prof["fallback"], prof
            # the serial loop still binds everything
            assert len(cache.binder.binds) == 4
        finally:
            plugin_registry._plugin_builders.pop("event_only_test", None)

    def test_seg_limbs_exact_past_lo_limb_wrap(self):
        """70k rows of 64-core requests: the naive cumsum of even the SPLIT
        lo limbs wraps int32 (~2.19e9); the carry-normalizing scan must
        report the exact total."""
        import jax.numpy as jnp
        from volcano_tpu.ops import rounds as R

        t = 70_000
        req = jnp.full((t, 1), 64_000, jnp.int32)
        start_idx = jnp.zeros(t, jnp.int32)  # one segment
        hi, lo = R._seg_limbs(req, start_idx)
        total = int(hi[-1, 0]) * 32768 + int(lo[-1, 0])
        assert total == 70_000 * 64_000, total
        assert int(lo[-1, 0]) < 32768


class TestRoundsResidue:
    """The EncoderFallback cliff is gone in rounds mode: un-modeled
    constructs degrade to a per-task serial residue pass (or host-side
    masks), never a whole-session serial outage."""

    def _affinity(self, labels):
        return objects.Affinity(
            pod_anti_affinity=objects.PodAntiAffinity(required_terms=[
                objects.PodAffinityTerm(
                    label_selector=objects.LabelSelector(match_labels=labels),
                    topology_key="kubernetes.io/hostname",
                )
            ])
        )

    def test_affinity_task_as_residue(self):
        """One anti-affinity pod among plain gangs: bulk solves the gangs,
        the serial pass places the affinity pod — no session fallback."""
        def populate(c):
            c.add_queue(build_queue("default"))
            for g in range(6):
                pg = f"pg{g}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1", min_member=2))
                for i in range(2):
                    c.add_pod(build_pod("ns1", f"{pg}-p{i}", "",
                                        objects.POD_PHASE_PENDING,
                                        {"cpu": "1", "memory": "1Gi"}, pg))
            c.add_pod_group(build_pod_group("pga", namespace="ns1", min_member=1))
            pod = build_pod("ns1", "pga-p0", "", objects.POD_PHASE_PENDING,
                            {"cpu": "1", "memory": "1Gi"}, "pga",
                            labels={"app": "solo"})
            pod.spec.affinity = self._affinity({"app": "solo"})
            c.add_pod(pod)
            for n in range(4):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("8", "16Gi")))

        cache, prof = run_rounds(populate)
        # the qualifying (hostname self-anti) pod is PROMOTED into a device
        # exclusion group — no residue pass at all
        assert prof.get("residue") == 0, prof
        assert len(cache.binder.binds) == 13  # 12 gang + 1 exclusion-group
        assert "ns1/pga-p0" in cache.binder.binds

    def test_zone_affinity_task_stays_residue(self):
        """Non-hostname topology does not qualify for device exclusion
        groups: the pod goes through the serial residue pass as before."""
        def populate(c):
            c.add_queue(build_queue("default"))
            for g in range(4):
                pg = f"pg{g}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1", min_member=2))
                for i in range(2):
                    c.add_pod(build_pod("ns1", f"{pg}-p{i}", "",
                                        objects.POD_PHASE_PENDING,
                                        {"cpu": "1", "memory": "1Gi"}, pg))
            c.add_pod_group(build_pod_group("pgz", namespace="ns1", min_member=1))
            pod = build_pod("ns1", "pgz-p0", "", objects.POD_PHASE_PENDING,
                            {"cpu": "1", "memory": "1Gi"}, "pgz",
                            labels={"app": "zoned"})
            pod.spec.affinity = objects.Affinity(
                pod_anti_affinity=objects.PodAntiAffinity(required_terms=[
                    objects.PodAffinityTerm(
                        label_selector=objects.LabelSelector(
                            match_labels={"app": "zoned"}),
                        topology_key="zone")]))
            c.add_pod(pod)
            for n in range(4):
                c.add_node(build_node(
                    f"node-{n:03d}",
                    build_resource_list_with_pods("8", "16Gi"),
                    labels={"zone": f"z{n % 2}"}))

        cache, prof = run_rounds(populate)
        assert prof.get("residue") == 1, prof
        assert "ns1/pgz-p0" in cache.binder.binds

    def test_host_port_tasks_as_residue(self):
        """Two pods wanting the same host port land on different nodes via
        the serial residue pass."""
        def populate(c):
            c.add_queue(build_queue("default"))
            for k in range(2):
                pg = f"pgp{k}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1", min_member=1))
                pod = build_pod("ns1", f"{pg}-p0", "", objects.POD_PHASE_PENDING,
                                {"cpu": "1", "memory": "1Gi"}, pg)
                pod.spec.containers[0].ports = [
                    objects.ContainerPort(host_port=8080)]
                c.add_pod(pod)
            # filler gang so the bulk solve has work
            c.add_pod_group(build_pod_group("pgf", namespace="ns1", min_member=2))
            for i in range(2):
                c.add_pod(build_pod("ns1", f"pgf-p{i}", "",
                                    objects.POD_PHASE_PENDING,
                                    {"cpu": "1", "memory": "1Gi"}, "pgf"))
            for n in range(2):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("8", "16Gi")))

        cache, prof = run_rounds(populate)
        # single-hostPort pods are PROMOTED into a port exclusion group
        # (at most one (port, protocol) holder per node) — no residue
        assert prof.get("residue") == 0, prof
        binds = cache.binder.binds
        assert len(binds) == 4, binds
        assert binds["ns1/pgp0-p0"] != binds["ns1/pgp1-p0"], binds

    def test_port_pod_matching_label_group_demotes_it(self):
        """A port-promoted pod whose labels match a label group's selector
        is device-placed but invisible to the group's kernel occupancy —
        the closure must demote the label group to residue so the serial
        pass (which sees all residents live) enforces the anti-affinity."""
        def populate(c):
            c.add_queue(build_queue("default"))
            c.add_pod_group(build_pod_group("pga", namespace="ns1", min_member=1))
            pod = build_pod("ns1", "pga-p0", "", objects.POD_PHASE_PENDING,
                            {"cpu": "1", "memory": "1Gi"}, "pga",
                            labels={"app": "solo"})
            pod.spec.affinity = self._affinity({"app": "solo"})
            c.add_pod(pod)
            # port pod carrying the SAME label, no affinity of its own
            c.add_pod_group(build_pod_group("pgp", namespace="ns1", min_member=1))
            ppod = build_pod("ns1", "pgp-p0", "", objects.POD_PHASE_PENDING,
                             {"cpu": "1", "memory": "1Gi"}, "pgp",
                             labels={"app": "solo"})
            ppod.spec.containers[0].ports = [
                objects.ContainerPort(host_port=8080)]
            c.add_pod(ppod)
            c.add_pod_group(build_pod_group("pgf", namespace="ns1", min_member=2))
            for i in range(2):
                c.add_pod(build_pod("ns1", f"pgf-p{i}", "",
                                    objects.POD_PHASE_PENDING,
                                    {"cpu": "1", "memory": "1Gi"}, "pgf"))
            for n in range(3):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("8", "16Gi")))

        cache, prof = run_rounds(populate)
        # the label group demoted (residue); the port pod stays promoted
        assert prof.get("residue") == 1, prof
        binds = cache.binder.binds
        assert len(binds) == 4, binds
        # anti-affinity honored: the two app=solo pods are apart
        assert binds["ns1/pga-p0"] != binds["ns1/pgp-p0"], binds

    def test_multi_port_tasks_stay_residue(self):
        """A pod with TWO host ports exceeds the one-group-per-task kernel
        model and keeps the serial residue path; port conflicts against a
        device-placed single-port pod are still honored (live check)."""
        def populate(c):
            c.add_queue(build_queue("default"))
            for k in range(2):
                pg = f"pgp{k}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1", min_member=1))
                pod = build_pod("ns1", f"{pg}-p0", "", objects.POD_PHASE_PENDING,
                                {"cpu": "1", "memory": "1Gi"}, pg)
                ports = [objects.ContainerPort(host_port=7070)]
                if k == 1:
                    ports.append(objects.ContainerPort(host_port=7071))
                pod.spec.containers[0].ports = ports
                c.add_pod(pod)
            c.add_pod_group(build_pod_group("pgf", namespace="ns1", min_member=2))
            for i in range(2):
                c.add_pod(build_pod("ns1", f"pgf-p{i}", "",
                                    objects.POD_PHASE_PENDING,
                                    {"cpu": "1", "memory": "1Gi"}, "pgf"))
            for n in range(2):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("8", "16Gi")))

        cache, prof = run_rounds(populate)
        assert prof.get("residue") == 1, prof  # only the two-port pod
        binds = cache.binder.binds
        assert len(binds) == 4, binds
        assert binds["ns1/pgp0-p0"] != binds["ns1/pgp1-p0"], binds

    def test_existing_anti_affinity_symmetry_masks_bulk(self):
        """An existing pod's required anti-affinity bars matching bulk pods
        from its node (host-precomputed signature mask, not fallback)."""
        def populate(c):
            c.add_queue(build_queue("default"))
            # existing running pod with anti-affinity against app=web
            c.add_pod_group(build_pod_group("pge", namespace="ns1", min_member=1))
            epod = build_pod("ns1", "pge-p0", "node-000", objects.POD_PHASE_RUNNING,
                             {"cpu": "1", "memory": "1Gi"}, "pge",
                             labels={"app": "guard"})
            epod.spec.affinity = self._affinity({"app": "web"})
            c.add_pod(epod)
            # plain bulk pods labeled app=web
            c.add_pod_group(build_pod_group("pgw", namespace="ns1", min_member=2))
            for i in range(2):
                c.add_pod(build_pod("ns1", f"pgw-p{i}", "",
                                    objects.POD_PHASE_PENDING,
                                    {"cpu": "1", "memory": "1Gi"}, "pgw",
                                    labels={"app": "web"}))
            for n in range(3):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("8", "16Gi")))

        cache, prof = run_rounds(populate)
        binds = cache.binder.binds
        assert len(binds) == 2, binds
        assert all(v != "node-000" for v in binds.values()), binds

    def test_releasing_capacity_pipelines_leftovers(self):
        """A draining node no longer aborts encoding: bulk places what idle
        allows and the serial pass pipelines the leftover onto releasing
        capacity (committed because the job reaches ready via its
        idle-fitting task, allocate.go:238-242 semantics)."""
        from volcano_tpu.api.types import TaskStatus

        def populate(c):
            c.add_queue(build_queue("default"))
            # node-000 free; node-001 fully used by a terminating pod
            c.add_node(build_node("node-000",
                                  build_resource_list_with_pods("4", "8Gi")))
            c.add_node(build_node("node-001",
                                  build_resource_list_with_pods("4", "8Gi")))
            c.add_pod_group(build_pod_group("pgr", namespace="ns1", min_member=1))
            rpod = build_pod("ns1", "pgr-p0", "node-001", objects.POD_PHASE_RUNNING,
                             {"cpu": "4", "memory": "8Gi"}, "pgr")
            rpod.metadata.deletion_timestamp = 1.0
            c.add_pod(rpod)
            # 2-task job (min=1): one task fits idle node-000, the other
            # only fits node-001 once the releasing pod drains
            c.add_pod_group(build_pod_group("pgn", namespace="ns1", min_member=1))
            for i in range(2):
                c.add_pod(build_pod("ns1", f"pgn-p{i}", "",
                                    objects.POD_PHASE_PENDING,
                                    {"cpu": "4", "memory": "8Gi"}, "pgn"))

        cache = make_cache()
        populate(cache)
        ssn = open_session(
            cache, make_tiers(["tpuscore"], *DEFAULT_TIERS, arguments=ROUNDS_ARGS))
        get_action("allocate").execute(ssn)
        prof = dict(ssn.plugins["tpuscore"].profile)
        assert prof.get("has_releasing"), prof
        # one task bound on the idle node; the other pipelined onto the
        # draining one — pipelining is session-local (no binder call), so
        # assert on the session tree before close
        assert list(cache.binder.binds.values()) == ["node-000"], cache.binder.binds
        job = ssn.jobs["ns1/pgn"]
        pip = job.task_status_index.get(TaskStatus.PIPELINED, {})
        assert len(pip) == 1, dict(job.task_status_index)
        assert next(iter(pip.values())).node_name == "node-001"
        close_session(ssn)

    def test_symmetry_distinguishes_labels_within_plain_signature(self):
        """Two plain pods differing only in labels must get independent
        symmetry verdicts (signatures alone don't encode labels; the
        encoder extends keys when symmetry terms are live)."""
        def populate(c):
            c.add_queue(build_queue("default"))
            c.add_pod_group(build_pod_group("pge", namespace="ns1", min_member=1))
            epod = build_pod("ns1", "pge-p0", "node-000", objects.POD_PHASE_RUNNING,
                             {"cpu": "1", "memory": "1Gi"}, "pge",
                             labels={"app": "guard"})
            epod.spec.affinity = self._affinity({"app": "web"})
            c.add_pod(epod)
            # unlabeled plain pod FIRST (becomes the '<plain>' rep without
            # the key extension), labeled app=web pod second
            c.add_pod_group(build_pod_group("pgu", namespace="ns1", min_member=1))
            c.add_pod(build_pod("ns1", "pgu-p0", "", objects.POD_PHASE_PENDING,
                                {"cpu": "4", "memory": "1Gi"}, "pgu"))
            c.add_pod_group(build_pod_group("pgw", namespace="ns1", min_member=1))
            c.add_pod(build_pod("ns1", "pgw-p0", "", objects.POD_PHASE_PENDING,
                                {"cpu": "4", "memory": "1Gi"}, "pgw",
                                labels={"app": "web"}))
            c.add_node(build_node("node-000", build_resource_list_with_pods("9", "16Gi")))
            c.add_node(build_node("node-001", build_resource_list_with_pods("4", "4Gi")))

        cache, prof = run_rounds(populate)
        binds = cache.binder.binds
        assert len(binds) == 2, binds
        assert binds["ns1/pgw-p0"] == "node-001", binds


class TestWarmPath:
    """Steady-state sessions must never retrace: shapes are bucket-padded
    (ops/solver.py _bucket) so identical-bucket snapshots hit the jit cache.
    CompileWatcher.assert_no_compiles makes a retrace fail HERE, not three
    rounds later as a bench regression (bench tpu_warm_compiles)."""

    def test_second_identical_session_does_not_compile(self):
        populate = gang_cluster(n_groups=20, min_member=4, n_nodes=6)
        run_rounds(populate)  # cold run: compiles allowed
        watcher = CompileWatcher.install()
        with watcher.assert_no_compiles("second identical-shape session"):
            cache, prof = run_rounds(populate)
        assert prof["rounds"] >= 1
        check_invariants(cache, 4)

    def test_same_bucket_churn_does_not_compile(self):
        # 80 -> 76 tasks and 20 -> 19 jobs both land in the same buckets
        # (128 / 32): count churn inside a bucket must reuse the program
        run_rounds(gang_cluster(n_groups=20, min_member=4, n_nodes=6))
        watcher = CompileWatcher.install()
        with watcher.assert_no_compiles("same-bucket churned session"):
            cache, _ = run_rounds(gang_cluster(n_groups=19, min_member=4,
                                               n_nodes=6))
        check_invariants(cache, 4)


class TestPolicyShape:
    """Bulk-synchronous placement must still express each scoring policy's
    intent: spreading policies distribute across tied nodes, packing
    policies consolidate (rounds._choices capacity walk + tie rotation)."""

    def _populate(self, c):
        c.add_queue(build_queue("default"))
        for n in range(6):
            c.add_node(build_node(
                f"n{n:02d}", build_resource_list_with_pods("16", "32Gi", pods=64)))
        for g in range(6):
            pg = f"pg{g}"
            c.add_pod_group(build_pod_group(pg, namespace="d", min_member=4))
            for i in range(4):
                c.add_pod(build_pod("d", f"{pg}-{i}", "", objects.POD_PHASE_PENDING,
                                    {"cpu": "1", "memory": "1Gi"}, pg))

    @staticmethod
    def _per_node(cache):
        per = {}
        for _, node in cache.binder.binds.items():
            per[node] = per.get(node, 0) + 1
        return per

    def test_least_requested_spreads_across_tied_nodes(self):
        cache, _ = run_rounds(
            self._populate,
            tiers=(["priority", "gang"],
                   ["drf", "predicates", "proportion", "nodeorder"]))
        per = self._per_node(cache)
        assert sum(per.values()) == 24
        assert len(per) == 6, per  # every identical node used

    def test_binpack_consolidates(self):
        cache, _ = run_rounds(
            self._populate,
            tiers=(["priority", "gang"],
                   ["drf", "predicates", "proportion", "binpack"]))
        per = self._per_node(cache)
        assert sum(per.values()) == 24
        assert len(per) <= 3, per  # fill node by node, not spread
