"""Rounds-mode solver invariants.

Rounds mode trades the serial loop's visit-granular ordering for bulk
placement (ops/rounds.py), so bindings are not bit-identical to the oracle.
These tests assert what IS guaranteed: feasibility of every placement under
the epsilon arithmetic and predicate masks, node capacity and pod-count
limits, gang all-or-nothing atomicity, and placement quality (>= the serial
loop's bind count on capacity-abundant clusters, since rounds mode sees every
node where the serial loop samples).
"""

from __future__ import annotations

import random

from tests.helpers import make_cache, make_tiers
from tests.test_tpu_parity import DEFAULT_TIERS, gang_cluster
from volcano_tpu.api import objects
from volcano_tpu.api.resource import Resource
from volcano_tpu.scheduler.framework import close_session, get_action, open_session
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list_with_pods,
)

ROUNDS_ARGS = {"tpuscore": {"tpuscore.mode": "rounds"}}


def run_rounds(populate, tiers=DEFAULT_TIERS):
    cache = make_cache()
    populate(cache)
    ssn = open_session(
        cache, make_tiers(["tpuscore"], *tiers, arguments=ROUNDS_ARGS))
    get_action("allocate").execute(ssn)
    prof = dict(ssn.plugins["tpuscore"].profile)
    assert prof.get("mode") == "rounds", prof
    assert "fallback" not in prof, prof
    close_session(ssn)
    return cache, prof


def run_serial(populate, tiers=DEFAULT_TIERS):
    cache = make_cache()
    populate(cache)
    ssn = open_session(cache, make_tiers(*tiers))
    get_action("allocate").execute(ssn)
    close_session(ssn)
    return cache.binder.binds


def check_invariants(cache, populate_min_members):
    """Feasibility + gang atomicity over the FakeBinder result."""
    binds = cache.binder.binds
    # rebuild node capacity from the cache's own node infos
    per_node = {}
    for key, node_name in binds.items():
        per_node.setdefault(node_name, []).append(key)
    for node_name, keys in per_node.items():
        node = cache.nodes[node_name]
        total = Resource.empty()
        for key in keys:
            ns, name = key.split("/")
            pg = name.rsplit("-", 1)[0]
            job = cache.jobs[f"{ns}/{pg}"]
            task = next(t for t in job.tasks.values() if t.name == name)
            total.add(task.resreq)
        assert total.less_equal(node.allocatable), (
            f"node {node_name} over-allocated: {total} > {node.allocatable}")
        assert len(keys) <= node.allocatable.max_task_num

    # gang all-or-nothing
    counts = {}
    for key in binds:
        pg = key.split("/")[1].rsplit("-", 1)[0]
        counts[pg] = counts.get(pg, 0) + 1
    for pg, n in counts.items():
        assert n >= populate_min_members, f"gang {pg} bound {n} < min"


class TestRounds:
    def test_gang_atomicity_and_feasibility(self):
        populate = gang_cluster(n_groups=20, min_member=4, n_nodes=6)
        cache, prof = run_rounds(populate)
        check_invariants(cache, 4)
        assert prof["rounds"] >= 1

    def test_matches_serial_quality_when_abundant(self):
        # with abundant capacity both backends must place every task
        populate = gang_cluster(n_groups=10, min_member=4, n_nodes=20)
        serial = run_serial(populate)
        cache, _ = run_rounds(populate)
        assert len(cache.binder.binds) == len(serial) == 40

    def test_quality_at_contention(self):
        # tight capacity: rounds mode must bind at least as many whole gangs
        # as the serial loop does (it sees all nodes, never samples)
        populate = gang_cluster(n_groups=24, min_member=4, n_nodes=5)
        serial = run_serial(populate)
        cache, _ = run_rounds(populate)
        check_invariants(cache, 4)
        assert len(cache.binder.binds) >= len(serial) * 0.9

    def test_no_capacity_binds_nothing(self):
        def populate(c):
            c.add_queue(build_queue("default"))
            c.add_pod_group(build_pod_group("pg1", namespace="ns1", min_member=3))
            for i in range(3):
                c.add_pod(build_pod("ns1", f"pg1-p{i}", "", objects.POD_PHASE_PENDING,
                                    {"cpu": "4", "memory": "4Gi"}, "pg1"))
            c.add_node(build_node("n1", build_resource_list_with_pods("4", "8Gi")))

        cache, _ = run_rounds(populate)
        assert cache.binder.binds == {}

    def test_selectors_respected(self):
        def populate(c):
            c.add_queue(build_queue("default"))
            for g, zone in enumerate(["a", "b", "a", "b"]):
                pg = f"pg{g}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1", min_member=2))
                for i in range(2):
                    c.add_pod(build_pod("ns1", f"{pg}-p{i}", "",
                                        objects.POD_PHASE_PENDING,
                                        {"cpu": "1", "memory": "1Gi"}, pg,
                                        node_selector={"zone": zone}))
            for n in range(4):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("4", "8Gi"),
                    labels={"zone": "a" if n < 2 else "b"}))

        cache, _ = run_rounds(populate)
        assert len(cache.binder.binds) == 8
        for key, node in cache.binder.binds.items():
            g = int(key.split("/")[1][2])
            want = "a" if g % 2 == 0 else "b"
            n = int(node.split("-")[1])
            assert (n < 2) == (want == "a"), f"{key} on wrong zone node {node}"

    def test_fair_share_multi_queue(self):
        # 2 queues, equal weight, demand 2x capacity: each queue should land
        # roughly half the bindings through the overused gate
        def populate(c):
            rng = random.Random(9)
            c.add_queue(build_queue("q-a", weight=1))
            c.add_queue(build_queue("q-b", weight=1))
            for g in range(16):
                q = "q-a" if g % 2 == 0 else "q-b"
                pg = f"pg{g:02d}"
                c.add_pod_group(build_pod_group(pg, namespace="ns1",
                                                min_member=2, queue=q))
                for i in range(2):
                    c.add_pod(build_pod("ns1", f"{pg}-p{i}", "",
                                        objects.POD_PHASE_PENDING,
                                        {"cpu": "1", "memory": "1Gi"}, pg))
            for n in range(4):
                c.add_node(build_node(
                    f"node-{n:03d}", build_resource_list_with_pods("4", "8Gi")))

        cache, _ = run_rounds(populate, tiers=(["priority", "gang"],
                                               ["drf", "proportion"]))
        by_queue = {"q-a": 0, "q-b": 0}
        for key in cache.binder.binds:
            g = int(key.split("/")[1][2:4])
            by_queue["q-a" if g % 2 == 0 else "q-b"] += 1
        total = sum(by_queue.values())
        assert total > 0
        assert abs(by_queue["q-a"] - by_queue["q-b"]) <= 4, by_queue
