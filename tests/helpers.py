"""Shared test scaffolding: build a cache with fakes, open sessions with
explicit tiers (the allocate_test.go:39-223 harness shape).

The builders live in volcano_tpu.bench.clusters so the bench rig and the
test harness can never diverge; this module re-exports them plus the
session lifecycle helpers.
"""

from __future__ import annotations

from volcano_tpu.bench.clusters import make_cache, make_tiers  # noqa: F401
from volcano_tpu.scheduler.framework import open_session, close_session  # noqa: F401
import volcano_tpu.scheduler.actions  # noqa: F401  (register actions)
