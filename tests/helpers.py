"""Shared test scaffolding: build a cache with fakes, open sessions with
explicit tiers (the allocate_test.go:39-223 harness shape)."""

from __future__ import annotations

from volcano_tpu.scheduler import conf
from volcano_tpu.scheduler.cache import SchedulerCache
from volcano_tpu.scheduler.framework import open_session, close_session  # noqa: F401
from volcano_tpu.scheduler.plugins import apply_plugin_conf_defaults
from volcano_tpu.scheduler.util import scheduler_helper
from volcano_tpu.scheduler.util.test_utils import (
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
)
import volcano_tpu.scheduler.actions  # noqa: F401  (register actions)


def make_cache(store=None, **kwargs):
    scheduler_helper.reset_round_robin()
    return SchedulerCache(
        store=store,
        binder=FakeBinder(),
        evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
        volume_binder=FakeVolumeBinder(),
        **kwargs,
    )


def make_tiers(*tier_plugin_names, arguments=None):
    """make_tiers(["priority", "gang"], ["drf", "proportion"]) — with all
    enable flags defaulted True."""
    arguments = arguments or {}
    tiers = []
    for names in tier_plugin_names:
        options = []
        for name in names:
            option = conf.PluginOption(name=name, arguments=arguments.get(name, {}))
            apply_plugin_conf_defaults(option)
            options.append(option)
        tiers.append(conf.Tier(plugins=options))
    return tiers
