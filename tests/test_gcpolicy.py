"""LowLatencyGC (utils/gcpolicy.py): refcounted install/uninstall and
between-cycle maintenance."""

from __future__ import annotations

import gc

from volcano_tpu.utils.gcpolicy import LowLatencyGC


class TestLowLatencyGC:
    def test_install_disables_and_uninstall_restores(self):
        was = gc.isenabled()
        gc.enable()
        try:
            p = LowLatencyGC.install()
            assert not gc.isenabled()
            p.maintain()  # young-gen collect must not re-enable
            assert not gc.isenabled()
            p.uninstall()
            assert gc.isenabled()
        finally:
            (gc.enable if was else gc.disable)()

    def test_refcounted_overlapping_installs(self):
        """Two HA loops: the first uninstall must NOT re-enable automatic
        GC under the survivor; the last one restores the outer state."""
        was = gc.isenabled()
        gc.enable()
        try:
            a = LowLatencyGC.install()
            b = LowLatencyGC.install()
            a.uninstall()
            assert not gc.isenabled(), "survivor still runs under the policy"
            b.uninstall()
            assert gc.isenabled()
        finally:
            (gc.enable if was else gc.disable)()

    def test_double_uninstall_is_idempotent(self):
        was = gc.isenabled()
        gc.enable()
        try:
            a = LowLatencyGC.install()
            b = LowLatencyGC.install()
            a.uninstall()
            a.uninstall()  # second call must not decrement again
            assert not gc.isenabled()
            b.uninstall()
            assert gc.isenabled()
        finally:
            (gc.enable if was else gc.disable)()

    def test_full_collection_on_stride(self):
        was = gc.isenabled()
        try:
            p = LowLatencyGC.install()
            before = gc.get_count()  # noqa: F841 (smoke the API)
            for _ in range(LowLatencyGC.FULL_EVERY):
                p.maintain()  # the stride-th call runs a full collect
            p.uninstall()
        finally:
            (gc.enable if was else gc.disable)()
