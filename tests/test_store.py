"""State store tests: CRUD, watches, admission middleware, events."""

import pytest

from volcano_tpu.api import objects
from volcano_tpu.scheduler.util.test_utils import build_node, build_pod, build_queue, build_resource_list
from volcano_tpu.store import AdmissionError, ConflictError, NotFoundError, Store, WatchHandler


def make_pod(name="p1", ns="default"):
    return build_pod(ns, name, "", objects.POD_PHASE_PENDING,
                     build_resource_list("1", "1Gi"), "pg1")


class TestCrud:
    def test_create_get(self):
        s = Store()
        pod = s.create(make_pod())
        assert pod.metadata.resource_version == 1
        assert s.get("Pod", "default", "p1") is pod

    def test_create_conflict(self):
        s = Store()
        s.create(make_pod())
        with pytest.raises(ConflictError):
            s.create(make_pod())

    def test_update_bumps_version(self):
        s = Store()
        pod = s.create(make_pod())
        pod.status.phase = objects.POD_PHASE_RUNNING
        s.update(pod)
        assert pod.metadata.resource_version == 2

    def test_update_missing(self):
        s = Store()
        with pytest.raises(NotFoundError):
            s.update(make_pod())

    def test_delete(self):
        s = Store()
        s.create(make_pod())
        s.delete("Pod", "default", "p1")
        assert s.try_get("Pod", "default", "p1") is None

    def test_cluster_scoped(self):
        s = Store()
        s.create(build_node("n1", build_resource_list("4", "8Gi")))
        s.create(build_queue("q1"))
        assert s.get("Node", "", "n1").metadata.name == "n1"
        assert s.get("Queue", "", "q1").metadata.name == "q1"

    def test_list_with_namespace_and_selector(self):
        s = Store()
        p = make_pod("a")
        p.metadata.labels["app"] = "x"
        s.create(p)
        s.create(make_pod("b"))
        s.create(make_pod("c", ns="other"))
        assert len(s.list("Pod")) == 3
        assert len(s.list("Pod", namespace="default")) == 2
        assert len(s.list("Pod", selector={"app": "x"})) == 1


class TestWatch:
    def test_watch_events(self):
        s = Store()
        seen = []
        s.watch("Pod", WatchHandler(
            added=lambda o: seen.append(("add", o.metadata.name)),
            updated=lambda old, new: seen.append(("upd", new.metadata.name)),
            deleted=lambda o: seen.append(("del", o.metadata.name)),
        ))
        pod = s.create(make_pod())
        s.update(pod)
        s.delete("Pod", "default", "p1")
        assert seen == [("add", "p1"), ("upd", "p1"), ("del", "p1")]

    def test_watch_replay(self):
        s = Store()
        s.create(make_pod("a"))
        s.create(make_pod("b"))
        seen = []
        s.watch("Pod", WatchHandler(added=lambda o: seen.append(o.metadata.name)))
        assert sorted(seen) == ["a", "b"]


class TestAdmission:
    def test_mutator_then_validator(self):
        s = Store()
        s.register_admission(
            "Pod",
            mutator=lambda p: p.metadata.labels.__setitem__("mutated", "yes"),
            validator=lambda p: None,
        )
        pod = s.create(make_pod())
        assert pod.metadata.labels["mutated"] == "yes"

    def test_validator_rejects(self):
        def reject(pod):
            raise AdmissionError("no")

        s = Store()
        s.register_admission("Pod", validator=reject)
        with pytest.raises(AdmissionError):
            s.create(make_pod())
        assert s.try_get("Pod", "default", "p1") is None


class TestEvents:
    def test_record(self):
        s = Store()
        pod = s.create(make_pod())
        s.record_event(pod, "Warning", "FailedScheduling", "no nodes")
        evs = s.events_for(pod)
        assert len(evs) == 1
        assert evs[0].reason == "FailedScheduling"
