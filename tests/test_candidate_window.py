"""Candidate-window exactness: windowed rounds == full-width rounds, bit
for bit.

The rounds solver's top-k candidate windows + dirty-column rescoring
(ops/rounds.py) are PRUNING devices, not sampling devices: a per-class
coverage bit falls back to a full-width nomination whenever the windowed
answer is not provably identical, so the solve must produce bit-identical
assignments to the full-width solver (window_k=0) on any snapshot. The fuzz
drives cfg2/cfg4/cfg6-shaped randomized clusters — heterogeneous requests
(GPU scalars included), selectors/zones, exclusion groups (required
anti-affinity -> device exclusion classes), overcommitted capacity (gang
rollback fixpoint), multi-queue overused gating, binpack and spreading
score policies, and the diminishing-returns cap + straggler rounds + device
tail — through both solvers and compares raw kernel outputs.

A small deterministic seed subset runs in the default tier-1 gate; the long
randomized sweep is `-m slow` (pytest.ini marker), mirroring the scale-gate
convention.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from tests.helpers import make_cache, make_tiers
from volcano_tpu.api import objects
from volcano_tpu.scheduler.framework import close_session, get_action, open_session
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list_with_pods,
)
from volcano_tpu.utils.jaxcompile import CompileWatcher

ROUNDS_ARGS = {"tpuscore": {"tpuscore.mode": "rounds"}}

# cfg5/cfg4-shaped (spreading), cfg2/cfg6-shaped (packing), cfg3-shaped
TIER_SHAPES = (
    (["priority", "gang"], ["drf", "predicates", "proportion", "nodeorder"]),
    (["priority", "gang"], ["predicates", "binpack", "proportion"]),
    (["priority", "gang"], ["drf", "proportion"]),
)


def _anti_affinity(labels):
    return objects.Affinity(
        pod_anti_affinity=objects.PodAntiAffinity(required_terms=[
            objects.PodAffinityTerm(
                label_selector=objects.LabelSelector(match_labels=labels),
                topology_key="kubernetes.io/hostname",
            )
        ])
    )


def random_cluster(seed: int):
    """cfg2/cfg4/cfg6-shaped randomized snapshot: the exclusion-group,
    rollback, overused-queue, and heterogeneous-class shapes the window's
    coverage fallback must survive."""
    def populate(c):
        rng = random.Random(seed)
        n_nodes = rng.choice([8, 12, 24, 40])
        n_groups = rng.choice([8, 16, 28])
        queues = rng.choice([1, 1, 2])
        tight = rng.random() < 0.4  # overcommit -> gang rollback fixpoint
        for q in range(queues):
            c.add_queue(build_queue(f"q{q}", weight=1 + q))
        for g in range(n_groups):
            pg = f"pg{g:03d}"
            members = rng.choice([2, 3, 4])
            minm = rng.choice([1, members])
            c.add_pod_group(build_pod_group(
                pg, namespace="ns1", min_member=minm,
                queue=f"q{g % queues}"))
            aff_group = rng.random() < 0.25
            for i in range(members):
                req = {
                    "cpu": f"{rng.choice([250, 500, 1000, 2000])}m",
                    "memory": rng.choice(["512Mi", "1Gi", "2Gi"]),
                }
                if rng.random() < 0.2:
                    req["nvidia.com/gpu"] = str(rng.choice([1, 2]))
                sel = ({"zone": rng.choice(["a", "b"])}
                       if rng.random() < 0.3 else None)
                pod = build_pod(
                    "ns1", f"{pg}-p{i}", "", objects.POD_PHASE_PENDING,
                    req, pg, priority=rng.choice([0, 0, 10]),
                    node_selector=sel)
                if aff_group:
                    app = f"aff-{g % 5}"
                    pod.metadata.labels["app"] = app
                    pod.spec.affinity = _anti_affinity({"app": app})
                c.add_pod(pod)
        cpu, mem = ("4", "8Gi") if tight else ("16", "32Gi")
        for n in range(n_nodes):
            c.add_node(build_node(
                f"node-{n:03d}",
                build_resource_list_with_pods(
                    cpu, mem, pods=rng.choice([8, 64]),
                    **({"nvidia.com/gpu": "4"} if n % 3 == 0 else {})),
                labels={"zone": "a" if n % 2 == 0 else "b"}))
    return populate


def _encode(populate, tiers):
    """Snapshot -> padded rounds-kernel arrays + spec (the solver's exact
    prep, minus the float32 cast — tests run x64 so host arithmetic
    matches)."""
    from volcano_tpu.ops.encoder import encode_session
    from volcano_tpu.ops.solver import _ROUNDS_SKIP, pad_encoded

    cache = make_cache()
    populate(cache)
    ssn = open_session(cache, make_tiers(*tiers))
    enc = encode_session(ssn, allow_residue=True)
    arrays = {k: v for k, v in pad_encoded(enc).items()
              if k not in _ROUNDS_SKIP}
    close_session(ssn)
    return enc.spec, arrays


def _solve(spec, arrays):
    from volcano_tpu.ops import rounds as R

    (assign, n_rounds, tail_placed, full_sweeps, capped, hist,
     _touched) = R.solve_rounds(spec, arrays)
    return (np.asarray(assign), int(n_rounds), int(tail_placed),
            int(full_sweeps), bool(capped), np.asarray(hist))


def assert_window_parity(seed, window_k=8, dirty_k=16, min_progress=0,
                         stragglers=0):
    tiers = TIER_SHAPES[seed % len(TIER_SHAPES)]
    spec, arrays = _encode(random_cluster(seed), tiers)
    n = int(arrays["node_idle"].shape[0])
    spec = spec._replace(round_min_progress=min_progress,
                         straggler_rounds=stragglers)
    full = _solve(spec._replace(window_k=0, dirty_k=0), arrays)
    win = _solve(spec._replace(window_k=min(window_k, n),
                               dirty_k=min(dirty_k, n)), arrays)
    assert np.array_equal(full[0], win[0]), (
        f"seed {seed}: windowed bindings diverge from full-width "
        f"({int((full[0] != win[0]).sum())} tasks differ; "
        f"rounds {full[1]} vs {win[1]})")
    # exactness means the whole round TRAJECTORY matches, not just the end
    # state: same round count, same placed-per-round histogram
    assert full[1] == win[1], (seed, full[1], win[1])
    assert np.array_equal(full[5], win[5]), (seed, full[5], win[5])
    return full, win


class TestWindowParityGate:
    """Small deterministic subset — runs in the default tier-1 gate."""

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_windowed_bindings_bit_identical(self, seed):
        assert_window_parity(seed)

    def test_parity_with_cap_and_straggler_rounds(self):
        # diminishing-returns exit + straggler rounds + device tail active
        # in both solvers: trajectories must still match exactly
        assert_window_parity(1, min_progress=2, stragglers=2)
        assert_window_parity(4, min_progress=2, stragglers=2)

    def test_dirty_rescoring_alone_is_exact(self):
        # window off, carried scores + dirty-column rescoring on: isolates
        # the score-maintenance half of the machinery
        tiers = TIER_SHAPES[0]
        spec, arrays = _encode(random_cluster(2), tiers)
        full = _solve(spec._replace(window_k=0, dirty_k=0), arrays)
        dirty = _solve(spec._replace(window_k=0, dirty_k=8), arrays)
        assert np.array_equal(full[0], dirty[0])
        assert full[1] == dirty[1]

    def test_tiny_window_forces_coverage_fallback(self):
        # a 2-wide window cannot cover a class whose demand spans many
        # nodes: the coverage bit must trigger full-width rounds and the
        # result must still be exact
        spec, arrays = _encode(random_cluster(0), TIER_SHAPES[0])
        full = _solve(spec._replace(window_k=0, dirty_k=0), arrays)
        win = _solve(spec._replace(window_k=2, dirty_k=8), arrays)
        assert np.array_equal(full[0], win[0])
        assert win[3] >= 1, "expected full-sweep fallback rounds"


@pytest.mark.slow
class TestWindowParitySweep:
    """The long randomized sweep (-m slow)."""

    @pytest.mark.parametrize("seed", list(range(4, 24)))
    def test_windowed_bindings_bit_identical(self, seed):
        assert_window_parity(seed, window_k=4 + (seed % 3) * 4,
                             dirty_k=8 + (seed % 2) * 8,
                             min_progress=(seed % 3 == 0) and 2 or 0,
                             stragglers=2 if seed % 3 == 0 else 0)


def _window_session_cluster(n_groups, seed=7):
    """A session big enough that the solver's bucket ladder turns candidate
    windows ON (2 * window bucket <= node axis)."""
    def populate(c):
        rng = random.Random(seed)
        c.add_queue(build_queue("default"))
        for g in range(n_groups):
            pg = f"pg{g:03d}"
            c.add_pod_group(build_pod_group(pg, namespace="ns1", min_member=4))
            for i in range(4):
                c.add_pod(build_pod(
                    "ns1", f"{pg}-p{i}", "", objects.POD_PHASE_PENDING,
                    {"cpu": f"{rng.choice([500, 1000, 2000])}m",
                     "memory": "1Gi"}, pg))
        for n in range(128):
            c.add_node(build_node(
                f"node-{n:03d}", build_resource_list_with_pods("8", "16Gi")))
    return populate


def _run_rounds_session(populate):
    cache = make_cache()
    populate(cache)
    ssn = open_session(
        cache, make_tiers(["tpuscore"],
                          ["priority", "gang"],
                          ["predicates", "binpack", "proportion"],
                          arguments=ROUNDS_ARGS))
    get_action("allocate").execute(ssn)
    prof = dict(ssn.plugins["tpuscore"].profile)
    assert prof.get("mode") == "rounds", prof
    close_session(ssn)
    return cache, prof


class TestWindowSessions:
    def test_ladder_enables_window_and_binds_match_full_width(self, monkeypatch):
        cache, prof = _run_rounds_session(_window_session_cluster(40))
        assert prof.get("window_k", 0) > 0, prof
        assert prof.get("rounds", 0) >= 1
        # per-round profile is part of the session record
        assert len(prof.get("round_placed", [])) == prof["rounds"]
        assert sum(prof["round_placed"]) >= len(cache.binder.binds) - \
            prof.get("tail_placed", 0)
        monkeypatch.setenv("VOLCANO_TPU_WINDOW", "0")
        cache0, prof0 = _run_rounds_session(_window_session_cluster(40))
        assert prof0.get("window_k", 1) == 0, prof0
        assert cache.binder.binds == cache0.binder.binds

    def test_same_window_bucket_churn_does_not_compile(self):
        """Window-size bucket transitions are jit re-keys BY DESIGN; what
        must never retrace is count churn that stays inside every bucket —
        including the window/dirty buckets the ladder derives."""
        cache, prof = _run_rounds_session(_window_session_cluster(40))
        assert prof.get("window_k", 0) > 0, prof
        watcher = CompileWatcher.install()
        with watcher.assert_no_compiles("same-window-bucket churned session"):
            cache2, prof2 = _run_rounds_session(_window_session_cluster(38))
        assert prof2.get("window_k") == prof.get("window_k")
        assert prof2.get("dirty_k") == prof.get("dirty_k")


# ---------------------------------------------------------------------------
# mesh-aware window ladder (ROADMAP item 3): window_k/dirty_k size off the
# PER-SHARD node count under a device mesh, with identical bucket keys (and
# therefore identical compiled programs) at 1 device
# ---------------------------------------------------------------------------


def _wf_arrays(nodes, tasks, classes=4, idle=4.0, req=1.0):
    return {
        "node_idle": np.full((nodes, 2), idle),
        "task_cls": (np.arange(tasks) % classes).astype(np.int32),
        "cls_req": np.full((classes, 2), req),
    }


class TestMeshWindowLadder:
    def test_one_device_bucket_keys_unchanged(self):
        """shards=1 must reproduce the pre-mesh ladder exactly — the
        window/dirty buckets are jit keys, so any drift here would
        recompile every single-device deployment on upgrade."""
        from volcano_tpu.ops.solver import _window_fields

        for nodes, tasks in [(1024, 256), (4096, 1024), (512, 64)]:
            arrays = _wf_arrays(nodes, tasks)
            default = _window_fields(arrays)
            assert default == _window_fields(arrays, shards=1), (nodes, tasks)
            assert default["window_k"] > 0, (nodes, tasks, default)

    def test_window_disables_when_shard_slice_too_small(self):
        """A window spanning most of each shard's slice prunes nothing:
        the ladder must judge coverage against the per-shard node count,
        not global N."""
        from volcano_tpu.ops.solver import _window_fields

        arrays = _wf_arrays(128, 64)
        one = _window_fields(arrays, shards=1)
        eight = _window_fields(arrays, shards=8)
        assert one["window_k"] > 0, one
        assert eight == {"window_k": 0, "dirty_k": 0}, eight

    def test_dirty_gather_caps_off_per_shard_count(self):
        """dirty_k's node-count cap shrinks with the shard slice — a
        gather sized off global N would fetch shards x the useful
        columns."""
        from volcano_tpu.ops.solver import _bucket, _window_fields

        arrays = _wf_arrays(8192, 512)
        one = _window_fields(arrays, shards=1)
        eight = _window_fields(arrays, shards=8)
        assert one["window_k"] == eight["window_k"], (one, eight)
        assert eight["dirty_k"] <= one["dirty_k"], (one, eight)
        k = eight["window_k"]
        assert eight["dirty_k"] == min(
            _bucket(max(4 * k, 64)), _bucket(max(8192 // 8 // 8, 64)))

    def test_sharded_session_binds_match_unsharded(self):
        """End-to-end under the 8-device mesh: the mesh-aware ladder may
        pick different (incl. disabled) windows per shard count, but
        bindings must stay bit-identical to the single-device session —
        the coverage machinery's exactness contract is shard-blind."""
        import jax
        from jax.sharding import Mesh

        devs = jax.devices()
        assert len(devs) >= 8, devs
        populate = _window_session_cluster(40)

        def run(mesh):
            cache = make_cache()
            populate(cache)
            ssn = open_session(
                cache, make_tiers(["tpuscore"],
                                  ["priority", "gang"],
                                  ["predicates", "binpack", "proportion"],
                                  arguments=ROUNDS_ARGS))
            if mesh is not None:
                ssn.plugins["tpuscore"].mesh = mesh
                ssn.batch_allocator.mesh = mesh
            get_action("allocate").execute(ssn)
            prof = dict(ssn.plugins["tpuscore"].profile)
            close_session(ssn)
            assert prof.get("mode") == "rounds", prof
            return dict(cache.binder.binds), prof

        sharded, s_prof = run(Mesh(np.array(devs[:8]), ("nodes",)))
        unsharded, u_prof = run(None)
        assert sharded == unsharded
        # the single-device arm ran windowed; the 8-shard arm's 16-node
        # slices disable the window (2k > n_shard) — different program,
        # same bindings
        assert u_prof.get("window_k", 0) > 0, u_prof
        assert s_prof.get("window_k", 1) == 0, s_prof
