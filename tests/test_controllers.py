"""Controller-manager tests: job lifecycle state machine, policy engine,
job plugins, podgroup auto-creation, queue status, TTL GC
(mirrors pkg/controllers/job/job_state_test.go and friends)."""

from __future__ import annotations

from volcano_tpu.api import objects
from volcano_tpu.api.objects import JobAction, JobEvent, JobPhase
from volcano_tpu.controllers.garbagecollector import GarbageCollector
from volcano_tpu.controllers.job import JobController
from volcano_tpu.controllers.job.policies import apply_policies
from volcano_tpu.controllers.apis import Request
from volcano_tpu.controllers.podgroup import PodGroupController
from volcano_tpu.controllers.queue import QueueController
from volcano_tpu.store.store import Store


def make_job(name="job1", namespace="ns1", min_available=2,
             tasks=(("worker", 3),), plugins=None, policies=None,
             task_policies=None, max_retry=3, ttl=None) -> objects.Job:
    specs = []
    for task_name, replicas in tasks:
        specs.append(objects.TaskSpec(
            name=task_name, replicas=replicas,
            template=objects.PodTemplateSpec(
                spec=objects.PodSpec(containers=[objects.Container(
                    name="c", image="busybox",
                    requests={"cpu": "1", "memory": "1Gi"})])),
            policies=list(task_policies or []),
        ))
    job = objects.Job(
        metadata=objects.ObjectMeta(name=name, namespace=namespace),
        spec=objects.JobSpec(
            min_available=min_available,
            tasks=specs,
            plugins=dict(plugins or {}),
            policies=list(policies or []),
            max_retry=max_retry,
            ttl_seconds_after_finished=ttl,
            queue="default",
        ),
    )
    return job


def set_pod_phase(store: Store, namespace: str, name: str, phase: str,
                  exit_code: int = 0) -> None:
    """Simulated kubelet: flip a pod's phase through the store."""
    import copy

    pod = store.get("Pod", namespace, name)
    updated = copy.deepcopy(pod)
    updated.status.phase = phase
    if phase == objects.POD_PHASE_FAILED:
        updated.status.container_statuses = [
            objects.ContainerStatus(name="c", exit_code=exit_code)]
    store.update_status(updated)


def job_phase(store, job):
    return store.get("Job", job.metadata.namespace, job.metadata.name).status.state.phase


class TestJobSync:
    def test_sync_creates_pods_and_podgroup(self):
        store = Store()
        cc = JobController(store)
        job = make_job()
        store.create(job)
        cc.process_all()

        pods = store.list("Pod", namespace="ns1")
        assert len(pods) == 3
        names = {p.metadata.name for p in pods}
        assert names == {"job1-worker-0", "job1-worker-1", "job1-worker-2"}
        for p in pods:
            assert p.metadata.annotations[objects.JOB_NAME_KEY] == "job1"
            assert p.metadata.annotations[objects.TASK_SPEC_KEY] == "worker"
        pg = store.get("PodGroup", "ns1", "job1")
        assert pg.spec.min_member == 2
        assert pg.spec.min_resources["cpu"] == 2.0
        assert job_phase(store, job) == JobPhase.PENDING

    def test_pending_to_running_to_completed(self):
        store = Store()
        cc = JobController(store)
        job = make_job(min_available=2, tasks=(("worker", 2),))
        store.create(job)
        cc.process_all()

        for i in range(2):
            set_pod_phase(store, "ns1", f"job1-worker-{i}", objects.POD_PHASE_RUNNING)
        cc.process_all()
        assert job_phase(store, job) == JobPhase.RUNNING

        for i in range(2):
            set_pod_phase(store, "ns1", f"job1-worker-{i}", objects.POD_PHASE_SUCCEEDED)
        cc.process_all()
        assert job_phase(store, job) == JobPhase.COMPLETED

    def test_scale_replicas_diff(self):
        store = Store()
        cc = JobController(store)
        job = make_job(min_available=1, tasks=(("worker", 3),))
        store.create(job)
        cc.process_all()
        assert len(store.list("Pod", namespace="ns1")) == 3

        # scale down to 1 replica -> extra pods deleted
        import copy

        updated = copy.deepcopy(store.get("Job", "ns1", "job1"))
        updated.spec.tasks[0].replicas = 1
        store.update(updated)
        cc.process_all()
        assert {p.metadata.name for p in store.list("Pod", namespace="ns1")} == {
            "job1-worker-0"}


class TestPolicies:
    def test_pod_failed_restarts_job(self):
        store = Store()
        cc = JobController(store)
        job = make_job(
            min_available=2, tasks=(("worker", 2),),
            policies=[objects.LifecyclePolicy(
                event=JobEvent.POD_FAILED, action=JobAction.RESTART_JOB)])
        store.create(job)
        cc.process_all()
        for i in range(2):
            set_pod_phase(store, "ns1", f"job1-worker-{i}", objects.POD_PHASE_RUNNING)
        cc.process_all()
        assert job_phase(store, job) == JobPhase.RUNNING

        set_pod_phase(store, "ns1", "job1-worker-0", objects.POD_PHASE_FAILED)
        cc.process_all()
        # restarted: back to Pending (pods recreated) and retry counted
        stored = store.get("Job", "ns1", "job1")
        assert stored.status.retry_count == 1
        assert stored.status.state.phase in (JobPhase.PENDING, JobPhase.RUNNING)
        assert len(store.list("Pod", namespace="ns1")) == 2

    def test_exit_code_policy(self):
        job = make_job(policies=[objects.LifecyclePolicy(
            exit_code=137, action=JobAction.TERMINATE_JOB)])
        req = Request(event=JobEvent.POD_FAILED, exit_code=137)
        assert apply_policies(job, req) == JobAction.TERMINATE_JOB
        req = Request(event=JobEvent.POD_FAILED, exit_code=1)
        assert apply_policies(job, req) == JobAction.SYNC_JOB

    def test_task_policies_override_job_policies(self):
        job = make_job(
            tasks=(("worker", 1),),
            policies=[objects.LifecyclePolicy(
                event=JobEvent.POD_FAILED, action=JobAction.ABORT_JOB)],
            task_policies=[objects.LifecyclePolicy(
                event=JobEvent.POD_FAILED, action=JobAction.RESTART_JOB)])
        req = Request(task_name="worker", event=JobEvent.POD_FAILED)
        assert apply_policies(job, req) == JobAction.RESTART_JOB

    def test_stale_version_degrades_to_sync(self):
        job = make_job(policies=[objects.LifecyclePolicy(
            event=JobEvent.POD_FAILED, action=JobAction.RESTART_JOB)])
        job.status.version = 5
        req = Request(event=JobEvent.POD_FAILED, job_version=3)
        assert apply_policies(job, req) == JobAction.SYNC_JOB

    def test_max_retry_fails_job(self):
        store = Store()
        cc = JobController(store)
        job = make_job(
            min_available=1, tasks=(("worker", 1),), max_retry=2,
            policies=[objects.LifecyclePolicy(
                event=JobEvent.POD_FAILED, action=JobAction.RESTART_JOB)])
        store.create(job)
        cc.process_all()

        for _ in range(4):
            pods = store.list("Pod", namespace="ns1")
            if not pods:
                break
            set_pod_phase(store, "ns1", pods[0].metadata.name,
                          objects.POD_PHASE_FAILED)
            cc.process_all()
            if job_phase(store, job) == JobPhase.FAILED:
                break
        assert job_phase(store, job) == JobPhase.FAILED


class TestCommands:
    def test_abort_and_resume(self):
        store = Store()
        cc = JobController(store)
        job = make_job(min_available=1, tasks=(("worker", 2),))
        store.create(job)
        cc.process_all()
        assert len(store.list("Pod", namespace="ns1")) == 2

        # vcctl job suspend == AbortJob Command (cli suspend.go)
        store.create(objects.Command(
            metadata=objects.ObjectMeta(name="abort-job1", namespace="ns1"),
            action=JobAction.ABORT_JOB,
            target_object=objects.OwnerReference(
                kind=objects.Job.KIND, name="job1")))
        cc.process_all()
        assert job_phase(store, job) == JobPhase.ABORTED
        assert store.list("Pod", namespace="ns1") == []
        # command consumed exactly-once
        assert store.list("Command", namespace="ns1") == []

        store.create(objects.Command(
            metadata=objects.ObjectMeta(name="resume-job1", namespace="ns1"),
            action=JobAction.RESUME_JOB,
            target_object=objects.OwnerReference(
                kind=objects.Job.KIND, name="job1")))
        cc.process_all()
        stored = store.get("Job", "ns1", "job1")
        assert stored.status.state.phase in (JobPhase.PENDING, JobPhase.RUNNING)
        assert len(store.list("Pod", namespace="ns1")) == 2


class TestJobPlugins:
    def test_svc_ssh_env(self):
        store = Store()
        cc = JobController(store)
        job = make_job(
            min_available=2, tasks=(("mpimaster", 1), ("mpiworker", 2)),
            plugins={"svc": [], "ssh": [], "env": []})
        store.create(job)
        cc.process_all()

        # hostfile ConfigMap with task host lists (svc.go generateHost)
        cm = store.get("ConfigMap", "ns1", "job1-svc")
        assert cm.data["mpiworker.host"] == (
            "job1-mpiworker-0.job1\njob1-mpiworker-1.job1")
        assert cm.data["mpimaster.host"] == "job1-mpimaster-0.job1"
        # headless service
        svc = store.get("Service", "ns1", "job1")
        assert svc.cluster_ip == "None"
        # ssh keypair configmap
        ssh_cm = store.get("ConfigMap", "ns1", "job1-ssh")
        assert "id_rsa" in ssh_cm.data and "authorized_keys" in ssh_cm.data

        pod = store.get("Pod", "ns1", "job1-mpiworker-1")
        assert pod.spec.hostname == "job1-mpiworker-1"
        assert pod.spec.subdomain == "job1"
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert env["VK_TASK_INDEX"] == "1"
        mounts = [m.mount_path for m in pod.spec.containers[0].volume_mounts]
        assert "/etc/volcano" in mounts and "/root/.ssh" in mounts

    def test_plugin_resources_deleted_on_kill(self):
        store = Store()
        cc = JobController(store)
        job = make_job(min_available=1, tasks=(("w", 1),),
                       plugins={"svc": []})
        store.create(job)
        cc.process_all()
        assert store.try_get("Service", "ns1", "job1") is not None

        store.create(objects.Command(
            metadata=objects.ObjectMeta(name="t", namespace="ns1"),
            action=JobAction.TERMINATE_JOB,
            target_object=objects.OwnerReference(kind="Job", name="job1")))
        cc.process_all()
        assert store.try_get("Service", "ns1", "job1") is None
        assert store.try_get("ConfigMap", "ns1", "job1-svc") is None


class TestPodGroupController:
    def test_bare_pod_gets_podgroup(self):
        store = Store()
        pgc = PodGroupController(store, scheduler_name="volcano")
        pod = objects.Pod(
            metadata=objects.ObjectMeta(name="bare", namespace="ns1"),
            spec=objects.PodSpec(scheduler_name="volcano"))
        pod.metadata.ensure_identity()
        store.create(pod)
        pgc.process_all()

        pod = store.get("Pod", "ns1", "bare")
        group = pod.metadata.annotations[objects.GROUP_NAME_ANNOTATION_KEY]
        pg = store.get("PodGroup", "ns1", group)
        assert pg.spec.min_member == 1
        assert pg.metadata.owner_references[0].name == "bare"

    def test_other_scheduler_ignored(self):
        store = Store()
        pgc = PodGroupController(store, scheduler_name="volcano")
        pod = objects.Pod(
            metadata=objects.ObjectMeta(name="k8s-pod", namespace="ns1"),
            spec=objects.PodSpec(scheduler_name="default-scheduler"))
        pod.metadata.ensure_identity()
        store.create(pod)
        assert pgc.process_all() == 0
        assert store.list("PodGroup", namespace="ns1") == []


class TestQueueController:
    def test_status_aggregation(self):
        store = Store()
        qc = QueueController(store)
        q = objects.Queue(metadata=objects.ObjectMeta(name="default"))
        q.metadata.ensure_identity()
        store.create(q)
        phases = [objects.PodGroupPhase.PENDING, objects.PodGroupPhase.RUNNING,
                  objects.PodGroupPhase.RUNNING, objects.PodGroupPhase.INQUEUE]
        for i, phase in enumerate(phases):
            pg = objects.PodGroup(
                metadata=objects.ObjectMeta(name=f"pg{i}", namespace="ns1"),
                spec=objects.PodGroupSpec(queue="default"),
                status=objects.PodGroupStatus(phase=phase))
            pg.metadata.ensure_identity()
            store.create(pg)
        qc.process_all()
        status = store.get("Queue", "", "default").status
        assert (status.pending, status.running, status.inqueue) == (1, 2, 1)


class TestGarbageCollector:
    def test_ttl_cleanup(self):
        store = Store()
        now = [1000.0]
        gc = GarbageCollector(store, clock=lambda: now[0])
        job = make_job(ttl=60)
        job.status.state.phase = JobPhase.COMPLETED
        job.status.state.last_transition_time = 1000.0
        store.create(job)

        assert gc.process_expired() == 0  # not expired yet
        now[0] = 1061.0
        assert gc.process_expired() == 1
        assert store.try_get("Job", "ns1", "job1") is None

    def test_no_ttl_never_collected(self):
        store = Store()
        now = [1000.0]
        gc = GarbageCollector(store, clock=lambda: now[0])
        job = make_job(ttl=None)
        job.status.state.phase = JobPhase.COMPLETED
        job.status.state.last_transition_time = 1000.0
        store.create(job)
        now[0] = 1e9
        assert gc.process_expired() == 0
        assert store.try_get("Job", "ns1", "job1") is not None
