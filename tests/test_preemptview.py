"""Dense preempt/reclaim view parity (VERDICT r1 weak #4: hybrid-accelerate
preempt/reclaim; reference pkg/scheduler/actions/preempt/preempt.go:45-260,
reclaim.go:42-202).

The dense view must be a pure acceleration: identical candidate streams
(round-robin window + stable score order), identical victim sets, identical
evictions and pipelined placements as the serial closure sweeps.
"""

from __future__ import annotations

import volcano_tpu.scheduler.actions  # noqa: F401 (register actions)
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.bench.clusters import build_config
from volcano_tpu.ops import preemptview
from volcano_tpu.scheduler.framework import close_session, get_action, open_session
from volcano_tpu.scheduler.util import scheduler_helper as helper


def _run_session(tiers_kind: str, scale: float, actions=("allocate", "backfill", "preempt", "reclaim")):
    cache, serial_tiers, tpu_tiers, _, _ = build_config(4, scale)
    tiers = serial_tiers if tiers_kind == "serial" else tpu_tiers
    ssn = open_session(cache, tiers)
    for name in actions:
        get_action(name).execute(ssn)
    pipelined = {
        t.uid: t.node_name
        for job in ssn.jobs.values()
        for t in job.task_status_index.get(TaskStatus.PIPELINED, {}).values()
    }
    bound = dict(cache.binder.binds)
    evicts = list(cache.evictor.evicts)
    close_session(ssn)
    return bound, evicts, pipelined


class TestPreemptReclaimParity:
    def test_full_pipeline_parity_small(self):
        """Serial vs dense-view session (allocate below the rounds threshold
        runs serial in both, so preempt/reclaim inputs are identical):
        bindings, evictions, and pipelined placements must match exactly."""
        s_bound, s_evicts, s_pipe = _run_session("serial", 0.02)
        d_bound, d_evicts, d_pipe = _run_session("tpu", 0.02)
        assert s_bound == d_bound
        assert s_evicts == d_evicts
        assert s_pipe == d_pipe
        assert len(s_evicts) > 0, "config must actually exercise preemption"
        assert len(s_pipe) > 0

    def test_preemption_actually_triggers_midscale(self):
        bound, evicts, pipe = _run_session("tpu", 0.05)
        assert len(evicts) > 0
        assert len(pipe) > 0

    def test_candidates_match_serial_window_and_order(self):
        """view.candidates(task) == predicate_nodes + prioritize + sort_nodes
        for the same rr cursor, task by task."""
        cache, _, tpu_tiers, _, _ = build_config(4, 0.02)
        ssn = open_session(cache, tpu_tiers)
        try:
            view = preemptview.build(ssn)
            assert view is not None
            all_nodes = helper.get_node_list(ssn.nodes)
            tasks = [
                t for job in ssn.jobs.values()
                for t in job.task_status_index.get(TaskStatus.PENDING, {}).values()
                if not t.resreq.is_empty()
            ][:40]
            assert tasks
            for task in tasks:
                rr0 = helper._last_processed_node_index
                found, _ = helper.predicate_nodes(task, all_nodes, ssn.predicate_fn)
                scores = helper.prioritize_nodes(
                    task, found, ssn.batch_node_order_fn,
                    ssn.node_order_map_fn, ssn.node_order_reduce_fn)
                serial_order = [n.name for n in helper.sort_nodes(scores)]
                rr_serial = helper._last_processed_node_index

                helper._last_processed_node_index = rr0
                dense = view.candidates(task)
                assert dense is not None
                assert [n.name for n in dense] == serial_order
                assert helper._last_processed_node_index == rr_serial
        finally:
            close_session(ssn)

    def test_scalar_score_twin_bit_identical(self):
        """_score_one (scalar replay path) must match _scores (vectorized)
        bit-for-bit on every node, including after pipelines mutate state."""
        import numpy as np

        cache, _, tpu_tiers, _, _ = build_config(4, 0.02)
        ssn = open_session(cache, tpu_tiers)
        try:
            view = preemptview.build(ssn)
            tasks = [
                t for job in ssn.jobs.values()
                for t in job.task_status_index.get(TaskStatus.PENDING, {}).values()
                if not t.resreq.is_empty()
            ][:5]
            for k, task in enumerate(tasks):
                if k:  # mutate state between checks
                    view.on_pipeline(view.node_names[k], task)
                rows = view._rows(task)
                assert rows is not None
                aff = rows[1]
                allnodes = np.arange(view.n)
                vec = view._scores(task, allnodes, aff)
                for i in range(view.n):
                    assert view._score_one(task, i, aff) == vec[i], (k, i)
        finally:
            close_session(ssn)

    def test_poison_retires_view_after_fallback_placement(self):
        """A serially-placed un-modeled pod (affinity/ports) makes cached
        masks stale; poison() must force serial for the rest of the action."""
        cache, _, tpu_tiers, _, _ = build_config(4, 0.02)
        ssn = open_session(cache, tpu_tiers)
        try:
            view = preemptview.build(ssn)
            task = next(
                t for job in ssn.jobs.values()
                for t in job.task_status_index.get(TaskStatus.PENDING, {}).values()
                if not t.resreq.is_empty())
            assert view.candidates(task) is not None
            view.poison()
            assert view.candidates(task) is None
            assert view.masked_nodes_in_name_order(task) is None
        finally:
            close_session(ssn)

    def test_view_disabled_without_tpuscore(self):
        cache, serial_tiers, _, _, _ = build_config(4, 0.02)
        ssn = open_session(cache, serial_tiers)
        try:
            assert preemptview.build(ssn) is None
        finally:
            close_session(ssn)

    def test_reclaim_masked_nodes_match_serial(self):
        from volcano_tpu.api.unschedule_info import FitFailure

        cache, _, tpu_tiers, _, _ = build_config(4, 0.02)
        ssn = open_session(cache, tpu_tiers)
        try:
            view = preemptview.build(ssn)
            tasks = [
                t for job in ssn.jobs.values()
                for t in job.task_status_index.get(TaskStatus.PENDING, {}).values()
                if not t.resreq.is_empty()
            ][:10]
            for task in tasks:
                serial = []
                for node in helper.get_node_list(ssn.nodes):
                    try:
                        ssn.predicate_fn(task, node)
                    except FitFailure:
                        continue
                    serial.append(node.name)
                dense = view.masked_nodes_in_name_order(task)
                assert dense is not None
                assert [n.name for n in dense] == serial
        finally:
            close_session(ssn)
