"""Scheduler driver tests: conf loading, hot-reload, periodic run_once
(mirrors the reference's scheduler.go/util.go behavior)."""

from __future__ import annotations

import textwrap

import pytest

from tests.helpers import make_cache
from volcano_tpu.api import objects
from volcano_tpu.scheduler.scheduler import (
    DEFAULT_SCHEDULER_CONF,
    TPU_SCHEDULER_CONF,
    Scheduler,
    load_scheduler_conf,
)
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list_with_pods,
)


class TestConfLoader:
    def test_default_conf(self):
        actions, tiers = load_scheduler_conf(DEFAULT_SCHEDULER_CONF)
        assert [a.name() for a in actions] == ["enqueue", "allocate", "backfill"]
        assert [[p.name for p in t.plugins] for t in tiers] == [
            ["priority", "gang"],
            ["drf", "predicates", "proportion", "nodeorder"],
        ]
        # all flags defaulted True (plugins/defaults.go:24)
        assert tiers[0].plugins[0].enabled_job_order is True
        assert tiers[1].plugins[1].enabled_predicate is True

    def test_flag_override_and_arguments(self):
        conf_str = textwrap.dedent("""
            actions: "allocate"
            tiers:
            - plugins:
              - name: gang
                enableJobOrder: false
              - name: binpack
                arguments:
                  binpack.weight: 5
        """)
        actions, tiers = load_scheduler_conf(conf_str)
        assert [a.name() for a in actions] == ["allocate"]
        gang, binpack = tiers[0].plugins
        assert gang.enabled_job_order is False
        assert gang.enabled_job_ready is True  # others still defaulted
        assert binpack.arguments == {"binpack.weight": "5"}

    def test_unknown_action_raises(self):
        with pytest.raises(KeyError):
            load_scheduler_conf('actions: "teleport"')


def _populate(cache):
    cache.add_queue(build_queue("default"))
    cache.add_pod_group(build_pod_group(
        "pg1", namespace="ns1", min_member=2,
        phase=objects.PodGroupPhase.PENDING))
    for i in range(2):
        cache.add_pod(build_pod("ns1", f"p{i}", "", objects.POD_PHASE_PENDING,
                                {"cpu": "1", "memory": "1Gi"}, "pg1"))
    cache.add_node(build_node("n1", build_resource_list_with_pods("4", "8Gi")))


class TestSchedulerDriver:
    def test_run_once_end_to_end(self):
        # enqueue flips the Pending PodGroup to Inqueue, allocate binds
        cache = make_cache()
        _populate(cache)
        s = Scheduler(cache)
        s.run_once()
        assert len(cache.binder.binds) == 2

    def test_run_once_tpu_conf(self):
        cache = make_cache()
        _populate(cache)
        s = Scheduler(cache, scheduler_conf=TPU_SCHEDULER_CONF)
        s.run_once()
        assert len(cache.binder.binds) == 2

    def test_conf_hot_reload_from_file(self, tmp_path):
        conf_file = tmp_path / "scheduler.yaml"
        conf_file.write_text('actions: "allocate"\ntiers:\n- plugins:\n  - name: gang\n')
        cache = make_cache()
        _populate(cache)
        # PodGroup stays Pending without the enqueue action -> nothing binds
        s = Scheduler(cache, conf_path=str(conf_file))
        s.run_once()
        assert cache.binder.binds == {}
        # rewrite the conf: next cycle picks it up (scheduler.go:77 hot reload)
        conf_file.write_text(DEFAULT_SCHEDULER_CONF)
        s.run_once()
        assert len(cache.binder.binds) == 2

    def test_bad_conf_path_falls_back_to_default(self):
        cache = make_cache()
        _populate(cache)
        s = Scheduler(cache, conf_path="/nonexistent/scheduler.yaml")
        s.run_once()
        assert len(cache.binder.binds) == 2

    def test_periodic_loop(self):
        cache = make_cache()
        _populate(cache)
        s = Scheduler(cache, schedule_period=0.05)
        s.run()
        try:
            assert cache.binder.wait_for_binds(2, timeout=10.0)
        finally:
            s.stop()

    def test_express_loop_places_between_sessions(self):
        """Scheduler(express=True): an eligible arrival binds through the
        express lane during the inter-cycle wait — well before the next
        periodic session would have run — and the following session
        confirms it."""
        cache = make_cache()
        cache.add_node(build_node(
            "n1", build_resource_list_with_pods("8", "16Gi", pods=64)))
        cache.add_queue(build_queue("default"))
        # long period: a bind inside the window proves the express path
        s = Scheduler(cache, schedule_period=5.0, express=True)
        s.run()
        try:
            import time

            time.sleep(0.2)  # let the first session drain the empty queue
            cache.add_pod_group(build_pod_group(
                "svc", namespace="xp", min_member=1))
            cache.add_pod(build_pod(
                "xp", "svc-t0", "", objects.POD_PHASE_PENDING,
                {"cpu": "250m", "memory": "256Mi"}, "svc"))
            assert cache.binder.wait_for_binds(1, timeout=3.0), \
                "express lane did not place within the schedule period"
            assert s.express_lane.counters["placed"] == 1
        finally:
            s.stop()
