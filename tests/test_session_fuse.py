"""Whole-session fused dispatch (ops/session_fuse.py) vs the per-action path.

The contract: within the fuse envelope the chained device program — allocate
rounds -> backfill -> preempt -> reclaim with donated carries and device-
rebuilt heaps — lands EXACTLY the session state the per-action path lands
(`VOLCANO_TPU_FUSE=0`): same bindings/evictions in the same effector order,
same events, same SnapshotKeeper dirty-set consequences (consecutive-session
parity), same drf/proportion shares and preemption metrics. Out-of-envelope
sessions must fall back per-action with a recorded `fuse_fallback` reason
and identical results. Warm fused sessions must reuse every compiled stage
program."""

from __future__ import annotations

import os

import pytest

from tests.helpers import close_session, make_tiers, open_session
from tests.test_evict_kernel import (
    ACTIONS,
    TIER_SETS,
    _overcommit_cluster,
    _session_signature,
)
from volcano_tpu.scheduler.framework import run_actions
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_resource_list_with_pods,
)

# force rounds mode: the fuzz clusters sit far below the auto threshold,
# and the fused chain only engages when allocate runs the packed rounds
# solve (exactly the headline regime)
ARGS = {"tpuscore": {"tpuscore.mode": "rounds"}}


def _run(cache, tiers_spec, fuse_on, monkeypatch, sessions: int = 1,
         actions=ACTIONS):
    import volcano_tpu.ops.victimview as vv

    from volcano_tpu.scheduler import metrics

    monkeypatch.setenv("VOLCANO_TPU_EVICT", "1")
    monkeypatch.setenv("VOLCANO_TPU_FUSE", "1" if fuse_on else "0")
    monkeypatch.setattr(vv.VictimSelector, "MIN_BATCH", 1)
    reg = metrics.registry()
    m0 = (reg.preemption_victims.get(), reg.preemption_attempts.get())
    sig = None
    profs = []
    for _ in range(sessions):
        ssn = open_session(
            cache, make_tiers(["tpuscore"], *tiers_spec, arguments=ARGS))
        try:
            run_actions(ssn, actions)
            sig = _session_signature(ssn)
            profs.append(dict(ssn.plugins["tpuscore"].profile))
        finally:
            close_session(ssn)
    sig["metrics"] = (reg.preemption_victims.get() - m0[0],
                      reg.preemption_attempts.get() - m0[1])
    return sig, dict(cache.binder.binds), list(cache.evictor.evicts), profs


@pytest.mark.parametrize("tiers_spec,seed", [
    (TIER_SETS[0], 11), (TIER_SETS[0], 42), (TIER_SETS[2], 7)])
def test_fused_chain_parity(tiers_spec, seed, monkeypatch):
    """Fused-vs-per-action over randomized overcommitted clusters: task
    statuses/placements, node accounting, job readiness, plugin shares,
    fit errors, preemption metrics, binds and evictions in effector order
    — all equal, and the fused path must actually have run."""
    got = _run(_overcommit_cluster(seed), tiers_spec, True, monkeypatch)
    want = _run(_overcommit_cluster(seed), tiers_spec, False, monkeypatch)
    assert got[0] == want[0], (tiers_spec, seed)
    assert got[1] == want[1]          # binds
    assert got[2] == want[2]          # evictions, in effector order
    prof = got[3][0]
    assert prof.get("fuse") == 1, prof.get("fuse_fallback", prof)
    assert "fuse_fallback" not in prof, prof["fuse_fallback"]
    # the per-action arm must NOT have fused
    assert "fuse" not in want[3][0]


@pytest.mark.slow
@pytest.mark.parametrize("seed", list(range(300, 308)))
def test_fused_chain_parity_wide(seed, monkeypatch):
    """Wider fuzz band: fresh cluster shapes (fresh buckets, fresh
    compiles) across all tier sets."""
    import random

    rng = random.Random(seed * 13)
    kw = dict(nodes=rng.choice([4, 7, 9]),
              running_jobs=rng.choice([8, 14, 18]),
              tasks_per_job=rng.choice([3, 4, 5]),
              queues=rng.choice([2, 3]),
              hi_jobs=rng.choice([3, 5]))
    tiers_spec = TIER_SETS[seed % len(TIER_SETS)]
    got = _run(_overcommit_cluster(seed, **kw), tiers_spec, True,
               monkeypatch)
    want = _run(_overcommit_cluster(seed, **kw), tiers_spec, False,
                monkeypatch)
    assert got[0] == want[0], (kw, tiers_spec)
    assert got[1] == want[1]
    assert got[2] == want[2]


def test_consecutive_sessions_parity_with_honest_fallback(monkeypatch):
    """Two back-to-back sessions on one cache: the first session's
    evictions leave releasing capacity, which is OUTSIDE the fuse envelope
    (the allocate serial pipeline pass would run between stages) — the
    second session must fall back per-action with a recorded reason, and
    end-state parity must hold through the SnapshotKeeper dirty-sets."""
    tiers = TIER_SETS[0]
    got = _run(_overcommit_cluster(21), tiers, True, monkeypatch,
               sessions=2)
    want = _run(_overcommit_cluster(21), tiers, False, monkeypatch,
                sessions=2)
    assert got[0] == want[0]
    assert got[1] == want[1]
    assert got[2] == want[2]
    assert got[3][0].get("fuse") == 1
    assert "releasing" in got[3][1].get("fuse_fallback", ""), got[3][1]


def test_warm_fused_session_pins_no_compiles(monkeypatch):
    """A second identically-shaped fused session must reuse every compiled
    stage program (bucketed shapes + static specs/layouts/sizes)."""
    from volcano_tpu.utils.jaxcompile import CompileWatcher

    tiers = TIER_SETS[0]
    _run(_overcommit_cluster(11), tiers, True, monkeypatch)
    watcher = CompileWatcher.install()
    with watcher.assert_no_compiles("warm fused session"):
        got = _run(_overcommit_cluster(11), tiers, True, monkeypatch)
    assert got[3][0].get("fuse") == 1


def test_env_flag_restores_per_action_path(monkeypatch):
    """VOLCANO_TPU_FUSE=0 must route through the untouched per-action
    loop: no fuse profile keys at all, batched evict still engaged."""
    got = _run(_overcommit_cluster(11), TIER_SETS[0], False, monkeypatch)
    prof = got[3][0]
    assert "fuse" not in prof and "fuse_fallback" not in prof
    assert "evict_preempt" in prof  # per-action batched evict still ran


def test_scalar_resources_fall_back_per_action(monkeypatch):
    """Scalar dims leave the evict envelope: the chain must record a
    fuse_fallback and produce results identical to the per-action path
    (which itself falls back to the dense/serial ladder)."""
    def cluster():
        cache = _overcommit_cluster(11)
        rl = build_resource_list_with_pods("8", "16Gi", pods=64)
        rl["nvidia.com/gpu"] = "4"
        cache.add_node(build_node("node-gpu", rl))
        return cache

    got = _run(cluster(), TIER_SETS[0], True, monkeypatch)
    want = _run(cluster(), TIER_SETS[0], False, monkeypatch)
    assert got[0] == want[0]
    assert got[1] == want[1]
    assert got[2] == want[2]
    prof = got[3][0]
    assert "fuse" not in prof
    assert "fuse_fallback" in prof, prof


def test_chain_grammar():
    """Only order-respecting chains containing allocate+preempt fuse."""
    from volcano_tpu.ops.session_fuse import _split_chain

    assert _split_chain(("allocate", "backfill", "preempt", "reclaim")) \
        == ([], ["allocate", "backfill", "preempt", "reclaim"])
    assert _split_chain(("enqueue", "allocate", "preempt")) \
        == (["enqueue"], ["allocate", "preempt"])
    assert _split_chain(("allocate",)) is None            # no evict stage
    assert _split_chain(("allocate", "backfill")) is None  # no preempt
    assert _split_chain(("allocate", "preempt", "backfill")) is None
    assert _split_chain(("preempt", "reclaim")) is None   # no allocate
    assert _split_chain(("allocate", "reclaim", "preempt")) is None


def test_fallback_applies_nothing_twice(monkeypatch):
    """When the fused chain falls back mid-way, the per-action rerun must
    not double-apply: total binds/evictions equal the oracle's. Forced by
    an out-of-envelope plugin set (custom preemptable fn -> evict encode
    _Unsupported at build time)."""
    monkeypatch.setenv("VOLCANO_TPU_EVICT", "1")
    monkeypatch.setenv("VOLCANO_TPU_FUSE", "1")

    cache = _overcommit_cluster(11)
    ssn = open_session(
        cache, make_tiers(["tpuscore"], *TIER_SETS[0], arguments=ARGS))
    try:
        ssn.add_preemptable_fn("priority", lambda c, cs: cs)
        run_actions(ssn, ACTIONS)
        prof = ssn.plugins["tpuscore"].profile
        assert "fuse_fallback" in prof, prof
        sig = _session_signature(ssn)
    finally:
        close_session(ssn)

    monkeypatch.setenv("VOLCANO_TPU_FUSE", "0")
    cache2 = _overcommit_cluster(11)
    ssn = open_session(
        cache2, make_tiers(["tpuscore"], *TIER_SETS[0], arguments=ARGS))
    try:
        ssn.add_preemptable_fn("priority", lambda c, cs: cs)
        run_actions(ssn, ACTIONS)
        sig2 = _session_signature(ssn)
    finally:
        close_session(ssn)
    assert sig == sig2
    assert dict(cache.binder.binds) == dict(cache2.binder.binds)
    assert list(cache.evictor.evicts) == list(cache2.evictor.evicts)


def test_devprof_counters_land_in_profile(monkeypatch):
    """The session device-interaction counters (sync points, D2H fetches,
    overlap window) must be collectable around a fused session."""
    from volcano_tpu.utils import devprof

    monkeypatch.setenv("VOLCANO_TPU_EVICT", "1")
    monkeypatch.setenv("VOLCANO_TPU_FUSE", "1")
    cache = _overcommit_cluster(11)
    ssn = open_session(
        cache, make_tiers(["tpuscore"], *TIER_SETS[0], arguments=ARGS))
    prof = {}
    try:
        with devprof.session(prof):
            run_actions(ssn, ACTIONS)
    finally:
        close_session(ssn)
    assert prof["tpu_d2h_fetches"] >= 4   # one per fused stage
    assert prof["tpu_sync_points"] >= prof["tpu_d2h_fetches"]
    assert prof["tpu_overlap_ms"] >= 0.0
