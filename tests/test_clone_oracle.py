"""Fast field-copying clones vs the replay oracle.

NodeInfo.clone / JobInfo.clone copy the incrementally-maintained
accounting instead of re-deriving it through add_task / add_task_info;
clone_replay keeps the original re-derivation path. These tests churn
state through the public mutators (including the fused update paths the
bulk writeback and fasttrans mirror) and assert the two clones are
value-identical — any drift between the incremental sums and the task
set would split them apart.
"""

import random

from volcano_tpu.api import objects
from volcano_tpu.api.job_info import JobInfo, new_task_info
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_resource_list,
)


def _task(name, cpu="1000m", mem="1Gi", phase=objects.POD_PHASE_PENDING,
          node="", group="pg1", scalars=None):
    rl = build_resource_list(cpu, mem)
    if scalars:
        rl.update(scalars)
    pod = build_pod("ns1", name, node, phase, rl, group)
    return new_task_info(pod)


def _res_tuple(r):
    return (r.milli_cpu, r.memory,
            tuple(sorted((k, v) for k, v in (r.scalar_resources or {}).items()
                         if v)))


def _node_state(n):
    return {
        "name": n.name,
        "idle": _res_tuple(n.idle),
        "used": _res_tuple(n.used),
        "releasing": _res_tuple(n.releasing),
        "alloc": _res_tuple(n.allocatable),
        "cap": _res_tuple(n.capability),
        "phase": int(n.state.phase),
        "reason": n.state.reason,
        "tasks": {k: (t.uid, int(t.status), t.node_name,
                      _res_tuple(t.resreq))
                  for k, t in n.tasks.items()},
        "others": id(n.others),
    }


def _job_state(j):
    return {
        "uid": j.uid,
        "name": j.name,
        "queue": j.queue,
        "min_available": j.min_available,
        "alloc": _res_tuple(j.allocated),
        "pend": _res_tuple(j.pending_sum),
        "total": _res_tuple(j.total_request),
        "buckets": {int(k): sorted(v) for k, v in j.task_status_index.items()},
        "tasks": {uid: (int(t.status), t.node_name, _res_tuple(t.resreq))
                  for uid, t in j.tasks.items()},
        "ready": j.ready_task_num(),
        "valid": j.valid_task_num(),
    }


class TestNodeCloneOracle:
    def test_churned_node(self):
        rng = random.Random(7)
        ni = NodeInfo(build_node(
            "n1", build_resource_list("128", "256Gi",
                                      **{"nvidia.com/gpu": "16"})))
        tasks = []
        for i in range(40):
            t = _task(f"t{i}", cpu=f"{rng.choice([500, 1000, 2000])}m",
                      phase=objects.POD_PHASE_RUNNING, node="n1",
                      scalars={"nvidia.com/gpu": "1"} if i % 4 == 0 else None)
            ni.add_task(t)
            tasks.append(t)
        # churn: remove some, flip statuses through update_task (the fused
        # transition path), remove again
        for t in tasks[::3]:
            ni.remove_task(t)
        for t in tasks[1::3]:
            flip = t.shared_clone()
            flip.status = TaskStatus.RELEASING
            ni.update_task(flip)
        fast = ni.clone()
        replay = ni.clone_replay()
        assert _node_state(fast) == _node_state(replay)
        # the clone is independent: mutating it leaves the source intact
        before = _node_state(ni)
        fast.idle.milli_cpu -= 500
        fast.tasks.clear()
        assert _node_state(ni) == before

    def test_empty_and_nodeless(self):
        ni = NodeInfo(build_node("n2", build_resource_list("4", "8Gi")))
        assert _node_state(ni.clone()) == _node_state(ni.clone_replay())
        bare = NodeInfo(None)
        assert _node_state(bare.clone()) == _node_state(bare.clone_replay())


class TestJobCloneOracle:
    def _churned_job(self):
        job = JobInfo("ns1/pg1")
        pg = objects.PodGroup(
            metadata=objects.ObjectMeta(name="pg1", namespace="ns1"),
            spec=objects.PodGroupSpec(min_member=3, queue="default"),
        )
        job.set_pod_group(pg)
        tasks = []
        for i in range(30):
            t = _task(f"t{i}",
                      phase=(objects.POD_PHASE_RUNNING if i % 3 == 0
                             else objects.POD_PHASE_PENDING),
                      node=("n1" if i % 3 == 0 else ""))
            job.add_task_info(t)
            tasks.append(t)
        # fused status churn across the PENDING and allocated boundaries
        for t in tasks[1::5]:
            flip = t.shared_clone()
            job.update_task_status(flip, TaskStatus.ALLOCATED)
        for t in tasks[2::5]:
            flip = t.shared_clone()
            job.update_task_status(flip, TaskStatus.PIPELINED)
        for t in tasks[::6]:
            if t.uid in job.tasks:
                job.delete_task_info(job.tasks[t.uid])
        return job

    def test_churned_job(self):
        job = self._churned_job()
        fast = job.clone()
        replay = job.clone_replay()
        assert _job_state(fast) == _job_state(replay)
        # pending axis: same (uid -> row, row_gen) set, version-valid.
        # Order may differ (fast walks the PENDING bucket, replay the task
        # map) — the encoder lexsorts the axis, so order is immaterial.
        fa, ra = fast.pending_axis(), replay.pending_axis()
        assert fa is not None and ra is not None
        f_map = {t.uid: (r, g) for t, r, g in zip(*fa)}
        r_map = {t.uid: (r, g) for t, r, g in zip(*ra)}
        assert f_map == r_map

    def test_incremental_sums_match_recompute(self):
        from volcano_tpu.api.types import allocated_status

        job = self._churned_job()
        alloc = sum(t.resreq.milli_cpu for t in job.tasks.values()
                    if allocated_status(t.status))
        pend = sum(t.resreq.milli_cpu for t in job.tasks.values()
                   if t.status == TaskStatus.PENDING)
        assert job.allocated.milli_cpu == alloc
        assert job.pending_sum.milli_cpu == pend

    def test_clone_is_independent(self):
        job = self._churned_job()
        fast = job.clone()
        before = _job_state(job)
        # mutate the clone through the public mutators
        any_pending = next(iter(
            job.task_status_index.get(TaskStatus.PENDING, {}).values()), None)
        if any_pending is not None:
            flip = fast.tasks[any_pending.uid].shared_clone()
            fast.update_task_status(flip, TaskStatus.ALLOCATED)
        fast.allocated.milli_cpu += 123
        fast.pending_sum.milli_cpu += 7
        assert _job_state(job) == before
