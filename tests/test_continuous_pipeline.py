"""Continuous scheduling pipeline (volcano_tpu/pipeline) vs the serial loop.

The contract (DESIGN.md §16): for the SAME per-cycle delta trace, the
pipelined loop — double-buffered snapshots, speculative solve-ahead sealed
by a delta fingerprint — lands EXACTLY the cache/effector end state the
serial open->actions->close loop lands, with speculation forced on, forced
off, committed, or discarded. An invalidated speculative stage is never
applied (the discard counters are the accounting proof; the parity fuzz is
the behavioral one), and the stale-at-apply re-check never fires.
"""

from __future__ import annotations

import os
import random

import pytest

from tests.helpers import close_session, make_cache, make_tiers, open_session
from volcano_tpu.api import objects
from volcano_tpu.scheduler.framework import run_actions
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list_with_pods,
)

ACTIONS = ["enqueue", "allocate", "backfill"]
# rounds mode forced: the fuzz clusters sit below the auto threshold, and
# the pipeline only solves ahead when allocate runs the packed rounds
# dispatch (exactly the headline regime)
ARGS = {"tpuscore": {"tpuscore.mode": "rounds"}}
TIERS_SPEC = (["tpuscore"], ["priority", "gang"],
              ["drf", "predicates", "proportion", "nodeorder"])


def _mk_driver(cache, tiers, spec=True, intake=None):
    from volcano_tpu.pipeline import PipelineDriver
    from volcano_tpu.scheduler.degrade import DegradeLadder

    return PipelineDriver(
        cache, lambda: (ACTIONS, tiers), degrade=DegradeLadder(),
        spec=spec, intake=intake)


# -- deterministic cluster + delta trace -------------------------------------


def _cluster(seed):
    rng = random.Random(seed)
    cache = make_cache()
    cache.add_queue(build_queue("default"))
    state = {"cache": cache, "rng": rng, "pods": {}, "n": 0}
    # deliberately CPU-overcommitted (the cfg5_storm shape in miniature):
    # a pending backlog persists across cycles, so every cycle re-runs the
    # warm packed solve — the regime the solve-ahead seals
    for n in range(rng.choice([2, 3])):
        cache.add_node(build_node(
            f"n{n:02d}", build_resource_list_with_pods("4", "12Gi",
                                                       pods=64)))
    for _ in range(rng.choice([8, 10])):
        _add_gang(state)
    return state


def _add_gang(state):
    i, rng, cache = state["n"], state["rng"], state["cache"]
    state["n"] += 1
    pg = f"pg-{i:04d}"
    tasks = rng.choice([2, 3, 4])
    cache.add_pod_group(build_pod_group(
        pg, namespace="pl", min_member=max(1, tasks - 1),
        phase=objects.PodGroupPhase.PENDING))
    for t in range(tasks):
        pod = build_pod(
            "pl", f"{pg}-t{t}", "", objects.POD_PHASE_PENDING,
            {"cpu": f"{rng.choice([500, 1000, 2000])}m", "memory": "1Gi"},
            pg)
        cache.add_pod(pod)
        state["pods"][f"pl/{pg}-t{t}"] = pod


def _del_pod(state):
    pods = state["pods"]
    if not pods:
        return
    key = sorted(pods)[state["rng"].randrange(len(pods))]
    state["cache"].delete_pod(pods.pop(key))


def _schedule(seed, cycles):
    """Per-cycle delta descriptors, a function of the seed alone so both
    arms replay the identical trace. 'none' cycles are the speculation
    windows; 'gang'/'del' are the watch deltas that must invalidate."""
    rng = random.Random(seed * 7919)
    kinds = ["none", "none", "gang", "none", "del", "none"]
    return [rng.choice(kinds) for _ in range(cycles)]


def _apply_delta(state, kind):
    if kind == "gang":
        _add_gang(state)
    elif kind == "del":
        _del_pod(state)


def _signature(cache):
    jobs = {}
    for uid in sorted(cache.jobs):
        job = cache.jobs[uid]
        jobs[uid] = {
            "phase": job.pod_group.status.phase
            if job.pod_group is not None else None,
            "tasks": {t: (int(job.tasks[t].status),
                          job.tasks[t].node_name)
                      for t in sorted(job.tasks)},
        }
    nodes = {}
    for name in sorted(cache.nodes):
        node = cache.nodes[name]
        nodes[name] = (round(node.used.milli_cpu, 6),
                       round(node.idle.milli_cpu, 6),
                       round(node.used.memory, 3))
    return {"jobs": jobs, "nodes": nodes,
            "binds": dict(cache.binder.binds),
            "evicts": list(getattr(cache.evictor, "evicts", []))}


def _drive(seed, cycles, pipeline, spec=True):
    state = _cluster(seed)
    cache = state["cache"]
    tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
    drv = _mk_driver(cache, tiers, spec=spec) if pipeline else None
    for kind in _schedule(seed, cycles):
        _apply_delta(state, kind)
        if drv is None:
            ssn = open_session(cache, tiers)
            try:
                run_actions(ssn, ACTIONS)
            finally:
                close_session(ssn)
        else:
            drv.run_cycle()
    if drv is not None:
        drv.abandon()
    cache.flush_mirror()
    return _signature(cache), (dict(drv.stats) if drv else None)


def _check_accounting(stats):
    """The never-applied proof, as accounting: every dispatched stage is
    either applied or discarded, every non-abandoned discard re-ran the
    cycle serially, and the apply-time re-check never caught a stale
    fingerprint (nothing may move state between the two probes)."""
    assert stats["stale_commits"] == 0, stats
    discards = stats["spec_discards"]
    assert stats["spec_applied"] + stats["spec_discarded"] \
        == stats["spec_dispatched"], stats
    non_abandoned = sum(n for reason, n in discards.items()
                       if reason != "abandoned")
    assert non_abandoned == stats["spec_reruns"], stats


@pytest.mark.parametrize("seed", [3, 17])
def test_pipeline_parity_fuzz(seed):
    """Same delta trace => identical end state (task statuses and
    placements, node accounting, PodGroup phases, binds, evictions in
    effector order) for serial, pipelined+speculative, and
    pipelined-without-speculation."""
    want, _ = _drive(seed, 10, pipeline=False)
    got_spec, stats = _drive(seed, 10, pipeline=True, spec=True)
    got_nospec, nstats = _drive(seed, 10, pipeline=True, spec=False)
    assert got_spec == want, seed
    assert got_nospec == want, seed
    _check_accounting(stats)
    # the no-speculation arm must never dispatch ahead
    assert nstats["spec_dispatched"] == 0, nstats


@pytest.mark.slow
@pytest.mark.parametrize("seed", list(range(400, 410)))
def test_pipeline_parity_wide(seed):
    want, _ = _drive(seed, 14, pipeline=False)
    got, stats = _drive(seed, 14, pipeline=True, spec=True)
    assert got == want, seed
    _check_accounting(stats)


def test_speculation_commits_on_quiet_cycles():
    """Delta-free cycles are the speculation windows: with a standing
    backlog and nothing moving between seal and apply, the solve-ahead
    must actually commit (spec_applied > 0, kind="quiet") — and a NEW
    gang landing on sealed state must still discard: membership growth
    is work the serial order would have admitted into the sealed cycle,
    so the read-set scope calls it a phantom row."""
    state = _cluster(5)
    cache = state["cache"]
    tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
    drv = _mk_driver(cache, tiers)
    for _ in range(4):  # quiet back-to-back cycles
        drv.run_cycle()
    assert drv.stats["spec_applied"] >= 1, drv.stats
    assert drv.stats["spec_commits"].get("quiet", 0) >= 1, drv.stats
    _add_gang(state)  # a watch delta lands on sealed state
    drv.run_cycle()
    assert drv.stats["spec_discards"].get("readset:phantom", 0) >= 1, \
        drv.stats
    drv.abandon()
    _check_accounting(drv.stats)


# -- read-set-scoped speculation ---------------------------------------------


_WIDE_N = 96


def _wide_cluster(anchors=4):
    """A node axis wide enough for WINDOWED rounds nomination (the
    touched-node mask covers a strict subset of the axis), anchored by a
    standing backlog of unplaceable gangs (8 cpu tasks vs 4 cpu nodes:
    n_feas == 0, so the coverage bit stays exact and no full sweep
    widens the mask) — every cycle re-runs the packed solve and every
    speculation seals a partial node read set."""
    cache = make_cache()
    cache.add_queue(build_queue("default"))
    state = {"cache": cache, "rng": random.Random(0), "pods": {}, "n": 0}
    for n in range(_WIDE_N):
        cache.add_node(build_node(
            f"w{n:02d}", build_resource_list_with_pods("4", "12Gi",
                                                       pods=64)))
    for i in range(anchors):
        pg = f"anchor-{i}"
        cache.add_pod_group(build_pod_group(
            pg, namespace="pl", min_member=1,
            phase=objects.PodGroupPhase.PENDING))
        for t in range(2):
            pod = build_pod(
                "pl", f"{pg}-t{t}", "", objects.POD_PHASE_PENDING,
                {"cpu": "8000m", "memory": "1Gi"}, pg)
            cache.add_pod(pod)
            state["pods"][f"pl/{pg}-t{t}"] = pod
    return state


def _echo_node(cache, name):
    """A value-neutral node status echo (the kubelet's periodic resync):
    same name, same capacity — marks the keeper, moves the coarse
    fingerprint, changes nothing the solve could have read differently."""
    cache.add_node(build_node(
        name, build_resource_list_with_pods("4", "12Gi", pods=64)))


def test_readset_echo_on_untouched_node_commits():
    """Directed commit case: a status echo on a node OUTSIDE the sealed
    stage's touched mask is provably disjoint — the stage must COMMIT
    (kind="readset") with zero discards, and the disjointness witness
    must record the delta/read split for the auditor."""
    state = _wide_cluster()
    cache = state["cache"]
    tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
    drv = _mk_driver(cache, tiers)
    drv.run_cycle()
    st = drv._inflight
    assert st is not None and st.readset is not None
    read = drv._read_node_set(st)
    assert read is not None
    untouched = sorted(set(cache.nodes) - read)
    assert untouched, "window covered the whole axis; widen _WIDE_N"
    _echo_node(cache, untouched[0])
    drv.run_cycle()
    assert drv.stats["spec_commits"].get("readset", 0) == 1, drv.stats
    assert drv.stats["spec_discarded"] == 0, drv.stats
    assert drv.stats["stale_commits"] == 0, drv.stats
    audit = drv.readset_audit[-1]
    assert audit["delta_nodes"] == [untouched[0]], audit
    assert untouched[0] not in audit["read_nodes"], audit
    drv.abandon()
    _check_accounting(drv.stats)


def test_readset_capacity_change_on_read_node_discards():
    """Directed discard case: a CAPACITY change on a node the sealed
    solve actually read intersects the read set — the stage must discard
    with the readset:node family (and the serial re-run then sees the
    new capacity: the anchors fit the grown node)."""
    state = _wide_cluster()
    cache = state["cache"]
    tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
    drv = _mk_driver(cache, tiers)
    drv.run_cycle()
    st = drv._inflight
    assert st is not None and st.readset is not None
    read = drv._read_node_set(st)
    assert read, "empty node read set; the solve read nothing?"
    target = sorted(read)[0]
    cache.add_node(build_node(  # capacity grows 4 -> 16 cpu: a real delta
        target, build_resource_list_with_pods("16", "48Gi", pods=64)))
    drv.run_cycle()
    assert drv.stats["spec_discards"].get("readset:node", 0) >= 1, \
        drv.stats
    assert drv.stats["spec_commits"].get("readset", 0) == 0, drv.stats
    drv.abandon()
    _check_accounting(drv.stats)


def _drive_mixed(seed, readset_on, cycles=8):
    """One arm of the read-set parity fuzz: node echoes + gang arrivals +
    pod deletes over the wide cluster, with read-set scoping on or off.
    The delta trace is a function of the seed alone."""
    prev = os.environ.get("VOLCANO_TPU_READSET")
    os.environ["VOLCANO_TPU_READSET"] = "1" if readset_on else "0"
    try:
        state = _wide_cluster()
        state["rng"] = random.Random(seed)
        trace_rng = random.Random(seed * 104729)
        cache = state["cache"]
        tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
        drv = _mk_driver(cache, tiers)
        kinds = ["none", "echo", "echo", "gang", "del", "echo"]
        for _ in range(cycles):
            kind = trace_rng.choice(kinds)
            if kind == "echo":
                _echo_node(cache, f"w{trace_rng.randrange(_WIDE_N):02d}")
            else:
                _apply_delta(state, kind)
            drv.run_cycle()
        drv.abandon()
        cache.flush_mirror()
        return _signature(cache), dict(drv.stats)
    finally:
        if prev is None:
            os.environ.pop("VOLCANO_TPU_READSET", None)
        else:
            os.environ["VOLCANO_TPU_READSET"] = prev


def test_readset_mixed_churn_parity_ten_seeds():
    """The oracle contract under real churn, 10 seeds: for the SAME
    echo/gang/delete trace, read-set scoping ON lands byte-for-byte the
    end state scoping OFF lands (every commit it adds is of a stage the
    old seal would merely have re-run on identical state) — and across
    the seeds the on-arm actually commits through churn at least once
    while the off-arm, by construction, never can."""
    total_readset_commits = 0
    for seed in range(60, 70):
        got_on, stats_on = _drive_mixed(seed, True)
        got_off, stats_off = _drive_mixed(seed, False)
        assert got_on == got_off, (seed, stats_on, stats_off)
        _check_accounting(stats_on)
        _check_accounting(stats_off)
        assert stats_off["spec_commits"].get("readset", 0) == 0, stats_off
        total_readset_commits += stats_on["spec_commits"].get("readset", 0)
    assert total_readset_commits >= 1


def test_readset_off_restores_whole_fingerprint_scope(monkeypatch):
    """VOLCANO_TPU_READSET=0: the same new-gang delta discards with the
    coarse watch_delta attribution — the pre-read-set behavior, bit for
    bit."""
    monkeypatch.setenv("VOLCANO_TPU_READSET", "0")
    state = _cluster(5)
    cache = state["cache"]
    tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
    drv = _mk_driver(cache, tiers)
    for _ in range(2):
        drv.run_cycle()
    _add_gang(state)
    drv.run_cycle()
    assert drv.stats["spec_discards"].get("watch_delta", 0) >= 1, drv.stats
    drv.abandon()
    _check_accounting(drv.stats)


def test_abandon_never_applies():
    """abandon() (shutdown / lost leadership / crashed cycle) discards the
    in-flight stage without any observable cache effect."""
    from volcano_tpu.utils import devprof

    state = _cluster(9)
    cache = state["cache"]
    tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
    drv = _mk_driver(cache, tiers)
    drv.run_cycle()
    assert drv._inflight is not None  # solve-ahead left dispatched
    before = _signature(cache)
    drv.abandon()
    assert _signature(cache) == before
    assert drv.stats["spec_discards"].get("abandoned") == 1
    devprof.drain()  # nothing in flight may dangle


def test_express_commit_discards_and_tokens_drain():
    """The express interaction contract: (a) a token minted AFTER the seal
    (an express commit in the inter-cycle window) moves the lane's commit
    epoch and discards the in-flight stage; (b) the re-run session drains
    the token through normal reconciliation; (c) the speculation guard
    refuses to seal while tokens are outstanding."""
    from volcano_tpu.express.trigger import ExpressToken

    state = _cluster(11)
    cache = state["cache"]
    tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
    drv = _mk_driver(cache, tiers)

    class _Lane:
        outstanding = {}
        commit_epoch = 0
        session_seq = 0
        last_reverts = []
        counters = {"terminal": 0, "reconciled": 0, "reverted": 0,
                    "batches": 0}
        denylist = set()

        def set_tiers(self, tiers):
            pass

        def _count(self, key, n):
            self.counters[key] += n

    lane = cache.express_lane = _Lane()
    drv.run_cycle()
    assert drv._inflight is not None
    # an express commit lands between seal and apply: epoch moves, a
    # token appears (job unknown to sessions => terminal at reconcile)
    lane.commit_epoch += 1
    lane.outstanding["ghost/job"] = ExpressToken(
        job_uid="ghost/job", binds={}, seq=lane.session_seq, epoch=1)
    # (c) the guard, probed directly: speculation refuses to seal past an
    # unresolved token
    info = {}
    drv._speculate(ACTIONS, ACTIONS, tiers, info)
    assert drv.stats["spec_skips"].get("express_tokens") == 1, drv.stats
    # (a)+(b): the cycle discards the stale stage, re-runs serially, and
    # the committing session's reconcile drains the token
    drv.run_cycle()
    assert drv.stats["spec_discards"].get("express_commit", 0) >= 1, \
        drv.stats
    assert not lane.outstanding
    assert lane.counters["terminal"] == 1
    drv.abandon()
    _check_accounting(drv.stats)


def test_fence_epoch_discards_speculation():
    """A leadership change between seal and apply must kill the in-flight
    stage through the fingerprint's fence component (the PR 8 takeover
    path: a new term never applies a deposed term's solve)."""
    state = _cluster(13)
    cache = state["cache"]
    tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
    drv = _mk_driver(cache, tiers)
    drv.run_cycle()
    assert drv._inflight is not None
    cache.set_fence_epoch(7)
    drv.run_cycle()
    assert drv.stats["spec_discards"].get("fence_epoch", 0) >= 1, drv.stats
    drv.abandon()
    _check_accounting(drv.stats)


def test_mesh_change_discards_speculation():
    """A mesh-shape change mid-flight (driver re-installs the default mesh
    — device added/removed, shard spec change) must discard the sealed
    stage instead of applying a MIS-SHARDED solve: its packed buffers,
    window ladder and padded node extent were all keyed to the old device
    count. Counted as pipeline_spec_discard{reason="mesh"}."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from volcano_tpu.scheduler.plugins import tpuscore

    state = _cluster(17)
    cache = state["cache"]
    tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
    drv = _mk_driver(cache, tiers)
    try:
        drv.run_cycle()
        assert drv._inflight is not None
        tpuscore.set_default_mesh(
            Mesh(np.array(jax.devices()[:8]), ("nodes",)))
        drv.run_cycle()
        assert drv.stats["spec_discards"].get("mesh", 0) >= 1, drv.stats
        drv.abandon()
        _check_accounting(drv.stats)
    finally:
        tpuscore.set_default_mesh(None)


def test_policy_meta_delta_discards_speculation():
    """A queue spec update (weight change) between seal and apply has no
    per-object dirty mark — QueueInfos re-derive fresh each snapshot —
    but the sealed solve read the OLD policy, so the keeper's scoped
    queue mark must invalidate the stage — the sealed solve consumed
    this queue's policy row, so the read-set scope intersects."""
    state = _cluster(19)
    cache = state["cache"]
    tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
    drv = _mk_driver(cache, tiers)
    drv.run_cycle()
    assert drv._inflight is not None
    cache.add_queue(build_queue("default", weight=7))  # spec update
    drv.run_cycle()
    assert drv.stats["spec_discards"].get("readset:queue", 0) >= 1, \
        drv.stats
    drv.abandon()
    _check_accounting(drv.stats)


def test_conf_change_discards_speculation():
    """A hot-reloaded policy invalidates the sealed stage (tiers identity
    is part of the fingerprint)."""
    state = _cluster(15)
    cache = state["cache"]
    tiers_box = {"tiers": make_tiers(*TIERS_SPEC, arguments=ARGS)}
    from volcano_tpu.scheduler.degrade import DegradeLadder
    from volcano_tpu.pipeline import PipelineDriver

    drv = PipelineDriver(
        cache, lambda: (ACTIONS, tiers_box["tiers"]),
        degrade=DegradeLadder(), spec=True)
    drv.run_cycle()
    assert drv._inflight is not None
    tiers_box["tiers"] = make_tiers(*TIERS_SPEC, arguments=ARGS)
    drv.run_cycle()
    assert drv.stats["spec_discards"].get("conf_changed", 0) >= 1, drv.stats
    drv.abandon()


def test_intake_keeps_speculation_valid():
    """Arrivals funneled through the intake hook land BEFORE the seal, so
    they ride the next speculative snapshot instead of invalidating it —
    and the end state still matches the serial loop fed the same trace at
    the same points."""
    state = _cluster(21)
    cache = state["cache"]
    tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
    pending = []

    def intake():
        while pending:
            _apply_delta(state, pending.pop(0))

    drv = _mk_driver(cache, tiers, intake=intake)
    trace = ["none", "gang", "none", "gang", "none", "none"]
    for kind in trace:
        pending.append(kind)
        drv.run_cycle()
    drv.abandon()
    cache.flush_mirror()
    got = _signature(cache)
    stats = dict(drv.stats)
    # intake-quantized arrivals never invalidate
    assert stats["spec_discards"].get("watch_delta", 0) == 0, stats
    assert stats["spec_applied"] >= 2, stats

    # serial arm: the same arrivals applied at the same quantization
    # points (right after each committed cycle => visible to the next)
    state2 = _cluster(21)
    cache2 = state2["cache"]
    for kind in trace:
        _apply_delta(state2, kind)
        ssn = open_session(cache2, tiers)
        try:
            run_actions(ssn, ACTIONS)
        finally:
            close_session(ssn)
    cache2.flush_mirror()
    assert got == _signature(cache2)


def test_pipeline_disabled_rung_falls_back():
    """Repeated pipelined-cycle errors open the ladder's pipeline breaker:
    pipeline_allowed() goes False (the scheduler loop then runs the serial
    run_once oracle) and the rung reads pipeline_disabled."""
    from volcano_tpu.scheduler.degrade import DegradeLadder

    ladder = DegradeLadder(pipeline_threshold=3)
    assert ladder.pipeline_allowed()
    for _ in range(3):
        ladder.note_pipeline_error()
    assert not ladder.pipeline_allowed()
    assert ladder.rung() == "pipeline_disabled"
    ladder.note_pipeline_ok()
    assert ladder.pipeline_allowed()


def test_crashed_cycle_abandons_and_meters(monkeypatch):
    """A cycle that raises must not strand a half-dispatched speculation,
    and must feed the ladder's pipeline breaker."""
    state = _cluster(23)
    cache = state["cache"]
    tiers = make_tiers(*TIERS_SPEC, arguments=ARGS)
    drv = _mk_driver(cache, tiers)
    drv.run_cycle()
    assert drv._inflight is not None

    def boom(*a, **k):
        raise RuntimeError("policy exploded")

    drv.policy_fn = boom
    with pytest.raises(RuntimeError):
        drv.run_cycle()
    assert drv._inflight is None
    assert drv.stats["spec_discards"].get("abandoned") == 1
    assert drv.degrade.pipeline.stats["failures"] >= 1


def test_scheduler_pipeline_mode(monkeypatch):
    """Scheduler(pipeline=True) drives cycles through the driver;
    VOLCANO_TPU_PIPELINE=0 keeps the serial loop (driver never built)."""
    import time

    from volcano_tpu.scheduler.scheduler import Scheduler

    monkeypatch.delenv("VOLCANO_TPU_PIPELINE", raising=False)
    state = _cluster(31)
    cache = state["cache"]
    s = Scheduler(cache, schedule_period=0.05, pipeline=True)
    s.run()
    try:
        assert cache.binder.wait_for_binds(1, timeout=10.0)
        deadline = time.time() + 5.0
        while s.pipeline_driver is None and time.time() < deadline:
            time.sleep(0.01)
        assert s.pipeline_driver is not None
        deadline = time.time() + 5.0
        while s.pipeline_driver.stats["committed"] == 0 \
                and time.time() < deadline:
            time.sleep(0.01)
        assert s.pipeline_driver.stats["committed"] >= 1
    finally:
        s.stop()
    assert s.pipeline_driver._inflight is None  # abandoned at stop

    monkeypatch.setenv("VOLCANO_TPU_PIPELINE", "0")
    state2 = _cluster(31)
    s2 = Scheduler(state2["cache"], schedule_period=0.05, pipeline=True)
    s2.run()
    try:
        assert state2["cache"].binder.wait_for_binds(1, timeout=10.0)
        assert s2.pipeline_driver is None
    finally:
        s2.stop()
