"""Test configuration.

Forces JAX onto an 8-device virtual CPU mesh so multi-chip sharding paths can
be exercised without TPU hardware (the sandbox's sitecustomize registers the
real TPU backend and pins JAX_PLATFORMS, so the override must go through
jax.config after import), enables float64 so device parity tests match the
host oracle's arithmetic bit-for-bit (TPU bench runs use float32; see
ops/solver.py), and enables panic-on-assert so resource accounting violations
fail tests loudly.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["VOLCANO_TPU_PANIC"] = "1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_degrade_ladder():
    """The process-default degradation ladder (scheduler/degrade.py) is
    deliberately global — a test that trips its breakers must not leak a
    degraded rung into later tests' solve paths."""
    from volcano_tpu.scheduler import degrade

    degrade.reset()
    yield
    degrade.reset()
