"""Test configuration.

Forces JAX onto an 8-device virtual CPU mesh so multi-chip sharding paths can
be exercised without TPU hardware, and enables panic-on-assert so resource
accounting violations fail tests loudly.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["VOLCANO_TPU_PANIC"] = "1"
