"""End-to-end allocate action tests
(mirrors pkg/scheduler/actions/allocate/allocate_test.go)."""

from tests.helpers import make_cache, make_tiers
from volcano_tpu.api import objects
from volcano_tpu.scheduler.framework import close_session, get_action, open_session
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)


def run_allocate(cache, tiers):
    ssn = open_session(cache, tiers)
    get_action("allocate").execute(ssn)
    close_session(ssn)
    return ssn


class TestAllocate:
    def test_one_job_two_tasks(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=0))
        c.add_pod(build_pod("c1", "p1", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1"))
        c.add_pod(build_pod("c1", "p2", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1"))
        c.add_node(build_node("n1", build_resource_list_pods("2", "4Gi")))
        run_allocate(c, make_tiers(["drf", "proportion"]))
        assert c.binder.binds == {"c1/p1": "n1", "c1/p2": "n1"}

    def test_two_jobs_on_one_node_fair(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        for pg in ("pg1", "pg2"):
            c.add_pod_group(build_pod_group(pg, namespace="c1", min_member=0))
        c.add_pod(build_pod("c1", "p1", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1"))
        c.add_pod(build_pod("c1", "p2", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1"))
        c.add_pod(build_pod("c1", "p3", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg2"))
        c.add_pod(build_pod("c1", "p4", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg2"))
        c.add_node(build_node("n1", build_resource_list_pods("2", "4Gi")))
        run_allocate(c, make_tiers(["drf", "proportion"]))
        # DRF alternates between the jobs: one task each
        assert len(c.binder.binds) == 2
        bound_jobs = {k.split("/")[1][0:2] for k in c.binder.binds}
        assert len(c.binder.binds) == 2

    def test_gang_all_or_nothing(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        # gang of 3, but only capacity for 2 -> nothing binds
        c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=3))
        for i in range(3):
            c.add_pod(build_pod("c1", f"p{i}", "", objects.POD_PHASE_PENDING,
                                build_resource_list("1", "1Gi"), "pg1"))
        c.add_node(build_node("n1", build_resource_list_pods("2", "4Gi")))
        run_allocate(c, make_tiers(["gang"], ["drf", "proportion"]))
        assert c.binder.binds == {}

    def test_gang_fits(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=3))
        for i in range(3):
            c.add_pod(build_pod("c1", f"p{i}", "", objects.POD_PHASE_PENDING,
                                build_resource_list("1", "1Gi"), "pg1"))
        c.add_node(build_node("n1", build_resource_list_pods("4", "8Gi")))
        run_allocate(c, make_tiers(["gang"], ["drf", "proportion"]))
        assert len(c.binder.binds) == 3

    def test_pending_podgroup_not_allocated(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=1,
                                        phase=objects.PodGroupPhase.PENDING))
        c.add_pod(build_pod("c1", "p1", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1"))
        c.add_node(build_node("n1", build_resource_list_pods("4", "8Gi")))
        run_allocate(c, make_tiers(["gang"], ["drf", "proportion"]))
        assert c.binder.binds == {}

    def test_node_selector_respected(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=1))
        c.add_pod(build_pod("c1", "p1", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1",
                            node_selector={"zone": "a"}))
        c.add_node(build_node("n1", build_resource_list_pods("4", "8Gi"),
                              labels={"zone": "b"}))
        c.add_node(build_node("n2", build_resource_list_pods("4", "8Gi"),
                              labels={"zone": "a"}))
        run_allocate(c, make_tiers(["gang"], ["drf", "proportion", "predicates"]))
        assert c.binder.binds == {"c1/p1": "n2"}

    def test_binpack_prefers_used_node(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg0", namespace="c1", min_member=0))
        # n2 already has a running pod
        c.add_pod(build_pod("c1", "existing", "n2", objects.POD_PHASE_RUNNING,
                            build_resource_list("2", "4Gi"), "pg0"))
        c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=1))
        c.add_pod(build_pod("c1", "p1", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1"))
        for n in ("n1", "n2"):
            c.add_node(build_node(n, build_resource_list_pods("8", "16Gi")))
        run_allocate(c, make_tiers(["gang"], ["binpack"]))
        assert c.binder.binds == {"c1/p1": "n2"}

    def test_queue_missing_skips_job(self):
        c = make_cache()
        c.add_pod_group(build_pod_group("pg1", namespace="c1", queue="nope"))
        c.add_pod(build_pod("c1", "p1", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1"))
        c.add_node(build_node("n1", build_resource_list_pods("4", "8Gi")))
        run_allocate(c, make_tiers(["gang"], ["drf"]))
        assert c.binder.binds == {}


def build_resource_list_pods(cpu, mem):
    rl = build_resource_list(cpu, mem)
    rl["pods"] = 110
    return rl
