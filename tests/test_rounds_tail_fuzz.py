"""Adversarial rounds-tail characterization (VERDICT r3 item 8).

The rounds solver's convergence tail is where latency regressions hide:
cfg6 showed a fixed ~20ms/round device cost times the round count, plus
whatever the diminishing-returns cap hands to the tail pass. This fuzz
corpus drives the shapes that inflate the tail on purpose —

- tie-heavy: identical nodes x identical tasks => every score ties and
  the within-group rotation does ALL the spreading work;
- selector contention: task families pinned to overlapping small node
  subsets => classes fight for the same few nodes every round;
- tiny gangs: hundreds of min==size gangs => gang-rollback fixpoint
  pressure;
- binpack packing: score-concentrating policy (the serial behavior fills
  node by node) => the capacity-apportioning logic is the only thing
  standing between the solve and one-node-per-round crawl;
- two-queue churn: the proportion overused gate flips queues in and out
  across rounds.

— and pins the OBSERVED tail: round count and capped/tail-placed task
counts stay under documented bounds (margin over the measured values
noted at BOUNDS, far below the 2(T+J) runaway budget), so a tail-cost
regression fails loudly instead of silently re-inflating cfg6.
Invariants (feasible placements, gang atomicity) are asserted via the
shared checker.
"""

from __future__ import annotations

import random

import pytest

from tests.test_rounds import check_invariants, run_rounds
from volcano_tpu.api import objects
from volcano_tpu.scheduler.util.test_utils import (
    build_node, build_pod, build_pod_group, build_queue,
    build_resource_list_with_pods,
)

# documented per-scenario bounds: (max rounds, max capped+tail tasks,
# populate min_member). Measured rounds: tie_heavy 1, tiny_gangs 3,
# two_queue_churn 4, binpack_packing 6, selector_contention 63. The
# selector scenario's cost model: an infeasible-overload cluster pays ~2
# rounds (stall + conservative retry) per gang the rollback fixpoint
# retires — linear in UNPLACEABLE GANGS, not in tasks — so its bound
# carries the least headroom (~1.7x); the cheap scenarios get wider
# absolute slack. A change that pushes past these bounds re-inflates
# the cfg6-style tail: look at it.
BOUNDS = {
    "tie_heavy": (4, 0, 2),
    "selector_contention": (110, 40, 2),
    "tiny_gangs": (8, 0, 2),
    "binpack_packing": (16, 40, 1),
    "two_queue_churn": (10, 0, 2),
}


def _run(populate, tiers, min_member):
    cache, prof = run_rounds(populate, tiers)
    check_invariants(cache, min_member)
    return cache, prof


def _tie_heavy(cache):
    """600 identical tasks on 40 identical nodes: all-ties spreading."""
    cache.add_queue(build_queue("default"))
    for n in range(40):
        cache.add_node(build_node(
            f"node-{n:03d}", build_resource_list_with_pods("16", "32Gi")))
    for g in range(150):
        pg = f"tie{g:04d}"
        cache.add_pod_group(build_pod_group(pg, namespace="f", min_member=2))
        for i in range(4):
            cache.add_pod(build_pod(
                "f", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                {"cpu": "500m", "memory": "512Mi"}, pg))


def _selector_contention(cache):
    """8 task families pinned to overlapping 6-node windows of a 24-node
    cluster; demand ~2x the windows' capacity."""
    cache.add_queue(build_queue("default"))
    for n in range(24):
        cache.add_node(build_node(
            f"node-{n:03d}", build_resource_list_with_pods("8", "16Gi"),
            labels={"zone": f"z{n // 3}"}))
    rng = random.Random(7)
    for g in range(120):
        fam = g % 8
        zones = [f"z{(fam + d) % 8}" for d in range(2)]
        pg = f"sel{g:04d}"
        cache.add_pod_group(build_pod_group(pg, namespace="f", min_member=2))
        for i in range(3):
            cache.add_pod(build_pod(
                "f", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                {"cpu": f"{rng.choice([500, 1000])}m", "memory": "1Gi"}, pg,
                node_selector={"zone": rng.choice(zones)}))


def _tiny_gangs(cache):
    """400 gangs of 2 with min==2 on a cluster that fits ~80% of them:
    the gang rollback fixpoint must retire the excess, one per pass."""
    cache.add_queue(build_queue("default"))
    for n in range(20):
        cache.add_node(build_node(
            f"node-{n:03d}", build_resource_list_with_pods("16", "32Gi")))
    for g in range(400):
        pg = f"tg{g:04d}"
        cache.add_pod_group(build_pod_group(pg, namespace="f", min_member=2))
        for i in range(2):
            cache.add_pod(build_pod(
                "f", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                {"cpu": "1", "memory": "1Gi"}, pg))


def _binpack_packing(cache):
    """Score-concentrating binpack with 30 heterogeneous classes: every
    class walks the same node order; only demand-share apportioning keeps
    the rounds from crawling."""
    cache.add_queue(build_queue("default"))
    for n in range(32):
        cache.add_node(build_node(
            f"node-{n:03d}", build_resource_list_with_pods("16", "32Gi")))
    rng = random.Random(23)
    for g in range(200):
        pg = f"bp{g:04d}"
        cache.add_pod_group(build_pod_group(pg, namespace="f", min_member=1))
        for i in range(3):
            cache.add_pod(build_pod(
                "f", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                {"cpu": f"{rng.choice([250, 500, 750, 1000, 1500])}m",
                 "memory": rng.choice(["256Mi", "512Mi", "1Gi"])}, pg))


def _two_queue_churn(cache):
    """Two weighted queues at ~2x capacity: the proportion overused gate
    flips participation across rounds."""
    cache.add_queue(build_queue("qa", weight=3))
    cache.add_queue(build_queue("qb", weight=1))
    for n in range(24):
        cache.add_node(build_node(
            f"node-{n:03d}", build_resource_list_with_pods("8", "16Gi")))
    for g in range(160):
        pg = f"qc{g:04d}"
        cache.add_pod_group(build_pod_group(
            pg, namespace="f", min_member=2, queue=("qa", "qb")[g % 2]))
        for i in range(3):
            cache.add_pod(build_pod(
                "f", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                {"cpu": "1", "memory": "1Gi"}, pg))


SCENARIOS = {
    "tie_heavy": (_tie_heavy, (["priority", "gang"],
                               ["drf", "predicates", "proportion",
                                "nodeorder"])),
    "selector_contention": (_selector_contention,
                            (["priority", "gang"],
                             ["predicates", "binpack", "proportion"])),
    "tiny_gangs": (_tiny_gangs, (["priority", "gang"],
                                 ["drf", "predicates", "proportion",
                                  "nodeorder"])),
    "binpack_packing": (_binpack_packing,
                        (["priority", "gang"],
                         ["predicates", "binpack", "proportion"])),
    "two_queue_churn": (_two_queue_churn,
                        (["priority", "gang"],
                         ["drf", "predicates", "proportion", "nodeorder"])),
}


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_adversarial_tail_bounded(name):
    populate, tiers = SCENARIOS[name]
    rounds_bound, capped_bound, min_member = BOUNDS[name]
    cache, prof = _run(populate, tiers, min_member)
    rounds = prof.get("rounds", 0)
    capped = prof.get("round_capped_tasks", 0) + prof.get("tail_placed", 0)
    assert rounds <= rounds_bound, (
        f"{name}: {rounds} rounds > documented bound {rounds_bound} "
        f"(profile {prof})")
    assert capped <= capped_bound, (
        f"{name}: {capped} capped/tail tasks > documented bound "
        f"{capped_bound} (profile {prof})")
    # the scenario must be real work, not a degenerate no-op
    assert len(cache.binder.binds) > 100, (name, len(cache.binder.binds))
