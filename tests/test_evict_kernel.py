"""Batched device eviction (ops/evict.py) vs the serial statement walk.

The contract: within the modeled envelope the batched preempt/reclaim/
backfill actions are bindings-and-evictions-IDENTICAL to the old path
(`VOLCANO_TPU_EVICT=0`) — same evictions in the same cache-effector order,
same pipelined placements, same post-session accounting (node vectors, drf
job shares, proportion queue shares), over randomized overcommitted
clusters including gang preemptors, multi-queue reclaim tiers, and
PDB-driven minAvailable edge cases. The warm path must reuse the compiled
programs (CompileWatcher.assert_no_compiles)."""

from __future__ import annotations

import os
import random

import pytest

from tests.helpers import close_session, make_cache, make_tiers, open_session
from volcano_tpu.api import objects
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.scheduler.framework import get_action
from volcano_tpu.scheduler.util.test_utils import (
    build_node, build_pod, build_pod_group, build_queue,
    build_resource_list_with_pods,
)

ACTIONS = ("allocate", "backfill", "preempt", "reclaim")

# conf shapes: cfg4's two-tier default (gang decides both victim kinds),
# a reclaim-tier conf where gang ∧ proportion decide reclaim (the
# deserved-floor walk engages), and a single tier where gang ∧ drf ∧
# conformance decide preempt (the cumulative-share walk engages)
TIER_SETS = [
    (["priority", "gang"], ["drf", "predicates", "proportion", "nodeorder"]),
    (["priority"], ["gang", "proportion", "predicates", "nodeorder"]),
    (["gang", "drf", "conformance", "proportion", "predicates"],),
]


def _overcommit_cluster(seed: int, nodes: int = 6, running_jobs: int = 12,
                        tasks_per_job: int = 4, queues: int = 2,
                        hi_jobs: int = 4):
    """Dense running fill bound round-robin with almost no idle headroom,
    pending high-priority gangs (preemptors), a starved low-weight queue
    (reclaimers), best-effort pods (backfill), and PDBs overriding some
    victims' minAvailable."""
    rng = random.Random(seed)
    c = make_cache()
    for q in range(queues):
        c.add_queue(build_queue(f"q{q}", weight=1 + q))
    per_node = running_jobs * tasks_per_job // nodes + 1
    cpu = per_node + 2
    for n in range(nodes):
        c.add_node(build_node(
            f"node-{n:03d}",
            build_resource_list_with_pods(str(cpu), f"{cpu * 2}Gi", pods=64)))
    slot = 0
    for g in range(running_jobs):
        pg = f"run-{g:03d}"
        queue = f"q{g % queues}"
        min_member = rng.choice([1, 1, 2, tasks_per_job])
        c.add_pod_group(build_pod_group(
            pg, namespace="ev", min_member=min_member, queue=queue))
        if rng.random() < 0.25:
            # PDB-driven minAvailable override: the gang victim gate then
            # runs against the PDB's floor, not the PodGroup's
            c.add_pdb(objects.PodDisruptionBudget(
                metadata=objects.ObjectMeta(name=pg, namespace="ev"),
                min_available=rng.choice([1, 2, tasks_per_job])))
        for i in range(tasks_per_job):
            pod = build_pod(
                "ev", f"{pg}-t{i}", f"node-{slot % nodes:03d}",
                objects.POD_PHASE_RUNNING,
                {"cpu": "1000m", "memory": rng.choice(["1Gi", "2Gi"])},
                pg, priority=rng.choice([0, 1, 5]))
            if rng.random() < 0.1:
                # conformance-protected victims
                pod.spec.priority_class_name = objects.SYSTEM_CLUSTER_CRITICAL
            c.add_pod(pod)
            slot += 1
    for g in range(hi_jobs):
        pg = f"hi-{g:02d}"
        mm = rng.choice([1, 1, 2])
        c.add_pod_group(build_pod_group(
            pg, namespace="ev", min_member=mm, queue=f"q{g % queues}"))
        for i in range(2):
            c.add_pod(build_pod(
                "ev", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                {"cpu": f"{rng.choice([3000, 4000])}m",
                 "memory": rng.choice(["4Gi", "8Gi"])},
                pg, priority=100))
    # mixed jobs: RUNNING victims + PENDING preemptors in one job, so the
    # job sits in the preemptors heap while other preemptors evict its
    # running tasks — its drf-share/gang-ready heap keys mutate IN-heap,
    # which is exactly the case where heapq pop order is heap-structural
    # rather than an argmin (the kernel's sift simulation must match)
    for g in range(3):
        pg = f"mx-{g:02d}"
        c.add_pod_group(build_pod_group(
            pg, namespace="ev", min_member=1, queue=f"q{g % queues}"))
        for i in range(2):
            c.add_pod(build_pod(
                "ev", f"{pg}-r{i}", f"node-{(slot + i) % nodes:03d}",
                objects.POD_PHASE_RUNNING,
                {"cpu": "1000m", "memory": "1Gi"}, pg, priority=1))
        for i in range(2):
            c.add_pod(build_pod(
                "ev", f"{pg}-p{i}", "", objects.POD_PHASE_PENDING,
                {"cpu": "2000m", "memory": "2Gi"}, pg,
                priority=rng.choice([20, 100])))
    # starved-queue reclaimers (cross-queue eviction pressure)
    for g in range(2):
        pg = f"rc-{g:02d}"
        c.add_pod_group(build_pod_group(
            pg, namespace="ev", min_member=1, queue=f"q{queues - 1}"))
        for i in range(2):
            c.add_pod(build_pod(
                "ev", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                {"cpu": "2000m", "memory": "2Gi"}, pg, priority=10))
    # best-effort pods for backfill
    for g in range(2):
        pg = f"be-{g:02d}"
        c.add_pod_group(build_pod_group(
            pg, namespace="ev", min_member=1, queue="q0"))
        for i in range(2):
            c.add_pod(build_pod(
                "ev", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING, {},
                pg, priority=1))
    return c


def _res_tuple(r):
    return (round(r.milli_cpu, 6), round(r.memory, 3),
            tuple(sorted((r.scalar_resources or {}).items())))


def _session_signature(ssn):
    """Everything the parity contract covers: task statuses/placements,
    node accounting, job readiness, plugin shares."""
    tasks = sorted(
        (t.uid, int(t.status), t.node_name)
        for job in ssn.jobs.values() for t in job.tasks.values())
    nodes = sorted(
        (n.name, _res_tuple(n.idle), _res_tuple(n.used),
         _res_tuple(n.releasing), len(n.tasks))
        for n in ssn.nodes.values())
    jobs = sorted(
        (j.uid, j.ready_task_num(), j.waiting_task_num())
        for j in ssn.jobs.values())
    drf = ssn.plugins.get("drf")
    shares = sorted(
        (uid, a.share, _res_tuple(a.allocated))
        for uid, a in drf.job_attrs.items()) if drf is not None else []
    prop = ssn.plugins.get("proportion")
    qshares = sorted(
        (q, a.share, _res_tuple(a.allocated))
        for q, a in prop.queue_opts.items()) if prop is not None else []
    fit_errors = sorted(
        (uid, fe.error()) for job in ssn.jobs.values()
        for uid, fe in job.nodes_fit_errors.items())
    return dict(tasks=tasks, nodes=nodes, jobs=jobs, shares=shares,
                qshares=qshares, fit_errors=fit_errors)


def _run(cache, tiers_spec, evict_on, monkeypatch, sessions: int = 1,
         actions=ACTIONS):
    import volcano_tpu.ops.victimview as vv

    from volcano_tpu.scheduler import metrics

    monkeypatch.setenv("VOLCANO_TPU_EVICT", "1" if evict_on else "0")
    # engage victim batching on the oracle path too (its own parity is
    # pinned by test_victimview)
    monkeypatch.setattr(vv.VictimSelector, "MIN_BATCH", 1)
    reg = metrics.registry()
    m0 = (reg.preemption_victims.get(), reg.preemption_attempts.get())
    sig = None
    profs = []
    for _ in range(sessions):
        ssn = open_session(cache, make_tiers(["tpuscore"], *tiers_spec))
        try:
            for name in actions:
                get_action(name).execute(ssn)
            sig = _session_signature(ssn)
            profs.append(dict(ssn.plugins["tpuscore"].profile))
        finally:
            close_session(ssn)
    sig["metrics"] = (reg.preemption_victims.get() - m0[0],
                      reg.preemption_attempts.get() - m0[1])
    return sig, dict(cache.binder.binds), list(cache.evictor.evicts), profs


@pytest.mark.parametrize("tiers_spec", TIER_SETS)
@pytest.mark.parametrize("seed", [11, 42, 7])
def test_fuzzed_action_parity(tiers_spec, seed, monkeypatch):
    got = _run(_overcommit_cluster(seed), tiers_spec, True, monkeypatch)
    want = _run(_overcommit_cluster(seed), tiers_spec, False, monkeypatch)
    assert got[0] == want[0], (tiers_spec, seed)
    assert got[1] == want[1]          # binds
    assert got[2] == want[2]          # evictions, in effector order
    # the batched path must actually have run (not silently fallen back)
    prof = got[3][0]
    for kind in ("preempt", "reclaim", "backfill"):
        assert f"evict_{kind}" in prof, prof.get(
            f"evict_{kind}_fallback", prof)


@pytest.mark.slow
@pytest.mark.parametrize("seed", list(range(100, 116)))
def test_fuzzed_action_parity_wide(seed, monkeypatch):
    """Wider fuzz band: randomized cluster shapes (fresh buckets, fresh
    compiles) across all tier sets."""
    rng = random.Random(seed * 7)
    kw = dict(nodes=rng.choice([4, 7, 9]),
              running_jobs=rng.choice([8, 14, 18]),
              tasks_per_job=rng.choice([3, 4, 5]),
              queues=rng.choice([2, 3]),
              hi_jobs=rng.choice([3, 5]))
    tiers_spec = TIER_SETS[seed % len(TIER_SETS)]
    got = _run(_overcommit_cluster(seed, **kw), tiers_spec, True,
               monkeypatch)
    want = _run(_overcommit_cluster(seed, **kw), tiers_spec, False,
                monkeypatch)
    assert got[0] == want[0], (kw, tiers_spec)
    assert got[1] == want[1]
    assert got[2] == want[2]


@pytest.mark.parametrize("seed", [21])
def test_consecutive_sessions_parity(seed, monkeypatch):
    """Two back-to-back sessions on one cache: the second one's snapshot is
    delta-maintained from the SnapshotKeeper dirty-sets the eviction
    effectors marked — accounting must stay identical to the serial arm."""
    tiers = TIER_SETS[0]
    got = _run(_overcommit_cluster(seed), tiers, True, monkeypatch,
               sessions=2)
    want = _run(_overcommit_cluster(seed), tiers, False, monkeypatch,
                sessions=2)
    assert got[0] == want[0]
    assert got[1] == want[1]
    assert got[2] == want[2]


def test_evictions_mark_snapshot_dirty_sets(monkeypatch):
    """Replayed evictions go through cache.evict, so the keeper's dirty
    sets must cover every evicted task's job and node before the next
    snapshot rebuild."""
    cache = _overcommit_cluster(11)
    monkeypatch.setenv("VOLCANO_TPU_EVICT", "1")
    ssn = open_session(cache, make_tiers(["tpuscore"], *TIER_SETS[0]))
    try:
        for name in ACTIONS:
            get_action(name).execute(ssn)
        evicted = [
            t for job in ssn.jobs.values() for t in job.tasks.values()
            if t.status == TaskStatus.RELEASING]
        if evicted:  # seed 11 evicts (asserted in the parity fuzz above)
            assert cache.snap_keeper.stats.get("evict_marks", 0) > 0
            for t in evicted:
                assert t.job in cache.snap_keeper.dirty_jobs
                assert t.node_name in cache.snap_keeper.dirty_nodes
    finally:
        close_session(ssn)


def test_warm_path_pins_no_compiles(monkeypatch):
    """Second identically-shaped session must reuse every compiled evict
    program (bucketed shapes + static spec)."""
    from volcano_tpu.utils.jaxcompile import CompileWatcher

    tiers = TIER_SETS[0]
    _run(_overcommit_cluster(11), tiers, True, monkeypatch)
    watcher = CompileWatcher.install()
    with watcher.assert_no_compiles("warm batched evict session"):
        _run(_overcommit_cluster(11), tiers, True, monkeypatch)


def test_env_flag_forces_old_path(monkeypatch):
    from volcano_tpu.ops import evict as evict_mod

    cache = _overcommit_cluster(11)
    monkeypatch.setenv("VOLCANO_TPU_EVICT", "0")
    ssn = open_session(cache, make_tiers(["tpuscore"], *TIER_SETS[0]))
    try:
        assert evict_mod.build(ssn, "preempt") is None
        assert evict_mod.build(ssn, "reclaim") is None
        assert evict_mod.build(ssn, "backfill") is None
    finally:
        close_session(ssn)


def test_scalar_resources_fall_back(monkeypatch):
    """Scalar dims leave the modeled envelope (Resource nil-map compare
    asymmetries): build must refuse, the action must still work serially."""
    from volcano_tpu.ops import evict as evict_mod

    cache = _overcommit_cluster(11)
    rl = build_resource_list_with_pods("8", "16Gi", pods=64)
    rl["nvidia.com/gpu"] = "4"
    cache.add_node(build_node("node-gpu", rl))
    monkeypatch.setenv("VOLCANO_TPU_EVICT", "1")
    ssn = open_session(cache, make_tiers(["tpuscore"], *TIER_SETS[0]))
    try:
        assert evict_mod.build(ssn, "preempt") is None
        prof = ssn.plugins["tpuscore"].profile
        assert "scalar" in prof["evict_preempt_fallback"]
        for name in ACTIONS:  # the old path still runs end-to-end
            get_action(name).execute(ssn)
    finally:
        close_session(ssn)


def test_custom_victim_plugin_falls_back(monkeypatch):
    from volcano_tpu.ops import evict as evict_mod

    cache = _overcommit_cluster(11)
    monkeypatch.setenv("VOLCANO_TPU_EVICT", "1")
    ssn = open_session(cache, make_tiers(["tpuscore"], *TIER_SETS[0]))
    try:
        ssn.add_preemptable_fn("priority", lambda c, cs: cs)
        assert evict_mod.build(ssn, "preempt") is None
        # reclaimable registry untouched -> still batchable
        assert evict_mod.build(ssn, "reclaim") is not None
    finally:
        close_session(ssn)


# ---------------------------------------------------------------------------
# backfill diagnostics-budget coverage (backfill.py replay_budget)
# ---------------------------------------------------------------------------


def _backfill_failure_cluster(failing: int):
    """Zero-request pods whose node selector matches nothing: every one
    fails on the dense path, exercising the bounded diagnostics replay."""
    c = make_cache()
    c.add_queue(build_queue("default"))
    for n in range(3):
        c.add_node(build_node(
            f"node-{n:03d}",
            build_resource_list_with_pods("8", "16Gi", pods=16),
            labels={"zone": "a"}))
    for g in range(failing):
        pg = f"bf-{g:03d}"
        c.add_pod_group(build_pod_group(
            pg, namespace="bf", min_member=1, queue="default"))
        c.add_pod(build_pod(
            "bf", f"{pg}-t0", "", objects.POD_PHASE_PENDING, {}, pg,
            node_selector={"zone": "nowhere"}))
    return c


@pytest.mark.parametrize("evict_on", [True, False])
def test_backfill_replay_budget_serial_fidelity(evict_on, monkeypatch):
    """A session with more view-path backfill failures than the replay
    budget (8) must keep the dense path and still produce serial-fidelity
    per-node FitErrors for the first 8 tasks; the rest get the summary
    error. Both the batched kernel path and the dense-view path honor the
    same budget, and their FitErrors match the fully serial walk."""
    monkeypatch.setenv("VOLCANO_TPU_EVICT", "1" if evict_on else "0")
    failing = 12
    cache = _backfill_failure_cluster(failing)
    ssn = open_session(
        cache, make_tiers(["tpuscore"], ["gang"], ["predicates"]))
    try:
        get_action("backfill").execute(ssn)
        prof = dict(ssn.plugins["tpuscore"].profile)
        errors = {}
        for job in ssn.jobs.values():
            for uid, fe in job.nodes_fit_errors.items():
                errors[uid] = fe
        assert len(errors) == failing
        detailed = [fe for fe in errors.values() if fe.nodes]
        summary = [fe for fe in errors.values() if not fe.nodes]
        assert len(detailed) == 8          # replay budget spent exactly
        assert len(summary) == failing - 8
        for fe in detailed:                # serial-fidelity per-node reasons
            assert len(fe.nodes) == 3
        for fe in summary:
            assert fe.err == "0/3 nodes are feasible for backfill"
    finally:
        close_session(ssn)

    # serial-fidelity: the serial walk's per-node reasons are identical
    cache2 = _backfill_failure_cluster(failing)
    ssn2 = open_session(cache2, make_tiers(["gang"], ["predicates"]))
    try:
        get_action("backfill").execute(ssn2)
        serial_errors = {}
        for job in ssn2.jobs.values():
            for uid, fe in job.nodes_fit_errors.items():
                serial_errors[uid] = fe
        # the serial walk records per-node reasons for EVERY task; the
        # dense/batched path's first-8 detailed errors must match it
        for uid, fe in errors.items():
            if fe.nodes:
                assert fe.error() == serial_errors[uid].error()
    finally:
        close_session(ssn2)
    if evict_on:
        assert "evict_backfill" in prof
