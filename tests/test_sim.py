"""volcano_tpu.sim — the virtual-time simulator gate (docs/DESIGN.md §12).

Four layers:
1. engine/clock units: event ordering, hash sensitivity, RNG stream
   independence;
2. smoke scenarios through the REAL stack (smoke_small fault-free,
   smoke_chaos with every fault family) — zero auditor violations, and
   the determinism contract: same seed ⇒ byte-identical event-log hash
   IN-PROCESS (the strictest form — global counters, jit caches, and
   helper state must all be properly reset between runs);
3. auditor self-test: a deliberately reintroduced evict-accounting-leak /
   phantom-pod corruption (the VOLCANO_TPU_EVICT=0-era bug class) MUST be
   caught, with a repro bundle dumped;
4. the cfg5-shaped scale gate: reduced-scale cfg5_storm end-to-end
   through the real TPU rounds solve with warm assert-no-compiles
   (full scale runs as slow).
"""

from __future__ import annotations

import copy
import json
import os
import subprocess
import sys

import pytest

from volcano_tpu.sim import (
    RngStreams,
    SimCluster,
    VirtualClock,
    load_scenario,
    scale_scenario,
)
from volcano_tpu.sim.engine import SimEngine

pytestmark = pytest.mark.sim


# ---------------------------------------------------------------------------
# 1. engine / clock units
# ---------------------------------------------------------------------------


class TestEngine:
    def test_event_order_is_time_then_schedule_order(self):
        clock = VirtualClock()
        engine = SimEngine(clock)
        seen = []
        engine.schedule_at(2.0, "b", lambda: seen.append("b"))
        engine.schedule_at(1.0, "a", lambda: seen.append("a"))
        engine.schedule_at(2.0, "c", lambda: seen.append("c"))
        engine.run_until(10.0)
        assert seen == ["a", "b", "c"]
        assert clock.now() == 10.0

    def test_log_hash_tracks_content_and_time(self):
        def run(detail):
            clock = VirtualClock()
            engine = SimEngine(clock)
            engine.schedule_at(1.0, "x", lambda: detail)
            engine.run_until(5.0)
            return engine.log_hash()

        assert run("same") == run("same")
        assert run("same") != run("different")

    def test_events_during_run_can_schedule_more(self):
        clock = VirtualClock()
        engine = SimEngine(clock)
        seen = []

        def tick():
            seen.append(clock.now())
            if clock.now() < 3.0:
                engine.schedule_in(1.0, "tick", tick)

        engine.schedule_at(1.0, "tick", tick)
        engine.run_until(10.0)
        assert seen == [1.0, 2.0, 3.0]

    def test_virtual_timestamps_strictly_increase(self):
        clock = VirtualClock()
        stamps = [clock.timestamp() for _ in range(5)]
        assert stamps == sorted(stamps)
        assert len(set(stamps)) == 5

    def test_rng_streams_stable_and_independent(self):
        a1 = RngStreams(7).stream("workload").random()
        # drawing from another stream first must not perturb this one
        rngs = RngStreams(7)
        rngs.stream("chaos:node_flap").random()
        a2 = rngs.stream("workload").random()
        assert a1 == a2
        assert RngStreams(8).stream("workload").random() != a1


# ---------------------------------------------------------------------------
# 2. smoke scenarios (tier-1 gates)
# ---------------------------------------------------------------------------


def _run(name, seed, duration=None, mutate=None, repro_dir=None):
    cfg = copy.deepcopy(load_scenario(name))
    if mutate is not None:
        mutate(cfg)
    sim = SimCluster(cfg, seed=seed, repro_dir=repro_dir)
    return sim.run(duration=duration)


class TestSmokeScenarios:
    def test_smoke_small_pipeline_converges_clean(self):
        s = _run("smoke_small", seed=7)
        assert s["sessions"] >= 15
        assert s["binds"] > 0
        assert s["jobs"]["completed"] > 0, s["jobs"]
        assert s["audit"]["checks"] >= 15
        assert s["audit"]["violations"] == 0, s["audit"]
        # lifecycles actually churned: some pods finished
        assert s["pods"]["succeeded"] > 0

    def test_smoke_chaos_every_fault_family_clean(self):
        s = _run("smoke_chaos", seed=3)
        assert s["audit"]["violations"] == 0, s["audit"]
        # the chaos actually happened — each seam was exercised
        assert s["faults"].get("node_flap", 0) >= 1, s["faults"]
        assert s["faults"].get("reset_storm", 0) >= 1, s["faults"]
        assert s["session_kills"] >= 1
        assert s["restarts"]["scheduler"] >= 1
        # ring overflow forced the reset/re-list path with DELETED
        # synthesis — the phantom-object protocol under test
        pod_mirror = s["mirrors"]["Pod"]
        assert pod_mirror["resets"] >= 1, s["mirrors"]
        assert pod_mirror["synthesized_deletes"] >= 1, s["mirrors"]

    def test_same_seed_identical_hash_in_process(self):
        a = _run("smoke_small", seed=12, duration=16.0)
        b = _run("smoke_small", seed=12, duration=16.0)
        assert a["event_log_hash"] == b["event_log_hash"]
        assert a["binds"] == b["binds"]
        assert (a["audit"]["checks"], a["audit"]["violations"]) \
            == (b["audit"]["checks"], b["audit"]["violations"])

    def test_chaos_same_seed_identical_hash_different_seed_differs(self):
        a = _run("smoke_chaos", seed=5, duration=40.0)
        b = _run("smoke_chaos", seed=5, duration=40.0)
        c = _run("smoke_chaos", seed=6, duration=40.0)
        assert a["event_log_hash"] == b["event_log_hash"]
        assert a["event_log_hash"] != c["event_log_hash"]

    def test_trace_replay_lifecycle(self):
        s = _run("trace_replay", seed=2)
        assert s["jobs"]["submitted"] == 5
        assert s["jobs"]["completed"] >= 2
        assert s["jobs"]["failed"] == 1      # trace-c carries fail: true
        assert s["jobs"]["cancelled"] == 1   # trace-d deleted at t=20
        assert s["audit"]["violations"] == 0, s["audit"]

    def test_queues_mix_evictions_run_clean(self):
        s = _run("queues_mix", seed=5, duration=120.0)
        assert s["audit"]["violations"] == 0, s["audit"]
        # overcommit + priority spread + weighted queues actually drove
        # the preempt/reclaim pipeline
        assert s["evictions"] > 0
        assert s["binds"] > 0

    def test_serving_mix_express_lane_clean(self):
        """serving_mix smoke: interactive arrivals ride the express lane
        between sessions, batch gangs stay with the sessions, and the
        express_reconciliation invariant (plus all standing rules) holds
        through flaps/restarts/kills."""
        cfg = scale_scenario(load_scenario("serving_mix"), 0.5)
        s = SimCluster(cfg, seed=11).run(duration=60.0)
        assert s["audit"]["violations"] == 0, s["audit"]
        ex = s["express"]
        assert ex is not None
        # the lane actually placed interactive arrivals...
        assert ex["placed"] > 0, ex
        # ...and every optimistic bind got a session verdict
        assert ex["placed"] == 0 or ex["reconciled"] + ex["reverted"] > 0 \
            or ex["outstanding"] <= ex["placed"], ex
        # sessions still own the (express-ineligible) batch gangs
        assert s["binds"] > ex["placed"], (s["binds"], ex)

    def test_serving_mix_same_seed_identical_hash(self):
        cfg = scale_scenario(load_scenario("serving_mix"), 0.25)
        a = SimCluster(cfg, seed=4).run(duration=45.0)
        b = SimCluster(cfg, seed=4).run(duration=45.0)
        assert a["event_log_hash"] == b["event_log_hash"]
        assert a["express"]["placed"] == b["express"]["placed"]
        assert a["express"]["reverted"] == b["express"]["reverted"]

    def test_ha_failover_fenced_takeovers_clean(self):
        """ha_failover smoke (reduced scale): three leader kills — one
        mid-defer-window, one mid-fused-chain, one mid-express-commit —
        each promoting the warm standby via the real resource-lock CAS,
        with the auditor holding the fencing balance and the takeover
        bounds through mirror 5xx storms."""
        cfg = scale_scenario(load_scenario("ha_failover"), 0.5)
        s = SimCluster(cfg, seed=7).run()
        assert s["audit"]["violations"] == 0, s["audit"]
        ha = s["ha"]
        assert ha is not None
        # every injected seam actually deposed a leader
        assert ha["leader_kills"].get("mid_defer", 0) >= 1, ha
        assert ha["leader_kills"].get("mid_chain", 0) >= 1, ha
        assert ha["leader_kills"].get("mid_express", 0) >= 1, ha
        assert sum(ha["leader_kills"].values()) >= 3
        assert ha["epoch"] >= 4  # epoch 1 + three takeovers
        # the fence actually fired (a deposed term's in-flight writes
        # were rejected) and the rejection ledger balances exactly
        fence = ha["fence"]
        assert fence["rejected"] >= 1, fence
        assert fence["rejected"] == fence["observed_by_effectors"], fence
        assert fence["epoch"] == ha["epoch"]
        # every takeover met the warm-standby contract: first led session
        # within <= 2 cycle periods, zero wholesale rebuilds, zero
        # recompiles, deposed-term express tokens drained
        assert len(ha["takeovers"]) == 3, ha["takeovers"]
        period = cfg["scheduler"]["period_s"]
        for t in ha["takeovers"]:
            assert t["first_session_at"] is not None, t
            assert t["first_session_at"] - t["at"] <= 2 * period + 1e-9, t
            assert t["rebuilds_delta"] == 0, t
            assert t["first_session_compiles"] == 0, t
            assert t["undrained_tokens"] == [], t
        # the 5xx storm raged (polls dropped) yet mirrors converged
        assert s["mirrors"]["Pod"]["dropped_polls"] >= 1, s["mirrors"]

    def test_ha_failover_same_seed_identical_hash(self):
        def strip_warmth(t):
            # first_session_compiles reflects process jit-cache warmth
            # (run b inherits run a's compiled buckets) — everything else
            # about a takeover must replay exactly
            return {k: v for k, v in t.items()
                    if k != "first_session_compiles"}

        cfg = scale_scenario(load_scenario("ha_failover"), 0.25)
        a = SimCluster(cfg, seed=5).run(duration=60.0)
        b = SimCluster(cfg, seed=5).run(duration=60.0)
        assert a["event_log_hash"] == b["event_log_hash"]
        assert a["ha"]["fence"] == b["ha"]["fence"]
        assert [strip_warmth(t) for t in a["ha"]["takeovers"]] \
            == [strip_warmth(t) for t in b["ha"]["takeovers"]]

    def test_pipeline_storm_speculation_and_mid_spec_kill_clean(self):
        """pipeline_storm smoke (reduced scale): the pipelined session
        loop under Poisson churn + express arrivals, with a leader kill
        landing while a speculative solve is in flight. The auditor's
        pipeline_no_stale_commit ledger (and every standing rule) must
        hold; the speculation must BOTH commit on quiet windows and
        discard on deltas; the mid_spec takeover must recover through the
        fencing path with zero wholesale rebuilds and no double-apply."""
        cfg = scale_scenario(load_scenario("pipeline_storm"), 0.25)
        s = SimCluster(cfg, seed=7).run(duration=100.0)
        assert s["audit"]["violations"] == 0, s["audit"]
        pipe = s["pipeline"]
        assert pipe is not None and pipe["cycles"] >= 20, pipe
        # both halves of the speculation contract actually exercised;
        # the read-set scope attributes every discard to the row family
        # that actually moved — post-seal arrivals land as phantoms of
        # the sealed snapshot, express placements as intersections with
        # the jobs the sealed solve encoded
        assert pipe["spec_applied"] >= 1, pipe
        assert pipe["spec_discards"].get("readset:phantom", 0) >= 1, pipe
        assert pipe["spec_discards"].get("readset:job", 0) >= 1, pipe
        # the commit-rate floor budget really ran (denominator past
        # min_n) and the gate regime clears it with margin — a rate at
        # the whole-fingerprint level (~0) fails the audit above
        fb = s["fallbacks"]
        assert fb["pipeline_spec_dispatched"] >= 25, fb
        assert fb["pipeline_spec_commit_rate"] >= 0.1, fb
        # never-applied, as accounting: zero stale commits, every
        # non-abandoned discard re-ran serially
        assert pipe["stale_commits"] == 0, pipe
        non_abandoned = sum(
            n for r, n in pipe["spec_discards"].items() if r != "abandoned")
        assert non_abandoned == pipe["spec_reruns"], pipe
        # the mid_spec kill actually deposed a leader with a solve in
        # flight, and the takeover met the warm-standby contract (both
        # snapshot buffers warm => zero wholesale rebuilds)
        ha = s["ha"]
        assert ha["leader_kills"].get("mid_spec", 0) >= 1, ha
        assert len(ha["takeovers"]) >= 1
        for t in ha["takeovers"]:
            assert t["rebuilds_delta"] == 0, t
            assert t["first_session_compiles"] == 0, t
            assert t["undrained_tokens"] == [], t

    def test_pipeline_storm_same_seed_identical_hash(self):
        cfg = scale_scenario(load_scenario("pipeline_storm"), 0.25)
        a = SimCluster(cfg, seed=11).run(duration=60.0)
        b = SimCluster(cfg, seed=11).run(duration=60.0)
        assert a["event_log_hash"] == b["event_log_hash"]
        assert a["pipeline"] == b["pipeline"]
        assert a["binds"] == b["binds"]

    def test_pipeline_commit_floor_budget_fails_when_tightened(self):
        """The commit-rate FLOOR is non-vacuous: requiring a near-1.0
        commit rate of the storm must FAIL the audit (the same
        proven-to-fire idiom as the max budgets)."""
        cfg = scale_scenario(load_scenario("pipeline_storm"), 0.25)
        cfg["audit"]["budgets"]["pipeline_spec_commit_rate"] = {
            "min": 0.99, "min_n": 10, "max_scale": 0.5}
        s = SimCluster(cfg, seed=7).run(duration=100.0)
        assert s["audit"]["violations"] > 0
        assert "fallback_budget" in s["audit"]["kinds"], s["audit"]

    def test_chaos_soak_pipelined_holds_commit_floor(self):
        """chaos_soak with the pipelined loop mutated on — the tier-1
        arming of the scenario's commit floor. The standing backlog
        keeps every solve-ahead non-empty, so the floor's denominator
        clears min_n, and under the full fault mix the scoped seal
        still converts the quiet windows the soak leaves (zero
        violations includes the floor AND the readset-disjoint rule)."""
        cfg = scale_scenario(load_scenario("chaos_soak"), 0.2)
        cfg["scheduler"]["pipeline"] = True
        s = SimCluster(cfg, seed=5).run(duration=240.0)
        assert s["audit"]["violations"] == 0, s["audit"]
        fb = s["fallbacks"]
        assert fb["pipeline_spec_dispatched"] >= 25, fb
        assert fb["pipeline_spec_commit_rate"] >= 0.02, fb
        # readset families carry the discard ledger under real chaos
        assert any(r.startswith("readset:")
                   for r in s["pipeline"]["spec_discards"]), s["pipeline"]

    def test_chaos_soak_commit_floor_budget_fails_when_tightened(self):
        cfg = scale_scenario(load_scenario("chaos_soak"), 0.2)
        cfg["scheduler"]["pipeline"] = True
        cfg["audit"]["budgets"]["pipeline_spec_commit_rate"] = {
            "min": 0.99, "min_n": 10, "max_scale": 0.5}
        s = SimCluster(cfg, seed=5).run(duration=240.0)
        assert s["audit"]["violations"] > 0
        assert "fallback_budget" in s["audit"]["kinds"], s["audit"]

    def test_front_door_storm_sheds_with_retry_and_converges(self):
        """front_door_storm smoke (reduced scale): a heavy-tailed
        submission storm against the intake gate plus a flow-controlled
        watcher fleet with a deliberately slow tail, through reset
        storms, mirror 5xx, and one leader kill. The auditor must hold
        the shed-with-retry and fan-out-convergence contracts (plus the
        shed/coalesce budgets and every standing rule) with zero
        violations — while the scheduler keeps committing sessions."""
        cfg = scale_scenario(load_scenario("front_door_storm"), 0.5)
        s = SimCluster(cfg, seed=7).run()
        assert s["audit"]["violations"] == 0, s["audit"]
        fd = s["front_door"]
        assert fd is not None
        # the storm actually shed — and every shed scheduled a retry,
        # with a real share re-admitted inside the horizon
        assert fd["shed_submissions"] > 50, fd
        assert fd["shed_submissions"] == fd["shed_retries_scheduled"]
        assert fd["shed_readmitted"] > 0, fd
        # priority-aware shedding: the batch class sheds at a strictly
        # higher rate than the interactive/express-eligible class
        intake = fd["intake"]
        batch_attempts = intake["admitted_batch"] + intake["shed_batch"]
        inter_attempts = (intake["admitted_interactive"]
                          + intake["shed_interactive"])
        assert batch_attempts > 0 and inter_attempts > 0
        assert (intake["shed_batch"] / batch_attempts
                > intake["shed_interactive"] / inter_attempts), intake
        # the slow tail was demoted to snapshot-resync AND converged
        # (auditor-verified: front_door_watchers ran with 0 violations)
        watch = fd["watch"]
        assert watch["counters"]["demotions"] >= 5, watch["counters"]
        assert watch["counters"]["promotions"] >= 5, watch["counters"]
        assert fd["fleet"]["resets"] >= 1
        assert fd["fleet"]["synthesized_deletes"] >= 1
        # bounded retention held (the journal-pinning fix)
        journal = watch["journal"]
        assert journal["peak_occupancy"] <= min(
            max(watch["demote_lag"], journal["cap"]),
            journal["hard_cap"])
        # the scheduler kept committing sessions through the storm (no
        # skips beyond the PR 8 staleness budget — sessions track the
        # horizon/period exactly)
        horizon = s["sim_duration_s"]
        period = cfg["scheduler"]["period_s"]
        assert s["sessions"] >= int(horizon / period) - 2, s["sessions"]
        assert s["binds"] > 100
        # the leader kill landed and the takeover met the HA contract
        assert sum(s["ha"]["leader_kills"].values()) >= 1
        # shed/coalesce rates are budget-metered in the summary
        rates = s["fallbacks"]
        assert 0.0 < rates["admission_shed_rate"] <= 0.75
        assert rates["watch_events_coalesced"] >= 0

    def test_front_door_storm_same_seed_identical_hash(self):
        cfg = scale_scenario(load_scenario("front_door_storm"), 0.25)
        a = SimCluster(cfg, seed=11).run(duration=60.0)
        b = SimCluster(cfg, seed=11).run(duration=60.0)
        assert a["event_log_hash"] == b["event_log_hash"]
        assert a["front_door"]["intake"] == b["front_door"]["intake"]
        assert a["front_door"]["watch"]["counters"] \
            == b["front_door"]["watch"]["counters"]
        assert a["binds"] == b["binds"]

    def test_front_door_shed_budget_fails_when_tightened(self):
        """The budget gate is non-vacuous: tightening the shed budget to
        an impossible bound must FAIL the audit (the same proven-to-fire
        idiom as PR 11's fallback budgets)."""
        def mutate(cfg):
            cfg["audit"]["budgets"]["admission_shed_rate"] = {
                "max": 0.001, "min_n": 10}

        cfg = scale_scenario(load_scenario("front_door_storm"), 0.5)
        mutate(cfg)
        s = SimCluster(cfg, seed=7).run(duration=60.0)
        assert s["audit"]["violations"] > 0
        assert "fallback_budget" in s["audit"]["kinds"], s["audit"]


# ---------------------------------------------------------------------------
# 3. auditor self-test (seeded bug fixtures)
# ---------------------------------------------------------------------------


class TestAuditorSelfTest:
    @pytest.mark.parametrize("kind,expected", [
        ("accounting_leak", "cache_accounting"),
        ("phantom_pod", "phantom_cache"),
    ])
    def test_seeded_bug_is_caught(self, tmp_path, kind, expected):
        def mutate(cfg):
            cfg["scheduler"]["conf"] = "default"
            cfg["faults"] = {"seeded_bug": {"kind": kind, "at_s": 5.0}}

        s = _run("smoke_small", seed=1, duration=12.0, mutate=mutate,
                 repro_dir=str(tmp_path))
        assert s["audit"]["violations"] > 0
        assert expected in s["audit"]["kinds"], s["audit"]
        bundles = sorted(tmp_path.glob("violation-*.json"))
        assert bundles, "violation must dump a repro bundle"
        bundle = json.loads(bundles[0].read_text())
        assert bundle["seed"] == 1
        assert bundle["violations"][0]["invariant"] == expected
        assert "repro_command" in bundle
        assert bundle["event_log_tail"], "bundle carries the log tail"

    def test_clean_run_dumps_nothing(self, tmp_path):
        s = _run("smoke_small", seed=7, duration=10.0,
                 repro_dir=str(tmp_path))
        assert s["audit"]["violations"] == 0
        assert not list(tmp_path.glob("violation-*.json"))


# ---------------------------------------------------------------------------
# 4. cfg5-shaped scale gate (reduced scale; full scale = slow)
# ---------------------------------------------------------------------------


def _run_cfg5(scale, duration, seed=7):
    cfg = scale_scenario(load_scenario("cfg5_storm"), scale)
    sim = SimCluster(cfg, seed=seed, repro_dir=None)
    return sim.run(duration=duration)


class TestCfg5Scale:
    def test_reduced_scale_real_tpu_solve_warm_no_compiles(self):
        s = _run_cfg5(scale=0.01, duration=60.0)
        # the storm placed to capacity and kept an overcommit backlog —
        # the warm re-solve regime
        assert s["binds"] > 300, s["binds"]
        assert s["pods"]["pending"] > 0
        assert s["audit"]["violations"] == 0, s["audit"]
        # the REAL device rounds path ran (it compiled at least once)...
        assert s["compiles"]["total"] >= 1, s["compiles"]
        # ...and the steady state is retrace-free: warm sessions re-solve
        # the same backlog through the SAME compiled program
        assert s["compiles"]["after_warmup"] == 0, s["compiles"]
        assert s["sessions"] >= 10

    @pytest.mark.slow
    def test_full_scale_cfg5_storm(self):
        # 50k tasks x 10k nodes end-to-end: store submit -> controllers ->
        # enqueue -> TPU rounds solve -> bind writeback, audited
        s = _run_cfg5(scale=1.0, duration=25.0)
        assert s["binds"] > 30000, s["binds"]
        assert s["audit"]["violations"] == 0, s["audit"]
        assert s["compiles"]["after_warmup"] == 0, s["compiles"]

    @pytest.mark.slow
    def test_full_scale_serving_mix(self):
        cfg = copy.deepcopy(load_scenario("serving_mix"))
        s = SimCluster(cfg, seed=11, repro_dir=None).run()
        assert s["audit"]["violations"] == 0, s["audit"]
        ex = s["express"]
        assert ex["placed"] > 20, ex
        assert s["binds"] > ex["placed"]

    @pytest.mark.slow
    def test_full_scale_ha_failover(self):
        cfg = copy.deepcopy(load_scenario("ha_failover"))
        s = SimCluster(cfg, seed=7, repro_dir=None).run()
        assert s["audit"]["violations"] == 0, s["audit"]
        assert sum(s["ha"]["leader_kills"].values()) >= 3
        assert s["ha"]["fence"]["rejected"] \
            == s["ha"]["fence"]["observed_by_effectors"]

    @pytest.mark.slow
    def test_full_scale_front_door_storm(self):
        cfg = copy.deepcopy(load_scenario("front_door_storm"))
        s = SimCluster(cfg, seed=7, repro_dir=None).run()
        assert s["audit"]["violations"] == 0, s["audit"]
        fd = s["front_door"]
        assert fd["shed_submissions"] > 100
        assert fd["shed_submissions"] == fd["shed_retries_scheduled"]
        assert fd["watch"]["counters"]["demotions"] > 50
        assert sum(s["ha"]["leader_kills"].values()) >= 1

    @pytest.mark.slow
    def test_chaos_soak_two_hours(self):
        cfg = copy.deepcopy(load_scenario("chaos_soak"))
        sim = SimCluster(cfg, seed=11, repro_dir=None)
        s = sim.run()
        assert s["sim_duration_s"] >= 7200.0
        assert s["audit"]["violations"] == 0, s["audit"]
        assert s["faults"].get("node_flap", 0) > 10
        assert s["mirrors"]["Pod"]["resets"] > 10


# ---------------------------------------------------------------------------
# 5. device replica under chaos (PR 13): the standing device copy of
#    cluster state rides the soak's rounds-pinned conf — coherence and
#    rebuild-rate budgets audited, and the replica must be INVISIBLE to
#    the event log (same seed, flag on vs off ⇒ byte-identical hash)
# ---------------------------------------------------------------------------


def _run_soak(seed, replica, duration):
    cfg = scale_scenario(load_scenario("chaos_soak"), 0.2)
    old = os.environ.get("VOLCANO_TPU_REPLICA")
    os.environ["VOLCANO_TPU_REPLICA"] = replica
    try:
        return SimCluster(cfg, seed=seed, repro_dir=None).run(
            duration=duration)
    finally:
        if old is None:
            os.environ.pop("VOLCANO_TPU_REPLICA", None)
        else:
            os.environ["VOLCANO_TPU_REPLICA"] = old


class TestDeviceReplicaSim:
    def test_soak_replica_clean_and_flag_invisible_to_event_log(self):
        """Shortened chaos_soak with the replica standing (default) vs
        killed (VOLCANO_TPU_REPLICA=0), same seed: the on-run must hold
        zero violations — which now includes replica_coherence and the
        replica_rebuild_rate budget — while serving real scatters across
        scheduler restarts; and the two event logs must be
        byte-identical, because the replica is a pure staging substrate
        that may never change WHAT gets scheduled."""
        a = _run_soak(seed=5, replica="1", duration=240.0)
        b = _run_soak(seed=5, replica="0", duration=240.0)

        assert a["audit"]["violations"] == 0, a["audit"]
        rep = a["replica"]
        assert rep and rep["serves"] > 0, rep
        # restarts/chaos exercised the rebuild ladder (every fresh cache
        # generation's first serve is cold) AND the delta path carried
        # steady state between faults
        assert rep["rebuilds"].get("cold", 0) >= 1, rep
        fb = a["fallbacks"]
        assert fb["replica_serves"] == rep["serves"]
        assert "replica_rebuild_rate" in fb, fb

        # flag-off: no replica anywhere in the run...
        assert b["replica"] is None, b["replica"]
        assert "replica_serves" not in b["fallbacks"]
        # ...and the schedule itself is untouched by the flag
        assert a["event_log_hash"] == b["event_log_hash"]
        assert a["binds"] == b["binds"]


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


class TestCli:
    def test_run_emits_summary_tail_line(self, tmp_path):
        out = subprocess.run(
            [sys.executable, "-m", "volcano_tpu.sim", "run", "smoke_small",
             "--seed", "4", "--duration", "8", "--quiet",
             "--repro-dir", str(tmp_path / "repro"),
             "--json", str(tmp_path / "summary.json")],
            capture_output=True, text=True, timeout=240)
        assert out.returncode == 0, out.stderr[-2000:]
        tail = out.stdout.strip().splitlines()[-1]
        summary = json.loads(tail)
        assert summary["scenario"] == "smoke_small"
        assert summary["event_log_hash"]
        assert (tmp_path / "summary.json").exists()

    def test_list_names_committed_scenarios(self):
        out = subprocess.run(
            [sys.executable, "-m", "volcano_tpu.sim", "list"],
            capture_output=True, text=True, timeout=60)
        names = out.stdout.split()
        for expected in ("smoke_small", "smoke_chaos", "cfg5_storm",
                         "chaos_soak", "queues_mix", "trace_replay"):
            assert expected in names, names
