"""Sharded rounds-mode execution + mid-scale serial-vs-rounds quality gate.

VERDICT r1 weak-spot #6: rounds mode previously had no mesh-sharded test
(the only sharded test ran the parity scan) and no mid-scale comparison
against the serial oracle in the regime BENCH actually runs. These tests
close both gaps on the 8-device virtual CPU mesh (conftest).
"""

from __future__ import annotations

import random

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from tests.helpers import make_cache, make_tiers
from tests.test_rounds import ROUNDS_ARGS, check_invariants
from tests.test_tpu_parity import DEFAULT_TIERS
from volcano_tpu.api import objects
from volcano_tpu.scheduler.framework import close_session, get_action, open_session
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list_with_pods,
)


def _mixed_cluster(n_groups, group_size, min_member, n_nodes, queues=1,
                   seed=13, node_cpu="16", node_mem="32Gi"):
    """Heterogeneous gangs over queues; capacity-tight but satisfiable."""

    def populate(c):
        rng = random.Random(seed)
        for q in range(queues):
            c.add_queue(build_queue(f"q-{q}", weight=1 + q % 3))
        for g in range(n_groups):
            pg = f"pg{g:05d}"
            c.add_pod_group(build_pod_group(
                pg, namespace="scale", min_member=min_member,
                queue=f"q-{g % queues}"))
            for i in range(group_size):
                c.add_pod(build_pod(
                    "scale", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                    {"cpu": f"{rng.choice([250, 500, 1000])}m",
                     "memory": rng.choice(["512Mi", "1Gi"])}, pg))
        for n in range(n_nodes):
            c.add_node(build_node(
                f"node-{n:05d}",
                build_resource_list_with_pods(node_cpu, node_mem, pods=64)))

    return populate


class TestShardedRounds:
    def test_mesh_sharded_rounds_non_divisible_nodes(self):
        """ROUNDS mode (not the parity scan) on an 8-device mesh with a
        node count not divisible by the mesh — exercises node-axis padding
        plus the sharded bulk solve end-to-end."""
        devs = jax.devices()
        assert len(devs) >= 8, devs
        populate = _mixed_cluster(
            n_groups=40, group_size=4, min_member=2, n_nodes=10)
        cache = make_cache()
        populate(cache)
        ssn = open_session(
            cache, make_tiers(["tpuscore"], *DEFAULT_TIERS,
                              arguments=ROUNDS_ARGS))
        mesh = Mesh(np.array(devs[:8]), ("nodes",))
        ssn.plugins["tpuscore"].mesh = mesh
        ssn.batch_allocator.mesh = mesh
        get_action("allocate").execute(ssn)
        prof = dict(ssn.plugins["tpuscore"].profile)
        close_session(ssn)
        assert prof.get("mode") == "rounds", prof
        assert "fallback" not in prof, prof
        # 160 tasks over 10x16-CPU nodes: every gang fits
        assert len(cache.binder.binds) == 160, len(cache.binder.binds)
        check_invariants(cache, 2)
        # placements actually use the whole (non-padded) node range
        used_nodes = set(cache.binder.binds.values())
        assert len(used_nodes) >= 8, used_nodes
        assert all(n.startswith("node-0000") for n in used_nodes)


@pytest.mark.slow
class TestMidScaleQualityGate:
    def test_serial_vs_rounds_5k(self):
        """~5k tasks, 250 nodes, 3 weighted queues: rounds mode must match
        the serial oracle on placement count (within 5%), respect all
        feasibility invariants, and reproduce the serial loop's fair-share
        split across queues (within 10% of total)."""
        populate = _mixed_cluster(
            n_groups=1280, group_size=4, min_member=2, n_nodes=250,
            queues=3)

        serial_cache = make_cache()
        populate(serial_cache)
        ssn = open_session(serial_cache, make_tiers(
            *DEFAULT_TIERS))
        get_action("allocate").execute(ssn)
        close_session(ssn)
        serial = dict(serial_cache.binder.binds)

        rounds_cache = make_cache()
        populate(rounds_cache)
        ssn = open_session(rounds_cache, make_tiers(
            ["tpuscore"], *DEFAULT_TIERS, arguments=ROUNDS_ARGS))
        get_action("allocate").execute(ssn)
        prof = dict(ssn.plugins["tpuscore"].profile)
        close_session(ssn)
        rounds = dict(rounds_cache.binder.binds)
        assert prof.get("mode") == "rounds", prof
        assert "fallback" not in prof, prof

        check_invariants(rounds_cache, 2)

        # placement-count parity: every node sees all tasks in rounds mode
        # (the serial loop samples), so rounds must not under-place
        assert len(rounds) >= len(serial) * 0.95, (len(rounds), len(serial))

        # fair-share: per-queue share of total bindings comparable
        def queue_shares(binds):
            per_q = {}
            for key in binds:
                g = int(key.split("/")[1][2:7])
                q = f"q-{g % 3}"
                per_q[q] = per_q.get(q, 0) + 1
            total = max(sum(per_q.values()), 1)
            return {q: n / total for q, n in per_q.items()}

        s_shares = queue_shares(serial)
        r_shares = queue_shares(rounds)
        for q in s_shares:
            assert abs(s_shares[q] - r_shares.get(q, 0.0)) < 0.10, (
                s_shares, r_shares)


    def test_serial_vs_rounds_10k_headline_regime(self):
        """VERDICT r2 item 7: quality asserted in the regime BENCH reports,
        not extrapolated — ~10k tasks over 2k nodes, 4 weighted queues,
        ~75% capacity pressure. Rounds mode must stay within 5% of the
        serial oracle's placement count, reproduce the per-queue fair-share
        split within 10%, and uphold every feasibility/gang invariant."""
        # 2k nodes x 4cpu = 8k cpu against ~5.8k cpu of demand (~73%
        # pressure): fair-share and packing decisions are real, yet the
        # workload remains satisfiable so under-placement is attributable
        populate = _mixed_cluster(
            n_groups=2500, group_size=4, min_member=2, n_nodes=2000,
            queues=4, seed=41, node_cpu="4", node_mem="8Gi")

        serial_cache = make_cache()
        populate(serial_cache)
        ssn = open_session(serial_cache, make_tiers(*DEFAULT_TIERS))
        get_action("allocate").execute(ssn)
        close_session(ssn)
        serial = dict(serial_cache.binder.binds)

        rounds_cache = make_cache()
        populate(rounds_cache)
        ssn = open_session(rounds_cache, make_tiers(
            ["tpuscore"], *DEFAULT_TIERS, arguments=ROUNDS_ARGS))
        get_action("allocate").execute(ssn)
        prof = dict(ssn.plugins["tpuscore"].profile)
        close_session(ssn)
        rounds = dict(rounds_cache.binder.binds)
        assert prof.get("mode") == "rounds", prof
        assert "fallback" not in prof, prof

        check_invariants(rounds_cache, 2)
        assert len(serial) > 5000  # the regime is real, not degenerate
        assert len(rounds) >= len(serial) * 0.95, (len(rounds), len(serial))

        def queue_shares(binds):
            per_q = {}
            for key in binds:
                g = int(key.split("/")[1][2:7])
                q = f"q-{g % 4}"
                per_q[q] = per_q.get(q, 0) + 1
            total = max(sum(per_q.values()), 1)
            return {q: n / total for q, n in per_q.items()}

        s_shares = queue_shares(serial)
        r_shares = queue_shares(rounds)
        for q in s_shares:
            assert abs(s_shares[q] - r_shares.get(q, 0.0)) < 0.10, (
                s_shares, r_shares)


@pytest.mark.slow
class TestShardedUnshardedParity:
    def test_mesh_bindings_equal_single_device_10k(self):
        """The reference's guarantee that 16-worker parallel predicate/
        score is decision-identical to serial (scheduler_helper.go:64-118)
        maps here to: the rounds solve sharded over the 8-device mesh must
        produce EXACTLY the bindings of the single-device solve. The solve
        is deterministic — scores are elementwise per node, the conflict
        cumsums are exact integer limbs, argmax ties break by index — so
        any divergence is a sharding bug (e.g. in the non-divisible
        node-axis padding masks). ~10k tasks x 1000 nodes (1000 % 8 == 0
        is avoided: 998 nodes forces real padding)."""
        devs = jax.devices()
        assert len(devs) >= 8, devs
        populate = _mixed_cluster(
            n_groups=2500, group_size=4, min_member=2, n_nodes=998,
            queues=3, seed=59, node_cpu="8", node_mem="16Gi")

        def run(mesh):
            cache = make_cache()
            populate(cache)
            ssn = open_session(cache, make_tiers(
                ["tpuscore"], *DEFAULT_TIERS, arguments=ROUNDS_ARGS))
            if mesh is not None:
                ssn.plugins["tpuscore"].mesh = mesh
                ssn.batch_allocator.mesh = mesh
            get_action("allocate").execute(ssn)
            prof = dict(ssn.plugins["tpuscore"].profile)
            close_session(ssn)
            assert prof.get("mode") == "rounds", prof
            assert "fallback" not in prof, prof
            return dict(cache.binder.binds), prof

        sharded, s_prof = run(Mesh(np.array(devs[:8]), ("nodes",)))
        unsharded, u_prof = run(None)
        assert len(sharded) >= 9000, len(sharded)
        assert sharded == unsharded, (
            f"sharded vs unsharded bindings diverge: "
            f"{len(sharded)} vs {len(unsharded)} binds; "
            f"first diffs: "
            f"{[(k, sharded.get(k), unsharded.get(k)) for k in list(set(sharded) ^ set(unsharded))[:3]] or [(k, sharded[k], unsharded[k]) for k in sharded if sharded[k] != unsharded.get(k)][:3]}")
        assert s_prof.get("rounds") == u_prof.get("rounds"), (s_prof, u_prof)


class TestFuzzInvariants:
    """Seeded fuzz: random heterogeneous clusters (selectors, taints,
    tolerations, scalar resources, priorities, varying gang sizes, tight
    capacity) — rounds mode must uphold every feasibility/gang invariant
    and not under-place vs the serial oracle."""

    @pytest.mark.parametrize("seed", [7, 23, 61, 97])
    def test_random_cluster(self, seed):
        rng = random.Random(seed)

        def populate(c):
            c.add_queue(build_queue("qa", weight=2))
            c.add_queue(build_queue("qb", weight=1))
            zones = [f"z{z}" for z in range(3)]
            for n in range(rng.randint(20, 40)):
                rl = build_resource_list_with_pods(
                    str(rng.choice([4, 8, 16])),
                    rng.choice(["8Gi", "16Gi"]), pods=32)
                if rng.random() < 0.3:
                    rl["example.com/acc"] = str(rng.choice([2, 4]))
                node = build_node(f"node-{n:03d}", rl,
                                  labels={"zone": rng.choice(zones)})
                if rng.random() < 0.15:
                    node.spec.taints.append(objects.Taint(
                        key="dedicated", value="batch",
                        effect="NoSchedule"))
                c.add_node(node)
            n_groups = rng.randint(20, 60)
            for g in range(n_groups):
                size = rng.randint(1, 6)
                mm = rng.randint(1, size)
                pg = f"pg{g:05d}"
                c.add_pod_group(build_pod_group(
                    pg, namespace="fuzz", min_member=mm,
                    queue=rng.choice(["qa", "qb"])))
                sel = ({"zone": rng.choice(zones)}
                       if rng.random() < 0.3 else None)
                tolerate = rng.random() < 0.25  # may land on tainted nodes
                for i in range(size):
                    req = {"cpu": f"{rng.choice([250, 500, 1000, 2000])}m",
                           "memory": rng.choice(["256Mi", "1Gi", "2Gi"])}
                    if rng.random() < 0.2:
                        req["example.com/acc"] = "1"
                    pod = build_pod(
                        "fuzz", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
                        req, pg, node_selector=sel,
                        priority=rng.choice([1, 10, 100]))
                    if tolerate:
                        pod.spec.tolerations.append(objects.Toleration(
                            key="dedicated", operator="Equal",
                            value="batch", effect="NoSchedule"))
                    c.add_pod(pod)
            return n_groups

        serial_cache = make_cache()
        populate(serial_cache)
        ssn = open_session(serial_cache, make_tiers(*DEFAULT_TIERS))
        get_action("allocate").execute(ssn)
        close_session(ssn)
        serial = dict(serial_cache.binder.binds)

        rng = random.Random(seed)  # identical cluster
        rounds_cache = make_cache()
        populate(rounds_cache)
        ssn = open_session(rounds_cache, make_tiers(
            ["tpuscore"], *DEFAULT_TIERS, arguments=ROUNDS_ARGS))
        get_action("allocate").execute(ssn)
        prof = dict(ssn.plugins["tpuscore"].profile)
        close_session(ssn)
        rounds = dict(rounds_cache.binder.binds)

        assert prof.get("mode") == "rounds", prof
        assert "fallback" not in prof, prof
        check_invariants(rounds_cache, 1)
        # min_member varies per gang: check exact gang atomicity per group
        counts = {}
        for key in rounds:
            pg = key.split("/")[1].rsplit("-", 1)[0]
            counts[pg] = counts.get(pg, 0) + 1
        for pg, n in counts.items():
            job = rounds_cache.jobs[f"fuzz/{pg}"]
            assert n >= job.min_available, (pg, n, job.min_available)
        # rounds sees every node (serial samples), so it should place at
        # least as much — modulo a small placement-mix divergence: under
        # tight selector/taint contention the bulk rounds can consume a
        # constrained node pool with a different task mix than the serial
        # visit order, leaving a straggler the serial order happened to fit
        # (seed 61: one 500m zone-selector task). Bounded, not systematic.
        slack = max(2, len(serial) // 50)
        assert len(rounds) >= len(serial) - slack, (len(rounds), len(serial))
