"""Columnar pod table (scheduler/cache/podtable.py): row lifecycle,
generation-validated gathers, encoder fallback on staleness, and the
solver's content-validated device-buffer cache (_stage)."""

from __future__ import annotations

import numpy as np

from volcano_tpu.api import objects
from volcano_tpu.bench.clusters import make_cache, make_tiers
import volcano_tpu.scheduler.actions  # noqa: F401
from volcano_tpu.ops import encoder, solver
from volcano_tpu.scheduler.framework import close_session, get_action, open_session
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list_with_pods,
)


def _cluster(tasks=8):
    c = make_cache()
    c.add_queue(build_queue("default"))
    for n in range(3):
        c.add_node(build_node(
            f"n{n}", build_resource_list_with_pods("8", "16Gi")))
    for g in range(tasks // 4):
        pg = f"pg{g}"
        c.add_pod_group(build_pod_group(pg, namespace="d", min_member=1))
        for i in range(4):
            c.add_pod(build_pod(
                "d", f"{pg}-{i}", "", objects.POD_PHASE_PENDING,
                {"cpu": "500m", "memory": "1Gi"}, pg, priority=i))
    return c


class TestPodTable:
    def test_rows_assigned_and_released(self):
        c = _cluster(8)
        t = c.pod_table
        assert len(t._uid_row) == 8
        # every cached task carries a valid (row, gen)
        for job in c.jobs.values():
            for task in job.tasks.values():
                assert task.row >= 0
                assert t.gen[task.row] == task.row_gen
        pod = c.jobs[next(iter(c.jobs))].tasks[
            next(iter(c.jobs[next(iter(c.jobs))].tasks))].pod
        c.delete_pod(pod)
        assert len(t._uid_row) == 7

    def test_update_bumps_generation(self):
        c = _cluster(4)
        job = next(iter(c.jobs.values()))
        task = next(iter(job.tasks.values()))
        old_row, old_gen = task.row, task.row_gen
        group = task.pod.metadata.annotations[objects.GROUP_NAME_ANNOTATION_KEY]
        new_pod = build_pod("d", task.name, "", objects.POD_PHASE_PENDING,
                            {"cpu": "2", "memory": "2Gi"}, group)
        new_pod.metadata.uid = task.uid
        c.update_pod_from_watch(task.pod, new_pod)
        new_task = c.jobs[task.job].tasks[task.uid]
        assert (new_task.row, new_task.row_gen) != (old_row, old_gen)
        # a stale (row, gen) gather must fail validation
        g = c.pod_table.gather(np.array([old_row]), np.array([old_gen]), [])
        assert g is None

    def test_gather_values_match_objects(self):
        c = _cluster(8)
        t = c.pod_table
        tasks = [task for job in c.jobs.values() for task in job.tasks.values()]
        rows = np.array([x.row for x in tasks])
        gens = np.array([x.row_gen for x in tasks])
        g = t.gather(rows, gens, [])
        assert g is not None
        for i, task in enumerate(tasks):
            assert g["cpu"][i] == task.resreq.milli_cpu
            assert g["mem"][i] == task.resreq.memory
            assert g["priority"][i] == task.priority

    def test_encoder_falls_back_on_stale_rows(self):
        """Stale rows between snapshot and encode => object-walk fallback,
        identical output."""
        c = _cluster(8)
        tiers = make_tiers(["tpuscore"], ["priority", "gang"],
                           ["drf", "predicates", "proportion", "nodeorder"])
        ssn = open_session(c, tiers)
        try:
            enc_fast = encoder.encode_session(ssn, allow_residue=True)
            # poison every session task's generation
            for job in ssn.jobs.values():
                for task in job.tasks.values():
                    task.row_gen = -99
            enc_slow = encoder.encode_session(ssn, allow_residue=True)
            assert [t.uid for t in enc_fast.task_infos] == \
                   [t.uid for t in enc_slow.task_infos]
            np.testing.assert_array_equal(
                enc_fast.arrays["task_req"], enc_slow.arrays["task_req"])
            np.testing.assert_array_equal(
                enc_fast.arrays["job_task_count"],
                enc_slow.arrays["job_task_count"])
        finally:
            close_session(ssn)

    def test_grow_past_initial_capacity(self):
        from volcano_tpu.scheduler.cache.podtable import PodTable

        t = PodTable()
        cap0 = t._cap

        class FakeTask:
            def __init__(self, i):
                self.uid = f"u{i}"
                from volcano_tpu.api.resource import Resource

                self.resreq = Resource(100.0, 1024.0)
                self.init_resreq = Resource(100.0, 1024.0)
                self.priority = 1
                self.row = -1
                self.row_gen = -1

        pods = []
        for i in range(cap0 + 10):
            pod = build_pod("d", f"p{i}", "", objects.POD_PHASE_PENDING,
                            {"cpu": "100m"})
            task = FakeTask(i)
            t.add(pod, task)
            pods.append((pod, task))
        assert t._cap > cap0
        assert len(t._uid_row) == cap0 + 10
        rows = np.array([task.row for _, task in pods])
        gens = np.array([task.row_gen for _, task in pods])
        assert t.gather(rows, gens, []) is not None


class TestDeviceBufferCache:
    def test_stage_reuses_unchanged_buffers(self):
        solver._DEVICE_CACHE.clear()
        a = {"x.f": np.arange(8, dtype=np.float32)}
        s1 = solver._stage(a)
        s2 = solver._stage({"x.f": np.arange(8, dtype=np.float32)})
        assert s1["x.f"] is s2["x.f"], "identical bytes must reuse the device twin"
        s3 = solver._stage({"x.f": np.arange(1, 9, dtype=np.float32)})
        assert s3["x.f"] is not s1["x.f"], "changed bytes must re-transfer"
        solver._DEVICE_CACHE.clear()

    def test_stage_detects_shape_and_dtype_change(self):
        solver._DEVICE_CACHE.clear()
        s1 = solver._stage({"y.i": np.arange(4, dtype=np.int32)})
        s2 = solver._stage({"y.i": np.arange(5, dtype=np.int32)})
        assert s2["y.i"].shape != s1["y.i"].shape
        s3 = solver._stage({"y.i": np.arange(5, dtype=np.int64)})
        assert np.asarray(s3["y.i"]).dtype == np.int64
        solver._DEVICE_CACHE.clear()
