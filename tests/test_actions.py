"""enqueue / backfill / preempt / reclaim action tests
(mirrors the respective *_test.go suites)."""

from tests.helpers import make_cache, make_tiers
from volcano_tpu.api import objects
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.scheduler.framework import close_session, get_action, open_session
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)


def rl(cpu, mem, pods=110):
    r = build_resource_list(cpu, mem)
    r["pods"] = pods
    return r


class SessionResult:
    def __init__(self, jobs):
        self.jobs = jobs


def run_actions(cache, tiers, *action_names):
    ssn = open_session(cache, tiers)
    for name in action_names:
        get_action(name).execute(ssn)
    jobs = dict(ssn.jobs)  # close_session clears session state
    close_session(ssn)
    return SessionResult(jobs)


class TestEnqueue:
    def test_pending_pg_flips_to_inqueue(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_node(build_node("n1", rl("4", "8Gi")))
        pg = build_pod_group("pg1", namespace="c1", min_member=1,
                             phase=objects.PodGroupPhase.PENDING,
                             min_resources=build_resource_list("1", "1Gi"))
        c.add_pod_group(pg)
        ssn = run_actions(c, make_tiers(["gang"], ["proportion"]), "enqueue")
        job = ssn.jobs["c1/pg1"]
        assert job.pod_group.status.phase == objects.PodGroupPhase.INQUEUE

    def test_overcommit_cap(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_node(build_node("n1", rl("4", "8Gi")))
        # min_resources larger than 1.2x the cluster -> stays pending
        pg = build_pod_group("pg1", namespace="c1", min_member=1,
                             phase=objects.PodGroupPhase.PENDING,
                             min_resources=build_resource_list("50", "100Gi"))
        c.add_pod_group(pg)
        ssn = run_actions(c, make_tiers(["gang"], ["proportion"]), "enqueue")
        assert ssn.jobs["c1/pg1"].pod_group.status.phase == objects.PodGroupPhase.PENDING

    def test_no_min_resources_always_inqueue(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        pg = build_pod_group("pg1", namespace="c1",
                             phase=objects.PodGroupPhase.PENDING)
        c.add_pod_group(pg)
        ssn = run_actions(c, make_tiers(["gang"], ["proportion"]), "enqueue")
        assert ssn.jobs["c1/pg1"].pod_group.status.phase == objects.PodGroupPhase.INQUEUE

    def test_queue_capability_cap(self):
        c = make_cache()
        c.add_queue(build_queue("default", capability=build_resource_list("2", "4Gi")))
        c.add_node(build_node("n1", rl("16", "32Gi")))
        pg = build_pod_group("pg1", namespace="c1",
                             phase=objects.PodGroupPhase.PENDING,
                             min_resources=build_resource_list("4", "8Gi"))
        c.add_pod_group(pg)
        ssn = run_actions(c, make_tiers(["gang"], ["proportion"]), "enqueue")
        assert ssn.jobs["c1/pg1"].pod_group.status.phase == objects.PodGroupPhase.PENDING


class TestBackfill:
    def test_best_effort_placed(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=1))
        c.add_pod(build_pod("c1", "be", "", objects.POD_PHASE_PENDING, {}, "pg1"))
        c.add_node(build_node("n1", rl("1", "1Gi")))
        run_actions(c, make_tiers(["gang"], ["predicates"]), "backfill")
        assert c.binder.binds == {"c1/be": "n1"}

    def test_non_best_effort_ignored(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=1))
        c.add_pod(build_pod("c1", "p1", "", objects.POD_PHASE_PENDING,
                            build_resource_list("1", "1Gi"), "pg1"))
        c.add_node(build_node("n1", rl("4", "8Gi")))
        run_actions(c, make_tiers(["gang"], ["predicates"]), "backfill")
        assert c.binder.binds == {}


class TestPreempt:
    def build(self):
        """One node fully used by low-priority pg1; high-priority pg2 pending."""
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_priority_class(objects.PriorityClass(
            metadata=objects.ObjectMeta(name="high"), value=1000))
        c.add_priority_class(objects.PriorityClass(
            metadata=objects.ObjectMeta(name="low"), value=1))
        pg1 = build_pod_group("pg1", namespace="c1", min_member=1)
        pg1.spec.priority_class_name = "low"
        c.add_pod_group(pg1)
        pg2 = build_pod_group("pg2", namespace="c1", min_member=1)
        pg2.spec.priority_class_name = "high"
        c.add_pod_group(pg2)
        for i in range(2):
            c.add_pod(build_pod("c1", f"low-{i}", "n1", objects.POD_PHASE_RUNNING,
                                build_resource_list("2", "4Gi"), "pg1", priority=1))
        c.add_pod(build_pod("c1", "high", "", objects.POD_PHASE_PENDING,
                            build_resource_list("2", "4Gi"), "pg2", priority=1000))
        c.add_node(build_node("n1", rl("4", "8Gi")))
        return c

    def test_preempts_lower_priority(self):
        c = self.build()
        tiers = make_tiers(["priority", "gang", "conformance"], ["drf", "predicates"])
        ssn = run_actions(c, tiers, "preempt")
        assert len(c.evictor.evicts) >= 1
        assert c.evictor.evicts[0].startswith("c1/low-")
        # preemptor pipelined onto the node
        job2 = ssn.jobs["c1/pg2"]
        assert len(job2.task_status_index.get(TaskStatus.PIPELINED, {})) == 1

    def test_no_preemption_when_gang_would_break(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        # low job needs both tasks (min_member=2): gang forbids eviction
        c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=2))
        c.add_pod_group(build_pod_group("pg2", namespace="c1", min_member=1))
        for i in range(2):
            c.add_pod(build_pod("c1", f"low-{i}", "n1", objects.POD_PHASE_RUNNING,
                                build_resource_list("2", "4Gi"), "pg1", priority=1))
        c.add_pod(build_pod("c1", "high", "", objects.POD_PHASE_PENDING,
                            build_resource_list("2", "4Gi"), "pg2", priority=1000))
        c.add_node(build_node("n1", rl("4", "8Gi")))
        tiers = make_tiers(["priority", "gang", "conformance"], ["drf", "predicates"])
        run_actions(c, tiers, "preempt")
        assert c.evictor.evicts == []


class TestReclaim:
    def test_starved_queue_reclaims(self):
        c = make_cache()
        c.add_queue(build_queue("q1", weight=1))
        c.add_queue(build_queue("q2", weight=1))
        # q1 occupies the whole node; q2's job is starved
        c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=1, queue="q1"))
        c.add_pod_group(build_pod_group("pg2", namespace="c1", min_member=1, queue="q2"))
        for i in range(2):
            c.add_pod(build_pod("c1", f"q1-{i}", "n1", objects.POD_PHASE_RUNNING,
                                build_resource_list("2", "4Gi"), "pg1"))
        c.add_pod(build_pod("c1", "starved", "", objects.POD_PHASE_PENDING,
                            build_resource_list("2", "4Gi"), "pg2"))
        c.add_node(build_node("n1", rl("4", "8Gi")))
        tiers = make_tiers(["priority", "gang", "conformance"],
                           ["drf", "proportion", "predicates"])
        ssn = run_actions(c, tiers, "reclaim")
        assert len(c.evictor.evicts) >= 1
        assert c.evictor.evicts[0].startswith("c1/q1-")

    def test_no_reclaim_within_deserved(self):
        c = make_cache()
        c.add_queue(build_queue("q1", weight=1))
        c.add_queue(build_queue("q2", weight=1))
        c.add_pod_group(build_pod_group("pg1", namespace="c1", min_member=1, queue="q1"))
        c.add_pod_group(build_pod_group("pg2", namespace="c1", min_member=1, queue="q2"))
        # q1 uses only half the node (its deserved share) -> nothing to reclaim
        c.add_pod(build_pod("c1", "q1-0", "n1", objects.POD_PHASE_RUNNING,
                            build_resource_list("2", "4Gi"), "pg1"))
        c.add_pod(build_pod("c1", "starved", "", objects.POD_PHASE_PENDING,
                            build_resource_list("4", "8Gi"), "pg2"))
        c.add_node(build_node("n1", rl("4", "8Gi")))
        tiers = make_tiers(["priority", "gang", "conformance"],
                           ["drf", "proportion", "predicates"])
        run_actions(c, tiers, "reclaim")
        assert c.evictor.evicts == []


class TestAntiAffinitySymmetryIndex:
    """The predicates plugin's anti_resident fast-path index must mirror
    node-task membership exactly, matching the full-scan oracle through
    allocate / evict (RELEASING stays resident) / statement rollback."""

    def _anti(self, labels):
        return objects.Affinity(
            pod_anti_affinity=objects.PodAntiAffinity(required_terms=[
                objects.PodAffinityTerm(
                    label_selector=objects.LabelSelector(match_labels=labels),
                    topology_key="kubernetes.io/hostname",
                )
            ])
        )

    def _cluster(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        for n in ("n1", "n2"):
            c.add_node(build_node(n, rl("8", "16Gi")))
        c.add_pod_group(build_pod_group("guard", namespace="c1", min_member=1))
        gpod = build_pod("c1", "guard-p0", "n1", objects.POD_PHASE_RUNNING,
                         {"cpu": "1", "memory": "1Gi"}, "guard",
                         labels={"app": "guard"})
        gpod.spec.affinity = self._anti({"app": "web"})
        c.add_pod(gpod)
        c.add_pod_group(build_pod_group("web", namespace="c1", min_member=1))
        c.add_pod(build_pod("c1", "web-p0", "", objects.POD_PHASE_PENDING,
                            {"cpu": "1", "memory": "1Gi"}, "web",
                            labels={"app": "web"}))
        return c

    def _fits(self, ssn, node_name):
        from volcano_tpu.api.unschedule_info import FitFailure

        task = next(iter(
            ssn.jobs["c1/web"].task_status_index[TaskStatus.PENDING].values()))
        try:
            ssn.predicate_fn(task, ssn.nodes[node_name])
            return True
        except FitFailure:
            return False

    def test_symmetry_blocks_and_survives_evict(self):
        c = self._cluster()
        ssn = open_session(c, make_tiers(["gang"], ["predicates", "proportion"]))
        assert not self._fits(ssn, "n1")  # guard's anti-affinity bars n1
        assert self._fits(ssn, "n2")

        # evict the guard: it stays on n1 as RELEASING, so symmetry must
        # still bar n1 (matches the full-scan oracle over node.tasks)
        guard = next(iter(
            ssn.jobs["c1/guard"].task_status_index[TaskStatus.RUNNING].values()))
        stmt = ssn.statement()
        stmt.evict(guard, "test")
        assert not self._fits(ssn, "n1")
        stmt.discard()  # un-evict restores RUNNING; still resident
        assert not self._fits(ssn, "n1")
        close_session(ssn)

    def test_index_tracks_statement_rollback(self):
        # allocate a second anti-affinity pod onto n2, then roll back
        c = self._cluster()
        c.add_pod_group(build_pod_group("guard2", namespace="c1", min_member=1))
        p2 = build_pod("c1", "guard2-p0", "", objects.POD_PHASE_PENDING,
                       {"cpu": "1", "memory": "1Gi"}, "guard2",
                       labels={"app": "guard2"})
        p2.spec.affinity = self._anti({"app": "web"})
        c.add_pod(p2)
        ssn = open_session(c, make_tiers(["gang"], ["predicates", "proportion"]))
        g2 = next(iter(
            ssn.jobs["c1/guard2"].task_status_index[TaskStatus.PENDING].values()))
        stmt = ssn.statement()
        stmt.allocate(g2, "n2")
        assert not self._fits(ssn, "n2")  # now barred by guard2 on n2
        stmt.discard()
        assert self._fits(ssn, "n2")  # rollback clears the residency
        close_session(ssn)
