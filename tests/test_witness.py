"""The runtime lock-witness shim (volcano_tpu/analysis/witness.py) — the
dynamic half of the VT007/VT008 static model — plus regression tests for
the real findings this PR's analysis surfaced and fixed.

Four layers:
1. seeded injections proving the witness is NOT vacuous: a deliberately
   unmarked mutation and an out-of-lock write must both be caught;
2. transparency: ``assert_no_compiles``-grade behavior is unchanged under
   ``VOLCANO_TPU_WITNESS=1`` (zero warm compiles through the real rounds
   solve) and the sim's same-seed event-log hash is byte-identical with
   the witness armed vs off;
3. the tier-1 sim scenarios (smoke_chaos, pipeline_storm) run green under
   the witness — the empirical cross-check of what VT007/VT008 claim
   lexically;
4. regressions for the surfaced fixes: the delete_queue mutation path,
   the express-lane counter lock, and the job-side fingerprint
   belt-and-braces (VT009).
"""

from __future__ import annotations

import threading

import pytest

from volcano_tpu.analysis import witness
from volcano_tpu.analysis.witness import WitnessViolation
from volcano_tpu.api.job_info import JobInfo
from volcano_tpu.scheduler.cache import SchedulerCache
from volcano_tpu.scheduler.util.test_utils import (
    build_node,
    build_queue,
    build_resource_list,
)


def _witnessed_cache(strict=True):
    cache = SchedulerCache(store=None)
    w = witness.install(cache, strict=strict)
    return cache, w


# ---------------------------------------------------------------------------
# 1. seeded injections — the witness catches what VT007/VT008 model
# ---------------------------------------------------------------------------


class TestInjections:
    def test_out_of_lock_write_is_caught(self):
        cache, w = _witnessed_cache()
        with pytest.raises(WitnessViolation, match="without the cache lock"):
            cache.jobs["ns/j"] = JobInfo("ns/j")
        assert w.summary()["kinds"] == ["out_of_lock_write"]

    def test_locked_marked_mutations_are_clean(self):
        cache, w = _witnessed_cache()
        cache.add_node(build_node("n1", build_resource_list("4", "8Gi")))
        assert w.check_session() == 0
        # a real effector-shaped mutation: mark + gen bump together
        with cache._lock:
            cache.snap_keeper.mark_node("n1")
            cache.nodes["n1"]._acct_gen += 1
        assert w.check_session() == 0

    def test_unmarked_acct_gen_bump_is_caught(self):
        cache, w = _witnessed_cache()
        cache.add_node(build_node("n1", build_resource_list("4", "8Gi")))
        w.check_session()
        with cache._lock:
            cache.nodes["n1"]._acct_gen += 1  # mutation, no mark
        with pytest.raises(WitnessViolation, match="no keeper mark"):
            w.check_session()

    def test_unmarked_job_insert_and_version_bump_are_caught(self):
        cache, w = _witnessed_cache(strict=False)
        with cache._lock:
            cache.jobs["ns/j"] = JobInfo("ns/j")  # insert, no mark
        assert w.check_session() == 1
        with cache._lock:
            cache.snap_keeper.mark_job("ns/j")
        assert w.check_session() == 0  # marked: clean again
        with cache._lock:
            cache.jobs["ns/j"]._status_version += 1  # bump, no mark
        assert w.check_session() == 1
        kinds = {v["kind"] for v in w.violations}
        assert kinds == {"unmarked_mutation"}

    def test_flush_style_sync_explains_movement(self):
        cache, w = _witnessed_cache()
        cache.add_node(build_node("n1", build_resource_list("4", "8Gi")))
        w.check_session()
        with cache._lock:
            node = cache.nodes["n1"]
            node._acct_gen += 1
            cache.snap_keeper.sync_node("n1", node._acct_gen)
        assert w.check_session() == 0

    def test_wholesale_invalidation_explains_everything(self):
        cache, w = _witnessed_cache()
        cache.add_node(build_node("n1", build_resource_list("4", "8Gi")))
        w.check_session()
        with cache._lock:
            cache.nodes["n1"]._acct_gen += 1  # unmarked...
            cache.snap_keeper.invalidate()    # ...but wholesale-rebuilt
        assert w.check_session() == 0

    def test_mark_outside_lock_is_caught(self):
        cache, w = _witnessed_cache()
        with pytest.raises(WitnessViolation, match="marks are dirty-set"):
            cache.snap_keeper.mark_job("ns/j")

    def test_install_is_idempotent(self):
        cache, w = _witnessed_cache()
        assert witness.install(cache) is w
        assert witness.get(cache) is w


# ---------------------------------------------------------------------------
# 4. regressions for the fixes the analysis surfaced
# ---------------------------------------------------------------------------


class TestSurfacedFixes:
    def test_delete_queue_unknown_does_not_invalidate(self):
        """VT007 fix: deleting a queue the cache never held must neither
        mutate the queue map nor force a wholesale snapshot rebuild."""
        cache = SchedulerCache(store=None)
        q = build_queue("known")
        cache.add_queue(q)
        gen0 = cache.snap_keeper.generation
        cache.delete_queue(build_queue("never-added"))
        assert cache.snap_keeper.generation == gen0
        assert "known" in cache.queues
        cache.delete_queue(q)
        assert cache.snap_keeper.generation == gen0 + 1
        assert "known" not in cache.queues

    def test_express_counters_exact_under_concurrent_arrivals(self):
        """VT008 fix: counter bumps share the _qlock with note_arrival,
        so cross-thread read-modify-writes cannot lose updates."""
        from volcano_tpu.express.trigger import ExpressLane

        lane = ExpressLane.__new__(ExpressLane)  # wiring-free instance
        lane._qlock = threading.Lock()
        lane._queue = __import__("collections").deque()
        lane._queued = set()
        lane.wake = threading.Event()
        lane.counters = {"arrivals": 0, "deferred": 0}

        def arrivals():
            for i in range(2000):
                lane.note_arrival(f"ns/j{i % 7}")

        threads = [threading.Thread(target=arrivals) for _ in range(4)]
        for t in threads:
            t.start()
        for _ in range(2000):
            lane._count("deferred", 1)
        for t in threads:
            t.join()
        assert lane.counters["arrivals"] == 8000
        assert lane.counters["deferred"] == 2000

    def test_job_version_is_a_fingerprint_component(self):
        """VT009 fix: an unmarked job-side status-version movement must
        move the speculation fingerprint (the node acct sum's twin), and
        the driver attributes the discard as job_version."""
        from volcano_tpu.pipeline.driver import PipelineDriver, _InFlight

        cache = SchedulerCache(store=None)
        cache.jobs["ns/j"] = JobInfo("ns/j")
        fp0 = cache.pipeline_fingerprint()
        cache.jobs["ns/j"]._status_version += 1
        fp1 = cache.pipeline_fingerprint()
        assert fp0 != fp1
        assert fp0[:5] == fp1[:5]  # dirty epoch / generation / fence /
        #                            acct untouched: only the job sum moved
        drv = PipelineDriver(cache, lambda: ([], []))
        tiers = []
        sealed = drv._fingerprint(tiers)
        cache.jobs["ns/j"]._status_version += 1
        st = _InFlight(None, [], None, None, None, sealed, [], tiers, 0.0)
        ok, reason = drv._check(st, tiers)
        assert not ok and reason == "job_version"


# ---------------------------------------------------------------------------
# 2+3. scenarios under the witness (the empirical cross-check)
# ---------------------------------------------------------------------------


def _run_scenario(name, seed, scale=1.0, duration=None):
    from volcano_tpu.sim import SimCluster, load_scenario, scale_scenario

    cfg = scale_scenario(load_scenario(name), scale)
    return SimCluster(cfg, seed=seed, repro_dir=None).run(duration=duration)


@pytest.mark.sim
class TestScenariosUnderWitness:
    def test_smoke_chaos_green_and_hash_identical(self, monkeypatch):
        """Every fault family under the witness: zero violations, and the
        armed run's event-log hash is byte-identical to the unarmed one —
        the shim observes, it never steers."""
        monkeypatch.setenv("VOLCANO_TPU_WITNESS", "1")
        on = _run_scenario("smoke_chaos", seed=5, duration=40.0)
        assert on["witness"]["violations"] == 0, on["witness"]
        assert on["witness"]["checks"] > 0
        assert on["witness"]["mark_asserts"] > 0
        assert on["audit"]["violations"] == 0
        monkeypatch.delenv("VOLCANO_TPU_WITNESS")
        off = _run_scenario("smoke_chaos", seed=5, duration=40.0)
        assert off["witness"] is None
        assert on["event_log_hash"] == off["event_log_hash"]

    def test_pipeline_storm_green_under_witness(self, monkeypatch):
        """Double-buffered speculation + leader kill under the witness:
        the keeper's buffer-pair marks, staged enqueue flips, and discard
        paths all satisfy the mutation->invalidation contract at
        runtime."""
        monkeypatch.setenv("VOLCANO_TPU_WITNESS", "1")
        s = _run_scenario("pipeline_storm", seed=11, scale=0.25,
                          duration=50.0)
        assert s["witness"]["violations"] == 0, s["witness"]
        assert s["audit"]["violations"] == 0
        assert s["pipeline"]["spec_dispatched"] > 0

    def test_no_compiles_under_witness(self, monkeypatch):
        """The shim adds no device work: the warm rounds solve stays
        compile-free with the witness armed (the assert_no_compiles
        contract, cfg5_storm-gate idiom)."""
        monkeypatch.setenv("VOLCANO_TPU_WITNESS", "1")
        s = _run_scenario("cfg5_storm", seed=7, scale=0.01, duration=30.0)
        assert s["witness"]["violations"] == 0, s["witness"]
        assert s["compiles"]["after_warmup"] == 0, s["compiles"]
        assert s["binds"] > 0
