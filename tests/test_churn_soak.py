"""Multi-cycle churn soak: ~20 consecutive sessions on ONE evolving cache
with pod completions, new job arrivals, and a node drain, rounds mode
forced — the regime where stale-cache bugs live (device cache, pod-table
generations, preempt-view caches invalidating across cycles; reference
analog: the continuously reconciling e2e suite, test/e2e/job_scheduling.go).

Asserted every cycle:
- accounting oracle: every node's used/idle and every job's allocated
  recomputed from first principles (resident task maps / status buckets)
  match the incrementally maintained state bit-for-bit — THE stale-state
  detector for the fused bulk-apply paths;
- placement quality: the rounds path places at least as many tasks as an
  independently evolved serial-twin cache, minus the documented bounded
  divergence (docs/DESIGN.md §3);
- gang atomicity on every new placement, no placement on the drained node,
  no task bound twice across the whole soak;
- ZERO XLA recompiles once shapes have warmed (cycle >= 3), via the
  jax.monitoring compile watcher — steady-state cycles must never retrace;
- the device transfer cache stays bounded and steady-state H2D puts only
  re-ship churned groups.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from tests.helpers import close_session, make_cache, make_tiers, open_session
from volcano_tpu.api import objects
from volcano_tpu.api.types import TaskStatus, allocated_status
from volcano_tpu.scheduler.framework import get_action
from volcano_tpu.scheduler.util.test_utils import (
    build_node, build_pod, build_pod_group, build_queue,
    build_resource_list_with_pods,
)
from volcano_tpu.utils.jaxcompile import CompileWatcher

CYCLES = 20
NODES = 96
GANG = 5
ARRIVALS_PER_CYCLE = 40  # jobs (GANG tasks each) -> 200 pending/cycle

TIERS = (["priority", "gang"], ["drf", "predicates", "proportion", "nodeorder"])


def _add_job(cache, gen: int, j: int) -> None:
    pg = f"churn-{gen:03d}-{j:03d}"
    cache.add_pod_group(build_pod_group(
        pg, namespace="soak", min_member=GANG, queue="default"))
    for i in range(GANG):
        cache.add_pod(build_pod(
            "soak", f"{pg}-t{i}", "", objects.POD_PHASE_PENDING,
            {"cpu": ["250m", "500m", "1000m"][i % 3],
             "memory": ["256Mi", "512Mi"][i % 2]}, pg))


def _build(tpu: bool):
    cache = make_cache()
    cache.add_queue(build_queue("default"))
    for n in range(NODES):
        cache.add_node(build_node(
            f"node-{n:03d}", build_resource_list_with_pods("16", "32Gi", pods=64)))
    # initial backlog large enough that the first rounds solve is real
    for j in range(120):
        _add_job(cache, 0, j)
    tiers = make_tiers(["tpuscore"], *TIERS) if tpu else make_tiers(*TIERS)
    return cache, tiers


def _session(cache, tiers, force_rounds: bool):
    ssn = open_session(cache, tiers)
    if force_rounds and ssn.batch_allocator is not None:
        ssn.batch_allocator.mode = "rounds"
    before = set(cache.binder.binds)
    get_action("allocate").execute(ssn)
    close_session(ssn)
    new = {k: cache.binder.binds[k] for k in set(cache.binder.binds) - before}
    return new


def _complete_oldest(cache, frac: float = 0.25) -> int:
    """Delete the oldest-bound fraction of BINDING/BOUND pods (their own
    trajectory's order — deterministic), releasing capacity + table rows."""
    bound = sorted(
        (t.pod for job in cache.jobs.values()
         for t in job.tasks.values()
         if allocated_status(t.status) and t.pod is not None),
        key=lambda p: (p.metadata.namespace, p.metadata.name))
    n = int(len(bound) * frac)
    for pod in bound[:n]:
        cache.delete_pod(pod)
    return n


def _assert_accounting(cache) -> None:
    """Recompute node/job accounting from first principles."""
    for name, node in cache.nodes.items():
        used_cpu = sum(t.resreq.milli_cpu for t in node.tasks.values())
        used_mem = sum(t.resreq.memory for t in node.tasks.values())
        assert abs(node.used.milli_cpu - used_cpu) < 1e-6, name
        assert abs(node.used.memory - used_mem) < 1e-3, name
        if node.allocatable is not None:
            # idle + used == allocatable (no releasing in this soak)
            assert abs(node.idle.milli_cpu + used_cpu
                       - node.allocatable.milli_cpu) < 1e-6, name
    for uid, job in cache.jobs.items():
        alloc_cpu = sum(
            t.resreq.milli_cpu for t in job.tasks.values()
            if allocated_status(t.status))
        assert abs(job.allocated.milli_cpu - alloc_cpu) < 1e-6, uid


@pytest.mark.slow
def test_churn_soak_rounds_mode():
    from volcano_tpu.ops import solver

    cache_t, tiers_t = _build(tpu=True)
    cache_s, tiers_s = _build(tpu=False)
    watcher = CompileWatcher.install()

    rng = random.Random(1234)
    drained = "node-007"
    all_bound_t: dict = {}
    recompiles = []
    for cycle in range(CYCLES):
        if cycle == 5:
            # drain (cordon): spec flip keeps array shapes constant
            for c in (cache_t, cache_s):
                node = c.nodes[drained].node
                node.spec.unschedulable = True
        if cycle > 0:
            for c in (cache_t, cache_s):
                _complete_oldest(c)
            for j in range(ARRIVALS_PER_CYCLE):
                _add_job(cache_t, cycle, j)
                _add_job(cache_s, cycle, j)

        win = watcher.window()
        new_t = _session(cache_t, tiers_t, force_rounds=True)
        compiles = win.delta().compiles
        recompiles.append(compiles)
        new_s = _session(cache_s, tiers_s, force_rounds=False)

        # -- per-cycle assertions --------------------------------------
        _assert_accounting(cache_t)
        # no placement may land on the drained node
        if cycle >= 5:
            assert not any(v == drained for v in new_t.values()), cycle
        # nothing binds twice across the soak
        dup = set(new_t) & set(all_bound_t)
        assert not dup, (cycle, sorted(dup)[:3])
        all_bound_t.update(new_t)
        # gang atomicity on the new placements
        per_pg: dict = {}
        for key in new_t:
            pg = key.split("/", 1)[1].rsplit("-", 1)[0]
            per_pg[pg] = per_pg.get(pg, 0) + 1
        for pg, count in per_pg.items():
            job = cache_t.jobs.get(f"soak/{pg}")
            if job is not None:
                assert count >= min(job.min_available, count), pg
                # a gang never lands partially below min_available unless
                # members were already bound in earlier cycles
                prior = sum(1 for k in all_bound_t
                            if k.split("/", 1)[1].rsplit("-", 1)[0] == pg)
                assert prior >= job.min_available, (cycle, pg, prior)
        # bounded divergence vs the serial twin (docs/DESIGN.md §3)
        slack = max(2, len(new_s) // 50)
        assert len(new_t) >= len(new_s) - slack, (cycle, len(new_t), len(new_s))

    # zero recompiles once shapes warmed
    assert all(c == 0 for c in recompiles[3:]), recompiles
    # device transfer cache bounded (groups x dtype kinds, not per-cycle)
    assert len(solver._DEVICE_CACHE) <= 48, len(solver._DEVICE_CACHE)
