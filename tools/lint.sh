#!/usr/bin/env bash
# The lint gate — the ONE definition shared by tests/test_static_analysis.py
# and any CI wrapper, so "what the gate checks" can never fork:
#   1. vclint (python -m volcano_tpu.analysis): the VT001-VT005 invariant
#      rules over the whole package, zero unsuppressed findings required
#      (rationale per rule: docs/static-analysis.md);
#   2. compileall: every module byte-compiles (import-free syntax gate).
#
# Usage: tools/lint.sh   (from anywhere; PYTHON overrides the interpreter)
set -euo pipefail
cd "$(dirname "$0")/.."
PY="${PYTHON:-python3}"
"$PY" -m volcano_tpu.analysis volcano_tpu
"$PY" -m compileall -q volcano_tpu
