#!/usr/bin/env bash
# The lint gate — the ONE definition shared by tests/test_static_analysis.py
# and any CI wrapper, so "what the gate checks" can never fork:
#   1. vclint (python -m volcano_tpu.analysis): the VT001-VT012 invariant
#      rules over the whole package — zero unsuppressed findings AND zero
#      suppression drift against tools/lint_baseline.json (a new justified
#      suppression must be landed deliberately via --write-baseline);
#      a machine-readable JSON report lands at $LINT_REPORT
#      (default /tmp/vclint_report.json) for CI archival, including
#      lint_wall_ms (this run vs cold reference, cache mode);
#   2. compileall: every module byte-compiles (import-free syntax gate).
#
# Warm runs are incremental: per-file findings are memoized by content
# hash in $LINT_CACHE (default /tmp/vclint_cache.json), so a re-run after
# editing one file only re-analyzes that file (plus the whole-program
# rules). Delete the cache file or change any analysis/*.py to force cold.
#
# Usage: tools/lint.sh   (from anywhere; PYTHON overrides the interpreter,
#                         LINT_REPORT / LINT_CACHE override the artifacts)
set -euo pipefail
cd "$(dirname "$0")/.."
PY="${PYTHON:-python3}"
"$PY" -m volcano_tpu.analysis \
    --baseline tools/lint_baseline.json \
    --report "${LINT_REPORT:-/tmp/vclint_report.json}" \
    --cache "${LINT_CACHE:-/tmp/vclint_cache.json}" \
    volcano_tpu
"$PY" -m compileall -q volcano_tpu
