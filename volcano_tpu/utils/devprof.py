"""Device-interaction profiler: sync points, D2H fetches, overlap wall.

Nothing in volcano_tpu ever fenced the device before PR 6: `dispatch_s` /
`solve_s` windows conflated queueing with compute (jax dispatch is async on
every backend), and the bench floor probe measured whatever the runtime
happened to flush. This module is the ONE place host<->device
synchronization happens so it can be counted:

- ``start_fetch(x)`` begins the D2H copy immediately (``copy_to_host_async``
  when the array supports it) and returns a wait closure; the span between
  the two calls is host work OVERLAPPED with device compute/transfer and is
  accumulated into ``overlap_s``. The wait itself is a counted sync point.
- ``fence(x=None)`` is an explicit ``block_until_ready`` barrier — with no
  argument it drains every in-flight array registered by ``start_fetch``.
  The bench places these around the floor probe and each warm sample so a
  timed window can never inherit queued work from its predecessor.
- ``session(profile)`` scopes the counters to one scheduler session; the
  collector lands ``tpu_sync_points`` / ``tpu_d2h_fetches`` /
  ``tpu_overlap_ms`` in the session profile.

The counters are honest only because every dispatch site in ops/ routes its
fetch through here (vclint VT006 guards the donation half of the contract).
Single-threaded by design, like the session loop that owns it.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

import numpy as np

# the active collector (one scheduler session at a time); counters are
# module-level so call sites need no plumbing through the action stack
_active: Optional[dict] = None

# in-flight device arrays with pending fetches/dispatches, for fence();
# entries are dropped once waited on
_inflight: List = []


def _forget(x) -> None:
    """Drop ``x`` from the in-flight list by IDENTITY. list.remove would
    compare elements with ``==``, which on device arrays broadcasts (and
    raises outright for mismatched shapes — real the moment two solves
    of different buckets are in flight, e.g. a speculative solve-ahead
    behind an uncollected predecessor)."""
    for i, t in enumerate(_inflight):
        if t is x:
            del _inflight[i]
            return


class _Collector(object):
    """Context manager installing a per-session counter dict."""

    def __init__(self, profile: dict):
        self.profile = profile
        self._prev: Optional[dict] = None

    def __enter__(self) -> dict:
        global _active
        self._prev = _active
        _active = {"sync_points": 0, "d2h_fetches": 0, "overlap_s": 0.0,
                   "fence_wait_s": 0.0, "overlappable_dispatches": 0,
                   "overlappable_rows": 0}
        return _active

    def __exit__(self, *exc) -> None:
        global _active
        counters, _active = _active, self._prev
        if counters is not None and self.profile is not None:
            self.profile["tpu_sync_points"] = counters["sync_points"]
            self.profile["tpu_d2h_fetches"] = counters["d2h_fetches"]
            self.profile["tpu_overlap_ms"] = round(
                counters["overlap_s"] * 1e3, 3)
            self.profile["tpu_fence_wait_ms"] = round(
                counters["fence_wait_s"] * 1e3, 3)
            self.profile["tpu_overlappable_dispatches"] = \
                counters["overlappable_dispatches"]
            self.profile["tpu_overlappable_rows"] = \
                counters["overlappable_rows"]


def session(profile: dict) -> _Collector:
    """Scope the counters to one session; results land in ``profile``."""
    return _Collector(profile)


def counters() -> Optional[dict]:
    """The live counter dict, or None outside any session scope."""
    return _active


def start_fetch(x) -> Callable[[], np.ndarray]:
    """Begin fetching device array ``x``; returns wait() -> np.ndarray.

    The copy starts NOW (overlapping whatever host work runs before wait),
    and the wait is the session's counted sync point. Works on plain
    numpy/host arrays too (wait degenerates to np.asarray) so callers never
    need a backend check.
    """
    t0 = time.perf_counter()
    if _active is not None:
        _active["d2h_fetches"] += 1
    copy_async = getattr(x, "copy_to_host_async", None)
    if copy_async is not None:
        try:
            copy_async()
        except Exception:  # pragma: no cover - backend without async copy
            pass
    _inflight.append(x)

    def wait() -> np.ndarray:
        t1 = time.perf_counter()
        out = np.asarray(x)
        if _active is not None:
            _active["sync_points"] += 1
            _active["overlap_s"] += t1 - t0
            _active["fence_wait_s"] += time.perf_counter() - t1
        _forget(x)
        return out

    return wait


def note_overlappable(rows: int = 0) -> None:
    """Count an async device dispatch whose result is never fetched or
    fenced by its issuer — the replica's row scatters (ops/replica.py):
    the scatter enqueues, the session's host work continues, and the
    buffers are consumed device-side by the next solve. These are the
    opposite of sync points — item 1's floor attribution subtracts them
    from the h2d traffic a real-TPU session would have to hide."""
    if _active is not None:
        _active["overlappable_dispatches"] += 1
        _active["overlappable_rows"] += int(rows)


def register(x) -> None:
    """Track a dispatched array so a later fence() drains it (for results
    that are consumed device-side rather than fetched)."""
    _inflight.append(x)


def discard(x) -> None:
    """Forget a dispatched array WITHOUT fetching it — the pipeline's
    invalidated speculative results: the device work is abandoned, the
    value is never read (the never-applied contract), and later fence()
    calls no longer wait on it."""
    _forget(x)


def fence(x=None) -> None:
    """Explicit block_until_ready barrier (a counted sync point).

    With an argument, blocks on that array/pytree; with none, drains every
    registered in-flight array. Placed only at profiling/apply boundaries —
    the overlap scheme depends on everything else staying async.
    """
    t0 = time.perf_counter()
    blocked = False
    targets = [x] if x is not None else list(_inflight)
    for t in targets:
        block = getattr(t, "block_until_ready", None)
        try:
            if block is not None:
                block()
            else:
                np.asarray(t)
            blocked = True
        except Exception:  # pragma: no cover - deleted/donated buffers
            pass
        if x is None:
            _forget(t)
    if _active is not None and blocked:
        _active["sync_points"] += 1
        _active["fence_wait_s"] += time.perf_counter() - t0


def drain() -> None:
    """fence() alias for bench call sites: drain all in-flight work."""
    fence(None)
