"""Shared low-level utilities."""
