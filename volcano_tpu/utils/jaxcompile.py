"""Compile-event accounting for steady-state guarantees.

The scheduler's latency story depends on XLA compiling each program variant
ONCE: a retrace in a warm session turns a ~100 ms cycle into a multi-second
stall (the reference never pays anything like this — its hot loop is
pre-compiled Go — so the rebuild must prove compilation is out of the
steady-state path). This watcher hooks `jax.monitoring`'s duration events
and exposes per-window deltas; bench.py records them per session, so any
warm-path retrace shows up as `compiles > 0` in the BENCH record.

Thread-safe for the single-writer / many-reader pattern JAX uses (listener
callbacks fire on whichever thread compiles).
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass

_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"
_TRACE = "/jax/core/compile/jaxpr_trace_duration"


@dataclass
class CompileStats:
    compiles: int = 0
    compile_s: float = 0.0
    traces: int = 0
    trace_s: float = 0.0


class CompileWatcher:
    """Process-global counter of XLA backend compiles + jaxpr traces.

    install() is idempotent; `window()` returns an object whose `delta()`
    yields the stats accumulated since the window was opened."""

    _instance: "CompileWatcher | None" = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._stats = CompileStats()

    @classmethod
    def install(cls) -> "CompileWatcher":
        with cls._lock:
            if cls._instance is None:
                inst = cls()
                from jax._src import monitoring

                def on_duration(event: str, duration: float, **kw) -> None:
                    if event == _BACKEND_COMPILE:
                        with inst._mu:
                            inst._stats.compiles += 1
                            inst._stats.compile_s += duration
                    elif event == _TRACE:
                        with inst._mu:
                            inst._stats.traces += 1
                            inst._stats.trace_s += duration

                monitoring.register_event_duration_secs_listener(on_duration)
                cls._instance = inst
            return cls._instance

    def snapshot(self) -> CompileStats:
        with self._mu:
            return CompileStats(**self._stats.__dict__)

    def window(self) -> "_Window":
        return _Window(self)

    @contextlib.contextmanager
    def assert_no_compiles(self, what: str = "warm path"):
        """Fail loudly if any XLA backend compile lands inside the block.

        The enforcement twin of bench.py's per-session compile deltas
        (``tpu_warm_compiles``): wrap a steady-state session in this and a
        retrace fails the TEST that introduced it, instead of surfacing as
        a multi-second stall in the next bench round. Yields the window so
        callers can also inspect trace counts."""
        win = self.window()
        yield win
        d = win.delta()
        if d.compiles:
            raise AssertionError(
                f"{what}: {d.compiles} XLA compile(s) ({d.compile_s:.3f}s, "
                f"{d.traces} retrace(s)) inside a no-compile window — the "
                f"session solve must stay ONE pre-compiled program "
                f"(docs/static-analysis.md; BENCH tpu_warm_compiles)")


class _Window:
    def __init__(self, watcher: CompileWatcher):
        self._w = watcher
        self._base = watcher.snapshot()

    def delta(self) -> CompileStats:
        now = self._w.snapshot()
        b = self._base
        return CompileStats(
            compiles=now.compiles - b.compiles,
            compile_s=now.compile_s - b.compile_s,
            traces=now.traces - b.traces,
            trace_s=now.trace_s - b.trace_s,
        )
