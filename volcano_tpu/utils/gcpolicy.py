"""Low-latency GC policy for the scheduler process.

CPython's automatic cyclic GC triggers full-heap gen2 scans from allocation
pressure — at headline scale the session/cache heap holds millions of
objects, and a collection landing inside the apply path costs 0.5-1.3s
(measured at cfg5), dwarfing the work it interrupts. The reference has no
analog problem (Go's concurrent collector); the CPython-native equivalent of
its predictable latency is the standard service recipe:

- disable *automatic* collection (refcounting still reclaims everything
  acyclic immediately — the vast majority of session garbage);
- collect explicitly at safe points, BETWEEN scheduling cycles: young
  generations every cycle, the full heap on a long stride so cyclic garbage
  still cannot accumulate unboundedly.

Scheduler._loop and bench.py install this around their cycle loops; library
users who embed a Scheduler keep whatever policy their process already has
unless they opt in.
"""

from __future__ import annotations

import gc
import threading


class LowLatencyGC:
    """Handle around the disable/collect-at-safe-points policy.

    Usage:
        policy = LowLatencyGC.install()
        while ...:
            run_cycle()
            policy.maintain()   # between cycles: young gens now, full rarely
        policy.uninstall()
    """

    FULL_EVERY = 50  # gen2 stride (cycles)

    # install/uninstall are reference-counted at class level: two scheduler
    # loops in one process (the HA active/passive topology) must not have
    # the first uninstall re-enable automatic GC under the survivor
    _installs = 0
    _outermost_was_enabled = False
    _lock = threading.Lock()  # two HA loops may install concurrently

    def __init__(self):
        self._cycles = 0
        self._active = True

    @classmethod
    def install(cls) -> "LowLatencyGC":
        with cls._lock:
            if cls._installs == 0:
                cls._outermost_was_enabled = gc.isenabled()
                gc.disable()
            cls._installs += 1
        return cls()

    def maintain(self) -> None:
        """Call between cycles (outside the latency path)."""
        self._cycles += 1
        if self._cycles % self.FULL_EVERY == 0:
            gc.collect()  # full: bounded cyclic-garbage accumulation
        else:
            gc.collect(1)  # young gens: cheap, keeps the nursery drained

    def uninstall(self) -> None:
        cls = type(self)
        with cls._lock:
            if not self._active:
                return
            self._active = False
            cls._installs -= 1
            if cls._installs == 0 and cls._outermost_was_enabled:
                gc.enable()
