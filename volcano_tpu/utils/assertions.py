"""Env-gated assertions (analog of volcano pkg/scheduler/util/assert).

By default violations log; set VOLCANO_TPU_PANIC=1 (the analog of the
reference's PANIC_ON_ERROR) to raise instead — tests enable this.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)


class AssertionViolation(AssertionError):
    pass


def panic_enabled() -> bool:
    return os.environ.get("VOLCANO_TPU_PANIC", "").lower() in ("1", "true", "yes")


def assertf(condition: bool, msg: str, *args) -> None:
    if condition:
        return
    text = msg % args if args else msg
    if panic_enabled():
        raise AssertionViolation(text)
    logger.error("assertion violated: %s", text)
