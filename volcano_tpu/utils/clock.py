"""Injectable wall-clock seam for everything that STAMPS state.

Every place the stack writes a timestamp into durable state — object
identity (``creation_timestamp``), job state transitions, pod
``start_time``/``deletion_timestamp``, recorded events — reads the clock
through :func:`now` instead of calling ``time.time()`` directly. In
production the source IS ``time.time``; the simulator
(``volcano_tpu/sim``) swaps in its virtual clock so a simulated cluster's
whole causal history is expressed in deterministic virtual time and two
runs of the same scenario+seed produce byte-identical state (the
determinism contract in docs/DESIGN.md §12).

Measurement-only reads (``perf_counter`` latency spans, thread backoffs)
deliberately do NOT go through here: they never influence a decision or a
stored value, and redirecting them would make virtual runs report fake
latencies.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

_source: Optional[Callable[[], float]] = None


def now() -> float:
    """Current time from the installed source (default: ``time.time``)."""
    src = _source
    return time.time() if src is None else src()


def set_source(source: Optional[Callable[[], float]]) -> None:
    """Install a time source (``None`` restores ``time.time``). The
    simulator installs its virtual clock for the duration of a run and
    restores the default in a ``finally`` — leaking a virtual source into
    production code paths would freeze their timestamps."""
    global _source
    _source = source
