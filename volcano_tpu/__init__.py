"""volcano-tpu: a TPU-native gang-scheduling batch framework.

A brand-new framework with the capabilities of Volcano (the Kubernetes batch
scheduler): PodGroup/Queue/Job APIs, a session-based scheduler with pluggable
actions (enqueue/allocate/backfill/preempt/reclaim) and policy plugins (gang,
DRF, proportion, priority, predicates, nodeorder, binpack, conformance), a
job-lifecycle controller manager, admission, and a CLI.

The control plane keeps the session/plugin architecture; the per-session
placement solve — predicate masks x node scores x gang feasibility x
fair-share over (tasks x nodes) — is a batched JAX/XLA constraint solve
sharded across TPU chips (see volcano_tpu.ops),
behind the plugin API so the serial loop remains as fallback and parity
oracle.
"""

from volcano_tpu.version import __version__  # noqa: E402,F401 (build metadata)
