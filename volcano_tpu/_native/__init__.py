"""Native runtime components, compiled lazily at first use.

The control plane is Python with the solve on TPU; the few remaining
interpreted hot loops (the bulk-apply writeback, the per-operation
preempt/reclaim transitions) have native equivalents here, compiled on
demand with the system toolchain into this package directory and imported
like any extension module. Every native path has a pure-Python fallback — a
missing compiler, failed build, or failed import degrades to the oracle
implementation, never to an error.
"""

from __future__ import annotations

import importlib
import logging
import os
import subprocess
import sys
import sysconfig

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
# per-module load state: name -> {"mod": module|None, "tried": bool,
# "done": bool, "thread": Thread|None}. "tried" gates re-attempts;
# "done" means the attempt fully finished (build+import) — the two differ
# while a build is in flight.
_STATE: dict = {}
# per-module build locks, deliberately OUTSIDE _STATE: _reset() must not
# clear them, or a reset mid-compile would let a second cc race the first
# on the shared .so.tmp output
_LOCKS: dict = {}


def _lock(modname: str):
    import threading

    lk = _LOCKS.get(modname)
    if lk is None:
        lk = _LOCKS.setdefault(modname, threading.Lock())
    return lk


def _paths(src: str, modname: str):
    ext = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_DIR, src), os.path.join(_DIR, modname + ext)


def _is_fresh(src_path: str, out: str) -> bool:
    return (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src_path))


def _build(src: str, modname: str) -> bool:
    src_path, out = _paths(src, modname)
    if _is_fresh(src_path, out):
        return True
    cc = sysconfig.get_config_var("CC") or "cc"
    include = sysconfig.get_paths()["include"]
    cmd = [*cc.split(), "-O2", "-fPIC", "-shared",
           f"-I{include}", src_path, "-o", out + ".tmp"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except Exception as e:  # toolchain absent / sandboxed
        logger.info("native build unavailable (%s); using Python fallback", e)
        return False
    if proc.returncode != 0:
        logger.warning("native build failed; using Python fallback:\n%s",
                       proc.stderr[-2000:])
        return False
    os.replace(out + ".tmp", out)
    return True


def _get(src: str, modname: str):
    """The compiled module, or None (callers keep the Python loop).
    Build+import attempted once per process per module. BLOCKS on the
    compiler the first time — latency-critical callers use _get_nowait.
    The per-module lock serializes a blocking call racing the background
    thread (only one cc ever writes the .so.tmp)."""
    with _lock(modname):
        st = _STATE.setdefault(
            modname, {"mod": None, "tried": False, "done": False, "thread": None})
        if st["tried"]:
            return st["mod"]
        st["tried"] = True
        try:
            if os.environ.get("VOLCANO_TPU_NO_NATIVE"):
                return None
            try:
                if _build(src, modname):
                    if _DIR not in sys.path:
                        sys.path.insert(0, _DIR)
                    st["mod"] = importlib.import_module(modname)
            except Exception:
                logger.exception(
                    "native %s unavailable; using Python fallback", modname)
                st["mod"] = None
        finally:
            st["done"] = True
        return st["mod"]


def _get_nowait(src: str, modname: str):
    """Non-blocking variant for critical paths: returns the module if it is
    already available (cached .so imports in milliseconds), else kicks the
    compile off on a background thread ONCE and returns None — the first
    session runs the Python fallback instead of waiting on cc."""
    st = _STATE.setdefault(
        modname, {"mod": None, "tried": False, "done": False, "thread": None})
    if st["done"]:
        return st["mod"]
    if os.environ.get("VOLCANO_TPU_NO_NATIVE"):
        return None
    src_path, out = _paths(src, modname)
    if _is_fresh(src_path, out):
        return _get(src, modname)  # import only — no compiler run
    if st["thread"] is None:
        import threading

        st["thread"] = threading.Thread(
            target=_get, args=(src, modname), daemon=True)
        st["thread"].start()
    return None


def _reset() -> None:
    """Forget load state so the next get_* re-evaluates the env gate and
    build (tests poke this; the .so cache on disk is untouched). The build
    locks survive, so a reset cannot let two compiles race."""
    _STATE.clear()


def settled(modname: str) -> bool:
    """True once a load attempt for `modname` fully finished (module built,
    failed, or env-disabled); False while a build is still in flight."""
    if os.environ.get("VOLCANO_TPU_NO_NATIVE"):
        return True
    st = _STATE.get(modname)
    return bool(st and st["done"])


def get_fastapply():
    return _get("fastapply.c", "_fastapply")


def get_fastapply_nowait():
    return _get_nowait("fastapply.c", "_fastapply")


def get_fasttrans():
    return _get("fasttrans.c", "_fasttrans")


def get_fasttrans_nowait():
    return _get_nowait("fasttrans.c", "_fasttrans")
