"""Native runtime components, compiled lazily at first use.

The control plane is Python with the solve on TPU; the few remaining
interpreted hot loops (the bulk-apply writeback) have native equivalents
here, compiled on demand with the system toolchain into this package
directory and imported like any extension module. Every native path has a
pure-Python fallback — a missing compiler, failed build, or failed import
degrades to the oracle implementation, never to an error.
"""

from __future__ import annotations

import importlib
import logging
import os
import subprocess
import sys
import sysconfig

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_FASTAPPLY = None
_TRIED = False
_BUILD_THREAD = None


def _paths(src: str, modname: str):
    ext = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_DIR, src), os.path.join(_DIR, modname + ext)


def _is_fresh(src_path: str, out: str) -> bool:
    return (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src_path))


def _build(src: str, modname: str) -> bool:
    src_path, out = _paths(src, modname)
    if _is_fresh(src_path, out):
        return True
    cc = sysconfig.get_config_var("CC") or "cc"
    include = sysconfig.get_paths()["include"]
    cmd = [*cc.split(), "-O2", "-fPIC", "-shared",
           f"-I{include}", src_path, "-o", out + ".tmp"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except Exception as e:  # toolchain absent / sandboxed
        logger.info("native build unavailable (%s); using Python fallback", e)
        return False
    if proc.returncode != 0:
        logger.warning("native build failed; using Python fallback:\n%s",
                       proc.stderr[-2000:])
        return False
    os.replace(out + ".tmp", out)
    return True


def get_fastapply():
    """The compiled _fastapply module, or None (callers keep the Python
    loop). Build+import attempted once per process. BLOCKS on the compiler
    the first time — latency-critical callers use get_fastapply_nowait."""
    global _FASTAPPLY, _TRIED
    if _TRIED:
        return _FASTAPPLY
    _TRIED = True
    if os.environ.get("VOLCANO_TPU_NO_NATIVE"):
        return None
    try:
        if _build("fastapply.c", "_fastapply"):
            if _DIR not in sys.path:
                sys.path.insert(0, _DIR)
            _FASTAPPLY = importlib.import_module("_fastapply")
    except Exception:
        logger.exception("native fastapply unavailable; using Python fallback")
        _FASTAPPLY = None
    return _FASTAPPLY


def get_fastapply_nowait():
    """Non-blocking variant for the apply critical path: returns the module
    if it is already available (cached .so imports in milliseconds), else
    kicks the compile off on a background thread ONCE and returns None —
    the first session runs the Python fallback instead of waiting on cc."""
    global _BUILD_THREAD
    if _TRIED:
        return _FASTAPPLY
    if os.environ.get("VOLCANO_TPU_NO_NATIVE"):
        return None
    src_path, out = _paths("fastapply.c", "_fastapply")
    if _is_fresh(src_path, out):
        return get_fastapply()  # import only — no compiler run
    if _BUILD_THREAD is None:
        import threading

        _BUILD_THREAD = threading.Thread(target=get_fastapply, daemon=True)
        _BUILD_THREAD.start()
    return None
