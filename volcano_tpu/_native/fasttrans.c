/* fasttrans — native per-operation transition engine for the
 * preempt/reclaim/backfill hot paths.
 *
 * The bulk-apply writeback (fastapply.c) nativized the allocate action's
 * whole-session commit; what remained interpreted was the PER-OPERATION
 * Statement machinery the preempt/reclaim actions execute thousands of
 * times per session (reference pkg/scheduler/framework/statement.go:29-156,
 * session.go:198-369): a task status flip is a job status-index bucket
 * move + allocated-resource boundary accounting + a node-accounting
 * transition + the DRF/proportion share event handlers — ~15 interpreted
 * calls, each microseconds, summing to hundreds of milliseconds at the
 * overcommit benchmark scale.
 *
 * This module executes one whole transition per C call, with semantics
 * IDENTICAL to the Python methods it shadows (JobInfo.update_task_status,
 * NodeInfo.update_task/add_task/remove_task, drf/proportion event
 * handlers). The Python implementations remain the behavioral oracle and
 * the fallback: a TransCtx is only built when the session's event-handler
 * set is exactly the recognized stock set (ops/fasttrans.py), and any
 * sub-case the fused paths do not model is delegated back to the original
 * Python method mid-operation (never skipped).
 *
 * The predicates plugin's resident-affinity tracker stays in Python and is
 * invoked by the wrapper (ops/fasttrans.py) after each C call, in the same
 * relative order the session would fire it; its deallocate arm is a
 * statically-verifiable no-op for RELEASING tasks (predicates.py
 * _track_deallocate guards both branches on status != RELEASING), which is
 * the one case this module skips it.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <math.h>

/* epsilon constants — volcano_tpu/api/resource.py:26-28
 * (resource_info.go:70-72) */
#define MIN_MILLI_CPU 10.0
#define MIN_MILLI_SCALAR 10.0
#define MIN_MEMORY (10.0 * 1024.0 * 1024.0)

static PyObject *s_milli_cpu, *s_memory, *s_scalar_resources, *s_status,
    *s_uid, *s_job, *s_queue, *s_node_name, *s_tasks, *s_task_status_index,
    *s_status_version, *s_allocated, *s_resreq, *s_init_resreq, *s_pod,
    *s_metadata, *s_namespace, *s_name, *s_acct_gen, *s_idle, *s_used,
    *s_releasing, *s_node, *s_state, *s_update_task_status, *s_update_task,
    *s_shared_clone, *s_priority, *s_volume_ready, *s_row, *s_row_gen,
    *s_key, *s_share, *s_dominant_resource, *s_deserved, *s_error,
    *s_pending_sum;

static int
intern_all(void)
{
#define I(var, str) if (!(var = PyUnicode_InternFromString(str))) return -1;
    I(s_milli_cpu, "milli_cpu") I(s_memory, "memory")
    I(s_scalar_resources, "scalar_resources") I(s_status, "status")
    I(s_uid, "uid") I(s_job, "job") I(s_queue, "queue")
    I(s_node_name, "node_name") I(s_tasks, "tasks")
    I(s_task_status_index, "task_status_index")
    I(s_status_version, "_status_version") I(s_allocated, "allocated")
    I(s_resreq, "resreq") I(s_init_resreq, "init_resreq") I(s_pod, "pod")
    I(s_metadata, "metadata") I(s_namespace, "namespace") I(s_name, "name")
    I(s_acct_gen, "_acct_gen") I(s_idle, "idle") I(s_used, "used")
    I(s_releasing, "releasing") I(s_node, "node") I(s_state, "state")
    I(s_update_task_status, "update_task_status")
    I(s_update_task, "update_task") I(s_shared_clone, "shared_clone")
    I(s_priority, "priority") I(s_volume_ready, "volume_ready")
    I(s_row, "row") I(s_row_gen, "row_gen") I(s_key, "key")
    I(s_share, "share") I(s_dominant_resource, "dominant_resource")
    I(s_deserved, "deserved") I(s_error, "error")
    I(s_pending_sum, "pending_sum")
#undef I
    return 0;
}

/* ------------------------------------------------------------------ */
/* small object helpers                                               */
/* ------------------------------------------------------------------ */

static int
get_f64(PyObject *obj, PyObject *attr, double *out)
{
    PyObject *v = PyObject_GetAttr(obj, attr);
    if (v == NULL)
        return -1;
    *out = PyFloat_AsDouble(v);
    Py_DECREF(v);
    if (*out == -1.0 && PyErr_Occurred())
        return -1;
    return 0;
}

static int
set_f64(PyObject *obj, PyObject *attr, double val)
{
    PyObject *v = PyFloat_FromDouble(val);
    if (v == NULL)
        return -1;
    int rc = PyObject_SetAttr(obj, attr, v);
    Py_DECREF(v);
    return rc;
}

static int
bump_int_attr(PyObject *obj, PyObject *name)
{
    PyObject *v = PyObject_GetAttr(obj, name);
    if (v == NULL)
        return -1;
    long long x = PyLong_AsLongLong(v);
    Py_DECREF(v);
    if (x == -1 && PyErr_Occurred())
        return -1;
    PyObject *nv = PyLong_FromLongLong(x + 1);
    if (nv == NULL)
        return -1;
    int rc = PyObject_SetAttr(obj, name, nv);
    Py_DECREF(nv);
    return rc;
}

/* dict-or-raise lookup helper: returns BORROWED ref or NULL (sets
 * KeyError only when raise_missing). */
static PyObject *
dict_get(PyObject *d, PyObject *key, int raise_missing)
{
    PyObject *v = PyDict_GetItemWithError(d, key);
    if (v == NULL && !PyErr_Occurred() && raise_missing)
        PyErr_SetObject(PyExc_KeyError, key);
    return v;
}

/* ------------------------------------------------------------------ */
/* Resource arithmetic twins (volcano_tpu/api/resource.py)            */
/* ------------------------------------------------------------------ */

/* rr.less_equal(self-style): le(l, r) with per-dimension epsilons —
 * exact mirror of Resource.less_equal(l=self_res, r=rr). Returns 1/0,
 * -1 on error. */
static int
res_less_equal(PyObject *l, PyObject *r)
{
    double lc, lm, rc_, rm;
    if (get_f64(l, s_milli_cpu, &lc) < 0 || get_f64(l, s_memory, &lm) < 0 ||
        get_f64(r, s_milli_cpu, &rc_) < 0 || get_f64(r, s_memory, &rm) < 0)
        return -1;
    if (!(lc < rc_ || fabs(lc - rc_) < MIN_MILLI_CPU))
        return 0;
    if (!(lm < rm || fabs(lm - rm) < MIN_MEMORY))
        return 0;
    PyObject *ls = PyObject_GetAttr(l, s_scalar_resources);
    if (ls == NULL)
        return -1;
    if (ls == Py_None) {
        Py_DECREF(ls);
        return 1;
    }
    PyObject *rs = PyObject_GetAttr(r, s_scalar_resources);
    if (rs == NULL) {
        Py_DECREF(ls);
        return -1;
    }
    int result = 1;
    PyObject *name, *quant;
    Py_ssize_t pos = 0;
    while (PyDict_Next(ls, &pos, &name, &quant)) {
        double q = PyFloat_AsDouble(quant);
        if (q == -1.0 && PyErr_Occurred()) {
            result = -1;
            break;
        }
        if (q <= MIN_MILLI_SCALAR)
            continue;
        if (rs == Py_None) {
            result = 0;
            break;
        }
        PyObject *rq = PyDict_GetItemWithError(rs, name);
        if (rq == NULL && PyErr_Occurred()) {
            result = -1;
            break;
        }
        double rv = 0.0;
        if (rq != NULL) {
            rv = PyFloat_AsDouble(rq);
            if (rv == -1.0 && PyErr_Occurred()) {
                result = -1;
                break;
            }
        }
        if (!(q < rv || fabs(q - rv) < MIN_MILLI_SCALAR)) {
            result = 0;
            break;
        }
    }
    Py_DECREF(ls);
    Py_DECREF(rs);
    return result;
}

/* res.add(rr) — exact mirror of Resource.add (mutating). */
static int
res_add(PyObject *res, PyObject *rr)
{
    double a, b;
    if (get_f64(res, s_milli_cpu, &a) < 0 || get_f64(rr, s_milli_cpu, &b) < 0)
        return -1;
    if (set_f64(res, s_milli_cpu, a + b) < 0)
        return -1;
    if (get_f64(res, s_memory, &a) < 0 || get_f64(rr, s_memory, &b) < 0)
        return -1;
    if (set_f64(res, s_memory, a + b) < 0)
        return -1;
    PyObject *rs = PyObject_GetAttr(rr, s_scalar_resources);
    if (rs == NULL)
        return -1;
    if (rs == Py_None) {
        Py_DECREF(rs);
        return 0;
    }
    PyObject *ss = PyObject_GetAttr(res, s_scalar_resources);
    if (ss == NULL) {
        Py_DECREF(rs);
        return -1;
    }
    if (ss == Py_None && PyDict_Size(rs) > 0) {
        Py_DECREF(ss);
        ss = PyDict_New();
        if (ss == NULL || PyObject_SetAttr(res, s_scalar_resources, ss) < 0) {
            Py_XDECREF(ss);
            Py_DECREF(rs);
            return -1;
        }
    }
    int rc = 0;
    if (ss != Py_None) {
        PyObject *name, *quant;
        Py_ssize_t pos = 0;
        while (PyDict_Next(rs, &pos, &name, &quant)) {
            PyObject *cur = PyDict_GetItemWithError(ss, name);
            if (cur == NULL && PyErr_Occurred()) {
                rc = -1;
                break;
            }
            double c = cur ? PyFloat_AsDouble(cur) : 0.0;
            double q = PyFloat_AsDouble(quant);
            if (PyErr_Occurred()) {
                rc = -1;
                break;
            }
            PyObject *nv = PyFloat_FromDouble(c + q);
            if (nv == NULL || PyDict_SetItem(ss, name, nv) < 0) {
                Py_XDECREF(nv);
                rc = -1;
                break;
            }
            Py_DECREF(nv);
        }
    }
    Py_DECREF(rs);
    Py_DECREF(ss);
    return rc;
}

/* res.sub(rr) — mirror of Resource.sub including the assertf sufficiency
 * check (assert_cb is volcano_tpu.utils.assertions.assertf; it logs or
 * raises per the env gate, exactly as the Python path does). */
static int
res_sub(PyObject *res, PyObject *rr, PyObject *assert_cb)
{
    int le = res_less_equal(rr, res);
    if (le < 0)
        return -1;
    if (!le) {
        PyObject *sr = PyObject_Str(res);
        PyObject *srr = sr ? PyObject_Str(rr) : NULL;
        PyObject *text = srr ? PyUnicode_FromFormat(
            "resource is not sufficient to do operation: <%U> sub <%U>",
            sr, srr) : NULL;
        Py_XDECREF(sr);
        Py_XDECREF(srr);
        if (text == NULL)
            return -1;
        PyObject *r = PyObject_CallFunctionObjArgs(assert_cb, Py_False,
                                                   text, NULL);
        Py_DECREF(text);
        if (r == NULL)
            return -1;   /* panic mode: AssertionViolation propagates */
        Py_DECREF(r);
    }
    double a, b;
    if (get_f64(res, s_milli_cpu, &a) < 0 || get_f64(rr, s_milli_cpu, &b) < 0)
        return -1;
    if (set_f64(res, s_milli_cpu, a - b) < 0)
        return -1;
    if (get_f64(res, s_memory, &a) < 0 || get_f64(rr, s_memory, &b) < 0)
        return -1;
    if (set_f64(res, s_memory, a - b) < 0)
        return -1;
    PyObject *ss = PyObject_GetAttr(res, s_scalar_resources);
    if (ss == NULL)
        return -1;
    if (ss == Py_None) {
        Py_DECREF(ss);
        return 0;
    }
    PyObject *rs = PyObject_GetAttr(rr, s_scalar_resources);
    if (rs == NULL) {
        Py_DECREF(ss);
        return -1;
    }
    int rc = 0;
    if (rs != Py_None) {
        PyObject *name, *quant;
        Py_ssize_t pos = 0;
        while (PyDict_Next(rs, &pos, &name, &quant)) {
            PyObject *cur = PyDict_GetItemWithError(ss, name);
            if (cur == NULL && PyErr_Occurred()) {
                rc = -1;
                break;
            }
            double c = cur ? PyFloat_AsDouble(cur) : 0.0;
            double q = PyFloat_AsDouble(quant);
            if (PyErr_Occurred()) {
                rc = -1;
                break;
            }
            PyObject *nv = PyFloat_FromDouble(c - q);
            if (nv == NULL || PyDict_SetItem(ss, name, nv) < 0) {
                Py_XDECREF(nv);
                rc = -1;
                break;
            }
            Py_DECREF(nv);
        }
    }
    Py_DECREF(rs);
    Py_DECREF(ss);
    return rc;
}

/* Resource.get(name) with name as a Python str — mirror including the
 * nil-map zero default. */
static int
res_get_named(PyObject *res, PyObject *name, double *out)
{
    if (PyUnicode_CompareWithASCIIString(name, "cpu") == 0)
        return get_f64(res, s_milli_cpu, out);
    if (PyUnicode_CompareWithASCIIString(name, "memory") == 0)
        return get_f64(res, s_memory, out);
    PyObject *ss = PyObject_GetAttr(res, s_scalar_resources);
    if (ss == NULL)
        return -1;
    *out = 0.0;
    if (ss != Py_None) {
        PyObject *v = PyDict_GetItemWithError(ss, name);
        if (v == NULL && PyErr_Occurred()) {
            Py_DECREF(ss);
            return -1;
        }
        if (v != NULL) {
            *out = PyFloat_AsDouble(v);
            if (*out == -1.0 && PyErr_Occurred()) {
                Py_DECREF(ss);
                return -1;
            }
        }
    }
    Py_DECREF(ss);
    return 0;
}

/* ------------------------------------------------------------------ */
/* TransCtx                                                           */
/* ------------------------------------------------------------------ */

typedef struct {
    PyObject_HEAD
    PyObject *jobs;          /* dict uid -> JobInfo */
    PyObject *nodes;         /* dict name -> NodeInfo */
    PyObject *drf_attrs;     /* dict uid -> drf._Attr, or None */
    PyObject *drf_pairs;     /* list[(name, total_value)] or None */
    PyObject *drf_ns_attrs;  /* dict namespace -> drf._Attr, or None */
    PyObject *prop_attrs;    /* dict queue_uid -> _QueueAttr, or None */
    PyObject *st_pending, *st_allocated, *st_pipelined, *st_releasing,
        *st_running, *st_binding;
    PyObject *assert_cb;     /* assertions.assertf */
    PyObject *nodestate_cls; /* NodeState */
    PyObject *phase_notready;/* NodePhase.NOT_READY */
    PyObject *logger;        /* logging.Logger for swallowed errors */
    long alloc_mask;         /* bitwise-or of allocated statuses */
} TransCtx;

static int
status_long(PyObject *st, long *out)
{
    *out = PyLong_AsLong(st);
    if (*out == -1 && PyErr_Occurred())
        return -1;
    return 0;
}

static void
TransCtx_dealloc(TransCtx *self)
{
    Py_XDECREF(self->jobs);
    Py_XDECREF(self->nodes);
    Py_XDECREF(self->drf_attrs);
    Py_XDECREF(self->drf_pairs);
    Py_XDECREF(self->drf_ns_attrs);
    Py_XDECREF(self->prop_attrs);
    Py_XDECREF(self->st_pending);
    Py_XDECREF(self->st_allocated);
    Py_XDECREF(self->st_pipelined);
    Py_XDECREF(self->st_releasing);
    Py_XDECREF(self->st_running);
    Py_XDECREF(self->st_binding);
    Py_XDECREF(self->assert_cb);
    Py_XDECREF(self->nodestate_cls);
    Py_XDECREF(self->phase_notready);
    Py_XDECREF(self->logger);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static int
TransCtx_init(TransCtx *self, PyObject *args, PyObject *kwds)
{
    PyObject *jobs, *nodes, *drf_attrs, *drf_pairs, *drf_ns_attrs,
        *prop_attrs;
    PyObject *pending, *allocated, *pipelined, *releasing, *running, *binding;
    PyObject *assert_cb, *nodestate_cls, *phase_notready, *logger;
    if (!PyArg_ParseTuple(args, "OOOOOOOOOOOOOOOO", &jobs, &nodes,
                          &drf_attrs, &drf_pairs, &drf_ns_attrs, &prop_attrs,
                          &pending, &allocated, &pipelined, &releasing,
                          &running, &binding, &assert_cb, &nodestate_cls,
                          &phase_notready, &logger))
        return -1;
#define KEEP(field, val) Py_INCREF(val); self->field = val;
    KEEP(jobs, jobs) KEEP(nodes, nodes) KEEP(drf_attrs, drf_attrs)
    KEEP(drf_pairs, drf_pairs) KEEP(drf_ns_attrs, drf_ns_attrs)
    KEEP(prop_attrs, prop_attrs)
    KEEP(st_pending, pending) KEEP(st_allocated, allocated)
    KEEP(st_pipelined, pipelined) KEEP(st_releasing, releasing)
    KEEP(st_running, running) KEEP(st_binding, binding)
    KEEP(assert_cb, assert_cb) KEEP(nodestate_cls, nodestate_cls)
    KEEP(phase_notready, phase_notready) KEEP(logger, logger)
#undef KEEP
    long a, b2, r, al;
    if (status_long(allocated, &a) < 0 || status_long(binding, &b2) < 0 ||
        status_long(running, &r) < 0)
        return -1;
    /* BOUND is not passed (never produced by these transitions) but is
     * part of the allocated set; statuses are single-bit IntFlags with
     * BOUND = BINDING << 1 (api/types.py:17-26). */
    al = a | b2 | (b2 << 1) | r;
    self->alloc_mask = al;
    return 0;
}

/* allocated_status(st) twin (api/types.py:32-40) — statuses are
 * single-bit IntFlags, so membership in the allocated set is a mask test */
static int
status_is_allocated(TransCtx *ctx, PyObject *st)
{
    long v = PyLong_AsLong(st);
    if (v == -1 && PyErr_Occurred())
        return -1;
    return (v & ctx->alloc_mask) != 0;
}

/* ------------------------------------------------------------------ */
/* JobInfo.update_task_status fused twin                               */
/* ------------------------------------------------------------------ */

/* Mirrors JobInfo.update_task_status (api/job_info.py:244-279): the fused
 * present-task path in C; absent task or mismatched request delegates to
 * the Python method itself. */
static int
job_update_task_status(TransCtx *ctx, PyObject *job, PyObject *task,
                       PyObject *new_status)
{
    PyObject *tasks = PyObject_GetAttr(job, s_tasks);
    if (tasks == NULL)
        return -1;
    PyObject *uid = PyObject_GetAttr(task, s_uid);
    if (uid == NULL) {
        Py_DECREF(tasks);
        return -1;
    }
    PyObject *stored = PyDict_GetItemWithError(tasks, uid); /* borrowed */
    if (stored == NULL && PyErr_Occurred())
        goto fail;
    int delegate = 0;
    if (stored == NULL) {
        delegate = 1;
    } else {
        PyObject *sreq = PyObject_GetAttr(stored, s_resreq);
        PyObject *treq = sreq ? PyObject_GetAttr(task, s_resreq) : NULL;
        if (treq == NULL) {
            Py_XDECREF(sreq);
            goto fail;
        }
        if (sreq != treq) {
            int ne = PyObject_RichCompareBool(sreq, treq, Py_NE);
            if (ne < 0) {
                Py_DECREF(sreq);
                Py_DECREF(treq);
                goto fail;
            }
            delegate = ne;
        }
        Py_DECREF(sreq);
        Py_DECREF(treq);
    }
    if (delegate) {
        PyObject *r = PyObject_CallMethodObjArgs(
            job, s_update_task_status, task, new_status, NULL);
        Py_DECREF(tasks);
        Py_DECREF(uid);
        if (r == NULL)
            return -1;
        Py_DECREF(r);
        return 0;
    }

    PyObject *old_status = PyObject_GetAttr(stored, s_status);
    if (old_status == NULL)
        goto fail;
    int old_alloc = status_is_allocated(ctx, old_status);
    int new_alloc = old_alloc < 0 ? -1 : status_is_allocated(ctx, new_status);
    if (new_alloc < 0) {
        Py_DECREF(old_status);
        goto fail;
    }

    /* _delete_task_index(stored) */
    PyObject *index = PyObject_GetAttr(job, s_task_status_index);
    if (index == NULL) {
        Py_DECREF(old_status);
        goto fail;
    }
    PyObject *bucket = PyDict_GetItemWithError(index, old_status);
    if (bucket == NULL && PyErr_Occurred()) {
        Py_DECREF(old_status);
        Py_DECREF(index);
        goto fail;
    }
    if (bucket != NULL) {
        if (PyDict_DelItem(bucket, uid) < 0) {
            if (!PyErr_ExceptionMatches(PyExc_KeyError)) {
                Py_DECREF(old_status);
                Py_DECREF(index);
                goto fail;
            }
            PyErr_Clear();
        }
        if (PyDict_Size(bucket) == 0) {
            if (PyDict_DelItem(index, old_status) < 0) {
                Py_DECREF(old_status);
                Py_DECREF(index);
                goto fail;
            }
        }
    }
    if (bump_int_attr(job, s_status_version) < 0) {
        Py_DECREF(old_status);
        Py_DECREF(index);
        goto fail;
    }

    /* task.status = new_status */
    if (PyObject_SetAttr(task, s_status, new_status) < 0) {
        Py_DECREF(old_status);
        Py_DECREF(index);
        goto fail;
    }

    /* allocated boundary accounting */
    if (old_alloc != new_alloc) {
        PyObject *alloc_res = PyObject_GetAttr(job, s_allocated);
        PyObject *req = alloc_res ? PyObject_GetAttr(stored, s_resreq) : NULL;
        int rc;
        if (req == NULL) {
            Py_XDECREF(alloc_res);
            Py_DECREF(old_status);
            Py_DECREF(index);
            goto fail;
        }
        if (old_alloc)
            rc = res_sub(alloc_res, req, ctx->assert_cb);
        else
            rc = res_add(alloc_res, req);
        Py_DECREF(alloc_res);
        Py_DECREF(req);
        if (rc < 0) {
            Py_DECREF(old_status);
            Py_DECREF(index);
            goto fail;
        }
    }

    /* pending boundary accounting — the PENDING-bucket request sum kept
     * incrementally on JobInfo (job_info.py update_task_status's fused
     * path), mirrored here so native transitions keep it in sync */
    {
        int old_p = (old_status == ctx->st_pending) ? 1 :
            PyObject_RichCompareBool(old_status, ctx->st_pending, Py_EQ);
        int new_p = (old_p < 0) ? -1 :
            ((new_status == ctx->st_pending) ? 1 :
             PyObject_RichCompareBool(new_status, ctx->st_pending, Py_EQ));
        if (new_p < 0) {
            Py_DECREF(old_status);
            Py_DECREF(index);
            goto fail;
        }
        if (old_p != new_p) {
            PyObject *psum = PyObject_GetAttr(job, s_pending_sum);
            PyObject *req = psum ? PyObject_GetAttr(stored, s_resreq) : NULL;
            int rc;
            if (req == NULL) {
                Py_XDECREF(psum);
                Py_DECREF(old_status);
                Py_DECREF(index);
                goto fail;
            }
            if (old_p)
                rc = res_sub(psum, req, ctx->assert_cb);
            else
                rc = res_add(psum, req);
            Py_DECREF(psum);
            Py_DECREF(req);
            if (rc < 0) {
                Py_DECREF(old_status);
                Py_DECREF(index);
                goto fail;
            }
        }
    }
    Py_DECREF(old_status);

    /* self.tasks[uid] = task; _add_task_index(task) */
    if (PyDict_SetItem(tasks, uid, task) < 0) {
        Py_DECREF(index);
        goto fail;
    }
    {
        PyObject *nbucket = PyDict_GetItemWithError(index, new_status);
        if (nbucket == NULL) {
            if (PyErr_Occurred()) {
                Py_DECREF(index);
                goto fail;
            }
            nbucket = PyDict_New();
            if (nbucket == NULL ||
                PyDict_SetItem(index, new_status, nbucket) < 0) {
                Py_XDECREF(nbucket);
                Py_DECREF(index);
                goto fail;
            }
            Py_DECREF(nbucket); /* dict holds it; borrowed below */
            nbucket = PyDict_GetItemWithError(index, new_status);
            if (nbucket == NULL) {
                Py_DECREF(index);
                goto fail;
            }
        }
        if (PyDict_SetItem(nbucket, uid, task) < 0) {
            Py_DECREF(index);
            goto fail;
        }
    }
    if (bump_int_attr(job, s_status_version) < 0) {
        Py_DECREF(index);
        goto fail;
    }
    Py_DECREF(index);
    Py_DECREF(tasks);
    Py_DECREF(uid);
    return 0;
fail:
    Py_DECREF(tasks);
    Py_DECREF(uid);
    return -1;
}

/* ------------------------------------------------------------------ */
/* NodeInfo transition twins                                           */
/* ------------------------------------------------------------------ */

/* key = pod_key(task.pod) if task.pod else f"{ns}/{name}" — both arms are
 * "namespace/name"; pods built by new_task_info share the task's metadata,
 * and TaskInfo.key precomputes exactly this string. The node-map key is
 * re-derived from the pod when present, as the Python methods do. */
static PyObject *
node_map_key(PyObject *task)
{
    PyObject *pod = PyObject_GetAttr(task, s_pod);
    if (pod == NULL)
        return NULL;
    if (pod == Py_None) {
        Py_DECREF(pod);
        PyObject *ns = PyObject_GetAttr(task, s_namespace);
        PyObject *nm = ns ? PyObject_GetAttr(task, s_name) : NULL;
        PyObject *key = nm ? PyUnicode_FromFormat("%U/%U", ns, nm) : NULL;
        Py_XDECREF(ns);
        Py_XDECREF(nm);
        return key;
    }
    PyObject *meta = PyObject_GetAttr(pod, s_metadata);
    Py_DECREF(pod);
    if (meta == NULL)
        return NULL;
    PyObject *ns = PyObject_GetAttr(meta, s_namespace);
    PyObject *nm = ns ? PyObject_GetAttr(meta, s_name) : NULL;
    Py_DECREF(meta);
    PyObject *key = nm ? PyUnicode_FromFormat("%U/%U", ns, nm) : NULL;
    Py_XDECREF(ns);
    Py_XDECREF(nm);
    return key;
}

static int
status_eq(PyObject *a, PyObject *b)
{
    if (a == b)
        return 1;
    return PyObject_RichCompareBool(a, b, Py_EQ);
}

/* NodeInfo._allocate_idle twin: idle.sub(req) after the sufficiency gate;
 * on failure sets OutOfSync and raises RuntimeError (node_info.py:101-106). */
static int
node_allocate_idle(TransCtx *ctx, PyObject *node, PyObject *req)
{
    PyObject *idle = PyObject_GetAttr(node, s_idle);
    if (idle == NULL)
        return -1;
    int le = res_less_equal(req, idle);
    if (le < 0) {
        Py_DECREF(idle);
        return -1;
    }
    if (le) {
        int rc = res_sub(idle, req, ctx->assert_cb);
        Py_DECREF(idle);
        return rc;
    }
    Py_DECREF(idle);
    PyObject *st = PyObject_CallFunction(ctx->nodestate_cls, "Os",
                                         ctx->phase_notready, "OutOfSync");
    if (st == NULL)
        return -1;
    int rc = PyObject_SetAttr(node, s_state, st);
    Py_DECREF(st);
    if (rc < 0)
        return -1;
    PyErr_SetString(PyExc_RuntimeError, "Selected node NotReady");
    return -1;
}

/* NodeInfo.update_task fused twin (node_info.py:154-200); transitions the
 * fused path does not model delegate to the Python method. */
static int
node_update_task(TransCtx *ctx, PyObject *node, PyObject *task)
{
    PyObject *key = node_map_key(task);
    if (key == NULL)
        return -1;
    PyObject *tasks = PyObject_GetAttr(node, s_tasks);
    if (tasks == NULL) {
        Py_DECREF(key);
        return -1;
    }
    PyObject *cur = PyDict_GetItemWithError(tasks, key); /* borrowed */
    Py_DECREF(key);
    if (cur == NULL && PyErr_Occurred()) {
        Py_DECREF(tasks);
        return -1;
    }
    Py_DECREF(tasks);
    if (cur == NULL) {
        /* Python raises before bumping nothing else — delegate keeps the
         * message exact (it re-raises "failed to find task ... on host") */
        PyObject *r = PyObject_CallMethodObjArgs(node, s_update_task,
                                                 task, NULL);
        if (r == NULL)
            return -1;
        Py_DECREF(r);
        return 0;
    }
    PyObject *old_st = PyObject_GetAttr(cur, s_status);
    PyObject *new_st = old_st ? PyObject_GetAttr(task, s_status) : NULL;
    if (new_st == NULL) {
        Py_XDECREF(old_st);
        return -1;
    }
    PyObject *creq = PyObject_GetAttr(cur, s_resreq);
    PyObject *treq = creq ? PyObject_GetAttr(task, s_resreq) : NULL;
    if (treq == NULL) {
        Py_XDECREF(creq);
        Py_DECREF(old_st);
        Py_DECREF(new_st);
        return -1;
    }
    int req_mismatch = 0;
    if (creq != treq) {
        req_mismatch = PyObject_RichCompareBool(creq, treq, Py_NE);
        if (req_mismatch < 0)
            goto fail;
    }
    PyObject *nobj = PyObject_GetAttr(node, s_node);
    if (nobj == NULL)
        goto fail;
    int have_node = nobj != Py_None;
    Py_DECREF(nobj);
    int old_pipelined = status_eq(old_st, ctx->st_pipelined);
    int old_releasing = old_pipelined ? 0 : status_eq(old_st, ctx->st_releasing);
    int new_pipelined = status_eq(new_st, ctx->st_pipelined);
    int new_releasing = new_pipelined ? 0 : status_eq(new_st, ctx->st_releasing);
    if (old_pipelined < 0 || old_releasing < 0 || new_pipelined < 0 ||
        new_releasing < 0)
        goto fail;
    if (req_mismatch ||
        (have_node && (old_pipelined || (old_releasing && new_pipelined)))) {
        /* legacy remove+add path — delegate whole method */
        Py_DECREF(creq);
        Py_DECREF(treq);
        Py_DECREF(old_st);
        Py_DECREF(new_st);
        PyObject *r = PyObject_CallMethodObjArgs(node, s_update_task,
                                                 task, NULL);
        if (r == NULL)
            return -1;
        Py_DECREF(r);
        return 0;
    }
    if (bump_int_attr(node, s_acct_gen) < 0)
        goto fail;
    int st_same = status_eq(old_st, new_st);
    if (st_same < 0)
        goto fail;
    if (have_node && !st_same) {
        if (new_releasing && !old_releasing) {
            PyObject *rel = PyObject_GetAttr(node, s_releasing);
            if (rel == NULL)
                goto fail;
            int rc = res_add(rel, treq);
            Py_DECREF(rel);
            if (rc < 0)
                goto fail;
        } else if (old_releasing && !new_releasing) {
            PyObject *rel = PyObject_GetAttr(node, s_releasing);
            if (rel == NULL)
                goto fail;
            int rc = res_sub(rel, treq, ctx->assert_cb);
            Py_DECREF(rel);
            if (rc < 0)
                goto fail;
        } else if (new_pipelined) { /* allocated -> PIPELINED */
            PyObject *idle = PyObject_GetAttr(node, s_idle);
            if (idle == NULL)
                goto fail;
            int rc = res_add(idle, treq);
            Py_DECREF(idle);
            if (rc < 0)
                goto fail;
            PyObject *rel = PyObject_GetAttr(node, s_releasing);
            if (rel == NULL)
                goto fail;
            rc = res_sub(rel, treq, ctx->assert_cb);
            Py_DECREF(rel);
            if (rc < 0)
                goto fail;
        }
    }
    /* in-place refresh of the node-owned clone */
    if (PyObject_SetAttr(cur, s_status, new_st) < 0)
        goto fail;
    {
        static PyObject *copy_attrs[6];
        if (copy_attrs[0] == NULL) {
            copy_attrs[0] = s_node_name;
            copy_attrs[1] = s_priority;
            copy_attrs[2] = s_volume_ready;
            copy_attrs[3] = s_init_resreq;
            copy_attrs[4] = s_row;
            copy_attrs[5] = s_row_gen;
        }
        for (int i = 0; i < 6; i++) {
            PyObject *v = PyObject_GetAttr(task, copy_attrs[i]);
            if (v == NULL)
                goto fail;
            int rc = PyObject_SetAttr(cur, copy_attrs[i], v);
            Py_DECREF(v);
            if (rc < 0)
                goto fail;
        }
        PyObject *v = PyObject_GetAttr(task, s_pod);
        if (v == NULL)
            goto fail;
        int rc = PyObject_SetAttr(cur, s_pod, v);
        Py_DECREF(v);
        if (rc < 0)
            goto fail;
    }
    Py_DECREF(creq);
    Py_DECREF(treq);
    Py_DECREF(old_st);
    Py_DECREF(new_st);
    return 0;
fail:
    Py_DECREF(creq);
    Py_DECREF(treq);
    Py_DECREF(old_st);
    Py_DECREF(new_st);
    return -1;
}

/* NodeInfo.add_task twin (node_info.py:108-132). */
static int
node_add_task(TransCtx *ctx, PyObject *node, PyObject *task)
{
    if (bump_int_attr(node, s_acct_gen) < 0)
        return -1;
    PyObject *key = node_map_key(task);
    if (key == NULL)
        return -1;
    PyObject *tasks = PyObject_GetAttr(node, s_tasks);
    if (tasks == NULL) {
        Py_DECREF(key);
        return -1;
    }
    int contains = PyDict_Contains(tasks, key);
    if (contains < 0) {
        Py_DECREF(key);
        Py_DECREF(tasks);
        return -1;
    }
    if (contains) {
        PyObject *ns = PyObject_GetAttr(task, s_namespace);
        PyObject *nm = ns ? PyObject_GetAttr(task, s_name) : NULL;
        PyObject *nn = nm ? PyObject_GetAttr(node, s_name) : NULL;
        if (nn != NULL)
            PyErr_Format(PyExc_RuntimeError,
                         "task <%U/%U> already on node <%U>", ns, nm, nn);
        Py_XDECREF(ns);
        Py_XDECREF(nm);
        Py_XDECREF(nn);
        Py_DECREF(key);
        Py_DECREF(tasks);
        return -1;
    }
    PyObject *ti = PyObject_CallMethodObjArgs(task, s_shared_clone, NULL);
    if (ti == NULL) {
        Py_DECREF(key);
        Py_DECREF(tasks);
        return -1;
    }
    PyObject *nobj = PyObject_GetAttr(node, s_node);
    if (nobj == NULL)
        goto fail;
    int have_node = nobj != Py_None;
    Py_DECREF(nobj);
    if (have_node) {
        PyObject *st = PyObject_GetAttr(ti, s_status);
        PyObject *req = st ? PyObject_GetAttr(ti, s_resreq) : NULL;
        if (req == NULL) {
            Py_XDECREF(st);
            goto fail;
        }
        int is_rel = status_eq(st, ctx->st_releasing);
        int is_pipe = is_rel ? 0 : status_eq(st, ctx->st_pipelined);
        Py_DECREF(st);
        if (is_rel < 0 || is_pipe < 0) {
            Py_DECREF(req);
            goto fail;
        }
        int rc = 0;
        if (is_rel) {
            rc = node_allocate_idle(ctx, node, req);
            if (rc == 0) {
                PyObject *rel = PyObject_GetAttr(node, s_releasing);
                rc = rel ? res_add(rel, req) : -1;
                Py_XDECREF(rel);
            }
        } else if (is_pipe) {
            PyObject *rel = PyObject_GetAttr(node, s_releasing);
            rc = rel ? res_sub(rel, req, ctx->assert_cb) : -1;
            Py_XDECREF(rel);
        } else {
            rc = node_allocate_idle(ctx, node, req);
        }
        if (rc == 0) {
            PyObject *used = PyObject_GetAttr(node, s_used);
            rc = used ? res_add(used, req) : -1;
            Py_XDECREF(used);
        }
        Py_DECREF(req);
        if (rc < 0)
            goto fail;
    }
    if (PyDict_SetItem(tasks, key, ti) < 0)
        goto fail;
    Py_DECREF(ti);
    Py_DECREF(key);
    Py_DECREF(tasks);
    return 0;
fail:
    Py_DECREF(ti);
    Py_DECREF(key);
    Py_DECREF(tasks);
    return -1;
}

/* NodeInfo.remove_task twin (node_info.py:134-152). */
static int
node_remove_task(TransCtx *ctx, PyObject *node, PyObject *task)
{
    if (bump_int_attr(node, s_acct_gen) < 0)
        return -1;
    PyObject *key = node_map_key(task);
    if (key == NULL)
        return -1;
    PyObject *tasks = PyObject_GetAttr(node, s_tasks);
    if (tasks == NULL) {
        Py_DECREF(key);
        return -1;
    }
    PyObject *cur = PyDict_GetItemWithError(tasks, key); /* borrowed */
    if (cur == NULL) {
        if (!PyErr_Occurred()) {
            PyObject *ns = PyObject_GetAttr(task, s_namespace);
            PyObject *nm = ns ? PyObject_GetAttr(task, s_name) : NULL;
            PyObject *nn = nm ? PyObject_GetAttr(node, s_name) : NULL;
            if (nn != NULL)
                PyErr_Format(PyExc_RuntimeError,
                             "failed to find task <%U/%U> on host <%U>",
                             ns, nm, nn);
            Py_XDECREF(ns);
            Py_XDECREF(nm);
            Py_XDECREF(nn);
        }
        Py_DECREF(key);
        Py_DECREF(tasks);
        return -1;
    }
    Py_INCREF(cur); /* keep alive across the del below */
    PyObject *nobj = PyObject_GetAttr(node, s_node);
    if (nobj == NULL)
        goto fail;
    int have_node = nobj != Py_None;
    Py_DECREF(nobj);
    if (have_node) {
        PyObject *st = PyObject_GetAttr(cur, s_status);
        PyObject *req = st ? PyObject_GetAttr(cur, s_resreq) : NULL;
        if (req == NULL) {
            Py_XDECREF(st);
            goto fail;
        }
        int is_rel = status_eq(st, ctx->st_releasing);
        int is_pipe = is_rel ? 0 : status_eq(st, ctx->st_pipelined);
        Py_DECREF(st);
        if (is_rel < 0 || is_pipe < 0) {
            Py_DECREF(req);
            goto fail;
        }
        int rc = 0;
        if (is_rel) {
            PyObject *rel = PyObject_GetAttr(node, s_releasing);
            rc = rel ? res_sub(rel, req, ctx->assert_cb) : -1;
            Py_XDECREF(rel);
            if (rc == 0) {
                PyObject *idle = PyObject_GetAttr(node, s_idle);
                rc = idle ? res_add(idle, req) : -1;
                Py_XDECREF(idle);
            }
        } else if (is_pipe) {
            PyObject *rel = PyObject_GetAttr(node, s_releasing);
            rc = rel ? res_add(rel, req) : -1;
            Py_XDECREF(rel);
        } else {
            PyObject *idle = PyObject_GetAttr(node, s_idle);
            rc = idle ? res_add(idle, req) : -1;
            Py_XDECREF(idle);
        }
        if (rc == 0) {
            PyObject *used = PyObject_GetAttr(node, s_used);
            rc = used ? res_sub(used, req, ctx->assert_cb) : -1;
            Py_XDECREF(used);
        }
        Py_DECREF(req);
        if (rc < 0)
            goto fail;
    }
    if (PyDict_DelItem(tasks, key) < 0)
        goto fail;
    Py_DECREF(cur);
    Py_DECREF(key);
    Py_DECREF(tasks);
    return 0;
fail:
    Py_DECREF(cur);
    Py_DECREF(key);
    Py_DECREF(tasks);
    return -1;
}

/* ------------------------------------------------------------------ */
/* plugin event-handler twins                                          */
/* ------------------------------------------------------------------ */

/* drf._update_share twin: allocated add/sub + share recompute over the
 * session-static total pairs (drf.py:52-73). */
static int
drf_attr_update(TransCtx *ctx, PyObject *attr, PyObject *req, int sign)
{
    PyObject *alloc = PyObject_GetAttr(attr, s_allocated);
    if (alloc == NULL)
        return -1;
    int rc = sign > 0 ? res_add(alloc, req)
                      : res_sub(alloc, req, ctx->assert_cb);
    if (rc < 0) {
        Py_DECREF(alloc);
        return -1;
    }
    double best = 0.0;
    PyObject *dominant = NULL; /* borrowed */
    Py_ssize_t n = PyList_GET_SIZE(ctx->drf_pairs);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pair = PyList_GET_ITEM(ctx->drf_pairs, i);
        PyObject *rn = PyTuple_GET_ITEM(pair, 0);
        double tv = PyFloat_AsDouble(PyTuple_GET_ITEM(pair, 1));
        if (tv == -1.0 && PyErr_Occurred()) {
            Py_DECREF(alloc);
            return -1;
        }
        double l;
        if (res_get_named(alloc, rn, &l) < 0) {
            Py_DECREF(alloc);
            return -1;
        }
        double s = tv == 0.0 ? (l == 0.0 ? 0.0 : 1.0) : l / tv;
        if (s > best) {
            best = s;
            dominant = rn;
        }
    }
    Py_DECREF(alloc);
    if (dominant == NULL) {
        /* share 0.0, dominant "" — mirror _calculate_share's defaults */
        PyObject *empty = PyUnicode_FromString("");
        if (empty == NULL)
            return -1;
        rc = PyObject_SetAttr(attr, s_dominant_resource, empty);
        Py_DECREF(empty);
        if (rc < 0)
            return -1;
    } else if (PyObject_SetAttr(attr, s_dominant_resource, dominant) < 0) {
        return -1;
    }
    return set_f64(attr, s_share, best);
}

/* drf on_allocate/on_deallocate (plugins/drf.py:170-186), including the
 * namespace-order arm when enabled (namespace_opts keyed by namespace). */
static int
drf_update(TransCtx *ctx, PyObject *task, int sign)
{
    if (ctx->drf_attrs == Py_None)
        return 0;
    PyObject *jobuid = PyObject_GetAttr(task, s_job);
    if (jobuid == NULL)
        return -1;
    PyObject *attr = dict_get(ctx->drf_attrs, jobuid, 1);
    Py_DECREF(jobuid);
    if (attr == NULL)
        return -1;
    PyObject *req = PyObject_GetAttr(task, s_resreq);
    if (req == NULL)
        return -1;
    if (drf_attr_update(ctx, attr, req, sign) < 0) {
        Py_DECREF(req);
        return -1;
    }
    if (ctx->drf_ns_attrs != Py_None) {
        PyObject *ns = PyObject_GetAttr(task, s_namespace);
        if (ns == NULL) {
            Py_DECREF(req);
            return -1;
        }
        PyObject *ns_attr = dict_get(ctx->drf_ns_attrs, ns, 1);
        Py_DECREF(ns);
        if (ns_attr == NULL || drf_attr_update(ctx, ns_attr, req, sign) < 0) {
            Py_DECREF(req);
            return -1;
        }
    }
    Py_DECREF(req);
    return 0;
}

/* proportion on_allocate/on_deallocate (plugins/proportion.py:156-166). */
static int
prop_update(TransCtx *ctx, PyObject *task, int sign)
{
    if (ctx->prop_attrs == Py_None)
        return 0;
    PyObject *jobuid = PyObject_GetAttr(task, s_job);
    if (jobuid == NULL)
        return -1;
    PyObject *job = dict_get(ctx->jobs, jobuid, 1); /* ssn.jobs[...] raises */
    Py_DECREF(jobuid);
    if (job == NULL)
        return -1;
    PyObject *queue = PyObject_GetAttr(job, s_queue);
    if (queue == NULL)
        return -1;
    PyObject *attr = dict_get(ctx->prop_attrs, queue, 1);
    Py_DECREF(queue);
    if (attr == NULL)
        return -1;
    PyObject *alloc = PyObject_GetAttr(attr, s_allocated);
    PyObject *req = alloc ? PyObject_GetAttr(task, s_resreq) : NULL;
    if (req == NULL) {
        Py_XDECREF(alloc);
        return -1;
    }
    int rc = sign > 0 ? res_add(alloc, req)
                      : res_sub(alloc, req, ctx->assert_cb);
    Py_DECREF(req);
    if (rc < 0) {
        Py_DECREF(alloc);
        return -1;
    }
    /* _update_share: max over deserved.resource_names() of
     * share(allocated.get(rn), deserved.get(rn)) */
    PyObject *deserved = PyObject_GetAttr(attr, s_deserved);
    if (deserved == NULL) {
        Py_DECREF(alloc);
        return -1;
    }
    double best = 0.0;
    double l, r;
    /* "cpu" then "memory" then scalar map order — resource_names() order */
    if (get_f64(alloc, s_milli_cpu, &l) < 0 ||
        get_f64(deserved, s_milli_cpu, &r) < 0)
        goto fail;
    double s = r == 0.0 ? (l == 0.0 ? 0.0 : 1.0) : l / r;
    if (s > best)
        best = s;
    if (get_f64(alloc, s_memory, &l) < 0 ||
        get_f64(deserved, s_memory, &r) < 0)
        goto fail;
    s = r == 0.0 ? (l == 0.0 ? 0.0 : 1.0) : l / r;
    if (s > best)
        best = s;
    {
        PyObject *ds = PyObject_GetAttr(deserved, s_scalar_resources);
        if (ds == NULL)
            goto fail;
        if (ds != Py_None) {
            PyObject *name, *quant;
            Py_ssize_t pos = 0;
            while (PyDict_Next(ds, &pos, &name, &quant)) {
                r = PyFloat_AsDouble(quant);
                if (r == -1.0 && PyErr_Occurred()) {
                    Py_DECREF(ds);
                    goto fail;
                }
                if (res_get_named(alloc, name, &l) < 0) {
                    Py_DECREF(ds);
                    goto fail;
                }
                s = r == 0.0 ? (l == 0.0 ? 0.0 : 1.0) : l / r;
                if (s > best)
                    best = s;
            }
        }
        Py_DECREF(ds);
    }
    Py_DECREF(alloc);
    Py_DECREF(deserved);
    return set_f64(attr, s_share, best);
fail:
    Py_DECREF(alloc);
    Py_DECREF(deserved);
    return -1;
}

/* ------------------------------------------------------------------ */
/* ctx methods: whole transitions                                      */
/* ------------------------------------------------------------------ */

static int
log_swallowed(TransCtx *ctx, const char *fmt, PyObject *a, PyObject *b)
{
    /* logger.error(fmt-with-%s, a[, b], err) — mirror of the try/except
     * logging in statement.py; the pending exception becomes the last %s
     * arg. b may be NULL for the 2-operand log lines. */
    PyObject *etype, *evalue, *etb;
    PyErr_Fetch(&etype, &evalue, &etb);
    PyObject *emsg = evalue ? PyObject_Str(evalue) : PyUnicode_FromString("");
    PyObject *r = NULL;
    if (emsg != NULL) {
        if (b != NULL)
            r = PyObject_CallMethod(ctx->logger, "error", "sOOO",
                                    fmt, a, b, emsg);
        else
            r = PyObject_CallMethod(ctx->logger, "error", "sOO",
                                    fmt, a, emsg);
    }
    Py_XDECREF(emsg);
    Py_XDECREF(etype);
    Py_XDECREF(evalue);
    Py_XDECREF(etb);
    if (r == NULL)
        return -1;
    Py_DECREF(r);
    return 0;
}

/* evict(task, strict) -> bool: statement.evict / session.evict mutation
 * core: job bucket flip to RELEASING + node transition + drf/prop
 * deallocate. strict=1 raises KeyError on a missing job (session.evict
 * semantics); strict=0 skips it (statement semantics). Returns True when
 * the status actually flipped to RELEASING — the predicates deallocate
 * tracker is a no-op then; on False (missing job, non-strict) the task's
 * status is untouched and the caller MUST fire the tracker. */
static PyObject *
TransCtx_evict(TransCtx *self, PyObject *args)
{
    PyObject *task;
    int strict;
    if (!PyArg_ParseTuple(args, "Op", &task, &strict))
        return NULL;
    PyObject *jobuid = PyObject_GetAttr(task, s_job);
    if (jobuid == NULL)
        return NULL;
    PyObject *job = PyDict_GetItemWithError(self->jobs, jobuid);
    if (job == NULL && PyErr_Occurred()) {
        Py_DECREF(jobuid);
        return NULL;
    }
    if (job == NULL && strict) {
        PyErr_Format(PyExc_KeyError, "failed to find job %U", jobuid);
        Py_DECREF(jobuid);
        return NULL;
    }
    Py_DECREF(jobuid);
    if (job != NULL &&
        job_update_task_status(self, job, task, self->st_releasing) < 0)
        return NULL;
    PyObject *host = PyObject_GetAttr(task, s_node_name);
    if (host == NULL)
        return NULL;
    PyObject *node = PyDict_GetItemWithError(self->nodes, host);
    Py_DECREF(host);
    if (node == NULL && PyErr_Occurred())
        return NULL;
    if (node != NULL && node_update_task(self, node, task) < 0)
        return NULL;
    if (drf_update(self, task, -1) < 0)
        return NULL;
    if (prop_update(self, task, -1) < 0)
        return NULL;
    if (job != NULL)
        Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

/* pipeline(task, hostname, strict): status flip to PIPELINED + node
 * add_task + drf/prop allocate. strict=1: session.pipeline KeyErrors;
 * strict=0: statement.pipeline (missing job/node skipped, add_task
 * RuntimeError swallowed with a log line). The caller (ops/fasttrans.py)
 * fires the predicates allocate tracker afterwards. */
static PyObject *
TransCtx_pipeline(TransCtx *self, PyObject *args)
{
    PyObject *task, *hostname;
    int strict;
    if (!PyArg_ParseTuple(args, "OOp", &task, &hostname, &strict))
        return NULL;
    PyObject *jobuid = PyObject_GetAttr(task, s_job);
    if (jobuid == NULL)
        return NULL;
    PyObject *job = PyDict_GetItemWithError(self->jobs, jobuid);
    if (job == NULL && PyErr_Occurred()) {
        Py_DECREF(jobuid);
        return NULL;
    }
    if (job == NULL && strict) {
        PyErr_Format(PyExc_KeyError, "failed to find job %U when pipelining",
                     jobuid);
        Py_DECREF(jobuid);
        return NULL;
    }
    Py_DECREF(jobuid);
    if (job != NULL &&
        job_update_task_status(self, job, task, self->st_pipelined) < 0)
        return NULL;
    if (PyObject_SetAttr(task, s_node_name, hostname) < 0)
        return NULL;
    PyObject *node = PyDict_GetItemWithError(self->nodes, hostname);
    if (node == NULL && PyErr_Occurred())
        return NULL;
    if (node == NULL && strict) {
        PyErr_Format(PyExc_KeyError, "failed to find node %U", hostname);
        return NULL;
    }
    if (node != NULL && node_add_task(self, node, task) < 0) {
        if (strict || !PyErr_ExceptionMatches(PyExc_RuntimeError))
            return NULL;
        PyObject *tname = PyObject_GetAttr(task, s_name);
        if (tname == NULL)
            return NULL;
        int rc = log_swallowed(self, "failed to pipeline task %s to %s: %s",
                               tname, hostname);
        Py_DECREF(tname);
        if (rc < 0)
            return NULL;
    }
    if (drf_update(self, task, 1) < 0)
        return NULL;
    if (prop_update(self, task, 1) < 0)
        return NULL;
    Py_RETURN_NONE;
}

/* unevict(task): statement discard twin of evict — status back to
 * RUNNING, node transition, drf/prop allocate (statement.py:48-60).
 * Caller fires the predicates allocate tracker afterwards. */
static PyObject *
TransCtx_unevict(TransCtx *self, PyObject *args)
{
    PyObject *task;
    if (!PyArg_ParseTuple(args, "O", &task))
        return NULL;
    PyObject *jobuid = PyObject_GetAttr(task, s_job);
    if (jobuid == NULL)
        return NULL;
    PyObject *job = PyDict_GetItemWithError(self->jobs, jobuid);
    Py_DECREF(jobuid);
    if (job == NULL && PyErr_Occurred())
        return NULL;
    if (job != NULL &&
        job_update_task_status(self, job, task, self->st_running) < 0)
        return NULL;
    PyObject *host = PyObject_GetAttr(task, s_node_name);
    if (host == NULL)
        return NULL;
    PyObject *node = PyDict_GetItemWithError(self->nodes, host);
    Py_DECREF(host);
    if (node == NULL && PyErr_Occurred())
        return NULL;
    if (node != NULL && node_update_task(self, node, task) < 0)
        return NULL;
    if (drf_update(self, task, 1) < 0)
        return NULL;
    if (prop_update(self, task, 1) < 0)
        return NULL;
    Py_RETURN_NONE;
}

/* unpipeline(task): statement discard twin of pipeline
 * (statement.py:80-92). Caller fires the predicates deallocate tracker
 * afterwards (status is PENDING — its label-index removal is real). */
static PyObject *
TransCtx_unpipeline(TransCtx *self, PyObject *args)
{
    PyObject *task;
    if (!PyArg_ParseTuple(args, "O", &task))
        return NULL;
    PyObject *jobuid = PyObject_GetAttr(task, s_job);
    if (jobuid == NULL)
        return NULL;
    PyObject *job = PyDict_GetItemWithError(self->jobs, jobuid);
    Py_DECREF(jobuid);
    if (job == NULL && PyErr_Occurred())
        return NULL;
    if (job != NULL &&
        job_update_task_status(self, job, task, self->st_pending) < 0)
        return NULL;
    PyObject *host = PyObject_GetAttr(task, s_node_name);
    if (host == NULL)
        return NULL;
    PyObject *node = PyDict_GetItemWithError(self->nodes, host);
    if (node == NULL && PyErr_Occurred()) {
        Py_DECREF(host);
        return NULL;
    }
    if (node != NULL && node_remove_task(self, node, task) < 0) {
        if (!PyErr_ExceptionMatches(PyExc_RuntimeError)) {
            Py_DECREF(host);
            return NULL;
        }
        PyObject *tname = PyObject_GetAttr(task, s_name);
        if (tname == NULL) {
            Py_DECREF(host);
            return NULL;
        }
        int rc = log_swallowed(self, "failed to unpipeline task %s: %s",
                               tname, NULL);
        Py_DECREF(tname);
        if (rc < 0) {
            Py_DECREF(host);
            return NULL;
        }
    }
    Py_DECREF(host);
    PyObject *empty = PyUnicode_FromString("");
    if (empty == NULL)
        return NULL;
    int rc = PyObject_SetAttr(task, s_node_name, empty);
    Py_DECREF(empty);
    if (rc < 0)
        return NULL;
    if (drf_update(self, task, -1) < 0)
        return NULL;
    if (prop_update(self, task, -1) < 0)
        return NULL;
    Py_RETURN_NONE;
}

/* allocate(task, hostname): session.allocate mutation core (status flip
 * to ALLOCATED + node add_task + drf/prop allocate); the gang-ready
 * dispatch loop stays in the Python caller. Both lookups raise, as
 * session.allocate does. Caller fires the predicates allocate tracker. */
static PyObject *
TransCtx_allocate(TransCtx *self, PyObject *args)
{
    PyObject *task, *hostname;
    if (!PyArg_ParseTuple(args, "OO", &task, &hostname))
        return NULL;
    PyObject *jobuid = PyObject_GetAttr(task, s_job);
    if (jobuid == NULL)
        return NULL;
    PyObject *job = PyDict_GetItemWithError(self->jobs, jobuid);
    if (job == NULL) {
        if (!PyErr_Occurred())
            PyErr_Format(PyExc_KeyError, "failed to find job %U", jobuid);
        Py_DECREF(jobuid);
        return NULL;
    }
    Py_DECREF(jobuid);
    if (job_update_task_status(self, job, task, self->st_allocated) < 0)
        return NULL;
    if (PyObject_SetAttr(task, s_node_name, hostname) < 0)
        return NULL;
    PyObject *node = PyDict_GetItemWithError(self->nodes, hostname);
    if (node == NULL) {
        if (!PyErr_Occurred())
            PyErr_Format(PyExc_KeyError, "failed to find node %U", hostname);
        return NULL;
    }
    if (node_add_task(self, node, task) < 0)
        return NULL;
    if (drf_update(self, task, 1) < 0)
        return NULL;
    if (prop_update(self, task, 1) < 0)
        return NULL;
    Py_INCREF(job);
    return job; /* the caller's gang-ready check needs it anyway */
}

/* mirror_evict(task_info) -> (cache_task, pod): the cache-side mutation
 * of SchedulerCache.evict (cache.py:417-425) under the caller-held lock:
 * find the cache's own job/task, flip to RELEASING, node transition.
 * Returns the cache's task (for resync on effector failure) and its pod
 * (for the evictor/event calls). */
static PyObject *
TransCtx_mirror_evict(TransCtx *self, PyObject *args)
{
    PyObject *ti;
    if (!PyArg_ParseTuple(args, "O", &ti))
        return NULL;
    PyObject *jobuid = PyObject_GetAttr(ti, s_job);
    if (jobuid == NULL)
        return NULL;
    PyObject *job = PyDict_GetItemWithError(self->jobs, jobuid);
    if (job == NULL) {
        if (!PyErr_Occurred()) {
            PyObject *uid = PyObject_GetAttr(ti, s_uid);
            if (uid != NULL)
                PyErr_Format(PyExc_KeyError,
                             "failed to find Job %U for Task %U",
                             jobuid, uid);
            Py_XDECREF(uid);
        }
        Py_DECREF(jobuid);
        return NULL;
    }
    Py_DECREF(jobuid);
    PyObject *uid = PyObject_GetAttr(ti, s_uid);
    if (uid == NULL)
        return NULL;
    PyObject *jtasks = PyObject_GetAttr(job, s_tasks);
    if (jtasks == NULL) {
        Py_DECREF(uid);
        return NULL;
    }
    PyObject *task = PyDict_GetItemWithError(jtasks, uid); /* borrowed */
    Py_DECREF(jtasks);
    if (task == NULL) {
        if (!PyErr_Occurred()) {
            PyObject *st = PyObject_GetAttr(ti, s_status);
            PyObject *sts = st ? PyObject_Str(st) : NULL;
            if (sts != NULL)
                PyErr_Format(PyExc_KeyError,
                             "failed to find task in status %U by id %U",
                             sts, uid);
            Py_XDECREF(st);
            Py_XDECREF(sts);
        }
        Py_DECREF(uid);
        return NULL;
    }
    Py_DECREF(uid);
    Py_INCREF(task);
    PyObject *host = PyObject_GetAttr(task, s_node_name);
    if (host == NULL) {
        Py_DECREF(task);
        return NULL;
    }
    PyObject *node = PyDict_GetItemWithError(self->nodes, host);
    if (node == NULL) {
        if (!PyErr_Occurred()) {
            PyObject *tuid = PyObject_GetAttr(task, s_uid);
            if (tuid != NULL)
                PyErr_Format(PyExc_KeyError,
                             "failed to evict Task %U: host %U does not exist",
                             tuid, host);
            Py_XDECREF(tuid);
        }
        Py_DECREF(host);
        Py_DECREF(task);
        return NULL;
    }
    Py_DECREF(host);
    if (job_update_task_status(self, job, task, self->st_releasing) < 0) {
        Py_DECREF(task);
        return NULL;
    }
    if (node_update_task(self, node, task) < 0) {
        Py_DECREF(task);
        return NULL;
    }
    PyObject *pod = PyObject_GetAttr(task, s_pod);
    if (pod == NULL) {
        Py_DECREF(task);
        return NULL;
    }
    PyObject *out = PyTuple_Pack(2, task, pod);
    Py_DECREF(task);
    Py_DECREF(pod);
    return out;
}

/* mirror_bind(task_info, hostname) -> (cache_task, pod): cache-side
 * mutation of SchedulerCache.bind (cache.py:394-405) under the
 * caller-held lock. */
static PyObject *
TransCtx_mirror_bind(TransCtx *self, PyObject *args)
{
    PyObject *ti, *hostname;
    if (!PyArg_ParseTuple(args, "OO", &ti, &hostname))
        return NULL;
    PyObject *jobuid = PyObject_GetAttr(ti, s_job);
    if (jobuid == NULL)
        return NULL;
    PyObject *job = PyDict_GetItemWithError(self->jobs, jobuid);
    if (job == NULL) {
        if (!PyErr_Occurred()) {
            PyObject *uid = PyObject_GetAttr(ti, s_uid);
            if (uid != NULL)
                PyErr_Format(PyExc_KeyError,
                             "failed to find Job %U for Task %U",
                             jobuid, uid);
            Py_XDECREF(uid);
        }
        Py_DECREF(jobuid);
        return NULL;
    }
    Py_DECREF(jobuid);
    PyObject *uid = PyObject_GetAttr(ti, s_uid);
    if (uid == NULL)
        return NULL;
    PyObject *jtasks = PyObject_GetAttr(job, s_tasks);
    if (jtasks == NULL) {
        Py_DECREF(uid);
        return NULL;
    }
    PyObject *task = PyDict_GetItemWithError(jtasks, uid); /* borrowed */
    Py_DECREF(jtasks);
    if (task == NULL) {
        if (!PyErr_Occurred()) {
            PyObject *st = PyObject_GetAttr(ti, s_status);
            PyObject *sts = st ? PyObject_Str(st) : NULL;
            if (sts != NULL)
                PyErr_Format(PyExc_KeyError,
                             "failed to find task in status %U by id %U",
                             sts, uid);
            Py_XDECREF(st);
            Py_XDECREF(sts);
        }
        Py_DECREF(uid);
        return NULL;
    }
    Py_DECREF(uid);
    Py_INCREF(task);
    PyObject *node = PyDict_GetItemWithError(self->nodes, hostname);
    if (node == NULL) {
        if (!PyErr_Occurred()) {
            PyObject *tuid = PyObject_GetAttr(task, s_uid);
            if (tuid != NULL)
                PyErr_Format(
                    PyExc_KeyError,
                    "failed to bind Task %U to host %U: host does not exist",
                    tuid, hostname);
            Py_XDECREF(tuid);
        }
        Py_DECREF(task);
        return NULL;
    }
    if (job_update_task_status(self, job, task, self->st_binding) < 0) {
        Py_DECREF(task);
        return NULL;
    }
    if (PyObject_SetAttr(task, s_node_name, hostname) < 0) {
        Py_DECREF(task);
        return NULL;
    }
    if (node_add_task(self, node, task) < 0) {
        Py_DECREF(task);
        return NULL;
    }
    PyObject *pod = PyObject_GetAttr(task, s_pod);
    if (pod == NULL) {
        Py_DECREF(task);
        return NULL;
    }
    PyObject *out = PyTuple_Pack(2, task, pod);
    Py_DECREF(task);
    Py_DECREF(pod);
    return out;
}

/* ------------------------------------------------------------------ */
/* module-level: candidate-stream head pick                            */
/* ------------------------------------------------------------------ */

/* pick_first(idx_i64, row_f64, rr, num_to_find, n) -> (best_pos, processed)
 *
 * The head of DensePreemptView.candidates' stream (preempt/reclaim
 * consume exactly one element in practice): over the round-robin window
 * of the sorted eligible-node index array `idx` (same arithmetic as the
 * Python path — split at the cursor, take num_to_find circularly, else
 * the full circle), return the POSITION IN idx of the first maximum of
 * row[idx[...]] in window order (== head of the stable descending sort)
 * and the cursor advance. Pure C twin of candidates()'s selection math;
 * the Python generator remains the oracle and the continuation path. */
static PyObject *
fasttrans_pick_first(PyObject *self, PyObject *args)
{
    PyObject *idx_obj, *row_obj;
    long long rr, ntf, n;
    if (!PyArg_ParseTuple(args, "OOLLL", &idx_obj, &row_obj, &rr, &ntf, &n))
        return NULL;
    Py_buffer idx_buf, row_buf;
    if (PyObject_GetBuffer(idx_obj, &idx_buf, PyBUF_CONTIG_RO) < 0)
        return NULL;
    if (PyObject_GetBuffer(row_obj, &row_buf, PyBUF_CONTIG_RO) < 0) {
        PyBuffer_Release(&idx_buf);
        return NULL;
    }
    if (idx_buf.itemsize != 8 || row_buf.itemsize != 8) {
        PyBuffer_Release(&idx_buf);
        PyBuffer_Release(&row_buf);
        PyErr_SetString(PyExc_TypeError,
                        "pick_first: expected int64 idx and float64 row");
        return NULL;
    }
    const long long *idx = (const long long *)idx_buf.buf;
    const double *row = (const double *)row_buf.buf;
    Py_ssize_t ft = idx_buf.len / 8;
    long long processed;
    Py_ssize_t best_pos = -1;
    double best = 0.0;
    if (ft == 0) {
        processed = 0;
    } else {
        /* split = lower_bound(idx, rr) */
        Py_ssize_t lo = 0, hi = ft;
        while (lo < hi) {
            Py_ssize_t mid = (lo + hi) / 2;
            if (idx[mid] < rr)
                lo = mid + 1;
            else
                hi = mid;
        }
        Py_ssize_t split = lo;
        Py_ssize_t take_tail, wrap;
        if (ft >= ntf) {
            take_tail = ft - split < ntf ? ft - split : (Py_ssize_t)ntf;
            wrap = (Py_ssize_t)ntf - take_tail;
            long long last = wrap > 0 ? idx[wrap - 1]
                                      : idx[split + take_tail - 1];
            processed = ((last - rr) % n + n) % n + 1;
        } else {
            take_tail = ft - split;
            wrap = split;
            processed = n;
        }
        /* first max in WINDOW order (== stable descending-sort head);
         * best_pos < 0 seeds in BOTH loops — an all-wrap window (cursor
         * past every eligible index) with non-positive scores must still
         * yield its first element, exactly as np.argmax does */
        for (Py_ssize_t i = 0; i < take_tail; i++) {
            double s = row[idx[split + i]];
            if (best_pos < 0 || s > best) {
                best = s;
                best_pos = split + i;
            }
        }
        for (Py_ssize_t i = 0; i < wrap; i++) {
            double s = row[idx[i]];
            if (best_pos < 0 || s > best) {
                best = s;
                best_pos = i;
            }
        }
    }
    PyBuffer_Release(&idx_buf);
    PyBuffer_Release(&row_buf);
    return Py_BuildValue("nL", best_pos, processed);
}

static PyMethodDef fasttrans_functions[] = {
    {"pick_first", fasttrans_pick_first, METH_VARARGS, NULL},
    {NULL, NULL, 0, NULL},
};

static PyMethodDef TransCtx_methods[] = {
    {"evict", (PyCFunction)TransCtx_evict, METH_VARARGS, NULL},
    {"pipeline", (PyCFunction)TransCtx_pipeline, METH_VARARGS, NULL},
    {"unevict", (PyCFunction)TransCtx_unevict, METH_VARARGS, NULL},
    {"unpipeline", (PyCFunction)TransCtx_unpipeline, METH_VARARGS, NULL},
    {"allocate", (PyCFunction)TransCtx_allocate, METH_VARARGS, NULL},
    {"mirror_evict", (PyCFunction)TransCtx_mirror_evict, METH_VARARGS, NULL},
    {"mirror_bind", (PyCFunction)TransCtx_mirror_bind, METH_VARARGS, NULL},
    {NULL, NULL, 0, NULL},
};

static PyTypeObject TransCtxType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_fasttrans.TransCtx",
    .tp_basicsize = sizeof(TransCtx),
    .tp_dealloc = (destructor)TransCtx_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_methods = TransCtx_methods,
    .tp_init = (initproc)TransCtx_init,
    .tp_new = PyType_GenericNew,
};

static struct PyModuleDef fasttrans_module = {
    PyModuleDef_HEAD_INIT, "_fasttrans",
    "native per-operation transition engine", -1, fasttrans_functions,
};

PyMODINIT_FUNC
PyInit__fasttrans(void)
{
    if (intern_all() < 0)
        return NULL;
    if (PyType_Ready(&TransCtxType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&fasttrans_module);
    if (m == NULL)
        return NULL;
    Py_INCREF(&TransCtxType);
    if (PyModule_AddObject(m, "TransCtx", (PyObject *)&TransCtxType) < 0) {
        Py_DECREF(&TransCtxType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
