/* fastapply — native inner loop of the bulk placement writeback.
 *
 * The reference's scheduler is compiled Go; this framework's control plane
 * is Python with the placement solve on TPU, which leaves the per-task
 * writeback (status flips, node task-map inserts, cache mirror updates) as
 * interpreted overhead on the session's critical path — ~3 us/task at 50k
 * tasks/session. This module is the native equivalent of that loop:
 * identical semantics to the Python body in ops/solver.py::_apply_bulk
 * (which remains the fallback and the behavioral oracle), minus the
 * interpreter dispatch.
 *
 * Called per job segment with the job's pre-resolved dicts; the GIL is
 * held throughout (all operations are object mutations).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

static PyObject *s_node_name, *s_status, *s_uid, *s_namespace, *s_name,
    *s_tasks, *s_pod;

/* apply_job_tasks(tis, task_infos, assign, node_names, binding,
 *                 s_pending, s_binding, c_tasks, c_pending, c_binding,
 *                 ssn_nodes, cache_nodes, bind_tasks, bind_hosts)
 *
 * tis: list[int] task indices (one job's placements)
 * task_infos / node_names: session decode lists
 * assign: list[int] node index per task
 * binding: the TaskStatus.BINDING enum member
 * s_pending: dict | None  (session job PENDING bucket; None => moved)
 * s_binding: dict         (session job BINDING bucket)
 * c_tasks / c_pending / c_binding: cache-job analogs (or None)
 * ssn_nodes / cache_nodes: name -> NodeInfo dicts (cache_nodes may be None)
 * bind_tasks / bind_pods / bind_hosts: output lists, appended in task
 * order (pods pre-extracted here so the binder dispatch needs no 50k
 * Python-level `.pod` getattrs)
 */
static PyObject *
apply_job_tasks(PyObject *self, PyObject *args)
{
    PyObject *tis, *task_infos, *assign, *node_names, *binding;
    PyObject *s_pending, *s_binding_d, *c_tasks, *c_pending, *c_binding;
    PyObject *ssn_nodes, *cache_nodes, *bind_tasks, *bind_pods, *bind_hosts;

    if (!PyArg_ParseTuple(args, "OOOOOOOOOOOOOOO",
                          &tis, &task_infos, &assign, &node_names, &binding,
                          &s_pending, &s_binding_d, &c_tasks, &c_pending,
                          &c_binding, &ssn_nodes, &cache_nodes,
                          &bind_tasks, &bind_pods, &bind_hosts))
        return NULL;

    int have_s_pending = s_pending != Py_None;
    int have_c = c_tasks != Py_None;
    int have_c_pending = c_pending != Py_None;
    int have_cache_nodes = cache_nodes != Py_None;

    Py_ssize_t n = PyList_GET_SIZE(tis);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *ti_obj = PyList_GET_ITEM(tis, i);          /* borrowed */
        Py_ssize_t ti = PyLong_AsSsize_t(ti_obj);
        if (ti < 0 && PyErr_Occurred())
            return NULL;
        PyObject *task = PyList_GET_ITEM(task_infos, ti);    /* borrowed */
        PyObject *ni_obj = PyList_GET_ITEM(assign, ti);      /* borrowed */
        Py_ssize_t ni = PyLong_AsSsize_t(ni_obj);
        if (ni < 0 && PyErr_Occurred())
            return NULL;
        PyObject *host = PyList_GET_ITEM(node_names, ni);    /* borrowed */

        if (PyObject_SetAttr(task, s_node_name, host) < 0)
            return NULL;
        if (PyObject_SetAttr(task, s_status, binding) < 0)
            return NULL;

        PyObject *uid = PyObject_GetAttr(task, s_uid);       /* new */
        if (uid == NULL)
            return NULL;

        if (have_s_pending) {
            if (PyDict_DelItem(s_pending, uid) < 0) {
                /* pop(uid, None): only absence is swallowed — any other
                 * failure (unhashable uid, comparison error) propagates */
                if (!PyErr_ExceptionMatches(PyExc_KeyError)) {
                    Py_DECREF(uid);
                    return NULL;
                }
                PyErr_Clear();
            }
            if (PyDict_SetItem(s_binding_d, uid, task) < 0) {
                Py_DECREF(uid);
                return NULL;
            }
        }

        /* key = f"{namespace}/{name}" */
        PyObject *ns = PyObject_GetAttr(task, s_namespace);  /* new */
        PyObject *nm = ns ? PyObject_GetAttr(task, s_name) : NULL;
        PyObject *key = nm ? PyUnicode_FromFormat("%U/%U", ns, nm) : NULL;
        Py_XDECREF(ns);
        Py_XDECREF(nm);
        if (key == NULL) {
            Py_DECREF(uid);
            return NULL;
        }

        PyObject *node = PyDict_GetItemWithError(ssn_nodes, host); /* borrowed */
        if (node == NULL) {
            /* match the Python oracle exactly: ssn_nodes[host] raises on a
             * missing node — a broken invariant must fail loudly, not bind
             * a pod with silently-wrong session accounting */
            if (!PyErr_Occurred())
                PyErr_SetObject(PyExc_KeyError, host);
            goto fail;
        }
        {
            PyObject *tasks = PyObject_GetAttr(node, s_tasks);   /* new */
            if (tasks == NULL)
                goto fail;
            int rc = PyDict_SetItem(tasks, key, task);
            Py_DECREF(tasks);
            if (rc < 0)
                goto fail;
        }

        if (have_c) {
            PyObject *ctask = PyDict_GetItemWithError(c_tasks, uid); /* borrowed */
            if (ctask == NULL && PyErr_Occurred())
                goto fail;
            if (ctask != NULL) {
                if (PyObject_SetAttr(ctask, s_node_name, host) < 0)
                    goto fail;
                if (PyObject_SetAttr(ctask, s_status, binding) < 0)
                    goto fail;
                if (have_c_pending) {
                    if (PyDict_DelItem(c_pending, uid) < 0) {
                        if (!PyErr_ExceptionMatches(PyExc_KeyError))
                            goto fail;      /* see s_pending DelItem above */
                        PyErr_Clear();
                    }
                    if (PyDict_SetItem(c_binding, uid, ctask) < 0)
                        goto fail;
                }
                if (have_cache_nodes) {
                    PyObject *cnode =
                        PyDict_GetItemWithError(cache_nodes, host); /* borrowed */
                    if (cnode == NULL && PyErr_Occurred())
                        goto fail;
                    if (cnode != NULL) {
                        PyObject *ctasks = PyObject_GetAttr(cnode, s_tasks);
                        if (ctasks == NULL)
                            goto fail;
                        int rc = PyDict_SetItem(ctasks, key, task);
                        Py_DECREF(ctasks);
                        if (rc < 0)
                            goto fail;
                    }
                }
            }
        }

        if (PyList_Append(bind_tasks, task) < 0)
            goto fail;
        {
            PyObject *pod = PyObject_GetAttr(task, s_pod);    /* new */
            if (pod == NULL)
                goto fail;
            int rc = PyList_Append(bind_pods, pod);
            Py_DECREF(pod);
            if (rc < 0)
                goto fail;
        }
        if (PyList_Append(bind_hosts, host) < 0)
            goto fail;

        Py_DECREF(uid);
        Py_DECREF(key);
        continue;
    fail:
        Py_DECREF(uid);
        Py_DECREF(key);
        return NULL;
    }
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"apply_job_tasks", apply_job_tasks, METH_VARARGS,
     "Native per-task placement writeback for one job segment."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastapply",
    "Native bulk-apply inner loop (see ops/solver.py::_apply_bulk).",
    -1, methods,
};

PyMODINIT_FUNC
PyInit__fastapply(void)
{
    s_node_name = PyUnicode_InternFromString("node_name");
    s_status = PyUnicode_InternFromString("status");
    s_uid = PyUnicode_InternFromString("uid");
    s_namespace = PyUnicode_InternFromString("namespace");
    s_name = PyUnicode_InternFromString("name");
    s_tasks = PyUnicode_InternFromString("tasks");
    s_pod = PyUnicode_InternFromString("pod");
    if (!s_node_name || !s_status || !s_uid || !s_namespace || !s_name ||
        !s_tasks || !s_pod)
        return NULL;
    return PyModule_Create(&moduledef);
}
