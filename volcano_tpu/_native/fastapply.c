/* fastapply — native inner loop of the bulk placement writeback.
 *
 * The reference's scheduler is compiled Go; this framework's control plane
 * is Python with the placement solve on TPU, which leaves the per-task
 * writeback (status flips, node task-map inserts, cache mirror updates) as
 * interpreted overhead on the session's critical path — ~3 us/task at 50k
 * tasks/session. This module is the native equivalent of that loop:
 * identical semantics to the Python body in ops/solver.py::_apply_bulk
 * (which remains the fallback and the behavioral oracle), minus the
 * interpreter dispatch.
 *
 * Called per job segment with the job's pre-resolved dicts; the GIL is
 * held throughout (all operations are object mutations).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

static PyObject *s_node_name, *s_status, *s_uid, *s_namespace, *s_name,
    *s_tasks, *s_pod, *s_status_version, *s_task_status_index, *s_allocated,
    *s_key, *s_acct_gen, *s_pending_sum, *s_resreq, *s_milli_cpu_g,
    *s_memory_g, *s_scalar_res_g;

/* apply_job_tasks(tis, task_infos, assign, node_names, binding,
 *                 s_pending, s_binding, c_tasks, c_pending, c_binding,
 *                 ssn_nodes, cache_nodes, bind_tasks, bind_hosts)
 *
 * tis: list[int] task indices (one job's placements)
 * task_infos / node_names: session decode lists
 * assign: list[int] node index per task
 * binding: the TaskStatus.BINDING enum member
 * s_pending: dict | None  (session job PENDING bucket; None => moved)
 * s_binding: dict         (session job BINDING bucket)
 * c_tasks / c_pending / c_binding: cache-job analogs (or None)
 * ssn_nodes / cache_nodes: name -> NodeInfo dicts (cache_nodes may be None)
 * bind_tasks / bind_pods / bind_hosts: output lists, appended in task
 * order (pods pre-extracted here so the binder dispatch needs no 50k
 * Python-level `.pod` getattrs)
 */
static PyObject *
apply_job_tasks(PyObject *self, PyObject *args)
{
    PyObject *tis, *task_infos, *assign, *node_names, *binding;
    PyObject *s_pending, *s_binding_d, *c_tasks, *c_pending, *c_binding;
    PyObject *ssn_nodes, *cache_nodes, *bind_tasks, *bind_pods, *bind_hosts;

    if (!PyArg_ParseTuple(args, "OOOOOOOOOOOOOOO",
                          &tis, &task_infos, &assign, &node_names, &binding,
                          &s_pending, &s_binding_d, &c_tasks, &c_pending,
                          &c_binding, &ssn_nodes, &cache_nodes,
                          &bind_tasks, &bind_pods, &bind_hosts))
        return NULL;

    int have_s_pending = s_pending != Py_None;
    int have_c = c_tasks != Py_None;
    int have_c_pending = c_pending != Py_None;
    int have_cache_nodes = cache_nodes != Py_None;

    Py_ssize_t n = PyList_GET_SIZE(tis);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *ti_obj = PyList_GET_ITEM(tis, i);          /* borrowed */
        Py_ssize_t ti = PyLong_AsSsize_t(ti_obj);
        if (ti < 0 && PyErr_Occurred())
            return NULL;
        PyObject *task = PyList_GET_ITEM(task_infos, ti);    /* borrowed */
        PyObject *ni_obj = PyList_GET_ITEM(assign, ti);      /* borrowed */
        Py_ssize_t ni = PyLong_AsSsize_t(ni_obj);
        if (ni < 0 && PyErr_Occurred())
            return NULL;
        PyObject *host = PyList_GET_ITEM(node_names, ni);    /* borrowed */

        if (PyObject_SetAttr(task, s_node_name, host) < 0)
            return NULL;
        if (PyObject_SetAttr(task, s_status, binding) < 0)
            return NULL;

        PyObject *uid = PyObject_GetAttr(task, s_uid);       /* new */
        if (uid == NULL)
            return NULL;

        if (have_s_pending) {
            if (PyDict_DelItem(s_pending, uid) < 0) {
                /* pop(uid, None): only absence is swallowed — any other
                 * failure (unhashable uid, comparison error) propagates */
                if (!PyErr_ExceptionMatches(PyExc_KeyError)) {
                    Py_DECREF(uid);
                    return NULL;
                }
                PyErr_Clear();
            }
            if (PyDict_SetItem(s_binding_d, uid, task) < 0) {
                Py_DECREF(uid);
                return NULL;
            }
        }

        /* key = f"{namespace}/{name}" */
        PyObject *ns = PyObject_GetAttr(task, s_namespace);  /* new */
        PyObject *nm = ns ? PyObject_GetAttr(task, s_name) : NULL;
        PyObject *key = nm ? PyUnicode_FromFormat("%U/%U", ns, nm) : NULL;
        Py_XDECREF(ns);
        Py_XDECREF(nm);
        if (key == NULL) {
            Py_DECREF(uid);
            return NULL;
        }

        PyObject *node = PyDict_GetItemWithError(ssn_nodes, host); /* borrowed */
        if (node == NULL) {
            /* match the Python oracle exactly: ssn_nodes[host] raises on a
             * missing node — a broken invariant must fail loudly, not bind
             * a pod with silently-wrong session accounting */
            if (!PyErr_Occurred())
                PyErr_SetObject(PyExc_KeyError, host);
            goto fail;
        }
        {
            PyObject *tasks = PyObject_GetAttr(node, s_tasks);   /* new */
            if (tasks == NULL)
                goto fail;
            int rc = PyDict_SetItem(tasks, key, task);
            Py_DECREF(tasks);
            if (rc < 0)
                goto fail;
        }

        if (have_c) {
            PyObject *ctask = PyDict_GetItemWithError(c_tasks, uid); /* borrowed */
            if (ctask == NULL && PyErr_Occurred())
                goto fail;
            if (ctask != NULL) {
                if (PyObject_SetAttr(ctask, s_node_name, host) < 0)
                    goto fail;
                if (PyObject_SetAttr(ctask, s_status, binding) < 0)
                    goto fail;
                if (have_c_pending) {
                    if (PyDict_DelItem(c_pending, uid) < 0) {
                        if (!PyErr_ExceptionMatches(PyExc_KeyError))
                            goto fail;      /* see s_pending DelItem above */
                        PyErr_Clear();
                    }
                    if (PyDict_SetItem(c_binding, uid, ctask) < 0)
                        goto fail;
                }
                if (have_cache_nodes) {
                    PyObject *cnode =
                        PyDict_GetItemWithError(cache_nodes, host); /* borrowed */
                    if (cnode == NULL && PyErr_Occurred())
                        goto fail;
                    if (cnode != NULL) {
                        PyObject *ctasks = PyObject_GetAttr(cnode, s_tasks);
                        if (ctasks == NULL)
                            goto fail;
                        int rc = PyDict_SetItem(ctasks, key, task);
                        Py_DECREF(ctasks);
                        if (rc < 0)
                            goto fail;
                    }
                }
            }
        }

        if (PyList_Append(bind_tasks, task) < 0)
            goto fail;
        {
            PyObject *pod = PyObject_GetAttr(task, s_pod);    /* new */
            if (pod == NULL)
                goto fail;
            int rc = PyList_Append(bind_pods, pod);
            Py_DECREF(pod);
            if (rc < 0)
                goto fail;
        }
        if (PyList_Append(bind_hosts, host) < 0)
            goto fail;

        Py_DECREF(uid);
        Py_DECREF(key);
        continue;
    fail:
        Py_DECREF(uid);
        Py_DECREF(key);
        return NULL;
    }
    Py_RETURN_NONE;
}

/* ------------------------------------------------------------------ */
/* whole-session batched writeback                                     */
/* ------------------------------------------------------------------ */

/* res.milli_cpu += sign*vec[0]; res.memory += sign*vec[1];
 * res.add_scalar(name, sign*vec[2+si]) for nonzero scalar deltas.
 * Mirrors ops/solver.py::_apply_bulk.apply_delta exactly. */
static int
res_add_vec(PyObject *res, const double *vec, Py_ssize_t R,
            PyObject *scalar_names, double sign)
{
    static PyObject *s_milli_cpu, *s_memory, *s_add_scalar;
    if (s_milli_cpu == NULL) {
        s_milli_cpu = PyUnicode_InternFromString("milli_cpu");
        s_memory = PyUnicode_InternFromString("memory");
        s_add_scalar = PyUnicode_InternFromString("add_scalar");
        if (!s_milli_cpu || !s_memory || !s_add_scalar)
            return -1;
    }
    PyObject *names[2] = {s_milli_cpu, s_memory};
    for (int d = 0; d < 2; d++) {
        PyObject *v = PyObject_GetAttr(res, names[d]);
        if (v == NULL)
            return -1;
        double cur = PyFloat_AsDouble(v);
        Py_DECREF(v);
        if (cur == -1.0 && PyErr_Occurred())
            return -1;
        PyObject *nv = PyFloat_FromDouble(cur + sign * vec[d]);
        if (nv == NULL)
            return -1;
        int rc = PyObject_SetAttr(res, names[d], nv);
        Py_DECREF(nv);
        if (rc < 0)
            return -1;
    }
    for (Py_ssize_t si = 0; si + 2 < R; si++) {
        double q = vec[2 + si];
        if (q == 0.0)
            continue;
        PyObject *name = PyTuple_GET_ITEM(scalar_names, si); /* borrowed */
        PyObject *qv = PyFloat_FromDouble(sign * q);
        if (qv == NULL)
            return -1;
        PyObject *r = PyObject_CallMethodObjArgs(res, s_add_scalar,
                                                 name, qv, NULL);
        Py_DECREF(qv);
        if (r == NULL)
            return -1;
        Py_DECREF(r);
    }
    return 0;
}

/* obj.<name> += 1 for integer version/generation counters */
static int
bump_int_attr(PyObject *obj, PyObject *name)
{
    PyObject *v = PyObject_GetAttr(obj, name);
    if (v == NULL)
        return -1;
    long long x = PyLong_AsLongLong(v);
    Py_DECREF(v);
    if (x == -1 && PyErr_Occurred())
        return -1;
    PyObject *nv = PyLong_FromLongLong(x + 1);
    if (nv == NULL)
        return -1;
    int rc = PyObject_SetAttr(obj, name, nv);
    Py_DECREF(nv);
    return rc;
}

#define bump_version(job) bump_int_attr((job), s_status_version)

/* dict.pop(uid, None) where only absence is swallowed */
static int
dict_pop_ignore_missing(PyObject *d, PyObject *k)
{
    if (PyDict_DelItem(d, k) < 0) {
        if (!PyErr_ExceptionMatches(PyExc_KeyError))
            return -1;
        PyErr_Clear();
    }
    return 0;
}

/* contiguous int64 / float64 buffer views */
static int
get_i64(PyObject *obj, Py_buffer *buf, const char *what)
{
    if (PyObject_GetBuffer(obj, buf, PyBUF_CONTIG_RO) < 0)
        return -1;
    if (buf->itemsize != 8) {
        PyBuffer_Release(buf);
        PyErr_Format(PyExc_TypeError, "%s: expected int64 buffer", what);
        return -1;
    }
    return 0;
}

/* apply_all_jobs(job_nz, seg_ends, placed, assign, task_infos, node_names,
 *                ssn_nodes, cache_nodes, job_infos, cache_jobs,
 *                pending, binding, job_sums, scalar_names,
 *                bind_tasks, bind_pods, bind_hosts, bind_keys)
 *
 * Whole-session equivalent of the per-job Python wrapper around
 * apply_job_tasks in ops/solver.py::_apply_bulk: per-job status-index
 * surgery (wholesale PENDING->BINDING bucket move when the entire bucket
 * placed), cache-job mirror updates, per-task attribute/bucket/node-map
 * writes, allocated-resource deltas — one call for the whole assignment.
 *
 * job_nz/seg_ends: int64 buffers (jobs with placements / prefix ends into
 * placed). placed: int64 task indices, job-major contiguous. assign: int64
 * node id per task index. job_sums: float64 [J, R] per-job placed
 * resource sums. cache_jobs: uid -> cache JobInfo dict (or None).
 * bind_keys receives the "ns/name" key per placement (reused by the
 * binder/event batch paths so they need no 50k re-derivations). */
static PyObject *
apply_all_jobs(PyObject *self, PyObject *args)
{
    PyObject *job_nz_o, *seg_ends_o, *placed_o, *assign_o;
    PyObject *task_infos, *node_names, *ssn_nodes, *cache_nodes;
    PyObject *job_infos, *cache_jobs, *pending, *binding;
    PyObject *job_sums_o, *scalar_names;
    PyObject *bind_tasks, *bind_pods, *bind_hosts, *bind_keys;
    /* want_pods=0 skips the per-task .pod extraction into bind_pods — a
     * keyed binder that does not consume pod objects (the k8s Bind
     * subresource needs only name + target) saves one getattr + append
     * per placement */
    int want_pods = 1;

    if (!PyArg_ParseTuple(args, "OOOOOOOOOOOOOOOOOO|i",
                          &job_nz_o, &seg_ends_o, &placed_o, &assign_o,
                          &task_infos, &node_names, &ssn_nodes, &cache_nodes,
                          &job_infos, &cache_jobs, &pending, &binding,
                          &job_sums_o, &scalar_names,
                          &bind_tasks, &bind_pods, &bind_hosts, &bind_keys,
                          &want_pods))
        return NULL;

    int have_cache_nodes = cache_nodes != Py_None;
    int have_cache_jobs = cache_jobs != Py_None;

    Py_buffer job_nz_b = {0}, seg_ends_b = {0}, placed_b = {0},
              assign_b = {0}, sums_b = {0};
    PyObject **ntasks = NULL, **ctasks_n = NULL;
    char *cresolved = NULL;
    PyObject *ret = NULL;

    if (get_i64(job_nz_o, &job_nz_b, "job_nz") < 0)
        return NULL;
    if (get_i64(seg_ends_o, &seg_ends_b, "seg_ends") < 0)
        goto done;
    if (get_i64(placed_o, &placed_b, "placed") < 0)
        goto done;
    if (get_i64(assign_o, &assign_b, "assign") < 0)
        goto done;
    if (PyObject_GetBuffer(job_sums_o, &sums_b, PyBUF_CONTIG_RO) < 0)
        goto done;
    if (sums_b.itemsize != 8) {
        PyErr_SetString(PyExc_TypeError, "job_sums: expected float64 buffer");
        goto done;
    }

    const int64_t *job_nz = (const int64_t *)job_nz_b.buf;
    const int64_t *seg_ends = (const int64_t *)seg_ends_b.buf;
    const int64_t *placed = (const int64_t *)placed_b.buf;
    const int64_t *assign = (const int64_t *)assign_b.buf;
    const double *sums = (const double *)sums_b.buf;
    Py_ssize_t n_jobs_nz = job_nz_b.len / 8;
    Py_ssize_t R = sums_b.len ? (sums_b.ndim == 2 ? sums_b.shape[1]
                                                  : sums_b.len / 8) : 0;
    Py_ssize_t n_nodes = PyList_GET_SIZE(node_names);

    /* lazily-resolved per-node task dicts (strong refs) */
    ntasks = PyMem_Calloc(n_nodes ? n_nodes : 1, sizeof(PyObject *));
    ctasks_n = PyMem_Calloc(n_nodes ? n_nodes : 1, sizeof(PyObject *));
    cresolved = PyMem_Calloc(n_nodes ? n_nodes : 1, 1);
    if (!ntasks || !ctasks_n || !cresolved) {
        PyErr_NoMemory();
        goto done;
    }

    int64_t lo = 0;
    for (Py_ssize_t jj = 0; jj < n_jobs_nz; jj++) {
        int64_t ji = job_nz[jj];
        int64_t hi = seg_ends[jj];
        Py_ssize_t seg_len = (Py_ssize_t)(hi - lo);
        PyObject *job = PyList_GET_ITEM(job_infos, ji);      /* borrowed */

        if (bump_version(job) < 0)
            goto done;
        PyObject *idx = PyObject_GetAttr(job, s_task_status_index); /* new */
        if (idx == NULL)
            goto done;
        PyObject *s_pend = PyDict_GetItemWithError(idx, pending); /* borrowed */
        if (s_pend == NULL && PyErr_Occurred()) {
            Py_DECREF(idx);
            goto done;
        }
        PyObject *s_bind;                                    /* borrowed */
        int s_pend_active = 0;
        if (s_pend != NULL && PyDict_GET_SIZE(s_pend) == seg_len) {
            /* wholesale bucket move: every PENDING task placed */
            s_bind = PyDict_GetItemWithError(idx, binding);
            if (s_bind == NULL) {
                if (PyErr_Occurred()) {
                    Py_DECREF(idx);
                    goto done;
                }
                if (PyDict_SetItem(idx, binding, s_pend) < 0) {
                    Py_DECREF(idx);
                    goto done;
                }
                s_bind = s_pend;
            } else if (PyDict_Merge(s_bind, s_pend, 1) < 0) {
                Py_DECREF(idx);
                goto done;
            }
            if (PyDict_DelItem(idx, pending) < 0) {
                Py_DECREF(idx);
                goto done;
            }
        } else {
            s_pend_active = s_pend != NULL;
            s_bind = PyDict_GetItemWithError(idx, binding);
            if (s_bind == NULL) {
                if (PyErr_Occurred()) {
                    Py_DECREF(idx);
                    goto done;
                }
                PyObject *fresh = PyDict_New();
                if (fresh == NULL ||
                    PyDict_SetItem(idx, binding, fresh) < 0) {
                    Py_XDECREF(fresh);
                    Py_DECREF(idx);
                    goto done;
                }
                s_bind = fresh;
                Py_DECREF(fresh); /* idx holds it */
            }
        }
        Py_DECREF(idx);

        /* cache-job mirror */
        PyObject *cache_job = NULL;                          /* borrowed */
        PyObject *c_tasks = NULL;                            /* new */
        PyObject *c_pend = NULL, *c_bind = NULL;             /* borrowed */
        int c_pend_active = 0;
        if (have_cache_jobs) {
            PyObject *juid = PyObject_GetAttr(job, s_uid);   /* new */
            if (juid == NULL)
                goto done;
            cache_job = PyDict_GetItemWithError(cache_jobs, juid);
            Py_DECREF(juid);
            if (cache_job == NULL && PyErr_Occurred())
                goto done;
        }
        if (cache_job != NULL) {
            if (bump_version(cache_job) < 0)
                goto done;
            c_tasks = PyObject_GetAttr(cache_job, s_tasks);
            if (c_tasks == NULL)
                goto done;
            PyObject *cidx = PyObject_GetAttr(cache_job, s_task_status_index);
            if (cidx == NULL)
                goto job_fail;
            c_pend = PyDict_GetItemWithError(cidx, pending);
            if (c_pend == NULL && PyErr_Occurred()) {
                Py_DECREF(cidx);
                goto job_fail;
            }
            if (c_pend != NULL && PyDict_GET_SIZE(c_pend) == seg_len) {
                c_bind = PyDict_GetItemWithError(cidx, binding);
                if (c_bind == NULL) {
                    if (PyErr_Occurred()) {
                        Py_DECREF(cidx);
                        goto job_fail;
                    }
                    if (PyDict_SetItem(cidx, binding, c_pend) < 0) {
                        Py_DECREF(cidx);
                        goto job_fail;
                    }
                    c_bind = c_pend;
                } else if (PyDict_Merge(c_bind, c_pend, 1) < 0) {
                    Py_DECREF(cidx);
                    goto job_fail;
                }
                if (PyDict_DelItem(cidx, pending) < 0) {
                    Py_DECREF(cidx);
                    goto job_fail;
                }
            } else {
                c_pend_active = c_pend != NULL;
                c_bind = PyDict_GetItemWithError(cidx, binding);
                if (c_bind == NULL) {
                    if (PyErr_Occurred()) {
                        Py_DECREF(cidx);
                        goto job_fail;
                    }
                    PyObject *fresh = PyDict_New();
                    if (fresh == NULL ||
                        PyDict_SetItem(cidx, binding, fresh) < 0) {
                        Py_XDECREF(fresh);
                        Py_DECREF(cidx);
                        goto job_fail;
                    }
                    c_bind = fresh;
                    Py_DECREF(fresh);
                }
            }
            Py_DECREF(cidx);
        }

        /* per-task writeback */
        for (int64_t k = lo; k < hi; k++) {
            int64_t ti = placed[k];
            int64_t ni = assign[ti];
            PyObject *task = PyList_GET_ITEM(task_infos, ti); /* borrowed */
            PyObject *host = PyList_GET_ITEM(node_names, ni); /* borrowed */

            if (PyObject_SetAttr(task, s_node_name, host) < 0)
                goto job_fail;
            if (PyObject_SetAttr(task, s_status, binding) < 0)
                goto job_fail;

            PyObject *uid = PyObject_GetAttr(task, s_uid);   /* new */
            if (uid == NULL)
                goto job_fail;
            if (s_pend_active) {
                if (dict_pop_ignore_missing(s_pend, uid) < 0 ||
                    PyDict_SetItem(s_bind, uid, task) < 0) {
                    Py_DECREF(uid);
                    goto job_fail;
                }
            }

            PyObject *key = PyObject_GetAttr(task, s_key); /* precomputed */
            if (key == NULL) {
                Py_DECREF(uid);
                goto job_fail;
            }

            /* session node task-map (lazy dict resolve per node); the
             * resolve also bumps the node's accounting generation ONCE —
             * any touched node invalidates the snapshot node-axis capture */
            if (ntasks[ni] == NULL) {
                PyObject *node = PyDict_GetItemWithError(ssn_nodes, host);
                if (node == NULL) {
                    if (!PyErr_Occurred())
                        PyErr_SetObject(PyExc_KeyError, host);
                    goto task_fail;
                }
                if (bump_int_attr(node, s_acct_gen) < 0)
                    goto task_fail;
                ntasks[ni] = PyObject_GetAttr(node, s_tasks); /* strong */
                if (ntasks[ni] == NULL)
                    goto task_fail;
            }
            if (PyDict_SetItem(ntasks[ni], key, task) < 0)
                goto task_fail;

            if (c_tasks != NULL) {
                PyObject *ctask = PyDict_GetItemWithError(c_tasks, uid);
                if (ctask == NULL && PyErr_Occurred())
                    goto task_fail;
                if (ctask != NULL) {
                    if (PyObject_SetAttr(ctask, s_node_name, host) < 0)
                        goto task_fail;
                    if (PyObject_SetAttr(ctask, s_status, binding) < 0)
                        goto task_fail;
                    if (c_pend_active) {
                        if (dict_pop_ignore_missing(c_pend, uid) < 0 ||
                            PyDict_SetItem(c_bind, uid, ctask) < 0)
                            goto task_fail;
                    }
                    if (have_cache_nodes) {
                        if (!cresolved[ni]) {
                            cresolved[ni] = 1;
                            PyObject *cnode =
                                PyDict_GetItemWithError(cache_nodes, host);
                            if (cnode == NULL && PyErr_Occurred())
                                goto task_fail;
                            if (cnode != NULL) {
                                if (bump_int_attr(cnode, s_acct_gen) < 0)
                                    goto task_fail;
                                ctasks_n[ni] =
                                    PyObject_GetAttr(cnode, s_tasks);
                                if (ctasks_n[ni] == NULL)
                                    goto task_fail;
                            }
                        }
                        if (ctasks_n[ni] != NULL &&
                            PyDict_SetItem(ctasks_n[ni], key, task) < 0)
                            goto task_fail;
                    }
                }
            }

            if (PyList_Append(bind_tasks, task) < 0)
                goto task_fail;
            if (want_pods) {
                PyObject *pod = PyObject_GetAttr(task, s_pod);
                if (pod == NULL)
                    goto task_fail;
                int rc = PyList_Append(bind_pods, pod);
                Py_DECREF(pod);
                if (rc < 0)
                    goto task_fail;
            }
            if (PyList_Append(bind_hosts, host) < 0 ||
                PyList_Append(bind_keys, key) < 0)
                goto task_fail;

            Py_DECREF(uid);
            Py_DECREF(key);
            continue;
        task_fail:
            Py_DECREF(uid);
            Py_XDECREF(key);
            goto job_fail;
        }

        /* PENDING -> BINDING leaves total_request unchanged; allocated
         * grows by the job's placed sum (both trees) */
        {
            const double *vec = sums + ji * R;
            PyObject *alloc = PyObject_GetAttr(job, s_allocated);
            if (alloc == NULL)
                goto job_fail;
            int rc = res_add_vec(alloc, vec, R, scalar_names, 1.0);
            Py_DECREF(alloc);
            if (rc < 0)
                goto job_fail;
            /* every placed task left the PENDING bucket: the
             * incrementally-maintained pending request sum shrinks by
             * the same vector (job_info.py pending_sum) */
            alloc = PyObject_GetAttr(job, s_pending_sum);
            if (alloc == NULL)
                goto job_fail;
            rc = res_add_vec(alloc, vec, R, scalar_names, -1.0);
            Py_DECREF(alloc);
            if (rc < 0)
                goto job_fail;
            if (cache_job != NULL) {
                alloc = PyObject_GetAttr(cache_job, s_allocated);
                if (alloc == NULL)
                    goto job_fail;
                rc = res_add_vec(alloc, vec, R, scalar_names, 1.0);
                Py_DECREF(alloc);
                if (rc < 0)
                    goto job_fail;
                alloc = PyObject_GetAttr(cache_job, s_pending_sum);
                if (alloc == NULL)
                    goto job_fail;
                rc = res_add_vec(alloc, vec, R, scalar_names, -1.0);
                Py_DECREF(alloc);
                if (rc < 0)
                    goto job_fail;
            }
        }

        Py_XDECREF(c_tasks);
        lo = hi;
        continue;
    job_fail:
        Py_XDECREF(c_tasks);
        goto done;
    }

    ret = Py_None;
    Py_INCREF(ret);
done:
    if (ntasks) {
        for (Py_ssize_t i = 0; i < n_nodes; i++)
            Py_XDECREF(ntasks[i]);
        PyMem_Free(ntasks);
    }
    if (ctasks_n) {
        for (Py_ssize_t i = 0; i < n_nodes; i++)
            Py_XDECREF(ctasks_n[i]);
        PyMem_Free(ctasks_n);
    }
    PyMem_Free(cresolved);
    if (job_nz_b.obj)
        PyBuffer_Release(&job_nz_b);
    if (seg_ends_b.obj)
        PyBuffer_Release(&seg_ends_b);
    if (placed_b.obj)
        PyBuffer_Release(&placed_b);
    if (assign_b.obj)
        PyBuffer_Release(&assign_b);
    if (sums_b.obj)
        PyBuffer_Release(&sums_b);
    return ret;
}

/* apply_node_deltas(nz, sums, node_names, ssn_nodes, cache_nodes,
 *                   scalar_names)
 *
 * Bulk node accounting: for each touched node index in nz (int64 buffer),
 * idle -= vec and used += vec on the session NodeInfo and the cache
 * mirror (when present). sums: float64 [N, R]. Same semantics as the
 * Python loop in _apply_bulk's post section. */
static PyObject *
apply_node_deltas(PyObject *self, PyObject *args)
{
    PyObject *nz_o, *sums_o, *node_names, *ssn_nodes, *cache_nodes;
    PyObject *scalar_names;
    if (!PyArg_ParseTuple(args, "OOOOOO", &nz_o, &sums_o, &node_names,
                          &ssn_nodes, &cache_nodes, &scalar_names))
        return NULL;

    static PyObject *s_idle, *s_used;
    if (s_idle == NULL) {
        s_idle = PyUnicode_InternFromString("idle");
        s_used = PyUnicode_InternFromString("used");
        if (!s_idle || !s_used)
            return NULL;
    }

    Py_buffer nz_b = {0}, sums_b = {0};
    PyObject *ret = NULL;
    if (get_i64(nz_o, &nz_b, "nz") < 0)
        return NULL;
    if (PyObject_GetBuffer(sums_o, &sums_b, PyBUF_CONTIG_RO) < 0)
        goto done;
    if (sums_b.itemsize != 8) {
        PyErr_SetString(PyExc_TypeError, "sums: expected float64 buffer");
        goto done;
    }
    const int64_t *nz = (const int64_t *)nz_b.buf;
    const double *sums = (const double *)sums_b.buf;
    Py_ssize_t count = nz_b.len / 8;
    Py_ssize_t R = sums_b.ndim == 2 ? sums_b.shape[1] : 0;
    if (R == 0) {
        PyErr_SetString(PyExc_TypeError, "sums: expected [N, R] array");
        goto done;
    }
    int have_cache = cache_nodes != Py_None;

    for (Py_ssize_t i = 0; i < count; i++) {
        int64_t ni = nz[i];
        const double *vec = sums + ni * R;
        PyObject *name = PyList_GET_ITEM(node_names, ni);    /* borrowed */
        for (int tree = 0; tree < 2; tree++) {
            PyObject *src = tree == 0 ? ssn_nodes : cache_nodes;
            if (tree == 1 && !have_cache)
                break;
            PyObject *node = PyDict_GetItemWithError(src, name);
            if (node == NULL) {
                if (PyErr_Occurred())
                    goto done;
                continue;
            }
            if (bump_int_attr(node, s_acct_gen) < 0)
                goto done;
            PyObject *idle = PyObject_GetAttr(node, s_idle);
            if (idle == NULL)
                goto done;
            int rc = res_add_vec(idle, vec, R, scalar_names, -1.0);
            Py_DECREF(idle);
            if (rc < 0)
                goto done;
            PyObject *used = PyObject_GetAttr(node, s_used);
            if (used == NULL)
                goto done;
            rc = res_add_vec(used, vec, R, scalar_names, 1.0);
            Py_DECREF(used);
            if (rc < 0)
                goto done;
        }
    }
    ret = Py_None;
    Py_INCREF(ret);
done:
    if (nz_b.obj)
        PyBuffer_Release(&nz_b);
    if (sums_b.obj)
        PyBuffer_Release(&sums_b);
    return ret;
}

/* update_drf_shares(job_nz, sums, attrs, total_names, total_vals,
 *                   scalar_names)
 *
 * Per placed job: attr.allocated += sums[ji]; then recompute the DRF
 * dominant share exactly like drf._update_share / share_helpers.share
 * (r == 0 -> 0 if l == 0 else 1; strictly-greater keeps the FIRST
 * dominant dimension on ties). attrs is aligned with job_nz and may hold
 * None for jobs without a DRF attr. total_names[0:2] must be
 * ("cpu", "memory"); later entries are scalar resource names looked up in
 * allocated.scalar_resources. */
static PyObject *
update_drf_shares(PyObject *self, PyObject *args)
{
    PyObject *job_nz_o, *sums_o, *attrs, *total_names, *total_vals_o;
    PyObject *scalar_names;
    if (!PyArg_ParseTuple(args, "OOOOOO", &job_nz_o, &sums_o, &attrs,
                          &total_names, &total_vals_o, &scalar_names))
        return NULL;

    static PyObject *s_alloc_attr, *s_share, *s_dominant, *s_milli_cpu2,
        *s_memory2, *s_scalar_resources, *s_empty;
    if (s_alloc_attr == NULL) {
        s_alloc_attr = PyUnicode_InternFromString("allocated");
        s_share = PyUnicode_InternFromString("share");
        s_dominant = PyUnicode_InternFromString("dominant_resource");
        s_milli_cpu2 = PyUnicode_InternFromString("milli_cpu");
        s_memory2 = PyUnicode_InternFromString("memory");
        s_scalar_resources = PyUnicode_InternFromString("scalar_resources");
        s_empty = PyUnicode_InternFromString("");
        if (!s_alloc_attr || !s_share || !s_dominant || !s_milli_cpu2 ||
            !s_memory2 || !s_scalar_resources || !s_empty)
            return NULL;
    }

    Py_buffer nz_b = {0}, sums_b = {0}, tv_b = {0};
    PyObject *ret = NULL;
    if (get_i64(job_nz_o, &nz_b, "job_nz") < 0)
        return NULL;
    if (PyObject_GetBuffer(sums_o, &sums_b, PyBUF_CONTIG_RO) < 0)
        goto done;
    if (PyObject_GetBuffer(total_vals_o, &tv_b, PyBUF_CONTIG_RO) < 0)
        goto done;
    if (sums_b.itemsize != 8 || tv_b.itemsize != 8) {
        PyErr_SetString(PyExc_TypeError, "expected float64 buffers");
        goto done;
    }
    const int64_t *nz = (const int64_t *)nz_b.buf;
    const double *sums = (const double *)sums_b.buf;
    const double *tvals = (const double *)tv_b.buf;
    Py_ssize_t count = nz_b.len / 8;
    Py_ssize_t R = sums_b.ndim == 2 ? sums_b.shape[1] : 0;
    Py_ssize_t D = PyTuple_GET_SIZE(total_names);
    if (R == 0) {
        PyErr_SetString(PyExc_TypeError, "sums: expected [J, R] array");
        goto done;
    }

    for (Py_ssize_t i = 0; i < count; i++) {
        PyObject *attr = PyList_GET_ITEM(attrs, i);          /* borrowed */
        if (attr == Py_None)
            continue;
        int64_t ji = nz[i];
        const double *vec = sums + ji * R;
        PyObject *alloc = PyObject_GetAttr(attr, s_alloc_attr); /* new */
        if (alloc == NULL)
            goto done;
        if (res_add_vec(alloc, vec, R, scalar_names, 1.0) < 0) {
            Py_DECREF(alloc);
            goto done;
        }
        /* dominant share over the cluster total's dimensions */
        double best = 0.0;
        PyObject *dom = s_empty;                             /* borrowed */
        PyObject *scalars = NULL;                            /* new */
        int fail = 0;
        for (Py_ssize_t d = 0; d < D; d++) {
            double av;
            if (d < 2) {
                PyObject *v = PyObject_GetAttr(
                    alloc, d == 0 ? s_milli_cpu2 : s_memory2);
                if (v == NULL) { fail = 1; break; }
                av = PyFloat_AsDouble(v);
                Py_DECREF(v);
                if (av == -1.0 && PyErr_Occurred()) { fail = 1; break; }
            } else {
                if (scalars == NULL) {
                    scalars = PyObject_GetAttr(alloc, s_scalar_resources);
                    if (scalars == NULL) { fail = 1; break; }
                }
                av = 0.0;
                if (scalars != Py_None) {
                    PyObject *q = PyDict_GetItemWithError(
                        scalars, PyTuple_GET_ITEM(total_names, d));
                    if (q == NULL && PyErr_Occurred()) { fail = 1; break; }
                    if (q != NULL) {
                        av = PyFloat_AsDouble(q);
                        if (av == -1.0 && PyErr_Occurred()) {
                            fail = 1;
                            break;
                        }
                    }
                }
            }
            double tv = tvals[d];
            double s = tv == 0.0 ? (av == 0.0 ? 0.0 : 1.0) : av / tv;
            if (s > best) {
                best = s;
                dom = PyTuple_GET_ITEM(total_names, d);
            }
        }
        Py_XDECREF(scalars);
        Py_DECREF(alloc);
        if (fail)
            goto done;
        PyObject *bv = PyFloat_FromDouble(best);
        if (bv == NULL)
            goto done;
        int rc = PyObject_SetAttr(attr, s_share, bv);
        Py_DECREF(bv);
        if (rc < 0 || PyObject_SetAttr(attr, s_dominant, dom) < 0)
            goto done;
    }
    ret = Py_None;
    Py_INCREF(ret);
done:
    if (nz_b.obj)
        PyBuffer_Release(&nz_b);
    if (sums_b.obj)
        PyBuffer_Release(&sums_b);
    if (tv_b.obj)
        PyBuffer_Release(&tv_b);
    return ret;
}

/* mirror_all_jobs(job_nz, seg_ends, placed, assign, task_infos,
 *                 node_names, cache_nodes, job_infos, cache_jobs,
 *                 pending, binding, job_sums, scalar_names)
 *
 * The CACHE half of apply_all_jobs, for the deferred mirror flush
 * (scheduler/cache/cache.py flush_mirror): per cache-job status flips,
 * bucket moves, session-task inserts into cache node maps, and
 * allocated/pending_sum deltas. Unlike the session side, the cache may
 * have CHURNED in the defer window (watch events delete/re-status
 * tasks), so there is NO wholesale bucket-move fast path and every move
 * pops from the task's ACTUAL current bucket with update_task_status's
 * boundary rules (alloc_mask gates the allocated add; only tasks leaving
 * PENDING shrink pending_sum) — identical to the Python fallback loop,
 * which stays as the oracle. Caller holds the cache lock.
 *
 * Returns the list of SKIPPED placed-positions (indices into `placed`):
 * placements whose cache twin vanished in the defer window (task deleted,
 * or the whole job gone). The caller excludes exactly these from the node
 * idle/used deltas so cache accounting stays per-flipped-task. */
static int
append_idx(PyObject *list, int64_t k)
{
    PyObject *o = PyLong_FromLongLong((long long)k);
    if (o == NULL)
        return -1;
    int rc = PyList_Append(list, o);
    Py_DECREF(o);
    return rc;
}

static PyObject *
mirror_all_jobs(PyObject *self, PyObject *args)
{
    PyObject *job_nz_o, *seg_ends_o, *placed_o, *assign_o;
    PyObject *task_infos, *node_names, *cache_nodes;
    PyObject *job_infos, *cache_jobs, *pending, *binding;
    PyObject *job_sums_o, *scalar_names;
    long alloc_mask;

    if (!PyArg_ParseTuple(args, "OOOOOOOOOOOOOl",
                          &job_nz_o, &seg_ends_o, &placed_o, &assign_o,
                          &task_infos, &node_names, &cache_nodes,
                          &job_infos, &cache_jobs, &pending, &binding,
                          &job_sums_o, &scalar_names, &alloc_mask))
        return NULL;

    Py_buffer job_nz_b = {0}, seg_ends_b = {0}, placed_b = {0},
              assign_b = {0}, sums_b = {0};
    PyObject **ctasks_n = NULL;
    char *cresolved = NULL;
    PyObject *ret = NULL;
    PyObject *skipped = PyList_New(0);

    if (skipped == NULL)
        return NULL;
    if (get_i64(job_nz_o, &job_nz_b, "job_nz") < 0) {
        Py_DECREF(skipped);
        return NULL;
    }
    if (get_i64(seg_ends_o, &seg_ends_b, "seg_ends") < 0)
        goto done;
    if (get_i64(placed_o, &placed_b, "placed") < 0)
        goto done;
    if (get_i64(assign_o, &assign_b, "assign") < 0)
        goto done;
    if (PyObject_GetBuffer(job_sums_o, &sums_b, PyBUF_CONTIG_RO) < 0)
        goto done;
    if (sums_b.itemsize != 8) {
        PyErr_SetString(PyExc_TypeError, "job_sums: expected float64 buffer");
        goto done;
    }

    const int64_t *job_nz = (const int64_t *)job_nz_b.buf;
    const int64_t *seg_ends = (const int64_t *)seg_ends_b.buf;
    const int64_t *placed = (const int64_t *)placed_b.buf;
    const int64_t *assign = (const int64_t *)assign_b.buf;
    const double *sums = (const double *)sums_b.buf;
    Py_ssize_t n_jobs_nz = job_nz_b.len / 8;
    Py_ssize_t R = sums_b.len ? (sums_b.ndim == 2 ? sums_b.shape[1]
                                                  : sums_b.len / 8) : 0;
    Py_ssize_t n_nodes = PyList_GET_SIZE(node_names);

    ctasks_n = PyMem_Calloc(n_nodes ? n_nodes : 1, sizeof(PyObject *));
    cresolved = PyMem_Calloc(n_nodes ? n_nodes : 1, 1);
    if (!ctasks_n || !cresolved) {
        PyErr_NoMemory();
        goto done;
    }

    int64_t lo = 0;
    for (Py_ssize_t jj = 0; jj < n_jobs_nz; jj++) {
        int64_t ji = job_nz[jj];
        int64_t hi = seg_ends[jj];
        Py_ssize_t seg_len = (Py_ssize_t)(hi - lo);
        PyObject *job = PyList_GET_ITEM(job_infos, ji);      /* borrowed */

        PyObject *juid = PyObject_GetAttr(job, s_uid);       /* new */
        if (juid == NULL)
            goto done;
        PyObject *cache_job = PyDict_GetItemWithError(cache_jobs, juid);
        Py_DECREF(juid);
        if (cache_job == NULL) {
            if (PyErr_Occurred())
                goto done;
            for (int64_t k = lo; k < hi; k++)
                if (append_idx(skipped, k) < 0)
                    goto done;
            lo = hi;  /* job no longer in the cache: skip its segment */
            continue;
        }

        if (bump_version(cache_job) < 0)
            goto done;
        PyObject *c_tasks = PyObject_GetAttr(cache_job, s_tasks); /* new */
        if (c_tasks == NULL)
            goto done;
        PyObject *cidx = PyObject_GetAttr(cache_job, s_task_status_index);
        if (cidx == NULL)
            goto job_fail2;

        /* per-flipped-task accounting accumulators (R <= 64 scalars is
         * far beyond any real session; larger R falls back by erroring
         * out to the Python oracle) */
        double vec_alloc[64], vec_pend[64];
        if (R > 64) {
            PyErr_SetString(PyExc_ValueError, "mirror_all_jobs: R > 64");
            goto job_fail;
        }
        for (Py_ssize_t r = 0; r < R; r++)
            vec_alloc[r] = vec_pend[r] = 0.0;

        for (int64_t k = lo; k < hi; k++) {
            int64_t ti = placed[k];
            int64_t ni = assign[ti];
            PyObject *task = PyList_GET_ITEM(task_infos, ti); /* borrowed */
            PyObject *host = PyList_GET_ITEM(node_names, ni); /* borrowed */

            PyObject *uid = PyObject_GetAttr(task, s_uid);   /* new */
            if (uid == NULL)
                goto job_fail;
            PyObject *ctask = PyDict_GetItemWithError(c_tasks, uid);
            if (ctask == NULL) {
                Py_DECREF(uid);
                if (PyErr_Occurred())
                    goto job_fail;
                if (append_idx(skipped, k) < 0)
                    goto job_fail;
                continue;  /* deleted in the defer window: its sums were
                            * settled by delete_task_info already */
            }

            /* pop from the task's ACTUAL current bucket (it may have
             * been re-statused by a watch event since the session ran),
             * deleting the bucket when it empties — the Python oracle's
             * exact moves */
            PyObject *old_status = PyObject_GetAttr(ctask, s_status);
            if (old_status == NULL) {
                Py_DECREF(uid);
                goto job_fail;
            }
            long old_l = PyLong_AsLong(old_status);
            if (old_l == -1 && PyErr_Occurred()) {
                Py_DECREF(old_status);
                Py_DECREF(uid);
                goto job_fail;
            }
            PyObject *old_bucket = PyDict_GetItemWithError(cidx, old_status);
            if (old_bucket == NULL && PyErr_Occurred()) {
                Py_DECREF(old_status);
                Py_DECREF(uid);
                goto job_fail;
            }
            if (old_bucket != NULL) {
                if (dict_pop_ignore_missing(old_bucket, uid) < 0) {
                    Py_DECREF(old_status);
                    Py_DECREF(uid);
                    goto job_fail;
                }
                if (PyDict_GET_SIZE(old_bucket) == 0 &&
                    PyDict_DelItem(cidx, old_status) < 0) {
                    Py_DECREF(old_status);
                    Py_DECREF(uid);
                    goto job_fail;
                }
            }

            if (PyObject_SetAttr(ctask, s_node_name, host) < 0 ||
                PyObject_SetAttr(ctask, s_status, binding) < 0) {
                Py_DECREF(old_status);
                Py_DECREF(uid);
                goto job_fail;
            }

            /* insert into the BINDING bucket, created lazily (looked up
             * per task: the pop above may have deleted-and-recreated it) */
            {
                PyObject *nb = PyDict_GetItemWithError(cidx, binding);
                if (nb == NULL) {
                    if (PyErr_Occurred()) {
                        Py_DECREF(old_status);
                        Py_DECREF(uid);
                        goto job_fail;
                    }
                    nb = PyDict_New();
                    if (nb == NULL ||
                        PyDict_SetItem(cidx, binding, nb) < 0) {
                        Py_XDECREF(nb);
                        Py_DECREF(old_status);
                        Py_DECREF(uid);
                        goto job_fail;
                    }
                    Py_DECREF(nb);
                    nb = PyDict_GetItemWithError(cidx, binding);
                    if (nb == NULL) {
                        Py_DECREF(old_status);
                        Py_DECREF(uid);
                        goto job_fail;
                    }
                }
                if (PyDict_SetItem(nb, uid, ctask) < 0) {
                    Py_DECREF(old_status);
                    Py_DECREF(uid);
                    goto job_fail;
                }
            }
            Py_DECREF(uid);

            /* boundary-ruled accounting accumulation: BINDING is in the
             * allocated class, so allocated grows only for tasks NOT
             * already allocated-class, and pending_sum shrinks only for
             * tasks leaving PENDING (job_info.update_task_status rules) */
            int was_alloc = (old_l & alloc_mask) != 0;
            int was_pend = old_status == pending;
            if (!was_pend) {
                int eq = PyObject_RichCompareBool(old_status, pending, Py_EQ);
                if (eq < 0) {
                    Py_DECREF(old_status);
                    goto job_fail;
                }
                was_pend = eq;
            }
            Py_DECREF(old_status);
            if (!was_alloc || was_pend) {
                PyObject *req = PyObject_GetAttr(ctask, s_resreq);
                if (req == NULL)
                    goto job_fail;
                PyObject *mc = PyObject_GetAttr(req, s_milli_cpu_g);
                PyObject *mem = mc ? PyObject_GetAttr(req, s_memory_g) : NULL;
                if (mem == NULL) {
                    Py_XDECREF(mc);
                    Py_DECREF(req);
                    goto job_fail;
                }
                double mcv = PyFloat_AsDouble(mc);
                double memv = PyFloat_AsDouble(mem);
                Py_DECREF(mc);
                Py_DECREF(mem);
                if (PyErr_Occurred()) {
                    Py_DECREF(req);
                    goto job_fail;
                }
                if (!was_alloc) { vec_alloc[0] += mcv; vec_alloc[1] += memv; }
                if (was_pend)   { vec_pend[0] += mcv;  vec_pend[1] += memv; }
                PyObject *scal = PyObject_GetAttr(req, s_scalar_res_g);
                Py_DECREF(req);
                if (scal == NULL)
                    goto job_fail;
                if (scal != Py_None && PyDict_GET_SIZE(scal) > 0) {
                    PyObject *sk, *sv;
                    Py_ssize_t pos = 0;
                    while (PyDict_Next(scal, &pos, &sk, &sv)) {
                        double q = PyFloat_AsDouble(sv);
                        if (q == -1.0 && PyErr_Occurred()) {
                            Py_DECREF(scal);
                            goto job_fail;
                        }
                        for (Py_ssize_t r = 2; r < R; r++) {
                            PyObject *rn = PyTuple_GET_ITEM(scalar_names,
                                                            r - 2);
                            int same = PyObject_RichCompareBool(sk, rn,
                                                                Py_EQ);
                            if (same < 0) {
                                Py_DECREF(scal);
                                goto job_fail;
                            }
                            if (same) {
                                if (!was_alloc) vec_alloc[r] += q;
                                if (was_pend)   vec_pend[r] += q;
                                break;
                            }
                        }
                    }
                }
                Py_DECREF(scal);
            }

            /* cache node task-map: the SESSION task object is shared in,
             * exactly as the inline writeback and the Python flush do */
            if (!cresolved[ni]) {
                cresolved[ni] = 1;
                PyObject *cnode = PyDict_GetItemWithError(cache_nodes, host);
                if (cnode == NULL && PyErr_Occurred())
                    goto job_fail;
                if (cnode != NULL) {
                    if (bump_int_attr(cnode, s_acct_gen) < 0)
                        goto job_fail;
                    ctasks_n[ni] = PyObject_GetAttr(cnode, s_tasks);
                    if (ctasks_n[ni] == NULL)
                        goto job_fail;
                }
            }
            if (ctasks_n[ni] != NULL) {
                PyObject *key = PyObject_GetAttr(task, s_key);
                if (key == NULL)
                    goto job_fail;
                int rc = PyDict_SetItem(ctasks_n[ni], key, task);
                Py_DECREF(key);
                if (rc < 0)
                    goto job_fail;
            }
        }

        {
            PyObject *res = PyObject_GetAttr(cache_job, s_allocated);
            if (res == NULL)
                goto job_fail;
            int rc = res_add_vec(res, vec_alloc, R, scalar_names, 1.0);
            Py_DECREF(res);
            if (rc < 0)
                goto job_fail;
            res = PyObject_GetAttr(cache_job, s_pending_sum);
            if (res == NULL)
                goto job_fail;
            rc = res_add_vec(res, vec_pend, R, scalar_names, -1.0);
            Py_DECREF(res);
            if (rc < 0)
                goto job_fail;
        }

        Py_DECREF(cidx);
        Py_DECREF(c_tasks);
        lo = hi;
        continue;
    job_fail:
        Py_DECREF(cidx);
    job_fail2:
        Py_DECREF(c_tasks);
        goto done;
    }

    ret = skipped;
    skipped = NULL;
done:
    Py_XDECREF(skipped);
    if (ctasks_n) {
        for (Py_ssize_t i = 0; i < n_nodes; i++)
            Py_XDECREF(ctasks_n[i]);
        PyMem_Free(ctasks_n);
    }
    PyMem_Free(cresolved);
    if (job_nz_b.obj)
        PyBuffer_Release(&job_nz_b);
    if (seg_ends_b.obj)
        PyBuffer_Release(&seg_ends_b);
    if (placed_b.obj)
        PyBuffer_Release(&placed_b);
    if (assign_b.obj)
        PyBuffer_Release(&assign_b);
    if (sums_b.obj)
        PyBuffer_Release(&sums_b);
    return ret;
}

static PyMethodDef methods[] = {
    {"apply_job_tasks", apply_job_tasks, METH_VARARGS,
     "Native per-task placement writeback for one job segment."},
    {"mirror_all_jobs", mirror_all_jobs, METH_VARARGS,
     "Cache-half of apply_all_jobs for the deferred mirror flush."},
    {"apply_all_jobs", apply_all_jobs, METH_VARARGS,
     "Whole-session batched placement writeback (all jobs, one call)."},
    {"apply_node_deltas", apply_node_deltas, METH_VARARGS,
     "Bulk idle/used node accounting for touched nodes."},
    {"update_drf_shares", update_drf_shares, METH_VARARGS,
     "Batched DRF allocated-delta + dominant-share recompute."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastapply",
    "Native bulk-apply inner loop (see ops/solver.py::_apply_bulk).",
    -1, methods,
};

PyMODINIT_FUNC
PyInit__fastapply(void)
{
    s_node_name = PyUnicode_InternFromString("node_name");
    s_status = PyUnicode_InternFromString("status");
    s_uid = PyUnicode_InternFromString("uid");
    s_namespace = PyUnicode_InternFromString("namespace");
    s_name = PyUnicode_InternFromString("name");
    s_tasks = PyUnicode_InternFromString("tasks");
    s_pod = PyUnicode_InternFromString("pod");
    s_status_version = PyUnicode_InternFromString("_status_version");
    s_task_status_index = PyUnicode_InternFromString("task_status_index");
    s_allocated = PyUnicode_InternFromString("allocated");
    s_key = PyUnicode_InternFromString("key");
    s_acct_gen = PyUnicode_InternFromString("_acct_gen");
    s_pending_sum = PyUnicode_InternFromString("pending_sum");
    s_resreq = PyUnicode_InternFromString("resreq");
    s_milli_cpu_g = PyUnicode_InternFromString("milli_cpu");
    s_memory_g = PyUnicode_InternFromString("memory");
    s_scalar_res_g = PyUnicode_InternFromString("scalar_resources");
    if (!s_resreq || !s_milli_cpu_g || !s_memory_g || !s_scalar_res_g)
        return NULL;
    if (!s_node_name || !s_status || !s_uid || !s_namespace || !s_name ||
        !s_tasks || !s_pod || !s_status_version || !s_task_status_index ||
        !s_allocated || !s_key || !s_acct_gen || !s_pending_sum)
        return NULL;
    return PyModule_Create(&moduledef);
}
