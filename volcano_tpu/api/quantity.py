"""Kubernetes-style resource quantity parsing.

Accepts ints/floats directly, or strings in the k8s quantity grammar:
plain numbers ("2", "1.5", "1e3"), milli-suffixed ("500m"), binary
suffixes ("8Gi"), and decimal suffixes ("2k", "1G").
"""

from __future__ import annotations

_BINARY = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}
_DECIMAL = {
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}


def parse_quantity(q) -> float:
    """Parse a quantity into its base-unit value (cores, bytes, counts)."""
    if isinstance(q, (int, float)):
        return float(q)
    if not isinstance(q, str):
        raise TypeError(f"cannot parse quantity from {type(q)!r}")
    s = q.strip()
    if not s:
        raise ValueError("empty quantity")

    for suffix, mult in _BINARY.items():
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * mult
    if s.endswith("m"):
        return float(s[:-1]) / 1000.0
    # Decimal suffixes are single characters; check after "m" (milli) and
    # binary ("Mi" etc., already handled above).
    if s[-1] in _DECIMAL and not s[-1].isdigit():
        return float(s[:-1]) * _DECIMAL[s[-1]]
    return float(s)


def milli_value(q) -> float:
    """Quantity scaled to milli-units (the scheduler's working unit for CPU
    and scalar resources, matching k8s Quantity.MilliValue)."""
    return parse_quantity(q) * 1000.0
