"""Pod → task helpers (volcano pkg/scheduler/api/{helpers.go,pod_info.go})."""

from __future__ import annotations

from volcano_tpu.api import objects
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import TaskStatus


def pod_key(pod: objects.Pod) -> str:
    """"namespace/name" key (helpers.go PodKey)."""
    return f"{pod.metadata.namespace}/{pod.metadata.name}"


def get_task_status(pod: objects.Pod) -> TaskStatus:
    """Pod phase + deletion/node state → TaskStatus (helpers.go getTaskStatus)."""
    phase = pod.status.phase
    if phase == objects.POD_PHASE_RUNNING:
        if pod.metadata.deletion_timestamp is not None:
            return TaskStatus.RELEASING
        return TaskStatus.RUNNING
    if phase == objects.POD_PHASE_PENDING:
        if pod.metadata.deletion_timestamp is not None:
            return TaskStatus.RELEASING
        if not pod.spec.node_name:
            return TaskStatus.PENDING
        return TaskStatus.BOUND
    if phase == objects.POD_PHASE_SUCCEEDED:
        return TaskStatus.SUCCEEDED
    if phase == objects.POD_PHASE_FAILED:
        return TaskStatus.FAILED
    return TaskStatus.UNKNOWN


def get_pod_resource_without_init_containers(pod: objects.Pod) -> Resource:
    """Sum of main-container requests (pod_info.go:66-74)."""
    result = Resource.empty()
    for container in pod.spec.containers:
        result.add(Resource.from_resource_list(container.requests))
    return result


def get_pod_resource_request(pod: objects.Pod) -> Resource:
    """max(sum of main containers, each init container) per dimension —
    init containers run sequentially (pod_info.go:53-62)."""
    result = get_pod_resource_without_init_containers(pod)
    for container in pod.spec.init_containers:
        result.set_max_resource(Resource.from_resource_list(container.requests))
    return result
