"""Task/node status enums and plugin function conventions.

Mirrors volcano pkg/scheduler/api/types.go. Plugin extension-point callables
are plain Python callables; their signatures are documented on the Session
registration methods (see volcano_tpu.scheduler.framework.session).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class TaskStatus(enum.IntFlag):
    """Status of a task/pod in the scheduler (types.go:24-58)."""

    PENDING = 1 << 0      # pending in the store
    ALLOCATED = 1 << 1    # scheduler assigned a host (session-local)
    PIPELINED = 1 << 2    # assigned a host, waiting on releasing resources
    BINDING = 1 << 3      # bind request sent
    BOUND = 1 << 4        # bound to a host
    RUNNING = 1 << 5      # running on the host
    RELEASING = 1 << 6    # being deleted
    SUCCEEDED = 1 << 7
    FAILED = 1 << 8
    UNKNOWN = 1 << 9

    def __str__(self) -> str:  # "Pending", "Allocated", ...
        return self.name.capitalize() if self.name else "Unknown"


def allocated_status(status: TaskStatus) -> bool:
    """Whether the status counts as occupying resources
    (pkg/scheduler/api/helpers.go AllocatedStatus)."""
    return status in (
        TaskStatus.BOUND,
        TaskStatus.BINDING,
        TaskStatus.RUNNING,
        TaskStatus.ALLOCATED,
    )


class NodePhase(enum.IntEnum):
    READY = 1
    NOT_READY = 2

    def __str__(self) -> str:
        return "Ready" if self is NodePhase.READY else "NotReady"


@dataclass
class ValidateResult:
    """Result of a JobValid extension point (types.go:121-125)."""

    pass_: bool
    reason: str = ""
    message: str = ""
