"""TaskInfo and JobInfo — the session's working view of pods and pod groups
(volcano pkg/scheduler/api/job_info.go)."""

from __future__ import annotations

from typing import Dict, Optional

from volcano_tpu.api import objects
from volcano_tpu.api.objects import GROUP_NAME_ANNOTATION_KEY
from volcano_tpu.api.pod_helpers import (
    get_pod_resource_request,
    get_pod_resource_without_init_containers,
    get_task_status,
)
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import TaskStatus, allocated_status
from volcano_tpu.api.unschedule_info import FitErrors


def get_job_id(pod: objects.Pod) -> str:
    """Job key of a pod via its group-name annotation (job_info.go:57-65)."""
    gn = pod.metadata.annotations.get(GROUP_NAME_ANNOTATION_KEY, "")
    if gn:
        return f"{pod.metadata.namespace}/{gn}"
    return ""


class TaskInfo:
    """All scheduler-relevant info about one task/pod (job_info.go:37-55)."""

    __slots__ = (
        "uid",
        "job",
        "name",
        "namespace",
        "resreq",
        "init_resreq",
        "node_name",
        "status",
        "priority",
        "volume_ready",
        "pod",
        # columnar-mirror coordinates (scheduler/cache/podtable.py): the
        # cache assigns them; clones inherit; (row, row_gen) validate reads
        "row",
        "row_gen",
        # "namespace/name", precomputed once — the node task-map / binder /
        # event key that hot paths would otherwise re-format per use
        "key",
    )

    def __init__(
        self,
        uid: str,
        job: str,
        name: str,
        namespace: str,
        resreq: Resource,
        init_resreq: Resource,
        node_name: str = "",
        status: TaskStatus = TaskStatus.PENDING,
        priority: int = 1,
        volume_ready: bool = False,
        pod: Optional[objects.Pod] = None,
    ):
        self.uid = uid
        self.job = job
        self.name = name
        self.namespace = namespace
        self.resreq = resreq
        self.init_resreq = init_resreq
        self.node_name = node_name
        self.status = status
        self.priority = priority
        self.volume_ready = volume_ready
        self.pod = pod
        self.row = -1
        self.row_gen = -1
        self.key = namespace + "/" + name

    def clone(self) -> "TaskInfo":
        t = TaskInfo(
            uid=self.uid,
            job=self.job,
            name=self.name,
            namespace=self.namespace,
            resreq=self.resreq.clone(),
            init_resreq=self.init_resreq.clone(),
            node_name=self.node_name,
            status=self.status,
            priority=self.priority,
            volume_ready=self.volume_ready,
            pod=self.pod,
        )
        t.row = self.row
        t.row_gen = self.row_gen
        return t

    def shared_clone(self) -> "TaskInfo":
        """Status-frozen copy for node task-maps that SHARES the resreq /
        init_resreq Resource objects. Node maps clone tasks only so later
        status flips don't corrupt node accounting (node_info.go:196-197);
        the request Resources are never mutated through a node map, so the
        bulk-apply path avoids 2 Resource deep-copies per placement."""
        t = TaskInfo.__new__(TaskInfo)
        t.uid = self.uid
        t.job = self.job
        t.name = self.name
        t.namespace = self.namespace
        t.resreq = self.resreq
        t.init_resreq = self.init_resreq
        t.node_name = self.node_name
        t.status = self.status
        t.priority = self.priority
        t.volume_ready = self.volume_ready
        t.pod = self.pod
        t.row = self.row
        t.row_gen = self.row_gen
        t.key = self.key
        return t

    def __repr__(self) -> str:
        return (
            f"Task ({self.uid}:{self.namespace}/{self.name}): "
            f"job {self.job}, status {self.status}, pri {self.priority}, "
            f"resreq {self.resreq}"
        )


def new_task_info(pod: objects.Pod) -> TaskInfo:
    """Build a TaskInfo from a Pod (job_info.go:68-92)."""
    ti = TaskInfo(
        uid=pod.metadata.uid,
        job=get_job_id(pod),
        name=pod.metadata.name,
        namespace=pod.metadata.namespace,
        resreq=get_pod_resource_without_init_containers(pod),
        init_resreq=get_pod_resource_request(pod),
        node_name=pod.spec.node_name,
        status=get_task_status(pod),
        priority=pod.spec.priority if pod.spec.priority is not None else 1,
        pod=pod,
    )
    return ti


class JobInfo:
    """All info about one job (= PodGroup + its tasks), with resource
    accounting kept incrementally (job_info.go:126-178)."""

    def __init__(self, uid: str, *tasks: TaskInfo):
        self.uid = uid
        self.name = ""
        self.namespace = ""
        self.queue = ""
        self.priority = 0
        self.min_available = 0

        self.nodes_fit_delta: Dict[str, Resource] = {}
        self.job_fit_errors = ""
        self.nodes_fit_errors: Dict[str, FitErrors] = {}

        self.task_status_index: Dict[TaskStatus, Dict[str, TaskInfo]] = {}
        self.tasks: Dict[str, TaskInfo] = {}
        # status-index mutation counter + ready_task_num memo; code that
        # mutates task_status_index directly (the bulk apply path) must
        # bump _status_version
        self._status_version = 0
        self._ready_cache = None
        self._valid_cache = None
        # columnar view of the PENDING bucket captured by clone() while it
        # is already touching every task: (tasks, rows, row_gens, version).
        # Valid only while _status_version still matches — any index
        # mutation invalidates it (see pending_axis)
        self._pending_axis = None

        self.allocated = Resource.empty()
        self.total_request = Resource.empty()
        # sum of PENDING tasks' requests, kept incrementally like
        # `allocated`: proportion's queue `request` (allocated + pending)
        # becomes two O(1) adds per job at session open instead of a
        # per-task walk (proportion.go:72-102 recomputes per task; with
        # 50k pending tasks that walk alone costs ~100 ms per session)
        self.pending_sum = Resource.empty()

        self.creation_timestamp = 0.0
        self.pod_group: Optional[objects.PodGroup] = None
        self.pdb: Optional[objects.PodDisruptionBudget] = None

        for task in tasks:
            self.add_task_info(task)

    # -- pod group / pdb binding ------------------------------------------

    def set_pod_group(self, pg: objects.PodGroup) -> None:
        self.name = pg.metadata.name
        self.namespace = pg.metadata.namespace
        self.min_available = pg.spec.min_member
        self.queue = pg.spec.queue
        self.creation_timestamp = pg.metadata.creation_timestamp
        self.pod_group = pg

    def unset_pod_group(self) -> None:
        self.pod_group = None

    def set_pdb(self, pdb: objects.PodDisruptionBudget) -> None:
        self.name = pdb.metadata.name
        self.namespace = pdb.metadata.namespace
        self.min_available = pdb.min_available
        self.creation_timestamp = pdb.metadata.creation_timestamp
        self.pdb = pdb

    def unset_pdb(self) -> None:
        self.pdb = None

    # -- task bookkeeping --------------------------------------------------

    def _add_task_index(self, ti: TaskInfo) -> None:
        self.task_status_index.setdefault(ti.status, {})[ti.uid] = ti
        self._status_version += 1

    def _delete_task_index(self, ti: TaskInfo) -> None:
        tasks = self.task_status_index.get(ti.status)
        if tasks is not None:
            tasks.pop(ti.uid, None)
            if not tasks:
                del self.task_status_index[ti.status]
        self._status_version += 1

    def add_task_info(self, ti: TaskInfo) -> None:
        self.tasks[ti.uid] = ti
        self._add_task_index(ti)
        self.total_request.add(ti.resreq)
        if allocated_status(ti.status):
            self.allocated.add(ti.resreq)
        elif ti.status == TaskStatus.PENDING:
            self.pending_sum.add(ti.resreq)

    def delete_task_info(self, ti: TaskInfo) -> None:
        task = self.tasks.get(ti.uid)
        if task is None:
            raise KeyError(
                f"failed to find task <{ti.namespace}/{ti.name}> "
                f"in job <{self.namespace}/{self.name}>"
            )
        self.total_request.sub(task.resreq)
        if allocated_status(task.status):
            self.allocated.sub(task.resreq)
        elif task.status == TaskStatus.PENDING:
            self.pending_sum.sub(task.resreq)
        del self.tasks[task.uid]
        self._delete_task_index(task)

    def update_task_status(self, task: TaskInfo, status: TaskStatus) -> None:
        """Move a task to a new status bucket, keeping the resource
        accounting consistent. A task not currently in the job is simply
        (re-)added under the new status — the reference discards the delete
        error (job_info.go:232-245) and session code relies on that.

        The present-task case fuses delete_task_info + add_task_info: a
        status flip with a value-equal request leaves total_request
        unchanged and moves `allocated` only across the allocated-status
        boundary, so the fused path performs exactly the net Resource ops
        (and the index bucket move) — identical end state, minus the
        sub-then-add round trips and their trivially-net-zero sufficiency
        asserts. Mismatched requests take the legacy path."""
        stored = self.tasks.get(task.uid)
        if stored is None:
            task.status = status
            self.add_task_info(task)
            return
        if stored.resreq != task.resreq:
            self.delete_task_info(task)
            task.status = status
            self.add_task_info(task)
            return
        old_status = stored.status
        old_alloc = allocated_status(old_status)
        self._delete_task_index(stored)
        task.status = status
        new_alloc = allocated_status(status)
        if old_alloc and not new_alloc:
            self.allocated.sub(stored.resreq)
        elif new_alloc and not old_alloc:
            self.allocated.add(task.resreq)
        if old_status == TaskStatus.PENDING and status != TaskStatus.PENDING:
            self.pending_sum.sub(stored.resreq)
        elif status == TaskStatus.PENDING and old_status != TaskStatus.PENDING:
            self.pending_sum.add(task.resreq)
        # the incoming object replaces the stored one, as legacy
        # delete+add does (session code passes clones with independent
        # status words)
        self.tasks[task.uid] = task
        self._add_task_index(task)

    # -- readiness math ----------------------------------------------------

    def ready_task_num(self) -> int:
        # memoized on the status-index mutation counter: gang gates call
        # this per candidate visit in the preempt/allocate hot loops
        cached = self._ready_cache
        if cached is not None and cached[0] == self._status_version:
            return cached[1]
        n = 0
        for status, tasks in self.task_status_index.items():
            if allocated_status(status) or status == TaskStatus.SUCCEEDED:
                n += len(tasks)
        self._ready_cache = (self._status_version, n)
        return n

    def waiting_task_num(self) -> int:
        return len(self.task_status_index.get(TaskStatus.PIPELINED, {}))

    def valid_task_num(self) -> int:
        # memoized on the status-index version like ready_task_num: the
        # gang job-valid gate runs per job in every session open/encode
        cached = self._valid_cache
        if cached is not None and cached[0] == self._status_version:
            return cached[1]
        n = 0
        for status, tasks in self.task_status_index.items():
            if (
                allocated_status(status)
                or status == TaskStatus.SUCCEEDED
                or status == TaskStatus.PIPELINED
                or status == TaskStatus.PENDING
            ):
                n += len(tasks)
        self._valid_cache = (self._status_version, n)
        return n

    def ready(self) -> bool:
        return self.ready_task_num() >= self.min_available

    def pipelined(self) -> bool:
        return self.waiting_task_num() + self.ready_task_num() >= self.min_available

    # -- misc --------------------------------------------------------------

    def fit_error(self) -> str:
        """Status histogram message for unschedulable conditions
        (job_info.go:324-341)."""
        reasons = {str(s): len(t) for s, t in self.task_status_index.items()}
        reasons["minAvailable"] = self.min_available
        parts = sorted(f"{v} {k}" for k, v in reasons.items())
        return f"{objects.POD_GROUP_NOT_READY}, {', '.join(parts)}."

    def _clone_header(self) -> "JobInfo":
        info = JobInfo(self.uid)
        info.name = self.name
        info.namespace = self.namespace
        info.queue = self.queue
        info.priority = self.priority
        info.min_available = self.min_available
        info.pdb = self.pdb
        info.pod_group = self.pod_group
        info.creation_timestamp = self.creation_timestamp
        return info

    def clone(self) -> "JobInfo":
        """Field-copying clone: tasks become status-frozen shared_clones
        (resreq/init_resreq are never mutated in place anywhere in the
        tree — the same contract node task-maps already rely on), the
        status index is rebuilt with dict ops only, and the accounting
        sums (allocated / total_request / pending_sum) are deep-copied
        from the incrementally-maintained values instead of being
        re-derived one Resource.add per task. End state is identical to
        the replay clone (clone_replay, kept as the test oracle).

        Also captures the PENDING columnar axis while this walk already
        holds each task: the encoder's task axis becomes list-concats +
        one fromiter instead of a second 50k-object walk per session."""
        info = self._clone_header()
        info.allocated = self.allocated.clone()
        info.total_request = self.total_request.clone()
        info.pending_sum = self.pending_sum.clone()
        tasks = info.tasks
        index = info.task_status_index
        pend_t: list = []
        pend_r: list = []
        pend_g: list = []
        # bucket-wise walk: every task in a bucket shares its status, so
        # the per-task bucket lookup and PENDING branch hoist out of the
        # inner loop (at 50k tasks this loop is the bulk of session open)
        for status, bucket in self.task_status_index.items():
            nb = index[status] = {}
            for uid, task in bucket.items():
                t = task.shared_clone()
                nb[uid] = t
                tasks[uid] = t
            if status == TaskStatus.PENDING:
                pend_t = list(nb.values())
                pend_r = [t.row for t in pend_t]
                pend_g = [t.row_gen for t in pend_t]
        info._pending_axis = (pend_t, pend_r, pend_g, info._status_version)
        return info

    def clone_replay(self) -> "JobInfo":
        """Replay clone — rebuild the index and accounting through
        add_task_info from deep task clones (the original clone path).
        The oracle for clone(): drift between the incremental sums and
        the task set shows up as a mismatch between the two."""
        info = self._clone_header()
        pend_t: list = []
        pend_r: list = []
        pend_g: list = []
        for task in self.tasks.values():
            t = task.clone()
            info.add_task_info(t)
            if t.status == TaskStatus.PENDING:
                pend_t.append(t)
                pend_r.append(t.row)
                pend_g.append(t.row_gen)
        info._pending_axis = (pend_t, pend_r, pend_g, info._status_version)
        return info

    def pending_axis(self):
        """The clone-captured (tasks, rows, row_gens) of the PENDING
        bucket, or None when the status index changed since capture (the
        caller walks the bucket instead)."""
        ax = self._pending_axis
        if ax is not None and ax[3] == self._status_version:
            return ax[0], ax[1], ax[2]
        return None

    def is_terminated(self) -> bool:
        """helpers.go JobTerminated."""
        return self.pod_group is None and self.pdb is None and not self.tasks

    def __repr__(self) -> str:
        return (
            f"Job ({self.uid}): namespace {self.namespace} ({self.queue}), "
            f"name {self.name}, minAvailable {self.min_available}, "
            f"{len(self.tasks)} tasks"
        )
