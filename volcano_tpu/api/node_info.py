"""NodeInfo — per-node resource accounting (volcano pkg/scheduler/api/node_info.go).

The node holds *clones* of tasks so later status flips on the session's task
objects can't corrupt the accounting (node_info.go:196-197). Over-allocation
flips the node to NotReady/OutOfSync instead of corrupting state
(node_info.go:175-185).
"""

from __future__ import annotations

from typing import Dict, Optional

from volcano_tpu.api import objects
from volcano_tpu.api.pod_helpers import pod_key
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import NodePhase, TaskStatus
from volcano_tpu.api.job_info import TaskInfo


class NodeState:
    __slots__ = ("phase", "reason")

    def __init__(self, phase: NodePhase, reason: str = ""):
        self.phase = phase
        self.reason = reason


class NodeInfo:
    """Node-level aggregated accounting: Idle/Used/Releasing vs
    Allocatable/Capability (node_info.go:28-50)."""

    def __init__(self, node: Optional[objects.Node] = None):
        self.node = node
        self.releasing = Resource.empty()
        self.used = Resource.empty()
        self.tasks: Dict[str, TaskInfo] = {}
        self.others: Dict[str, object] = {}
        # accounting generation: bumped by every mutation of the node's
        # resource state (add/remove/update_task, set_node, and the bulk
        # writeback's direct idle/used deltas). The snapshot-captured
        # columnar node axis (cache/nodeaxis.py) records it so the encoder
        # can prove the capture still reflects this node
        self._acct_gen = 0

        if node is None:
            self.name = ""
            self.idle = Resource.empty()
            self.allocatable = Resource.empty()
            self.capability = Resource.empty()
        else:
            self.name = node.metadata.name
            self.idle = Resource.from_resource_list(node.status.allocatable)
            self.allocatable = Resource.from_resource_list(node.status.allocatable)
            self.capability = Resource.from_resource_list(node.status.capacity)

        self.state = NodeState(NodePhase.NOT_READY, "UnInitialized")
        self._set_node_state(node)

    # -- state -------------------------------------------------------------

    def ready(self) -> bool:
        return self.state.phase == NodePhase.READY

    def _set_node_state(self, node: Optional[objects.Node]) -> None:
        """(node_info.go:110-145)"""
        if node is None:
            self.state = NodeState(NodePhase.NOT_READY, "UnInitialized")
            return
        if not self.used.less_equal(Resource.from_resource_list(node.status.allocatable)):
            self.state = NodeState(NodePhase.NOT_READY, "OutOfSync")
            return
        for cond in node.status.conditions:
            if cond.type == "Ready" and cond.status != "True":
                self.state = NodeState(NodePhase.NOT_READY, "NotReady")
                return
        self.state = NodeState(NodePhase.READY)

    def set_node(self, node: objects.Node) -> None:
        """Refresh from the node object, recomputing accounting from held
        tasks (node_info.go:148-173)."""
        self._acct_gen += 1
        self._set_node_state(node)
        if not self.ready():
            return

        self.name = node.metadata.name
        self.node = node
        self.allocatable = Resource.from_resource_list(node.status.allocatable)
        self.capability = Resource.from_resource_list(node.status.capacity)
        self.idle = Resource.from_resource_list(node.status.allocatable)
        self.used = Resource.empty()

        for task in self.tasks.values():
            if task.status == TaskStatus.RELEASING:
                self.releasing.add(task.resreq)
            self.idle.sub(task.resreq)
            self.used.add(task.resreq)

    # -- task accounting ---------------------------------------------------

    def _allocate_idle(self, ti: TaskInfo) -> None:
        if ti.resreq.less_equal(self.idle):
            self.idle.sub(ti.resreq)
            return
        self.state = NodeState(NodePhase.NOT_READY, "OutOfSync")
        raise RuntimeError("Selected node NotReady")

    def add_task(self, task: TaskInfo) -> None:
        """(node_info.go:188-220)"""
        self._acct_gen += 1
        key = pod_key(task.pod) if task.pod is not None else f"{task.namespace}/{task.name}"
        if key in self.tasks:
            raise RuntimeError(
                f"task <{task.namespace}/{task.name}> already on node <{self.name}>"
            )
        # status-frozen copy: the map entry must not see later status flips
        # of the caller's object (node_info.go:188-220 clones for the same
        # reason), but resreq/init_resreq are never mutated in place
        # anywhere in the tree, so sharing them skips two Resource
        # deep-copies per placement — the statement-path analog of the bulk
        # writeback's shared_clone usage
        ti = task.shared_clone()
        if self.node is not None:
            if ti.status == TaskStatus.RELEASING:
                self._allocate_idle(ti)
                self.releasing.add(ti.resreq)
            elif ti.status == TaskStatus.PIPELINED:
                self.releasing.sub(ti.resreq)
            else:
                self._allocate_idle(ti)
            self.used.add(ti.resreq)
        self.tasks[key] = ti

    def remove_task(self, ti: TaskInfo) -> None:
        """(node_info.go:223-249)"""
        self._acct_gen += 1
        key = pod_key(ti.pod) if ti.pod is not None else f"{ti.namespace}/{ti.name}"
        task = self.tasks.get(key)
        if task is None:
            raise RuntimeError(
                f"failed to find task <{ti.namespace}/{ti.name}> on host <{self.name}>"
            )
        if self.node is not None:
            if task.status == TaskStatus.RELEASING:
                self.releasing.sub(task.resreq)
                self.idle.add(task.resreq)
            elif task.status == TaskStatus.PIPELINED:
                self.releasing.add(task.resreq)
            else:
                self.idle.add(task.resreq)
            self.used.sub(task.resreq)
        del self.tasks[key]

    def update_task(self, ti: TaskInfo) -> None:
        """remove_task + add_task, fused for the transitions the actions
        actually perform (evict: allocated->RELEASING, unevict back,
        pipeline commits). In those the idle/used movements of remove and
        add cancel exactly and the interleaved sufficiency checks are
        trivially true (remove just returned the same quantity add takes
        back), so the fused path applies only the net releasing/idle delta
        and refreshes the node-owned clone in place — bit-identical end
        state, minus two Resource deep-copies and two no-op epsilon checks
        per call. Transitions whose checks are REAL (from PIPELINED, or
        RELEASING->PIPELINED) and mismatched requests take the legacy
        remove+add path."""
        self._acct_gen += 1
        key = pod_key(ti.pod) if ti.pod is not None else f"{ti.namespace}/{ti.name}"
        cur = self.tasks.get(key)
        if cur is None:
            raise RuntimeError(
                f"failed to find task <{ti.namespace}/{ti.name}> on host <{self.name}>"
            )
        old, new = cur.status, ti.status
        RELEASING, PIPELINED = TaskStatus.RELEASING, TaskStatus.PIPELINED
        if cur.resreq != ti.resreq or (
            self.node is not None
            and (old == PIPELINED or (old == RELEASING and new == PIPELINED))
        ):
            self.remove_task(ti)
            self.add_task(ti)
            return
        if self.node is not None and old != new:
            req = ti.resreq
            if new == RELEASING and old != RELEASING:
                self.releasing.add(req)
            elif old == RELEASING and new != RELEASING:
                self.releasing.sub(req)
            elif new == PIPELINED:  # allocated -> PIPELINED
                self.idle.add(req)
                self.releasing.sub(req)
        # in-place refresh of the node-owned clone (remove+add would have
        # replaced it with ti.clone(); resreq is value-equal by the gate)
        cur.status = new
        cur.node_name = ti.node_name
        cur.priority = ti.priority
        cur.volume_ready = ti.volume_ready
        cur.init_resreq = ti.init_resreq  # never mutated via node maps
        cur.pod = ti.pod
        cur.row = ti.row
        cur.row_gen = ti.row_gen

    # -- misc --------------------------------------------------------------

    def clone(self) -> "NodeInfo":
        """Field-copying clone: the accounting Resources are deep-copied
        (the session and the bulk writeback mutate idle/used/releasing in
        place), tasks are status-frozen shared_clones, and the parsed
        allocatable/capability are copied WITHOUT re-parsing the node's
        quantity strings — the replay clone (clone_replay) re-derived all
        accounting through add_task, costing 12 parse_quantity calls and a
        per-task replay per node per snapshot. End state is identical
        (asserted by tests against clone_replay); the invariant that
        accounting == sum over held tasks is maintained incrementally by
        every mutator above."""
        res = NodeInfo.__new__(NodeInfo)
        res.node = self.node
        res.name = self.name
        res.releasing = self.releasing.clone()
        res.used = self.used.clone()
        res.idle = self.idle.clone()
        # allocatable/capability are REASSIGNED (set_node) but never
        # mutated in place anywhere in the tree — shared like task
        # resreqs, skipping two Resource deep-copies per node per snapshot
        res.allocatable = self.allocatable
        res.capability = self.capability
        res.tasks = {k: t.shared_clone() for k, t in self.tasks.items()}
        res.others = self.others
        res._acct_gen = self._acct_gen
        res.state = NodeState(self.state.phase, self.state.reason)
        return res

    def clone_replay(self) -> "NodeInfo":
        """Replay clone: rebuild accounting from the node object + held
        tasks through add_task (the original clone path). Kept as the
        oracle for clone() — any drift between the incremental accounting
        and the task set shows up as a mismatch between the two."""
        res = NodeInfo(self.node)
        for task in self.tasks.values():
            res.add_task(task)
        res.others = self.others
        res._acct_gen = self._acct_gen
        return res

    def pods(self) -> list:
        return [t.pod for t in self.tasks.values()]

    def __repr__(self) -> str:
        return (
            f"Node ({self.name}): idle <{self.idle}>, used <{self.used}>, "
            f"releasing <{self.releasing}>, state <{self.state.phase}, "
            f"{self.state.reason}>"
        )
