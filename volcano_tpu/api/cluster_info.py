"""ClusterInfo — the per-session snapshot handed to every action
(volcano pkg/scheduler/api/cluster_info.go)."""

from __future__ import annotations

from typing import Dict

from volcano_tpu.api.job_info import JobInfo
from volcano_tpu.api.namespace_info import NamespaceInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.queue_info import QueueInfo


class ClusterInfo:
    __slots__ = ("jobs", "nodes", "queues", "namespace_info", "node_axis")

    def __init__(self):
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.namespace_info: Dict[str, NamespaceInfo] = {}
        # columnar capture of the ready nodes (cache/nodeaxis.py), built by
        # snapshot() in the same pass that clones them; None when the
        # embedding cache does not capture
        self.node_axis = None

    def __repr__(self) -> str:
        return (
            f"ClusterInfo: {len(self.jobs)} jobs, {len(self.nodes)} nodes, "
            f"{len(self.queues)} queues"
        )
