"""Share/min helpers (volcano pkg/scheduler/api/helpers/)."""

from __future__ import annotations

from volcano_tpu.api.resource import Resource


def share(l: float, r: float) -> float:
    if r == 0:
        return 0.0 if l == 0 else 1.0
    return l / r


def min_resource(l: Resource, r: Resource) -> Resource:
    res = Resource(min(l.milli_cpu, r.milli_cpu), min(l.memory, r.memory))
    if l.scalar_resources is None or r.scalar_resources is None:
        return res
    res.scalar_resources = {}
    for name, quant in l.scalar_resources.items():
        res.scalar_resources[name] = min(quant, r.scalar_resources.get(name, 0.0))
    return res
