"""Resource vector arithmetic with the reference's epsilon semantics.

Parity-critical: binding decisions depend on the exact comparison semantics of
the reference implementation (volcano pkg/scheduler/api/resource_info.go):

- working units are milli-CPU, bytes of memory, and milli-units of arbitrary
  scalar resources (e.g. "nvidia.com/gpu");
- ``less_equal`` uses per-dimension epsilons (resource_info.go:267-301):
  10 milli-CPU, 10 MiB memory, 10 milli-scalar;
- ``sub`` asserts sufficiency first (resource_info.go:145-159);
- scalar dimensions absent from a Resource are treated as zero, with the same
  nil-map special cases the reference has.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, Optional

from volcano_tpu.api.quantity import milli_value, parse_quantity
from volcano_tpu.utils.assertions import assertf

GPU_RESOURCE_NAME = "nvidia.com/gpu"

# Minimum meaningful quantities (resource_info.go:70-72).
MIN_MILLI_CPU = 10.0
MIN_MILLI_SCALAR = 10.0
MIN_MEMORY = 10.0 * 1024 * 1024

_NATIVE = ("cpu", "memory", "pods")


def is_scalar_resource_name(name: str) -> bool:
    """Mirrors k8s v1helper.IsScalarResourceName: extended resources
    (non-kubernetes.io domain-prefixed, not quota "requests.*" aliases),
    hugepages, and attachable volume counts."""
    if name.startswith("hugepages-") or name.startswith("attachable-volumes-"):
        return True
    if name.startswith("requests."):
        return False
    if "/" in name:
        return name.split("/", 1)[0] != "kubernetes.io"
    return False


class Resource:
    """A resource vector: milli_cpu (milli-cores), memory (bytes), and a map
    of scalar resources in milli-units.

    ``max_task_num`` (from the "pods" resource) is only consulted by
    predicates and deliberately excluded from arithmetic
    (resource_info.go:37-39).
    """

    __slots__ = ("milli_cpu", "memory", "scalar_resources", "max_task_num")

    def __init__(
        self,
        milli_cpu: float = 0.0,
        memory: float = 0.0,
        scalar_resources: Optional[Dict[str, float]] = None,
        max_task_num: int = 0,
    ):
        self.milli_cpu = float(milli_cpu)
        self.memory = float(memory)
        self.scalar_resources: Optional[Dict[str, float]] = scalar_resources
        self.max_task_num = max_task_num

    # -- constructors ------------------------------------------------------

    @classmethod
    def empty(cls) -> "Resource":
        return cls()

    @classmethod
    def from_resource_list(cls, rl: Optional[Dict[str, object]]) -> "Resource":
        """Build from a k8s-style resource list, e.g.
        ``{"cpu": "4", "memory": "8Gi", "pods": 110, "nvidia.com/gpu": 1}``
        (resource_info.go:75-93)."""
        r = cls()
        if not rl:
            return r
        for name, quant in rl.items():
            if name == "cpu":
                r.milli_cpu += milli_value(quant)
            elif name == "memory":
                r.memory += parse_quantity(quant)
            elif name == "pods":
                r.max_task_num += int(parse_quantity(quant))
            elif is_scalar_resource_name(name):
                r.add_scalar(name, milli_value(quant))
        return r

    def clone(self) -> "Resource":
        return Resource(
            self.milli_cpu,
            self.memory,
            dict(self.scalar_resources) if self.scalar_resources is not None else None,
            self.max_task_num,
        )

    # -- predicates --------------------------------------------------------

    def is_empty(self) -> bool:
        """True when every dimension is below its minimum meaningful value
        (resource_info.go:96-108)."""
        if not (self.milli_cpu < MIN_MILLI_CPU and self.memory < MIN_MEMORY):
            return False
        for quant in (self.scalar_resources or {}).values():
            if quant >= MIN_MILLI_SCALAR:
                return False
        return True

    def is_zero(self, name: str) -> bool:
        """True when the named dimension is below its minimum
        (resource_info.go:111-127)."""
        if name == "cpu":
            return self.milli_cpu < MIN_MILLI_CPU
        if name == "memory":
            return self.memory < MIN_MEMORY
        if self.scalar_resources is None:
            return True
        assertf(name in self.scalar_resources, "unknown resource %s", name)
        return self.scalar_resources.get(name, 0.0) < MIN_MILLI_SCALAR

    # -- arithmetic (mutating, returning self, like the reference) ---------

    def add(self, rr: "Resource") -> "Resource":
        self.milli_cpu += rr.milli_cpu
        self.memory += rr.memory
        for name, quant in (rr.scalar_resources or {}).items():
            if self.scalar_resources is None:
                self.scalar_resources = {}
            self.scalar_resources[name] = self.scalar_resources.get(name, 0.0) + quant
        return self

    def sub(self, rr: "Resource") -> "Resource":
        """Subtract, asserting sufficiency (resource_info.go:145-159)."""
        assertf(
            rr.less_equal(self),
            "resource is not sufficient to do operation: <%s> sub <%s>",
            self,
            rr,
        )
        self.milli_cpu -= rr.milli_cpu
        self.memory -= rr.memory
        if self.scalar_resources is None:
            return self
        for name, quant in (rr.scalar_resources or {}).items():
            self.scalar_resources[name] = self.scalar_resources.get(name, 0.0) - quant
        return self

    def multi(self, ratio: float) -> "Resource":
        self.milli_cpu *= ratio
        self.memory *= ratio
        for name in self.scalar_resources or {}:
            self.scalar_resources[name] *= ratio
        return self

    def set_max_resource(self, rr: Optional["Resource"]) -> None:
        """Per-dimension max, in place (resource_info.go:162-187)."""
        if rr is None:
            return
        if rr.milli_cpu > self.milli_cpu:
            self.milli_cpu = rr.milli_cpu
        if rr.memory > self.memory:
            self.memory = rr.memory
        for name, quant in (rr.scalar_resources or {}).items():
            if self.scalar_resources is None:
                self.scalar_resources = dict(rr.scalar_resources)
                return
            if quant > self.scalar_resources.get(name, 0.0):
                self.scalar_resources[name] = quant

    def fit_delta(self, rr: "Resource") -> "Resource":
        """Availability minus request, padded by the per-dimension minimum;
        any negative dimension marks insufficiency (resource_info.go:193-213)."""
        if rr.milli_cpu > 0:
            self.milli_cpu -= rr.milli_cpu + MIN_MILLI_CPU
        if rr.memory > 0:
            self.memory -= rr.memory + MIN_MEMORY
        for name, quant in (rr.scalar_resources or {}).items():
            if self.scalar_resources is None:
                self.scalar_resources = {}
            if quant > 0:
                self.scalar_resources[name] = (
                    self.scalar_resources.get(name, 0.0) - quant - MIN_MILLI_SCALAR
                )
        return self

    # -- comparisons -------------------------------------------------------

    def less(self, rr: "Resource") -> bool:
        """Strictly less on every dimension (resource_info.go:226-264,
        including its nil-map asymmetries)."""
        if not self.milli_cpu < rr.milli_cpu:
            return False
        if not self.memory < rr.memory:
            return False
        if self.scalar_resources is None:
            if rr.scalar_resources is not None:
                for quant in rr.scalar_resources.values():
                    if quant <= MIN_MILLI_SCALAR:
                        return False
            return True
        if rr.scalar_resources is None:
            return False
        for name, quant in self.scalar_resources.items():
            if not quant < rr.scalar_resources.get(name, 0.0):
                return False
        return True

    def less_equal(self, rr: "Resource") -> bool:
        """Less-or-equal with per-dimension epsilon tolerance
        (resource_info.go:267-301). THE feasibility comparison."""

        def le(l: float, r: float, diff: float) -> bool:
            return l < r or abs(l - r) < diff

        if not le(self.milli_cpu, rr.milli_cpu, MIN_MILLI_CPU):
            return False
        if not le(self.memory, rr.memory, MIN_MEMORY):
            return False
        if self.scalar_resources is None:
            return True
        for name, quant in self.scalar_resources.items():
            if quant <= MIN_MILLI_SCALAR:
                continue
            if rr.scalar_resources is None:
                return False
            if not le(quant, rr.scalar_resources.get(name, 0.0), MIN_MILLI_SCALAR):
                return False
        return True

    def diff(self, rr: "Resource") -> tuple["Resource", "Resource"]:
        """(increased, decreased) per-dimension differences
        (resource_info.go:304-336)."""
        inc, dec = Resource(), Resource()
        if self.milli_cpu > rr.milli_cpu:
            inc.milli_cpu += self.milli_cpu - rr.milli_cpu
        else:
            dec.milli_cpu += rr.milli_cpu - self.milli_cpu
        if self.memory > rr.memory:
            inc.memory += self.memory - rr.memory
        else:
            dec.memory += rr.memory - self.memory
        for name, quant in (self.scalar_resources or {}).items():
            rr_quant = (rr.scalar_resources or {}).get(name, 0.0)
            if quant > rr_quant:
                if inc.scalar_resources is None:
                    inc.scalar_resources = {}
                inc.scalar_resources[name] = (
                    inc.scalar_resources.get(name, 0.0) + quant - rr_quant
                )
            else:
                if dec.scalar_resources is None:
                    dec.scalar_resources = {}
                dec.scalar_resources[name] = (
                    dec.scalar_resources.get(name, 0.0) + rr_quant - quant
                )
        return inc, dec

    # -- accessors ---------------------------------------------------------

    def get(self, name: str) -> float:
        if name == "cpu":
            return self.milli_cpu
        if name == "memory":
            return self.memory
        if self.scalar_resources is None:
            return 0.0
        return self.scalar_resources.get(name, 0.0)

    def resource_names(self) -> list[str]:
        return ["cpu", "memory", *list(self.scalar_resources or {})]

    def add_scalar(self, name: str, quantity: float) -> None:
        self.set_scalar(name, (self.scalar_resources or {}).get(name, 0.0) + quantity)

    def set_scalar(self, name: str, quantity: float) -> None:
        if self.scalar_resources is None:
            self.scalar_resources = {}
        self.scalar_resources[name] = quantity

    # -- misc --------------------------------------------------------------

    def __repr__(self) -> str:
        s = f"cpu {self.milli_cpu:0.2f}, memory {self.memory:0.2f}"
        for name, quant in (self.scalar_resources or {}).items():
            s += f", {name} {quant:0.2f}"
        return s

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Resource):
            return NotImplemented
        if self.milli_cpu != other.milli_cpu or self.memory != other.memory:
            return False
        mine = {k: v for k, v in (self.scalar_resources or {}).items() if v != 0}
        theirs = {k: v for k, v in (other.scalar_resources or {}).items() if v != 0}
        return mine == theirs

    def __hash__(self):
        raise TypeError("Resource is mutable and unhashable")
