"""The framework's API object model — the analog of volcano's CRDs and the
slice of core/v1 it consumes.

These are plain mutable dataclasses living in the in-process event store
(volcano_tpu.store). They mirror:
- Pod/Node: the consumed subset of k8s core/v1;
- PodGroup/Queue: pkg/apis/scheduling/types.go;
- Job (batch): pkg/apis/batch/v1alpha1/job.go;
- Command (bus): pkg/apis/bus/v1alpha1/types.go.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

GROUP_NAME_ANNOTATION_KEY = "scheduling.volcano.sh/group-name"
TASK_SPEC_KEY = "volcano.sh/task-spec"
JOB_NAME_KEY = "volcano.sh/job-name"
JOB_VERSION_KEY = "volcano.sh/job-version"
NAMESPACE_WEIGHT_KEY = "volcano.sh/namespace.weight"

POD_PHASE_PENDING = "Pending"
POD_PHASE_RUNNING = "Running"
POD_PHASE_SUCCEEDED = "Succeeded"
POD_PHASE_FAILED = "Failed"
POD_PHASE_UNKNOWN = "Unknown"

_uid_counter = itertools.count(1)


def new_uid(prefix: str = "uid") -> str:
    return f"{prefix}-{next(_uid_counter):08d}"


@dataclass
class OwnerReference:
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False
    block_owner_deletion: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    resource_version: int = 0
    owner_references: List[OwnerReference] = field(default_factory=list)

    def ensure_identity(self) -> None:
        if not self.uid:
            self.uid = new_uid(self.name or "obj")
        if not self.creation_timestamp:
            from volcano_tpu.utils import clock

            self.creation_timestamp = clock.now()


# ---------------------------------------------------------------------------
# Pod (consumed subset of core/v1)
# ---------------------------------------------------------------------------


@dataclass
class ContainerPort:
    container_port: int = 0
    host_port: int = 0
    protocol: str = "TCP"


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""


@dataclass
class Container:
    name: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    requests: Dict[str, object] = field(default_factory=dict)
    limits: Dict[str, object] = field(default_factory=dict)
    ports: List[ContainerPort] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    volume_mounts: List[VolumeMount] = field(default_factory=list)


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # NoSchedule | PreferNoSchedule | NoExecute | "" (all)

    def tolerates(self, taint: "Taint") -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.key == "":
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass
class LabelSelectorRequirement:
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist
    values: List[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for req in self.match_expressions:
            present = req.key in labels
            if req.operator == "In":
                if not present or labels[req.key] not in req.values:
                    return False
            elif req.operator == "NotIn":
                if present and labels[req.key] in req.values:
                    return False
            elif req.operator == "Exists":
                if not present:
                    return False
            elif req.operator == "DoesNotExist":
                if present:
                    return False
        return True


@dataclass
class NodeSelectorRequirement:
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: List[str] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        present = self.key in labels
        req_val = labels.get(self.key)
        if self.operator == "In":
            return present and req_val in self.values
        if self.operator == "NotIn":
            return not present or req_val not in self.values
        if self.operator == "Exists":
            return present
        if self.operator == "DoesNotExist":
            return not present
        if self.operator in ("Gt", "Lt"):
            if not present or not self.values:
                return False
            have, want = _as_int(req_val), _as_int(self.values[0])
            if have is None or want is None:
                return False
            return have > want if self.operator == "Gt" else have < want
        return False


def _as_int(v) -> Optional[int]:
    try:
        return int(v)
    except (TypeError, ValueError):
        return None


@dataclass
class NodeSelectorTerm:
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        return all(r.matches(labels) for r in self.match_expressions)


@dataclass
class PreferredSchedulingTerm:
    weight: int = 0
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class NodeAffinity:
    # requiredDuringSchedulingIgnoredDuringExecution: OR of terms
    required_terms: List[NodeSelectorTerm] = field(default_factory=list)
    preferred_terms: List[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    label_selector: Optional[LabelSelector] = None
    namespaces: List[str] = field(default_factory=list)
    topology_key: str = "kubernetes.io/hostname"


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 0
    pod_affinity_term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class PodAffinity:
    required_terms: List[PodAffinityTerm] = field(default_factory=list)
    preferred_terms: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class PodAntiAffinity:
    required_terms: List[PodAffinityTerm] = field(default_factory=list)
    preferred_terms: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


@dataclass
class Volume:
    name: str = ""
    persistent_volume_claim: str = ""  # claim name
    config_map: str = ""
    empty_dir: bool = False


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    priority: Optional[int] = None
    priority_class_name: str = ""
    scheduler_name: str = ""
    hostname: str = ""
    subdomain: str = ""
    restart_policy: str = "Always"
    volumes: List[Volume] = field(default_factory=list)
    service_account_name: str = ""


@dataclass
class ContainerStatus:
    name: str = ""
    exit_code: int = 0
    ready: bool = False


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class PodStatus:
    phase: str = POD_PHASE_PENDING
    reason: str = ""
    message: str = ""
    conditions: List[PodCondition] = field(default_factory=list)
    container_statuses: List[ContainerStatus] = field(default_factory=list)
    init_container_statuses: List[ContainerStatus] = field(default_factory=list)
    start_time: Optional[float] = None


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    KIND = "Pod"


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"


@dataclass
class NodeCondition:
    type: str = "Ready"
    status: str = "True"


@dataclass
class NodeSpec:
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False


@dataclass
class NodeStatus:
    capacity: Dict[str, object] = field(default_factory=dict)
    allocatable: Dict[str, object] = field(default_factory=dict)
    conditions: List[NodeCondition] = field(default_factory=lambda: [NodeCondition()])


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    KIND = "Node"


# ---------------------------------------------------------------------------
# PodGroup / Queue (scheduling group; pkg/apis/scheduling/types.go)
# ---------------------------------------------------------------------------


class PodGroupPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    UNKNOWN = "Unknown"
    INQUEUE = "Inqueue"


POD_GROUP_UNSCHEDULABLE_TYPE = "Unschedulable"
POD_GROUP_NOT_READY = "PodGroupNotReady"

NOT_ENOUGH_RESOURCES_REASON = "NotEnoughResources"
NOT_ENOUGH_PODS_REASON = "NotEnoughTasks"


@dataclass
class PodGroupCondition:
    type: str = ""
    status: str = ""  # "True" | "False"
    transition_id: str = ""
    last_transition_time: float = 0.0
    reason: str = ""
    message: str = ""


@dataclass
class PodGroupSpec:
    min_member: int = 0
    queue: str = ""
    priority_class_name: str = ""
    min_resources: Optional[Dict[str, object]] = None


@dataclass
class PodGroupStatus:
    phase: str = PodGroupPhase.PENDING
    conditions: List[PodGroupCondition] = field(default_factory=list)
    running: int = 0
    succeeded: int = 0
    failed: int = 0

    def clone(self) -> "PodGroupStatus":
        return PodGroupStatus(
            phase=self.phase,
            conditions=list(self.conditions),
            running=self.running,
            succeeded=self.succeeded,
            failed=self.failed,
        )


@dataclass
class PodGroup:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)

    KIND = "PodGroup"


@dataclass
class QueueSpec:
    weight: int = 1
    capability: Optional[Dict[str, object]] = None
    reclaimable: bool = True


@dataclass
class QueueStatus:
    state: str = "Open"
    unknown: int = 0
    pending: int = 0
    running: int = 0
    inqueue: int = 0


@dataclass
class Queue:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: QueueSpec = field(default_factory=QueueSpec)
    status: QueueStatus = field(default_factory=QueueStatus)

    KIND = "Queue"


# ---------------------------------------------------------------------------
# PriorityClass / quota / disruption-budget analogs
# ---------------------------------------------------------------------------


@dataclass
class PriorityClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    preemption_policy: str = "PreemptLowerPriority"

    KIND = "PriorityClass"


SYSTEM_CLUSTER_CRITICAL = "system-cluster-critical"
SYSTEM_NODE_CRITICAL = "system-node-critical"


@dataclass
class ResourceQuota:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    hard: Dict[str, object] = field(default_factory=dict)

    KIND = "ResourceQuota"


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    min_available: int = 0

    KIND = "PodDisruptionBudget"


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    requests: Dict[str, object] = field(default_factory=dict)
    phase: str = "Pending"
    volume_name: str = ""  # bound PV (set by the volume binder)

    KIND = "PersistentVolumeClaim"


@dataclass
class PersistentVolume:
    """Cluster-scoped storage the volume binder assumes/binds PVCs
    against (the reference binds through the k8s volumebinder —
    pkg/scheduler/cache/cache.go:240-258; this is the store-native
    equivalent). Empty ``node_names`` means host-agnostic storage;
    otherwise the volume is local to those nodes and constrains
    placement at binding time."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    capacity: Dict[str, object] = field(default_factory=dict)  # {"storage": "10Gi"}
    node_names: List[str] = field(default_factory=list)
    claim_ref: str = ""  # "namespace/name" of the bound PVC
    phase: str = "Available"  # Available | Bound

    KIND = "PersistentVolume"


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)

    KIND = "ConfigMap"


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    cluster_ip: str = ""  # "None" = headless
    selector: Dict[str, str] = field(default_factory=dict)

    KIND = "Service"


# ---------------------------------------------------------------------------
# batch Job (pkg/apis/batch/v1alpha1/job.go)
# ---------------------------------------------------------------------------


class JobEvent:
    """Events the lifecycle policy engine reacts to (job.go:120-144)."""

    ANY = "*"
    POD_FAILED = "PodFailed"
    POD_EVICTED = "PodEvicted"
    JOB_UNKNOWN = "Unknown"
    TASK_COMPLETED = "TaskCompleted"
    # internal
    OUT_OF_SYNC = "OutOfSync"
    COMMAND_ISSUED = "CommandIssued"


class JobAction:
    """Actions the job controller can take (job.go:146-172)."""

    ABORT_JOB = "AbortJob"
    RESTART_JOB = "RestartJob"
    RESTART_TASK = "RestartTask"
    TERMINATE_JOB = "TerminateJob"
    COMPLETE_JOB = "CompleteJob"
    RESUME_JOB = "ResumeJob"
    # internal
    SYNC_JOB = "SyncJob"
    ENQUEUE_JOB = "EnqueueJob"


class JobPhase:
    """Job lifecycle phases (job.go:223-246)."""

    PENDING = "Pending"
    ABORTING = "Aborting"
    ABORTED = "Aborted"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    COMPLETING = "Completing"
    COMPLETED = "Completed"
    TERMINATING = "Terminating"
    TERMINATED = "Terminated"
    FAILED = "Failed"


@dataclass
class LifecyclePolicy:
    action: str = ""
    event: str = ""
    events: List[str] = field(default_factory=list)
    exit_code: Optional[int] = None
    timeout_seconds: Optional[float] = None


@dataclass
class TaskSpec:
    name: str = ""
    replicas: int = 0
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    policies: List[LifecyclePolicy] = field(default_factory=list)


@dataclass
class VolumeSpec:
    mount_path: str = ""
    volume_claim_name: str = ""
    volume_claim: Optional[Dict[str, object]] = None  # PVC spec (requests)


@dataclass
class JobSpec:
    scheduler_name: str = ""
    min_available: int = 0
    volumes: List[VolumeSpec] = field(default_factory=list)
    tasks: List[TaskSpec] = field(default_factory=list)
    policies: List[LifecyclePolicy] = field(default_factory=list)
    plugins: Dict[str, List[str]] = field(default_factory=dict)
    queue: str = ""
    max_retry: int = 3
    ttl_seconds_after_finished: Optional[int] = None
    priority_class_name: str = ""


@dataclass
class JobState:
    phase: str = JobPhase.PENDING
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class JobStatus:
    state: JobState = field(default_factory=JobState)
    min_available: int = 0
    pending: int = 0
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    terminating: int = 0
    unknown: int = 0
    version: int = 0
    retry_count: int = 0
    controlled_resources: Dict[str, str] = field(default_factory=dict)


@dataclass
class Job:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)

    KIND = "Job"


# ---------------------------------------------------------------------------
# bus Command (pkg/apis/bus/v1alpha1/types.go)
# ---------------------------------------------------------------------------


@dataclass
class Command:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    action: str = ""
    target_object: Optional[OwnerReference] = None
    reason: str = ""
    message: str = ""

    KIND = "Command"
