"""QueueInfo (volcano pkg/scheduler/api/queue_info.go)."""

from __future__ import annotations

from volcano_tpu.api import objects


class QueueInfo:
    __slots__ = ("uid", "name", "weight", "queue")

    def __init__(self, queue: objects.Queue):
        self.uid = queue.metadata.name  # QueueID is the queue name
        self.name = queue.metadata.name
        self.weight = queue.spec.weight
        self.queue = queue

    def clone(self) -> "QueueInfo":
        return QueueInfo(self.queue)

    def reclaimable(self) -> bool:
        return self.queue.spec.reclaimable

    def __repr__(self) -> str:
        return f"Queue ({self.name}): weight {self.weight}"
