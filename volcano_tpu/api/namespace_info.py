"""Namespace weights from ResourceQuotas
(volcano pkg/scheduler/api/namespace_info.go).

A namespace's weight is the max `volcano.sh/namespace.weight` hard-quota
value across its ResourceQuotas (namespace_info.go:75-130); default 1.
"""

from __future__ import annotations

from typing import Dict, Optional

from volcano_tpu.api import objects
from volcano_tpu.api.quantity import parse_quantity

DEFAULT_NAMESPACE_WEIGHT = 1
NAMESPACE_WEIGHT_KEY = objects.NAMESPACE_WEIGHT_KEY


class NamespaceInfo:
    __slots__ = ("name", "weight")

    def __init__(self, name: str, weight: int = DEFAULT_NAMESPACE_WEIGHT):
        self.name = name
        self.weight = weight

    def get_weight(self) -> int:
        if self.weight == 0:
            return DEFAULT_NAMESPACE_WEIGHT
        return self.weight


def _quota_weight(quota: objects.ResourceQuota) -> Optional[int]:
    if NAMESPACE_WEIGHT_KEY not in quota.hard:
        return None
    return int(parse_quantity(quota.hard[NAMESPACE_WEIGHT_KEY]))


class NamespaceCollection:
    """Tracks the weight-bearing quotas of one namespace; the effective
    weight is the max one still present."""

    def __init__(self, name: str):
        self.name = name
        # quota-name -> weight; max wins (the reference uses a heap keyed on
        # weight with named entries — a dict-max is equivalent).
        self._quota_weights: Dict[str, int] = {}

    def update(self, quota: objects.ResourceQuota) -> None:
        w = _quota_weight(quota)
        if w is None:
            self._quota_weights.pop(quota.metadata.name, None)
        else:
            self._quota_weights[quota.metadata.name] = w

    def delete(self, quota: objects.ResourceQuota) -> None:
        self._quota_weights.pop(quota.metadata.name, None)

    def snapshot(self) -> NamespaceInfo:
        if not self._quota_weights:
            return NamespaceInfo(self.name, DEFAULT_NAMESPACE_WEIGHT)
        return NamespaceInfo(self.name, max(self._quota_weights.values()))

    def empty(self) -> bool:
        return not self._quota_weights
