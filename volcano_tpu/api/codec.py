"""Wire codec for the API object model: dataclass <-> JSON-safe dicts.

The reference's CRDs travel as JSON through the Kubernetes API server;
here the same objects (api/objects.py dataclasses) travel through the
store gateway (store/gateway.py) to remote clients (store/remote.py,
vcctl --server). The model is deliberately JSON-shaped — plain
dataclasses of primitives, lists, string-keyed dicts and nested
dataclasses, no enums — so the codec is a generic reflection over
dataclass fields with type-hint-driven hydration.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, Optional, get_type_hints

from volcano_tpu.api import objects

# kind -> dataclass, for every store-storable object (classes declaring
# KIND) plus the nested types hydrate() reaches through type hints
_KINDS: Dict[str, type] = {}
for _name in dir(objects):
    _cls = getattr(objects, _name)
    if isinstance(_cls, type) and dataclasses.is_dataclass(_cls):
        kind = getattr(_cls, "KIND", None)
        if isinstance(kind, str) and kind:
            _KINDS[kind] = _cls

_hints_cache: Dict[type, Dict[str, Any]] = {}


def kind_class(kind: str) -> type:
    cls = _KINDS.get(kind)
    if cls is None:
        raise KeyError(f"unknown kind {kind!r}")
    return cls


def to_wire(obj: Any) -> Any:
    """Dataclass tree -> JSON-safe structure (no type tags needed: the
    receiver hydrates against the declared field types)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: to_wire(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    return obj


def envelope(obj: Any) -> dict:
    """{kind, object} wrapper for transport."""
    kind = getattr(obj, "KIND", None) or type(obj).__name__
    return {"kind": kind, "object": to_wire(obj)}


def from_envelope(data: dict) -> Any:
    return from_wire(kind_class(data["kind"]), data["object"])


def _hints(cls: type) -> Dict[str, Any]:
    h = _hints_cache.get(cls)
    if h is None:
        h = _hints_cache[cls] = get_type_hints(cls)
    return h


def from_wire(cls: type, data: Optional[dict]) -> Any:
    """Hydrate a dataclass tree from its wire form, using field type
    hints; unknown fields are ignored (forward compatibility)."""
    if data is None:
        return None
    hints = _hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        kwargs[f.name] = _hydrate(hints.get(f.name, Any), data[f.name])
    return cls(**kwargs)


def _hydrate(hint: Any, raw: Any) -> Any:
    if raw is None:
        return None
    origin = typing.get_origin(hint)
    if origin is typing.Union:  # Optional[X] and friends
        for arg in typing.get_args(hint):
            if arg is type(None):
                continue
            return _hydrate(arg, raw)
        return raw
    if origin in (list, tuple):
        (arg,) = typing.get_args(hint) or (Any,)
        return [_hydrate(arg, v) for v in raw]
    if origin is dict:
        args = typing.get_args(hint)
        varg = args[1] if len(args) == 2 else Any
        return {k: _hydrate(varg, v) for k, v in raw.items()}
    if isinstance(hint, type) and dataclasses.is_dataclass(hint):
        return from_wire(hint, raw)
    return raw
