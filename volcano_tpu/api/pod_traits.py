"""Scheduler-relevant pod traits: predicate signature key + host-port /
pod-affinity flags, cached per pod version.

Pods stamped from one template share node-selector / affinity / toleration
constraints, so static feasibility collapses to one row per *signature*
(S << T) — the compression both the TPU encoder (ops/encoder.py) and the
cache's columnar pod table (scheduler/cache/podtable.py) build on. The
reference evaluates these per (pod, node) in closures
(pkg/scheduler/plugins/predicates/predicates.go:165-299); here the per-pod
part is computed once per pod *version* and keyed for dedup.
"""

from __future__ import annotations

from typing import Optional

from volcano_tpu.api import objects


def signature_key(pod: Optional[objects.Pod]) -> str:
    if pod is None:
        return "<none>"
    spec = pod.spec
    if not spec.node_selector and spec.affinity is None and not spec.tolerations:
        return "<plain>"
    parts = [repr(sorted(spec.node_selector.items()))]
    aff = spec.affinity
    if aff is not None and aff.node_affinity is not None:
        parts.append(repr([_term_repr(t) for t in aff.node_affinity.required_terms]))
        parts.append(
            repr([(p.weight, _term_repr(p.preference)) for p in aff.node_affinity.preferred_terms])
        )
    parts.append(repr([(t.key, t.operator, t.value, t.effect) for t in spec.tolerations]))
    return "|".join(parts)


def _term_repr(term) -> str:
    return repr(getattr(term, "match_expressions", term))


def has_pod_affinity(pod: Optional[objects.Pod]) -> bool:
    if pod is None or pod.spec.affinity is None:
        return False
    a = pod.spec.affinity
    return a.pod_affinity is not None or a.pod_anti_affinity is not None


def has_host_ports(pod: Optional[objects.Pod]) -> bool:
    if pod is None:
        return False
    # plain loops: this runs per fresh pod in hot paths and a genexpr-under-
    # any costs ~3x the common no-ports case
    for c in pod.spec.containers:
        for p in c.ports:
            if p.host_port > 0:
                return True
    return False


def pod_encode_traits(pod: objects.Pod):
    """(signature key, has_host_ports, has_pod_affinity), cached on the pod.

    Pod objects persist across sessions (snapshot clones TaskInfos but
    shares the pod reference), so caching amortizes the per-task
    string/scan work to one computation per pod *version*: the store bumps
    metadata.resource_version on every create/update (store.py:121-136),
    including in-place mutations re-stored by effectors, so the cache is
    keyed on it and recomputes whenever the pod changed."""
    rv = pod.metadata.resource_version
    try:
        cached_rv, traits = pod._enc_traits
        if cached_rv == rv:
            return traits
    except AttributeError:
        pass
    traits = (
        signature_key(pod),
        has_host_ports(pod),
        has_pod_affinity(pod),
    )
    pod._enc_traits = (rv, traits)
    return traits
