"""Scheduler-facing data model (the analog of volcano pkg/scheduler/api +
pkg/apis): typed objects, resource arithmetic, and the in-memory infos the
session operates on."""

from volcano_tpu.api.quantity import parse_quantity, milli_value
from volcano_tpu.api.resource import Resource, GPU_RESOURCE_NAME
from volcano_tpu.api.types import (
    TaskStatus,
    NodePhase,
    ValidateResult,
    allocated_status,
)
from volcano_tpu.api.objects import (
    ObjectMeta,
    Container,
    PodSpec,
    PodStatus,
    Pod,
    Toleration,
    Taint,
    NodeSpec,
    NodeStatus,
    Node,
    PodGroupSpec,
    PodGroupStatus,
    PodGroupCondition,
    PodGroup,
    PodGroupPhase,
    QueueSpec,
    QueueStatus,
    Queue,
    Command,
    GROUP_NAME_ANNOTATION_KEY,
    POD_PHASE_PENDING,
    POD_PHASE_RUNNING,
    POD_PHASE_SUCCEEDED,
    POD_PHASE_FAILED,
    POD_PHASE_UNKNOWN,
)
from volcano_tpu.api.job_info import TaskInfo, JobInfo, new_task_info
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.queue_info import QueueInfo
from volcano_tpu.api.namespace_info import NamespaceInfo, NamespaceCollection
from volcano_tpu.api.cluster_info import ClusterInfo
from volcano_tpu.api.unschedule_info import FitError, FitErrors, FitFailure
from volcano_tpu.api.pod_helpers import (
    pod_key,
    get_pod_resource_request,
    get_pod_resource_without_init_containers,
)
