"""Per-node failure-reason bookkeeping for events and conditions
(volcano pkg/scheduler/api/unschedule_info.go)."""

from __future__ import annotations

from typing import Dict, List

ALL_NODE_UNAVAILABLE = "all nodes are unavailable"

# (unschedule_info.go:14-15)
NODE_RESOURCE_FIT_FAILED = "node(s) resource fit failed"


class FitFailure(Exception):
    """Raised by predicate fns when a task cannot fit a node; carries the
    failure reasons (the error-return analog of api.PredicateFn)."""

    def __init__(self, *reasons: str):
        super().__init__(", ".join(reasons))
        self.reasons = list(reasons)

    def fit_error(self, task, node) -> "FitError":
        return FitError(task, node, *self.reasons)


class FitError:
    """Why one task failed to fit on one node (unschedule_info.go:82)."""

    __slots__ = ("task_namespace", "task_name", "node_name", "reasons")

    def __init__(self, task, node, *reasons: str):
        self.task_namespace = task.namespace
        self.task_name = task.name
        self.node_name = node.name
        self.reasons: List[str] = list(reasons)

    def error(self) -> str:
        return (
            f"task {self.task_namespace}/{self.task_name} on node "
            f"{self.node_name} fit failed: {', '.join(self.reasons)}"
        )

    def __repr__(self) -> str:
        return self.error()


class FitErrors:
    """Histogram of failure reasons across nodes for one task
    (unschedule_info.go:22)."""

    def __init__(self):
        self.nodes: Dict[str, FitError] = {}
        self.err: str = ""

    def set_error(self, err: str) -> None:
        self.err = err

    def set_node_error(self, node_name: str, fit_error: FitError) -> None:
        self.nodes[node_name] = fit_error

    def error(self) -> str:
        """"<err>: <lexically-sorted '<count> <reason>' histogram>." —
        matching the reference format exactly (unschedule_info.go Error) so
        parity oracles can compare events/conditions byte-for-byte."""
        reasons: Dict[str, int] = {}
        for fe in self.nodes.values():
            for reason in fe.reasons:
                reasons[reason] = reasons.get(reason, 0) + 1
        prefix = self.err if self.err else ALL_NODE_UNAVAILABLE
        parts = sorted(f"{count} {reason}" for reason, count in reasons.items())
        return f"{prefix}: {', '.join(parts)}."
