"""vclint v3 — abstract interpretation over the device kernels.

vclint v1/v2 check the HOST program (purity, bucket shapes, locks,
mutation->invalidation effects). Nothing statically checked the kernels'
numerics, yet every number in the real-TPU campaign rides on int32 packed
op logs, milli-scaled accumulators, and mesh-padded axes: a silent int32
overflow or a pad row leaking into a cross-row reduce corrupts binds
without failing a single CPU-proxy parity test (PR 10 had to rewrite
``_window`` by hand for exactly that reason). This module turns those two
bug classes — plus donated-buffer lifetimes — into machine-checked rules
by running a small abstract interpreter over each kernel function.

Abstract domain (per value)
---------------------------
- ``[lo, hi]``     integer value range, seeded from the bucket-ladder
                   worst case (cfg7: 100k tasks x 50k nodes, padded to the
                   8-device mesh multiple; see ``EXTENTS``);
- ``kind``         ``pyint`` (host int, arbitrary precision — never
                   overflows), ``i32``/``i64``/``bool``/``float``/``obj``;
- ``taint``        pad-slot lattice CLEAN < GUARD < PAD. Rows past
                   ``node_real``/``real_n`` are PAD until masked; ``real``
                   masks (any ``*_real`` name) and ``real_n`` comparisons
                   are GUARD; ``PAD & GUARD``, ``PAD * GUARD`` and
                   ``where(GUARD, ..)`` sanitize;
- ``axis``         worst-case extent of the leading (pad) axis;
- ``total``        bound on the SUM over the pad axis for non-negative
                   arrays (an indicator array has total <= axis even
                   though ``hi * axis`` would be quadratic) — this is what
                   keeps the sanctioned scatter+cumsum window idiom from
                   flagging.

Transfer functions: add/mul widen ranges; cumsum/sum multiply by the axis
extent (or use ``total``); ``top_k``/gather/scatter propagate taint;
``lax.cond`` joins branch states; loop results are TOP. Recognized
overflow mitigations: ``.astype(jnp.int64)`` widening, ``& 0x7FFF``
masks, ``jnp.minimum``/``clip`` clamps, saturating
``lax.associative_scan(lambda a, b: minimum(a+b, cap), ..)``, and the
two-15-bit-limb tuple scan (``_seg_limbs``).

Rules
-----
- **VT010** int32 overflow: an ``i32`` value whose DERIVED range at the
  maximal bucket shapes exceeds 2^31-1. Blessed by a machine-checked
  ``# vclint: headroom(<arith over EXTENTS names>)`` proof on the line
  (or the line above) whose value must stay < 2^31 — an invalid, empty
  or failing proof is itself a finding.
- **VT011** pad taint: a PAD value reaching an unmasked cross-row reduce
  (cumsum/sum/argmax/argsort/top_k/max/min/any/all over the pad axis) or
  the packed D2H tail (``jnp.concatenate``).
- **VT012** donation lifetime: a read through an ALIAS of a donated
  buffer after its dispatch (generalizes VT006's decorator-lexical check
  to dataflow: ``x = carry``/``x = carry["k"]``/ternary aliases die with
  the root; rebinding from the dispatch result revives only the rebound
  name).

Soundness caveats (documented, deliberate)
------------------------------------------
- Under-approximating on ranges: unknown values are TOP and NEVER flag —
  only derivations from seeded bounds fire, so absent seeds mean silence,
  not noise. Loop-carried accumulators (fori/while/scan results) are TOP.
- The analysis is intra-procedural: results of local helper calls are
  TOP/CLEAN; a pad leak laundered through a helper boundary is caught
  when the helper itself is analyzed (it sees its own params seeded).
- Name-based seeding: the pad axis is recognized via the repo's naming
  contract (``real``/``node_real``/``real_n``/``vic_*``/``node_*``); a
  function is pad-aware iff it touches a guard name.
- The headroom bless checks the ARITHMETIC of the claimed bound, not its
  correspondence to the code — that obligation stays with the reviewer,
  like ``neutral(...)`` for VT007.
- VT012 alias tracking is name-versioned like VT006: an alias taken
  BEFORE a donate-then-rebind of its root is not tracked across the
  rebind.

Summaries are memoized per (path, content-hash) so repeated analysis of
an unchanged file (rule pairs sharing one interpretation, warm lint runs
in one process) is a dict hit.
"""

from __future__ import annotations

import ast
import hashlib
import io
import re
import tokenize
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Sequence, Set, Tuple

from volcano_tpu.analysis.core import Finding, Rule, register_rule
from volcano_tpu.analysis.rules import DonatedBufferReuse, dotted

INF = float("inf")
INT32_MAX = 2 ** 31 - 1

# Canonical worst-case extents: cfg7 (100k tasks x 50k nodes — 2x the
# paper's 50k x 10k target) on an 8-device mesh, through the bucket
# ladder (ops/solver.py _bucket: 16, then doubling powers of two).
EXTENTS: Dict[str, int] = {
    "TASKS": 100_000,        # live tasks, cfg7
    "NODES": 50_000,         # real nodes, cfg7
    "MESH_DEV": 8,           # devices in the mesh
    "NODES_PAD": 50_048,     # node axis padded to the mesh multiple
    "TB": 131_072,           # _bucket(100_000) — task/job/queue bucket
    "V_WIDTH": 131_072,      # victim bucket: no per-node cap, <= _bucket(tasks)
    "LOG_ROWS": 262_144,     # packed op-log rows
    "INT32_MAX": INT32_MAX,
}

_TASKS = EXTENTS["TASKS"]
_NODES = EXTENTS["NODES"]
_NP = EXTENTS["NODES_PAD"]
_TB = EXTENTS["TB"]
_VW = EXTENTS["V_WIDTH"]
_LOG = EXTENTS["LOG_ROWS"]
_AXIS_DEFAULT = _TB          # largest ladder bucket: unknown reduce extent

CLEAN, GUARD, PAD = 0, 1, 2


# ---------------------------------------------------------------------------
# abstract values
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AbsVal:
    lo: float = -INF
    hi: float = INF
    kind: str = "obj"               # pyint | i32 | i64 | bool | float | obj
    taint: int = CLEAN
    axis: Optional[int] = None      # leading (pad) axis extent
    axis1: Optional[int] = None     # second-axis extent (vic_* tables)
    total: Optional[float] = None   # bound on sum over the pad axis
    chain: Tuple[str, ...] = ()     # provenance, for --explain

    @property
    def known(self) -> bool:
        return self.hi < INF and self.lo > -INF


TOP = AbsVal()

_INT_KINDS = ("pyint", "i32", "i64", "bool")


def _const(v: int, kind: str = "pyint") -> AbsVal:
    return AbsVal(v, v, kind)


def _tmax(a: int, b: int) -> int:
    """Taint join for plain data flow: PAD dominates, then GUARD."""
    return max(a, b)


def _sanitize(a: int, b: int) -> int:
    """Taint for '&' / '*' / where(GUARD,..): a guard masks a pad."""
    if {a, b} >= {PAD, GUARD}:
        return CLEAN
    return max(a, b)


def _kind_join(a: str, b: str) -> str:
    for k in ("obj", "float", "i64"):
        if k in (a, b):
            return k
    if a == b == "pyint":
        return "pyint"
    return "i32"


def _join(a: AbsVal, b: AbsVal) -> AbsVal:
    return AbsVal(
        min(a.lo, b.lo), max(a.hi, b.hi), _kind_join(a.kind, b.kind),
        _tmax(a.taint, b.taint),
        a.axis if a.axis == b.axis else (a.axis or b.axis),
        a.axis1 if a.axis1 == b.axis1 else (a.axis1 or b.axis1),
        None if (a.total is None or b.total is None)
        else max(a.total, b.total),
        (a.chain or b.chain)[:6])


def _chain(v: AbsVal, entry: str) -> Tuple[str, ...]:
    c = v.chain + (entry,)
    if len(c) > 6:
        c = c[:2] + c[-4:]
    return c


def _src(node: ast.AST, limit: int = 56) -> str:
    try:
        s = ast.unparse(node)
    except Exception:
        s = type(node).__name__
    s = " ".join(s.split())
    return s if len(s) <= limit else s[:limit - 2] + ".."


# ---------------------------------------------------------------------------
# headroom bless grammar: # vclint: headroom(<arith over EXTENTS names>)
# ---------------------------------------------------------------------------

_HEADROOM_RE = re.compile(r"vclint:\s*headroom\(([^()]*)\)")


def headroom_lines(src: str) -> Dict[int, str]:
    """line -> proof expression, from comments only (tokenizer-based, so
    a 'headroom(' inside a string can never bless anything)."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _HEADROOM_RE.search(tok.string)
            if m is not None:
                out[tok.start[0]] = m.group(1).strip()
    except tokenize.TokenError:
        pass
    return out


def eval_headroom(expr: str):
    """(ok, value_or_reason). The proof must be closed arithmetic over
    EXTENTS names (+ - * // % and min/max) evaluating below 2^31."""
    if not expr:
        return False, "empty proof — write headroom(<bound arithmetic>)"
    try:
        tree = ast.parse(expr, mode="eval")
    except SyntaxError:
        return False, f"unparseable proof {expr!r}"

    def ev(n):
        if isinstance(n, ast.Expression):
            return ev(n.body)
        if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                and not isinstance(n.value, bool):
            return n.value
        if isinstance(n, ast.Name):
            if n.id in EXTENTS:
                return EXTENTS[n.id]
            raise ValueError(f"unknown name {n.id!r} "
                             f"(allowed: {', '.join(sorted(EXTENTS))})")
        if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.USub):
            return -ev(n.operand)
        if isinstance(n, ast.BinOp):
            l, r = ev(n.left), ev(n.right)
            if isinstance(n.op, ast.Add):
                return l + r
            if isinstance(n.op, ast.Sub):
                return l - r
            if isinstance(n.op, ast.Mult):
                return l * r
            if isinstance(n.op, ast.FloorDiv):
                return l // r
            if isinstance(n.op, ast.Mod):
                return l % r
            raise ValueError("only + - * // % arithmetic is allowed")
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id in ("min", "max") and not n.keywords:
            vals = [ev(a) for a in n.args]
            return min(vals) if n.func.id == "min" else max(vals)
        raise ValueError(f"disallowed syntax {type(n).__name__}")

    try:
        val = ev(tree)
    except (ValueError, ZeroDivisionError) as e:
        return False, str(e)
    if not isinstance(val, int):
        return False, f"proof is not an integer: {val!r}"
    if val > INT32_MAX:
        return False, (f"proof evaluates to {val} > 2**31-1 — the bound "
                       f"does not fit int32")
    return True, val


# ---------------------------------------------------------------------------
# seeding: the repo's naming contract -> worst-case abstract values
# ---------------------------------------------------------------------------

_SCALAR_SEEDS: Dict[str, Tuple[int, int, str]] = {
    "rr": (0, _NP - 1, "round-robin cursor < NODES_PAD"),
    "node": (0, _NP - 1, "node index < NODES_PAD"),
    "slot": (0, _VW - 1, "victim slot < V_WIDTH"),
    "t": (0, _TB - 1, "task index < TB"),
    "j": (0, _TB - 1, "job index < TB"),
    "q": (0, _TB - 1, "queue index < TB"),
    "num_to_find": (0, _NODES, "window width <= NODES"),
    "t_cap": (0, _TASKS, "per-step task cap <= TASKS"),
    "n_rounds": (0, _TASKS, "round counter <= TASKS"),
    "log_len": (0, _LOG, "op-log cursor <= LOG_ROWS"),
    "kind": (0, 7, "op-log kind tag"),
}

_BOOL_ARRAYS = frozenset((
    "elig", "mask", "ok", "valid", "alive", "sel", "fit", "cand", "vm",
    "win", "live", "claim", "vic_valid", "dirty", "gang_valid",
))

# per-node counters WITHOUT mass conservation (per-node caps): cumsum is
# genuinely quadratic, so no `total` bound
_CAP_ARRAYS = frozenset(("maxt", "node_maxt", "node_max_tasks"))

# per-node counters WITH mass conservation (each task counted once):
# total <= TASKS even though per-element hi is TASKS
_COUNT_ARRAYS = frozenset(("cnt", "node_cnt", "counts"))

_VIC_IDX_ARRAYS = frozenset(("vic_job", "vic_queue", "vic_task"))

# node-axis float payloads: rows past node_real hold stale/garbage values
_NODE_FLOAT_ARRAYS = frozenset((
    "used", "idle", "alloc", "node_used", "node_idle", "node_alloc",
    "sig_mask",
))


def _seed(name: str, pad_aware: bool) -> AbsVal:
    if name == "real_n" or name.endswith("_real_n"):
        return AbsVal(1, _NODES, "i32", GUARD,
                      chain=(f"{name}: real row count in [1, NODES]",))
    if name in ("real", "node_real") or name.endswith("_real"):
        return AbsVal(0, 1, "bool", GUARD, _NP, None, _NODES,
                      (f"{name}: validity mask (guard, <= NODES ones)",))
    if name in _SCALAR_SEEDS:
        lo, hi, why = _SCALAR_SEEDS[name]
        return AbsVal(lo, hi, "i32", CLEAN,
                      chain=(f"{name}: seeded [{lo}, {hi}] ({why})",))
    t = PAD if pad_aware else CLEAN
    if name in _BOOL_ARRAYS:
        return AbsVal(0, 1, "bool", t, _NP, None, _NP,
                      (f"{name}: node-axis mask (rows past node_real "
                       f"are pad)",))
    if name in _CAP_ARRAYS:
        return AbsVal(0, _TASKS, "i32", t, _NP, None, None,
                      (f"{name}: per-node cap <= TASKS, no mass bound",))
    if name in _COUNT_ARRAYS:
        return AbsVal(0, _TASKS, "i32", t, _NP, None, _TASKS,
                      (f"{name}: per-node count, sum <= TASKS",))
    if name in _VIC_IDX_ARRAYS:
        return AbsVal(0, _TB - 1, "i32", t, _NP, _VW, None,
                      (f"{name}: victim table [NODES_PAD, V_WIDTH]",))
    if name == "vic_req":
        return AbsVal(-INF, INF, "float", t, _NP, _VW, None,
                      (f"{name}: victim requests (float)",))
    if name in _NODE_FLOAT_ARRAYS:
        return AbsVal(-INF, INF, "float", t, _NP, None, None,
                      (f"{name}: node-axis payload (rows past node_real "
                       f"are pad)",))
    if name == "log":
        return AbsVal(-INF, INF, "i32", CLEAN, _LOG, 3, None,
                      (f"{name}: packed op log [LOG_ROWS, 3]",))
    # unknown: TOP and CLEAN — the analysis under-approximates, so an
    # unrecognized name means silence, never noise (see module docstring)
    return AbsVal(chain=(f"{name}: unknown (top)",))


_GUARD_KEYS = ("real", "node_real", "real_n")


def _pad_aware(fn: ast.AST) -> bool:
    """A function is pad-aware iff it touches the node-validity contract:
    a guard param name or a guard dict key anywhere in its body."""
    args = getattr(fn, "args", None)
    if args is not None:
        names = [a.arg for a in args.args + args.kwonlyargs + args.posonlyargs]
        if any(n in _GUARD_KEYS or n.endswith("_real") for n in names):
            return True
    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript) \
                and isinstance(node.slice, ast.Constant) \
                and node.slice.value in _GUARD_KEYS:
            return True
    return False


# ---------------------------------------------------------------------------
# events + module summary
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AbsEvent:
    rule: str                 # VT010 | VT011 | VT012 | bless
    line: int
    col: int
    msg: str
    fn: str = ""
    detail: Tuple[str, ...] = ()


_SUMMARY_CACHE: Dict[str, Tuple[str, Tuple[AbsEvent, ...]]] = {}


def summarize(tree: ast.AST, src: str, path: str) -> Tuple[AbsEvent, ...]:
    """Abstract summary of one module, memoized by content hash."""
    key = hashlib.sha256(src.encode("utf-8", "replace")).hexdigest()
    hit = _SUMMARY_CACHE.get(path)
    if hit is not None and hit[0] == key:
        return hit[1]
    events = tuple(_ModuleInterp(tree, src, path).run())
    _SUMMARY_CACHE[path] = (key, events)
    return events


# reduce-style callables: name -> is_accumulating (VT010 surface)
_REDUCES = {
    "cumsum": True, "sum": True, "nansum": True, "cumprod": True,
    "max": False, "min": False, "amax": False, "amin": False,
    "argmax": False, "argmin": False, "argsort": False, "lexsort": False,
    "sort": False, "top_k": False, "any": False, "all": False,
    "cummax": False, "nanargmax": False, "median": False,
}

_PASSTHROUGH = frozenset((
    "roll", "flip", "asarray", "array", "abs", "ravel", "reshape",
    "broadcast_to", "stop_gradient", "squeeze", "expand_dims", "copy",
    "transpose", "sign", "tile", "repeat", "mod", "remainder",
))

_DTYPE_KINDS = (
    ("int64", "i64"), ("int32", "i32"), ("int16", "i32"), ("int8", "i32"),
    ("uint32", "i32"), ("float64", "float"), ("float32", "float"),
    ("bfloat16", "float"), ("float16", "float"), ("bool_", "bool"),
    ("bool", "bool"),
)


def _dtype_kind(node: Optional[ast.AST]) -> Optional[str]:
    name = dotted(node) if node is not None else None
    if not name:
        return None
    leaf = name.split(".")[-1]
    for suffix, kind in _DTYPE_KINDS:
        if leaf == suffix:
            return kind
    return None


class _ModuleInterp:
    """Drives one _FnInterp per function (methods and nested defs get
    their own scope, closing over the enclosing abstract env)."""

    def __init__(self, tree: ast.AST, src: str, path: str):
        self.tree = tree
        self.path = path
        self.headroom = headroom_lines(src)
        self.events: List[AbsEvent] = []
        self.flagged: Set[Tuple[str, int]] = set()
        self.consts: Dict[str, AbsVal] = {}

    def run(self) -> List[AbsEvent]:
        body = getattr(self.tree, "body", [])
        for stmt in body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                try:
                    val = ast.literal_eval(stmt.value)
                except (ValueError, SyntaxError, TypeError):
                    continue
                if isinstance(val, int) and not isinstance(val, bool):
                    self.consts[stmt.targets[0].id] = _const(val)
        for stmt in body:
            self._walk_defs(stmt, {})
        return self.events

    def _walk_defs(self, stmt: ast.AST, closure: Dict[str, AbsVal]):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FnInterp(self, stmt, dict(closure)).run()
        elif isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                self._walk_defs(sub, closure)

    def emit(self, rule: str, node: ast.AST, msg: str, fn: str,
             detail: Sequence[str] = ()):
        if (rule, node.lineno) in self.flagged:
            return
        self.flagged.add((rule, node.lineno))
        self.events.append(AbsEvent(rule, node.lineno, node.col_offset,
                                    msg, fn, tuple(detail)))

    def headroom_at(self, line: int) -> Optional[str]:
        if line in self.headroom:
            return self.headroom[line]
        return self.headroom.get(line - 1)


class _FnInterp:
    """Abstract interpretation of one function body."""

    def __init__(self, ow: _ModuleInterp, fn, closure: Dict[str, AbsVal],
                 pad_parent: bool = False):
        self.ow = ow
        self.fn = fn
        self.pad = pad_parent or _pad_aware(fn)
        # widest safe i32 bound derived in this body (explain-only)
        self.peak: Optional[Tuple[float, int, Tuple[str, ...]]] = None
        self.env: Dict[str, AbsVal] = dict(ow.consts)
        self.env.update(closure)
        a = fn.args
        for arg in a.posonlyargs + a.args + a.kwonlyargs:
            self.env[arg.arg] = _seed(arg.arg, self.pad)
        if a.vararg:
            self.env[a.vararg.arg] = TOP
        if a.kwarg:
            self.env[a.kwarg.arg] = TOP

    # ---- statements ------------------------------------------------------

    def run(self):
        self.exec_block(self.fn.body)
        if self.peak is not None:
            bound, line, chain = self.peak
            self.ow.events.append(AbsEvent(
                "range", line, 0,
                f"widest i32 bound {bound:.4g} "
                f"(headroom {INT32_MAX / max(bound, 1):.1f}x)",
                self.fn.name, chain))

    def exec_block(self, stmts):
        for s in stmts:
            self.exec_stmt(s)

    def exec_stmt(self, s):
        if isinstance(s, ast.Assign):
            val = self.ev(s.value)
            for t in s.targets:
                self.assign(t, val, s.value)
        elif isinstance(s, ast.AugAssign):
            synth = ast.BinOp(left=ast.Name(id=getattr(s.target, "id", "_"),
                                            ctx=ast.Load()),
                              op=s.op, right=s.value)
            ast.copy_location(synth, s)
            ast.fix_missing_locations(synth)
            if isinstance(s.target, ast.Name):
                self.env[s.target.id] = self.ev(synth)
            else:
                self.ev(s.value)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                val = self.ev(s.value)
                self.assign(s.target, val, s.value)
        elif isinstance(s, (ast.Expr, ast.Return)):
            if s.value is not None:
                self.ev(s.value)
        elif isinstance(s, ast.If):
            self.ev(s.test)
            saved = dict(self.env)
            self.exec_block(s.body)
            then_env = self.env
            self.env = saved
            self.exec_block(s.orelse)
            self.env = self._join_envs(then_env, self.env)
        elif isinstance(s, ast.For):
            it = self.ev(s.iter)
            if isinstance(s.target, ast.Name):
                rng = self._range_of(s.iter)
                self.env[s.target.id] = rng if rng is not None else \
                    replace(it, axis=None, total=None)
            self.exec_block(s.body)
            self.exec_block(s.orelse)
        elif isinstance(s, ast.While):
            self.ev(s.test)
            self.exec_block(s.body)
            self.exec_block(s.orelse)
        elif isinstance(s, ast.With):
            for item in s.items:
                self.ev(item.context_expr)
            self.exec_block(s.body)
        elif isinstance(s, ast.Try):
            self.exec_block(s.body)
            for h in s.handlers:
                self.exec_block(h.body)
            self.exec_block(s.orelse)
            self.exec_block(s.finalbody)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FnInterp(self.ow, s, dict(self.env), self.pad).run()
        elif isinstance(s, (ast.Assert, ast.Raise)):
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self.ev(child)
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    self.env.pop(t.id, None)

    def assign(self, target, val: AbsVal, rhs):
        if isinstance(target, ast.Name):
            self.env[target.id] = replace(
                val, chain=_chain(val, f"{target.id} = {_src(rhs)}"))
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(rhs, (ast.Tuple, ast.List)) \
                    and len(rhs.elts) == len(target.elts):
                for t, r in zip(target.elts, rhs.elts):
                    self.assign(t, self.ev(r), r)
            else:
                for t in target.elts:
                    if isinstance(t, ast.Name):
                        self.env[t.id] = replace(val, chain=val.chain)
        # subscript/attribute stores: no env effect

    @staticmethod
    def _join_envs(a: Dict[str, AbsVal], b: Dict[str, AbsVal]):
        out: Dict[str, AbsVal] = {}
        for k in set(a) | set(b):
            if k in a and k in b:
                out[k] = _join(a[k], b[k])
            else:
                out[k] = a.get(k, b.get(k, TOP))
        return out

    def _range_of(self, it) -> Optional[AbsVal]:
        if isinstance(it, ast.Call) and dotted(it.func) == "range" \
                and it.args:
            hi = self.ev(it.args[-1])
            if hi.known:
                return AbsVal(0, max(0, hi.hi - 1), "pyint")
        return None

    # ---- expressions -----------------------------------------------------

    def ev(self, node) -> AbsVal:
        handler = getattr(self, "_ev_" + type(node).__name__, None)
        if handler is not None:
            return handler(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.ev(child)
        return TOP

    def _ev_Constant(self, node) -> AbsVal:
        v = node.value
        if isinstance(v, bool):
            return AbsVal(int(v), int(v), "bool")
        if isinstance(v, int):
            return _const(v)
        if isinstance(v, float):
            return AbsVal(v, v, "float")
        return TOP

    def _ev_Name(self, node) -> AbsVal:
        return self.env.get(node.id, TOP)

    def _ev_Tuple(self, node) -> AbsVal:
        vals = [self.ev(e) for e in node.elts]
        out = TOP
        for v in vals:
            out = _join(out, v) if out is not TOP else v
        return out if vals else TOP

    _ev_List = _ev_Tuple

    def _ev_Attribute(self, node) -> AbsVal:
        base = self.ev(node.value)
        if node.attr == "T":
            return replace(base, axis=base.axis1, axis1=base.axis)
        return TOP

    def _ev_Subscript(self, node) -> AbsVal:
        # x.shape[k] -> static extent
        if isinstance(node.value, ast.Attribute) \
                and node.value.attr == "shape":
            base = self.ev(node.value.value)
            k = node.slice.value if isinstance(node.slice, ast.Constant) \
                else None
            ext = {0: base.axis, 1: base.axis1}.get(k)
            if ext is not None:
                return AbsVal(ext, ext, "pyint",
                              chain=_chain(base, f"shape[{k}] = {ext}"))
            return AbsVal(1, _AXIS_DEFAULT, "pyint")
        # dict read by string key: seed by the repo naming contract
        if isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str):
            self.ev(node.value)
            v = _seed(node.slice.value, self.pad)
            return replace(v, chain=_chain(v, f"{_src(node)}"))
        # gather: drops the leading axis, propagates taint
        base = self.ev(node.value)
        idx = self.ev(node.slice)
        return AbsVal(base.lo, base.hi, base.kind,
                      _tmax(base.taint, idx.taint), base.axis1, None, None,
                      _chain(base, f"gather {_src(node)}"))

    def _ev_UnaryOp(self, node) -> AbsVal:
        v = self.ev(node.operand)
        if isinstance(node.op, ast.USub):
            return replace(v, lo=-v.hi, hi=-v.lo, total=None)
        if isinstance(node.op, (ast.Invert, ast.Not)):
            # ~real selects exactly the pad rows: an inverted guard is
            # a pad selector, not a guard
            t = PAD if v.taint == GUARD else v.taint
            return AbsVal(0, 1, "bool", t, v.axis, v.axis1, v.axis,
                          _chain(v, f"~{_src(node.operand, 32)}"))
        return v

    def _ev_BoolOp(self, node) -> AbsVal:
        vals = [self.ev(v) for v in node.values]
        t = CLEAN
        for v in vals:
            t = _sanitize(t, v.taint)
        out = vals[0]
        for v in vals[1:]:
            out = _join(out, v)
        return replace(out, taint=t)

    def _ev_Compare(self, node) -> AbsVal:
        t = CLEAN
        for v in [self.ev(node.left)] + [self.ev(c) for c in
                                         node.comparators]:
            t = _tmax(t, GUARD if v.taint == GUARD else v.taint)
        return AbsVal(0, 1, "bool", t)

    def _ev_IfExp(self, node) -> AbsVal:
        test = self.ev(node.test)
        a, b = self.ev(node.body), self.ev(node.orelse)
        out = _join(a, b)
        if test.taint == GUARD:
            return replace(out, taint=GUARD)
        return replace(out, taint=_tmax(out.taint, test.taint))

    def _ev_BinOp(self, node) -> AbsVal:
        l, r = self.ev(node.left), self.ev(node.right)
        kind = _kind_join(l.kind, r.kind)
        op = node.op
        lo, hi = -INF, INF
        total = None
        taint = _tmax(l.taint, r.taint)
        if isinstance(op, (ast.Add, ast.Sub)):
            if l.known and r.known:
                if isinstance(op, ast.Add):
                    lo, hi = l.lo + r.lo, l.hi + r.hi
                else:
                    lo, hi = l.lo - r.hi, l.hi - r.lo
            if isinstance(op, ast.Add) and l.total is not None \
                    and r.total is not None and l.lo >= 0 and r.lo >= 0:
                total = l.total + r.total
        elif isinstance(op, ast.Mult):
            taint = _sanitize(l.taint, r.taint)
            if l.known and r.known:
                cands = (l.lo * r.lo, l.lo * r.hi, l.hi * r.lo, l.hi * r.hi)
                lo, hi = min(cands), max(cands)
            scal, arr = (l, r) if l.axis is None else (r, l)
            if arr.total is not None and scal.known and scal.lo >= 0 \
                    and arr.lo >= 0:
                total = arr.total * scal.hi
        elif isinstance(op, ast.FloorDiv):
            if l.known and r.known and r.lo >= 1:
                lo, hi = min(l.lo // r.lo, l.lo // r.hi, 0), \
                    max(l.hi // r.lo, 0)
        elif isinstance(op, ast.Mod):
            if r.known and r.lo >= 1:
                lo, hi = 0, r.hi - 1
        elif isinstance(op, ast.BitAnd):
            taint = _sanitize(l.taint, r.taint)
            if kind == "bool":
                lo, hi = 0, 1
                if l.total is not None or r.total is not None:
                    total = min(x for x in (l.total, r.total)
                                if x is not None)
            elif r.known and r.lo >= 0:
                lo, hi = 0, r.hi          # masking: x & 0x7FFF
            elif l.known and l.lo >= 0:
                lo, hi = 0, l.hi
        elif isinstance(op, ast.BitOr):
            if kind == "bool":
                lo, hi = 0, 1
        elif isinstance(op, ast.RShift):
            if l.known and r.known and r.lo >= 0:
                lo, hi = min(l.lo, 0), max(int(l.hi) >> int(r.lo), 0)
        elif isinstance(op, ast.LShift):
            if l.known and r.known:
                lo, hi = min(l.lo, 0), int(l.hi) << int(r.hi)
        elif isinstance(op, (ast.Div, ast.Pow)):
            kind = "float" if isinstance(op, ast.Div) else kind
            if isinstance(op, ast.Pow) and l.known and r.known \
                    and 0 <= r.hi <= 64 and abs(l.hi) <= 2 ** 20:
                hi = max(abs(l.lo), abs(l.hi)) ** r.hi
                lo = 0 if l.lo >= 0 else -hi
        out = AbsVal(lo, hi, kind, taint,
                     l.axis or r.axis, l.axis1 or r.axis1, total)
        if out.known:
            out = replace(out, chain=_chain(
                l if l.chain else r,
                f"L{node.lineno}: {_src(node)} -> [{lo:g}, {hi:g}]"))
        return self._chk32(out, node)

    # ---- overflow check --------------------------------------------------

    def _chk32(self, val: AbsVal, node, what: str = "") -> AbsVal:
        if val.kind != "i32" or val.hi == INF \
                or (val.hi <= INT32_MAX and val.lo >= -INT32_MAX - 1):
            if val.kind == "i32" and val.hi != INF and val.chain \
                    and (self.peak is None
                         or max(val.hi, -val.lo) > self.peak[0]):
                self.peak = (max(val.hi, -val.lo), node.lineno, val.chain)
            return val
        bound = max(val.hi, -val.lo)
        proof = self.ow.headroom_at(node.lineno)
        if proof is not None:
            ok, res = eval_headroom(proof)
            if ok:
                self.ow.events.append(AbsEvent(
                    "bless", node.lineno, node.col_offset,
                    f"headroom({proof}) = {res} < 2**31 — blessed",
                    self.fn.name, val.chain))
                return replace(val, lo=max(val.lo, -res), hi=min(val.hi, res))
            self.ow.emit(
                "VT010", node,
                f"headroom proof rejected: {res} — the int32 range here "
                f"derives to {bound:.4g} at cfg7 extents and the bless "
                f"must prove a bound < 2**31", self.fn.name, val.chain)
            return replace(val, lo=-INF, hi=INF, total=None)
        self.ow.emit(
            "VT010", node,
            f"int32 overflow: {what or _src(node)!r} spans "
            f"[{val.lo:.4g}, {val.hi:.4g}] at cfg7 x mesh extents "
            f"(|range| > 2**31-1); widen to int64, saturate/limb-split, "
            f"or prove '# vclint: headroom(<bound>)'",
            self.fn.name, val.chain)
        return replace(val, lo=-INF, hi=INF, total=None)

    # ---- calls -----------------------------------------------------------

    def _ev_Call(self, node) -> AbsVal:
        f = node.func
        # x.at[idx].add(v) scatter family
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Subscript) \
                and isinstance(f.value.value, ast.Attribute) \
                and f.value.value.attr == "at":
            return self._scatter(node, f.attr, f.value.value.value,
                                 f.value.slice)
        name = dotted(f)
        head = name.split(".")[-1] if name else \
            (f.attr if isinstance(f, ast.Attribute) else None)
        # module-namespace call vs method call on a value
        is_module_call = name is not None and (
            "." not in name or name.split(".")[0] in
            ("jnp", "np", "lax", "jax", "numpy", "jsp"))

        if head == "where" and len(node.args) == 3:
            return self._where(node)
        if head in ("cond", "select") and name and "lax" in name:
            return self._cond(node)
        if head in ("while_loop", "fori_loop", "scan", "switch") \
                and name and ("lax" in name or is_module_call):
            for a in node.args:
                if isinstance(a, ast.Lambda):
                    _FnInterp(self.ow, _lambda_fn(a), dict(self.env),
                              self.pad).run()
                else:
                    self.ev(a)
            return TOP
        if head == "associative_scan":
            return self._assoc_scan(node)
        if head in _REDUCES and (is_module_call
                                 or isinstance(f, ast.Attribute)):
            operand = node.args[0] if is_module_call and node.args else \
                (f.value if isinstance(f, ast.Attribute)
                 and not is_module_call else None)
            if operand is not None:
                return self._reduce(node, head, operand)
        if head == "astype" and isinstance(f, ast.Attribute):
            return self._cast(node, f.value,
                              _dtype_kind(node.args[0]) if node.args
                              else None)
        if head and is_module_call and _dtype_kind(f) and node.args:
            return self._cast(node, node.args[0], _dtype_kind(f))
        if head in ("minimum", "maximum", "clip") and node.args:
            vals = [self.ev(a) for a in node.args]
            for kw in node.keywords:
                self.ev(kw.value)
            out = vals[0]
            if head == "minimum" and len(vals) >= 2:
                out = replace(_join(vals[0], vals[1]),
                              hi=min(vals[0].hi, vals[1].hi),
                              taint=_sanitize(vals[0].taint, vals[1].taint))
            elif head == "maximum" and len(vals) >= 2:
                out = replace(_join(vals[0], vals[1]),
                              lo=max(vals[0].lo, vals[1].lo))
            elif head == "clip" and len(vals) >= 3:
                out = replace(vals[0], lo=max(vals[0].lo, vals[1].lo),
                              hi=min(vals[0].hi, vals[2].hi))
            return replace(out, chain=_chain(vals[0],
                                             f"L{node.lineno}: {head}"))
        if head == "arange" and node.args:
            n = self.ev(node.args[-1])
            if n.known:
                ext = int(n.hi)
                return AbsVal(0, max(ext - 1, 0), "i32", CLEAN, ext,
                              chain=(f"arange({ext})",))
            return AbsVal(0, _AXIS_DEFAULT - 1, "i32", CLEAN, _AXIS_DEFAULT)
        if head in ("zeros", "ones", "full", "zeros_like", "ones_like",
                    "full_like"):
            return self._fill(node, head)
        if head in ("concatenate", "stack", "hstack", "vstack"):
            return self._concat(node, head)
        if head in _PASSTHROUGH and (node.args
                                     or isinstance(f, ast.Attribute)):
            base = node.args[0] if node.args else f.value
            out = self.ev(base)
            for a in node.args[1:]:
                self.ev(a)
            for kw in node.keywords:
                self.ev(kw.value)
            if head in ("reshape", "ravel"):
                out = replace(out, axis1=None)
            return out
        if head in ("take", "take_along_axis", "gather", "dynamic_slice",
                    "dynamic_update_slice") and node.args:
            vals = [self.ev(a) for a in node.args]
            t = CLEAN
            for v in vals:
                t = _tmax(t, v.taint)
            return replace(vals[0], taint=t, total=None)
        if head in ("logical_and", "logical_or") and len(node.args) >= 2:
            a, b = self.ev(node.args[0]), self.ev(node.args[1])
            t = _sanitize(a.taint, b.taint) if head == "logical_and" \
                else _tmax(a.taint, b.taint)
            return replace(_join(a, b), taint=t, kind="bool", lo=0, hi=1)
        # unknown / local helper: evaluate args (nested sinks still fire),
        # result TOP-clean (intra-procedural: the helper is analyzed on
        # its own with seeded params)
        for a in node.args:
            if isinstance(a, ast.Lambda):
                continue
            self.ev(a)
        for kw in node.keywords:
            self.ev(kw.value)
        if isinstance(f, ast.Attribute) and not name:
            self.ev(f.value)
        return TOP

    def _where(self, node) -> AbsVal:
        cond = self.ev(node.args[0])
        a, b = self.ev(node.args[1]), self.ev(node.args[2])
        out = _join(a, b)
        if cond.taint == GUARD:
            taint = GUARD      # pads deliberately parked at the fill value
        elif cond.taint == PAD:
            taint = PAD
        else:
            taint = out.taint
        total = None
        if a.total is not None and b.known and b.lo >= 0 and b.hi == 0:
            total = a.total
        elif b.total is not None and a.known and a.lo >= 0 and a.hi == 0:
            total = b.total
        elif cond.taint == GUARD and cond.total is not None \
                and out.known and out.lo >= 0:
            total = cond.total * out.hi
        return replace(out, taint=taint, total=total,
                       chain=_chain(out, f"L{node.lineno}: where("
                                         f"{_src(node.args[0], 28)}, ..)"))

    def _cond(self, node) -> AbsVal:
        out = None
        for a in node.args:
            if isinstance(a, ast.Lambda):
                v = self.ev(a.body)
                out = v if out is None else _join(out, v)
            else:
                self.ev(a)
        return out if out is not None else TOP

    def _assoc_scan(self, node) -> AbsVal:
        """lax.associative_scan: limb-tuple operand and saturating-minimum
        combiners are recognized mitigations; a plain additive combiner is
        a cumsum."""
        if len(node.args) < 2:
            return TOP
        comb, operand = node.args[0], node.args[1]
        if isinstance(operand, (ast.Tuple, ast.List)):
            for e in operand.elts:
                self.ev(e)
            return AbsVal(-INF, INF, "i32", CLEAN,
                          chain=("limb-tuple associative_scan "
                                 "(carry-normalizing, exact)",))
        x = self.ev(operand)
        if isinstance(comb, ast.Lambda):
            body = comb.body
            if isinstance(body, ast.Call) \
                    and (dotted(body.func) or "").endswith("minimum") \
                    and len(body.args) == 2:
                cap = self.ev(body.args[1])
                hi = cap.hi if cap.known else INF
                return AbsVal(min(x.lo, 0), hi, x.kind, x.taint, x.axis,
                              chain=_chain(x, f"L{node.lineno}: saturating "
                                              f"scan capped at "
                                              f"{_src(body.args[1], 24)}"))
        return self._reduce(node, "cumsum", operand, pre=x)

    def _axis_of(self, node, skip_args: int) -> Optional[object]:
        for kw in node.keywords:
            if kw.arg == "axis":
                if isinstance(kw.value, ast.Constant):
                    return kw.value.value
                return "dyn"
        rest = node.args[skip_args:]
        if rest and isinstance(rest[0], ast.Constant) \
                and isinstance(rest[0].value, int):
            return rest[0].value
        return None

    def _reduce(self, node, head: str, operand, pre=None) -> AbsVal:
        x = pre if pre is not None else self.ev(operand)
        for a in node.args:
            if a is not operand and not isinstance(a, ast.Lambda):
                self.ev(a)
        for kw in node.keywords:
            if kw.arg != "axis":
                self.ev(kw.value)
        skip = 1 if node.args and node.args[0] is operand else \
            (2 if head == "associative_scan" else 0)
        axis = self._axis_of(node, skip)
        over_pad_axis = axis in (None, 0, -1)
        if x.taint == PAD and over_pad_axis:
            self.ow.emit(
                "VT011", node,
                f"pad-tainted value reaches '{head}' without a "
                f"real/real_n guard — rows past node_real contaminate the "
                f"cross-row result; mask with '& node_real' or "
                f"'jnp.where(real, .., fill)' first "
                f"(source: {x.chain[0] if x.chain else 'unknown'})",
                self.fn.name, x.chain)
            x = replace(x, taint=CLEAN)
        elif self.pad and over_pad_axis:
            # explain-only trace: a cross-row reduce in a pad-aware
            # kernel whose operand arrived sanitized
            self.ow.events.append(AbsEvent(
                "reduce", node.lineno, node.col_offset,
                f"'{head}' operand {'guard-masked' if x.taint == GUARD else 'clean'}",
                self.fn.name, x.chain))
        if head in ("argmax", "argmin", "argsort", "lexsort"):
            ext = x.axis or _AXIS_DEFAULT
            return AbsVal(0, ext - 1, "i32", CLEAN, x.axis,
                          chain=_chain(x, f"L{node.lineno}: {head} index"))
        if head in ("any", "all"):
            return AbsVal(0, 1, "bool", x.taint if not over_pad_axis
                          else CLEAN)
        if head in ("max", "min", "amax", "amin", "median", "sort",
                    "cummax", "top_k", "nanargmax"):
            return replace(x, total=None)
        # cumsum/sum family: the accumulation surface
        ext = x.axis or _AXIS_DEFAULT
        if not x.known:
            return AbsVal(-INF, INF, _acc_kind(x.kind), x.taint)
        if x.lo >= 0 and x.total is not None:
            hi, lo = x.total, 0
        else:
            hi = max(x.hi * ext, x.hi)
            lo = min(x.lo * ext, x.lo)
        out = AbsVal(lo, hi, _acc_kind(x.kind), x.taint,
                     x.axis if head.startswith("cum") else None,
                     None, x.total if x.lo >= 0 else None,
                     _chain(x, f"L{node.lineno}: {head} over axis extent "
                               f"{ext} -> [{lo:g}, {hi:g}]"))
        return self._chk32(out, node, what=_src(node))

    def _scatter(self, node, mode: str, base, idx) -> AbsVal:
        b = self.ev(base)
        i = self.ev(idx)
        v = self.ev(node.args[0]) if node.args else TOP
        for a in node.args[1:]:
            self.ev(a)
        taint = b.taint
        if PAD in (v.taint, i.taint):
            taint = PAD
        elif GUARD in (v.taint, i.taint):
            taint = _tmax(taint, GUARD) if taint != PAD else taint
        if mode == "add":
            if v.known and v.lo >= 0 and b.known and b.lo >= 0:
                mass = v.total if v.total is not None else \
                    (v.hi * (v.axis or _AXIS_DEFAULT))
                out = AbsVal(b.lo, b.hi + mass, _acc_kind(
                    _kind_join(b.kind, v.kind)), taint, b.axis, b.axis1,
                    (b.total + mass) if b.total is not None else None,
                    _chain(v, f"L{node.lineno}: scatter-add mass "
                              f"<= {mass:g}"))
                return self._chk32(out, node, what=_src(node))
            return AbsVal(-INF, INF, _acc_kind(_kind_join(b.kind, v.kind)),
                          taint, b.axis, b.axis1)
        if mode in ("set", "max", "min"):
            return replace(_join(b, v), taint=taint, total=None)
        return replace(b, taint=taint, total=None)

    def _fill(self, node, head: str) -> AbsVal:
        """zeros/ones/full(+_like): constant arrays with a static shape."""
        axis = axis1 = None
        if node.args:
            shape = node.args[0]
            if head.endswith("_like"):
                ref = self.ev(shape)
                axis, axis1 = ref.axis, ref.axis1
            elif isinstance(shape, (ast.Tuple, ast.List)) and shape.elts:
                dims = [self.ev(e) for e in shape.elts]
                if dims[0].known:
                    axis = int(dims[0].hi)
                if len(dims) > 1 and dims[1].known:
                    axis1 = int(dims[1].hi)
            else:
                n = self.ev(shape)
                if n.known:
                    axis = int(n.hi)
        kind = "float"
        for kw in node.keywords:
            if kw.arg == "dtype":
                kind = _dtype_kind(kw.value) or "obj"
        for a in node.args[1:]:
            dk = _dtype_kind(a)
            if dk:
                kind = dk
        if head.startswith("zeros"):
            lo = hi = 0
        elif head.startswith("ones"):
            lo = hi = 1
        elif head.startswith("full") and len(node.args) > 1:
            v = self.ev(node.args[1])
            lo, hi = v.lo, v.hi
        else:
            lo, hi = -INF, INF
        total = hi * (axis or 1) if hi != INF and hi >= 0 and lo >= 0 \
            else None
        return AbsVal(lo, hi, kind, CLEAN, axis, axis1, total,
                      (f"L{node.lineno}: {head} fill [{lo:g}, {hi:g}]"
                       if hi != INF else f"L{node.lineno}: {head}",))

    def _concat(self, node, head: str) -> AbsVal:
        """concatenate/stack: the packed D2H tail — a PAD element here is
        a VT011 sink (pad rows ship to the host verbatim)."""
        elts = []
        if node.args and isinstance(node.args[0], (ast.Tuple, ast.List)):
            elts = [self.ev(e) for e in node.args[0].elts]
        elif node.args:
            elts = [self.ev(node.args[0])]
        for a in node.args[1:]:
            self.ev(a)
        out = TOP
        for i, v in enumerate(elts):
            out = v if i == 0 else _join(out, v)
        if head == "concatenate" and self.pad \
                and any(v.taint == PAD for v in elts):
            bad = next(v for v in elts if v.taint == PAD)
            self.ow.emit(
                "VT011", node,
                f"pad-tainted rows reach the packed D2H tail "
                f"(jnp.{head}) unmasked — the host decodes pad garbage; "
                f"park pads with 'jnp.where(real, .., fill)' before "
                f"packing (source: "
                f"{bad.chain[0] if bad.chain else 'unknown'})",
                self.fn.name, bad.chain)
            out = replace(out, taint=CLEAN)
        return replace(out, total=None,
                       chain=_chain(out, f"L{node.lineno}: {head}"))

    def _cast(self, node, operand, kind: Optional[str]) -> AbsVal:
        x = self.ev(operand)
        if kind is None:
            return replace(x, kind="obj")
        if kind == "bool":
            return AbsVal(0, 1, "bool", x.taint, x.axis, x.axis1, x.axis,
                          x.chain)
        out = replace(x, kind=kind,
                      chain=_chain(x, f"L{node.lineno}: cast to {kind}"))
        if kind == "i32":
            return self._chk32(out, node, what=_src(node))
        return out


def _acc_kind(kind: str) -> str:
    if kind in ("bool", "pyint", "i32"):
        return "i32"
    return kind


def _lambda_fn(lam: ast.Lambda) -> ast.FunctionDef:
    fn = ast.FunctionDef(
        name="<lambda>", args=lam.args,
        body=[ast.Return(value=lam.body)], decorator_list=[])
    ast.copy_location(fn, lam)
    ast.fix_missing_locations(fn)
    return fn


# ---------------------------------------------------------------------------
# VT012 — donation lifetimes (may-alias dataflow over VT006's decorators)
# ---------------------------------------------------------------------------


def donation_events(tree: ast.AST) -> List[dict]:
    """Statement-ordered may-alias donation timeline, per function."""
    donating = DonatedBufferReuse._donated_positions(tree)
    if not donating:
        return []
    events: List[dict] = []
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        _DonationFlow(fn.name, donating, events).scan(fn.body)
    return events


class _DonationFlow:
    def __init__(self, fn_name: str, donating, events: List[dict]):
        self.fn = fn_name
        self.donating = donating
        self.events = events
        # buffers are tracked per GENERATION ('carry#0', 'carry#1', ...):
        # rebinding a donated name starts a new live generation, but the
        # old one stays dead — aliases captured before the donation keep
        # pointing at it, so their reads still flag
        self.donated: Dict[str, Tuple[str, int]] = {}
        self.alias: Dict[str, Set[str]] = {}
        self.ver: Dict[str, int] = {}

    def vkey(self, name: str) -> str:
        return f"{name}#{self.ver.get(name, 0)}"

    def scan(self, stmts):
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            for expr in self._value_exprs(s):
                self._scan_expr(expr)
            self._apply_stores(s)
            for body in (getattr(s, "body", None),
                         getattr(s, "orelse", None),
                         getattr(s, "finalbody", None)):
                if isinstance(body, list):
                    self.scan(body)
            for h in getattr(s, "handlers", ()) or ():
                self.scan(h.body)

    @staticmethod
    def _value_exprs(s):
        if isinstance(s, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                          ast.Return, ast.Expr)):
            return [s.value] if s.value is not None else []
        if isinstance(s, (ast.If, ast.While)):
            return [s.test]
        if isinstance(s, ast.For):
            return [s.iter]
        if isinstance(s, ast.With):
            return [i.context_expr for i in s.items]
        return []

    def _scan_expr(self, node):
        # identity checks against None are host metadata, not buffer reads
        if isinstance(node, ast.Compare) \
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops):
            return
        for child in ast.iter_child_nodes(node):
            self._scan_expr(child)
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if self.vkey(node.id) in self.donated:
                return    # direct read of the donated name: VT006 territory
            roots = self.alias.get(node.id, ())
            dead = [r for r in roots if r in self.donated]
            if dead:
                callee, line = self.donated[dead[0]]
                self.events.append(dict(
                    kind="read", fn=self.fn, line=node.lineno,
                    col=node.col_offset, name=node.id,
                    root=dead[0].split("#")[0],
                    callee=callee, donate_line=line))
                self.alias.pop(node.id, None)
        elif isinstance(node, ast.Call):
            callee = (dotted(node.func) or "").split(".")[-1]
            for p in self.donating.get(callee, ()):
                if p >= len(node.args):
                    continue
                for nm in self._arg_names(node.args[p]):
                    kills = {self.vkey(nm)} | self.alias.get(nm, set())
                    for k in kills:
                        self.donated[k] = (callee, node.lineno)
                    self.events.append(dict(
                        kind="donate", fn=self.fn, line=node.lineno,
                        name=nm, callee=callee))

    @staticmethod
    def _arg_names(arg) -> Set[str]:
        if isinstance(arg, ast.Name):
            return {arg.id}
        return {n.id for n in ast.walk(arg)
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}

    def _roots_of(self, rhs) -> Set[str]:
        if isinstance(rhs, ast.Name):
            return self.alias.get(rhs.id, None) or {self.vkey(rhs.id)}
        if isinstance(rhs, ast.IfExp):
            return self._roots_of(rhs.body) | self._roots_of(rhs.orelse)
        if isinstance(rhs, ast.Attribute):
            if rhs.attr in ("shape", "dtype", "ndim", "size"):
                return set()    # host metadata, not a buffer handle
            return self._roots_of(rhs.value)
        if isinstance(rhs, ast.Subscript):
            return self._roots_of(rhs.value)
        if isinstance(rhs, ast.BoolOp):
            out: Set[str] = set()
            for v in rhs.values:
                out |= self._roots_of(v)
            return out
        return set()

    def _apply_stores(self, s):
        if isinstance(s, ast.Assign):
            for t in s.targets:
                self._store(t, s.value)
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            self._store(s.target, None)
        elif isinstance(s, ast.For):
            self._store(s.target, None)

    def _store(self, target, rhs):
        if isinstance(target, (ast.Tuple, ast.List)):
            for t in target.elts:
                self._store(t, None)
            return
        if not isinstance(target, ast.Name):
            return
        name = target.id
        roots = self._roots_of(rhs) if rhs is not None else set()
        if self.vkey(name) in self.donated:
            # new generation: the rebound name is alive again, the dead
            # generation stays recorded for aliases that captured it
            self.events.append(dict(kind="rebind", fn=self.fn,
                                    line=target.lineno, name=name))
            self.ver[name] = self.ver.get(name, 0) + 1
        roots.discard(self.vkey(name))
        if roots:
            self.alias[name] = roots
        else:
            self.alias.pop(name, None)


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------

_KERNEL_SCOPE = ("*/ops/*.py", "*/express/place.py")


class _AbsIntRule(Rule):
    def check(self, tree, src, path):
        return [Finding(self.id, path, e.line, e.col, e.msg)
                for e in summarize(tree, src, path) if e.rule == self.id]


@register_rule
class IntRangeOverflow(_AbsIntRule):
    """int32 value whose derived range at cfg7 x mesh extents exceeds
    2^31-1 (see module docstring; bless grammar: headroom(<proof>))."""

    id = "VT010"
    title = "int32 range overflow at maximal bucket shapes"
    patterns = _KERNEL_SCOPE


@register_rule
class PadTaintLeak(_AbsIntRule):
    """Pad-slot rows reaching an unmasked cross-row reduce / argsort /
    packed D2H tail (the pre-PR-10 _window bug class)."""

    id = "VT011"
    title = "pad rows reach an unmasked cross-row reduce"
    patterns = _KERNEL_SCOPE


@register_rule
class DonationLifetime(Rule):
    """Reads through may-aliases of donated buffers after dispatch —
    the dataflow generalization of VT006's decorator-lexical check."""

    id = "VT012"
    title = "aliased read of a donated buffer after dispatch"
    patterns = DonatedBufferReuse.patterns

    def check(self, tree, src, path):
        out: List[Finding] = []
        for e in donation_events(tree):
            if e["kind"] != "read":
                continue
            out.append(Finding(
                self.id, path, e["line"], e["col"],
                f"'{e['name']}' may alias '{e['root']}', donated to "
                f"device dispatch '{e['callee']}' (line "
                f"{e['donate_line']}); a post-dispatch read dereferences "
                f"freed device memory — rebind from the dispatch result "
                f"or refetch before reuse"))
        return out


# ---------------------------------------------------------------------------
# --explain plumbing
# ---------------------------------------------------------------------------


def explain(rule_id: str, norm_paths) -> int:
    """Print derivation chains (VT010), taint paths (VT011) or donation
    timelines (VT012) over the rule's scope, VT007-explain style."""
    import os

    from volcano_tpu.analysis.core import iter_py_files

    rule = {"VT010": IntRangeOverflow, "VT011": PadTaintLeak,
            "VT012": DonationLifetime}[rule_id]()
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    files = [p for p in iter_py_files([pkg]) if rule.applies_to(p)]
    if norm_paths:
        files = [p for p in files
                 if any(p.replace(os.sep, "/").endswith(n)
                        or n in p.replace(os.sep, "/")
                        for n in norm_paths)]
    for path in sorted(files):
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        rel = path.replace(os.sep, "/")
        idx = rel.find("volcano_tpu/")
        rel = rel[idx:] if idx >= 0 else rel
        if rule_id == "VT012":
            for e in donation_events(tree):
                if e["kind"] == "donate":
                    print(f"{rel}:{e['line']} [{e['fn']}] donate   "
                          f"'{e['name']}' -> {e['callee']} (buffer dead)")
                elif e["kind"] == "rebind":
                    print(f"{rel}:{e['line']} [{e['fn']}] rebind   "
                          f"'{e['name']}' (alive again)")
                else:
                    print(f"{rel}:{e['line']} [{e['fn']}] READ     "
                          f"'{e['name']}' aliasing dead '{e['root']}' "
                          f"(donated at L{e['donate_line']})")
            continue
        for e in summarize(tree, src, path):
            if rule_id == "VT010" and e.rule in ("VT010", "bless", "range"):
                verdict = {"VT010": "OVERFLOW", "bless": "blessed",
                           "range": "checked"}[e.rule]
                print(f"{rel}:{e.line} [{e.fn}] {verdict}: {e.msg}")
                if e.rule != "range":
                    for step in e.detail:
                        print(f"    {step}")
            elif rule_id == "VT011" and e.rule in ("VT011", "reduce"):
                if e.rule == "reduce":
                    print(f"{rel}:{e.line} [{e.fn}] ok: {e.msg}")
                    continue
                print(f"{rel}:{e.line} [{e.fn}] TAINT SINK: {e.msg}")
                for step in e.detail:
                    print(f"    {step}")
    return 0
