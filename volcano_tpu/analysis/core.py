"""vclint core — AST visitor framework, rule registry, suppressions, output.

The repo's latency and correctness story rests on invariants no unit test
can see from the outside: kernel code must never host-sync mid-trace, every
dynamic extent must pass through the pad-to-bucket contract before it can
reach a jit static argument, watch handlers must stay fast and lock-clean,
statements must always close. vclint checks those contracts lexically, on
every tier-1 run, so a violation fails the PR that introduces it instead of
surfacing as a multi-second warm-path stall in a bench three rounds later.

Suppression contract: a finding is silenced by a ``# vclint: disable=VT00X``
comment on the finding line or the line directly above; a
``# vclint: disable-file=VT00X`` comment anywhere silences the rule for the
whole file. Every suppression MUST carry a justification after the rule
list (``# vclint: disable=VT002 - node axis pads to the mesh multiple``);
a bare suppression is itself a finding (VT000), so the gate cannot be
quietly eroded.
"""

from __future__ import annotations

import ast
import fnmatch
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence

_SUPPRESS_RE = re.compile(
    r"vclint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)\s*(.*)",
)


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "suppressed": self.suppressed,
        }


@dataclass
class Suppression:
    line: int
    rules: tuple
    file_level: bool
    justification: str


def parse_suppressions(src: str) -> List[Suppression]:
    """Extract vclint suppression comments via the tokenizer (comments only,
    so a 'vclint:' inside a string literal can never disable a rule)."""
    out: List[Suppression] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m is None:
                continue
            rules = tuple(r.strip() for r in m.group(2).split(","))
            just = m.group(3).strip().lstrip("-—–:. ").strip()
            out.append(Suppression(
                line=tok.start[0], rules=rules,
                file_level=m.group(1) == "disable-file",
                justification=just))
    except tokenize.TokenError:
        pass
    return out


class Rule:
    """A vclint rule: an id, the default path scope, and an AST check.

    ``patterns`` are fnmatch globs applied to '/' + the posix path, so
    ``*/ops/*.py`` matches both absolute and repo-relative spellings.
    """

    id: str = "VT000"
    title: str = ""
    patterns: Sequence[str] = ()

    def applies_to(self, path: str) -> bool:
        posix = "/" + path.replace(os.sep, "/").lstrip("/")
        return any(fnmatch.fnmatch(posix, pat) for pat in self.patterns)

    def check(self, tree: ast.AST, src: str, path: str) -> List[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Rule] = {}


def register_rule(cls):
    """Class decorator: instantiate and add to the global registry."""
    inst = cls()
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> List[Rule]:
    return [_REGISTRY[rid] for rid in sorted(_REGISTRY)]


def get_rule(rule_id: str) -> Rule:
    return _REGISTRY[rule_id]


def analyze_source(
    src: str,
    path: str,
    rules: Optional[Sequence[Rule]] = None,
    respect_filters: bool = True,
    include_meta: bool = True,
) -> List[Finding]:
    """Run ``rules`` over one source blob. Returns ALL findings with
    ``suppressed`` marked; callers filter on it. A syntax error is reported
    as a VT999 finding rather than an exception so one broken file cannot
    mask the rest of a tree scan.

    ``include_meta=False`` drops the per-file meta findings (VT000 bare
    suppressions, VT999 syntax errors) — for callers that split one file's
    rule set across several passes (the incremental lint cache re-runs
    only the whole-program rules on unchanged files) and must not emit
    the meta findings twice."""
    if rules is None:
        rules = all_rules()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        if not include_meta:
            return []
        return [Finding("VT999", path, e.lineno or 0, e.offset or 0,
                        f"syntax error: {e.msg}")]

    findings: List[Finding] = []
    for rule in rules:
        if respect_filters and not rule.applies_to(path):
            continue
        findings.extend(rule.check(tree, src, path))

    sups = parse_suppressions(src)
    # VT000 meta-rule: a suppression without a justification is a finding.
    if include_meta:
        for s in sups:
            if not s.justification:
                findings.append(Finding(
                    "VT000", path, s.line, 0,
                    "suppression without justification — write "
                    "'# vclint: disable=%s - <why this is safe>'"
                    % ",".join(s.rules)))

    file_disabled = set()
    line_disabled: Dict[int, set] = {}
    for s in sups:
        if s.file_level:
            file_disabled.update(s.rules)
        else:
            line_disabled.setdefault(s.line, set()).update(s.rules)
    for f in findings:
        if f.rule in file_disabled \
                or f.rule in line_disabled.get(f.line, ()) \
                or f.rule in line_disabled.get(f.line - 1, ()):
            f.suppressed = True
    return findings


def analyze_file(path: str, rules: Optional[Sequence[Rule]] = None,
                 respect_filters: bool = True) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        src = fh.read()
    return analyze_source(src, path, rules, respect_filters)


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif p.endswith(".py"):
            out.append(p)
    return out


def analyze_paths(paths: Iterable[str],
                  rules: Optional[Sequence[Rule]] = None,
                  respect_filters: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        findings.extend(analyze_file(path, rules, respect_filters))
    return findings


def render(findings: Sequence[Finding], as_json: bool = False,
           show_suppressed: bool = False) -> str:
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    if as_json:
        return json.dumps([f.to_dict() for f in shown], indent=2)
    lines = [f.format() for f in shown]
    active = sum(1 for f in findings if not f.suppressed)
    muted = sum(1 for f in findings if f.suppressed)
    lines.append(
        f"vclint: {active} finding(s), {muted} suppressed"
        if (active or muted) else "vclint: clean")
    return "\n".join(lines)
