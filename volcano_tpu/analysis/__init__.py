"""vclint — AST-based invariant checker for this repo's machine-checked
contracts (kernel purity, bucket shapes, lock discipline, statement
hygiene, hot-path determinism, and the v2 whole-program effect rules:
mutation->invalidation reachability, inferred lock/field maps,
fingerprint completeness — with an opt-in runtime witness shim).

Usage:
    python -m volcano_tpu.analysis volcano_tpu/
    python -m volcano_tpu.analysis --json --select VT003 volcano_tpu/controllers/
    python -m volcano_tpu.analysis --explain VT007 volcano_tpu/express/
    python -m volcano_tpu.analysis --baseline tools/lint_baseline.json volcano_tpu/

Rules live in volcano_tpu/analysis/rules.py; the framework (registry,
suppressions, output) in core.py; rationale per rule in
docs/static-analysis.md. tests/test_static_analysis.py wires the whole rule
set into the tier-1 gate via tools/lint.sh.
"""

from volcano_tpu.analysis.core import (  # noqa: F401
    Finding,
    Rule,
    all_rules,
    analyze_file,
    analyze_paths,
    analyze_source,
    get_rule,
    register_rule,
    render,
)
from volcano_tpu.analysis import rules  # noqa: F401  (populates the registry)
from volcano_tpu.analysis import absint  # noqa: F401  (v3 abstract-
# interpretation rules VT010-VT012 self-register on import)
